"""Collective method sweep: AG / RS / AR methods across message sizes.

Ref model: the per-kernel perf paths in the reference's tests
(test_all_gather.py / test_reduce_scatter.py / test_allreduce.py report
perf per method and size). One JSON line per (collective, method, size).

Run:  python benchmark/bench_collectives.py [--tpu] [--world N]
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "examples"))
from common import bootstrap  # noqa: E402

jax, mesh = bootstrap(world=4)  # --world/--tpu parsed by bootstrap

import jax.numpy as jnp                                        # noqa: E402
import numpy as np                                             # noqa: E402
from jax.sharding import PartitionSpec as P                    # noqa: E402

from triton_dist_tpu.kernels import (                          # noqa: E402
    AllReduceMethod,
    all_reduce,
    full_mesh_all_gather,
    ring_all_gather,
    ring_reduce_scatter,
)
from triton_dist_tpu.perf_model import (                       # noqa: E402
    estimate_ag_ms,
    estimate_ar_ms,
    estimate_collective_wire_ms,
    estimate_rs_ms,
)
from triton_dist_tpu.runtime.utils import chain_timer          # noqa: E402

ON_TPU = jax.devices()[0].platform == "tpu"
ROWS = [256, 2048, 16384] if ON_TPU else [32]
K_HI = 101 if ON_TPU else 3


def _time(fn, x):
    """Chain-timed: k data-dependent collective calls inside one jit."""

    def build(k):
        def per_rank(x):
            def body(_, x):
                c = fn(x)
                return (x * (1.0 + 0.0 * jnp.sum(c.astype(jnp.float32)))
                        ).astype(x.dtype)

            out = jax.lax.fori_loop(0, k, body, x)
            return jnp.sum(out.astype(jnp.float32)).reshape(1)

        return jax.jit(jax.shard_map(
            per_rank, mesh=mesh, in_specs=P("tp"), out_specs=P("tp"),
            check_vma=False,
        ))

    ms, _ = chain_timer(build, (x,), k_hi=K_HI,
                        pairs=7 if ON_TPU else 2, warmup=2)
    return ms


def main():
    n = int(mesh.shape["tp"])
    rng = np.random.default_rng(0)
    for rows in ROWS:
        x = jnp.asarray(rng.standard_normal((n * rows, 128)), jnp.float32)
        nbytes = rows * 128 * 4
        # model args use the collective's actual per-rank input size
        cases = [
            ("allgather", "ring",
             lambda s: ring_all_gather(s, "tp"),
             estimate_ag_ms(nbytes, n)),
            ("allgather", "full_mesh",
             lambda s: full_mesh_all_gather(s, "tp"),
             estimate_ag_ms(nbytes, n)),
            ("reduce_scatter", "ring",
             lambda s: ring_reduce_scatter(s, "tp"),
             estimate_rs_ms(nbytes, n)),
            # f32-wire variant: psum-grade accumulation at 2x hop bytes
            # (input stays f32 here, so the column isolates the knob's
            # protocol cost; with bf16 inputs the wire doubles too)
            ("reduce_scatter", "ring_f32wire",
             lambda s: ring_reduce_scatter(
                 s, "tp", accum_dtype=jnp.float32),
             estimate_rs_ms(nbytes, n)),
            ("reduce_scatter", "ring_bf16",
             lambda s: ring_reduce_scatter(
                 s.astype(jnp.bfloat16), "tp").astype(s.dtype),
             estimate_rs_ms(nbytes // 2, n)),
            ("reduce_scatter", "ring_bf16_f32wire",
             lambda s: ring_reduce_scatter(
                 s.astype(jnp.bfloat16), "tp",
                 accum_dtype=jnp.float32).astype(s.dtype),
             estimate_rs_ms(nbytes, n)),
            # quantized-wire variants (ISSUE 9): the block-scaled wire
            # image at 1 byte/element + scales — the bytes-by-precision
            # column beside the f32-accumulation one above (the two are
            # orthogonal knobs; see docs/performance.md "Quantized
            # wire"). Accuracy column: wire.numerics.drift_table.
            ("reduce_scatter", "ring_fp8wire",
             lambda s: ring_reduce_scatter(s, "tp", wire_format="fp8"),
             estimate_collective_wire_ms("reduce_scatter",
                                         nbytes, n,
                                         jnp.float32, "fp8")),
            ("allgather", "ring_fp8wire",
             lambda s: ring_all_gather(s, "tp", wire_format="fp8"),
             estimate_collective_wire_ms("allgather", nbytes, n,
                                         jnp.float32, "fp8")),
            ("allreduce", "two_shot_fp8wire",
             lambda s: all_reduce(s, "tp",
                                  method=AllReduceMethod.TwoShot,
                                  wire_format="fp8"),
             estimate_collective_wire_ms("allreduce", nbytes, n,
                                         jnp.float32, "fp8")),
            ("allreduce", "two_shot_int8wire",
             lambda s: all_reduce(s, "tp",
                                  method=AllReduceMethod.TwoShot,
                                  wire_format="int8"),
             estimate_collective_wire_ms("allreduce", nbytes, n,
                                         jnp.float32, "int8")),
            ("allreduce", "one_shot",
             lambda s: all_reduce(s, "tp",
                                  method=AllReduceMethod.OneShot),
             estimate_ar_ms(nbytes, n, method="one_shot")),
            ("allreduce", "two_shot",
             lambda s: all_reduce(s, "tp",
                                  method=AllReduceMethod.TwoShot),
             estimate_ar_ms(nbytes, n)),
            ("allreduce", "xla",
             lambda s: all_reduce(s, "tp", method=AllReduceMethod.XLA),
             estimate_ar_ms(nbytes, n)),
        ]
        for coll, method, fn, model_ms in cases:
            try:
                ms = _time(fn, x)
            except Exception as e:  # report, keep sweeping
                print(json.dumps({"bench": coll, "method": method,
                                  "rows": rows, "error": str(e)[:120]}))
                continue
            print(json.dumps({
                "bench": coll, "method": method, "world": n,
                "shard_rows": rows, "bytes": nbytes,
                "ms": round(ms, 4), "model_ms": round(model_ms, 4),
            }))


if __name__ == "__main__":
    main()
