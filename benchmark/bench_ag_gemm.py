"""AG+GEMM sweep: fused kernel vs decomposed parts vs XLA reference.

TPU-native re-design of the reference's benchmark harness
(ref: benchmark/bench_allgather_gemm.py:60-127 — sweeps M and reports
torch ref / AG-only / GEMM-only / fused side by side so the overlap win
and each component's share are visible). Prints one table plus one JSON
line per row (driver-friendly).

Run:  python benchmark/bench_ag_gemm.py [--tpu] [--world N]
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "examples"))
from common import bootstrap  # noqa: E402

jax, mesh = bootstrap(world=4)  # --world/--tpu parsed by bootstrap

import jax.numpy as jnp                                        # noqa: E402
import numpy as np                                             # noqa: E402
from jax.sharding import PartitionSpec as P                    # noqa: E402

from triton_dist_tpu.kernels import (                          # noqa: E402
    AgGemmConfig,
    ag_gemm,
    ag_gemm_ref,
    ring_all_gather,
)
from triton_dist_tpu.perf_model import estimate_ag_gemm_ms     # noqa: E402
from triton_dist_tpu.runtime.utils import (                    # noqa: E402
    chain_timer, slope_timer,
)

ON_TPU = jax.devices()[0].platform == "tpu"
# CPU interpret mode is ~1000x slower; keep shapes tiny there
MS = [2048, 4096, 8192] if ON_TPU else [64]
K = 5120 if ON_TPU else 128
# Qwen3-32B gate_up columns (ref bench shapes), divided per rank below
N_FULL = 6400 if ON_TPU else 512
DT = jnp.bfloat16 if ON_TPU else jnp.float32
K_HI = 101 if ON_TPU else 3


def _time(fn, a, b, a_spec=None):
    """Chain-timed per-iteration latency: k data-dependent calls inside
    one jit (RTT-proof; see runtime.utils.chain_timer). a_spec overrides
    the activation sharding (P(None) = pre-gathered/replicated)."""
    a_spec = P("tp") if a_spec is None else a_spec

    def build(k):
        def per_rank(a, b):
            def body(_, a):
                c = fn(a, b)
                # data dependency without changing the carried value
                return (a * (1.0 + 0.0 * jnp.sum(c.astype(jnp.float32)))
                        ).astype(a.dtype)

            out = jax.lax.fori_loop(0, k, body, a)
            return jnp.sum(out.astype(jnp.float32)).reshape(1)

        return jax.jit(jax.shard_map(
            per_rank, mesh=mesh, in_specs=(a_spec, P(None, "tp")),
            out_specs=P("tp"), check_vma=False,
        ))

    if ON_TPU:
        # long-chain Theil-Sen slopes (robust to the tunnel's two-sided
        # per-call overhead jitter; see runtime.utils.slope_timer)
        ms, _ = slope_timer(build, (a, b), ks=(1, K_HI // 2 + 1, K_HI))
    else:
        ms, _ = chain_timer(build, (a, b), k_hi=K_HI, pairs=2, warmup=2)
    return ms


def main():
    n = int(mesh.shape["tp"])
    N = N_FULL // n
    rng = np.random.default_rng(0)
    print(f"{'M':>6} {'xla_ms':>9} {'ag_ms':>9} {'gemm_ms':>9} "
          f"{'fused_ms':>9} {'model_ms':>9} {'speedup':>8}")
    for m in MS:
        a = jnp.asarray(rng.standard_normal((m, K)) * 0.02, DT)
        b = jnp.asarray(rng.standard_normal((K, N)) * 0.02, DT)
        cfg = AgGemmConfig(tile_m=min(1024, m // n),
                           tile_n=min(640, N), tile_k=min(1024, K))

        xla_ms = _time(lambda a, b: ag_gemm_ref(a, b, "tp"), a, b)
        ag_ms = _time(lambda a, b: ring_all_gather(a, "tp"), a, b)
        # pure-GEMM share: dot on the PRE-gathered (replicated) activation
        gemm_ms = _time(
            lambda a, b: jnp.dot(
                a, b, preferred_element_type=jnp.float32).astype(DT),
            a, b, a_spec=P(None))
        fused_ms = _time(
            lambda a, b: ag_gemm(a, b, "tp", config=cfg,
                                 force_kernel=True), a, b)
        model_ms = estimate_ag_gemm_ms(m, K, N, n, DT)
        print(f"{m:>6} {xla_ms:>9.3f} {ag_ms:>9.3f} {gemm_ms:>9.3f} "
              f"{fused_ms:>9.3f} {model_ms:>9.3f} "
              f"{xla_ms / fused_ms:>8.3f}")
        print(json.dumps({
            "bench": "ag_gemm", "m": m, "k": K, "n": N, "world": n,
            "xla_ms": round(xla_ms, 4), "ag_only_ms": round(ag_ms, 4),
            "gemm_only_ms": round(gemm_ms, 4),
            "fused_ms": round(fused_ms, 4),
            "model_ms": round(model_ms, 4),
        }))


if __name__ == "__main__":
    main()
