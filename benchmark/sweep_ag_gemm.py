"""On-chip config sweep for the AG+GEMM consumer at the bench shape.

Usage: python benchmark/sweep_ag_gemm.py  (real TPU; ~minutes)
Prints one line per config: tiles, cache mode, median ms, ratio vs XLA.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import jax                                                     # noqa: E402
import jax.numpy as jnp                                        # noqa: E402
import numpy as np                                             # noqa: E402
from jax.sharding import PartitionSpec as P                    # noqa: E402

from triton_dist_tpu.kernels import (                          # noqa: E402
    AgGemmConfig, ag_gemm, ag_gemm_ref,
)
from triton_dist_tpu.runtime import make_mesh                  # noqa: E402
from triton_dist_tpu.runtime.utils import slope_ratio_timer    # noqa: E402

M, K, N = 2048, 5120, 6400


def make_build(mesh, cfg, order="arrival"):
    """Chain builder; cfg=None -> the unfused XLA reference."""
    def build(k):
        def per_rank(x, w):
            def body(_, c):
                if cfg is not None:
                    h = ag_gemm(c, w, axis="tp", config=cfg,
                                force_kernel=True, c_order=order)
                else:
                    h = ag_gemm_ref(c, w, axis="tp")
                # barrier: keep XLA from sinking the carry slice into
                # its dot (see bench.bench_ag_gemm_kernel)
                h = jax.lax.optimization_barrier(h)
                return h[:M, :K].astype(c.dtype)

            out = jax.lax.fori_loop(0, k, body, x)
            return jnp.sum(out.astype(jnp.float32)).reshape(1)

        return jax.jit(jax.shard_map(
            per_rank, mesh=mesh, in_specs=(P("tp"), P(None, "tp")),
            out_specs=P("tp"), check_vma=False))

    return build


def main():
    mesh = make_mesh(mesh_shape=(1,), axis_names=("tp",))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((M, K)) * 0.02, jnp.bfloat16)
    w = jnp.asarray(rng.standard_normal((K, N)) * 0.02, jnp.bfloat16)

    # each config is measured INTERLEAVED with the XLA reference
    # (slope_ratio_timer: long-chain medians + Theil-Sen slopes — the
    # tunnel's per-call overhead jitters ~±30 ms two-sided, so short
    # paired diffs are meaningless; see runtime.utils.slope_timer).
    xla_build = make_build(mesh, None)
    xla_cache = {}

    def xla_memo(k):
        if k not in xla_cache:
            xla_cache[k] = xla_build(k)
        return xla_cache[k]

    sweeps = [
        ("dbuf  tm256  tn3200 tk512", AgGemmConfig(256, 3200, 512)),
        ("dbuf  tm512  tn3200 tk512", AgGemmConfig(512, 3200, 512)),
        ("dbuf  tm512  tn1280 tk1024", AgGemmConfig(512, 1280, 1024)),
        ("dbuf  tm1024 tn1280 tk512", AgGemmConfig(1024, 1280, 512)),
        ("dbuf  tm512  tn1280 tk512", AgGemmConfig(512, 1280, 512)),
        ("dbuf  tm1024 tn640 tk512", AgGemmConfig(1024, 640, 512)),
        ("cache tm512  tn1280 tk512",
         AgGemmConfig(512, 1280, 512, cache_a=True)),
        ("cache tm512  tn1280 tk1024",
         AgGemmConfig(512, 1280, 1024, cache_a=True)),
        ("cache tm1024 tn640 tk256",
         AgGemmConfig(1024, 640, 256, cache_a=True)),
    ]
    for label, cfg in sweeps:
        try:
            r, pm, xm = slope_ratio_timer(make_build(mesh, cfg),
                                          xla_memo, (x, w))
            print(f"{label:28s} {pm:7.4f} ms  ratio {r:.3f} "
                  f"(xla {xm:.4f})", flush=True)
        except Exception as e:
            print(f"{label:28s} FAILED {str(e)[:120]}", flush=True)


if __name__ == "__main__":
    main()
