"""Compatibility shims for legacy jax (0.4.x Pallas interpreter).

The package targets the modern Pallas TPU surface; this module back-fills
the pieces the 0.4.x CPU interpreter is missing so the interpret-mode
test rig runs unchanged on either jax line:

- remote ``semaphore_signal`` (``device_id=...``) discharge — the 0.4.x
  rule raises ``NotImplementedError("Remote signal not implemented.")``.
  The replacement applies the same SPMD all-gather trick the 0.4.x
  remote-DMA discharge already uses: every rank gathers the
  (target, inc) pairs issued along the axis this step and adds the
  signals addressed to itself to its LOCAL semaphore value. Lockstep
  SPMD execution (which the interpreter's remote-DMA discharge already
  assumes) makes this exact.

- ``pltpu.get_barrier_semaphore`` has no interpret path at all in 0.4.x;
  callers use :func:`scoped_collective_sem` which swaps in a
  ``pl.run_scoped`` REGULAR semaphore under the legacy interpreter.

Scalar/LOGICAL device-id translation for remote DMA lives in
``shmem._dma_device_id`` (the 0.4.x DMA discharge mis-handles dict mesh
coordinates); this module only hosts the version probe and the
primitive-level patch.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp


def _version_tuple() -> tuple:
    parts = []
    for p in jax.__version__.split("."):
        digits = "".join(ch for ch in p if ch.isdigit())
        if not digits:
            break
        parts.append(int(digits))
    return tuple(parts[:3])


#: True on the 0.4.x line, whose Pallas interpreter predates remote
#: signals, dict device ids, barrier semaphores, and multi-core mode.
LEGACY_JAX = _version_tuple() < (0, 5)

_namespace_installed = False


def install_jax_namespace() -> None:
    """Back-fill the top-level jax APIs this package calls that older
    jax (< 0.6) ships elsewhere or not at all (idempotent; no-op when
    the current jax already has them). Called from the package root
    BEFORE runtime/kernels import, so every module sees one surface:

    - ``jax.shard_map`` — under ``jax.experimental`` with ``check_rep``
      instead of ``check_vma`` on the old line.
    - ``jax.sharding.get_abstract_mesh`` — absent; None routes
      interpret-mode kernels to their safe XLA fallbacks
      (see lang.core.interpret_no_headroom).
    - ``jax.lax.axis_size`` — absent; axis_frame lookup.
    """
    global _namespace_installed
    if _namespace_installed:
        return
    _namespace_installed = True

    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        def _compat_shard_map(f=None, /, *, mesh, in_specs, out_specs,
                              check_vma=True, **kw):
            kw.setdefault("check_rep", check_vma)
            if f is None:
                return lambda g: _shard_map(
                    g, mesh=mesh, in_specs=in_specs,
                    out_specs=out_specs, **kw)
            return _shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kw)

        jax.shard_map = _compat_shard_map

    if not hasattr(jax.sharding, "get_abstract_mesh"):
        jax.sharding.get_abstract_mesh = lambda: None

    if not hasattr(jax.lax, "axis_size"):
        from jax._src import core as _jcore

        def _axis_size(name):
            return int(_jcore.axis_frame(name))

        jax.lax.axis_size = _axis_size


_installed = False


def legacy_interpret_active() -> bool:
    """True when kernels are being interpreted under legacy jax — the
    condition under which the shims below (and the callers' scalar
    device-id translation) engage."""
    from triton_dist_tpu.lang.core import use_interpret

    return LEGACY_JAX and use_interpret()


def install() -> None:
    """Install the legacy-interpreter patches (idempotent; no-op on
    modern jax). Called at ``triton_dist_tpu.lang`` import."""
    global _installed
    if _installed or not LEGACY_JAX:
        return
    _installed = True

    # The 0.4.x GSPMD sharding propagation check-fails (TileAssignment::
    # Reshape on a 0-element assignment) on programs that chain two
    # discharged interpret-mode kernels through a data dependency — every
    # multi-kernel decode step hits it. The Shardy partitioner handles
    # the same modules; opt in unless the user already chose.
    if os.environ.get("JAX_USE_SHARDY_PARTITIONER") is None:
        try:
            jax.config.update("jax_use_shardy_partitioner", True)
        except Exception:  # noqa: BLE001 — unknown flag on some builds; keep GSPMD
            pass

    from jax._src import core as jax_core
    from jax._src.pallas import core as pl_core
    from jax._src.pallas.mosaic import primitives as mp
    from jax._src.state import discharge as state_discharge

    prev_rule = state_discharge._discharge_rules[mp.semaphore_signal_p]

    def _signal_discharge(in_avals, out_avals, *flat_args, args_tree,
                          device_id_type):
        (ref, transforms, inc, device_id,
         core_index) = args_tree.unflatten(flat_args)
        if device_id is None:
            return prev_rule(in_avals, out_avals, *flat_args,
                             args_tree=args_tree,
                             device_id_type=device_id_type)
        if core_index is not None:
            raise NotImplementedError(
                "remote signal with core_index under the 0.4.x "
                "interpreter")
        # Resolve the team axis and target rank. Dict device ids address
        # `pe` along one axis holding the others fixed — exactly what an
        # axis-local all_gather sees, so multi-axis meshes work too.
        if isinstance(device_id, dict):
            (axis, pe), = device_id.items()
        else:
            axis_env = jax_core.get_axis_env()
            names = [nm for nm in axis_env.axis_sizes if nm is not None]
            if len(names) != 1:
                raise NotImplementedError(
                    "scalar device_id signal needs a single-axis mesh "
                    "under the 0.4.x interpreter")
            axis, pe = names[0], device_id
        me = jax.lax.axis_index(axis)
        pes = jax.lax.all_gather(jnp.asarray(pe, jnp.int32), axis)
        incs = jax.lax.all_gather(
            jnp.asarray(inc, pl_core.SEMAPHORE_INTERPRET_DTYPE), axis)
        add = jnp.sum(
            jnp.where(pes == me, incs, jnp.zeros_like(incs))
        ).astype(pl_core.SEMAPHORE_INTERPRET_DTYPE)
        sem_value = mp._transform_semaphore(ref, transforms, in_avals[0])
        _, new_sem_value = state_discharge.transform_swap_array(
            ref, transforms, sem_value + add)
        return (new_sem_value,) + (None,) * (len(in_avals) - 1), ()

    state_discharge._discharge_rules[mp.semaphore_signal_p] = (
        _signal_discharge)

    # Remote DMA: the 0.4.x discharge supports only single-axis meshes
    # (LOGICAL needs exactly one named axis; MESH tree-compares the
    # coordinate dict against the gathered axis index and TypeErrors).
    # Replace it for single-entry mesh-coordinate dicts — `{axis: pe}`
    # addresses rank `pe` along ONE axis holding the others at the
    # sender's own coordinates, so gathering the (pe, payload) pairs
    # along that axis alone is exact on any mesh; other-axis coordinates
    # never change. Everything else delegates to the stock rule.
    from jax import tree_util
    from jax._src.pallas import core as _plc
    from jax._src.pallas.mosaic import primitives as _mp

    prev_dma = state_discharge._discharge_rules[mp.dma_start_p]

    def _dma_start_discharge(in_avals, out_avals, *flat_args, tree,
                             device_id_type):
        unflat = tree_util.tree_unflatten(tree, flat_args)
        (src_ref, src_transforms, dst_ref, dst_transforms,
         dst_sem, dst_sem_transforms, src_sem, src_sem_transforms,
         device_id) = unflat
        if not (isinstance(device_id, dict) and len(device_id) == 1):
            return prev_dma(in_avals, out_avals, *flat_args, tree=tree,
                            device_id_type=device_id_type)
        (shard_axis, pe), = device_id.items()
        avals = tree_util.tree_unflatten(tree, in_avals)
        (_, src_tf_avals, _, dst_tf_avals, dst_sem_aval,
         dst_sem_tf_avals, src_sem_aval, src_sem_tf_avals, _) = avals

        n_src_sem_tf = len(tree_util.tree_leaves(src_sem_tf_avals))
        n_dst_sem_tf = len(tree_util.tree_leaves(dst_sem_tf_avals))
        n_src_tf = len(tree_util.tree_leaves(src_tf_avals))
        n_dst_tf = len(tree_util.tree_leaves(dst_tf_avals))

        updates = state_discharge.transform_array(src_ref, src_transforms)
        local_src = updates

        my_axis = jax.lax.axis_index(shard_axis)
        who_copy_to_me = jax.lax.all_gather(pe, shard_axis) == my_axis
        index = jnp.argmax(who_copy_to_me, axis=0)
        global_updates = jax.lax.all_gather(updates, shard_axis)
        updates = jax.lax.dynamic_index_in_dim(
            global_updates, index, axis=0, keepdims=False)
        # asymmetric dst indexing: take the SENDER's dst transforms
        global_dst_tf = tree_util.tree_map(
            lambda x: jax.lax.all_gather(x, shard_axis), dst_transforms)
        dst_transforms = tree_util.tree_map(
            lambda x: jax.lax.dynamic_index_in_dim(
                x, index, axis=0, keepdims=False),
            global_dst_tf)

        _, new_dst = state_discharge.transform_swap_array(
            dst_ref, dst_transforms, updates)

        recv_size = jnp.array(
            jnp.minimum(updates.size, _plc.SEMAPHORE_MAX_VALUE),
            dtype=_plc.SEMAPHORE_INTERPRET_DTYPE)
        dst_sem_value = _mp._transform_semaphore(
            dst_sem, dst_sem_transforms, dst_sem_aval)
        _, new_dst_sem = state_discharge.transform_swap_array(
            dst_sem, dst_sem_transforms, dst_sem_value + recv_size)
        send_size = jnp.array(
            jnp.minimum(local_src.size, _plc.SEMAPHORE_MAX_VALUE),
            dtype=_plc.SEMAPHORE_INTERPRET_DTYPE)
        src_sem_value = _mp._transform_semaphore(
            src_sem, src_sem_transforms, src_sem_aval)
        _, new_src_sem = state_discharge.transform_swap_array(
            src_sem, src_sem_transforms, src_sem_value + send_size)

        new_vals = (None,) + (None,) * n_src_tf
        new_vals += (new_dst,) + (None,) * n_dst_tf
        new_vals += (new_dst_sem,) + (None,) * n_dst_sem_tf
        new_vals += (new_src_sem,) + (None,) * n_src_sem_tf
        new_vals += (None,)  # device_id (single leaf of the dict)
        assert len(new_vals) == len(in_avals)
        return new_vals, ()

    state_discharge._discharge_rules[mp.dma_start_p] = _dma_start_discharge


def scoped_collective_sem(body) -> None:
    """Run ``body(sem)`` with a collective-barrier-class semaphore.

    Modern path: the hardware barrier semaphore selected by the
    surrounding kernel's collective_id. Legacy interpreter: a
    ``pl.run_scoped`` REGULAR semaphore — the interpreter executes ranks
    in lockstep, so a fresh zeroed semaphore plus the patched remote
    signal reproduces barrier semantics (each rank's instance receives
    exactly the signals addressed to it)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if legacy_interpret_active():
        pl.run_scoped(body, pltpu.SemaphoreType.REGULAR)
    else:
        body(pltpu.get_barrier_semaphore())
