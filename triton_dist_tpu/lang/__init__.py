"""Device-side language layer (the reference's `triton_dist.language` analog).

The reference's distributed dialect has 7 ops — wait, consume_token,
get_rank, get_num_ranks, symm_at, notify, extern_call
(ref: include/TritonDistributed/Dialect/Distributed/IR/DistributedOps.td:45-190).
On TPU these become Pallas semaphore/DMA operations; `symm_at` (translate a
symmetric address to a remote PE's address) has no analog because remote
memory is only reachable through explicit DMA — the `putmem`/`getmem`
family in `lang.shmem` covers those uses. `extern_call` (call into a device
bitcode library) has no TPU equivalent and is intentionally absent: Mosaic
kernels are closed-world.

The SIMT escape hatch (simt_exec_region/load_shared/store_shared,
ref: SIMTOps.td:48-127) is also unnecessary: Pallas kernels already mix
scalar (SMEM) and tile (VMEM) code freely.
"""

from triton_dist_tpu.lang import shmem  # noqa: F401
from triton_dist_tpu.lang.core import (  # noqa: F401
    tpu_call,
    use_interpret,
    cdiv,
    round_up,
    min_tile,
    compiler_params,
    compute_vmem_bytes,
)
from triton_dist_tpu.lang.shmem import (  # noqa: F401
    my_pe,
    n_pes,
    SIGNAL_SET,
    SIGNAL_ADD,
    CMP_EQ,
    CMP_GE,
)

import jax


def rank(axis="tp"):
    """Device-side rank (ref: distributed_ops.py:57-111 `rank`)."""
    return jax.lax.axis_index(axis)


def num_ranks(axis="tp"):
    """Device-side team size (ref: distributed_ops.py `num_ranks`)."""
    return jax.lax.axis_size(axis)


def wait(sem, num_barriers=1, scope="gpu", semantic="acquire", wait_value=1):
    """Spin-wait on `num_barriers` signals (ref: DistributedOps.td:45 `wait`).

    Maps to a consuming semaphore wait for num_barriers*wait_value. scope and
    semantic are accepted for parity; Pallas semaphore waits are always
    device-scope acquire. Returns a token for `consume_token`."""
    del scope, semantic
    from jax.experimental.pallas import tpu as pltpu

    pltpu.semaphore_wait(sem, num_barriers * wait_value)
    return 0


def consume_token(value, token):
    """Artificial dependency between a wait and subsequent loads
    (ref: DistributedOps.td:79 `consume_token`). Pallas kernels execute
    semaphore ops in program order relative to ref loads, so this is an
    identity; kept so ported kernel code reads the same."""
    del token
    return value


def notify(sem, pe, signal_val=1, sig_op=SIGNAL_ADD, comm_scope="intra_node", axis="tp"):
    """Set/add a signal on `pe` (ref: DistributedOps.td:151 `notify`)."""
    del comm_scope
    shmem.signal(sem, signal_val, sig_op, pe, axis)


# -- in-kernel trace primitives (triton_dist_tpu.trace.events) ---------------
# Lazy-imported so `lang` never pulls the trace package at import time
# (trace.collect is host-side machinery kernels don't need). Both are
# trace-time no-ops when `ctx` is None — i.e. whenever the kernel was
# built without `trace.building()` — so uninstrumented builds compile
# bit-identical programs.


def trace_span(ctx, region, payload=0, aux=0):
    """BEGIN/END span context manager around kernel-body code (the
    device-side analog of the reference's intra-kernel profiler slots)."""
    from triton_dist_tpu.trace.events import span as _span

    return _span(ctx, region, payload, aux)


def trace_instant(ctx, region, payload=0, aux=0):
    """One point event (prefetch hit/miss, send issued, ...)."""
    from triton_dist_tpu.trace.events import instant as _instant

    return _instant(ctx, region, payload, aux)
