"""Core Pallas helpers: backend-aware pallas_call, tiling utilities.

This is the foundation of the device-side language layer
(ref: python/triton_dist/language/core.py). Every kernel in the framework is
built through `tpu_call`, which compiles natively on TPU and transparently
switches to Pallas TPU interpret mode on CPU so the full kernel library —
including inter-chip remote DMA — runs on a virtual
`--xla_force_host_platform_device_count` mesh for testing.
"""

from __future__ import annotations

import functools
import inspect
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_FORCE_INTERPRET = os.environ.get("TDT_FORCE_INTERPRET", "") == "1"

# Older jax (< 0.6) names the params class TPUCompilerParams and drives
# interpret mode with a plain boolean (no InterpretParams class).
_COMPILER_PARAMS_CLS = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams
_HAS_INTERPRET_PARAMS = hasattr(pltpu, "InterpretParams")


def multicore_interpret_supported() -> bool:
    """True when this jax's interpreter can emulate multiple TensorCores
    (InterpretParams(num_cores_or_threads=...)). The 0.4.x interpreter
    cannot; multi-core megakernel tests skip there."""
    return _HAS_INTERPRET_PARAMS


def interpret_params(**kw):
    """pltpu.InterpretParams when available, else the legacy boolean
    (kw like num_cores_or_threads only exist on the modern class)."""
    if _HAS_INTERPRET_PARAMS:
        return pltpu.InterpretParams(**kw)
    if kw:
        raise RuntimeError(
            "this jax version's interpret mode does not support "
            f"InterpretParams({kw}); upgrade jax for multi-core interpret"
        )
    return True


@functools.lru_cache(maxsize=None)
def backend_platform() -> str:
    return jax.devices()[0].platform


def use_interpret() -> bool:
    """True when Pallas TPU kernels must run in interpreter mode (CPU mesh)."""
    return _FORCE_INTERPRET or backend_platform() != "tpu"


# Counts every Pallas kernel constructed through tpu_call. Lets tests and
# the driver dryrun assert the real protocol kernels were traced rather
# than silently rerouted to XLA fallbacks (a fail-open here previously made
# the whole fused-vs-ref suite vacuous).
_PALLAS_CALLS = 0


def pallas_call_count() -> int:
    return _PALLAS_CALLS


# Conformance-recording hook (verify/conform.py installs this at import;
# lang stays free of any verify import). With no recording active the
# hook returns None and tpu_call takes its unmodified path — the
# zero-cost-off contract the conform tests pin.
_CONFORM_INSTRUMENT = None


def tpu_call(kernel, **kwargs):
    """pl.pallas_call with automatic interpret-mode fallback off-TPU."""
    global _PALLAS_CALLS
    _PALLAS_CALLS += 1
    if use_interpret() and "interpret" not in kwargs:
        kwargs["interpret"] = interpret_params()
    if _CONFORM_INSTRUMENT is not None:
        instrumented = _CONFORM_INSTRUMENT(kernel, kwargs)
        if instrumented is not None:
            return instrumented
    return pl.pallas_call(kernel, **kwargs)


def interpret_no_headroom() -> bool:
    """True when interpret-mode Pallas kernels that block across devices
    must not be used because the host has no spare executor threads.

    XLA:CPU sizes its thunk-executor pool by the virtual device count, and
    interpret-mode kernels block pool threads inside callbacks (semaphore
    waits; operand materialization). When the surrounding mesh occupies
    every virtual device, those blocked callbacks exhaust the pool, pending
    compute starves, and cross-device-blocking kernels deadlock. Kernels
    consult this to route to their XLA-collective fallback instead — the
    result is identical, only the overlap protocol is skipped. This is what
    keeps `__graft_entry__.dryrun_multichip` (driver sets device count ==
    mesh size) deadlock-free while the test suite (12 virtual devices,
    8-device meshes) still exercises the real protocols.
    """
    if not use_interpret():
        return False
    from triton_dist_tpu.lang import _compat

    if _compat.LEGACY_JAX:
        # The 0.4.x interpreter is discharge-based (remote DMA/signals
        # lower to lockstep all_gathers at trace time): nothing blocks a
        # thunk-executor thread, so the pool-exhaustion deadlock this
        # guard exists for cannot occur — always run the real protocol.
        return False
    m = jax.sharding.get_abstract_mesh()
    if m is not None and m.shape:
        import math

        mesh_total = math.prod(m.shape.values())
        return mesh_total >= len(jax.devices())
    # Unknown mesh under interpret mode: the safe default is the
    # non-blocking XLA path (a wrong False here deadlocks; a wrong True
    # only skips the overlap protocol).
    return True


def interpret_divergence_unsafe() -> bool:
    """True when kernels whose remote ops sit under rank-divergent
    control flow (``pl.when(me == r)`` around a put/signal) must take
    their XLA fallback: the legacy interpreter discharges remote DMA and
    signals into lockstep collectives that EVERY rank must execute, so a
    rank skipping the branch hangs the gather. Uniform-flow kernels
    (every rank puts each step) are exact under that discharge and keep
    the real protocol — see interpret_no_headroom."""
    from triton_dist_tpu.lang import _compat

    return _compat.legacy_interpret_active()


def cdiv(a: int, b: int) -> int:
    return (a + b - 1) // b


def round_up(a: int, b: int) -> int:
    return cdiv(a, b) * b


def fit_tile(tile: int, dim: int) -> int:
    """Largest divisor of dim that is <= tile, preferring lane multiples
    (shared tile-fitting rule of the blocked GEMM kernels)."""
    t = min(tile, dim)
    while t > 128 and dim % t:
        t -= 128
    while dim % t:
        t //= 2
    return max(t, 1)


def min_tile(dtype) -> tuple:
    """Minimum (sublane, lane) tile for a dtype on TPU."""
    d = jnp.dtype(dtype)
    if d.itemsize == 4:
        return (8, 128)
    if d.itemsize == 2:
        return (16, 128)
    return (32, 128)


def compute_vmem_bytes(*shaped) -> int:
    """Sum byte sizes of (shape, dtype) pairs or arrays, for vmem_limit."""
    import math

    total = 0
    for s in shaped:
        if hasattr(s, "shape") and hasattr(s, "dtype"):
            shape, dtype = s.shape, s.dtype
        else:
            shape, dtype = s
        total += math.prod(shape) * jnp.dtype(dtype).itemsize
    return total


_COLLECTIVE_IDS: dict = {}


def next_collective_id(name: str) -> int:
    """Stable collective_id per kernel name.

    Mosaic requires every collective pallas_call to carry an id agreed on by
    all devices; ids key the shared barrier semaphore. The id is derived
    from the *name alone* (crc32), never from call order, so multi-controller
    processes that trace extra rank-local programs still agree. Cross-name
    collisions are detected per process and are a hard error (two distinct
    collectives sharing a barrier semaphore could race if XLA overlaps
    them)."""
    import zlib

    if name not in _COLLECTIVE_IDS:
        # int16 space: the Pallas interpreter stores collective ids as int16.
        cid = zlib.crc32(name.encode()) & 0x7FFF
        for other, oid in _COLLECTIVE_IDS.items():
            if oid == cid:
                raise RuntimeError(
                    f"collective_id collision: {name!r} and {other!r} both "
                    f"hash to {cid}; rename one kernel"
                )
        _COLLECTIVE_IDS[name] = cid
    return _COLLECTIVE_IDS[name]


# probed once, like _COMPILER_PARAMS_CLS: older jax has no
# remote_bytes_transferred field on CostEstimate
_COST_ESTIMATE_FIELDS = frozenset(
    inspect.signature(pl.CostEstimate).parameters)


def cost_estimate(flops: int = 0, bytes_accessed: int = 0,
                  remote_bytes: int = 0) -> "pl.CostEstimate":
    """Kernel cost metadata — the reference's `launch_metadata` flops/
    bytes reporting (ref: allgather_gemm.py:145-155) — consumed by the
    XLA scheduler and surfaced in profiles."""
    args = dict(
        flops=int(flops), bytes_accessed=int(bytes_accessed),
        transcendentals=0, remote_bytes_transferred=int(remote_bytes),
    )
    return pl.CostEstimate(
        **{k: v for k, v in args.items() if k in _COST_ESTIMATE_FIELDS})


def compiler_params(
    has_side_effects: bool = False,
    collective_id: Optional[int] = None,
    vmem_limit_bytes: Optional[int] = None,
    **kw: Any,
):
    args: dict = dict(kw)
    if has_side_effects:
        args["has_side_effects"] = True
    if collective_id is not None:
        args["collective_id"] = collective_id
    if vmem_limit_bytes is not None:
        args["vmem_limit_bytes"] = vmem_limit_bytes
    import dataclasses

    known = {f.name for f in dataclasses.fields(_COMPILER_PARAMS_CLS)}
    return _COMPILER_PARAMS_CLS(
        **{k: v for k, v in args.items() if k in known})
