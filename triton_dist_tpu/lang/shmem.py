"""Device-side OpenSHMEM-style API for Pallas TPU kernels.

TPU-native re-design of the reference's `libshmem_device`
(ref: python/triton_dist/language/extra/libshmem_device.py:28-341), which
exposes ~70 NVSHMEM device functions inside Triton kernels. On TPU the
symmetric heap is replaced by per-device refs inside a shard_map'd Pallas
kernel, remote puts are ICI async remote DMA (`pltpu.make_async_remote_copy`)
and signals are Pallas semaphores. Teams (NVSHMEM_TEAM_WORLD/NODE, ref
libshmem_device.py:326-340) map to mesh axis names.

Semantics notes (differences from NVSHMEM, by design of the hardware):
- ICI RDMA is push-based. `putmem*` is native; `getmem*` is provided for
  API parity by pulling through a peer push in cooperative kernels (see
  kernels/p2p.py) — prefer put-based algorithms.
- Signals are counting semaphores: `SIGNAL_ADD` is native; `SIGNAL_SET` is
  emulated (used only with value 1 on zeroed semaphores, which is equal to
  ADD 1 — asserted).
- `signal_wait_until(GE, v)` consumes v on success (semaphore decrement);
  all framework call sites are matched signal/wait pairs so this is
  invisible, and it is what makes kernels re-entrant without a re-zeroing
  pass (the reference needs explicit barrier-reset, e.g.
  allgather_gemm.py:107 local_copy_and_barrier_all).
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Any, Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.faults import guard as _guard
from triton_dist_tpu.faults import plan as _fplan
from triton_dist_tpu.lang import _compat
from triton_dist_tpu.obs import stats as _obs
from triton_dist_tpu.verify import capture as _vcap
from triton_dist_tpu.verify import conform as _conform

_compat.install()

# --- signal ops / comparison constants (ref: libshmem_device.py:293-323) ---
SIGNAL_SET = 0
SIGNAL_ADD = 1
CMP_EQ = 0
CMP_NE = 1
CMP_GT = 2
CMP_LE = 3
CMP_LT = 4
CMP_GE = 5

# Teams = mesh axes. TEAM_WORLD means "all named axes of the surrounding
# shard_map" and must be spelled explicitly by kernels (axis or tuple).
TEAM_WORLD = None

AxisName = Union[str, Sequence[str]]


def my_pe(axis: AxisName) -> jax.Array:
    """This device's rank within the team (ref: nvshmem_my_pe).

    Under verify.capturing(): the symbolic rank (every primitive below
    likewise records instead of executing — see verify/capture.py)."""
    if _vcap.active() is not None:
        return _vcap.Sym.var("me")
    return jax.lax.axis_index(axis)


def n_pes(axis: AxisName) -> jax.Array:
    """Team size (ref: nvshmem_n_pes)."""
    cap = _vcap.active()
    if cap is not None:
        return cap.n
    return jax.lax.axis_size(axis)


def team_device_id(axis: AxisName, pe) -> dict:
    """Mesh-coordinate device id addressing `pe` along `axis`, holding all
    other mesh axes at this device's coordinates (NVSHMEM team translate,
    ref: nvshmem_team_translate_pe)."""
    if isinstance(axis, str):
        return {axis: pe}
    raise NotImplementedError(
        "multi-axis teams: linearize explicitly with team_linear_device_id"
    )


def _dma_device_id(axis: AxisName, pe) -> tuple:
    """(device_id, device_id_type) for a remote DMA addressing `pe` on
    team `axis` — always the mesh-coordinate dict; under the legacy
    interpreter the _compat discharge rule gives single-entry dicts
    exact lockstep semantics on any mesh."""
    return team_device_id(axis, pe), pltpu.DeviceIdType.MESH


def team_linear_device_id(axes: Sequence[str], pe) -> dict:
    """Address flat rank `pe` within the team spanned by `axes` (row-major)."""
    coords = {}
    rem = pe
    for ax in reversed(axes):
        size = jax.lax.axis_size(ax)
        coords[ax] = jax.lax.rem(rem, size)
        rem = jax.lax.div(rem, size)
    return coords


@dataclasses.dataclass(frozen=True)
class PutHandle:
    """Handle for a non-blocking put (ref: *_nbi variants + quiet).

    `recv_sem`/`elems`/`nbytes` describe the symmetric incoming payload
    so an active guard build (faults.guard) can bound the delivery wait:
    readiness is `recv_sem >= amount` where the amount is the element
    count under the interpreter's discharge and the byte count on
    hardware (what the DMA semaphore actually tallies in each world)."""

    copy: Any
    recv_sem: Any = None
    elems: int = 0
    nbytes: int = 0
    # semaphore identities the conformance recorder threaded through
    # note_put (None whenever recording is off — the common case)
    conform_idents: Any = None

    def _recv_amount(self) -> int:
        from triton_dist_tpu.lang.core import use_interpret

        return self.elems if use_interpret() else self.nbytes

    def wait_send(self):
        _conform.note_wait_send(self.conform_idents)
        self.copy.wait_send()

    def wait_recv(self, slot=0):
        """Wait for the symmetric incoming payload on this device's recv_sem
        (every rank runs the same program, so 'my put's recv' is 'my inbox').

        Under an active guard build this is a bounded watchdog wait: on
        deadline the kernel records a structured guard row and continues
        instead of hanging (the host raises DeadlineExceeded)."""
        _conform.note_wait_recv(self.conform_idents)
        if _guard.current() is None or self.recv_sem is None:
            self.copy.wait_recv()
        else:
            _guard.watchdog_wait(self.copy.wait_recv, self.recv_sem,
                                 self._recv_amount(), "recv", slot=slot)
        _obs.meter_wait("sem_wait")

    def wait(self):
        self.wait_send()
        self.wait_recv()


def putmem_nbi(
    dst_ref,
    src_ref,
    send_sem,
    recv_sem,
    pe,
    axis: AxisName,
) -> PutHandle:
    """Non-blocking put of src_ref (local) into dst_ref on `pe` of team `axis`
    (ref: nvshmem_putmem_nbi_block, libshmem_device.py:150-180).

    recv_sem is incremented ON THE DESTINATION when the payload lands —
    i.e. every put is implicitly a put-with-signal; `putmem_signal_nbi`
    below only differs by signal amount.
    """
    cap = _vcap.active()
    if cap is not None:
        return cap.put(dst_ref, src_ref, send_sem, recv_sem, pe)
    device_id, id_type = _dma_device_id(axis, pe)
    copy = pltpu.make_async_remote_copy(
        src_ref=src_ref,
        dst_ref=dst_ref,
        send_sem=send_sem,
        recv_sem=recv_sem,
        device_id=device_id,
        device_id_type=id_type,
    )
    copy.start()
    elems = int(math.prod(src_ref.shape))
    nbytes = elems * jnp.dtype(src_ref.dtype).itemsize
    # stat-row metering (obs/stats.py): nbytes is what is actually on
    # the wire — quantized legs put int8 wire images, so the byte
    # ledger is per-format without a side channel
    _obs.meter_send(nbytes)
    idents = _conform.note_put(send_sem, recv_sem, pe, dst_ref, nbytes)
    return PutHandle(copy, recv_sem=recv_sem, elems=elems, nbytes=nbytes,
                     conform_idents=idents)


def putmem(dst_ref, src_ref, send_sem, recv_sem, pe, axis: AxisName) -> None:
    """Blocking put: returns when the local buffer is reusable
    (ref: nvshmem_putmem_block)."""
    putmem_nbi(dst_ref, src_ref, send_sem, recv_sem, pe, axis).wait_send()


def putmem_signal_nbi(
    dst_ref,
    src_ref,
    send_sem,
    recv_sem,
    sig_sem,
    signal_val,
    sig_op,
    pe,
    axis: AxisName,
) -> PutHandle:
    """Put + remote signal (ref: nvshmem_putmem_signal_nbi_block).

    TPU contract (WEAKER than NVSHMEM's — by hardware design): the named
    signal is a separate message issued after the local send completes; it
    does NOT imply the payload is visible at the destination. Payload
    visibility is carried by `recv_sem`, which the destination must wait via
    `PutHandle.wait_recv()` (every put on TPU is already put-with-signal
    through its delivery semaphore). Receivers therefore pair
    `signal_wait_until(sig,...)` with `h.wait_recv()`; the named signal is
    for counting/ordering across peers, the recv_sem for data visibility.
    All framework call sites follow this pairing."""
    h = putmem_nbi(dst_ref, src_ref, send_sem, recv_sem, pe, axis)
    h.wait_send()
    signal(sig_sem, signal_val, sig_op, pe, axis)
    return h


def _fault_signal_mask(value, axis: AxisName, label: Optional[str]):
    """Apply an active FaultPlan's dropped-signal fault: the faulted
    rank's inc masks to 0 (VALUE-level — never control-flow divergence,
    which would hang the legacy interpreter's lockstep discharge). No
    plan -> the value passes through untouched (zero cost off)."""
    plan = _fplan.active()
    if plan is None:
        return value
    r = plan.dropped_signal_rank(label)
    if r is None:
        return value
    me = jax.lax.axis_index(axis) if isinstance(axis, str) else \
        jax.lax.axis_index(tuple(axis)[0])
    return jnp.where(me == r, 0, jnp.asarray(value, jnp.int32))


def signal(sig_sem, value, sig_op, pe, axis: AxisName,
           label: Optional[str] = None) -> None:
    """Remote signal op on `pe`'s semaphore (ref: nvshmemx_signal_op).

    TPU semaphores are counting: only ADD is native. SET is accepted solely
    for the ubiquitous "set flag to 1 on a zeroed semaphore" pattern, where
    it equals ADD 1 — enforced below.

    `label` classifies the site ("credit", "barrier", ...) for the
    fault plane's DroppedSignal scheduling (faults/plan.py)."""
    assert sig_op in (SIGNAL_SET, SIGNAL_ADD), f"unknown sig_op {sig_op}"
    if sig_op == SIGNAL_SET:
        assert isinstance(value, int) and value == 1, (
            "SIGNAL_SET on TPU is only supported as set-to-1 on a zeroed "
            "semaphore (== ADD 1); use SIGNAL_ADD otherwise"
        )
    cap = _vcap.active()
    if cap is not None:
        cap.signal(sig_sem, value, pe)
        return
    _conform.note_signal(sig_sem, value, pe)
    pltpu.semaphore_signal(
        sig_sem,
        inc=_fault_signal_mask(value, axis, label),
        device_id=team_device_id(axis, pe),
        device_id_type=pltpu.DeviceIdType.MESH,
    )


def signal_local(sig_sem, value=1) -> None:
    """Signal this device's own semaphore."""
    cap = _vcap.active()
    if cap is not None:
        cap.signal(sig_sem, value, pe=None)
        return
    _conform.note_signal(sig_sem, value, None)
    pltpu.semaphore_signal(sig_sem, inc=value)


def signal_wait_until(sig_sem, cmp, value, site: str = "wait",
                      slot=0) -> None:
    """Wait for local semaphore (ref: nvshmem_signal_wait_until).

    Consuming wait: decrements by `value` once satisfied (see module doc).
    Only CMP_GE is supported — TPU semaphore waits are ">= then subtract";
    NVSHMEM's EQ (wait for exact value, non-consuming) cannot be expressed.

    Under an active guard build (faults.guard.building) this is a
    bounded watchdog wait classified at `site` ("wait"/"credit"/...):
    on deadline the kernel records a structured guard row — rank, site,
    slot, progress, expected, observed — and continues instead of
    hanging; the host raises DeadlineExceeded from the decoded row."""
    assert cmp == CMP_GE, "TPU signal_wait_until supports CMP_GE only"
    cap = _vcap.active()
    if cap is not None:
        cap.wait(sig_sem, value)
        return
    _conform.note_wait(sig_sem, value)
    if _guard.current() is None:
        pltpu.semaphore_wait(sig_sem, value)
    else:
        _guard.watchdog_wait(lambda: pltpu.semaphore_wait(sig_sem, value),
                             sig_sem, value, site, slot=slot)
    _obs.meter_wait("sem_wait")


def signal_read(sig_sem) -> jax.Array:
    """Non-destructive semaphore read (ref: atomic load of signal word)."""
    if _vcap.active() is not None:
        raise RuntimeError(
            "signal_read has no symbolic model (its VALUE would steer "
            "control flow the verifier cannot see) — protocols under "
            "verify.capturing() must be wait-structured"
        )
    read = getattr(pltpu, "semaphore_read", None) or pl.semaphore_read
    return read(sig_sem)


def fence() -> None:
    """Ordering fence (ref: nvshmem_fence). ICI delivers a single
    connection's DMAs in order and Pallas semaphore ops are program-ordered,
    so this is a no-op retained for API parity."""


def quiet(*handles: PutHandle) -> None:
    """Complete outstanding nbi puts (ref: nvshmem_quiet)."""
    for h in handles:
        h.wait_send()


def barrier_all(axis: AxisName) -> None:
    """Full-team barrier inside a kernel (ref: nvshmem_barrier_all /
    __syncthreads-free barrier_all_block, kernels/nvidia/common_ops.py:142-217).

    Signals every team member's global barrier semaphore, then waits for the
    whole team. O(n) signals over ICI; fine for the n<=8-per-axis meshes this
    targets per hop. Requires the surrounding pallas_call to set a
    collective_id (compiler_params) so all devices agree on the barrier
    semaphore."""
    cap = _vcap.active()
    if cap is not None:
        cap.barrier()
        return
    # one barrier note (the fan-out below signals through raw pltpu
    # calls, so nothing double-records)
    _conform.note_barrier()
    if isinstance(axis, str):
        n = jax.lax.axis_size(axis)
    else:
        n = 1
        for ax in axis:
            n = n * jax.lax.axis_size(ax)

    def with_sem(bsem):
        inc = _fault_signal_mask(1, axis, "barrier")

        def body(i, _):
            pltpu.semaphore_signal(
                bsem,
                inc=inc,
                device_id=team_device_id(axis, i)
                if isinstance(axis, str)
                else team_linear_device_id(axis, i),
                device_id_type=pltpu.DeviceIdType.MESH,
            )
            return _

        jax.lax.fori_loop(0, n, body, None)
        if _guard.current() is None:
            pltpu.semaphore_wait(bsem, n)
        else:
            _guard.watchdog_wait(lambda: pltpu.semaphore_wait(bsem, n),
                                 bsem, n, "barrier")
        _obs.meter_wait("sem_wait")

    _compat.scoped_collective_sem(with_sem)


def neighbor_barrier(axis: str, me, n: int) -> None:
    """Barrier with the two ring neighbors only — the standard prologue of
    ring kernels so remote DMA never lands in a peer that has not yet
    entered the kernel. Cheaper than barrier_all when only neighbors
    communicate (ref: the cuStreamWriteValue barrier preambles of
    kernels/nvidia/allgather.py:106-138)."""
    cap = _vcap.active()
    if cap is not None:
        # recorded as its exact sem decomposition — a neighbor sync is
        # NOT a full barrier cut, and modeling it as one would invent
        # happens-before the hardware does not provide
        bsem = _vcap.SymSem("__nbar__").at()
        for d in ((me - 1 + n) % n, (me + 1) % n):
            cap.signal(bsem, 1, d)
        cap.wait(bsem, 2)
        return

    def with_sem(bsem):
        inc = _fault_signal_mask(1, axis, "barrier")
        for d in (jax.lax.rem(me - 1 + n, n), jax.lax.rem(me + 1, n)):
            # recorded under the reserved NBAR identity: the model
            # shares one symbolic "__nbar__" sem across barriers while
            # the hardware scopes a fresh collective semaphore each
            # time — a naming difference with no protocol content
            _conform.note_signal(bsem, 1, d, nbar=True)
            pltpu.semaphore_signal(
                bsem, inc=inc, device_id={axis: d},
                device_id_type=pltpu.DeviceIdType.MESH,
            )
        _conform.note_wait(bsem, 2, nbar=True)
        if _guard.current() is None:
            pltpu.semaphore_wait(bsem, 2)
        else:
            _guard.watchdog_wait(lambda: pltpu.semaphore_wait(bsem, 2),
                                 bsem, 2, "barrier")
        _obs.meter_wait("sem_wait")

    _compat.scoped_collective_sem(with_sem)


def sync_all(axis: AxisName) -> None:
    """Alias of barrier_all — on TPU there is no separate 'quiet' phase
    because delivery semaphores already track payload arrival."""
    barrier_all(axis)


def straggler_delay(axis: AxisName, rank, nanos: int, sem=None) -> None:
    """Race-provocation hook: stall one team member inside the kernel
    (ref: the `straggler_option` per-rank torch.cuda._sleep injection,
    allgather_gemm.py:602-603 / allreduce.py:137-142, and the
    `for_correctness` random producer sleeps, allgather.py:74-78). A
    protocol kernel that is only correct when ranks happen to run in
    lockstep will corrupt data or hang under this delay — which is the
    point. rank < 0 or nanos == 0 is a no-op.

    Native TPU uses pl.delay (cycle-accurate). pl.delay is a NO-OP in
    interpret mode, so on the CPU mesh the stall is a loop of effectful
    self-signal/wait pairs on a semaphore — each iteration is real
    interpreter wall time on the delayed rank's executor thread, which
    is what actually skews rank progress there (nanos maps to iterations
    loosely; provocation needs skew, not precision).

    `sem`: the churn semaphore (defaults to the collective barrier
    semaphore). CAUTION — the semaphore churn is single-core-only: in a
    multi-core interpret kernel the unqualified signal and the wait can
    land on different cores' semaphore instances and deadlock; such
    kernels must implement their own delay from per-core primitives
    (e.g. a local-DMA churn — see the megakernel AR branch)."""
    if _vcap.active() is not None:
        return  # pure timing perturbation: no protocol content to model
    if nanos <= 0:
        return
    from triton_dist_tpu.lang.core import use_interpret

    me = my_pe(axis)

    @pl.when(me == rank)
    def _():
        if use_interpret():
            def with_sem(csem):
                def churn(_, carry):
                    pltpu.semaphore_signal(csem, inc=1)
                    pltpu.semaphore_wait(csem, 1)
                    return carry

                jax.lax.fori_loop(0, max(1, nanos // 5000), churn, 0)

            if sem is None:
                _compat.scoped_collective_sem(with_sem)
            else:
                with_sem(sem)
        else:
            pl.delay(nanos)


def fault_delay(axis: AxisName, protocol: str, sem=None) -> None:
    """Inject the active FaultPlan's scheduled straggler for `protocol`
    (DelayedSend / StalledRank -> straggler_delay at the faulted rank).
    Kernels without their own straggler= hook call this once after
    their entry barrier; no active plan is a trace-time no-op (the
    zero-cost-off contract)."""
    plan = _fplan.active()
    if plan is None:
        return
    s = plan.straggler_for(protocol)
    if s is not None:
        straggler_delay(axis, s[0], s[1], sem=sem)


def guard_progress(value) -> None:
    """Record the kernel's progress counter (ring step, chunk id) into
    the ambient guard context — watchdog trips report it. No active
    guard build: trace-time no-op."""
    _guard.set_progress(value)


def getmem_nbi(
    dst_ref,
    src_ref,
    send_sem,
    recv_sem,
    from_pe,
    axis: AxisName,
    reader_pe=None,
) -> PutHandle:
    """Pull `from_pe`'s src_ref into local dst_ref
    (ref: nvshmem_getmem_nbi_block, libshmem_device.py:181-210).

    ICI RDMA is push-only, so a get is its matched push in the SPMD
    program: every rank pushes its src to the rank that reads it. The
    read pattern must be a team permutation me -> from_pe(me);
    `reader_pe` is its inverse (the rank whose from_pe is me). For shift
    patterns from_pe = me+d it defaults to me-d; pass it explicitly for
    other permutations. The handle's wait_recv() is this rank's get
    completion."""
    # reader_pe inference is valid ONLY for uniform ring shifts
    # (from_pe = me+d with the same d on every rank). For any other
    # permutation the inferred inverse targets the wrong rank and the
    # failure is a silent corruption or hang — and shift-uniformity is
    # not locally checkable (it is a property of from_pe across ranks).
    # STRICT BY DEFAULT (round-4 verdict weak #6): omitting reader_pe is
    # a trace-time error; TDT_INFER_GETMEM=1 opts back into shift
    # inference for code that guarantees uniform-shift patterns.
    if reader_pe is None and os.environ.get("TDT_INFER_GETMEM") != "1":
        raise ValueError(
            "getmem_nbi: reader_pe not given — the shift inference is "
            "only correct for uniform ring shifts and fails SILENTLY "
            "otherwise; pass reader_pe (the inverse permutation) "
            "explicitly, or set TDT_INFER_GETMEM=1 to accept inference "
            "for guaranteed-shift patterns"
        )
    me = my_pe(axis)
    n = n_pes(axis)
    if reader_pe is None:
        if _vcap.active() is not None:
            # symbolic shift inference (me is a Sym; python arithmetic)
            d = (from_pe - me + n) % n
            reader_pe = (me - d + n) % n
        else:
            # infer the matched shift: from_pe = me+d  =>  reader = me-d
            d = jax.lax.rem(from_pe - me + n, n)
            reader_pe = jax.lax.rem(me - d + n, n)
    return putmem_nbi(dst_ref, src_ref, send_sem, recv_sem, reader_pe,
                      axis)


def getmem(dst_ref, src_ref, send_sem, recv_sem, from_pe,
           axis: AxisName, reader_pe=None) -> None:
    """Blocking get: returns when the pulled payload is in dst_ref."""
    getmem_nbi(dst_ref, src_ref, send_sem, recv_sem, from_pe, axis,
               reader_pe).wait()


def broadcast(dst_ref, src_ref, send_sem, recv_sem, root, axis: str,
              n: int) -> None:
    """Team broadcast: root's src_ref lands in every rank's dst_ref
    (ref: nvshmem_broadcast_block wrapper, nvshmem_wrapper.cu:28-80).

    Root pushes to all peers; non-roots wait one delivery. `n` must be
    the static team size (the send fan-out is unrolled). Caller must
    barrier the team before the FIRST collective of a kernel (same
    precondition as fcollect): a put must never land in a peer that has
    not yet entered the kernel."""
    cap = _vcap.active()
    if cap is not None:
        me = _vcap.Sym.var("me")
        with cap.when(me == root):
            cp = cap.copy(dst_ref, src_ref, send_sem)
            handles = [
                putmem_nbi(dst_ref, src_ref, send_sem, recv_sem,
                           (root + i) % n, axis)
                for i in range(1, n)
            ]
            cp.wait()
            for h in handles:
                h.wait_send()
        with cap.when(me != root):
            cap.wait(recv_sem, 1)
        return
    if _compat.legacy_interpret_active():
        # The 0.4.x interpreter discharges remote DMA through lockstep
        # all_gathers: the divergent root-only send below would deadlock
        # the gather. Value-level broadcast is exact in that lockstep
        # model (interpret only — never reached on hardware).
        data = jax.lax.all_gather(src_ref[...], axis)
        dst_ref[...] = jax.lax.dynamic_index_in_dim(data, root, 0,
                                                    keepdims=False)
        return
    me = my_pe(axis)

    @pl.when(me == root)
    def _send():
        cp = pltpu.make_async_copy(src_ref, dst_ref, send_sem)
        cp.start()
        handles = []
        for i in range(1, n):
            peer = jax.lax.rem(root + i, n)
            handles.append(
                putmem_nbi(dst_ref, src_ref, send_sem, recv_sem, peer,
                           axis)
            )
        cp.wait()
        for h in handles:
            h.wait_send()

    @pl.when(me != root)
    def _recv():
        # wait descriptor: same shape/sems as the incoming put
        device_id, id_type = _dma_device_id(axis, me)
        pltpu.make_async_remote_copy(
            src_ref=src_ref, dst_ref=dst_ref,
            send_sem=send_sem, recv_sem=recv_sem,
            device_id=device_id,
            device_id_type=id_type,
        ).wait_recv()


def fcollect_slots(slot_ref_of, src_ref, local_sem, send_sem, recv_sem,
                   axis: str, n: int) -> None:
    """Core of fcollect with a caller-shaped destination: slot_ref_of(me)
    must return the rank-`me` slot ref of the (symmetric) destination.
    Used directly by kernels whose gather target is not row-flat (e.g.
    the parity-buffered low-latency allgather)."""
    cap = _vcap.active()
    if cap is not None:
        me = _vcap.Sym.var("me")
        cp = cap.copy(slot_ref_of(me), src_ref, local_sem)
        handles = []
        for i in range(1, n):
            peer = (me + i) % n
            handles.append(
                putmem_nbi(slot_ref_of(me), src_ref, send_sem, recv_sem,
                           peer, axis)
            )
        cp.wait()
        for h in handles:
            h.wait()
        return
    me = my_pe(axis)

    cp = pltpu.make_async_copy(src_ref, slot_ref_of(me), local_sem)
    cp.start()
    handles = []
    for i in range(1, n):
        peer = jax.lax.rem(me + i, n)
        handles.append(
            putmem_nbi(slot_ref_of(me), src_ref, send_sem, recv_sem,
                       peer, axis)
        )
    cp.wait()
    for h in handles:
        # wait() covers our n-1 sends and, by symmetry, the n-1 incoming
        # puts of identical size targeting our slots.
        h.wait()


def fcollect(dst_ref, src_ref, local_sem, send_sem, recv_sem,
             axis: str, n: int) -> None:
    """Flat collect: every rank's src_ref (m rows) gathered into every
    rank's dst_ref (n*m rows), rank-major (ref: nvshmem_fcollect —
    the device-side allgather primitive). Full-mesh push: each rank puts
    its shard into slot `me` of all peers. Caller must barrier the team
    before first use (see kernels/allgather.py full-mesh kernel)."""
    if _vcap.active() is not None:
        fcollect_slots(lambda me: dst_ref.at(me), src_ref, local_sem,
                       send_sem, recv_sem, axis, n)
        return
    m = src_ref.shape[0]
    fcollect_slots(
        lambda me: dst_ref.at[pl.ds(me * m, m)],
        src_ref, local_sem, send_sem, recv_sem, axis, n,
    )
