"""Host→device work-injection ring for megakernel-resident serving.

The reference's endgame is the MegaTritonKernel driving a socket model
server (PAPER.md L7): the device holds the step loop and the host only
FEEDS it. T3's compute-triggered communication (arXiv 2401.16677) is
the idiom — the device reacts to work ARRIVALS instead of the host
re-dispatching per step. This module is the wire format of that
arrival channel, shared by the host producer (`InjectionRing`) and the
device consumer (`device_consume` / `slot_plan`, pure jnp traceable
into the resident step loop `models/engine.make_resident_loop`
compiles).

Two mirrored rings:

  injection ring  (cap, RW) i32 — host-written per-slot
                  admission/retirement records, consumed by the device
                  AT STEP BOUNDARIES in publication order. A record is
                  one row: a fixed header, the slot's page-table row
                  (full-lifetime allocation: the host reserves every
                  page the request can ever touch at admission, so the
                  device never needs a mid-loop allocator), and the
                  prompt tokens (padded; the device streams prefill
                  chunks straight out of the ring row — no copy).
  output ring     (out_cap, OW) i32 — device-written completion
                  records (emitted tokens + retirement flags), drained
                  by the host after each window so detokenization
                  streams while the device keeps stepping.

Visibility discipline (the lock-free part): `IR_SEQ` is the LAST field
the host commits — a record is visible to the device only when its
stored seq equals `consumed + 1`. A published-but-not-visible head
record (torn write, crashed producer) is an ABANDONED ring: the device
polls it a bounded number of times and exits the window with the
`starved` flag set instead of spinning — the faults-plane watchdog
contract (docs/robustness.md) applied to the injection channel. The
host side (`serve.worker.ResidentWorker`) turns a starved window into
a structured `DeadlineExceeded` guard trip, never a hang.

`IR_AT_STEP` gates a visible record on the device step counter, so
tests and arrival-replay harnesses can stagger admissions INSIDE one
resident window (a record with at_step=s is consumed at the boundary
of device step s, exactly as if the host had injected it then).
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

# -- injection record header (i32 fields) ------------------------------------

IR_SEQ = 0         # 1-based publication seq; 0 = never written (the gate)
IR_KIND = 1        # KIND_* below
IR_SLOT = 2        # target slot lane
IR_AT_STEP = 3     # device step this record becomes consumable at
IR_PROMPT_LEN = 4  # admission: tokens to prefill (full history)
IR_MAX_NEW = 5     # admission: output-token budget
IR_TEMP_BITS = 6   # admission: f32 temperature bit pattern
IR_SEED = 7        # admission: sampling seed (per-request key stream)
IR_EOS = 8         # admission: eos_id + 1; 0 = no eos stop
IR_REQID = 9       # request id (echoed in output records)
IR_NOUT = 10       # verify: the n_out the drafts were proposed at
IR_SPEC_K = 11     # verify: number of staged draft tokens
IR_PREFIX = 12     # admission: prefix-cache hit length (tokens whose
#                    KV is already live in the record's shared pages —
#                    the device starts prefill AND the slot length
#                    there; serve/prefix.py)
IR_HEADER = 16     # header rows reserved (room to grow the contract)

KIND_NOOP = 0      # consumed, no effect (host-side hole punching)
KIND_ADMIT = 1
KIND_RETIRE = 2
KIND_VERIFY = 3    # spec-verify slot (ISSUE 14): k draft tokens staged
#                    in the prompt region; the device verifies them in
#                    the next step iff the slot's req/n_out still match
#                    (a stale record — the slot decoded past the
#                    proposal or turned over — is a consumed no-op)

# -- device→host output record (i32 fields) ----------------------------------

OR_SEQ = 0         # 1-based, dense — the host drains in seq order
OR_SLOT = 1
OR_STEP = 2        # device step the record was written at
OR_TOKEN = 3       # emitted token (-1 on a token-less retirement)
OR_FLAGS = 4       # FLAG_* bits
OR_REASON = 5      # REASON_* on retirement rows
OR_REQID = 6
OR_SPEC_K = 7      # spec-verify steps: drafts verified (on the step's
#                    FIRST record only; 0 elsewhere) — the host's
#                    acceptance-rate source
OR_WIDTH = 8

FLAG_EMIT = 1      # the record carries a sampled token
FLAG_RETIRED = 2   # the slot retired at this record
FLAG_SPEC = 4      # the token came out of a spec-verify step

REASON_EOS = 1
REASON_LENGTH = 2
REASON_HOST = 3    # host-injected retirement (cancel / quarantine)

# -- device slot-state row (K, SS_WIDTH) i32 ---------------------------------

SS_ACTIVE = 0
SS_PHASE = 1       # 0 = prefill, 1 = decode
SS_POS = 2         # prefill progress (tokens already fed)
SS_PROMPT_LEN = 3
SS_MAX_NEW = 4
SS_N_OUT = 5       # tokens emitted so far (the sampling-key index)
SS_TEMP_BITS = 6
SS_SEED = 7
SS_EOS = 8         # eos_id + 1; 0 = none
SS_LAST_TOK = 9    # decode input (the previous emission)
SS_REC = 10        # ring row of the admission record (prompt source)
SS_REQID = 11
SS_SPEC_REC = 12   # ring row of a pending verify record (draft source)
SS_SPEC_SEQ = 13   # that record's seq (self-validation against reuse)
SS_SPEC_K = 14     # staged draft count; 0 = no verify pending
#                    (one-shot: cleared after the step that used it)
SS_WIDTH = 16


def ring_width(max_pages: int, prompt_cap: int, chunk: int) -> int:
    """Record width: header + page-table row + prompt region. The
    prompt region is over-provisioned by one chunk so the device's
    fixed-size dynamic_slice at the LAST prefill position never clamps
    back into valid tokens (the tail reads zeros instead)."""
    return IR_HEADER + max_pages + prompt_cap + chunk


class OutRecord(NamedTuple):
    """One decoded output-ring record (host side)."""

    seq: int
    slot: int
    step: int
    token: int
    flags: int
    reason: int
    req_id: int
    spec_k: int = 0

    @property
    def emitted(self) -> bool:
        return bool(self.flags & FLAG_EMIT)

    @property
    def retired(self) -> bool:
        return bool(self.flags & FLAG_RETIRED)

    @property
    def spec(self) -> bool:
        return bool(self.flags & FLAG_SPEC)


def decode_out_ring(buf, count: int) -> List[OutRecord]:
    """Decode the first `count` output records; enforces the dense
    1-based seq discipline (a gap means the device scatter broke)."""
    a = np.asarray(buf)
    assert a.ndim == 2 and a.shape[1] == OR_WIDTH, f"bad out ring {a.shape}"
    assert 0 <= count <= a.shape[0], f"out count {count} vs cap {a.shape[0]}"
    out = []
    for i in range(count):
        r = a[i]
        if int(r[OR_SEQ]) != i + 1:
            raise ValueError(
                f"output ring row {i} carries seq {int(r[OR_SEQ])} "
                f"(expected {i + 1}) — device scatter drift")
        out.append(OutRecord(
            seq=int(r[OR_SEQ]), slot=int(r[OR_SLOT]), step=int(r[OR_STEP]),
            token=int(r[OR_TOKEN]), flags=int(r[OR_FLAGS]),
            reason=int(r[OR_REASON]), req_id=int(r[OR_REQID]),
            spec_k=int(r[OR_SPEC_K])))
    return out


def summarize_records(records) -> dict:
    """Per-request roll-up of drained output records — the output-ring
    metadata leg of the request ledger (ISSUE 13, trace/ledger.py):

        {req_id: {"emits": n, "first_step": s0, "last_step": s1,
                  "retired": bool, "reason": REASON_* | 0}}

    `first_step`/`last_step` bound the request's device-step footprint
    in the drained window(s); `emits` counts its sampled tokens."""
    out: dict = {}
    for r in records:
        d = out.setdefault(r.req_id, {
            "emits": 0, "first_step": r.step, "last_step": r.step,
            "retired": False, "reason": 0})
        d["first_step"] = min(d["first_step"], r.step)
        d["last_step"] = max(d["last_step"], r.step)
        if r.emitted:
            d["emits"] += 1
        if r.retired:
            d["retired"] = True
            d["reason"] = r.reason
    return out


# -- host producer ------------------------------------------------------------


class InjectionRing:
    """Host-side producer of injection records (numpy; the scheduler
    thread owns it). `published` counts committed records; the device
    reports back `consumed` after each window and the producer refuses
    to overwrite an unreclaimable row (bounded ring, loud overflow).

    Commit order matters: every field of the row is written BEFORE the
    seq field — on real shared memory the seq store is the release
    fence; here it is what the torn-write fault (`abandon`) omits.

    Row lifetime is LONGER than consumption for admissions: the device
    streams prefill chunks straight out of the admission row
    (slot_plan reads `ring[SS_REC]`) for as long as the slot is in
    PREFILL — long after the record itself was consumed at its
    admission boundary. Every admission therefore PINS its row
    (keyed by req_id) and `_claim_row` refuses to wrap onto a pinned
    row; the consumer side calls `unpin` once the request's first
    emission (prefill complete) or retirement record comes back.
    Without the pin, ring churn during a long prefill could reclaim
    and overwrite the row mid-stream — silently wrong tokens, the
    exact class the resident mode's bit-identity contract forbids."""

    def __init__(self, cap: int, max_pages: int, prompt_cap: int,
                 chunk: int):
        assert cap >= 2 and max_pages >= 1 and prompt_cap >= 1
        self.cap = cap
        self.max_pages = max_pages
        self.prompt_cap = prompt_cap
        self.chunk = chunk
        self.width = ring_width(max_pages, prompt_cap, chunk)
        self.buf = np.zeros((cap, self.width), np.int32)
        self.published = 0
        self.consumed = 0  # device-acknowledged (refreshed per window)
        self.version = 0   # bumped per mutation (device-upload cache key)
        self._pins = {}    # req_id -> admission record seq (1-based)

    def pending(self) -> int:
        return self.published - self.consumed

    def _reclaimable(self) -> int:
        """Records whose rows may be overwritten: consumed AND not
        pinned by an in-flight prefill (rows recycle in FIFO order, so
        the oldest pin caps the watermark)."""
        floor = self.consumed
        if self._pins:
            floor = min(floor, min(self._pins.values()) - 1)
        return floor

    def can_claim(self) -> bool:
        """Room for one more record without touching an unconsumed or
        pinned row — the producer's backpressure probe (the scheduler
        defers admissions/retirements instead of overflowing)."""
        return self.published - self._reclaimable() < self.cap

    def unpin(self, req_id: int) -> None:
        """Release an admission row for reuse: the request's prefill
        completed (first emission) or it retired."""
        self._pins.pop(req_id, None)

    def _claim_row(self) -> int:
        if not self.can_claim():
            raise RuntimeError(
                f"injection ring overflow: {self.pending()} pending + "
                f"{len(self._pins)} pinned record(s) at cap {self.cap} "
                "(device not consuming, or a prefill still streaming "
                "from its admission row)")
        return self.published % self.cap

    def _commit(self, row: int) -> None:
        self.buf[row, IR_SEQ] = self.published + 1
        self.published += 1
        self.version += 1

    def admit(self, slot: int, prompt, max_new: int, temperature: float,
              seed: int, eos_id: Optional[int], req_id: int,
              table_row, at_step: int = 0, prefix: int = 0) -> None:
        prompt = np.asarray(prompt, np.int32)
        assert prompt.ndim == 1 and 1 <= prompt.size <= self.prompt_cap, (
            f"prompt of {prompt.size} tokens vs cap {self.prompt_cap}")
        assert 0 <= prefix < prompt.size, (
            f"prefix {prefix} must leave >= 1 token of {prompt.size} "
            "to prefill")
        table_row = np.asarray(table_row, np.int32)
        assert table_row.shape == (self.max_pages,), (
            f"table row {table_row.shape} != ({self.max_pages},)")
        row = self._claim_row()
        r = self.buf[row]
        r[:] = 0
        r[IR_KIND] = KIND_ADMIT
        r[IR_SLOT] = slot
        r[IR_AT_STEP] = at_step
        r[IR_PROMPT_LEN] = prompt.size
        r[IR_MAX_NEW] = max_new
        r[IR_TEMP_BITS] = np.float32(temperature).view(np.int32)
        r[IR_SEED] = seed
        r[IR_EOS] = 0 if eos_id is None else eos_id + 1
        r[IR_REQID] = req_id
        r[IR_PREFIX] = prefix
        r[IR_HEADER:IR_HEADER + self.max_pages] = table_row
        r[IR_HEADER + self.max_pages:
          IR_HEADER + self.max_pages + prompt.size] = prompt
        self._commit(row)
        self._pins[req_id] = self.published  # this record's seq

    def verify(self, slot: int, req_id: int, n_out: int, drafts,
               at_step: int = 0) -> object:
        """Stage a spec-verify record (KIND_VERIFY, ISSUE 14): the
        draft tokens ride in the prompt region; the device verifies
        them in its next step iff the slot still serves `req_id` at
        exactly `n_out` emitted tokens (staleness self-check — the
        device may have decoded past the proposal). Returns the pin
        key: verify rows are read by the step AFTER consumption, so
        the producer pins them like admission rows; the worker unpins
        once the window the record rode has returned."""
        drafts = np.asarray(drafts, np.int32)
        assert drafts.ndim == 1 and 1 <= drafts.size <= self.prompt_cap
        row = self._claim_row()
        r = self.buf[row]
        r[:] = 0
        r[IR_KIND] = KIND_VERIFY
        r[IR_SLOT] = slot
        r[IR_AT_STEP] = at_step
        r[IR_REQID] = req_id
        r[IR_NOUT] = n_out
        r[IR_SPEC_K] = drafts.size
        r[IR_HEADER + self.max_pages:
          IR_HEADER + self.max_pages + drafts.size] = drafts
        self._commit(row)
        pin = ("spec", self.published)
        self._pins[pin] = self.published
        return pin

    def retire(self, slot: int, req_id: int, at_step: int = 0) -> None:
        row = self._claim_row()
        r = self.buf[row]
        r[:] = 0
        r[IR_KIND] = KIND_RETIRE
        r[IR_SLOT] = slot
        r[IR_AT_STEP] = at_step
        r[IR_REQID] = req_id
        self._commit(row)

    def abandon(self) -> None:
        """Publish WITHOUT committing the record (seq stays stale): the
        torn-write / crashed-producer fault. The device must exit its
        bounded poll with the starved flag — never spin, never consume
        the garbage row (faults.plan.AbandonedRing injects this)."""
        row = self._claim_row()
        self.buf[row, IR_SEQ] = 0
        self.published += 1
        self.version += 1

    def ack(self, consumed: int) -> None:
        """Fold the device's post-window consumed count back in."""
        assert self.consumed <= consumed <= self.published, (
            f"device consumed {consumed} outside "
            f"[{self.consumed}, {self.published}]")
        self.consumed = consumed


# -- device consumer (pure jnp; traced into the resident loop) ---------------


def head_visible(ring, published, consumed, step):
    """Is the head record consumable right now? (seq committed AND its
    at_step gate open.)"""
    cap = ring.shape[0]
    head = ring[consumed % cap]
    return ((consumed < published)
            & (head[IR_SEQ] == consumed + 1)
            & (head[IR_AT_STEP] <= step))


def head_abandoned(ring, published, consumed):
    """Pending but not committed: the head row's seq doesn't match the
    expected publication number (torn write / crashed producer)."""
    cap = ring.shape[0]
    head = ring[consumed % cap]
    return (consumed < published) & (head[IR_SEQ] != consumed + 1)


def device_consume(ring, published, consumed, step, slot_state, table,
                   lengths):
    """Consume every currently-visible record at a step boundary.

    Returns (consumed, slot_state, table, lengths, retired_now) where
    retired_now (K,) i32 flags slots a RETIRE record deactivated at
    THIS boundary (the caller reports them out). ADMIT loads the slot
    row, installs the record's page-table row, and starts the slot
    length (and prefill cursor) at the record's IR_PREFIX — 0 on a
    cold admission; a prefix-cache hit starts both at the cached
    coverage, whose KV is already live in the shared pages the table
    row carries (serve/prefix.py). RETIRE deactivates iff the record's
    req_id matches the slot's (a stale retirement for an already-
    self-retired request is a no-op). VERIFY stages the record's
    drafts on the slot (SS_SPEC_*) iff the slot still serves that
    req_id at that n_out in decode — else a consumed no-op. Bounded:
    consumes at most `published - consumed` rows.
    """
    cap = ring.shape[0]
    max_pages = table.shape[1]
    retired0 = jnp.zeros((slot_state.shape[0],), jnp.int32)

    def cond(carry):
        consumed, ss, tb, ln, rt = carry
        return head_visible(ring, published, consumed, step)

    def body(carry):
        consumed, ss, tb, ln, rt = carry
        rec_row = consumed % cap
        rec = ring[rec_row]
        slot = rec[IR_SLOT]
        is_admit = rec[IR_KIND] == KIND_ADMIT
        is_retire = ((rec[IR_KIND] == KIND_RETIRE)
                     & (ss[slot, SS_ACTIVE] > 0)
                     & (ss[slot, SS_REQID] == rec[IR_REQID]))
        is_verify = ((rec[IR_KIND] == KIND_VERIFY)
                     & (ss[slot, SS_ACTIVE] > 0)
                     & (ss[slot, SS_PHASE] == 1)
                     & (ss[slot, SS_REQID] == rec[IR_REQID])
                     & (ss[slot, SS_N_OUT] == rec[IR_NOUT]))
        admit_row = (
            jnp.zeros((SS_WIDTH,), jnp.int32)
            .at[SS_ACTIVE].set(1)
            .at[SS_POS].set(rec[IR_PREFIX])
            .at[SS_PROMPT_LEN].set(rec[IR_PROMPT_LEN])
            .at[SS_MAX_NEW].set(rec[IR_MAX_NEW])
            .at[SS_TEMP_BITS].set(rec[IR_TEMP_BITS])
            .at[SS_SEED].set(rec[IR_SEED])
            .at[SS_EOS].set(rec[IR_EOS])
            .at[SS_REC].set(rec_row)
            .at[SS_REQID].set(rec[IR_REQID])
        )
        retired_row = ss[slot].at[SS_ACTIVE].set(0)
        verify_row = (ss[slot]
                      .at[SS_SPEC_REC].set(rec_row)
                      .at[SS_SPEC_SEQ].set(rec[IR_SEQ])
                      .at[SS_SPEC_K].set(rec[IR_SPEC_K]))
        new_row = jnp.where(
            is_admit, admit_row,
            jnp.where(is_retire, retired_row,
                      jnp.where(is_verify, verify_row, ss[slot])))
        ss = ss.at[slot].set(new_row)
        tb = tb.at[slot].set(jnp.where(
            is_admit, rec[IR_HEADER:IR_HEADER + max_pages], tb[slot]))
        ln = ln.at[slot].set(jnp.where(is_admit, rec[IR_PREFIX],
                                       ln[slot]))
        rt = rt.at[slot].set(jnp.where(is_retire, 1, rt[slot]))
        return consumed + 1, ss, tb, ln, rt

    return jax.lax.while_loop(
        cond, body, (consumed, slot_state, table, lengths, retired0))


def slot_plan(ring, slot_state, chunk: int, max_pages: int):
    """Assemble the per-slot step-plan arrays the serve step consumes —
    exactly what the host-loop scheduler builds each step, computed
    from device slot state instead (docs/serving.md "Device-resident
    serving"):

      tokens (K, C) i32   prefill chunk (streamed from the admission
                          record's prompt region) or [last_tok, 0...]
      n_valid (K,) i32    chunk fill / 1 / 0 — inactive rows are zero
      temps (K,) f32      request temperature ONLY on emitting rows
      keys (K, 2) u32     fold_in(PRNGKey(seed), n_out) on emitting
                          rows (the Worker.key_for derivation, traced)
      emits (K,) bool     the row's sampled token is meaningful
    """
    prompt_base = IR_HEADER + max_pages

    def one(ss_row):
        active = ss_row[SS_ACTIVE] > 0
        prefill = ss_row[SS_PHASE] == 0
        pos = ss_row[SS_POS]
        plen = ss_row[SS_PROMPT_LEN]
        n_pref = jnp.minimum(chunk, plen - pos)
        rec = ring[ss_row[SS_REC]]
        prow = jax.lax.dynamic_slice(
            rec, (prompt_base + pos,), (chunk,))
        drow = (jnp.zeros((chunk,), jnp.int32)
                .at[0].set(ss_row[SS_LAST_TOK]))
        tokens = jnp.where(prefill, prow, drow)
        n = jnp.where(prefill, n_pref, 1)
        n = jnp.where(active, n, 0)
        # zero padding columns like the host scheduler does (they are
        # causal-masked anyway; zeroing keeps the step inputs literal)
        tokens = jnp.where(
            active & (jnp.arange(chunk) < n), tokens, 0)
        emits = active & ((~prefill) | (pos + n_pref >= plen))
        temp = jnp.where(
            emits,
            jax.lax.bitcast_convert_type(ss_row[SS_TEMP_BITS],
                                         jnp.float32),
            jnp.float32(0.0))
        key = jax.random.fold_in(
            jax.random.PRNGKey(ss_row[SS_SEED]), ss_row[SS_N_OUT])
        key = jnp.where(emits, key, jnp.zeros_like(key))
        return tokens, n.astype(jnp.int32), temp, key, emits

    return jax.vmap(one)(slot_state)


def slot_plan_spec(ring, slot_state, chunk: int, max_pages: int,
                   k_max: int):
    """The spec-capable step plan (ISSUE 14): like `slot_plan`, plus a
    decoding slot with a FRESH staged verify record (SS_SPEC_*, set by
    device_consume) becomes a VERIFY row — [last_tok, d_1..d_kd] with
    n_valid = 1 + kd — and every column carries its own sampling key
    (fold_in(PRNGKey(seed), n_out + column-offset): the per-(seed,
    token-index) stream, so column j's token is bitwise the sequential
    emission for output index n_out + j).

    Freshness is self-validated against the ring row (seq / kind /
    req_id / n_out all re-checked): a verify row the producer has
    since overwritten, or one staged for a state the slot has decoded
    past, degrades to the plain one-token decode row — stale proposals
    cost nothing and can never corrupt.

    Returns (tokens (K, C) i32, n_valid (K,), temps (K,) f32,
    keys (K, C, 2) u32, emits (K,) bool, kd (K,) i32)."""
    prompt_base = IR_HEADER + max_pages

    def one(ss_row):
        active = ss_row[SS_ACTIVE] > 0
        prefill = ss_row[SS_PHASE] == 0
        pos = ss_row[SS_POS]
        plen = ss_row[SS_PROMPT_LEN]
        n_pref = jnp.minimum(chunk, plen - pos)
        rec = ring[ss_row[SS_REC]]
        prow = jax.lax.dynamic_slice(
            rec, (prompt_base + pos,), (chunk,))
        # -- staged verify record, self-validated against the ring row
        srec = ring[ss_row[SS_SPEC_REC]]
        fresh = (active & (~prefill) & (ss_row[SS_SPEC_K] > 0)
                 & (srec[IR_SEQ] == ss_row[SS_SPEC_SEQ])
                 & (srec[IR_KIND] == KIND_VERIFY)
                 & (srec[IR_REQID] == ss_row[SS_REQID])
                 & (srec[IR_NOUT] == ss_row[SS_N_OUT]))
        kd = jnp.where(
            fresh,
            jnp.minimum(
                jnp.minimum(ss_row[SS_SPEC_K], k_max),
                jnp.minimum(
                    chunk - 1,
                    ss_row[SS_MAX_NEW] - ss_row[SS_N_OUT] - 1)),
            0)
        kd = jnp.maximum(kd, 0)
        drow_spec = jax.lax.dynamic_slice(
            srec, (prompt_base,), (chunk,))
        drow = jnp.concatenate(
            [ss_row[SS_LAST_TOK][None], drow_spec[:chunk - 1]])
        tokens = jnp.where(prefill, prow, drow)
        n = jnp.where(prefill, n_pref, 1 + kd)
        n = jnp.where(active, n, 0)
        tokens = jnp.where(
            active & (jnp.arange(chunk) < n), tokens, 0)
        emits = active & ((~prefill) | (pos + n_pref >= plen))
        temp = jnp.where(
            emits,
            jax.lax.bitcast_convert_type(ss_row[SS_TEMP_BITS],
                                         jnp.float32),
            jnp.float32(0.0))
        # per-column keys: column j emits output index
        # n_out + (j - base), base = n - 1 - kd (decode rows sample
        # from column 0; a completing prefill from column n-1)
        base = jnp.maximum(n - 1 - kd, 0)
        idx = jnp.maximum(
            ss_row[SS_N_OUT] + jnp.arange(chunk) - base, 0)
        key0 = jax.random.PRNGKey(ss_row[SS_SEED])
        keys = jax.vmap(lambda i: jax.random.fold_in(key0, i))(idx)
        keys = jnp.where(emits, keys, jnp.zeros_like(keys))
        return (tokens, n.astype(jnp.int32), temp, keys, emits,
                kd.astype(jnp.int32))

    return jax.vmap(one)(slot_state)
