"""Qwen3 megakernel model: the whole TP decode layer stack as one task
graph executed by a single persistent Pallas kernel per step.

TPU-native re-design of the reference's Qwen3 megakernel
(ref: python/triton_dist/mega_triton_kernel/models/qwen3.py and
models/layers/{tp_attn,tp_mlp}.py): the per-layer make_* calls build one
Graph; the scheduler orders it; compile_graph lowers it to one
pallas_call. The decode step is then: embed (XLA gather) -> megakernel ->
lm_head matmul + logits all-gather (XLA) -> KV scatter (XLA
dynamic-update fused into the same jit) — two XLA ops around one kernel,
the TPU shape of "one launch per decode step".

Weights reuse models.dense's DenseLLMParams layout verbatim, so a
DenseLLM/Engine checkpoint drops in (the ref megakernel also reuses the
HF weights of its eager model, test/models/test_qwen3.py).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_dist_tpu.layers import rope_table
from triton_dist_tpu.mega.builder import ModelBuilder
from triton_dist_tpu.mega.kernel import CompiledMega, compile_graph
from triton_dist_tpu.mega.scheduler import schedule_graph, validate_schedule
from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.models.dense import (
    DenseLLMParams,
    init_params,
    param_specs,
)
from triton_dist_tpu.runtime.init import TP_AXIS


class MegaKVCache(NamedTuple):
    """Decode cache in megakernel layout (L, Hkv_loc, B, S_max, D): the
    per-head read `k[layer, h]` slices only leading dims, which is the
    Mosaic-friendly access (kernel.py module docstring)."""

    k: jax.Array
    v: jax.Array
    length: jax.Array  # (B,)

    @staticmethod
    def create(cfg: ModelConfig, batch: int, s_max: int, hkv_loc: int):
        shape = (cfg.num_layers, hkv_loc, batch, s_max, cfg.head_dim)
        dt = jnp.dtype(cfg.dtype)
        return MegaKVCache(jnp.zeros(shape, dt), jnp.zeros(shape, dt),
                           jnp.zeros((batch,), jnp.int32))

    @staticmethod
    def from_dense(cache, s_max: Optional[int] = None) -> "MegaKVCache":
        """Convert a models.kv_cache.KVCache (L, B, T, Hkv, D) — e.g. the
        output of an Engine prefill — into megakernel layout."""
        k = jnp.moveaxis(cache.k, 3, 1)  # (L, Hkv, B, T, D)
        v = jnp.moveaxis(cache.v, 3, 1)
        if s_max is not None and s_max != k.shape[3]:
            pad = s_max - k.shape[3]
            assert pad >= 0, "prefill longer than megakernel s_max"
            k = jnp.pad(k, ((0, 0), (0, 0), (0, 0), (0, pad), (0, 0)))
            v = jnp.pad(v, ((0, 0), (0, 0), (0, 0), (0, pad), (0, 0)))
        return MegaKVCache(k, v, cache.length)


class PagedMegaKVCache(NamedTuple):
    """Paged decode cache (ref: mega_triton_kernel/models/
    paged_kv_cache.py): k/v are SHARED page pools
    (L, Hkv_loc, n_pages, PAGE, D); `table` (B, MAXP) int32 maps
    (sequence, page index) -> pool page, allocated on demand (bump
    allocator `next_free`) as sequences grow — ragged batches consume
    pool pages proportional to their ACTUAL lengths, not B * S_max."""

    k: jax.Array
    v: jax.Array
    table: jax.Array      # (B, MAXP) int32; 0 until allocated
    length: jax.Array     # (B,)
    next_free: jax.Array  # () int32 bump-allocator head

    @staticmethod
    def create(cfg: ModelConfig, batch: int, hkv_loc: int, page: int,
               max_pages: int, total_pages: int) -> "PagedMegaKVCache":
        dt = jnp.dtype(cfg.dtype)
        shape = (cfg.num_layers, hkv_loc, total_pages, page, cfg.head_dim)
        return PagedMegaKVCache(
            jnp.zeros(shape, dt), jnp.zeros(shape, dt),
            jnp.zeros((batch, max_pages), jnp.int32),
            jnp.zeros((batch,), jnp.int32),
            jnp.zeros((), jnp.int32),
        )

    @staticmethod
    def from_dense(cache, page: int, total_pages: int,
                   max_pages: int) -> "PagedMegaKVCache":
        """Page an Engine prefill cache (L, B, T, Hkv, D): each
        sequence's VALID prefix (cache.length, not the cache's full
        allocated T — Engine allocates at max_len) claims
        ceil(len/page) consecutive pool pages, so
        next_free == sum_b ceil(len_b / page) and ragged batches share
        the pool. Runs outside jit: lengths are concrete, and the page
        walk is a host-built gather over the cache's page grid."""
        L, B, T, Hkv, D = cache.k.shape
        assert T % page == 0, f"cache len {T} % page {page}"
        lengths = np.asarray(cache.length)
        pages_per = -(-lengths // page)  # ceil
        used = int(pages_per.sum())
        assert used <= total_pages, "pool too small for the prefill"
        assert int(pages_per.max(initial=0)) <= max_pages, (
            "prefill longer than the table's max_pages"
        )
        # (seq, page-in-seq) of each claimed pool page, in claim order
        src_b = np.repeat(np.arange(B), pages_per)
        src_p = np.concatenate(
            [np.arange(p) for p in pages_per]
        ).astype(np.int64) if used else np.zeros((0,), np.int64)
        grid = jnp.moveaxis(cache.k, 3, 1).reshape(
            L, Hkv, B, T // page, page, D)
        gridv = jnp.moveaxis(cache.v, 3, 1).reshape(
            L, Hkv, B, T // page, page, D)
        k = grid[:, :, src_b, src_p]          # (L, Hkv, used, page, D)
        v = gridv[:, :, src_b, src_p]
        pad = total_pages - used
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0)))
        table = np.zeros((B, max_pages), np.int32)
        off = 0
        for b in range(B):
            table[b, :pages_per[b]] = np.arange(off, off + pages_per[b])
            off += int(pages_per[b])
        return PagedMegaKVCache(k, v, jnp.asarray(table), cache.length,
                                jnp.asarray(used, jnp.int32))


def build_qwen3_graph(
    cfg: ModelConfig, batch: int, world: int, s_max: int,
    axis: str = TP_AXIS, page: int = 0,
) -> Tuple[ModelBuilder, dict]:
    """The decode-step task graph (ref: Qwen3 model build over
    model_builder.make_* calls, mega_triton_kernel/models/qwen3.py).

    Norms-array row layout (stacked into one (4L+1, NW) input):
      [0,L) input_ln · [L,2L) post_attn_ln · [2L] final_ln ·
      [2L+1,3L+1) q_norm · [3L+1,4L+1) k_norm
    """
    n = world
    L = cfg.num_layers
    H = cfg.hidden_size
    D = cfg.head_dim
    hq_l = cfg.num_q_heads // n
    hkv_l = cfg.num_kv_heads // n
    i_l = cfg.intermediate_size // n
    wqkv = (hq_l + 2 * hkv_l) * D

    mb = ModelBuilder(batch, axis, world=n)
    x = mb.buffer(H, "x", pinned=True)
    mb.make_barrier()
    kn_bufs, vn_bufs = [], []
    for l in range(L):
        qkv = mb.make_rms_matmul("w_qkv", l, x, H, wqkv, norm_row=l,
                                 eps=cfg.rms_eps, tag=f"ln1+qkv[{l}]")
        attn, kn, vn = mb.make_attention(
            l, qkv, hq_l, hkv_l, D, s_max, cfg.rms_eps, cfg.use_qk_norm,
            q_norm_base=2 * L + 1, k_norm_base=3 * L + 1, page=page,
        )
        kn_bufs.append(kn)
        vn_bufs.append(vn)
        o = mb.make_matmul("w_o", l, attn, hq_l * D, H, tag=f"o[{l}]")
        x = mb.make_allreduce_add(o, x, H, tag=f"ar_attn[{l}]")
        gu = mb.make_rms_matmul("w_gate_up", l, x, H, 2 * i_l,
                                norm_row=L + l, eps=cfg.rms_eps,
                                tag=f"ln2+gate_up[{l}]")
        dn = mb.make_act_matmul("w_down", l, gu, i_l, H,
                                tag=f"silu+down[{l}]")
        x = mb.make_allreduce_add(dn, x, H, tag=f"ar_mlp[{l}]")
    final = mb.make_rms_norm(2 * L, x, H, cfg.rms_eps, tag="final_ln")
    mb.graph.pinned[final.id] = True
    meta = dict(
        input_buf=0, final=final, kn_bufs=kn_bufs, vn_bufs=vn_bufs,
        hq_l=hq_l, hkv_l=hkv_l, i_l=i_l, wqkv=wqkv,
    )
    return mb, meta


class MegaQwen3:
    """Engine-compatible decode over the megakernel (ref: ModelBuilder
    compile/run + model_server loop, mega_triton_kernel/test/models/).

    decode_step matches models.engine.Engine.decode_step's contract:
    tokens (B,) -> (logits (B, V) f32, cache). Prefill runs through the
    regular Engine (the megakernel covers decode, like the reference);
    `from_engine`/MegaKVCache.from_dense bridge the cache layouts.
    """

    def __init__(
        self,
        cfg: ModelConfig,
        mesh,
        batch: int,
        axis: str = TP_AXIS,
        s_max: Optional[int] = None,
        params: Optional[DenseLLMParams] = None,
        seed: int = 0,
        fast_init: bool = False,
        donate_cache: bool = True,
        num_cores: int = 1,
        straggler: tuple = (-1, 0),
        paged: bool = False,
        page_size: Optional[int] = None,
        total_pages: Optional[int] = None,
    ):
        assert not cfg.is_moe, "megakernel covers the dense decode graph"
        from triton_dist_tpu.lang.core import use_interpret

        if not use_interpret() and cfg.head_dim % 128 != 0:
            # the attention branch reshapes (B, H*D) -> (B, H, D): native
            # Mosaic only supports this when the minor dim is lane-width
            raise ValueError(
                f"megakernel on native TPU requires head_dim % 128 == 0 "
                f"(got {cfg.head_dim}); sub-lane head dims run in "
                "interpret mode only"
            )
        n_ = int(mesh.shape[axis])
        assert cfg.num_q_heads % n_ == 0 and cfg.num_kv_heads % n_ == 0, (
            f"head counts ({cfg.num_q_heads}q/{cfg.num_kv_heads}kv) must "
            f"be divisible by the tp size {n_}"
        )
        self.cfg = cfg
        self.mesh = mesh
        self.axis = axis
        self.batch = batch
        self.s_max = s_max or cfg.max_positions
        n = int(mesh.shape[axis])
        self.world = n
        self.hkv_loc = cfg.num_kv_heads // n
        self.params = (
            params if params is not None
            else init_params(cfg, mesh, seed, axis, fast=fast_init)
        )
        dt = jnp.dtype(cfg.dtype)
        self.dtype = dt

        from triton_dist_tpu.mega.kernel import _kv_chunk

        self.paged = paged
        self.page = page_size or _kv_chunk(self.s_max)
        assert self.s_max % self.page == 0
        self.max_pages = self.s_max // self.page
        # shared pool size: B*max_pages reproduces dense; smaller pools
        # SHARE capacity across ragged sequences (allocation is on
        # demand — the point of paging)
        self.total_pages = (total_pages if total_pages is not None
                            else batch * self.max_pages)

        mb, meta = build_qwen3_graph(
            cfg, batch, n, self.s_max, axis,
            page=(page_size or 0) if not paged else self.page,
        )
        self.graph = mb.graph
        sched = schedule_graph(self.graph, num_cores=num_cores)
        validate_schedule(self.graph, sched)
        self.sched = sched
        # construct the model inside trace.building() for a traced
        # megakernel: decode_step then returns (logits, cache, trace_buf)
        from triton_dist_tpu.trace.events import active_build

        self._trace_build = active_build()
        self.cm: CompiledMega = compile_graph(
            self.graph, sched, dt, name=f"mega_qwen3_{axis}{n}",
            straggler=straggler, tiled_weights=("w_gate_up",),
        )
        self._meta = meta

        # fuse gate|up ONCE at init for one-DMA weight streaming in the
        # kernel (params store them split so XLA can fuse the silu
        # epilogue in the eager paths; see models/dense.py) — and lay
        # the fused copy out TILE-MAJOR (L, n, nt, H, TN): this weight
        # is >half the 32B shard's streamed bytes and the copy is being
        # materialized anyway, so re-blocking it is free HBM-wise and
        # turns its per-tile DMA from N-strided TN*2-byte bursts into
        # one fully contiguous K*TN*2-byte block (the round-5 ledger's
        # biggest single burst-efficiency lever; kernel.
        # tile_weight_major). The split copies are then stripped from
        # the pytree this model's jit consumes — the kernel never reads
        # them, and for a standalone MegaQwen3 (no Engine sharing the
        # params) stripping frees their HBM.
        from triton_dist_tpu.mega.kernel import tile_weight_major

        gu_tn = self.cm.tile_cols("w_gate_up")
        self._w_gate_up = jax.jit(
            lambda g, u: tile_weight_major(
                jnp.concatenate([g, u], axis=-1), gu_tn),
            out_shardings=NamedSharding(mesh, P(None, axis)),
        )(self.params.layers.w_gate, self.params.layers.w_up)
        self.params = self.params._replace(
            layers=self.params.layers._replace(w_gate=None, w_up=None)
        )

        L = cfg.num_layers
        NW = self.cm.norm_width
        cos, sin = rope_table(cfg.head_dim, cfg.max_positions,
                              cfg.rope_theta)
        rope_cs = jnp.concatenate([cos, sin], axis=-1)  # (P, D) f32
        # 8-row stripes (see kernel.py norm/rope loads)
        self._rope_cs = jnp.repeat(rope_cs, 8, axis=0)
        self._norms = self._stack_norms(self.params)  # params-only: once

        slot = sched.buf_slot
        pb = self.cm.pb
        self._x_rows = int(slot[0]) * pb  # buffer 0 is the residual input
        self._final_rows = int(slot[meta["final"].id]) * pb
        self._kn_rows = np.array([int(slot[b.id]) * pb
                                  for b in meta["kn_bufs"]])
        self._vn_rows = np.array([int(slot[b.id]) * pb
                                  for b in meta["vn_bufs"]])

        from triton_dist_tpu.mega.kernel import _kv_chunk as _kc

        self._schunk = _kc(self.s_max, (page_size or 0) if not paged
                           else self.page)
        nch_d = self.s_max // self._schunk
        import numpy as _np

        self._ident_table = jnp.asarray(
            _np.arange(batch * nch_d, dtype=_np.int32).reshape(batch,
                                                               nch_d))

        p_specs = param_specs(axis, moe=False)
        p_specs = p_specs._replace(
            layers=p_specs.layers._replace(w_gate=None, w_up=None)
        )
        if paged:
            c_specs = PagedMegaKVCache(
                k=P(None, axis), v=P(None, axis), table=P(), length=P(),
                next_free=P(),
            )
        else:
            c_specs = MegaKVCache(k=P(None, axis), v=P(None, axis),
                                  length=P())

        def step(params: DenseLLMParams, w_gate_up, tokens,
                 cache: MegaKVCache):
            return self._device_step(params, w_gate_up, tokens, cache)

        out_specs = (P(), c_specs)
        if self._trace_build is not None:
            out_specs += (P(axis),)  # per-rank trace buffers, stacked
        self._decode = jax.jit(
            jax.shard_map(
                step, mesh=mesh,
                in_specs=(p_specs, P(None, axis), P(), c_specs),
                out_specs=out_specs,
                check_vma=False,
            ),
            donate_argnums=(3,) if donate_cache else (),
        )
        # resident multi-step decode executables, keyed on step count
        # (decode_resident; same specs as the one-step dispatch),
        # LRU-bounded like Engine._serve_cache — a window-size sweep
        # must not retain one executable per steps value forever
        self._decode_specs = (p_specs, P(None, axis), P(), c_specs)
        self._resident_fns: dict = {}
        self._resident_fns_max = 8
        self._donate = donate_cache

    # -- per-device step (inside shard_map) ---------------------------------

    def _stack_norms(self, params: DenseLLMParams):
        """Stacked norms (4L+1, NW) in f32 8-row stripes (packed bf16
        rows cannot be rank-reduced-sliced by a dynamic index on Mosaic;
        see kernel.py). Depends only on params — computed once at init and
        closed over by the jit (like the rope table)."""
        NW = self.cm.norm_width
        lp = params.layers

        def pad_to(v, w):
            return jnp.pad(v.astype(jnp.float32),
                           ((0, 0), (0, w - v.shape[-1])))

        norms = jnp.concatenate([
            pad_to(lp.input_ln, NW),
            pad_to(lp.post_attn_ln, NW),
            pad_to(params.final_ln[None, :], NW),
            pad_to(lp.q_norm, NW),
            pad_to(lp.k_norm, NW),
        ], axis=0)
        return jnp.repeat(norms, 8, axis=0)

    def _device_step(self, params: DenseLLMParams, w_gate_up, tokens,
                     cache):
        cfg = self.cfg
        L = cfg.num_layers
        H = cfg.hidden_size
        B = self.batch
        pb = self.cm.pb
        lp = params.layers
        dt = self.dtype
        norms = self._norms

        weights = {
            "w_qkv": lp.w_qkv[:, 0],
            "w_o": lp.w_o[:, 0],
            "w_gate_up": w_gate_up[:, 0],
            "w_down": lp.w_down[:, 0],
        }

        x = params.embed[tokens].astype(dt)  # (B, H)
        ws = self.cm.workspace(dt)
        ws = jax.lax.dynamic_update_slice(ws, x, (self._x_rows, 0))
        pos = cache.length

        if isinstance(cache, PagedMegaKVCache):
            k_pool, v_pool, table = cache.k, cache.v, cache.table
        else:
            # dense cache = identity page table over its own page grid
            # (free reshape; one kernel path serves both cache forms)
            Lh, Hh = cache.k.shape[0], cache.k.shape[1]
            nch = self.s_max // self._schunk
            k_pool = cache.k.reshape(Lh, Hh, B * nch, self._schunk,
                                     cfg.head_dim)
            v_pool = cache.v.reshape(Lh, Hh, B * nch, self._schunk,
                                     cfg.head_dim)
            table = self._ident_table

        res = self.cm.run(pos, table, ws, weights, norms,
                          self._rope_cs, k_pool, v_pool)
        if self._trace_build is not None:
            ws_o, trace_buf = res
        else:
            ws_o, trace_buf = res, None

        hidden = jax.lax.dynamic_slice(
            ws_o, (self._final_rows, 0), (pb, self.cm.wmax)
        )[:B, :H]
        head = params.lm_head[0]  # (H, V_loc)
        logits = jnp.dot(hidden, head, preferred_element_type=jnp.float32)
        logits = jax.lax.all_gather(logits, self.axis, axis=1, tiled=True)

        # KV scatter: gather the per-layer k/v rows out of the workspace
        # and write them at each sequence's position (the ref's paged KV
        # append, models/paged_kv_cache.py, as one fused XLA scatter).
        kw = self.hkv_loc * cfg.head_dim
        row_idx = (jnp.asarray(self._kn_rows)[:, None]
                   + jnp.arange(B)[None, :])  # (L, B)
        kn = ws_o[row_idx][..., :kw].reshape(L, B, self.hkv_loc,
                                             cfg.head_dim)
        row_idx_v = (jnp.asarray(self._vn_rows)[:, None]
                     + jnp.arange(B)[None, :])
        vn = ws_o[row_idx_v][..., :kw].reshape(L, B, self.hkv_loc,
                                               cfg.head_dim)
        kn = jnp.moveaxis(kn, 2, 1)  # (L, Hkv, B, D)
        vn = jnp.moveaxis(vn, 2, 1)
        bidx = jnp.arange(B)

        def ret(logits, new_cache):
            if trace_buf is not None:
                return logits, new_cache, trace_buf
            return logits, new_cache

        if isinstance(cache, PagedMegaKVCache):
            # page allocation (bump allocator): a sequence crossing into
            # a fresh page claims the next pool page(s) this step
            pidx = cache.length // self.page
            need = (cache.length % self.page) == 0
            new_ids = (cache.next_free
                       + jnp.cumsum(need.astype(jnp.int32)) - need)
            table = cache.table.at[bidx, pidx].set(
                jnp.where(need, new_ids.astype(jnp.int32),
                          cache.table[bidx, pidx]))
            next_free = cache.next_free + jnp.sum(need.astype(jnp.int32))
            slots = table[bidx, pidx]
            offs = cache.length % self.page
            k = cache.k.at[:, :, slots, offs].set(kn.astype(dt))
            v = cache.v.at[:, :, slots, offs].set(vn.astype(dt))
            return ret(logits, PagedMegaKVCache(k, v, table,
                                                cache.length + 1,
                                                next_free))
        k = cache.k.at[:, :, bidx, cache.length].set(kn.astype(dt))
        v = cache.v.at[:, :, bidx, cache.length].set(vn.astype(dt))
        return ret(logits, MegaKVCache(k, v, cache.length + 1))

    # -- public API ----------------------------------------------------------

    def new_paged_cache(self) -> PagedMegaKVCache:
        assert self.paged, "construct MegaQwen3 with paged=True"
        cache = PagedMegaKVCache.create(
            self.cfg, self.batch, self.hkv_loc, self.page,
            self.max_pages, self.total_pages,
        )
        specs = PagedMegaKVCache(k=P(None, self.axis),
                                 v=P(None, self.axis), table=P(),
                                 length=P(), next_free=P())
        return jax.tree.map(
            lambda a, sp: jax.device_put(a, NamedSharding(self.mesh, sp)),
            cache, specs,
        )

    def paged_cache_from_dense(self, cache) -> PagedMegaKVCache:
        assert self.paged, "construct MegaQwen3 with paged=True"
        pc = PagedMegaKVCache.from_dense(cache, self.page,
                                         self.total_pages,
                                         self.max_pages)
        specs = PagedMegaKVCache(k=P(None, self.axis),
                                 v=P(None, self.axis), table=P(),
                                 length=P(), next_free=P())
        return jax.tree.map(
            lambda a, sp: jax.device_put(a, NamedSharding(self.mesh, sp)),
            pc, specs,
        )

    def new_cache(self) -> MegaKVCache:
        cache = MegaKVCache.create(self.cfg, self.batch, self.s_max,
                                   self.hkv_loc * self.world)
        specs = MegaKVCache(k=P(None, self.axis),
                            v=P(None, self.axis), length=P())
        return jax.tree.map(
            lambda a, s: jax.device_put(a, NamedSharding(self.mesh, s)),
            cache, specs,
        )

    def decode_step(self, tokens, cache: MegaKVCache):
        """tokens (B,) -> (logits (B, V) f32, cache)."""
        return self._decode(
            self.params, self._w_gate_up, jnp.asarray(tokens, jnp.int32),
            cache
        )

    def decode_resident(self, tokens, cache, steps: int):
        """Device-RESIDENT decode: `steps` megakernel decode iterations
        — kernel step, greedy sampling, KV append, token feedback —
        inside ONE compiled dispatch (ISSUE 12: the persistent-loop
        form of the reference's model-server decode; the host re-enters
        once per WINDOW instead of once per token, which is exactly the
        per-step dispatch tax the r05 engine-vs-mega gap prices).
        Works over both cache forms; with a PagedMegaKVCache the loop
        iterates directly over the shared page pool — a serve-plane
        `KVPool.as_mega_cache()` export decodes in place.

        tokens (B,) -> (generated ids (B, steps), cache). Greedy only
        (argmax — the self-feeding loop's fixed point); bitwise equal
        to `steps` repeated decode_step/argmax calls, test-pinned
        (tests/test_serve_resident.py)."""
        assert steps >= 1
        assert self._trace_build is None, (
            "decode_resident does not thread per-step trace buffers; "
            "build the model outside trace.building()"
        )
        fn = self._resident_fns.pop(steps, None)
        if fn is None:
            fn = self._build_decode_resident(steps)
            while len(self._resident_fns) >= self._resident_fns_max:
                self._resident_fns.pop(next(iter(self._resident_fns)))
        self._resident_fns[steps] = fn  # re-insert = LRU touch
        return fn(self.params, self._w_gate_up,
                  jnp.asarray(tokens, jnp.int32), cache)

    def _build_decode_resident(self, steps: int):
        p_specs, gu_spec, t_spec, c_specs = self._decode_specs

        def per_rank(params, w_gate_up, tok, cache):
            b = tok.shape[0]

            def body(i, carry):
                tok, cache, out = carry
                logits, cache = self._device_step(params, w_gate_up,
                                                  tok, cache)
                nxt = jnp.argmax(logits, -1).astype(jnp.int32)
                return nxt, cache, out.at[:, i].set(nxt)

            out0 = jnp.zeros((b, steps), jnp.int32)
            _tok, cache, out = jax.lax.fori_loop(
                0, steps, body, (tok, cache, out0))
            return out, cache

        return jax.jit(
            jax.shard_map(
                per_rank, mesh=self.mesh,
                in_specs=(p_specs, gu_spec, t_spec, c_specs),
                out_specs=(t_spec, c_specs),
                check_vma=False,
            ),
            donate_argnums=(3,) if self._donate else (),
        )
