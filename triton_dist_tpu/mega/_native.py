"""ctypes loader/builder for the native scheduler library.

The reference ships native planning code built by its cmake tree
(ref: csrc/CMakeLists.txt, python/setup.py:54-146); here one translation
unit is compiled on demand with g++ into the package build dir (pybind11
is not available in this environment — the C ABI + ctypes is the binding).
Every native entry point has a pure-Python mirror in mega/scheduler.py;
`load()` returning None silently selects it (e.g. no toolchain).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_SRC = os.path.join(os.path.dirname(__file__), "..", "csrc", "scheduler.cc")
_OUT_DIR = os.path.join(os.path.dirname(__file__), "..", "csrc", "build")
_LIB = os.path.join(_OUT_DIR, "libtdtsched.so")

_lock = threading.Lock()
_cached: Optional[ctypes.CDLL] = None
_failed = False


def _build() -> bool:
    os.makedirs(_OUT_DIR, exist_ok=True)
    src = os.path.abspath(_SRC)
    tmp = _LIB + f".tmp{os.getpid()}"
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", src, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def load() -> Optional[ctypes.CDLL]:
    """The native lib, building it on first use; None => Python fallback."""
    global _cached, _failed
    if _cached is not None or _failed:
        return _cached
    with _lock:
        if _cached is not None or _failed:
            return _cached
        if os.environ.get("TDT_NO_NATIVE") == "1":
            _failed = True
            return None
        if not os.path.exists(_LIB) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_LIB)
        ):
            if not _build():
                _failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            _failed = True
            return None
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        f64p = ctypes.POINTER(ctypes.c_double)
        lib.tdt_schedule.restype = ctypes.c_int
        lib.tdt_schedule.argtypes = [
            ctypes.c_int32, ctypes.c_int32, i32p, i32p, f64p,
            ctypes.c_int32, ctypes.c_int32, i32p, i32p,
        ]
        lib.tdt_watermarks.restype = ctypes.c_int
        lib.tdt_watermarks.argtypes = [
            ctypes.c_int32, ctypes.c_int32, i32p, i32p, i32p, i32p,
            ctypes.c_int32, i32p,
        ]
        lib.tdt_plan_slots.restype = ctypes.c_int
        lib.tdt_plan_slots.argtypes = [
            ctypes.c_int32, i32p, i32p, u8p, i32p,
        ]
        _cached = lib
        return _cached
