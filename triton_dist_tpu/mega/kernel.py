"""The persistent TPU megakernel: one Pallas kernel runs the whole task
queue of a decode step.

TPU-native re-design of the reference's generated MEGA_TRITON_KERNEL
(ref: python/triton_dist/mega_triton_kernel/core/code_generator.py:31-175
and kernels/task_context.py:92-140). The mapping:

  NUM_SMS persistent blocks      -> one Pallas grid over the task queue
                                    (a TPU chip has 1-2 TensorCores, not
                                    132 SMs; see mega/scheduler.py)
  uint32 work-queue tensor       -> scalar-prefetched int32 queue rows
  generated if/elif on task_type -> lax.switch over branch closures built
                                    at trace time, one per distinct
                                    (op, static-config) key — trace-time
                                    specialization IS the codegen step
  tensor pointers in the row     -> workspace slot ids (flat HBM
                                    activation arena planned by
                                    tdt_plan_slots) + layer ids indexing
                                    stacked weight arrays
  scoreboard signal table        -> same-core program order within one
                                    queue (topologically sorted); ACROSS
                                    cores, per-queue completion counts on
                                    a regular-semaphore scoreboard: each
                                    task broadcasts "queue c finished its
                                    k-th task" and waiters consume static
                                    watermark deltas (see compile_graph;
                                    the ref's device scoreboard,
                                    kernels/task_context.py:92-140);
                                    cross-chip AR uses remote DMA delivery
                                    semaphores
  in-kernel multimem allreduce   -> one-shot mailbox AR over ICI remote
                                    DMA, parity-double-buffered across
                                    decode steps (ref mega
                                    kernels/allreduce.py)

Weight loads are double-buffered against the MXU inside the matmul branch
(the reference's prefetch task analog, mega kernels/prefetch.py).

Layout notes forced by Mosaic HBM tiling (slices along the second-minor
dim must be sublane-aligned): workspace slots are PB-row stripes with
PB = round_up(batch, sublane); the decode KV cache is (L, Hkv, B, S, D)
so per-head reads slice only leading dims; and the attention task does
NOT append to the cache in-kernel — it emits the rope'd k/v rows as
ordinary workspace outputs, folds the new element into its own softmax,
and the caller scatters them into the cache with one XLA
dynamic_update_slice fused into the same jit (the ref's paged KV append,
mega_triton_kernel/models/paged_kv_cache.py, is a device-side scatter for
the same reason: the cache write is not on the kernel's critical path).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.lang import shmem
from triton_dist_tpu.lang.core import (
    compiler_params,
    min_tile,
    next_collective_id,
    round_up,
    tpu_call,
)
from triton_dist_tpu.mega.core import Graph, plan_mm_tiles
from triton_dist_tpu.mega.scheduler import (
    Schedule,
    monotone_watermarks,
    plan_prefetch,
    plan_store_forward,
)
from triton_dist_tpu.trace import events as trace_ev

# Queue row layout (all static, built at compile time):
#   [branch, a0..a5,
#    pf_code, pf_layer, pf_slot, pf_in,      # weight-streaming pipeline
#    pend_w, pend_early, defer_st, fwd_in]   # store/forward pipeline
#
# pf_*: cross-task weight prefetch (the reference's prefetch tasks, mega
# kernels/prefetch.py, made implicit). The scheduler's prefetch plan
# (scheduler.plan_prefetch) assigns each upcoming matmul's first weight
# tile to an EARLIER row of the same queue: that row starts the DMA into
# rotating arena slot pf_slot as early as its own DMA ordering allows
# (see _maybe_prefetch), and the consuming matmul (pf_in = slot+1; 0 =
# cold) reads the arena instead of issuing a cold load. With arena depth
# >= 2 the issue site may be several tasks upstream — the hint streams
# through attention KV tails and AR wait windows without clobbering the
# tile the current matmul is about to consume.
#
# pend_w / pend_early / defer_st / fwd_in: the cross-task store pipeline
# (single-core only). defer_st=1 tells a task to leave its workspace
# store in flight instead of blocking on it; the FOLLOWING row drains it
# (pend_w = 1+index into the static store-width table) either before its
# own workspace loads (pend_early=1, required when its reads alias the
# stored slot) or just before it first overwrites vout. fwd_in=1 means
# this task's main input is the immediately preceding task's result and
# is read straight out of vout (VMEM) — the HBM store+load round trip
# leaves the critical path entirely.
ROW = 15


def physical_core_count():
    """TensorCores per chip, from the device-kind table (PJRT devices do
    not reliably expose num_cores). TDT_NUM_CORES overrides; unknown
    kinds return None (caller proceeds and lets Mosaic decide)."""
    env = os.environ.get("TDT_NUM_CORES")
    if env:
        return int(env)
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    if "v4" in kind or "v5p" in kind:
        return 2  # megacore chips
    if "lite" in kind or "v5e" in kind or "v6e" in kind:
        return 1
    return None


def tile_weight_major(w, tn: int):
    """Re-lay a stacked weight (..., K, N) as tile-major
    (..., N//tn, K, tn): block [..., j] is then K*tn*itemsize fully
    CONTIGUOUS bytes in HBM, so its DMA streams at peak bandwidth
    instead of N-strided tn-wide bursts. Done ONCE at init (a
    materializing transpose — never per step); the kernel reads tiled
    weights via compile_graph(tiled_weights=...)."""
    *lead, k, n = w.shape
    nt = n // tn
    assert nt * tn == n, f"N={n} not divisible by tile {tn}"
    return jnp.moveaxis(w.reshape(*lead, k, nt, tn), -2, -3)


@dataclasses.dataclass
class _Env:
    """Refs + static dims visible to branch builders."""

    dtype: Any
    batch: int     # logical batch rows
    pb: int        # sublane-padded stripe height of one workspace slot
    wmax: int
    pos: Any = None
    table: Any = None  # (B, MAXP) int32 page table in SMEM
    ws: Any = None
    weights: Dict[str, Any] = dataclasses.field(default_factory=dict)
    norms: Any = None
    rope_cs: Any = None
    k_cache: Any = None
    v_cache: Any = None
    vin: Any = None
    vin2: Any = None
    vout: Any = None
    straggler: tuple = (-1, 0)  # (rank, ns) AR-branch skew injection
    vw: Any = None
    vkv: Any = None
    vrope: Any = None
    vnq: Any = None
    vnk: Any = None
    vpf: Any = None
    pfsem: Any = None
    pf_specs: Any = None  # [(wname, K, TN)] in weight-name order
    pf_depth: int = 1     # rotating prefetch-arena slots
    # byte-budgeted matmul tile map (mega/core.plan_mm_tiles): branch
    # key -> TN; the scheduler's prefetch plan is built on the same map
    mm_tn: Dict = dataclasses.field(default_factory=dict)
    # weight names stored tile-major (L, nt, K, TN): block [layer, j]
    # is contiguous in HBM (see tile_weight_major)
    tiled: frozenset = frozenset()
    store_widths: Any = ()  # static store-width table (pend_w indexes it)
    chsem: Any = None       # scratch sem for the interpret-mode AR churn
    mailbox: Any = None
    ld1: Any = None
    ld2: Any = None
    st: Any = None
    wsems: Any = None
    kvsem: Any = None
    kvsems: Any = None
    send: Any = None
    recv: Any = None
    tctx: Any = None  # trace.events.TraceCtx (None = tracing off)

    def ws_rows(self, slot, width):
        return self.ws.at[pl.ds(slot * self.pb, self.pb), pl.ds(0, width)]


# -- shared op math (one definition: fused and standalone branches must
# never diverge — the e2e tests compare their outputs token-for-token) ---


def _rms_f32(x, w, eps):
    """rms_norm in f32: x (B, W) value, w (W,) value."""
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    return x * jax.lax.rsqrt(var + eps) * w[None, :]


def _silu_f32(g, u):
    return g * jax.nn.sigmoid(g) * u


# -- branch builders (one per op kind; key carries the static config) --------


def _w_tile_src(env: _Env, wname: str, layer, j, K: int, TN: int):
    """The (K, TN) HBM source of weight tile j: tile-major weights
    index a contiguous block, plain (L, K, N) weights a strided column
    slice. ONE definition for own-tile loads and prefetch issues — the
    layouts must never diverge between the two."""
    if wname in env.tiled:
        return env.weights[wname].at[layer, j]
    return env.weights[wname].at[layer, :, pl.ds(j * TN, TN)]


def _pf_copy(env: _Env, wname: str, layer, K: int, TN: int, slot):
    """THE prefetch descriptor: start (issuer) and wait (consumer) must
    reconstruct it identically for the semaphore accounting to balance —
    single construction site for both. `slot` selects the rotating arena
    slot (and its per-slot semaphore), so up to pf_depth first tiles can
    be in flight across task boundaries."""
    return pltpu.make_async_copy(
        _w_tile_src(env, wname, layer, 0, K, TN),
        env.vpf.at[slot, pl.ds(0, K), pl.ds(0, TN)],
        env.pfsem.at[slot],
    )


def _maybe_prefetch(env: _Env, pf_code, pf_layer, pf_slot):
    """Start an upcoming matmul's first weight tile (hinted by the queue
    row; assigned by scheduler.plan_prefetch). Branches that mark
    handles_prefetch issue it as EARLY as their own DMA ordering allows —
    right after queueing their input loads (rms/silu/add/AR), after the
    last own weight tile is queued (matmul), during the last KV load
    (attention), or before the rank wait (barrier). Measured on the 8B
    decode chain, early-within-task beats end-of-task by ~1.6%. Every
    current branch sets handles_prefetch; the dispatch wrapper's fallback
    only guards future branches that forget to."""
    for wi, (wname, K, TN) in enumerate(env.pf_specs):
        @pl.when(pf_code == wi + 1)
        def _(wname=wname, K=K, TN=TN):
            _pf_copy(env, wname, pf_layer, K, TN, pf_slot).start()


def _pf_args(args):
    """(pf_code, pf_layer, pf_slot) triple from a queue row."""
    return args[6], args[7], args[8]


def _drain_pending(env: _Env, pend_w):
    """Wait the PREVIOUS task's deferred workspace store (see the ROW
    comment). pend_w indexes the static store-width table + 1, so the
    wait descriptor reconstructs the exact byte count the deferred
    store's start put on env.st."""
    for i, w in enumerate(env.store_widths):
        @pl.when(pend_w == i + 1)
        def _(w=w):
            pltpu.make_async_copy(
                env.vout.at[:, pl.ds(0, w)],
                env.ws.at[pl.ds(0, env.pb), pl.ds(0, w)],
                env.st,
            ).wait()


def _drain_late(env: _Env, args):
    """The pend_early=0 drain: called by branch bodies right before they
    first overwrite vout (the deferred store's source)."""
    pend_w, pend_early = args[10], args[11]

    @pl.when(jnp.logical_and(pend_w > 0, pend_early == 0))
    def _():
        _drain_pending(env, pend_w)


def _finish_store(env: _Env, st, args):
    """Start the task's workspace store; block on it only when the row
    does not defer (defer_st=0: multi-core queues, or the queue's last
    row — the next row's _drain_pending otherwise picks it up)."""
    st.start()

    @pl.when(args[12] == 0)
    def _():
        st.wait()



def _matmul_branch(key, env: _Env):
    """Tiled matmul with an optional fused input prologue (the
    reference's fused task kernels, mega kernels/mlp_fc1.py: norm or
    activation computed in-register on the loaded input instead of
    round-tripping through a separate task + HBM slot — at decode shapes
    the saved task boundaries are a measurable share of the step).

    prologue: None · "rms" (input rms-norm, per-task norm row in a3) ·
    "silu" (input is [gate|up] of width 2K; a = silu(gate) * up)."""
    _, wname, K, N, prologue, eps = key
    TN = env.mm_tn[key]  # byte-budgeted tile map (core.plan_mm_tiles)
    nt = N // TN
    in_w = 2 * K if prologue == "silu" else K
    pf_eligible = any(w == wname and kk == K and tn == TN
                      for w, kk, tn in env.pf_specs)
    VW = env.vw.shape[0]  # own-tile arena depth (outstanding DMAs = VW-1)

    def wcopy(layer, j, slot):
        return pltpu.make_async_copy(
            _w_tile_src(env, wname, layer, j, K, TN),
            env.vw.at[slot, pl.ds(0, K), pl.ds(0, TN)],
            env.wsems.at[slot],
        )

    def body(args):
        layer, src, dst, nrow = args[0], args[1], args[2], args[3]
        pf_in, fwd_in = args[9], args[13]
        cp_in = pltpu.make_async_copy(
            env.ws_rows(src, in_w), env.vin.at[:, pl.ds(0, in_w)], env.ld1
        )

        @pl.when(fwd_in == 0)
        def _load():
            cp_in.start()

        if pf_eligible:
            # prefetch-arena consume: payload > 0 = hit (arena slot
            # pf_in - 1 was streamed by an earlier row), 0 = cold miss
            trace_ev.instant(env.tctx, trace_ev.REGIONS["mega.pf"],
                             payload=pf_in)

            @pl.when(pf_in == 0)
            def _cold_first_tile():
                wcopy(layer, 0, 0).start()
        else:
            wcopy(layer, 0, 0).start()
        if prologue == "rms":
            cp_w = pltpu.make_async_copy(
                env.norms.at[pl.ds(nrow * 8, 8)], env.vnq, env.ld2
            )
            cp_w.start()

        def _from_ws():
            cp_in.wait()
            return env.vin[:, :in_w]

        def _from_fwd():
            # previous task's result still lives in vout — skip the HBM
            # round trip (its deferred store only READS vout: safe)
            return env.vout[:, :in_w]

        raw = jax.lax.cond(fwd_in == 1, _from_fwd, _from_ws)
        if prologue == "rms":
            cp_w.wait()
            a = _rms_f32(
                raw[:, :K].astype(jnp.float32),
                env.vnq[0, :K].astype(jnp.float32), eps,
            ).astype(env.dtype)
        elif prologue == "silu":
            a = _silu_f32(
                raw[:, :K].astype(jnp.float32),
                raw[:, K:2 * K].astype(jnp.float32),
            ).astype(env.dtype)
        else:
            a = raw[:, :K]
        # about to overwrite vout (the deferred store's source)
        _drain_late(env, args)
        for j in range(nt):
            # keep VW-1 own-tile DMAs in flight ahead of the dot
            if j == 0:
                for ah in range(1, VW):
                    if ah < nt:
                        wcopy(layer, ah, ah % VW).start()
            elif j + VW - 1 < nt:
                wcopy(layer, j + VW - 1, (j + VW - 1) % VW).start()
            if j == nt - 1 and (nt > 1 or env.pf_depth > 1):
                # all own tiles are queued: queue the hinted matmul's
                # first weight tile NOW, before the last wait+dot, so the
                # weight stream never drains at the task boundary. (At
                # nt==1 this is only safe with a rotating arena — the
                # depth-1 arena would overwrite the tile this task is
                # reading; that case issues in the epilogue below.)
                _maybe_prefetch(env, *_pf_args(args))
            if j == 0:
                if pf_eligible:
                    def _from_prefetch():
                        slot = pf_in - 1
                        _pf_copy(env, wname, layer, K, TN, slot).wait()
                        return env.vpf[slot, :K, :TN]

                    def _from_cold():
                        wcopy(layer, 0, 0).wait()
                        return env.vw[0, :K, :TN]

                    w_tile = jax.lax.cond(pf_in > 0, _from_prefetch,
                                          _from_cold)
                else:
                    # weight excluded from prefetching (non-unique
                    # (K, TN)): pf_in is statically never > 0 for this
                    # branch and vpf may be smaller than (K, TN)
                    wcopy(layer, 0, 0).wait()
                    w_tile = env.vw[0, :K, :TN]
            else:
                wcopy(layer, j, j % VW).wait()
                w_tile = env.vw[j % VW, :K, :TN]
            acc = jax.lax.dot_general(
                a, w_tile, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            env.vout[:, j * TN:(j + 1) * TN] = acc.astype(env.dtype)
        st = pltpu.make_async_copy(
            env.vout.at[:, pl.ds(0, N)], env.ws_rows(dst, N), env.st
        )
        st.start()
        if nt == 1 and env.pf_depth == 1:
            _maybe_prefetch(env, *_pf_args(args))

        @pl.when(args[12] == 0)
        def _wait_store():
            st.wait()

    body.handles_prefetch = True
    return body


def _rms_norm_branch(key, env: _Env):
    _, W, eps = key

    def body(args):
        nrow, src, dst = args[0], args[1], args[2]
        fwd_in = args[13]
        cp_in = pltpu.make_async_copy(
            env.ws_rows(src, W), env.vin.at[:, pl.ds(0, W)], env.ld1
        )
        # norms ship 8-row-striped (row i at 8*i): single-row dynamic
        # slices are not tiling-aligned on Mosaic, 8-row stripes are
        cp_w = pltpu.make_async_copy(
            env.norms.at[pl.ds(nrow * 8, 8)], env.vnq, env.ld2
        )

        @pl.when(fwd_in == 0)
        def _load():
            cp_in.start()

        cp_w.start()
        _maybe_prefetch(env, *_pf_args(args))

        def _from_ws():
            cp_in.wait()
            return env.vin[:, :W]

        raw = jax.lax.cond(fwd_in == 1, lambda: env.vout[:, :W], _from_ws)
        cp_w.wait()
        y = _rms_f32(raw.astype(jnp.float32),
                     env.vnq[0, :W].astype(jnp.float32), eps)
        _drain_late(env, args)
        env.vout[:, :W] = y.astype(env.dtype)
        st = pltpu.make_async_copy(
            env.vout.at[:, pl.ds(0, W)], env.ws_rows(dst, W), env.st
        )
        _finish_store(env, st, args)

    body.handles_prefetch = True
    return body


def _silu_mul_branch(key, env: _Env):
    _, I = key

    def body(args):
        src, dst = args[0], args[1]
        fwd_in = args[13]
        cp_in = pltpu.make_async_copy(
            env.ws_rows(src, 2 * I), env.vin.at[:, pl.ds(0, 2 * I)],
            env.ld1,
        )

        @pl.when(fwd_in == 0)
        def _load():
            cp_in.start()

        _maybe_prefetch(env, *_pf_args(args))

        def _from_ws():
            cp_in.wait()
            return env.vin[:, :2 * I]

        raw = jax.lax.cond(fwd_in == 1, lambda: env.vout[:, :2 * I],
                           _from_ws)
        y = _silu_f32(raw[:, :I].astype(jnp.float32),
                      raw[:, I:2 * I].astype(jnp.float32))
        _drain_late(env, args)
        env.vout[:, :I] = y.astype(env.dtype)
        st = pltpu.make_async_copy(
            env.vout.at[:, pl.ds(0, I)], env.ws_rows(dst, I), env.st
        )
        _finish_store(env, st, args)

    body.handles_prefetch = True
    return body


def _add_branch(key, env: _Env):
    _, W = key

    def body(args):
        asrc, bsrc, dst = args[0], args[1], args[2]
        cp_a = pltpu.make_async_copy(
            env.ws_rows(asrc, W), env.vin.at[:, pl.ds(0, W)], env.ld1
        )
        cp_b = pltpu.make_async_copy(
            env.ws_rows(bsrc, W),
            env.vin2.at[pl.ds(0, env.pb), pl.ds(0, W)], env.ld2,
        )
        cp_a.start()
        cp_b.start()
        _maybe_prefetch(env, *_pf_args(args))
        cp_a.wait()
        cp_b.wait()
        _drain_late(env, args)
        env.vout[:, :W] = env.vin[:, :W] + env.vin2[:env.pb, :W]
        st = pltpu.make_async_copy(
            env.vout.at[:, pl.ds(0, W)], env.ws_rows(dst, W), env.st
        )
        _finish_store(env, st, args)

    body.handles_prefetch = True
    return body


def _barrier_branch(key, env: _Env):
    _, axis, n = key

    def body(args):
        # the pf DMA reads only local weights: issue it before waiting
        # for the slowest rank, not after
        _maybe_prefetch(env, *_pf_args(args))
        shmem.barrier_all(axis)

    body.handles_prefetch = True
    return body


def _allreduce_add_branch(key, env: _Env):
    """One-shot mailbox AR + residual add (ref mega kernels/allreduce.py
    multimem ld_reduce analog; see make_allreduce_add for the parity
    flow-control argument)."""
    _, W, axis, n = key

    def body(args):
        src, res, dst, parity = args[0], args[1], args[2], args[3]
        fwd_in = args[13]
        pb = env.pb
        cp_res = pltpu.make_async_copy(
            env.ws_rows(res, W),
            env.vin2.at[pl.ds(0, pb), pl.ds(0, W)], env.ld2,
        )
        cp_res.start()
        if n > 1:
            me = jax.lax.axis_index(axis)
            cp_loc = pltpu.make_async_copy(
                env.ws_rows(src, W),
                env.mailbox.at[parity, me, :, pl.ds(0, W)],
                env.ld1,
            )
            cp_loc.start()
            _maybe_prefetch(env, *_pf_args(args))

            def skew():
                # race provocation (tests only): stall the straggler
                # BETWEEN its individual puts, so its payload reaches
                # the first peer on time but the remaining peers late.
                # The on-time peer completes this AR and runs ahead to
                # the NEXT one; its next-parity delivery then arrives at
                # the still-waiting peers while the straggler's put for
                # THIS parity is in flight — exactly the misattribution
                # only per-parity recv semaphores prevent (a shared recv
                # counts the early next-parity bytes and reads a stale
                # mailbox row). Interpret-mode skew is a LOCAL-DMA
                # churn: semaphore churn is unusable in a multi-core
                # kernel (signal and wait can land on different cores'
                # semaphore instances); a copy start/wait pair is the
                # per-core pattern every branch already relies on. The
                # churn runs on its own scratch semaphore (chsem): on
                # ld1 its waits could consume cp_loc's identical-byte
                # completion while cp_loc is still in flight.
                # Native uses cycle-accurate pl.delay.
                s_rank, s_ns = env.straggler
                if s_ns <= 0:
                    return
                from triton_dist_tpu.lang.core import use_interpret

                if use_interpret():
                    @pl.when(me == s_rank)
                    def _skew():
                        def churn(_, c):
                            cp = pltpu.make_async_copy(
                                env.ws_rows(src, W),
                                env.vin.at[:, pl.ds(0, W)], env.chsem,
                            )
                            cp.start()
                            cp.wait()
                            return c

                        jax.lax.fori_loop(0, max(1, s_ns // 5000),
                                          churn, 0)
                else:
                    shmem.straggler_delay(axis, *env.straggler)

            handles = []
            for i in range(1, n):
                peer = jax.lax.rem(me + i, n)
                # recv is per-parity (DMA((2,))): under rank skew a fast
                # peer's AR m+1 delivery must not satisfy this rank's AR m
                # recv wait while a slow peer's AR m put is in flight —
                # same misattribution low_latency_allgather.py documents,
                # same fix (recv_sems.at[parity]).
                h = shmem.putmem_nbi(
                    env.mailbox.at[parity, me, :, pl.ds(0, W)],
                    env.ws_rows(src, W),
                    env.send, env.recv.at[parity], peer, axis,
                )
                handles.append(h)
                if i == 1:
                    skew()
            cp_loc.wait()
            for h in handles:
                h.wait()
            acc = env.mailbox[parity, 0, :, :W].astype(jnp.float32)
            for r in range(1, n):
                acc = acc + env.mailbox[parity, r, :, :W].astype(jnp.float32)
        else:
            cp_loc = pltpu.make_async_copy(
                env.ws_rows(src, W), env.vin.at[:, pl.ds(0, W)], env.ld1
            )

            @pl.when(fwd_in == 0)
            def _load():
                cp_loc.start()

            _maybe_prefetch(env, *_pf_args(args))

            def _from_ws():
                cp_loc.wait()
                return env.vin[:, :W]

            acc = jax.lax.cond(
                fwd_in == 1, lambda: env.vout[:, :W], _from_ws
            ).astype(jnp.float32)
        cp_res.wait()
        acc = acc + env.vin2[:env.pb, :W].astype(jnp.float32)
        _drain_late(env, args)
        env.vout[:, :W] = acc.astype(env.dtype)
        st = pltpu.make_async_copy(
            env.vout.at[:, pl.ds(0, W)], env.ws_rows(dst, W), env.st
        )
        _finish_store(env, st, args)

    body.handles_prefetch = True
    return body


def _kv_chunk(smax: int, page: int = 0) -> int:
    """KV page length for the chunked attention: whole-cache at small
    contexts (one page, the static path), 512-token pages past that.
    page > 0 pins an explicit page size (the paged-cache mode)."""
    if page > 0:
        assert smax % page == 0, f"s_max {smax} % page {page} != 0"
        return page
    if smax <= 1024:
        return smax
    assert smax % 512 == 0, f"s_max {smax} must be a multiple of 512"
    return 512


def _attention_branch(key, env: _Env):
    """qk-norm + rope + GQA decode (ref: mega kernels/flash_attn.py page
    attention task). The new token's k/v rows are written to workspace
    slots and folded into the softmax directly; the caller scatters them
    into the cache (see module docstring)."""
    (_, hq_l, hkv_l, D, SMAX, eps, use_qk_norm, q_base, k_base,
     page) = key
    B = env.batch
    half = D // 2
    g = hq_l // hkv_l
    scale = D ** -0.5
    kw = hkv_l * D
    hqd = hq_l * D
    WQKV = hqd + 2 * kw
    # lane-aligned staging layout (DMA widths padded to 128; readers only
    # consume the true kw/hqd prefixes of the destination slots)
    kwp = round_up(kw, 128)
    hqdp = round_up(hqd, 128)

    def rope(x, c, s):
        # x (B, h, D), c/s (B, half) f32; half-split convention
        x1 = x[..., :half]
        x2 = x[..., half:]
        cb = c[:B, None, :]
        sb = s[:B, None, :]
        return jnp.concatenate([x1 * cb - x2 * sb, x2 * cb + x1 * sb],
                               axis=-1)

    def rmsn(x, w):
        var = jnp.mean(x * x, axis=-1, keepdims=True)
        return x * jax.lax.rsqrt(var + eps) * w[None, None, :]

    def body(args):
        layer, src, dst, kn_dst, vn_dst = (
            args[0], args[1], args[2], args[3], args[4]
        )
        fwd_in = args[13]
        cp_in = pltpu.make_async_copy(
            env.ws_rows(src, WQKV), env.vin.at[:, pl.ds(0, WQKV)], env.ld1
        )

        @pl.when(fwd_in == 0)
        def _load():
            cp_in.start()

        if use_qk_norm:
            cp_qn = pltpu.make_async_copy(
                env.norms.at[pl.ds((q_base + layer) * 8, 8)], env.vnq,
                env.ld2,
            )
            cp_kn = pltpu.make_async_copy(
                env.norms.at[pl.ds((k_base + layer) * 8, 8)], env.vnk,
                env.kvsem,
            )
            cp_qn.start()
            cp_kn.start()
        rope_cps = []
        for b in range(B):
            cp = pltpu.make_async_copy(
                env.rope_cs.at[pl.ds(env.pos[b] * 8, 8)],
                env.vrope.at[b],
                env.wsems.at[b % 2],
            )
            cp.start()
            rope_cps.append(cp)
        def _from_ws():
            cp_in.wait()
            return env.vin[:, :WQKV]

        # fwd_in: the qkv matmul immediately precedes on this queue and
        # its result still sits in vout — read it there, skip the HBM
        # round trip (its deferred store only READS vout: safe)
        raw_qkv = jax.lax.cond(fwd_in == 1, lambda: env.vout[:, :WQKV],
                               _from_ws)
        if use_qk_norm:
            cp_qn.wait()
            cp_kn.wait()
        for cp in rope_cps:
            cp.wait()

        # full-PB loads/stores only: Mosaic rejects sub-sublane ref slices;
        # value-level slicing to the B live rows is free vreg selection
        qkv_full = raw_qkv.astype(jnp.float32)
        qkv = qkv_full[:B]
        q = qkv[:, :hqd].reshape(B, hq_l, D)
        kn = qkv[:, hqd:hqd + kw].reshape(B, hkv_l, D)
        vn = qkv[:, hqd + kw:WQKV].reshape(B, hkv_l, D)
        if use_qk_norm:
            q = rmsn(q, env.vnq[0, :D].astype(jnp.float32))
            kn = rmsn(kn, env.vnk[0, :D].astype(jnp.float32))
        cs_rows = env.vrope[:, 0, :]  # (B, D)
        c = cs_rows[:, :half]
        s = cs_rows[:, half:D]
        q = rope(q, c, s)
        kn = rope(kn, c, s)

        def pad_rows(v):
            pb = env.pb
            if v.shape[0] == pb:
                return v
            return jnp.concatenate(
                [v, jnp.zeros((pb - v.shape[0], v.shape[1]), v.dtype)], 0
            )

        # about to overwrite vout (a deferred store's source; raw_qkv is
        # already materialized in registers above)
        _drain_late(env, args)
        # stage: [0,hqdp) attention out · then k_new · then v_new
        env.vout[:, hqdp:hqdp + kw] = pad_rows(
            kn.reshape(B, kw).astype(env.dtype))
        env.vout[:, hqdp + kwp:hqdp + kwp + kw] = pad_rows(
            vn.reshape(B, kw).astype(env.dtype))

        # ---- chunked-KV online attention (flash-decode over the cache;
        # ref: mega_triton_kernel/models/paged_kv_cache.py — context
        # scales past VMEM by streaming SCHUNK-token KV pages). EVERY
        # cache access indirects through the page table (SMEM): the
        # dense cache is the identity table over its own page grid, the
        # paged cache maps (seq, chunk) -> pool page (per-seq growth +
        # pool sharing; the ref's page_table lookup, paged_kv_cache.py).
        # The online state is SEEDED with the new token's contribution
        # (always unmasked), so the running max is real from the start
        # and fully-masked chunks contribute exactly zero. Chunks past a
        # sequence's prefix read table slot 0 (zero-init) — in-bounds,
        # and their logits are position-masked to -inf.
        schunk = _kv_chunk(SMAX, page)
        nch = SMAX // schunk

        def kv_start(h, ci, slot):
            for which, ref in ((0, env.k_cache), (1, env.v_cache)):
                for b in range(B):
                    pid = env.table[b, ci]
                    pltpu.make_async_copy(
                        ref.at[layer, h, pid],
                        env.vkv.at[slot, which, b],
                        env.kvsems.at[slot],
                    ).start()

        def kv_wait(slot):
            for which, ref in ((0, env.k_cache), (1, env.v_cache)):
                for b in range(B):
                    pltpu.make_async_copy(
                        ref.at[0, 0, 0], env.vkv.at[slot, which, b],
                        env.kvsems.at[slot],
                    ).wait()

        def chunk_update(h, ci, state):
            """One KV page folded into the per-b online softmax state."""
            m, den, acc = state  # (B, g, 1), (B, g, 1), (B, g, D)
            kf = env.vkv[ci % 2, 0].astype(jnp.float32)  # (B, schunk, D)
            vf = env.vkv[ci % 2, 1].astype(jnp.float32)
            ms, dens, accs = [], [], []
            for b in range(B):
                qb = q[b, h * g:(h + 1) * g] * scale  # (g, D)
                lg = jax.lax.dot_general(
                    qb, kf[b], (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )  # (g, schunk)
                spos = jax.lax.broadcasted_iota(
                    jnp.int32, (g, schunk), 1) + ci * schunk
                lg = jnp.where(spos < env.pos[b], lg, -1e30)
                m_new = jnp.maximum(m[b], jnp.max(lg, -1, keepdims=True))
                alpha = jnp.exp(m[b] - m_new)
                p_ = jnp.exp(lg - m_new)
                ms.append(m_new)
                dens.append(den[b] * alpha
                            + jnp.sum(p_, -1, keepdims=True))
                accs.append(acc[b] * alpha + jax.lax.dot_general(
                    p_, vf[b], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ))
            return (jnp.stack(ms), jnp.stack(dens), jnp.stack(accs))

        out_rows = []  # per-b (1, hqd) attention outputs, kv-head-major
        for h in range(hkv_l):
            # seed: the new token (logit lg_new, value vn) at weight 1
            m0, d0, a0 = [], [], []
            for b in range(B):
                qb = q[b, h * g:(h + 1) * g] * scale
                lg_new = jnp.sum(qb * kn[b, h][None, :], axis=-1,
                                 keepdims=True)  # (g, 1)
                m0.append(lg_new)
                d0.append(jnp.ones_like(lg_new))
                a0.append(jnp.broadcast_to(vn[b, h][None, :], (g, D)))
            state = (jnp.stack(m0), jnp.stack(d0), jnp.stack(a0))

            if nch == 1:
                # static path (whole cache is one page; bench shapes)
                kv_start(h, 0, 0)
                if h == hkv_l - 1:
                    _maybe_prefetch(env, *_pf_args(args))
                kv_wait(0)
                state = chunk_update(h, 0, state)
            else:
                # long-context path: dynamic trip count — only pages
                # that intersect some sequence's prefix are touched
                maxp = env.pos[0]
                for b in range(1, B):
                    maxp = jnp.maximum(maxp, env.pos[b])
                n_act = jnp.minimum((maxp + schunk - 1) // schunk, nch)

                @pl.when(n_act > 0)
                def _first():
                    kv_start(h, 0, 0)

                if h == hkv_l - 1:
                    _maybe_prefetch(env, *_pf_args(args))

                def loop_body(ci, state):
                    @pl.when(ci + 1 < n_act)
                    def _ahead():
                        kv_start(h, ci + 1, (ci + 1) % 2)

                    kv_wait(ci % 2)
                    return chunk_update(h, ci, state)

                state = jax.lax.fori_loop(0, n_act, loop_body, state)

            _, den, acc = state
            for b in range(B):
                ob = acc[b] / den[b]
                if h == 0:
                    out_rows.append([ob.reshape(1, g * D)])
                else:
                    out_rows[b].append(ob.reshape(1, g * D))

        out = jnp.concatenate(
            [jnp.concatenate(per_b, axis=1) for per_b in out_rows], axis=0
        )  # (B, hqd)
        env.vout[:, :hqd] = pad_rows(out.astype(env.dtype))

        cps = [
            pltpu.make_async_copy(
                env.vout.at[:, pl.ds(0, hqdp)], env.ws_rows(dst, hqdp),
                env.st,
            ),
            pltpu.make_async_copy(
                env.vout.at[:, pl.ds(hqdp, kwp)],
                env.ws_rows(kn_dst, kwp), env.wsems.at[0],
            ),
            pltpu.make_async_copy(
                env.vout.at[:, pl.ds(hqdp + kwp, kwp)],
                env.ws_rows(vn_dst, kwp), env.wsems.at[1],
            ),
        ]
        for cp in cps:
            cp.start()
        for cp in cps:
            cp.wait()

    body.handles_prefetch = True
    return body


def _noop_branch(key, env: _Env):
    """Multi-core filler: drain rows (whose scoreboard waits happen in the
    dispatch wrapper) and queue padding execute this empty body."""

    def body(args):
        _maybe_prefetch(env, *_pf_args(args))

    body.handles_prefetch = True
    return body


_BRANCH_BUILDERS: Dict[str, Callable] = {
    "matmul": _matmul_branch,
    "rms_norm": _rms_norm_branch,
    "silu_mul": _silu_mul_branch,
    "add": _add_branch,
    "allreduce_add": _allreduce_add_branch,
    "attention": _attention_branch,
    "barrier": _barrier_branch,
    "noop": _noop_branch,
}


@dataclasses.dataclass
class CompiledMega:
    """The compiled megakernel + its static plan."""

    run: Callable  # (pos, ws, weights_dict, norms, rope_cs, k, v) -> ws
    queue: np.ndarray  # (n_tasks, ROW) int32
    n_slots: int
    pb: int        # stripe height (sublane-padded batch)
    wmax: int
    norm_width: int  # required minor dim of the stacked norms array
    branch_keys: List[Any]
    weight_names: List[str]
    # byte-budgeted matmul tile map (branch key -> TN) and the weight
    # names `run` expects in tile-major (L, nt, K, TN) layout
    mm_tiles: Dict[Any, int] = dataclasses.field(default_factory=dict)
    tiled_weights: tuple = ()

    def workspace(self, dtype) -> jnp.ndarray:
        return jnp.zeros((self.n_slots * self.pb, self.wmax), dtype)

    def slot_rows(self, buf_slot: int):
        return slice(buf_slot * self.pb, buf_slot * self.pb + self.pb)

    def tile_cols(self, wname: str) -> int:
        """TN of weight `wname` (every matmul using a weight must agree
        on one tile for it to be addressable here — same uniqueness rule
        as prefetchability)."""
        tns = {tn for k, tn in self.mm_tiles.items() if k[1] == wname}
        assert len(tns) == 1, f"{wname}: non-unique tile set {tns}"
        return tns.pop()


def compile_graph(
    graph: Graph,
    sched: Schedule,
    dtype,
    name: str = "megakernel",
    straggler: tuple = (-1, 0),
    tiled_weights: tuple = (),
) -> CompiledMega:
    """Lower (graph, schedule) to one pallas_call (the reference's
    ModelBuilder.compile, model_builder.py:372-389: codegen + jit). The
    queue array is built once; the returned `run` is pure and jittable
    (call it inside shard_map for world>1 graphs).

    Tracing: when compile_graph runs under trace.building(), the kernel
    carries a per-core record buffer — task spans (payload=branch,
    aux=queue row), scoreboard-wait spans, prefetch hit/miss instants —
    and `run` returns (ws, trace_buf); trace/attribution.
    compare_predicted diffs the result against scheduler.predicted_stalls
    queue by queue. Default builds are bit-identical (the flag is read
    ONCE here, at graph-compile time)."""
    build = trace_ev.active_build()
    B = graph.batch
    PB = round_up(B, min_tile(dtype)[0])
    tasks = graph.tasks
    nc = int(sched.watermarks.shape[1])
    # multi-core rows append the scoreboard plan: nc wait-delta columns
    # (consume this many completions of queue c' before starting) and one
    # broadcast flag (announce completion to every core)
    row_len = ROW + (nc + 1 if nc > 1 else 0)

    # branch table: first-seen order over the scheduled queue
    branch_keys: List[Any] = []
    branch_of: Dict[Any, int] = {}
    for t in tasks:
        if t.branch_key not in branch_of:
            branch_of[t.branch_key] = len(branch_keys)
            branch_keys.append(t.branch_key)
    if nc > 1 and ("noop",) not in branch_of:
        branch_of[("noop",)] = len(branch_keys)
        branch_keys.append(("noop",))

    # weight-streaming plan (scheduler.plan_prefetch): pf_specs is the
    # arena geometry, the per-task issue/consume arrays fill row columns
    # 7-10. Schedules produced by schedule_graph carry the plan; bare
    # Schedules (tests) get one planned here (byte-aware auto depth).
    pf_plan = sched.prefetch
    if pf_plan is None:
        pf_plan = plan_prefetch(graph, sched)
    pf_specs = pf_plan.specs
    pf_depth = pf_plan.depth

    # byte-budgeted matmul tile map — MUST be the map the prefetch plan
    # was built on (both call core.plan_mm_tiles; the assert catches an
    # env-var flip between scheduling and compiling)
    mm_tiles = plan_mm_tiles([k for k in {t.branch_key for t in tasks}
                              if k[0] == "matmul"])
    for wname, kk, tn in pf_specs:
        got = {mm_tiles[k] for k in mm_tiles if k[1] == wname}
        assert got == {tn}, (
            f"prefetch plan tiles {wname} at {tn} but the kernel would "
            f"tile it at {got} — TDT_MEGA_TILE_BYTES changed between "
            "schedule_graph and compile_graph")
    tiled_weights = tuple(tiled_weights)
    mm_names = {k[1] for k in mm_tiles}
    assert set(tiled_weights) <= mm_names, (
        f"tiled_weights {tiled_weights} not all matmul weights "
        f"({sorted(mm_names)})")

    # store/forward plan (single-core only; see scheduler.StorePlan).
    # Per-branch capabilities live here because only the kernel knows
    # each branch body's structure.
    def _store_caps(t):
        """(deferrable store width, can_late_drain, fwd_spec)."""
        k = t.branch_key
        if k[0] == "matmul":
            in_w = 2 * k[2] if k[4] == "silu" else k[2]
            return k[3], True, (t.reads[0], in_w)
        if k[0] == "rms_norm":
            return k[1], True, (t.reads[0], k[1])
        if k[0] == "silu_mul":
            return k[1], True, (t.reads[0], 2 * k[1])
        if k[0] == "add":
            return k[1], True, None  # two-input body: no single forward
        if k[0] == "allreduce_add":
            # n>1 publishes src to the mailbox — must come from HBM
            fwd = (t.reads[0], k[1]) if k[3] == 1 else None
            return k[1], True, fwd
        if k[0] == "attention":
            # multi-store epilogue cannot defer, but the body can both
            # late-drain and read its qkv input straight from vout
            wqkv = (k[1] + 2 * k[2]) * k[3]
            return 0, True, (t.reads[0], wqkv)
        return 0, False, None  # barrier

    caps = [_store_caps(t) for t in tasks]
    st_plan = plan_store_forward(
        graph, sched,
        [c[0] for c in caps], [c[1] for c in caps], [c[2] for c in caps],
    )
    store_widths = st_plan.widths

    def base_row(t):
        row = [branch_of[t.branch_key]] + list(t.args)
        row += [0] * (ROW - len(row))
        for pos_ in t.buf_args:
            row[1 + pos_] = int(sched.buf_slot[row[1 + pos_]])
        tid = t.id
        row[7] = int(pf_plan.issue_code[tid])
        row[8] = int(pf_plan.issue_layer[tid])
        row[9] = int(pf_plan.issue_slot[tid])
        row[10] = int(pf_plan.consume[tid])
        row[11] = int(st_plan.pend_w[tid])
        row[12] = int(st_plan.pend_early[tid])
        row[13] = int(st_plan.defer_st[tid])
        row[14] = int(st_plan.fwd_in[tid])
        return row[:ROW]

    order = sched.order
    if nc == 1:
        # queue rows in schedule order, buffer args rewritten to slots
        queue = np.zeros((len(order), ROW), np.int32)
        for qi, tid in enumerate(order):
            queue[qi] = base_row(tasks[tid])
        qmax = len(order)
    else:
        # per-core queues + scoreboard plan. Queue identity (program_id 0)
        # is decoupled from PHYSICAL core identity (the interpreter
        # randomizes the parallel-coordinate -> core assignment; Mosaic's
        # megacore split is its own choice), so completions are BROADCAST:
        # finishing a task of queue c signals scoreboard semaphore sb[c]
        # on every core, and a waiter consumes from its local instance —
        # whichever core it landed on. Watermarks are monotonized along
        # each queue (scheduler.monotone_watermarks) so each row's wait is
        # a static DELTA, and a final drain row per queue returns every
        # local semaphore instance to zero (Mosaic requires semaphores
        # drained at kernel exit).
        wm_mono = monotone_watermarks(sched)
        qlens = [len(q) for q in sched.queues]
        qmax = max(qlens) + 1  # +1 for the drain row
        queue = np.zeros((nc, qmax, row_len), np.int32)
        noop_row = [branch_of[("noop",)]] + [0] * (row_len - 1)
        for c, qtasks in enumerate(sched.queues):
            prev = np.zeros(nc, np.int64)
            for p, tid in enumerate(qtasks):
                r = base_row(tasks[tid]) + [0] * (nc + 1)
                for c2 in range(nc):
                    if c2 != c:
                        r[ROW + c2] = int(wm_mono[tid][c2] - prev[c2])
                prev = np.maximum(prev, wm_mono[tid])
                r[ROW + nc] = 1  # broadcast completion
                queue[c, p] = r
            dr = list(noop_row)
            for c2 in range(nc):
                dr[ROW + c2] = (qlens[c] if c2 == c
                                else int(qlens[c2] - prev[c2]))
            queue[c, qlens[c]] = dr
            for p in range(qlens[c] + 1, qmax):
                queue[c, p] = noop_row

    # static dims
    wmax = round_up(max(b.width for b in graph.buffers), 128)
    for k in branch_keys:
        if k[0] == "attention":  # padded staging layout (attention branch)
            wmax = max(wmax, round_up(k[1] * k[3], 128)
                       + 2 * round_up(k[2] * k[3], 128))
    mm_keys = [k for k in branch_keys if k[0] == "matmul"]
    kmax = max((k[2] for k in mm_keys), default=128)
    tnmax = max((mm_tiles[k] for k in mm_keys), default=128)
    at_keys = [k for k in branch_keys if k[0] == "attention"]
    assert len({k[1:] for k in at_keys}) <= 1, (
        "one attention geometry per megakernel graph"
    )
    if at_keys:
        _, hq_l, hkv_l, D, SMAX, _, _, _, _, page_ = at_keys[0]
        half = D // 2
    else:
        hkv_l, D, SMAX, half, page_ = 1, 128, 8, 64, 0
    SCHUNK = _kv_chunk(SMAX, page_)
    ar_keys = [k for k in branch_keys if k[0] in ("allreduce_add",
                                                  "barrier")]
    arw = max((k[1] for k in ar_keys if k[0] == "allreduce_add"),
              default=128)
    world = max((k[-1] for k in ar_keys), default=1)
    weight_names = sorted({k[1] for k in mm_keys})
    norm_ws = [k[1] for k in branch_keys if k[0] == "rms_norm"]
    norm_ws += [k[2] for k in mm_keys if k[4] == "rms"]
    if any(k[6] for k in at_keys):  # use_qk_norm
        norm_ws.append(D)
    norm_width = round_up(max(norm_ws, default=128), 128)

    pf_kmax = max((k for _, k, _ in pf_specs), default=8)
    pf_tnmax = max((t for _, _, t in pf_specs), default=128)

    n_slots = sched.n_slots
    isz = jnp.dtype(dtype).itemsize
    vmem = (
        pf_depth * pf_kmax * pf_tnmax * isz +
        4 * PB * wmax * max(isz, 4)
        + 2 * kmax * tnmax * isz
        + min(2, SMAX // SCHUNK) * 2 * B * SCHUNK * D * isz
        + 2 * world * PB * arw * isz
        + (4 << 20)
    )

    # world/axis for the trace header rank (the AR/barrier branch keys
    # carry the mesh axis when the graph is distributed)
    trace_axis = next((k[2] for k in ar_keys
                       if k[0] == "allreduce_add" and k[3] > 1),
                      None) or next((k[1] for k in ar_keys
                                     if k[0] == "barrier" and k[2] > 1),
                                    None)

    def kernel(q_ref, pos_ref, tbl_ref, ws_in, *rest):
        nw = len(weight_names)
        w_refs = rest[:nw]
        tail = list(rest[nw:])
        tcur = tail.pop() if build is not None else None
        if nc > 1:
            sb = tail.pop()
        (norms, rope_cs, k_cache, v_cache, ws_out) = tail[:5]
        tail = tail[5:]
        tbuf = tail.pop(0) if build is not None else None
        (vin, vin2, vout, vw, vkv, vrope, vnq, vnk, vpf, mailbox,
         ld1, ld2, st, wsems, kvsem, kvsems, send, recv, pfsem,
         chsem) = tail
        del ws_in  # aliased: access via the output ref
        tctx = trace_ev.make_ctx(
            build, tbuf, tcur,
            lane=pl.program_id(0) if nc > 1 else 0)
        env = _Env(
            tctx=tctx,
            dtype=dtype, batch=B, pb=PB, wmax=wmax, pos=pos_ref,
            table=tbl_ref, straggler=straggler,
            ws=ws_out, weights=dict(zip(weight_names, w_refs)),
            norms=norms, rope_cs=rope_cs, k_cache=k_cache,
            v_cache=v_cache, vin=vin, vin2=vin2, vout=vout, vw=vw,
            vkv=vkv, vrope=vrope, vnq=vnq, vnk=vnk, vpf=vpf,
            pfsem=pfsem, pf_specs=pf_specs, pf_depth=pf_depth,
            mm_tn=mm_tiles, tiled=frozenset(tiled_weights),
            store_widths=store_widths, chsem=chsem, mailbox=mailbox,
            ld1=ld1, ld2=ld2,
            st=st, wsems=wsems, kvsem=kvsem, kvsems=kvsems, send=send,
            recv=recv,
        )
        bodies = [_BRANCH_BUILDERS[k[0]](k, env) for k in branch_keys]
        if nc > 1:
            ci = pl.program_id(0)
            ti = pl.program_id(1)

            def row(j):
                return q_ref[ci, ti, j]
        else:
            ti = pl.program_id(0)

            def row(j):
                return q_ref[ti, j]

        a = [row(j) for j in range(1, ROW)]

        # trace init: each core's first queue row, before any emit
        if build is not None:
            @pl.when(ti == 0)
            def _trace_init():
                trace_ev.init_ctx(
                    tctx,
                    rank=(jax.lax.axis_index(trace_axis)
                          if trace_axis is not None else 0),
                    lane_id=pl.program_id(0) if nc > 1 else 0)

        if nc > 1:
            # scoreboard waits: consume the planned delta of completions
            # of each other queue from the LOCAL semaphore instance
            for c2 in range(nc):
                delta = row(ROW + c2)

                @pl.when(delta > 0)
                def _(c2=c2, delta=delta):
                    with trace_ev.span(tctx,
                                       trace_ev.REGIONS["mega.sb_wait"],
                                       payload=c2, aux=ti):
                        pltpu.semaphore_wait(sb.at[c2], delta)

        def dispatch(f):
            # pend_early=1: the previous row's deferred store must land
            # before this task's loads (its reads alias the stored slot,
            # or the branch has no late-drain site)
            @pl.when(jnp.logical_and(a[10] > 0, a[11] == 1))
            def _early_drain():
                _drain_pending(env, a[10])

            f(a)
            if not getattr(f, "handles_prefetch", False):
                _maybe_prefetch(env, a[6], a[7], a[8])

        # task span: payload = branch id, aux = queue position. Padding
        # and drain rows (the noop branch) are excluded so a queue's
        # traced span count equals its scheduled length
        # (attribution.compare_predicted's coverage check).
        if build is not None:
            noop_b = branch_of.get(("noop",))
            is_task = jnp.asarray(True) if noop_b is None \
                else (row(0) != noop_b)

            @pl.when(is_task)
            def _task_begin():
                trace_ev.emit(tctx, trace_ev.REGIONS["mega.task"],
                              trace_ev.KIND_BEGIN, payload=row(0),
                              aux=ti)

        jax.lax.switch(row(0), [lambda f=f: dispatch(f) for f in bodies])

        if build is not None:
            @pl.when(is_task)
            def _task_end():
                trace_ev.emit(tctx, trace_ev.REGIONS["mega.task"],
                              trace_ev.KIND_END, payload=row(0), aux=ti)

        if nc > 1:
            sig = row(ROW + nc)

            @pl.when(sig > 0)
            def _():
                # broadcast completion of queue `ci` to every core's
                # instance of sb[ci] (queue id != physical core id)
                for c2 in range(nc):
                    pltpu.semaphore_signal(sb.at[ci], 1, core_index=c2)

    def run(pos, table, ws, weights: Dict[str, jax.Array], norms,
            rope_cs, k, v):
        """k/v are PAGE POOLS (L, Hkv_loc, n_pages, SCHUNK, D); `table`
        (B, SMAX//SCHUNK) int32 maps (seq, chunk) -> pool page. Dense
        callers pass their cache reshaped to the page grid plus the
        identity table (see MegaQwen3._device_step)."""
        any_spec = pl.BlockSpec(memory_space=pl.ANY)
        nw = len(weight_names)
        grid = (nc, qmax) if nc > 1 else (len(order),)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] * 2
            + [any_spec] * (1 + nw + 4),
            out_specs=((any_spec, trace_ev.out_spec())
                       if build is not None else any_spec),
            scratch_shapes=[
                pltpu.VMEM((PB, wmax), dtype),           # vin
                pltpu.VMEM((max(PB, 2), wmax), dtype),   # vin2 (rows 0/1:
                                                         #  norm vectors)
                pltpu.VMEM((PB, wmax), dtype),           # vout
                pltpu.VMEM((2, kmax, tnmax), dtype),     # vw double buffer
                # KV page slots: 1 when the whole cache is one page,
                # a double buffer on the chunked long-context path
                pltpu.VMEM((min(2, SMAX // SCHUNK), 2, B, SCHUNK, D),
                           dtype),
                pltpu.VMEM((B, 8, D), jnp.float32),      # vrope stripes
                # f32 8-row stripes (see _rms_norm_branch)
                pltpu.VMEM((8, norm_width), jnp.float32),  # vnq
                pltpu.VMEM((8, norm_width), jnp.float32),  # vnk
                pltpu.VMEM((pf_depth, pf_kmax, pf_tnmax),  # vpf arena
                           dtype),
                pltpu.VMEM((2, world, PB, arw), dtype),  # AR mailbox
                pltpu.SemaphoreType.DMA,                 # ld1
                pltpu.SemaphoreType.DMA,                 # ld2
                pltpu.SemaphoreType.DMA,                 # st
                pltpu.SemaphoreType.DMA((2,)),           # wsems
                pltpu.SemaphoreType.DMA,                 # kvsem
                pltpu.SemaphoreType.DMA(                 # kvsems (pages)
                    (min(2, SMAX // SCHUNK),)),
                pltpu.SemaphoreType.DMA,                 # send
                pltpu.SemaphoreType.DMA((2,)),           # recv (per-parity)
                pltpu.SemaphoreType.DMA((pf_depth,)),    # pfsem (per-slot)
                pltpu.SemaphoreType.DMA,                 # chsem (AR churn)
            ] + (
                # multi-core scoreboard: sb[c] counts queue c completions
                [pltpu.SemaphoreType.REGULAR((nc,))] if nc > 1 else []
            ) + (
                [trace_ev.cursor_scratch()] if build is not None else []
            ),
        )
        extra: Dict[str, Any] = {}
        if nc > 1:
            from triton_dist_tpu.lang.core import use_interpret

            if use_interpret():
                from triton_dist_tpu.lang.core import interpret_params

                extra["interpret"] = interpret_params(
                    num_cores_or_threads=nc,
                    detect_races=os.environ.get("TDT_MEGA_RACES") == "1",
                )
            else:
                phys = physical_core_count()
                if phys is not None and phys < nc:
                    # only a POSITIVELY-known-insufficient chip raises;
                    # unknown device kinds proceed and let Mosaic decide
                    # (round-4 ADVICE: PJRT devices don't reliably expose
                    # num_cores, and a fail-closed default made the
                    # multi-core path unreachable on real megacore chips)
                    raise RuntimeError(
                        f"megakernel schedule uses {nc} cores but this "
                        f"chip has {phys} TensorCore(s); re-schedule with "
                        f"num_cores={phys} (multi-core needs v4/v5p-class "
                        "megacore chips)"
                    )
        out_shape = (jax.ShapeDtypeStruct(ws.shape, ws.dtype),) + (
            (trace_ev.out_shape(build, lanes=nc),)
            if build is not None else ())
        fn = tpu_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=out_shape if build is not None else out_shape[0],
            # inputs: queue(0) pos(1) table(2) ws(3) weights(4..) ...
            input_output_aliases={3: 0},
            compiler_params=compiler_params(
                has_side_effects=True,
                collective_id=next_collective_id(name) if world > 1
                else None,
                vmem_limit_bytes=int(vmem),
                dimension_semantics=(
                    ("parallel", "arbitrary") if nc > 1
                    else ("arbitrary",)
                ),
            ),
            **extra,
        )
        w_list = [weights[n] for n in weight_names]
        return fn(jnp.asarray(queue), pos, jnp.asarray(table, jnp.int32),
                  ws, *w_list, norms, rope_cs, k, v)
        # (traced builds: fn returns (ws, trace_buf) — see docstring)

    return CompiledMega(
        run=run, queue=queue, n_slots=n_slots, pb=PB, wmax=wmax,
        norm_width=norm_width, branch_keys=branch_keys,
        weight_names=weight_names, mm_tiles=mm_tiles,
        tiled_weights=tiled_weights,
    )
