"""ModelBuilder — the megakernel's host-side op API.

TPU-native re-design of the reference's ModelBuilder
(ref: python/triton_dist/mega_triton_kernel/models/model_builder.py:83-408
`make_qkv_proj/make_attn/make_allreduce/...` building tasks, :372 compile,
:391 run). Ops append Tasks to a Graph; each op carries a branch_key =
(op kind, static shape tuple) — the analog of the reference's CodeGenKey
specialization — so all layers with one shape share one generated switch
branch and the layer index rides in the dynamic args. Costs come from the
analytic perf model so the (multi-core) scheduler can load-balance by
critical path.

Dynamic-arg conventions per op (queue row = [branch, a0..a5]):
  matmul        [layer, src_buf, dst_buf, norm_row]  (norm_row used by
                the "rms" prologue; branch key carries (prologue, eps))
  rms_norm      [norm_row, src_buf, dst_buf]
  silu_mul      [src_buf, dst_buf]
  add           [a_buf, b_buf, dst_buf]
  allreduce_add [partial_buf, residual_buf, dst_buf, parity]
  attention     [layer, qkv_buf, dst_buf, k_new_buf, v_new_buf]
  barrier       []
Buffer-id args are rewritten to workspace slots at compile time
(Task.buf_args marks their positions).
"""

from __future__ import annotations

from typing import Optional, Tuple

from triton_dist_tpu.mega.core import BufferHandle, Graph, Task
from triton_dist_tpu.perf_model import (
    detect_chip,
    estimate_ar_ms,
    estimate_gemm_ms,
)


class ModelBuilder:
    """Builds the task graph of one decode step (batch rows × op widths).

    `weights` maps logical weight names (w_qkv, w_o, w_gate_up, w_down) to
    kernel input indices at compile time; the builder only records names.
    """

    def __init__(self, batch: int, axis: str = "tp", world: int = 1):
        self.graph = Graph(batch)
        self.batch = batch
        self.axis = axis
        self.world = world
        self._chip = detect_chip()
        self._ar_count = 0

    # -- buffers -------------------------------------------------------------

    def buffer(self, width: int, name: str = "",
               pinned: bool = False) -> BufferHandle:
        return self.graph.buffer(width, name, pinned)

    # -- ops -----------------------------------------------------------------

    def make_barrier(self) -> Optional[Task]:
        """Entry barrier: no remote DMA may land in a peer that has not
        entered the kernel (the ref's barrier task / BarrierAllContext,
        mega_triton_kernel/kernels/barrier.py)."""
        if self.world <= 1:
            return None
        return self.graph.add_task(
            "barrier", ("barrier", self.axis, self.world), [0, 0, 0],
            reads=[], writes=[], cost=0.01, tag="barrier",
        )

    def make_matmul(
        self,
        wname: str,
        layer: int,
        src: BufferHandle,
        k: int,
        n_cols: int,
        dst: Optional[BufferHandle] = None,
        tag: str = "",
        prologue: Optional[str] = None,
        eps: float = 0.0,
        norm_row: int = 0,
    ) -> BufferHandle:
        """dst(B, n_cols) = prologue(src) @ weights[wname][layer].
        (ref: make_qkv_proj/make_o_proj/make_mlp_fc, model_builder.py:189-300;
        fused prologues mirror the ref's fused task kernels,
        mega kernels/mlp_fc1.py — see kernel._matmul_branch)."""
        dst = dst or self.buffer(n_cols, tag or wname)
        self.graph.add_task(
            "matmul", ("matmul", wname, k, n_cols, prologue, eps),
            [layer, src.id, dst.id, norm_row],
            reads=[src], writes=[dst],
            cost=estimate_gemm_ms(self.batch, n_cols, k, chip=self._chip),
            tag=tag or f"{wname}[{layer}]", buf_args=(1, 2),
        )
        return dst

    def make_rms_matmul(self, wname, layer, src, k, n_cols, norm_row,
                        eps, dst=None, tag=""):
        """Fused rms_norm(src) @ W (saves one task + HBM round trip)."""
        return self.make_matmul(wname, layer, src, k, n_cols, dst=dst,
                                tag=tag or f"rms+{wname}[{layer}]",
                                prologue="rms", eps=eps,
                                norm_row=norm_row)

    def make_act_matmul(self, wname, layer, src, inter, n_cols,
                        dst=None, tag=""):
        """Fused (silu(gate) * up) @ W: src is the (B, 2*inter) gate_up
        output, contract dim = inter."""
        return self.make_matmul(wname, layer, src, inter, n_cols,
                                dst=dst,
                                tag=tag or f"silu+{wname}[{layer}]",
                                prologue="silu")

    def make_rms_norm(
        self,
        norm_row: int,
        src: BufferHandle,
        width: int,
        eps: float,
        dst: Optional[BufferHandle] = None,
        tag: str = "",
    ) -> BufferHandle:
        """dst = rms_norm(src) * norms[norm_row] over `width` columns
        (ref: make_rms_norm, model_builder.py:189-368)."""
        dst = dst or self.buffer(width, tag or "rmsnorm")
        self.graph.add_task(
            "rms_norm", ("rms_norm", width, eps),
            [norm_row, src.id, dst.id],
            reads=[src], writes=[dst], cost=0.02,
            tag=tag or f"rms[{norm_row}]", buf_args=(1, 2),
        )
        return dst

    def make_silu_mul(
        self, src: BufferHandle, inter: int,
        dst: Optional[BufferHandle] = None, tag: str = "",
    ) -> BufferHandle:
        """dst(B, inter) = silu(src[:, :inter]) * src[:, inter:2*inter]
        (ref: make_activation, mega kernels/activation.py)."""
        dst = dst or self.buffer(inter, tag or "silu_mul")
        self.graph.add_task(
            "silu_mul", ("silu_mul", inter), [src.id, dst.id, 0],
            reads=[src], writes=[dst], cost=0.02,
            tag=tag or "silu_mul", buf_args=(0, 1),
        )
        return dst

    def make_add(
        self, a: BufferHandle, b: BufferHandle, width: int,
        dst: Optional[BufferHandle] = None, tag: str = "",
    ) -> BufferHandle:
        """dst = a + b (residual adds; ref: make_elementwise)."""
        dst = dst or self.buffer(width, tag or "add")
        self.graph.add_task(
            "add", ("add", width), [a.id, b.id, dst.id],
            reads=[a, b], writes=[dst], cost=0.01,
            tag=tag or "add", buf_args=(0, 1, 2),
        )
        return dst

    def make_allreduce_add(
        self,
        partial: BufferHandle,
        residual: BufferHandle,
        width: int,
        dst: Optional[BufferHandle] = None,
        tag: str = "",
    ) -> BufferHandle:
        """dst = all_reduce(partial, axis) + residual — the TP row-parallel
        epilogue fused with the residual add (ref: make_allreduce,
        model_builder.py:331-351 + mega kernels/allreduce.py multimem AR).
        Mailbox reuse across calls is parity-double-buffered; flow control
        is the recv-wait itself (a device cannot start AR k+2 before every
        peer finished AR k — see kernel._allreduce_branch)."""
        dst = dst or self.buffer(width, tag or "ar")
        parity = self._ar_count % 2
        self._ar_count += 1
        self.graph.add_task(
            "allreduce_add",
            ("allreduce_add", width, self.axis, self.world),
            [partial.id, residual.id, dst.id, parity],
            reads=[partial, residual], writes=[dst],
            cost=estimate_ar_ms(
                width * self.batch * 2, self.world, self._chip
            ) + 0.01,
            tag=tag or f"ar[{self._ar_count - 1}]", buf_args=(0, 1, 2),
        )
        return dst

    def make_attention(
        self,
        layer: int,
        qkv: BufferHandle,
        hq_l: int,
        hkv_l: int,
        head_dim: int,
        s_max: int,
        eps: float,
        use_qk_norm: bool,
        q_norm_base: int = 0,
        k_norm_base: int = 0,
        dst: Optional[BufferHandle] = None,
        tag: str = "",
        page: int = 0,
    ) -> Tuple[BufferHandle, BufferHandle, BufferHandle]:
        """Decode attention: qk-norm + rope + GQA over the cached prefix,
        with the new token's k/v folded into the softmax in-register
        (ref: make_attn → paged flash decode task,
        model_builder.py:240-287). Returns (attn_out, k_new, v_new); the
        runner scatters k_new/v_new into the cache outside the kernel
        (see kernel.py module docstring). q/k_norm_base: row offsets of
        the per-layer qk-norm vectors in the stacked norms array."""
        dst = dst or self.buffer(hq_l * head_dim, tag or "attn")
        kn = self.buffer(hkv_l * head_dim, f"k_new[{layer}]", pinned=True)
        vn = self.buffer(hkv_l * head_dim, f"v_new[{layer}]", pinned=True)
        self.graph.add_task(
            "attention",
            ("attention", hq_l, hkv_l, head_dim, s_max, eps, use_qk_norm,
             q_norm_base, k_norm_base, page),
            [layer, qkv.id, dst.id, kn.id, vn.id],
            reads=[qkv], writes=[dst, kn, vn],
            cost=estimate_gemm_ms(
                self.batch * hq_l, s_max, head_dim, chip=self._chip
            ) * 2 + 0.03,
            tag=tag or f"attn[{layer}]", buf_args=(1, 2, 3, 4),
        )
        return dst, kn, vn
