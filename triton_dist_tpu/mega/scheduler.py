"""Megakernel scheduler: task -> per-core work queues + scoreboard
watermarks + workspace slot plan.

TPU-native re-design of the reference's scheduler
(ref: python/triton_dist/mega_triton_kernel/core/scheduler.py:30-95). The
reference round-robins task tuples over NUM_SMS queues; a TPU chip has
1-2 TensorCores, so the default is critical-path list scheduling
(strategy "least_loaded") and the scoreboard is per-core *progress
watermarks* rather than per-tile signals: core c broadcasts "I completed
my k-th task"; a task waits until progress[c'] >= wm[c'] for every other
core. Same-core order subsumes same-core deps, so at num_cores=1 (v5e,
CPU interpret) every watermark is zero and the queue is simply a
topological order.

The heavy lifting lives in the native C++ library (csrc/scheduler.cc via
mega/_native.py); the pure-Python mirrors below implement the identical
algorithms and are used when the native build is unavailable
(TDT_NO_NATIVE=1 forces them — the tests cross-check both).
"""

from __future__ import annotations

import ctypes
import dataclasses
import os
import heapq
from typing import Any, List, Optional, Tuple

import numpy as np

from triton_dist_tpu.mega import _native
from triton_dist_tpu.mega.core import Graph, plan_mm_tiles

STRATEGIES = {"round_robin": 0, "blocked": 1, "least_loaded": 2}


def pf_arena_bytes() -> int:
    """Prefetch-arena VMEM byte budget (TDT_MEGA_PF_ARENA_BYTES,
    default 32 MiB — two 32B-class first tiles in flight)."""
    return int(os.environ.get("TDT_MEGA_PF_ARENA_BYTES", str(32 << 20)))


def auto_pf_depth(specs) -> int:
    """Byte-aware arena depth: as many rotating slots as the byte
    budget buys at this graph's arena-rectangle size (the arena is one
    (depth, max K, max TN) VMEM block — the RECTANGLE is what occupies
    VMEM, not the per-weight tile), clamped to [2, 4]. The floor of 2
    keeps one tile in flight across every task boundary (depth 1 is
    the legacy single-tile lookahead, opt-in via TDT_MEGA_PF_DEPTH);
    the ceiling of 4 bounds plan churn — deeper arenas stopped
    converting cold opens well before 4 on the Qwen3 graphs
    (tests/test_mega_core.py monotonicity corpus)."""
    env = os.environ.get("TDT_MEGA_PF_DEPTH")
    if env:
        return max(1, int(env))
    if not specs:
        return 2
    rect = max(kk for _, kk, _ in specs) * max(tn for _, _, tn in specs)
    return max(2, min(4, pf_arena_bytes() // max(rect * 2, 1)))


@dataclasses.dataclass
class PrefetchPlan:
    """The cross-task weight-streaming plan (see kernel.py ROW comment):
    each prefetchable matmul ("consumer") is assigned a rotating arena
    slot and an earlier row of the SAME queue ("issuer") that starts the
    first weight tile's DMA. depth = arena slots = max prefetches in
    flight. Consumers with no legal issuer open cold and are recorded in
    `cold` — validate_schedule enforces that every consumer is exactly
    one of the two."""

    depth: int
    specs: List[Tuple[str, int, int]]   # [(wname, K, TN)] — pf_code order
    issue_code: np.ndarray              # (n_tasks,) 0 = row carries no hint
    issue_layer: np.ndarray
    issue_slot: np.ndarray
    consume: np.ndarray                 # (n_tasks,) pf_in: slot+1, 0 = cold
    cold: List[int]                     # consumer task ids opening cold


@dataclasses.dataclass
class StorePlan:
    """The cross-task store/forward pipeline (single-core queues only —
    under concurrent cores a scoreboard completion must imply the data is
    in HBM, which a deferred store would break). defer_st=1 rows leave
    their workspace store in flight; the FOLLOWING row drains it (pend_w
    = 1 + index into `widths`), before its own loads when pend_early=1
    (reads alias the stored slot, or the branch has no late-drain site)
    or right before it first overwrites vout otherwise. fwd_in=1 rows
    read their main input straight from the previous task's vout."""

    widths: Tuple[int, ...]
    defer_st: np.ndarray
    pend_w: np.ndarray
    pend_early: np.ndarray
    fwd_in: np.ndarray


@dataclasses.dataclass
class Schedule:
    core: np.ndarray         # (n_tasks,) core of each task
    pos: np.ndarray          # (n_tasks,) position within its core queue
    watermarks: np.ndarray   # (n_tasks, num_cores) scoreboard waits
    order: List[int]         # global order (core-major: core0 queue, ...)
    queues: List[List[int]]  # per-core task id lists
    buf_slot: np.ndarray     # (n_bufs,) workspace slot per buffer
    n_slots: int
    native: bool             # True when produced by the C++ scheduler
    # predicted scoreboard stall per queue (cost-model time a core spends
    # waiting on other cores' watermarks beyond its own availability),
    # from predicted_stalls; validate_schedule asserts monotonized
    # watermarks reproduce it exactly
    stall: Any = None
    prefetch: Optional[PrefetchPlan] = None
    # fusion-plan provenance: schedule_graph(plan=...) stamps the
    # triton_dist_tpu.plan.Plan id so resident serving and one-shot
    # forwards can be checked to agree on pairings
    plan_id: Optional[str] = None

    @property
    def num_cores(self) -> int:
        return int(self.watermarks.shape[1])


def _i32(a) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(a, dtype=np.int32))


# -- pure-Python mirrors of the native algorithms ----------------------------


def _py_schedule(n, edges, cost, num_cores, strategy, affinity=None):
    """affinity (optional, least_loaded only): per-task bool marking
    prefetch consumers (matmuls whose first weight tile can stream from
    an earlier row of the same queue — kernel.py ROW comment). Such a
    task prefers the core of its latest-scheduled predecessor among
    near-tied loads, so a branch able to ISSUE its prefetch precedes it
    in the same queue (the hint and the arena are per-core VMEM: a
    cross-core predecessor cannot feed it)."""
    succ = [[] for _ in range(n)]
    pred = [[] for _ in range(n)]
    indeg = [0] * n
    for s, d in edges:
        succ[s].append(d)
        pred[d].append(s)
        indeg[d] += 1
    # critical-path priorities over reverse topo order
    order = []
    stack = [t for t in range(n) if indeg[t] == 0]
    deg = list(indeg)
    while stack:
        t = stack.pop()
        order.append(t)
        for s in succ[t]:
            deg[s] -= 1
            if deg[s] == 0:
                stack.append(s)
    if len(order) != n:
        raise ValueError("dependency cycle in megakernel graph")
    def cost_of(t):
        return cost[t] if cost is not None else 1.0

    prio = [0.0] * n
    for t in reversed(order):
        prio[t] = cost_of(t) + max((prio[s] for s in succ[t]), default=0.0)

    ready = [(-prio[t], t) for t in range(n) if indeg[t] == 0]
    heapq.heapify(ready)
    deg = list(indeg)
    core = [0] * n
    pos = [0] * n
    sched_at = [0] * n  # scheduling step, for the affinity tie-break
    core_load = [0.0] * num_cores
    core_len = [0] * num_cores
    scheduled = 0
    rr = 0
    per = (n + num_cores - 1) // num_cores
    while ready:
        _, t = heapq.heappop(ready)
        if num_cores == 1:
            c = 0
        elif strategy == 0:
            c = rr % num_cores
            rr += 1
        elif strategy == 1:
            c = min(scheduled // per, num_cores - 1)
        else:
            c = min(range(num_cores), key=lambda k: core_load[k])
            if affinity is not None and affinity[t] and pred[t]:
                # prefetch co-location: among near-tied cores, follow the
                # latest-scheduled predecessor (load slack bounded by the
                # task's own cost — never trades real balance for it)
                want = core[max(pred[t], key=lambda p: sched_at[p])]
                if (want != c
                        and core_load[want] <= core_load[c] + cost_of(t)):
                    c = want
        core[t] = c
        pos[t] = core_len[c]
        core_len[c] += 1
        core_load[c] += cost_of(t)
        sched_at[t] = scheduled
        scheduled += 1
        for s in succ[t]:
            deg[s] -= 1
            if deg[s] == 0:
                heapq.heappush(ready, (-prio[s], s))
    return np.array(core, np.int32), np.array(pos, np.int32)


def _py_watermarks(n, edges, core, pos, num_cores):
    wm = np.zeros((n, num_cores), np.int32)
    for s, d in edges:
        if core[s] == core[d]:
            if pos[s] >= pos[d]:
                raise ValueError(f"invalid schedule: dep {s}->{d} inverted")
            continue
        wm[d, core[s]] = max(wm[d, core[s]], pos[s] + 1)
    return wm


def monotone_watermarks(sched: "Schedule") -> np.ndarray:
    """Watermarks rewritten as a running max along each core queue.

    Waiting for the running max blocks no longer than the original wait
    (every earlier task on the queue already waited for its own watermark,
    so by the time task d runs, progress has reached the prefix max) and
    makes consumed-count tracking static: the kernel's per-row wait is
    simply wm_mono[d] - wm_mono[previous row], a compile-time delta."""
    wm = np.array(sched.watermarks, np.int32, copy=True)
    for q in sched.queues:
        run = np.zeros(wm.shape[1], np.int32)
        for t in q:
            run = np.maximum(run, wm[t])
            wm[t] = run
    return wm


INF_POS = 1 << 30


def after_vectors(sched: "Schedule", wm_mono: np.ndarray) -> np.ndarray:
    """A[t, c] = the smallest queue position p such that task (c, p) is
    guaranteed to START strictly after task t COMPLETES (INF_POS if no
    such task). This is the happens-before closure of the multi-core
    execution order — same-core program order plus scoreboard watermark
    waits — used by the slot planner to prove that a workspace slot's
    previous tenant is fully drained before its next definer can run.

    At num_cores=1 this degenerates to A[t, 0] = pos[t] + 1 and the
    planner below reproduces the linear-interval planner exactly."""
    n, nc = wm_mono.shape
    core = np.asarray(sched.core)
    pos = np.asarray(sched.pos)
    # HB successor edges on tasks: same-core next task, plus each task u
    # whose (monotone) watermark on core c equals p+1 starts after task
    # (c, p) completes. Larger watermarks are reached transitively.
    succ: List[List[int]] = [[] for _ in range(n)]
    by_cp = {(int(core[t]), int(pos[t])): t for t in range(n)}
    for q in sched.queues:
        for a, b in zip(q, q[1:]):
            succ[a].append(b)
    for u in range(n):
        for c in range(nc):
            w = int(wm_mono[u, c])
            if w > 0 and c != core[u]:
                succ[by_cp[(c, w - 1)]].append(u)
    indeg = np.zeros(n, np.int64)
    for t in range(n):
        for s in succ[t]:
            indeg[s] += 1
    topo = [t for t in range(n) if indeg[t] == 0]
    head = 0
    while head < len(topo):
        t = topo[head]
        head += 1
        for s in succ[t]:
            indeg[s] -= 1
            if indeg[s] == 0:
                topo.append(s)
    assert len(topo) == n, "cycle in happens-before graph"
    A = np.full((n, nc), INF_POS, np.int64)
    for t in reversed(topo):
        for s in succ[t]:
            # start(s) is after comp(t): s's own position counts, and
            # everything after comp(s) is after start(s) >= comp(t)
            A[t] = np.minimum(A[t], A[s])
            A[t, core[s]] = min(A[t, core[s]], pos[s])
    return A


def _buffer_users(graph: Graph) -> Tuple[List[int], List[List[int]]]:
    """(defining task per buffer (-1 if external), every accessing task
    per buffer) — shared by the HB slot planner and its validator, whose
    agreement the multi-core slot safety argument depends on."""
    nb = len(graph.buffers)
    def_task = [-1] * nb
    users: List[List[int]] = [[] for _ in range(nb)]
    for t in graph.tasks:
        for b in t.writes:
            if def_task[b] < 0:
                def_task[b] = t.id
            users[b].append(t.id)
        for b in t.reads:
            users[b].append(t.id)
    return def_task, users


def _py_plan_slots_hb(graph: Graph, sched: "Schedule",
                      A: np.ndarray) -> Tuple[np.ndarray, int]:
    """Slot planning under concurrent cores: slot reuse is legal only
    when every task touching the previous tenant happens-before the new
    tenant's defining task (proved via the `after_vectors` closure, not
    linear order — two tasks adjacent in the core-major order may run
    CONCURRENTLY on different cores)."""
    nb = len(graph.buffers)
    nc = A.shape[1]
    core = np.asarray(sched.core)
    pos = np.asarray(sched.pos)
    gpos = {t: i for i, t in enumerate(sched.order)}
    def_task, users = _buffer_users(graph)
    order_b = sorted(range(nb),
                     key=lambda b: gpos.get(def_task[b], -1))
    slot = np.zeros(nb, np.int64)
    # release[s][c] = min position on core c from which a new tenant's
    # def task may start (max over the old tenant's users' A vectors)
    release: List[np.ndarray] = []
    for b in order_b:
        pinned = graph.pinned.get(b, False)
        d = def_task[b]
        chosen = -1
        if not pinned and d >= 0:
            for s, rel in enumerate(release):
                if rel is None:
                    continue  # pinned slot
                if pos[d] >= rel[core[d]]:
                    chosen = s
                    break
        if chosen < 0:
            chosen = len(release)
            release.append(np.zeros(nc, np.int64))
        slot[b] = chosen
        if pinned:
            release[chosen] = None
        else:
            rel = np.zeros(nc, np.int64)
            for u in users[b]:
                rel = np.maximum(rel, A[u])
            if not users[b]:
                rel[:] = INF_POS  # unused buffer: never reusable safely
            release[chosen] = rel
    return np.array(slot, np.int32), len(release)


def _py_plan_slots(ndef, last, pinned):
    n = len(ndef)
    free_at: List[int] = []
    slot = [0] * n
    for b in sorted(range(n), key=lambda b: ndef[b]):
        chosen = -1
        if not pinned[b]:
            for s, fa in enumerate(free_at):
                if fa <= ndef[b]:
                    chosen = s
                    break
        if chosen < 0:
            chosen = len(free_at)
            free_at.append(0)
        slot[b] = chosen
        free_at[chosen] = (1 << 30) if pinned[b] else last[b] + 1
    return np.array(slot, np.int32), len(free_at)


# -- prefetch / store-pipeline planning ---------------------------------------


def prefetch_specs(tasks) -> Tuple[List[Tuple[str, int, int]], dict]:
    """([(wname, K, TN)] in pf_code order, wname -> pf_code). A weight is
    prefetchable only when every matmul using it shares one (K, TN) —
    the single arena-tile geometry the issuer and consumer must agree
    on. Shared by kernel.compile_graph (builds the arena) and
    plan_prefetch/validate_schedule (assign and check the hints). Tiles
    come from the byte-budgeted plan_mm_tiles map — the same map the
    kernel tiles with."""
    tn_of = plan_mm_tiles([t.branch_key for t in tasks
                           if t.op == "matmul"])
    name_dims: dict = {}
    for t in tasks:
        if t.op != "matmul":
            continue
        k = t.branch_key
        name_dims.setdefault(k[1], set()).add((k[2], tn_of[k]))
    specs: List[Tuple[str, int, int]] = []
    code_of: dict = {}
    for wname in sorted(name_dims):
        if len(name_dims[wname]) == 1:
            (kk, tn), = name_dims[wname]
            code_of[wname] = len(specs) + 1
            specs.append((wname, kk, tn))
    return specs, code_of


def _matmul_nt(task, tn_of) -> int:
    n_cols = task.branch_key[3]
    return n_cols // tn_of[task.branch_key]


def plan_prefetch(graph: Graph, sched: "Schedule",
                  depth: Optional[int] = None) -> PrefetchPlan:
    """Assign each prefetchable matmul a rotating arena slot and an
    issuing predecessor row in the same queue.

    Policy: the hint rides the IMMEDIATELY preceding row (assigning it to
    the closest previous matmul instead — streaming through intervening
    small tasks — was measured WORSE on the 32B model: the 3-5 MB pf
    tile head-of-line-blocks every intervening task's small input DMA in
    the shared HBM->VMEM queue; what helps is issuing EARLY WITHIN the
    task — see the kernel branch bodies). The arena's job is different:
    with depth >= 2 an nt==1 matmul can issue the NEXT matmul's tile
    before its own last dot instead of in its store epilogue, and the
    slot being written is never the slot being read.

    Slot-safety invariant (replayed in _validate_prefetch): an issue into
    slot s must come strictly after the previous consumer of s has read
    it — equality (issue and previous consume on one row) is legal only
    when that row is a matmul with nt > 1, which reads its own tile at
    j==0 before issuing at j==nt-1; an nt==1 matmul under depth > 1
    issues BEFORE its read."""
    tasks = graph.tasks
    n = len(tasks)
    specs, code_of = prefetch_specs(tasks)
    tn_of = plan_mm_tiles([t.branch_key for t in tasks
                           if t.op == "matmul"])
    if depth is None:
        depth = auto_pf_depth(specs)
    plan = PrefetchPlan(
        depth=depth, specs=specs,
        issue_code=np.zeros(n, np.int32),
        issue_layer=np.zeros(n, np.int32),
        issue_slot=np.zeros(n, np.int32),
        consume=np.zeros(n, np.int32), cold=[],
    )
    for q in sched.queues:
        cons_rows: List[int] = []  # queue rows of slot-using consumers
        for qi, tid in enumerate(q):
            t = tasks[tid]
            if t.op != "matmul" or t.branch_key[1] not in code_of:
                continue
            k = len(cons_rows)
            lo = cons_rows[k - depth] if k >= depth else -1
            isr = qi - 1
            ok = isr >= 0 and plan.issue_code[q[isr]] == 0
            if ok and isr == lo:
                # issuer row IS the slot's previous consumer: only safe
                # when it reads its own tile before issuing (nt > 1)
                prev = tasks[q[isr]]
                ok = prev.op == "matmul" and _matmul_nt(prev, tn_of) > 1
            elif ok:
                ok = isr > lo
            if not ok:
                plan.cold.append(tid)
                continue
            slot = k % depth
            plan.issue_code[q[isr]] = code_of[t.branch_key[1]]
            plan.issue_layer[q[isr]] = t.args[0]
            plan.issue_slot[q[isr]] = slot
            plan.consume[tid] = slot + 1
            cons_rows.append(qi)
    _validate_prefetch(graph, sched, plan)  # self-check at plan time
    return plan


def _validate_prefetch(graph: Graph, sched: "Schedule",
                       plan: PrefetchPlan) -> None:
    """Replay the arena per queue: every issue targets a drained slot,
    every consume finds its slot filled with the matching weight tile,
    and every prefetchable matmul either consumes or is flagged cold."""
    tasks = graph.tasks
    specs, code_of = prefetch_specs(tasks)
    tn_of = plan_mm_tiles([t.branch_key for t in tasks
                           if t.op == "matmul"])
    assert plan.specs == specs, "prefetch plan built for a different graph"
    cold = set(plan.cold)
    seen = set()
    for q in sched.queues:
        filled: dict = {}  # slot -> (pf_code, layer)
        for qi, tid in enumerate(q):
            t = tasks[tid]
            is_consumer = (t.op == "matmul"
                           and t.branch_key[1] in code_of)
            code = int(plan.issue_code[tid])
            cons = int(plan.consume[tid])
            if not is_consumer:
                assert cons == 0, (
                    f"non-matmul task {tid} marked as prefetch consumer")
            # same-row ordering: nt>1 matmuls consume then issue;
            # everything else (incl. nt==1 under depth>1) issues first
            consume_first = (is_consumer and cons > 0
                             and _matmul_nt(t, tn_of) > 1)

            def do_consume():
                slot = cons - 1
                assert slot in filled, (
                    f"task {tid} consumes arena slot {slot} but no "
                    "prefetch is in flight there")
                got_code, got_layer = filled.pop(slot)
                assert got_code == code_of[t.branch_key[1]], (
                    f"task {tid}: arena slot {slot} holds weight code "
                    f"{got_code}, expected {code_of[t.branch_key[1]]}")
                assert got_layer == t.args[0], (
                    f"task {tid}: arena slot {slot} holds layer "
                    f"{got_layer}, expected {t.args[0]}")

            if consume_first:
                do_consume()
            if code:
                slot = int(plan.issue_slot[tid])
                assert 0 <= slot < plan.depth
                assert slot not in filled, (
                    f"task {tid} issues into arena slot {slot} while the "
                    "previous tile there is unconsumed")
                filled[slot] = (code, int(plan.issue_layer[tid]))
            if is_consumer:
                if cons > 0:
                    if not consume_first:
                        do_consume()
                    seen.add(tid)
                else:
                    assert tid in cold, (
                        f"matmul task {tid} ({t.tag}) has no issuing "
                        "predecessor and is not flagged cold")
                    seen.add(tid)
        assert not filled, (
            f"prefetches left in flight at queue end: {filled}")
    # coverage: every prefetchable matmul is either fed or flagged cold
    for t in tasks:
        if t.op == "matmul" and t.branch_key[1] in code_of:
            assert t.id in seen
    assert cold.isdisjoint(
        {t for t in range(len(tasks)) if plan.consume[t] > 0})


def plan_store_forward(
    graph: Graph,
    sched: "Schedule",
    store_width,
    can_late_drain,
    fwd_spec,
) -> StorePlan:
    """Build the deferred-store / forward plan for a single-core queue.

    store_width[t]: width of task t's deferrable workspace store (0 =
    the branch cannot defer: attention's multi-store epilogue, barrier).
    can_late_drain[t]: the branch drains a pending store right before
    overwriting vout (matmul/rms/silu/add/AR); others must drain EARLY,
    in the dispatch wrapper, before their loads. fwd_spec[t]: (main
    source buffer id, rows read from vout) for branches that can read
    their input from the previous task's vout, else None."""
    n = len(graph.tasks)
    empty = StorePlan((), np.zeros(n, np.int32), np.zeros(n, np.int32),
                      np.zeros(n, np.int32), np.zeros(n, np.int32))
    if sched.num_cores != 1:
        # concurrent queues: a scoreboard completion must imply the data
        # reached HBM — never defer across the scoreboard
        return empty
    q = sched.queues[0]
    tasks = graph.tasks
    pairs = []  # (producer, consumer, width, early, fwd)
    for qi in range(len(q) - 1):
        p, c = q[qi], q[qi + 1]
        w = int(store_width[p])
        if w == 0:
            continue
        tp, tc = tasks[p], tasks[c]
        assert len(tp.writes) == 1, (
            f"deferrable task {p} must write exactly one buffer")
        dst = tp.writes[0]
        fs = fwd_spec[c]
        fwd = (fs is not None and fs[0] == dst and fs[1] <= w
               # reads of dst beyond the main source still hit HBM and
               # would need the store drained first — no forward then
               and tc.reads.count(dst) == 1)
        if fwd:
            assert can_late_drain[c], "forward-capable branches late-drain"
            early = 0
        elif dst in tc.reads:
            early = 1  # consumer loads the stored slot from HBM
        else:
            early = 0 if can_late_drain[c] else 1
        pairs.append((p, c, w, early, 1 if fwd else 0))
    if not pairs:
        return empty
    widths = tuple(sorted({w for _, _, w, _, _ in pairs}))
    plan = StorePlan(widths, np.zeros(n, np.int32), np.zeros(n, np.int32),
                     np.zeros(n, np.int32), np.zeros(n, np.int32))
    for p, c, w, early, fwd in pairs:
        plan.defer_st[p] = 1
        plan.pend_w[c] = widths.index(w) + 1
        plan.pend_early[c] = early
        plan.fwd_in[c] = fwd
    return plan


# -- predicted scoreboard stall ----------------------------------------------


def predicted_stalls(graph: Graph, sched: "Schedule",
                     monotone: bool = False) -> np.ndarray:
    """Cost-model simulation of the multi-queue execution: each core runs
    its queue in order; a task starts at max(own core free, dep ends).
    Returns per-core stall = total time a core sits waiting on OTHER
    cores' watermarks beyond its own availability.

    monotone=True derives deps from the monotonized watermarks the
    kernel actually waits on (task t waits for task (c, wm_mono[t,c]-1))
    instead of the raw graph edges; validate_schedule asserts both give
    identical stalls — the monotone rewrite's no-extra-blocking theorem
    (see monotone_watermarks)."""
    tasks = graph.tasks
    n = len(tasks)
    nc = sched.num_cores
    core = np.asarray(sched.core)
    deps: List[List[int]] = [[] for _ in range(n)]
    if monotone:
        wm = monotone_watermarks(sched)
        by_cp = {(int(core[t]), int(sched.pos[t])): t for t in range(n)}
        for t in range(n):
            for c in range(nc):
                w = int(wm[t, c])
                if w > 0 and c != core[t]:
                    deps[t].append(by_cp[(c, w - 1)])
    else:
        for s, d in graph.edges:
            if core[s] != core[d]:
                deps[d].append(s)
    ptr = [0] * nc
    t_end = [None] * n
    core_time = [0.0] * nc
    stall = np.zeros(nc, np.float64)
    done = 0
    while done < n:
        best = None
        for c in range(nc):
            if ptr[c] >= len(sched.queues[c]):
                continue
            t = sched.queues[c][ptr[c]]
            if any(t_end[d] is None for d in deps[t]):
                continue
            start = max([core_time[c]] + [t_end[d] for d in deps[t]])
            if best is None or start < best[0]:
                best = (start, c, t)
        if best is None:
            raise ValueError("schedule simulation deadlocked "
                             "(inconsistent watermarks?)")
        start, c, t = best
        stall[c] += start - core_time[c]
        t_end[t] = start + tasks[t].cost
        core_time[c] = t_end[t]
        ptr[c] += 1
        done += 1
    return stall


# -- public entry -------------------------------------------------------------


def schedule_graph(
    graph: Graph,
    num_cores: int = 1,
    strategy: str = "least_loaded",
    use_native: Optional[bool] = None,
    pf_depth: Optional[int] = None,
    plan=None,
) -> Schedule:
    """Schedule + plan a Graph. use_native=None auto-selects the C++ lib.

    pf_depth sets the weight-prefetch arena depth the plan is built for
    (default: byte-aware auto_pf_depth from the graph's tile rectangle;
    TDT_MEGA_PF_DEPTH pins it); the returned schedule carries
    `prefetch` (PrefetchPlan) and `stall` (predicted per-queue scoreboard
    stall), both asserted by validate_schedule.

    plan (optional triton_dist_tpu.plan.Plan): the fusion plan this
    graph was lowered under — the schedule adopts its mega_strategy and
    carries its plan_id, so the megakernel and the layer-forward planes
    provably run the SAME pairing decisions. The plan_id hashes the
    plan's applied tune-cache winners (Plan.applied_configs) along with
    the routing, so a schedule built before the cache was populated can
    never be confused with one inheriting a measured config."""
    n = len(graph.tasks)
    if n == 0:
        raise ValueError("empty megakernel graph")
    if plan is not None:
        strategy = plan.mega_strategy
    if pf_depth is None:
        # byte-aware default: size the rotating arena from this graph's
        # actual tile rectangle (auto_pf_depth; TDT_MEGA_PF_DEPTH wins)
        pf_depth = auto_pf_depth(prefetch_specs(graph.tasks)[0])
    strat = STRATEGIES[strategy]
    edges = graph.edges
    cost = [t.cost for t in graph.tasks]
    lib = _native.load() if use_native in (None, True) else None
    if use_native is True and lib is None:
        raise RuntimeError("native scheduler requested but unavailable")

    def _finalize(sched: Schedule) -> Schedule:
        sched.stall = predicted_stalls(graph, sched)
        sched.prefetch = plan_prefetch(graph, sched, depth=pf_depth)
        if plan is not None:
            sched.plan_id = plan.plan_id
        return sched

    if lib is not None:
        src = _i32([e[0] for e in edges])
        dst = _i32([e[1] for e in edges])
        costs = np.ascontiguousarray(np.asarray(cost, np.float64))
        core = np.zeros(n, np.int32)
        pos = np.zeros(n, np.int32)
        rc = lib.tdt_schedule(
            n, len(edges),
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            dst.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            costs.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            num_cores, strat,
            core.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            pos.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        if rc != 0:
            raise ValueError(f"native scheduler failed rc={rc} "
                             "(dependency cycle?)")
        wm = np.zeros((n, num_cores), np.int32)
        rc = lib.tdt_watermarks(
            n, len(edges),
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            dst.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            core.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            pos.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            num_cores,
            wm.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        if rc != 0:
            raise ValueError(f"native watermarks failed rc={rc}")
    else:
        # prefetch-aware placement (pure-Python path): a prefetchable
        # matmul prefers its predecessor's core so the issuing row and
        # the consuming matmul share a queue (and a VMEM arena)
        _, code_of = prefetch_specs(graph.tasks)
        affinity = [t.op == "matmul" and t.branch_key[1] in code_of
                    for t in graph.tasks]
        core, pos = _py_schedule(n, edges, cost, num_cores, strat,
                                 affinity=affinity)
        wm = _py_watermarks(n, edges, core, pos, num_cores)

    queues: List[List[int]] = [[] for _ in range(num_cores)]
    for t in range(n):
        queues[core[t]].append(t)
    for q in queues:
        q.sort(key=lambda t: pos[t])
    order = [t for q in queues for t in q]

    if num_cores > 1:
        # concurrent queues: interval liveness over the core-major order
        # is unsound (adjacent order positions may run concurrently on
        # different cores) — plan via the happens-before closure instead
        sched = Schedule(core=np.asarray(core), pos=np.asarray(pos),
                         watermarks=wm, order=order, queues=queues,
                         buf_slot=np.zeros(len(graph.buffers), np.int32),
                         n_slots=0, native=lib is not None)
        slot, n_slots = _py_plan_slots_hb(
            graph, sched, after_vectors(sched, monotone_watermarks(sched)))
        sched.buf_slot = slot
        sched.n_slots = int(n_slots)
        return _finalize(sched)

    ndef, last = graph.liveness(order)
    pinned = [graph.pinned.get(b.id, False) for b in graph.buffers]
    if lib is not None:
        nd = _i32(ndef)
        lt = _i32(last)
        pn = np.ascontiguousarray(np.asarray(pinned, np.uint8))
        slot = np.zeros(len(graph.buffers), np.int32)
        n_slots = lib.tdt_plan_slots(
            len(graph.buffers),
            nd.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            lt.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            pn.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            slot.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
    else:
        slot, n_slots = _py_plan_slots(ndef, last, pinned)

    return _finalize(Schedule(core=np.asarray(core), pos=np.asarray(pos),
                              watermarks=wm, order=order, queues=queues,
                              buf_slot=slot, n_slots=int(n_slots),
                              native=lib is not None))


def validate_schedule(graph: Graph, sched: Schedule) -> None:
    """Sanity invariants (tests + compile-time assert): every dep either
    precedes its consumer on the same core or carries a watermark; no two
    buffers sharing a slot can be live concurrently (proved by interval
    order at one core, by the happens-before closure under many); the
    prefetch plan covers every prefetchable matmul (fed by an issuing
    predecessor or explicitly flagged cold) with a race-free arena
    replay; and the predicted scoreboard stall is reproduced exactly by
    the monotonized watermarks the kernel actually waits on (the
    monotone rewrite must add no blocking)."""
    for s, d in graph.edges:
        if sched.core[s] == sched.core[d]:
            assert sched.pos[s] < sched.pos[d], (s, d)
        else:
            assert sched.watermarks[d, sched.core[s]] >= sched.pos[s] + 1
    # prefetch-coverage invariant (weight-streaming pipeline)
    plan = sched.prefetch
    if plan is None:
        plan = plan_prefetch(graph, sched)
    else:
        _validate_prefetch(graph, sched, plan)
    # predicted-stall invariant: raw-edge and monotone-watermark
    # simulations must agree, and must match the recorded prediction
    raw = predicted_stalls(graph, sched)
    mono = predicted_stalls(graph, sched, monotone=True)
    assert np.allclose(mono, raw), (
        f"monotone watermark rewrite changes predicted stall: "
        f"{mono} vs {raw}")
    if sched.stall is not None:
        assert np.allclose(np.asarray(sched.stall), raw), (
            f"recorded stall prediction {sched.stall} does not match "
            f"the schedule's simulation {raw}")
    if sched.num_cores > 1:
        _validate_slots_hb(graph, sched)
        return
    ndef, last = graph.liveness(sched.order)
    by_slot: dict = {}
    for b in graph.buffers:
        by_slot.setdefault(sched.buf_slot[b.id], []).append(
            (ndef[b.id], last[b.id], b.id))
    for slot, spans in by_slot.items():
        spans.sort()
        for (d1, l1, b1), (d2, l2, b2) in zip(spans, spans[1:]):
            assert l1 < d2, (
                f"slot {slot}: buffers {b1} and {b2} overlap "
                f"([{d1},{l1}] vs [{d2},{l2}])"
            )


def task_hb_graph(sched: Schedule) -> "HBGraph":
    """The multi-core execution's happens-before DAG on task ids, built
    on the shared verify.hb engine (one HB implementation for protocol
    verification AND schedule validation): same-queue program order plus
    one edge per monotone-watermark wait (task u waiting wm[u, c] = w
    starts after task (c, w-1) completes). Edge semantics are
    completion(a) <= start(b), so `reaches(u, d)` iff task u is fully
    drained before task d can run — the slot-reuse safety predicate."""
    from triton_dist_tpu.verify.hb import HBGraph

    g = HBGraph()
    for t in range(len(sched.core)):
        g.add_node(t)
    for q in sched.queues:
        for a, b in zip(q, q[1:]):
            g.add_edge(a, b)
    wm = monotone_watermarks(sched)
    core = np.asarray(sched.core)
    by_cp = {(int(core[t]), int(sched.pos[t])): t
             for t in range(len(core))}
    for u in range(len(core)):
        for c in range(wm.shape[1]):
            w = int(wm[u, c])
            if w > 0 and c != core[u]:
                g.add_edge(by_cp[(c, w - 1)], u)
    return g


def _validate_slots_hb(graph: Graph, sched: Schedule) -> None:
    """Multi-core slot check: for each pair of buffers sharing a slot,
    one buffer's every accessor must happen-before the other's defining
    task (recomputed independently of the planner's choices — the
    planner proves via `after_vectors` position minima, the validator
    via shared-engine reachability; their agreement is the check)."""
    g = task_hb_graph(sched)
    def_task, users = _buffer_users(graph)

    def all_before(b1: int, b2: int) -> bool:
        d = def_task[b2]
        if d < 0:
            return False
        return all(g.reaches(u, d) for u in users[b1])

    by_slot: dict = {}
    for b in graph.buffers:
        by_slot.setdefault(int(sched.buf_slot[b.id]), []).append(b.id)
    for slot, bufs in by_slot.items():
        for i, b1 in enumerate(bufs):
            for b2 in bufs[i + 1:]:
                assert all_before(b1, b2) or all_before(b2, b1), (
                    f"slot {slot}: buffers {b1} and {b2} may be live "
                    "concurrently under the multi-core schedule"
                )
