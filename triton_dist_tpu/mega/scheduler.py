"""Megakernel scheduler: task -> per-core work queues + scoreboard
watermarks + workspace slot plan.

TPU-native re-design of the reference's scheduler
(ref: python/triton_dist/mega_triton_kernel/core/scheduler.py:30-95). The
reference round-robins task tuples over NUM_SMS queues; a TPU chip has
1-2 TensorCores, so the default is critical-path list scheduling
(strategy "least_loaded") and the scoreboard is per-core *progress
watermarks* rather than per-tile signals: core c broadcasts "I completed
my k-th task"; a task waits until progress[c'] >= wm[c'] for every other
core. Same-core order subsumes same-core deps, so at num_cores=1 (v5e,
CPU interpret) every watermark is zero and the queue is simply a
topological order.

The heavy lifting lives in the native C++ library (csrc/scheduler.cc via
mega/_native.py); the pure-Python mirrors below implement the identical
algorithms and are used when the native build is unavailable
(TDT_NO_NATIVE=1 forces them — the tests cross-check both).
"""

from __future__ import annotations

import ctypes
import dataclasses
import heapq
from typing import List, Optional

import numpy as np

from triton_dist_tpu.mega import _native
from triton_dist_tpu.mega.core import Graph

STRATEGIES = {"round_robin": 0, "blocked": 1, "least_loaded": 2}


@dataclasses.dataclass
class Schedule:
    core: np.ndarray         # (n_tasks,) core of each task
    pos: np.ndarray          # (n_tasks,) position within its core queue
    watermarks: np.ndarray   # (n_tasks, num_cores) scoreboard waits
    order: List[int]         # global order (core-major: core0 queue, ...)
    queues: List[List[int]]  # per-core task id lists
    buf_slot: np.ndarray     # (n_bufs,) workspace slot per buffer
    n_slots: int
    native: bool             # True when produced by the C++ scheduler


def _i32(a) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(a, dtype=np.int32))


# -- pure-Python mirrors of the native algorithms ----------------------------


def _py_schedule(n, edges, cost, num_cores, strategy):
    succ = [[] for _ in range(n)]
    indeg = [0] * n
    for s, d in edges:
        succ[s].append(d)
        indeg[d] += 1
    # critical-path priorities over reverse topo order
    order = []
    stack = [t for t in range(n) if indeg[t] == 0]
    deg = list(indeg)
    while stack:
        t = stack.pop()
        order.append(t)
        for s in succ[t]:
            deg[s] -= 1
            if deg[s] == 0:
                stack.append(s)
    if len(order) != n:
        raise ValueError("dependency cycle in megakernel graph")
    prio = [0.0] * n
    for t in reversed(order):
        c = cost[t] if cost is not None else 1.0
        prio[t] = c + max((prio[s] for s in succ[t]), default=0.0)

    ready = [(-prio[t], t) for t in range(n) if indeg[t] == 0]
    heapq.heapify(ready)
    deg = list(indeg)
    core = [0] * n
    pos = [0] * n
    core_load = [0.0] * num_cores
    core_len = [0] * num_cores
    scheduled = 0
    rr = 0
    per = (n + num_cores - 1) // num_cores
    while ready:
        _, t = heapq.heappop(ready)
        if num_cores == 1:
            c = 0
        elif strategy == 0:
            c = rr % num_cores
            rr += 1
        elif strategy == 1:
            c = min(scheduled // per, num_cores - 1)
        else:
            c = min(range(num_cores), key=lambda k: core_load[k])
        core[t] = c
        pos[t] = core_len[c]
        core_len[c] += 1
        core_load[c] += cost[t] if cost is not None else 1.0
        scheduled += 1
        for s in succ[t]:
            deg[s] -= 1
            if deg[s] == 0:
                heapq.heappush(ready, (-prio[s], s))
    return np.array(core, np.int32), np.array(pos, np.int32)


def _py_watermarks(n, edges, core, pos, num_cores):
    wm = np.zeros((n, num_cores), np.int32)
    for s, d in edges:
        if core[s] == core[d]:
            if pos[s] >= pos[d]:
                raise ValueError(f"invalid schedule: dep {s}->{d} inverted")
            continue
        wm[d, core[s]] = max(wm[d, core[s]], pos[s] + 1)
    return wm


def _py_plan_slots(ndef, last, pinned):
    n = len(ndef)
    free_at: List[int] = []
    slot = [0] * n
    for b in sorted(range(n), key=lambda b: ndef[b]):
        chosen = -1
        if not pinned[b]:
            for s, fa in enumerate(free_at):
                if fa <= ndef[b]:
                    chosen = s
                    break
        if chosen < 0:
            chosen = len(free_at)
            free_at.append(0)
        slot[b] = chosen
        free_at[chosen] = (1 << 30) if pinned[b] else last[b] + 1
    return np.array(slot, np.int32), len(free_at)


# -- public entry -------------------------------------------------------------


def schedule_graph(
    graph: Graph,
    num_cores: int = 1,
    strategy: str = "least_loaded",
    use_native: Optional[bool] = None,
) -> Schedule:
    """Schedule + plan a Graph. use_native=None auto-selects the C++ lib."""
    n = len(graph.tasks)
    if n == 0:
        raise ValueError("empty megakernel graph")
    strat = STRATEGIES[strategy]
    edges = graph.edges
    cost = [t.cost for t in graph.tasks]
    lib = _native.load() if use_native in (None, True) else None
    if use_native is True and lib is None:
        raise RuntimeError("native scheduler requested but unavailable")

    if lib is not None:
        src = _i32([e[0] for e in edges])
        dst = _i32([e[1] for e in edges])
        costs = np.ascontiguousarray(np.asarray(cost, np.float64))
        core = np.zeros(n, np.int32)
        pos = np.zeros(n, np.int32)
        rc = lib.tdt_schedule(
            n, len(edges),
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            dst.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            costs.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            num_cores, strat,
            core.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            pos.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        if rc != 0:
            raise ValueError(f"native scheduler failed rc={rc} "
                             "(dependency cycle?)")
        wm = np.zeros((n, num_cores), np.int32)
        rc = lib.tdt_watermarks(
            n, len(edges),
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            dst.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            core.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            pos.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            num_cores,
            wm.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        if rc != 0:
            raise ValueError(f"native watermarks failed rc={rc}")
    else:
        core, pos = _py_schedule(n, edges, cost, num_cores, strat)
        wm = _py_watermarks(n, edges, core, pos, num_cores)

    queues: List[List[int]] = [[] for _ in range(num_cores)]
    for t in range(n):
        queues[core[t]].append(t)
    for q in queues:
        q.sort(key=lambda t: pos[t])
    order = [t for q in queues for t in q]

    ndef, last = graph.liveness(order)
    pinned = [graph.pinned.get(b.id, False) for b in graph.buffers]
    if lib is not None:
        nd = _i32(ndef)
        lt = _i32(last)
        pn = np.ascontiguousarray(np.asarray(pinned, np.uint8))
        slot = np.zeros(len(graph.buffers), np.int32)
        n_slots = lib.tdt_plan_slots(
            len(graph.buffers),
            nd.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            lt.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            pn.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            slot.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
    else:
        slot, n_slots = _py_plan_slots(ndef, last, pinned)

    return Schedule(core=np.asarray(core), pos=np.asarray(pos),
                    watermarks=wm, order=order, queues=queues,
                    buf_slot=slot, n_slots=int(n_slots),
                    native=lib is not None)


def validate_schedule(graph: Graph, sched: Schedule) -> None:
    """Sanity invariants (tests + compile-time assert): every dep either
    precedes its consumer on the same core or carries a watermark; no two
    live buffers share a slot."""
    for s, d in graph.edges:
        if sched.core[s] == sched.core[d]:
            assert sched.pos[s] < sched.pos[d], (s, d)
        else:
            assert sched.watermarks[d, sched.core[s]] >= sched.pos[s] + 1
    ndef, last = graph.liveness(sched.order)
    by_slot: dict = {}
    for b in graph.buffers:
        by_slot.setdefault(sched.buf_slot[b.id], []).append(
            (ndef[b.id], last[b.id], b.id))
    for slot, spans in by_slot.items():
        spans.sort()
        for (d1, l1, b1), (d2, l2, b2) in zip(spans, spans[1:]):
            assert l1 < d2, (
                f"slot {slot}: buffers {b1} and {b2} overlap "
                f"([{d1},{l1}] vs [{d2},{l2}])"
            )
