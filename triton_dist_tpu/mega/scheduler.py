"""Megakernel scheduler: task -> per-core work queues + scoreboard
watermarks + workspace slot plan.

TPU-native re-design of the reference's scheduler
(ref: python/triton_dist/mega_triton_kernel/core/scheduler.py:30-95). The
reference round-robins task tuples over NUM_SMS queues; a TPU chip has
1-2 TensorCores, so the default is critical-path list scheduling
(strategy "least_loaded") and the scoreboard is per-core *progress
watermarks* rather than per-tile signals: core c broadcasts "I completed
my k-th task"; a task waits until progress[c'] >= wm[c'] for every other
core. Same-core order subsumes same-core deps, so at num_cores=1 (v5e,
CPU interpret) every watermark is zero and the queue is simply a
topological order.

The heavy lifting lives in the native C++ library (csrc/scheduler.cc via
mega/_native.py); the pure-Python mirrors below implement the identical
algorithms and are used when the native build is unavailable
(TDT_NO_NATIVE=1 forces them — the tests cross-check both).
"""

from __future__ import annotations

import ctypes
import dataclasses
import heapq
from typing import List, Optional, Tuple

import numpy as np

from triton_dist_tpu.mega import _native
from triton_dist_tpu.mega.core import Graph

STRATEGIES = {"round_robin": 0, "blocked": 1, "least_loaded": 2}


@dataclasses.dataclass
class Schedule:
    core: np.ndarray         # (n_tasks,) core of each task
    pos: np.ndarray          # (n_tasks,) position within its core queue
    watermarks: np.ndarray   # (n_tasks, num_cores) scoreboard waits
    order: List[int]         # global order (core-major: core0 queue, ...)
    queues: List[List[int]]  # per-core task id lists
    buf_slot: np.ndarray     # (n_bufs,) workspace slot per buffer
    n_slots: int
    native: bool             # True when produced by the C++ scheduler

    @property
    def num_cores(self) -> int:
        return int(self.watermarks.shape[1])


def _i32(a) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(a, dtype=np.int32))


# -- pure-Python mirrors of the native algorithms ----------------------------


def _py_schedule(n, edges, cost, num_cores, strategy):
    succ = [[] for _ in range(n)]
    indeg = [0] * n
    for s, d in edges:
        succ[s].append(d)
        indeg[d] += 1
    # critical-path priorities over reverse topo order
    order = []
    stack = [t for t in range(n) if indeg[t] == 0]
    deg = list(indeg)
    while stack:
        t = stack.pop()
        order.append(t)
        for s in succ[t]:
            deg[s] -= 1
            if deg[s] == 0:
                stack.append(s)
    if len(order) != n:
        raise ValueError("dependency cycle in megakernel graph")
    prio = [0.0] * n
    for t in reversed(order):
        c = cost[t] if cost is not None else 1.0
        prio[t] = c + max((prio[s] for s in succ[t]), default=0.0)

    ready = [(-prio[t], t) for t in range(n) if indeg[t] == 0]
    heapq.heapify(ready)
    deg = list(indeg)
    core = [0] * n
    pos = [0] * n
    core_load = [0.0] * num_cores
    core_len = [0] * num_cores
    scheduled = 0
    rr = 0
    per = (n + num_cores - 1) // num_cores
    while ready:
        _, t = heapq.heappop(ready)
        if num_cores == 1:
            c = 0
        elif strategy == 0:
            c = rr % num_cores
            rr += 1
        elif strategy == 1:
            c = min(scheduled // per, num_cores - 1)
        else:
            c = min(range(num_cores), key=lambda k: core_load[k])
        core[t] = c
        pos[t] = core_len[c]
        core_len[c] += 1
        core_load[c] += cost[t] if cost is not None else 1.0
        scheduled += 1
        for s in succ[t]:
            deg[s] -= 1
            if deg[s] == 0:
                heapq.heappush(ready, (-prio[s], s))
    return np.array(core, np.int32), np.array(pos, np.int32)


def _py_watermarks(n, edges, core, pos, num_cores):
    wm = np.zeros((n, num_cores), np.int32)
    for s, d in edges:
        if core[s] == core[d]:
            if pos[s] >= pos[d]:
                raise ValueError(f"invalid schedule: dep {s}->{d} inverted")
            continue
        wm[d, core[s]] = max(wm[d, core[s]], pos[s] + 1)
    return wm


def monotone_watermarks(sched: "Schedule") -> np.ndarray:
    """Watermarks rewritten as a running max along each core queue.

    Waiting for the running max blocks no longer than the original wait
    (every earlier task on the queue already waited for its own watermark,
    so by the time task d runs, progress has reached the prefix max) and
    makes consumed-count tracking static: the kernel's per-row wait is
    simply wm_mono[d] - wm_mono[previous row], a compile-time delta."""
    wm = np.array(sched.watermarks, np.int32, copy=True)
    for q in sched.queues:
        run = np.zeros(wm.shape[1], np.int32)
        for t in q:
            run = np.maximum(run, wm[t])
            wm[t] = run
    return wm


INF_POS = 1 << 30


def after_vectors(sched: "Schedule", wm_mono: np.ndarray) -> np.ndarray:
    """A[t, c] = the smallest queue position p such that task (c, p) is
    guaranteed to START strictly after task t COMPLETES (INF_POS if no
    such task). This is the happens-before closure of the multi-core
    execution order — same-core program order plus scoreboard watermark
    waits — used by the slot planner to prove that a workspace slot's
    previous tenant is fully drained before its next definer can run.

    At num_cores=1 this degenerates to A[t, 0] = pos[t] + 1 and the
    planner below reproduces the linear-interval planner exactly."""
    n, nc = wm_mono.shape
    core = np.asarray(sched.core)
    pos = np.asarray(sched.pos)
    # HB successor edges on tasks: same-core next task, plus each task u
    # whose (monotone) watermark on core c equals p+1 starts after task
    # (c, p) completes. Larger watermarks are reached transitively.
    succ: List[List[int]] = [[] for _ in range(n)]
    by_cp = {(int(core[t]), int(pos[t])): t for t in range(n)}
    for q in sched.queues:
        for a, b in zip(q, q[1:]):
            succ[a].append(b)
    for u in range(n):
        for c in range(nc):
            w = int(wm_mono[u, c])
            if w > 0 and c != core[u]:
                succ[by_cp[(c, w - 1)]].append(u)
    indeg = np.zeros(n, np.int64)
    for t in range(n):
        for s in succ[t]:
            indeg[s] += 1
    topo = [t for t in range(n) if indeg[t] == 0]
    head = 0
    while head < len(topo):
        t = topo[head]
        head += 1
        for s in succ[t]:
            indeg[s] -= 1
            if indeg[s] == 0:
                topo.append(s)
    assert len(topo) == n, "cycle in happens-before graph"
    A = np.full((n, nc), INF_POS, np.int64)
    for t in reversed(topo):
        for s in succ[t]:
            # start(s) is after comp(t): s's own position counts, and
            # everything after comp(s) is after start(s) >= comp(t)
            A[t] = np.minimum(A[t], A[s])
            A[t, core[s]] = min(A[t, core[s]], pos[s])
    return A


def _buffer_users(graph: Graph) -> Tuple[List[int], List[List[int]]]:
    """(defining task per buffer (-1 if external), every accessing task
    per buffer) — shared by the HB slot planner and its validator, whose
    agreement the multi-core slot safety argument depends on."""
    nb = len(graph.buffers)
    def_task = [-1] * nb
    users: List[List[int]] = [[] for _ in range(nb)]
    for t in graph.tasks:
        for b in t.writes:
            if def_task[b] < 0:
                def_task[b] = t.id
            users[b].append(t.id)
        for b in t.reads:
            users[b].append(t.id)
    return def_task, users


def _py_plan_slots_hb(graph: Graph, sched: "Schedule",
                      A: np.ndarray) -> Tuple[np.ndarray, int]:
    """Slot planning under concurrent cores: slot reuse is legal only
    when every task touching the previous tenant happens-before the new
    tenant's defining task (proved via the `after_vectors` closure, not
    linear order — two tasks adjacent in the core-major order may run
    CONCURRENTLY on different cores)."""
    nb = len(graph.buffers)
    nc = A.shape[1]
    core = np.asarray(sched.core)
    pos = np.asarray(sched.pos)
    gpos = {t: i for i, t in enumerate(sched.order)}
    def_task, users = _buffer_users(graph)
    order_b = sorted(range(nb),
                     key=lambda b: gpos.get(def_task[b], -1))
    slot = np.zeros(nb, np.int64)
    # release[s][c] = min position on core c from which a new tenant's
    # def task may start (max over the old tenant's users' A vectors)
    release: List[np.ndarray] = []
    for b in order_b:
        pinned = graph.pinned.get(b, False)
        d = def_task[b]
        chosen = -1
        if not pinned and d >= 0:
            for s, rel in enumerate(release):
                if rel is None:
                    continue  # pinned slot
                if pos[d] >= rel[core[d]]:
                    chosen = s
                    break
        if chosen < 0:
            chosen = len(release)
            release.append(np.zeros(nc, np.int64))
        slot[b] = chosen
        if pinned:
            release[chosen] = None
        else:
            rel = np.zeros(nc, np.int64)
            for u in users[b]:
                rel = np.maximum(rel, A[u])
            if not users[b]:
                rel[:] = INF_POS  # unused buffer: never reusable safely
            release[chosen] = rel
    return np.array(slot, np.int32), len(release)


def _py_plan_slots(ndef, last, pinned):
    n = len(ndef)
    free_at: List[int] = []
    slot = [0] * n
    for b in sorted(range(n), key=lambda b: ndef[b]):
        chosen = -1
        if not pinned[b]:
            for s, fa in enumerate(free_at):
                if fa <= ndef[b]:
                    chosen = s
                    break
        if chosen < 0:
            chosen = len(free_at)
            free_at.append(0)
        slot[b] = chosen
        free_at[chosen] = (1 << 30) if pinned[b] else last[b] + 1
    return np.array(slot, np.int32), len(free_at)


# -- public entry -------------------------------------------------------------


def schedule_graph(
    graph: Graph,
    num_cores: int = 1,
    strategy: str = "least_loaded",
    use_native: Optional[bool] = None,
) -> Schedule:
    """Schedule + plan a Graph. use_native=None auto-selects the C++ lib."""
    n = len(graph.tasks)
    if n == 0:
        raise ValueError("empty megakernel graph")
    strat = STRATEGIES[strategy]
    edges = graph.edges
    cost = [t.cost for t in graph.tasks]
    lib = _native.load() if use_native in (None, True) else None
    if use_native is True and lib is None:
        raise RuntimeError("native scheduler requested but unavailable")

    if lib is not None:
        src = _i32([e[0] for e in edges])
        dst = _i32([e[1] for e in edges])
        costs = np.ascontiguousarray(np.asarray(cost, np.float64))
        core = np.zeros(n, np.int32)
        pos = np.zeros(n, np.int32)
        rc = lib.tdt_schedule(
            n, len(edges),
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            dst.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            costs.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            num_cores, strat,
            core.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            pos.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        if rc != 0:
            raise ValueError(f"native scheduler failed rc={rc} "
                             "(dependency cycle?)")
        wm = np.zeros((n, num_cores), np.int32)
        rc = lib.tdt_watermarks(
            n, len(edges),
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            dst.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            core.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            pos.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            num_cores,
            wm.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        if rc != 0:
            raise ValueError(f"native watermarks failed rc={rc}")
    else:
        core, pos = _py_schedule(n, edges, cost, num_cores, strat)
        wm = _py_watermarks(n, edges, core, pos, num_cores)

    queues: List[List[int]] = [[] for _ in range(num_cores)]
    for t in range(n):
        queues[core[t]].append(t)
    for q in queues:
        q.sort(key=lambda t: pos[t])
    order = [t for q in queues for t in q]

    if num_cores > 1:
        # concurrent queues: interval liveness over the core-major order
        # is unsound (adjacent order positions may run concurrently on
        # different cores) — plan via the happens-before closure instead
        sched = Schedule(core=np.asarray(core), pos=np.asarray(pos),
                         watermarks=wm, order=order, queues=queues,
                         buf_slot=np.zeros(len(graph.buffers), np.int32),
                         n_slots=0, native=lib is not None)
        slot, n_slots = _py_plan_slots_hb(
            graph, sched, after_vectors(sched, monotone_watermarks(sched)))
        sched.buf_slot = slot
        sched.n_slots = int(n_slots)
        return sched

    ndef, last = graph.liveness(order)
    pinned = [graph.pinned.get(b.id, False) for b in graph.buffers]
    if lib is not None:
        nd = _i32(ndef)
        lt = _i32(last)
        pn = np.ascontiguousarray(np.asarray(pinned, np.uint8))
        slot = np.zeros(len(graph.buffers), np.int32)
        n_slots = lib.tdt_plan_slots(
            len(graph.buffers),
            nd.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            lt.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            pn.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            slot.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
    else:
        slot, n_slots = _py_plan_slots(ndef, last, pinned)

    return Schedule(core=np.asarray(core), pos=np.asarray(pos),
                    watermarks=wm, order=order, queues=queues,
                    buf_slot=slot, n_slots=int(n_slots),
                    native=lib is not None)


def validate_schedule(graph: Graph, sched: Schedule) -> None:
    """Sanity invariants (tests + compile-time assert): every dep either
    precedes its consumer on the same core or carries a watermark; no two
    buffers sharing a slot can be live concurrently (proved by interval
    order at one core, by the happens-before closure under many)."""
    for s, d in graph.edges:
        if sched.core[s] == sched.core[d]:
            assert sched.pos[s] < sched.pos[d], (s, d)
        else:
            assert sched.watermarks[d, sched.core[s]] >= sched.pos[s] + 1
    if sched.num_cores > 1:
        _validate_slots_hb(graph, sched)
        return
    ndef, last = graph.liveness(sched.order)
    by_slot: dict = {}
    for b in graph.buffers:
        by_slot.setdefault(sched.buf_slot[b.id], []).append(
            (ndef[b.id], last[b.id], b.id))
    for slot, spans in by_slot.items():
        spans.sort()
        for (d1, l1, b1), (d2, l2, b2) in zip(spans, spans[1:]):
            assert l1 < d2, (
                f"slot {slot}: buffers {b1} and {b2} overlap "
                f"([{d1},{l1}] vs [{d2},{l2}])"
            )


def _validate_slots_hb(graph: Graph, sched: Schedule) -> None:
    """Multi-core slot check: for each pair of buffers sharing a slot,
    one buffer's every accessor must happen-before the other's defining
    task (recomputed independently of the planner's choices)."""
    A = after_vectors(sched, monotone_watermarks(sched))
    core = np.asarray(sched.core)
    pos = np.asarray(sched.pos)
    def_task, users = _buffer_users(graph)

    def all_before(b1: int, b2: int) -> bool:
        d = def_task[b2]
        if d < 0:
            return False
        return all(pos[d] >= A[u][core[d]] for u in users[b1])

    by_slot: dict = {}
    for b in graph.buffers:
        by_slot.setdefault(int(sched.buf_slot[b.id]), []).append(b.id)
    for slot, bufs in by_slot.items():
        for i, b1 in enumerate(bufs):
            for b2 in bufs[i + 1:]:
                assert all_before(b1, b2) or all_before(b2, b1), (
                    f"slot {slot}: buffers {b1} and {b2} may be live "
                    "concurrently under the multi-core schedule"
                )
