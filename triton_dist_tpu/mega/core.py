"""Megakernel task graph: buffers, tasks, dependency tracking.

TPU-native re-design of the reference's megakernel task framework
(ref: python/triton_dist/mega_triton_kernel/core/task_base.py:36-220 —
CodeGenKey / TaskDependency / int-tuple task encoding — and
core/builder.py:33-64). The reference encodes raw tensor pointers into
uint32 work-queue rows; TPU kernels have no pointers, so activations live
in one flat HBM workspace of uniform B-row slots and tasks carry *slot
indices* (plus layer ids and op args) in their int32 rows. Dependencies
are derived from buffer def/use (the ref builds TaskDependency tile
ranges; at decode shapes every op is a single tile, so task == tile and
the dependency is the whole buffer).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Hashable, List, Sequence, Tuple


def fit_mm_tile(n: int, cap: int = 512) -> int:
    """Largest divisor of n that is <= cap, preferring lane multiples.

    Shared by the megakernel's matmul tiling and the scheduler's prefetch
    planner — both must agree on each matmul's (K, TN) tile or the
    prefetch-coverage invariant would be checked against the wrong
    weight-tile geometry. Deliberately NOT named fit_tile: lang.core.
    fit_tile is a different algorithm with swapped argument roles
    ((tile, dim) vs this (n, cap)); sharing the name invited silently
    wrong tiles."""
    best = 1
    for t in range(min(cap, n), 0, -1):
        if n % t == 0:
            if t % 128 == 0 or t == n:
                return t
            if best == 1:
                best = t
    return best


# Weight-tile VMEM byte budget (per tile slot, bf16 accounting). The
# round-5 32B ledger attributed the 1.18x gap-to-floor largely to SHORT
# DMA BURSTS: a (K, TN) slice of an HBM-resident (L, K, N) weight is
# strided — each contiguous run is only TN * itemsize bytes — and at the
# legacy 512-column cap the Qwen3-32B gate_up tile streamed in 512-byte
# bursts (~0.85 of peak HBM on the burst-efficiency model,
# perf_model.hbm_stream_efficiency). A byte budget instead of a fixed
# column cap lets big-K weights take the widest dividing lane-multiple
# column count whose tile still fits the budget: at 16 MiB the 32B shard
# tiles at TN=1280 (2.5 KiB bursts), the 8B at TN<=2048. The budget is
# per SLOT; the kernel keeps 2 own-tile slots plus the prefetch arena,
# so total weight VMEM is ~(2 + pf_depth) x this figure.
MM_TILE_BYTES_DEFAULT = 16 << 20


def mm_tile_bytes() -> int:
    """TDT_MEGA_TILE_BYTES override (bytes), else the 16 MiB default."""
    return int(os.environ.get("TDT_MEGA_TILE_BYTES",
                              str(MM_TILE_BYTES_DEFAULT)))


def mm_tile_cap(kmax: int, itemsize: int = 2) -> int:
    """Column cap implied by the tile byte budget at contract dim kmax,
    rounded down to a lane multiple; never below the legacy 512 (small
    graphs keep their historical tiling). itemsize is fixed at the bf16
    2 by callers so the scheduler (which has no dtype) and the kernel
    always derive the SAME cap — the budget is a sizing heuristic, not
    an exact VMEM bound (compile_graph computes the real vmem limit
    from the fitted tiles)."""
    cap = (mm_tile_bytes() // (max(kmax, 1) * itemsize)) // 128 * 128
    return max(512, cap)


def plan_mm_tiles(mm_keys: Sequence[Hashable]) -> Dict[Hashable, int]:
    """branch_key -> TN for every matmul branch key, under ONE
    byte-budgeted column cap derived from the graph's largest contract
    dim (the own-tile buffer and prefetch arena are shared rectangles of
    (max K, max TN), so the cap must be global, not per-weight).

    THE single tiling definition: kernel._matmul_branch, the prefetch
    planner (scheduler.prefetch_specs), and the byte-accurate floor
    model (perf_model.mega_decode_traffic_terms) all tile through this
    map; a divergence would break the prefetch-coverage invariant or
    make the floor model explain the wrong bytes."""
    keys = [k for k in mm_keys if k and k[0] == "matmul"]
    if not keys:
        return {}
    cap = mm_tile_cap(max(k[2] for k in keys))
    return {k: fit_mm_tile(k[3], cap) for k in set(keys)}


@dataclasses.dataclass(frozen=True)
class BufferHandle:
    """One logical activation tensor: a B-row × width stripe of the
    workspace. `slot` is assigned by the planner at compile time."""

    id: int
    width: int
    name: str = ""


@dataclasses.dataclass
class Task:
    """One schedulable unit (op == single tile at decode shapes).

    branch_key identifies the generated switch branch (the reference's
    CodeGenKey(task_type, layer_id, task_id) — ours keys on the op kind
    plus its *static* config, so all layers sharing a shape share one
    branch and layer_id moves into the dynamic args)."""

    id: int
    op: str
    branch_key: Hashable
    args: List[int]                 # dynamic scalars for the queue row
    reads: List[int]                # buffer ids
    writes: List[int]               # buffer ids
    cost: float = 1.0               # perf-model estimate for the scheduler
    tag: str = ""
    # arg positions holding buffer ids, rewritten to workspace slots at
    # compile time (queue rows must carry slots, not graph buffer ids)
    buf_args: Tuple[int, ...] = ()


class Graph:
    """Append-only op graph with last-writer/reader dependency tracking
    (the reference tracks deps through its tensor wrappers;
    model_builder.py:160-175)."""

    def __init__(self, batch: int):
        self.batch = batch
        self.buffers: List[BufferHandle] = []
        self.tasks: List[Task] = []
        self._writer: Dict[int, int] = {}        # buf -> task that wrote it
        self._readers: Dict[int, List[int]] = {}  # buf -> tasks that read it
        self._edges: set = set()
        self.edges: List[Tuple[int, int]] = []
        self.pinned: Dict[int, bool] = {}
        # last barrier task id: all tasks added after a barrier depend on
        # it (otherwise the critical-path scheduler, seeing no edges,
        # would sink the zero-cost barrier to the END of the queue — and
        # remote DMA could land in a peer that has not entered the kernel)
        self.barrier: int = -1

    # -- buffers -------------------------------------------------------------

    def buffer(self, width: int, name: str = "",
               pinned: bool = False) -> BufferHandle:
        """New logical activation buffer. pinned=True gives it a dedicated
        workspace slot (kernel I/O: the planner must not reuse it)."""
        b = BufferHandle(len(self.buffers), int(width), name)
        self.buffers.append(b)
        self.pinned[b.id] = pinned
        return b

    # -- tasks ---------------------------------------------------------------

    def _edge(self, src: int, dst: int) -> None:
        if src != dst and (src, dst) not in self._edges:
            self._edges.add((src, dst))
            self.edges.append((src, dst))

    def add_task(
        self,
        op: str,
        branch_key: Hashable,
        args: Sequence[int],
        reads: Sequence[BufferHandle],
        writes: Sequence[BufferHandle],
        cost: float = 1.0,
        tag: str = "",
        buf_args: Sequence[int] = (),
        extra_deps: Sequence["Task"] = (),
    ) -> Task:
        t = Task(len(self.tasks), op, branch_key, list(args),
                 [b.id for b in reads], [b.id for b in writes],
                 cost, tag, tuple(buf_args))
        for b in t.reads:
            w = self._writer.get(b)
            if w is not None:
                self._edge(w, t.id)          # RAW
            self._readers.setdefault(b, []).append(t.id)
        for b in t.writes:
            w = self._writer.get(b)
            if w is not None:
                self._edge(w, t.id)          # WAW
            for r in self._readers.get(b, ()):
                self._edge(r, t.id)          # WAR
            self._writer[b] = t.id
            self._readers[b] = []
        for d in extra_deps:
            self._edge(d.id, t.id)
        if op == "barrier":
            self.barrier = t.id
        elif self.barrier >= 0:
            self._edge(self.barrier, t.id)
        self.tasks.append(t)
        return t

    # -- liveness (for the slot planner) --------------------------------------

    def liveness(self, order: Sequence[int]) -> Tuple[List[int], List[int]]:
        """(def_time, last_use_time) per buffer, in global schedule order.
        Buffers never defined by a task (external inputs) get def 0;
        buffers never read after their last write keep last=def."""
        time_of = {t: i for i, t in enumerate(order)}
        ndef = [0] * len(self.buffers)
        last = [0] * len(self.buffers)
        seen_def = [False] * len(self.buffers)
        for t in self.tasks:
            ti = time_of[t.id]
            for b in t.writes:
                if not seen_def[b]:
                    ndef[b] = ti
                    seen_def[b] = True
                if ti > last[b]:
                    last[b] = ti
            for b in t.reads:
                if ti > last[b]:
                    last[b] = ti
        return ndef, last
