"""triton_dist_tpu.wire — block-scaled quantized-wire codec plane.

Every ICI-bound collective in this framework can trade precision for
wire bytes under an explicit error budget (the EQuARX direction,
arXiv 2506.17615): the `wire_format=` knob on the two-shot allreduce,
the ring/full-mesh/low-latency allgathers, and the fused AG+GEMM /
GEMM+RS wire legs quantizes at the send edge, dequantizes at the
consume edge, and accumulates in f32 — changing payload bytes but
NEVER the semaphore protocol (proved format-invariant by
`verify.protocol_skeleton`; docs/verification.md).

  codec      WireFormat ("native" | "fp8" | "int8", block-scaled f32
             scales riding the metadata-row idiom), quantize/dequantize,
             the int8 wire image (encode_rows/decode_rows, pack/unpack)
             usable at jnp level and inside Pallas kernel bodies.
  numerics   the ulp/cosine drift harness per (collective, format),
             replaying each kernel's exact fold order; the calibration
             source for perf_model.estimate_wire_drift and the
             DEFAULT_ERROR_BUDGET gate.

`perf_model.choose_wire_format` picks the fastest format whose modeled
drift clears the caller's error budget; docs/performance.md "Quantized
wire" has the bytes-by-precision rooflines and the measured columns.
"""

from triton_dist_tpu.wire.codec import (  # noqa: F401
    CHECKSUM_BYTES,
    FP8,
    FP8_MAX,
    INT8,
    INT8_MAX,
    LANE,
    NATIVE,
    SCALE_BYTES,
    SCALE_EPS,
    WireFormat,
    unpack_checked,
    verify_rows,
    decode_rows,
    dequantize,
    encode_rows,
    is_native,
    n_blocks,
    pack,
    payload_dtype,
    quantize,
    resolve,
    roundtrip,
    unpack,
    wire_cols,
    wire_row_bytes,
)
from triton_dist_tpu.wire.numerics import (  # noqa: F401
    DEFAULT_ERROR_BUDGET,
    codec_drift,
    collective_drift,
    cosine_drift,
    drift_monotone_in_block,
    drift_table,
    max_ulp_f32,
    simulate_allreduce,
    simulate_ring_rs,
)
