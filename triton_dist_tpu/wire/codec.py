"""Block-scaled quantized-wire codecs — the one quantization definition.

The repo proved the quantized-wire pattern twice in isolation before
this subsystem existed: the fp8 payload + f32 scale plane of the EP
dispatch (`kernels/ep_a2a.py`, the reference's latency-class wire) and
the `accum_dtype` f32-wire knob of `kernels/reduce_scatter.py`. EQuARX
(arXiv 2506.17615) shows the generalization pays: quantizing the
AllReduce wire inside the runtime buys ~2x wire bytes at negligible
model-quality cost. This module is that generalization's codec plane:

  WireFormat     "native" (pass-through — payload bytes ARE the tensor),
                 "fp8" (e4m3), "int8" — each quantized format block-
                 scaled along the last axis with f32 scales.
  quantize /     the (payload, scale) pair. The fp8 per-row path is
  dequantize     BITWISE the legacy ep_a2a formula (pinned by
                 tests/test_wire.py::test_fp8_matches_legacy_ep_formula)
                 — the repo has exactly one quantization definition.
  encode_rows /  the WIRE IMAGE: one int8 (rows, wire_cols) array with
  decode_rows    the f32 scales bitcast into trailing byte columns and
                 the row lane-padded to 128 — the ep_a2a metadata-row
                 idiom, generalized. Pure jnp, so the same functions run
                 at host level (pack an array before a transport kernel)
                 AND inside Pallas kernel bodies (encode a VMEM value at
                 the send edge, decode at the consume edge).
  pack / unpack  host-level wrappers flattening trailing dims.

The load-bearing invariant of every consumer kernel: a wire format
changes PAYLOAD BYTES ONLY — never the semaphore protocol. Transport
kernels move the wire image exactly as they move native rows (same
puts, same delivery semaphores, same credits); `verify` proves the
synchronization skeleton format-invariant (`verify.protocol_skeleton`,
docs/verification.md "Format invariance").
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Union

import jax
import jax.numpy as jnp

# e4m3 finite max (the legacy ep_a2a constant) / int8 symmetric max.
FP8_MAX = 448.0
INT8_MAX = 127.0
# scale floor — keeps all-zero blocks finite (legacy ep_a2a constant)
SCALE_EPS = 1e-12
SCALE_BYTES = 4  # one f32 scale per block
CHECKSUM_BYTES = 4  # one i32 byte-sum per row (checksum formats)
LANE = 128       # TPU lane width; wire rows pad to a multiple

_KINDS = ("native", "fp8", "int8")


@dataclasses.dataclass(frozen=True)
class WireFormat:
    """One wire encoding: `kind` picks the payload dtype, `block` the
    scale granularity along the (flattened) last axis — None means one
    scale per row (the legacy ep_a2a per-token scheme); an int block
    must divide the row width. Hashable/frozen so it can ride jit
    closure keys and autotuner cache keys.

    `checksum=True` reserves CHECKSUM_BYTES more columns per row for a
    per-row byte-sum riding the same metadata-row idiom as the scales:
    one put, one delivery semaphore, and an integrity verdict at the
    consume edge (`verify_rows` / `unpack_checked` — a corrupted
    payload or scale stripe raises WireIntegrityError instead of
    dequantizing garbage; docs/robustness.md)."""

    kind: str = "native"
    block: Optional[int] = None
    checksum: bool = False

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(
                f"unknown wire format kind {self.kind!r} (one of {_KINDS})")
        if self.block is not None and self.block <= 0:
            raise ValueError(f"wire block must be positive, got {self.block}")
        if self.checksum and self.kind == "native":
            raise ValueError(
                "checksum rides the wire image; the native format has "
                "none (move the tensor itself)")


NATIVE = WireFormat("native")
FP8 = WireFormat("fp8")
INT8 = WireFormat("int8")

WireFormatLike = Union[None, str, WireFormat]


def resolve(fmt: WireFormatLike) -> WireFormat:
    """None/str/WireFormat -> WireFormat (None and "native" are the
    pass-through format)."""
    if fmt is None:
        return NATIVE
    if isinstance(fmt, WireFormat):
        return fmt
    if isinstance(fmt, str):
        return WireFormat(fmt)
    raise TypeError(f"wire_format must be None/str/WireFormat, got "
                    f"{type(fmt).__name__}")


def is_native(fmt: WireFormatLike) -> bool:
    return resolve(fmt).kind == "native"


def payload_dtype(fmt: WireFormatLike):
    f = resolve(fmt)
    if f.kind == "fp8":
        return jnp.float8_e4m3fn
    if f.kind == "int8":
        return jnp.int8
    raise ValueError("native wire has no quantized payload dtype")


def _fmax(fmt: WireFormat) -> float:
    return FP8_MAX if fmt.kind == "fp8" else INT8_MAX


def n_blocks(h: int, fmt: WireFormatLike) -> int:
    """Scale blocks per row of width h (block must divide h)."""
    f = resolve(fmt)
    if f.block is None:
        return 1
    if h % f.block:
        raise ValueError(
            f"wire block {f.block} does not divide row width {h}")
    return h // f.block


def wire_cols(h: int, fmt: WireFormatLike) -> int:
    """Wire-image row width (int8 columns) for a logical row of h
    elements: payload bytes + bitcast f32 scales (+ the per-row
    checksum word of checksum formats), padded to the lane width.
    Native format has no wire image (raises)."""
    f = resolve(fmt)
    if f.kind == "native":
        raise ValueError("native wire has no packed image; move the "
                         "tensor itself")
    used = h + SCALE_BYTES * n_blocks(h, f)
    if f.checksum:
        used += CHECKSUM_BYTES
    return -(-used // LANE) * LANE


def wire_row_bytes(h: int, fmt: WireFormatLike, dtype) -> int:
    """Bytes one logical row occupies ON THE WIRE — the quantity the
    perf_model's bytes-by-precision rooflines and the trace byte
    attribution price. Native: the tensor's own bytes."""
    f = resolve(fmt)
    if f.kind == "native":
        return h * jnp.dtype(dtype).itemsize
    return wire_cols(h, f)


def quantize(x: jax.Array, fmt: WireFormatLike):
    """Block-scaled quantization along the last axis ->
    (payload (..., H) in the format's dtype, scale (..., nb) f32).

    The per-row (block=None) fp8 path is op-for-op the legacy ep_a2a
    `_quantize_fp8` formula — absmax/FP8_MAX, floored at SCALE_EPS —
    so the migrated EP dispatch ships bitwise-identical payloads
    (pinned by the dedupe test)."""
    f = resolve(fmt)
    if f.kind == "native":
        raise ValueError("native wire is not quantized")
    h = x.shape[-1]
    nb = n_blocks(h, f)
    xf = x.astype(jnp.float32)
    if nb == 1:
        amax = jnp.max(jnp.abs(xf), axis=-1)
    else:
        xb = xf.reshape(x.shape[:-1] + (nb, f.block))
        amax = jnp.max(jnp.abs(xb), axis=-1)          # (..., nb)
    s = jnp.maximum(amax / _fmax(f), SCALE_EPS)
    if nb == 1:
        scaled = xf / s[..., None]
    else:
        scaled = (xb / s[..., None]).reshape(x.shape)
    if f.kind == "fp8":
        q = scaled.astype(jnp.float8_e4m3fn)
    else:
        q = jnp.clip(jnp.round(scaled), -INT8_MAX, INT8_MAX).astype(
            jnp.int8)
    if nb == 1:
        s = s[..., None]
    return q, s


def dequantize(q: jax.Array, scale: jax.Array, fmt: WireFormatLike,
               out_dtype):
    """(payload, scale) -> (..., H) in out_dtype; f32 multiply (the
    consume-edge accumulation dtype), cast last — the legacy ep_a2a
    decode order."""
    f = resolve(fmt)
    h = q.shape[-1]
    nb = scale.shape[-1]
    qf = q.astype(jnp.float32)
    if nb == 1:
        out = qf * scale
    else:
        blk = h // nb
        out = (qf.reshape(q.shape[:-1] + (nb, blk))
               * scale[..., None]).reshape(q.shape)
    return out.astype(out_dtype)


def _row_checksum(body: jax.Array) -> jax.Array:
    """Per-row i32 byte-sum over payload + scale columns — a single
    flipped bit changes exactly one byte, so the sum always moves (the
    detection this plane needs; not cryptographic)."""
    return jnp.sum(body.astype(jnp.int32), axis=-1)


def _maybe_corrupt(img: jax.Array, h: int, scale_cols: int) -> jax.Array:
    """Apply an active FaultPlan's scheduled wire bit-flips at the
    FIRST send-edge encode of the traced program (after checksum
    embedding, so integrity checking can see them). No plan: the image
    passes through untouched (zero cost off)."""
    from triton_dist_tpu.faults import plan as _fplan

    plan = _fplan.active()
    if plan is None:
        return img
    flips = plan.take_wire_flips()
    if not flips:
        return img
    import numpy as np

    from triton_dist_tpu.faults.plan import BitFlipScale

    for fl in flips:
        if isinstance(fl, BitFlipScale):
            c = h + min(max(fl.byte, 0), scale_cols - 1)
        else:
            c = min(max(fl.byte, 0), h - 1)
        r = min(max(fl.row, 0), img.shape[0] - 1)
        mask = int(np.uint8(1 << (fl.bit % 8)).astype(np.int8))
        img = img.at[r, c].set(
            jnp.bitwise_xor(img[r, c], jnp.int8(mask)))
    return img


def encode_rows(x: jax.Array, fmt: WireFormatLike) -> jax.Array:
    """(rows, H) float -> (rows, wire_cols) int8 wire image: payload
    bytes, then the f32 scales bitcast into byte columns (then the
    per-row checksum word on checksum formats), then zero lane padding.
    Pure jnp — usable on host arrays and on VMEM values inside Pallas
    kernel bodies (the send edge)."""
    f = resolve(fmt)
    q, s = quantize(x, f)
    m, h = x.shape
    if f.kind == "fp8":
        qb = jax.lax.bitcast_convert_type(q, jnp.int8)
    else:
        qb = q
    sb = jax.lax.bitcast_convert_type(s, jnp.int8).reshape(m, -1)
    body = jnp.concatenate([qb, sb], axis=-1)
    used = h + sb.shape[1]
    if f.checksum:
        cb = jax.lax.bitcast_convert_type(
            _row_checksum(body)[:, None], jnp.int8).reshape(
                m, CHECKSUM_BYTES)
        body = jnp.concatenate([body, cb], axis=-1)
        used += CHECKSUM_BYTES
    pad = wire_cols(h, f) - used
    img = jnp.concatenate(
        [body, jnp.zeros((m, pad), jnp.int8)], axis=-1)
    return _maybe_corrupt(img, h, sb.shape[1])


def decode_rows(w: jax.Array, h: int, fmt: WireFormatLike,
                out_dtype) -> jax.Array:
    """(rows, wire_cols) int8 wire image -> (rows, h) in out_dtype (the
    consume edge; f32 math inside, see dequantize)."""
    f = resolve(fmt)
    nb = n_blocks(h, f)
    m = w.shape[0]
    qb = w[:, :h]
    if f.kind == "fp8":
        q = jax.lax.bitcast_convert_type(qb, jnp.float8_e4m3fn)
    else:
        q = qb
    s = jax.lax.bitcast_convert_type(
        w[:, h:h + SCALE_BYTES * nb].reshape(m, nb, SCALE_BYTES),
        jnp.float32)
    return dequantize(q, s, f, out_dtype)


def verify_rows(w: jax.Array, h: int, fmt: WireFormatLike) -> jax.Array:
    """Per-row integrity verdict of a checksummed wire image: True
    where the recomputed byte-sum over payload + scale columns matches
    the embedded checksum word. Pure jnp — usable at host level AND
    inside Pallas kernel bodies (the consume edge; pair with
    faults.guard.integrity_trip to turn a failure into a guard row)."""
    f = resolve(fmt)
    if not f.checksum:
        raise ValueError(
            f"wire format {f} carries no checksum (WireFormat("
            "checksum=True))")
    nb = n_blocks(h, f)
    used = h + SCALE_BYTES * nb
    got = _row_checksum(w[:, :used])
    want = jax.lax.bitcast_convert_type(
        w[:, used:used + CHECKSUM_BYTES].reshape(
            w.shape[0], 1, CHECKSUM_BYTES), jnp.int32)[:, 0]
    return got == want


def _eager_integrity_check(w, h: int, f: WireFormat) -> None:
    """Detect-and-raise consume edge for CONCRETE wire images: raises
    WireIntegrityError naming the corrupted rows. Traced values skip
    (a jit program cannot raise; in-jit consumers pair verify_rows
    with a host-side check or a guard row instead)."""
    import jax.core as jcore

    if isinstance(w, jcore.Tracer):
        return
    import numpy as np

    from triton_dist_tpu.faults.errors import WireIntegrityError

    ok = np.asarray(verify_rows(w, h, f))
    if not ok.all():
        bad = np.nonzero(~ok)[0].tolist()
        raise WireIntegrityError(
            f"wire image failed its checksum on {len(bad)} row(s) "
            f"{bad[:8]} (payload or scale stripe corrupted in flight)",
            rows=bad)


def unpack_checked(w: jax.Array, trailing_shape, fmt: WireFormatLike,
                   out_dtype) -> jax.Array:
    """`unpack` with a MANDATORY integrity check (checksum formats
    only): the consume edge that raises WireIntegrityError on a
    corrupted image rather than dequantizing garbage."""
    f = resolve(fmt)
    if f.kind == "native":
        raise ValueError("native wire has no checksum to check")
    import math as _math

    _eager_integrity_check(w, _math.prod(trailing_shape), f)
    return unpack(w, trailing_shape, f, out_dtype)


def pack(x: jax.Array, fmt: WireFormatLike) -> jax.Array:
    """Host-level send edge: per-device array (rows, ...) -> wire image
    (rows, wire_cols) int8, trailing dims flattened. Native format
    passes the array through untouched (zero cost when off)."""
    f = resolve(fmt)
    if f.kind == "native":
        return x
    if x.ndim < 2:
        raise ValueError(
            f"quantized wire needs >=2D per-device arrays, got {x.shape}")
    return encode_rows(x.reshape(x.shape[0], -1), f)


def unpack(w: jax.Array, trailing_shape, fmt: WireFormatLike,
           out_dtype) -> jax.Array:
    """Host-level consume edge: wire image (rows, wire_cols) ->
    (rows,) + trailing_shape in out_dtype. Native: pass-through."""
    f = resolve(fmt)
    if f.kind == "native":
        return w
    h = math.prod(trailing_shape)
    if f.checksum:
        # detect-and-raise on concrete images; traced ones defer to
        # verify_rows at the caller's consume edge (see unpack_checked)
        _eager_integrity_check(w, h, f)
    out = decode_rows(w, h, f, out_dtype)
    return out.reshape((w.shape[0],) + tuple(trailing_shape))


def roundtrip(x: jax.Array, fmt: WireFormatLike) -> jax.Array:
    """encode+decode in place — the wire-fidelity reference every
    quantized collective is tested against (transport moves wire bytes,
    never changes them, so kernel output == roundtrip-composed
    reference). Native: identity."""
    f = resolve(fmt)
    if f.kind == "native":
        return x
    return unpack(pack(x, f), x.shape[1:], f, x.dtype)
