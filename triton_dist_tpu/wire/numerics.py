"""Numerics harness: ulp/cosine drift of quantized-wire collectives.

A wire format is a precision/bandwidth trade, and the trade is only
honest when the precision side is MEASURED, not asserted. This module
measures it: for each (collective, format) pair it replays the exact
fold order the kernel executes — per-hop decode-add-(re)encode for the
reduction rings, one encode per shard for the gather family — in pure
jnp on stacked per-rank data (no mesh needed), and reports the drift of
the quantized result against the same fold over the f32/native wire:

  cosine_drift  1 - cosine similarity (f64), the EQuARX-style model-
                quality proxy; what `DEFAULT_ERROR_BUDGET` bounds.
  max_ulp_f32   worst-element ulp distance in f32 bit space — the
                bitwise face of the same comparison: 0 iff the results
                are bit-identical as f32 (the native-wire case, pinned
                by tests/test_wire.py).

The simulations are also the oracles the mesh tests compare the real
kernels against (transport moves wire bytes, never changes them, so a
kernel whose output differs from its simulation has a transport bug,
not a codec choice). `perf_model.estimate_wire_drift`'s constants are
calibrated on this harness — see the calibration note there.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np

from triton_dist_tpu.wire import codec

# Default cosine-drift budget for choose_wire_format callers that give
# none: admits fp8/int8 at the harness-measured drift of every shipped
# collective at n <= 8 (worst measured: fp8 two-shot AR ~1.5e-3 at
# n=8, H=512 normal data — per-hop requantization on the RS leg is the
# dominant term; see tests/test_wire.py), with ~3x headroom for seed
# variation — and tight enough that a genuinely lossy configuration
# (longer requant chains, distribution-mismatched data) trips it.
DEFAULT_ERROR_BUDGET = 5e-3

COLLECTIVES = (
    "allgather",
    "low_latency_allgather",
    "reduce_scatter",
    "allreduce",
    "allgather_gemm",
    "gemm_reduce_scatter",
)


def cosine_drift(a, b) -> float:
    """1 - cosine similarity of the flattened f64 views (0 = parallel).
    Degenerate zero vectors count as no drift only when both are."""
    af = np.asarray(a, np.float64).ravel()
    bf = np.asarray(b, np.float64).ravel()
    na, nb = float(np.linalg.norm(af)), float(np.linalg.norm(bf))
    if na == 0.0 or nb == 0.0:
        return 0.0 if na == nb else 1.0
    return float(1.0 - np.dot(af, bf) / (na * nb))


def max_ulp_f32(a, b) -> int:
    """Worst-element ulp distance between a and b viewed as f32 (0 iff
    bit-identical as f32; sign-aware via the usual monotone int map)."""
    ai = np.asarray(a, np.float32).ravel().view(np.int32).astype(np.int64)
    bi = np.asarray(b, np.float32).ravel().view(np.int32).astype(np.int64)
    ai = np.where(ai < 0, np.int64(-(2**31)) - ai - 1, ai)
    bi = np.where(bi < 0, np.int64(-(2**31)) - bi - 1, bi)
    return int(np.max(np.abs(ai - bi))) if ai.size else 0


def _drift(q, f) -> Dict[str, float]:
    return {"cos": cosine_drift(q, f), "ulp": max_ulp_f32(q, f)}


def codec_drift(fmt, shape=(64, 512), dtype=jnp.bfloat16,
                seed=0) -> Dict[str, float]:
    """Drift of one encode/decode roundtrip vs the tensor itself."""
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal(shape), dtype)
    return _drift(codec.roundtrip(x, fmt).astype(jnp.float32),
                  x.astype(jnp.float32))


def _chunk(x, c, n):
    m = x.shape[0] // n
    return x[c * m:(c + 1) * m].astype(jnp.float32)


def simulate_ring_rs(stacked, fmt, n: int):
    """The ring-RS fold in the kernels' exact order (chunk c: loaded by
    rank c+1, then decode-add-(re)encode at c+2, ..., c; the final
    arrival is decode+add WITHOUT re-encode). stacked: (n, n*m, K) per-
    rank contributions. Returns the (n, m, K) f32 result, chunk c at
    index c. Native format degrades to the plain f32 fold."""
    f = codec.resolve(fmt)
    out = []
    for c in range(n):
        order = [(c + 1 + j) % n for j in range(n)]
        val = _chunk(stacked[order[0]], c, n)
        for j, r in enumerate(order[1:]):
            if f.kind != "native":
                val = codec.decode_rows(
                    codec.encode_rows(val, f), val.shape[-1], f,
                    jnp.float32)
            val = val + _chunk(stacked[r], c, n)
        out.append(val)
    return jnp.stack(out)


def simulate_allreduce(stacked, fmt, n: int):
    """Two-shot AR = the RS fold (returned in the INPUT dtype, exactly
    as ring_reduce_scatter hands its chunk to the AG leg) + one
    gather-leg roundtrip of each reduced chunk (the AG wire image is
    encoded once and forwarded as bytes — no per-hop requantization on
    the gather leg). Result in the input dtype, chunk-major."""
    f = codec.resolve(fmt)
    rs = simulate_ring_rs(stacked, f, n).astype(stacked.dtype)
    if f.kind == "native":
        return rs.reshape(-1, rs.shape[-1])
    return jnp.stack([
        codec.roundtrip(rs[c], f) for c in range(n)
    ]).reshape(-1, rs.shape[-1])


def collective_drift(collective: str, fmt, n: int = 8, shape=(64, 512),
                     dtype=jnp.bfloat16, seed=0) -> Dict[str, float]:
    """Drift of one (collective, format) pair vs its f32/native-wire
    fold, replaying the kernel's fold order on stacked per-rank data.
    `shape` is the per-rank (rows, K); rows must divide by n for the
    reduction family."""
    rng = np.random.default_rng(seed)
    f = codec.resolve(fmt)
    if collective in ("allgather", "low_latency_allgather"):
        x = jnp.asarray(rng.standard_normal(shape), dtype)
        return _drift(codec.roundtrip(x, f).astype(jnp.float32),
                      x.astype(jnp.float32))
    if collective == "allgather_gemm":
        k = shape[1]
        a = jnp.asarray(rng.standard_normal(shape) * 0.1, dtype)
        b = jnp.asarray(rng.standard_normal((k, 128)) * 0.1, dtype)
        q = jnp.dot(codec.roundtrip(a, f).astype(jnp.float32), b.astype(
            jnp.float32))
        r = jnp.dot(a.astype(jnp.float32), b.astype(jnp.float32))
        return _drift(q, r)
    if collective in ("reduce_scatter", "allreduce",
                      "gemm_reduce_scatter"):
        if shape[0] % n:
            raise ValueError(f"rows {shape[0]} must divide by n={n}")
        stacked = jnp.asarray(
            rng.standard_normal((n,) + tuple(shape)), dtype)
        sim = (simulate_allreduce if collective == "allreduce"
               else simulate_ring_rs)
        return _drift(sim(stacked, f, n), sim(stacked, "native", n))
    raise ValueError(f"unknown collective {collective!r} "
                     f"(one of {COLLECTIVES})")


def drift_table(n: int = 8, shape=(64, 512), dtype=jnp.bfloat16,
                formats=("fp8", "int8"), seed=0):
    """{(collective, format kind): drift dict} over the full shipped
    grid — the accuracy column beside the bench's speedup columns."""
    out = {}
    for coll in COLLECTIVES:
        for fmt in formats:
            out[(coll, codec.resolve(fmt).kind)] = collective_drift(
                coll, fmt, n=n, shape=shape, dtype=dtype, seed=seed)
    return out


def drift_monotone_in_block(fmt_kind: str = "fp8", h: int = 512,
                            blocks=(32, 128, None), rows: int = 64,
                            seed: int = 0):
    """Cosine drift per scale-block size, smallest block first. Smaller
    blocks put every scale closer to its data, so drift is monotone
    non-decreasing in block size (None = whole row, the coarsest) —
    the property tests pin."""
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((rows, h)), jnp.bfloat16)
    return [
        codec_drift(codec.WireFormat(fmt_kind, b), shape=(rows, h),
                    seed=seed)["cos"]
        for b in blocks
    ]
