"""Contextual autotuner: tunes a thunk, with cross-rank cost consensus.

TPU-native re-design of the reference's contextual autotuner
(ref: python/triton_dist/autotuner.py:33-250, docs/autotuner.md). The
reference tunes a *thunk* — a multi-kernel pipeline, not one kernel — and
all-reduces the measured costs across ranks so every rank picks the same
config (a rank-local argmin would deadlock kernels whose two sides must
agree on tile shapes). On TPU, one controller process drives the whole
mesh, so consensus inside a slice is free; across multi-host controller
processes the same consensus runs over
`multihost_utils.process_allgather`. The monkey-patched `Autotuner.run`
(:244) becomes an explicit `autotune()` call / decorator — there is no
global JIT registry to patch into; jit caching keys off the chosen static
config naturally.

Costs are medians over timed repetitions (perf_func), failures (compile
error, VMEM OOM) score +inf and are skipped, and results are cached
in-process and optionally on disk (TDT_AUTOTUNE_CACHE=path.json) keyed by
(name, user key, chip generation).
"""

from __future__ import annotations

import dataclasses
import enum
import json
import os
import re
import statistics
from typing import Any, Callable, Dict, Iterable, Optional, Sequence

import jax

from triton_dist_tpu.perf_model import detect_chip
from triton_dist_tpu.runtime.utils import perf_func


@dataclasses.dataclass
class TuneResult:
    config: Any
    cost_ms: float
    costs: Dict[str, float]  # repr(config) -> measured ms (inf = failed)


def _consensus(costs: Sequence[float]) -> Sequence[float]:
    """Agree on one cost vector across controller processes (the
    reference's cross-rank cost allreduce, autotuner.py:186-204).
    Max-reduces each config's cost over processes: the pick is the config
    whose *worst* process is cheapest (minimax — the whole mesh waits on
    the slowest rank anyway), and a failure on any process (inf) poisons
    that config for all."""
    if jax.process_count() <= 1:
        return costs
    from jax.experimental import multihost_utils
    import numpy as np

    gathered = multihost_utils.process_allgather(
        np.asarray(costs, dtype=np.float64)
    )
    return np.max(gathered, axis=0).tolist()  # inf dominates


def _agree_on_hit(hit: Optional[TuneResult]) -> Optional[TuneResult]:
    """Cache hits must not desync controller processes: a process that
    returned early from its local cache while a peer entered the measuring
    collective would deadlock the mesh. All processes exchange their local
    hit; only a unanimous identical hit is used — otherwise everyone falls
    through to measuring together."""
    if jax.process_count() <= 1:
        return hit
    import zlib

    import numpy as np
    from jax.experimental import multihost_utils

    # fixed-size numeric encoding: process_allgather cannot ship strings
    mine = (zlib.crc32(repr(hit.config).encode()) + 1) if hit else 0
    theirs = np.asarray(multihost_utils.process_allgather(
        np.asarray(mine, dtype=np.int64)
    )).ravel()
    return hit if mine and (theirs == mine).all() else None


class ContextualAutotuner:
    """Measure thunks built per config; pick the globally cheapest."""

    def __init__(self, name: str, cache_path: Optional[str] = None):
        self.name = name
        self.cache_path = cache_path or os.environ.get("TDT_AUTOTUNE_CACHE")
        self._mem: Dict[str, TuneResult] = {}

    # -- cache --------------------------------------------------------------

    def _cache_key(self, key: Any) -> str:
        return json.dumps([self.name, detect_chip().name, repr(key)])

    def _load_disk(self, ck: str, configs) -> Optional[TuneResult]:
        if not self.cache_path or not os.path.exists(self.cache_path):
            return None
        try:
            with open(self.cache_path) as f:
                disk = json.load(f)
        except (OSError, ValueError):
            return None
        if ck not in disk:
            return None
        want = disk[ck]["config"]
        for cfg in configs:
            if repr(cfg) == want:
                return TuneResult(cfg, disk[ck]["cost_ms"], {})
        return None

    def _store_disk(self, ck: str, result: TuneResult) -> None:
        if not self.cache_path:
            return
        try:
            disk = {}
            if os.path.exists(self.cache_path):
                try:
                    with open(self.cache_path) as f:
                        disk = json.load(f)
                except (OSError, ValueError):
                    disk = {}
            disk[ck] = {"config": repr(result.config),
                        "cost_ms": result.cost_ms}
            parent = os.path.dirname(self.cache_path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            tmp = self.cache_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(disk, f, indent=1)
            os.replace(tmp, self.cache_path)
        except OSError:
            pass  # a cache-write failure must not abort a finished tune

    # -- tuning -------------------------------------------------------------

    def tune(
        self,
        make_thunk: Callable[[Any], Callable[[], Any]],
        configs: Iterable[Any],
        key: Any = None,
        iters: int = 5,
        warmup: int = 2,
        reps: int = 3,
        prune: Optional[Callable[[Any], bool]] = None,
        verbose: bool = False,
    ) -> TuneResult:
        """make_thunk(cfg) -> zero-arg callable running the pipeline.

        `prune` (perf-model predicate, True = keep) cuts the measured set —
        the analytic-model pre-filter the reference folds into its config
        spaces. Measurement is the median of `reps` perf_func timings."""
        configs = list(configs)
        if not configs:
            raise ValueError("empty config space")
        ck = self._cache_key(key)
        hit = self._mem.get(ck)
        if hit is not None and not any(
            repr(c) == repr(hit.config) for c in configs
        ):
            # Same tuner name + key but a different config space (e.g. two
            # fns sharing a name): the cached winner is not a valid choice
            # here — re-tune rather than hand back a foreign config.
            hit = None
        if hit is None:
            hit = self._load_disk(ck, configs)
        hit = _agree_on_hit(hit)
        if hit is not None:
            self._mem[ck] = hit
            return hit

        live = [c for c in configs if prune is None or prune(c)]
        if not live:
            live = configs  # model pruned everything: fall back to all
        costs = []
        for cfg in live:
            try:
                thunk = make_thunk(cfg)
                ms = statistics.median(
                    perf_func(thunk, iters=iters, warmup_iters=warmup)[1]
                    for _ in range(reps)
                )
            except Exception as e:  # noqa: BLE001 — compile failure / OOM => skip
                if verbose:
                    print(f"[autotune {self.name}] {cfg!r} failed: {e}")
                ms = float("inf")
            costs.append(ms)
            if verbose:
                print(f"[autotune {self.name}] {cfg!r}: {ms:.4f} ms")

        costs = list(_consensus(costs))
        best_i = min(range(len(live)), key=lambda i: costs[i])
        if costs[best_i] == float("inf"):
            raise RuntimeError(
                f"autotune {self.name}: every config failed for key {key!r}"
            )
        result = TuneResult(
            live[best_i],
            costs[best_i],
            {repr(c): t for c, t in zip(live, costs)},
        )
        self._mem[ck] = result
        self._store_disk(ck, result)
        return result


_TUNERS: Dict[str, ContextualAutotuner] = {}


def get_tuner(name: str) -> ContextualAutotuner:
    if name not in _TUNERS:
        _TUNERS[name] = ContextualAutotuner(name)
    return _TUNERS[name]


# -- persistent tune cache (measured winners the planner launches) -----------
#
# ContextualAutotuner above caches (name, key) -> winner for ONE process
# re-running the same tuned thunk. TuneCache is the cross-process half of
# the loop: bench.py's sweep arms write the measured winner per
# (kernel, shape-bucket, dtype, world, wire, rig), and plan_forward
# consults it BEFORE the model-ranked frontier — a measured result on the
# same rig beats a modeled one; a different rig's measurement is never
# trusted (the key carries the rig, so cross-rig hits cannot happen).
# Every entry stamps the producing artifact round, so a cached config is
# traceable to the BENCH_r*.json that measured it.

TUNE_CACHE_VERSION = 1
TUNE_CACHE_BASENAME = "TUNE_CACHE.json"

# kernel family -> the config dataclass its cached reprs parse into
# (parse_config). gemm_ar rides GemmRsConfig (the fused reduction takes
# the same config object); the EP plane caches a whole EpMoeConfig and
# consumers read .n_chunks.
_CONFIG_CLASS_OF = {
    "ag_gemm": ("triton_dist_tpu.kernels.allgather_gemm", "AgGemmConfig"),
    "ag_group_gemm": ("triton_dist_tpu.kernels.allgather_gemm",
                      "AgGemmConfig"),
    "gemm_rs": ("triton_dist_tpu.kernels.gemm_reduce_scatter",
                "GemmRsConfig"),
    "gemm_ar": ("triton_dist_tpu.kernels.gemm_reduce_scatter",
                "GemmRsConfig"),
    "moe_reduce_rs": ("triton_dist_tpu.kernels.gemm_reduce_scatter",
                      "GemmRsConfig"),
    "flash_prefill": ("triton_dist_tpu.kernels.flash_prefill",
                      "FlashPrefillConfig"),
    "ep_moe": ("triton_dist_tpu.kernels.ep_a2a", "EpMoeConfig"),
}

_CONFIG_KV_RE = re.compile(r"^\s*(\w+)\s*=\s*(-?\d+\.?\d*|True|False)\s*$")


def rig_name(chip=None, world: int = 1) -> str:
    """THE rig string tune-cache entries are keyed and looked up by:
    bench.py's cpu rig stamps its artifact `rig: "cpu-world1"` through
    this same formula, so a planner lookup on the measuring machine at
    the measured world hits, and nothing else does (same-rig-only by
    construction)."""
    chip = chip or detect_chip()
    return f"{chip.name}-world{int(world)}"


def shape_bucket(*dims) -> tuple:
    """Cache-key shape bucket: the leading (token/row) dim rounds up to
    the next power of two — serving batches wander, weight geometry does
    not — and every trailing dim (hidden/heads/inter) stays exact."""
    if not dims:
        return ()
    m = max(int(dims[0]), 1)
    return ((1 << (m - 1).bit_length()),) + tuple(int(x) for x in dims[1:])


def _dtype_name(dtype) -> str:
    import jax.numpy as jnp

    return jnp.dtype(dtype).name


def parse_config(kernel: str, text: str):
    """Parse a cached config repr ("AgGemmConfig(tile_m=256, ...)") back
    into the kernel family's config dataclass — a constrained kwarg
    parser, NOT eval: only `name=<int|float|bool>` pairs are accepted and
    only fields the dataclass defines are kept. Raises ValueError on
    anything else (the cache validators want corrupt entries loud)."""
    import importlib

    if kernel not in _CONFIG_CLASS_OF:
        raise ValueError(f"no config class for kernel family {kernel!r}")
    mod_name, cls_name = _CONFIG_CLASS_OF[kernel]
    cls = getattr(importlib.import_module(mod_name), cls_name)
    text = text.strip()
    if not (text.startswith(cls_name + "(") and text.endswith(")")):
        raise ValueError(
            f"cached {kernel} config {text!r} is not a {cls_name} repr")
    body = text[len(cls_name) + 1:-1].strip()
    fields = {f.name: f.type for f in dataclasses.fields(cls)}
    kw = {}
    for part in filter(None, (p.strip() for p in body.split(","))):
        m = _CONFIG_KV_RE.match(part)
        if not m:
            raise ValueError(
                f"cached {kernel} config {text!r}: unparseable field "
                f"{part!r}")
        name, val = m.group(1), m.group(2)
        if name not in fields:
            raise ValueError(
                f"cached {kernel} config {text!r}: unknown field "
                f"{name!r}")
        kw[name] = (val == "True" if val in ("True", "False")
                    else float(val) if "." in val else int(val))
    return cls(**kw)


class TuneCache:
    """On-disk JSON table of measured tuning winners.

    {"version": 1, "entries": {key: entry}} where key is the JSON list
    [kernel, shape_bucket, dtype, world, wire, rig] and entry carries
    {"config": repr, "cost_ms", "default_ms", "round"}. Loading a
    corrupt or wrong-version file raises ValueError LOUDLY — a planner
    silently launching default tiles because the cache rotted is exactly
    the un-observable regression this file exists to prevent
    (scripts/check_tune_cache.py gates the committed copy in CI)."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self.entries: Dict[str, dict] = {}
        if path and os.path.exists(path):
            self.load()

    @staticmethod
    def key(kernel: str, bucket, dtype, world: int, wire: Optional[str],
            rig: str) -> str:
        return json.dumps([kernel, list(bucket), _dtype_name(dtype),
                           int(world), wire or "native", rig])

    def load(self) -> None:
        with open(self.path) as f:
            try:
                disk = json.load(f)
            except ValueError as e:
                raise ValueError(
                    f"tune cache {self.path} is corrupt JSON: {e}") from e
        if not isinstance(disk, dict) \
                or disk.get("version") != TUNE_CACHE_VERSION:
            raise ValueError(
                f"tune cache {self.path} has version "
                f"{disk.get('version') if isinstance(disk, dict) else '?'}"
                f", expected {TUNE_CACHE_VERSION}")
        entries = disk.get("entries")
        if not isinstance(entries, dict):
            raise ValueError(f"tune cache {self.path} has no entries table")
        for k, v in entries.items():
            try:
                parts = json.loads(k)
            except ValueError:
                parts = None
            if not (isinstance(parts, list) and len(parts) == 6):
                raise ValueError(
                    f"tune cache {self.path}: malformed key {k!r}")
            if not (isinstance(v, dict) and isinstance(v.get("config"), str)
                    and isinstance(v.get("cost_ms"), (int, float))
                    and isinstance(v.get("round"), int)):
                raise ValueError(
                    f"tune cache {self.path}: malformed entry for {k!r}")
        self.entries = entries
        _bump_tune_generation()

    def save(self) -> None:
        if not self.path:
            raise ValueError("TuneCache has no path to save to")
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"version": TUNE_CACHE_VERSION,
                       "entries": self.entries}, f, indent=1,
                      sort_keys=True)
            f.write("\n")
        os.replace(tmp, self.path)

    def put(self, kernel: str, bucket, dtype, world: int,
            wire: Optional[str], rig: str, config, cost_ms: float,
            default_ms: Optional[float] = None,
            round_: int = 0) -> None:
        self.entries[self.key(kernel, bucket, dtype, world, wire, rig)] = {
            "config": config if isinstance(config, str) else repr(config),
            "cost_ms": round(float(cost_ms), 6),
            "default_ms": (None if default_ms is None
                           else round(float(default_ms), 6)),
            "round": int(round_),
        }
        _bump_tune_generation()

    def lookup(self, kernel: str, bucket, dtype, world: int,
               wire: Optional[str], rig: str) -> Optional[dict]:
        return self.entries.get(
            self.key(kernel, bucket, dtype, world, wire, rig))


_ACTIVE_TUNE_CACHE: Optional[TuneCache] = None
_TUNE_GENERATION = 0


def _bump_tune_generation() -> None:
    global _TUNE_GENERATION
    _TUNE_GENERATION += 1


def tune_cache_generation() -> int:
    """Monotone counter bumped on every cache load/put/swap — plan
    memoization keys include it, so a plan built before the cache was
    populated never masks a later measured winner."""
    return _TUNE_GENERATION


def default_tune_cache_path() -> Optional[str]:
    """TDT_TUNE_CACHE env wins (set-but-EMPTY pins the empty in-memory
    cache — tests/conftest.py uses this so tier-1 behavior never
    depends on what a bench round committed); else the repo-root
    TUNE_CACHE.json when present; else None (empty in-memory cache)."""
    p = os.environ.get("TDT_TUNE_CACHE")
    if p is not None:
        return p or None
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = os.path.join(root, TUNE_CACHE_BASENAME)
    return p if os.path.exists(p) else None


def active_tune_cache() -> TuneCache:
    global _ACTIVE_TUNE_CACHE
    if _ACTIVE_TUNE_CACHE is None:
        _ACTIVE_TUNE_CACHE = TuneCache(default_tune_cache_path())
    return _ACTIVE_TUNE_CACHE


def set_tune_cache(cache: Optional[TuneCache]) -> Optional[TuneCache]:
    """Install `cache` as the process-wide planner cache (tests; bench
    arms). Returns the previous cache so callers can restore it."""
    global _ACTIVE_TUNE_CACHE
    prev = _ACTIVE_TUNE_CACHE
    _ACTIVE_TUNE_CACHE = cache
    _bump_tune_generation()
    return prev


def ag_gemm_config_space():
    """Candidate AgGemmConfig grid for the contextual tuner (the reference
    folds these into its context factories; ours ship a measured default
    and let `autotune` override per shape). The wide-N rows (tn >= 1280,
    up to the FULL 6400-column gate|up width) are where the round-5
    sweep found the winners — per-grid-step overhead dominates at the
    benched shapes, so fewer/wider tiles beat traffic-optimal ones; tk
    spanning to 5120 covers the nk==1 direct-store regime (no f32
    accumulator round-trip, see _ag_gemm_kernel). tm=2048 (mt=1) rows
    cut the dominant B-re-read term (one pass per row-tile sweep) and
    only became measurable once the prune budget moved to the chip VMEM
    ceiling (perf_model.kernel_vmem_ceiling) — the 15 MiB fallback
    budget was pruning the frontier exactly where the roofline puts the
    winners (the world=1 tax push)."""
    from triton_dist_tpu.kernels.allgather_gemm import AgGemmConfig

    return [
        AgGemmConfig(tile_m=tm, tile_n=tn, tile_k=tk)
        for tm in (256, 512, 1024, 2048)
        for tn in (256, 640, 1024, 1280, 3200, 6400)
        for tk in (512, 1024, 2048, 5120)
    ]


def gemm_rs_config_space():
    from triton_dist_tpu.kernels.gemm_reduce_scatter import GemmRsConfig

    return [GemmRsConfig(tile_m=tm) for tm in (128, 256, 512, 1024)]


def gemm_rs_local_config_space():
    """Candidate local-regime (world=1 forced / blocked-matmul) tiles for
    gemm_rs — the benched Qwen3-32B down-proj path. tile_k_local=3200
    hits the nk==1 regime at the bench K (direct store, no accumulator
    read-modify-write); tm=2048 / tn=5120 rows reach the few-grid-step
    corner (e.g. (1024, 2560, 3200) is a 4-step direct-store sweep at
    the bench shape) that the old 14 MiB prune budget excluded — see
    ag_gemm_config_space on the kernel_vmem_ceiling change."""
    from triton_dist_tpu.kernels.gemm_reduce_scatter import GemmRsConfig

    return [
        GemmRsConfig(tile_m_local=tm, tile_n_local=tn, tile_k_local=tk)
        for tm in (256, 512, 1024, 2048)
        for tn in (640, 1280, 2560, 5120)
        for tk in (640, 1024, 1600, 3200)
    ]


# -- model-pruned candidate sets (perf_model roofline pre-filter) -----------


def _blocked_vmem_need(cfg, m, n, k, attr_names, dtype, out_dtype):
    """VMEM a blocked-GEMM config needs at (m, n, k) after tile fitting —
    THE formula `_prune_blocked_configs` prunes with and the launch-time
    re-validators (ag_gemm_config_fits / gemm_rs_local_config_fits) gate
    with, so a cached config is rejected by exactly the accounting that
    admitted it."""
    import jax.numpy as jnp

    from triton_dist_tpu.lang.core import fit_tile

    isz = jnp.dtype(dtype or jnp.bfloat16).itemsize
    osz = jnp.dtype(out_dtype or dtype or jnp.bfloat16).itemsize
    am, an, ak = attr_names
    tm = fit_tile(getattr(cfg, am), m)
    tn = fit_tile(getattr(cfg, an), n)
    tk = fit_tile(getattr(cfg, ak), k)
    need = 2 * (tm * tk + tk * tn) * isz + 2 * tm * tn * osz
    if -(-k // tk) > 1:
        need += tm * tn * 4  # f32 accumulator (skipped at nk==1)
    return need


def ag_gemm_config_fits(cfg, m, k, n_loc, dtype=None, out_dtype=None,
                        chip=None) -> bool:
    """Launch-time fit gate for a tuned/cached AgGemmConfig: its fitted
    tiles at this shape stay under the forced-kernel VMEM ceiling (the
    same budget the pruner admitted it against)."""
    from triton_dist_tpu.kernels.allgather_gemm import AgGemmConfig
    from triton_dist_tpu.perf_model import kernel_vmem_ceiling

    budget = max(AgGemmConfig().vmem_budget, kernel_vmem_ceiling(chip))
    return _blocked_vmem_need(cfg, m, n_loc, k,
                              ("tile_m", "tile_n", "tile_k"),
                              dtype, out_dtype) <= budget


def gemm_rs_local_config_fits(cfg, m, k_loc, n_full, dtype=None,
                              out_dtype=None, chip=None) -> bool:
    """Launch-time fit gate for a tuned/cached GemmRsConfig's local
    (blocked-matmul) tiles — the regime the world=1 sweeps measure."""
    from triton_dist_tpu.kernels.gemm_reduce_scatter import GemmRsConfig
    from triton_dist_tpu.perf_model import kernel_vmem_ceiling

    budget = max(GemmRsConfig().vmem_budget, kernel_vmem_ceiling(chip))
    return _blocked_vmem_need(
        cfg, m, n_full, k_loc,
        ("tile_m_local", "tile_n_local", "tile_k_local"),
        dtype, out_dtype) <= budget


def flash_prefill_config_fits(cfg, s_q, t, hq, hkv, d, dtype=None,
                              batch=1, chip=None) -> bool:
    """Launch-time fit gate for a tuned/cached FlashPrefillConfig: the
    fitted KV page (the kernel's own fit_block divisor rule) stays under
    the VMEM ceiling."""
    import jax.numpy as jnp

    from triton_dist_tpu.kernels.flash_prefill import (
        fit_block,
        flash_prefill_vmem_bytes,
    )
    from triton_dist_tpu.perf_model import kernel_vmem_ceiling

    block = cfg if isinstance(cfg, int) else cfg.block
    need = flash_prefill_vmem_bytes(s_q, hq, hkv, d, fit_block(t, block),
                                    dtype or jnp.bfloat16, batch=batch)
    return need <= kernel_vmem_ceiling(chip)


def _prune_blocked_configs(m, n, k, configs, attr_names, default_budget,
                           dtype, out_dtype, vmem_budget, slack, chip,
                           top_n):
    """Shared body of the blocked-GEMM prune helpers: keep the
    VMEM-fitting configs on the analytic roofline frontier (perf_model.
    estimate_blocked_gemm_ms within `slack` of the modeled optimum),
    dedupe configs that degrade to identical fitted tiles (they measure
    the same kernel), and optionally cap at the top_n model-ranked.
    Mirrors the kernels' tile fitting and VMEM accounting — both fused
    kernels double-buffer each block operand, keep a 2-deep output
    window, and carry an f32 accumulator only when the K sweep is tiled
    (nk > 1; nk == 1 is the direct-store regime) — so a config is never
    measured in a degraded form the model did not score.

    The default budget is the CHIP's forced-kernel ceiling
    (perf_model.kernel_vmem_ceiling), not the config dataclass's
    conservative auto-fallback figure: forced/tuned candidates get
    vmem_limit_bytes = what their tiling implies (both kernels grant
    it), so pruning at the 14-15 MiB fallback budget was cutting the
    frontier exactly where the roofline says the winners live — the
    wide-tm few-step sweeps and the nk==1 direct-store tiles need
    30-63 MiB of a v5e's 128."""
    import jax.numpy as jnp

    from triton_dist_tpu.lang.core import fit_tile
    from triton_dist_tpu.perf_model import (
        estimate_blocked_gemm_ms,
        roofline_frontier,
    )

    from triton_dist_tpu.perf_model import kernel_vmem_ceiling

    dtype = dtype or jnp.bfloat16
    budget = vmem_budget or max(default_budget,
                                kernel_vmem_ceiling(chip))
    am, an, ak = attr_names

    def fitted(cfg):
        return (fit_tile(getattr(cfg, am), m),
                fit_tile(getattr(cfg, an), n),
                fit_tile(getattr(cfg, ak), k))

    def vmem_need(cfg):
        return _blocked_vmem_need(cfg, m, n, k, attr_names, dtype,
                                  out_dtype)

    live = [c for c in configs if vmem_need(c) <= budget]
    if not live:
        # nothing fits: hand back the single least-VMEM candidate rather
        # than the whole rejected space (measuring known-overflow tilings
        # burns a Mosaic compile failure each on hardware)
        return [min(configs, key=vmem_need)]

    def model_ms(cfg):
        tm, tn, tk = fitted(cfg)
        return estimate_blocked_gemm_ms(m, n, k, tm, tn, tk, dtype=dtype,
                                        out_dtype=out_dtype, chip=chip)

    seen, uniq = set(), []
    for c in roofline_frontier(live, model_ms, slack):
        ft = fitted(c)
        if ft not in seen:
            seen.add(ft)
            uniq.append(c)
    if top_n is not None and len(uniq) > top_n:
        uniq = sorted(uniq, key=model_ms)[:top_n]
    return uniq


def prune_ag_gemm_configs(m, k, n_loc, configs=None, dtype=None,
                          out_dtype=None, vmem_budget=None,
                          slack=1.25, chip=None, top_n=None):
    """Model-pruned ag_gemm candidates at one shape (see
    _prune_blocked_configs)."""
    from triton_dist_tpu.kernels.allgather_gemm import AgGemmConfig

    configs = list(configs) if configs is not None \
        else ag_gemm_config_space()
    return _prune_blocked_configs(
        m, n_loc, k, configs, ("tile_m", "tile_n", "tile_k"),
        AgGemmConfig().vmem_budget, dtype, out_dtype, vmem_budget,
        slack, chip, top_n)


def prune_gemm_rs_local_configs(m, k_loc, n_full, configs=None,
                                dtype=None, out_dtype=None,
                                vmem_budget=None, slack=1.25,
                                chip=None, top_n=None):
    """Model-pruned local-regime (world=1 blocked-matmul) gemm_rs
    candidates (see _prune_blocked_configs; the local blocked matmul
    shares ag_gemm's (i, j, kk) grid and traffic shape)."""
    from triton_dist_tpu.kernels.gemm_reduce_scatter import GemmRsConfig

    configs = list(configs) if configs is not None \
        else gemm_rs_local_config_space()
    return _prune_blocked_configs(
        m, n_full, k_loc, configs,
        ("tile_m_local", "tile_n_local", "tile_k_local"),
        GemmRsConfig().vmem_budget, dtype, out_dtype, vmem_budget,
        slack, chip, top_n)


def flash_prefill_config_space():
    """Candidate FlashPrefillConfig grid for the SP/local flash-prefill
    kernels (kernels/flash_prefill.py): KV page heights spanning the
    latency (small pages start folding sooner after a segment lands) vs
    bandwidth (tall pages amortize the per-copy overhead) trade. Every
    candidate is re-fitted to the actual KV length by the kernel's
    divisor rule (_kv_block-style), so the space stays valid at any
    shape."""
    from triton_dist_tpu.kernels.flash_prefill import FlashPrefillConfig

    return [FlashPrefillConfig(block=blk) for blk in (128, 256, 512, 1024)]


def prune_flash_prefill_configs(s_q, t, hq, hkv, d, configs=None,
                                dtype=None, batch=1, slack=1.25,
                                chip=None, top_n=None):
    """Model-pruned flash-prefill candidates at one shape: keep the
    VMEM-fitting block heights (double-buffered (block, 2*Hkv*D) pages
    plus the per-head f32 states) on the estimate_flash_prefill_ms
    frontier, dedupe configs that degrade to the same fitted block, cap
    at top_n — the frontier+dedupe+top_n discipline of
    prune_ag_gemm_configs."""
    import jax.numpy as jnp

    from triton_dist_tpu.kernels.flash_prefill import FlashPrefillConfig
    from triton_dist_tpu.perf_model import (
        estimate_flash_prefill_ms,
        kernel_vmem_ceiling,
        roofline_frontier,
    )

    dtype = dtype or jnp.bfloat16
    configs = list(configs) if configs is not None \
        else flash_prefill_config_space()

    from triton_dist_tpu.kernels.flash_prefill import (
        fit_block,
        flash_prefill_vmem_bytes,
    )

    def fitted(cfg):
        # THE kernel's divisor rule (sp_flash_prefill, the ref replay
        # and the bench arm all fit the same way), so the pruner never
        # models a geometry the kernel would not run
        return fit_block(t, cfg.block)

    def vmem_need(cfg):
        return flash_prefill_vmem_bytes(s_q, hq, hkv, d, fitted(cfg),
                                        dtype)

    budget = kernel_vmem_ceiling(chip)
    live = [c for c in configs if vmem_need(c) <= budget]
    if not live:
        return [min(configs, key=vmem_need)]

    def model_ms(cfg):
        # block height enters through the KV page's DMA burst length
        # (perf_model.hbm_stream_efficiency): taller pages amortize the
        # per-burst gap, smaller pages start folding sooner after a
        # segment lands — the model ranks the bandwidth side, the
        # frontier slack keeps the latency side measurable
        return estimate_flash_prefill_ms(s_q, t, hq, hkv, d, batch,
                                         dtype, chip, block=fitted(cfg))

    seen, uniq = set(), []
    for c in roofline_frontier(live, model_ms, slack):
        ft = fitted(c)
        if ft not in seen:
            seen.add(ft)
            uniq.append(c)
    if top_n is not None and len(uniq) > top_n:
        uniq = sorted(uniq, key=model_ms)[:top_n]
    return uniq


def wire_format_space():
    """Candidate wire formats for a measured quantized-collective sweep
    (bench.py's allreduce-wire arm, a caller's autotune over the
    wire_format= knob): the pass-through native wire plus the shipped
    quantized codecs at per-row and 128-element scale blocks."""
    from triton_dist_tpu.wire import codec

    return [
        codec.NATIVE,
        codec.FP8,
        codec.INT8,
        codec.WireFormat("fp8", 128),
        codec.WireFormat("int8", 128),
    ]


def prune_wire_formats(nbytes, n, dtype=None, collective="allreduce",
                       error_budget=None, configs=None, chip=None,
                       row_width=512, top_n=None):
    """Model-pruned wire-format candidates: drop the formats whose
    modeled drift (perf_model.estimate_wire_drift) exceeds the error
    budget — a QUALITY gate the time model must not fold away, exactly
    like prune_ep_moe_configs keeps capacity-factor levels apart — then
    rank survivors by the bytes-by-precision roofline
    (perf_model.estimate_collective_wire_ms) and optionally cap at
    top_n. Native always survives (the fallback a tuned pick degrades
    to), so the result is never empty."""
    import jax.numpy as jnp

    from triton_dist_tpu.perf_model import (
        estimate_collective_wire_ms,
        estimate_wire_drift,
    )
    from triton_dist_tpu.wire import codec
    from triton_dist_tpu.wire.numerics import DEFAULT_ERROR_BUDGET

    dtype = dtype or jnp.bfloat16
    budget = DEFAULT_ERROR_BUDGET if error_budget is None else error_budget
    configs = list(configs) if configs is not None else wire_format_space()
    live = [codec.resolve(f) for f in configs]
    live = [f for f in live
            if codec.is_native(f)
            or estimate_wire_drift(f, n, collective) <= budget]
    if not any(codec.is_native(f) for f in live):
        live.insert(0, codec.NATIVE)

    def model_ms(f):
        return estimate_collective_wire_ms(
            collective, nbytes, n, dtype, f, chip, row_width)

    live = sorted(live, key=model_ms)
    if top_n is not None and len(live) > top_n:
        keep = live[:top_n]
        if not any(codec.is_native(f) for f in keep):
            keep[-1] = codec.NATIVE
        live = keep
    return live


def spec_k_space():
    """Candidate draft widths for a measured spec-decode sweep
    (bench.py's serve-spec arm, a caller's autotune over the
    SpecConfig(k=) knob): 0 (off) through the widths the verify row
    can carry without dominating the step."""
    return [0, 1, 2, 4, 6, 8]


def prune_spec_ks(num_layers, hidden, inter_loc, hq_loc, hkv_loc,
                  head_dim, vocab_loc, accept_rate, configs=None,
                  slots=4, kv_tokens=0, dtype=None, chip=None,
                  attn_impl="flash", top_n=None):
    """Model-pruned draft-width candidates at one shape + acceptance
    rate: rank by perf_model.estimate_spec_step_ms (per-EMITTED-token
    cost), dedupe, optionally cap at top_n. k=0 always survives (the
    off switch a tuned pick degrades to — the prune_wire_formats
    native-survives discipline), so the result is never empty."""
    import jax.numpy as jnp

    from triton_dist_tpu.perf_model import estimate_spec_step_ms

    dtype = dtype or jnp.bfloat16
    ks = sorted({int(k) for k in
                 (configs if configs is not None else spec_k_space())
                 if int(k) >= 0})
    if 0 not in ks:
        ks.insert(0, 0)

    def model_ms(k):
        return estimate_spec_step_ms(
            num_layers, hidden, inter_loc, hq_loc, hkv_loc, head_dim,
            vocab_loc, k=k, accept_rate=accept_rate, slots=slots,
            kv_tokens=kv_tokens, dtype=dtype, chip=chip,
            attn_impl=attn_impl)

    live = sorted(ks, key=model_ms)
    if top_n is not None and len(live) > top_n:
        keep = live[:top_n]
        if 0 not in keep:
            keep[-1] = 0
        live = keep
    return live


def ep_moe_config_space():
    """Candidate EpMoeConfig grid for the chunk-pipelined EP MoE
    (kernels/ep_a2a.ep_moe_pipeline): chunk counts spanning no-pipelining
    to fine-grained overlap, at the lossless capacity plus the two
    standard GShard capacity trades. capacity_factor < 1.0 changes WHAT
    is computed (tokens beyond capacity drop), not just how fast — see
    prune_ep_moe_configs for how the pruner keeps the trade visible."""
    from triton_dist_tpu.kernels.ep_a2a import EpMoeConfig

    return [
        EpMoeConfig(n_chunks=q, capacity_factor=cf)
        for q in (1, 2, 4, 8, 16)
        for cf in (1.0, 0.75, 0.5)
    ]


def prune_ep_moe_configs(m, hidden, inter, e_loc, n, top_k, configs=None,
                         dtype=None, payload_dtype=None, slack=1.25,
                         chip=None, top_n=None):
    """Model-pruned chunk-pipeline candidates at one shape: within EACH
    capacity_factor level (a quality trade the model cannot score — it
    predicts time, not accuracy), keep the chunk counts on the
    perf_model.estimate_ep_moe_ms roofline frontier, dedupe configs that
    degrade to the same fitted chunk count, and optionally cap each
    level at the top_n model-ranked. Mirrors prune_ag_gemm_configs'
    frontier+dedupe+top_n discipline."""
    import jax.numpy as jnp

    from triton_dist_tpu.kernels.ep_a2a import EpMoeConfig, fit_chunks
    from triton_dist_tpu.perf_model import (
        estimate_ep_moe_ms,
        roofline_frontier,
    )

    dtype = dtype or jnp.bfloat16
    configs = list(configs) if configs is not None else ep_moe_config_space()

    def fitted(cfg):
        # the config's own fitting rules: a tuned config must describe
        # the capacity and chunking that actually execute
        cap = cfg.fit_capacity(m, top_k)
        return cap, fit_chunks(cfg.n_chunks, cap)

    def model_ms(cfg):
        cap, q = fitted(cfg)
        return estimate_ep_moe_ms(
            m, hidden, inter, e_loc, n, top_k, capacity=cap, n_chunks=q,
            dtype=dtype, payload_dtype=payload_dtype, chip=chip,
            overlap=True,
        )

    out = []
    for cf in sorted({c.capacity_factor for c in configs}, reverse=True):
        level = [c for c in configs if c.capacity_factor == cf]
        seen = set()
        uniq = []
        for c in roofline_frontier(level, model_ms, slack):
            ft = fitted(c)
            if ft not in seen:
                seen.add(ft)
                uniq.append(c)
        if top_n is not None and len(uniq) > top_n:
            uniq = sorted(uniq, key=model_ms)[:top_n]
        out.extend(uniq)
    if not out:
        out = [EpMoeConfig()]
    return out


def _default_key_part(argname, a):
    """Stable cache-key fragment for one argument of an autotuned call.

    Arrays key by shape+dtype; scalars/types/enums by value; anything
    else by type name only — NOT default repr, which embeds the object
    address and would turn every call into a cache miss. When kernel
    behavior depends on such an object's *identity*, pass key_fn."""
    if isinstance(a, type):  # incl. np/jnp scalar types (callable, and
        # np ones carry a class-level `shape` descriptor: check first)
        return (argname, f"{a.__module__}.{a.__qualname__}")
    if hasattr(a, "shape") and hasattr(a, "dtype"):
        return (argname, tuple(a.shape), str(a.dtype))
    if a is None or isinstance(a, (bool, int, float, str, bytes)):
        return (argname, repr(a))
    if isinstance(a, enum.Enum):
        return (argname, f"{type(a).__qualname__}.{a.name}")
    if isinstance(a, (tuple, list)):
        return (argname, tuple(_default_key_part("", x) for x in a))
    return (argname, type(a).__qualname__)


def autotune(
    name: str,
    configs: Sequence[Any],
    key_fn: Optional[Callable[..., Any]] = None,
    **tune_kw,
):
    """Decorator: tune `fn(*args, config=cfg)` over `configs` on first call
    per key, then always run the winner (the reference's patched
    Autotuner.run path, autotuner.py:210-250).

    The wrapped fn must accept a `config=` kwarg and be safe to execute
    repeatedly on the same inputs (tuning runs it)."""

    def deco(fn):
        tuner = get_tuner(name)

        def wrapper(*args, **kwargs):
            key = (
                key_fn(*args, **kwargs)
                if key_fn is not None
                else tuple(
                    _default_key_part(argname, a)
                    for argname, a in list(enumerate(args))
                    + sorted(kwargs.items())
                )
            )
            result = tuner.tune(
                lambda cfg: (lambda: fn(*args, config=cfg, **kwargs)),
                configs,
                key=key,
                **tune_kw,
            )
            return fn(*args, config=result.config, **kwargs)

        wrapper.tuner = tuner
        wrapper.__wrapped__ = fn
        return wrapper

    return deco
