"""Contextual autotuner: tunes a thunk, with cross-rank cost consensus.

TPU-native re-design of the reference's contextual autotuner
(ref: python/triton_dist/autotuner.py:33-250, docs/autotuner.md). The
reference tunes a *thunk* — a multi-kernel pipeline, not one kernel — and
all-reduces the measured costs across ranks so every rank picks the same
config (a rank-local argmin would deadlock kernels whose two sides must
agree on tile shapes). On TPU, one controller process drives the whole
mesh, so consensus inside a slice is free; across multi-host controller
processes the same consensus runs over
`multihost_utils.process_allgather`. The monkey-patched `Autotuner.run`
(:244) becomes an explicit `autotune()` call / decorator — there is no
global JIT registry to patch into; jit caching keys off the chosen static
config naturally.

Costs are medians over timed repetitions (perf_func), failures (compile
error, VMEM OOM) score +inf and are skipped, and results are cached
in-process and optionally on disk (TDT_AUTOTUNE_CACHE=path.json) keyed by
(name, user key, chip generation).
"""

from __future__ import annotations

import dataclasses
import enum
import json
import os
import statistics
from typing import Any, Callable, Dict, Iterable, Optional, Sequence

import jax

from triton_dist_tpu.perf_model import detect_chip
from triton_dist_tpu.runtime.utils import perf_func


@dataclasses.dataclass
class TuneResult:
    config: Any
    cost_ms: float
    costs: Dict[str, float]  # repr(config) -> measured ms (inf = failed)


def _consensus(costs: Sequence[float]) -> Sequence[float]:
    """Agree on one cost vector across controller processes (the
    reference's cross-rank cost allreduce, autotuner.py:186-204).
    Max-reduces each config's cost over processes: the pick is the config
    whose *worst* process is cheapest (minimax — the whole mesh waits on
    the slowest rank anyway), and a failure on any process (inf) poisons
    that config for all."""
    if jax.process_count() <= 1:
        return costs
    from jax.experimental import multihost_utils
    import numpy as np

    gathered = multihost_utils.process_allgather(
        np.asarray(costs, dtype=np.float64)
    )
    return np.max(gathered, axis=0).tolist()  # inf dominates


def _agree_on_hit(hit: Optional[TuneResult]) -> Optional[TuneResult]:
    """Cache hits must not desync controller processes: a process that
    returned early from its local cache while a peer entered the measuring
    collective would deadlock the mesh. All processes exchange their local
    hit; only a unanimous identical hit is used — otherwise everyone falls
    through to measuring together."""
    if jax.process_count() <= 1:
        return hit
    import zlib

    import numpy as np
    from jax.experimental import multihost_utils

    # fixed-size numeric encoding: process_allgather cannot ship strings
    mine = (zlib.crc32(repr(hit.config).encode()) + 1) if hit else 0
    theirs = np.asarray(multihost_utils.process_allgather(
        np.asarray(mine, dtype=np.int64)
    )).ravel()
    return hit if mine and (theirs == mine).all() else None


class ContextualAutotuner:
    """Measure thunks built per config; pick the globally cheapest."""

    def __init__(self, name: str, cache_path: Optional[str] = None):
        self.name = name
        self.cache_path = cache_path or os.environ.get("TDT_AUTOTUNE_CACHE")
        self._mem: Dict[str, TuneResult] = {}

    # -- cache --------------------------------------------------------------

    def _cache_key(self, key: Any) -> str:
        return json.dumps([self.name, detect_chip().name, repr(key)])

    def _load_disk(self, ck: str, configs) -> Optional[TuneResult]:
        if not self.cache_path or not os.path.exists(self.cache_path):
            return None
        try:
            with open(self.cache_path) as f:
                disk = json.load(f)
        except (OSError, ValueError):
            return None
        if ck not in disk:
            return None
        want = disk[ck]["config"]
        for cfg in configs:
            if repr(cfg) == want:
                return TuneResult(cfg, disk[ck]["cost_ms"], {})
        return None

    def _store_disk(self, ck: str, result: TuneResult) -> None:
        if not self.cache_path:
            return
        try:
            disk = {}
            if os.path.exists(self.cache_path):
                try:
                    with open(self.cache_path) as f:
                        disk = json.load(f)
                except (OSError, ValueError):
                    disk = {}
            disk[ck] = {"config": repr(result.config),
                        "cost_ms": result.cost_ms}
            parent = os.path.dirname(self.cache_path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            tmp = self.cache_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(disk, f, indent=1)
            os.replace(tmp, self.cache_path)
        except OSError:
            pass  # a cache-write failure must not abort a finished tune

    # -- tuning -------------------------------------------------------------

    def tune(
        self,
        make_thunk: Callable[[Any], Callable[[], Any]],
        configs: Iterable[Any],
        key: Any = None,
        iters: int = 5,
        warmup: int = 2,
        reps: int = 3,
        prune: Optional[Callable[[Any], bool]] = None,
        verbose: bool = False,
    ) -> TuneResult:
        """make_thunk(cfg) -> zero-arg callable running the pipeline.

        `prune` (perf-model predicate, True = keep) cuts the measured set —
        the analytic-model pre-filter the reference folds into its config
        spaces. Measurement is the median of `reps` perf_func timings."""
        configs = list(configs)
        if not configs:
            raise ValueError("empty config space")
        ck = self._cache_key(key)
        hit = self._mem.get(ck)
        if hit is not None and not any(
            repr(c) == repr(hit.config) for c in configs
        ):
            # Same tuner name + key but a different config space (e.g. two
            # fns sharing a name): the cached winner is not a valid choice
            # here — re-tune rather than hand back a foreign config.
            hit = None
        if hit is None:
            hit = self._load_disk(ck, configs)
        hit = _agree_on_hit(hit)
        if hit is not None:
            self._mem[ck] = hit
            return hit

        live = [c for c in configs if prune is None or prune(c)]
        if not live:
            live = configs  # model pruned everything: fall back to all
        costs = []
        for cfg in live:
            try:
                thunk = make_thunk(cfg)
                ms = statistics.median(
                    perf_func(thunk, iters=iters, warmup_iters=warmup)[1]
                    for _ in range(reps)
                )
            except Exception as e:  # compile failure / OOM => skip
                if verbose:
                    print(f"[autotune {self.name}] {cfg!r} failed: {e}")
                ms = float("inf")
            costs.append(ms)
            if verbose:
                print(f"[autotune {self.name}] {cfg!r}: {ms:.4f} ms")

        costs = list(_consensus(costs))
        best_i = min(range(len(live)), key=lambda i: costs[i])
        if costs[best_i] == float("inf"):
            raise RuntimeError(
                f"autotune {self.name}: every config failed for key {key!r}"
            )
        result = TuneResult(
            live[best_i],
            costs[best_i],
            {repr(c): t for c, t in zip(live, costs)},
        )
        self._mem[ck] = result
        self._store_disk(ck, result)
        return result


_TUNERS: Dict[str, ContextualAutotuner] = {}


def get_tuner(name: str) -> ContextualAutotuner:
    if name not in _TUNERS:
        _TUNERS[name] = ContextualAutotuner(name)
    return _TUNERS[name]


def ag_gemm_config_space():
    """Candidate AgGemmConfig grid for the contextual tuner (the reference
    folds these into its context factories; ours ship a measured default
    and let `autotune` override per shape)."""
    from triton_dist_tpu.kernels.allgather_gemm import AgGemmConfig

    return [
        AgGemmConfig(tile_m=tm, tile_n=tn, tile_k=tk)
        for tm in (512, 1024, 2048)
        for tn in (256, 640, 1024)
        for tk in (512, 1024, 2048)
    ]


def gemm_rs_config_space():
    from triton_dist_tpu.kernels.gemm_reduce_scatter import GemmRsConfig

    return [GemmRsConfig(tile_m=tm) for tm in (128, 256, 512, 1024)]


def _default_key_part(argname, a):
    """Stable cache-key fragment for one argument of an autotuned call.

    Arrays key by shape+dtype; scalars/types/enums by value; anything
    else by type name only — NOT default repr, which embeds the object
    address and would turn every call into a cache miss. When kernel
    behavior depends on such an object's *identity*, pass key_fn."""
    if isinstance(a, type):  # incl. np/jnp scalar types (callable, and
        # np ones carry a class-level `shape` descriptor: check first)
        return (argname, f"{a.__module__}.{a.__qualname__}")
    if hasattr(a, "shape") and hasattr(a, "dtype"):
        return (argname, tuple(a.shape), str(a.dtype))
    if a is None or isinstance(a, (bool, int, float, str, bytes)):
        return (argname, repr(a))
    if isinstance(a, enum.Enum):
        return (argname, f"{type(a).__qualname__}.{a.name}")
    if isinstance(a, (tuple, list)):
        return (argname, tuple(_default_key_part("", x) for x in a))
    return (argname, type(a).__qualname__)


def autotune(
    name: str,
    configs: Sequence[Any],
    key_fn: Optional[Callable[..., Any]] = None,
    **tune_kw,
):
    """Decorator: tune `fn(*args, config=cfg)` over `configs` on first call
    per key, then always run the winner (the reference's patched
    Autotuner.run path, autotuner.py:210-250).

    The wrapped fn must accept a `config=` kwarg and be safe to execute
    repeatedly on the same inputs (tuning runs it)."""

    def deco(fn):
        tuner = get_tuner(name)

        def wrapper(*args, **kwargs):
            key = (
                key_fn(*args, **kwargs)
                if key_fn is not None
                else tuple(
                    _default_key_part(argname, a)
                    for argname, a in list(enumerate(args))
                    + sorted(kwargs.items())
                )
            )
            result = tuner.tune(
                lambda cfg: (lambda: fn(*args, config=cfg, **kwargs)),
                configs,
                key=key,
                **tune_kw,
            )
            return fn(*args, config=result.config, **kwargs)

        wrapper.tuner = tuner
        wrapper.__wrapped__ = fn
        return wrapper

    return deco
