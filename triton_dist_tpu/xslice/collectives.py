"""2-level ICI + DCN collectives (hierarchical allgather /
reduce-scatter / allreduce).

The reference's inter-node family (2D-ring inter-node AG, inter-node
RS — SURVEY VERDICT missing #2) crosses TWO transports with a ~30x
bandwidth cliff between them. The TPU-native analog keeps each
transport on its natural plane:

  ICI leg    the existing Pallas ring protocols (kernels/allgather.py,
             kernels/reduce_scatter.py), run PER SLICE over the "tp"
             axis of a ("dcn", "tp") mesh — slice-local rings never
             cross the cliff;
  DCN leg    an XLA collective between the slices ("dcn" axis): every
             rank exchanges with its RAIL (the same local rank in
             every slice — rails are disjoint, so no leader funnel
             serializes the hop), and `wire_format=` applies HERE,
             where the EQuARX economics (arXiv 2506.17615) pay most —
             the image is packed once at the send edge and decoded at
             the consume edge in fixed slice order, so chunked and
             unchunked runs reduce in the same order (bitwise).

Overlap: `chunks > 1` splits the payload along its last axis and
issues the ICI leg of chunk i+1 BEFORE the DCN leg of chunk i (T3's
compute-triggered communication idiom, arXiv 2401.16677, applied
across the transport cliff) — the legs carry no data dependency, so
XLA is free to run the slice rings under the slow DCN exchange.
Chunked output is BITWISE the unchunked staged composition
(tests/test_xslice.py pins it), so the overlap knob is free to turn.

Protocol models: the same slice-scoped skeletons (`space=` on
`_ag_protocol` / `_rs_protocol`) composed with the rail-exchange model
below register as `xslice_allgather` / `xslice_reduce_scatter` /
`xslice_allreduce`, concretized by the verifier at every global rank
of (slices=2, n_local=2/4) grids, wire grids skeleton-invariant
(verify.check_format_invariance).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.kernels.allgather import (
    AllGatherMethod,
    all_gather,
)
from triton_dist_tpu.kernels.reduce_scatter import (
    ReduceScatterMethod,
    reduce_scatter,
)
from triton_dist_tpu.lang import shmem
from triton_dist_tpu.runtime.init import TP_AXIS
from triton_dist_tpu.wire import codec as wcodec
from triton_dist_tpu.xslice.topo import DCN_AXIS, SliceTeam

__all__ = [
    "hier_all_gather", "hier_reduce_scatter", "hier_all_reduce",
    "hier_all_gather_op", "hier_reduce_scatter_op",
    "hier_all_reduce_op",
]


# -- chunked overlap ----------------------------------------------------------


def _split(x, chunks: int):
    """Split along the last axis for the overlap pipeline; a payload
    the chunk count does not divide runs unchunked (correctness never
    depends on the split)."""
    if chunks <= 1 or x.shape[-1] % chunks != 0:
        return [x]
    return jnp.split(x, chunks, axis=-1)


def _pipelined(pieces, ici_fn, dcn_fn):
    """Issue order: ICI(i+1) before DCN(i). The ICI ring of the next
    chunk carries no dependency on the previous chunk's DCN exchange,
    so the slice rings overlap the slow hop; the per-chunk results
    concat back in order."""
    outs, prev = [], None
    for p in pieces:
        cur = ici_fn(p)
        if prev is not None:
            outs.append(dcn_fn(prev))
        prev = cur
    outs.append(dcn_fn(prev))
    return outs


def _dcn_sum(part, dcn_axis: str, slices: int, fmt):
    """Sum `part` across slices. Native: lax.psum (XLA owns the DCN
    trees). Wire: pack once at the send edge, gather the images, and
    decode-accumulate in FIXED slice order — deterministic, so the
    chunked pipeline reduces bitwise like the unchunked run."""
    if wcodec.is_native(fmt):
        return jax.lax.psum(part, dcn_axis)
    w = wcodec.pack(part, fmt)
    g = jax.lax.all_gather(w, dcn_axis)          # (slices, rows_w, cw)
    acc = wcodec.unpack(g[0], part.shape[1:], fmt, jnp.float32)
    for j in range(1, slices):
        acc = acc + wcodec.unpack(g[j], part.shape[1:], fmt,
                                  jnp.float32)
    return acc.astype(part.dtype)


def _dcn_gather(blk, dcn_axis: str, slices: int, fmt):
    """Concatenate the slice blocks across the DCN axis, slice order
    (dcn-major — matches SliceTeam.globalize). Wire: the image crosses
    the hop; each slot decodes at the consume edge."""
    if wcodec.is_native(fmt):
        return jax.lax.all_gather(blk, dcn_axis, tiled=True)
    w = wcodec.pack(blk, fmt)
    g = jax.lax.all_gather(w, dcn_axis)
    return jnp.concatenate(
        [wcodec.unpack(g[j], blk.shape[1:], fmt, blk.dtype)
         for j in range(slices)], axis=0)


# -- per-device 2-level collectives -------------------------------------------


def hier_all_gather(x, dcn_axis: str = DCN_AXIS, ici_axis: str = TP_AXIS,
                    wire_format=None, chunks: int = 1,
                    ici_method: AllGatherMethod = AllGatherMethod.Auto):
    """Hierarchical AG, per-device: shard (m, ...) -> (N*m, ...) with
    shards in global-rank order (dcn-major). Phase 1 gathers the slice
    block over the ICI ring; phase 2 moves whole slice blocks across
    the DCN hop (`wire_format` applies to this leg only — the ICI leg
    stays native)."""
    fmt = wcodec.resolve(wire_format)
    slices = jax.lax.axis_size(dcn_axis)

    def ici(piece):
        return all_gather(piece, ici_axis, method=ici_method)

    def dcn(blk):
        return _dcn_gather(blk, dcn_axis, slices, fmt)

    outs = _pipelined(_split(x, chunks), ici, dcn)
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=-1)


def hier_reduce_scatter(x, dcn_axis: str = DCN_AXIS,
                        ici_axis: str = TP_AXIS, wire_format=None,
                        chunks: int = 1,
                        ici_method=ReduceScatterMethod.Auto):
    """Hierarchical RS, per-device: (N*m, ...) -> (m, ...). Phase 1
    reduce-scatters over the ICI ring (rank local i holds super-chunk i
    summed over its slice); phase 2 completes the sum across slices and
    scatters super-chunk i's `slices` sub-chunks down the rail. The
    OUTPUT CHUNK INDEX is therefore `local * slices + sid` (ICI-major)
    — the staged-composition order, pinned by tests/test_xslice.py.
    `wire_format` rides the DCN leg: the slice-partial crosses as a
    packed image and the cross-slice sum runs decode-accumulate in
    fixed slice order."""
    fmt = wcodec.resolve(wire_format)
    slices = jax.lax.axis_size(dcn_axis)
    sid = jax.lax.axis_index(dcn_axis)

    def ici(piece):
        return reduce_scatter(piece, ici_axis, method=ici_method)

    def dcn(part):
        if wcodec.is_native(fmt):
            return jax.lax.psum_scatter(part, dcn_axis, tiled=True)
        full = _dcn_sum(part, dcn_axis, slices, fmt)
        m = full.shape[0] // slices
        return jax.lax.dynamic_slice_in_dim(full, sid * m, m, axis=0)

    outs = _pipelined(_split(x, chunks), ici, dcn)
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=-1)


def hier_all_reduce(x, dcn_axis: str = DCN_AXIS,
                    ici_axis: str = TP_AXIS, wire_format=None,
                    chunks: int = 1,
                    rs_method=ReduceScatterMethod.Auto,
                    ag_method: AllGatherMethod = AllGatherMethod.Auto):
    """Two-level AR, per-device: (R, ...) -> (R, ...) summed over the
    whole team. RS over the ICI ring, AR across the DCN hop (wire
    image + fixed-order decode-sum when quantized), AG back over the
    ICI ring — the two-shot composition with the slow hop pinched to
    1/n_local of the payload. `rs_method` / `ag_method` pin the ICI-leg
    protocols past the byte-threshold auto dispatch (the registered
    xslice_allreduce model declares the ring skeletons, so conformance
    checking pins ring explicitly)."""
    fmt = wcodec.resolve(wire_format)
    slices = jax.lax.axis_size(dcn_axis)

    def ici(piece):
        return reduce_scatter(piece, ici_axis, method=rs_method)

    def dcn_then_ag(part):
        summed = _dcn_sum(part, dcn_axis, slices, fmt)
        return all_gather(summed, ici_axis, method=ag_method)

    outs = _pipelined(_split(x, chunks), ici, dcn_then_ag)
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=-1)


# -- host entries -------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _hier_jit(mesh, collective: str, dcn_axis: str, ici_axis: str, fmt,
              chunks: int):
    if collective == "allgather":
        def fn(xs):
            return hier_all_gather(xs, dcn_axis, ici_axis,
                                   wire_format=fmt, chunks=chunks)
        in_specs, out_specs = P((dcn_axis, ici_axis)), P()
    elif collective == "reduce_scatter":
        def fn(xs):
            return hier_reduce_scatter(xs[0], dcn_axis, ici_axis,
                                       wire_format=fmt, chunks=chunks)
        in_specs = P((dcn_axis, ici_axis))
        out_specs = P((dcn_axis, ici_axis))
    elif collective == "allreduce":
        def fn(xs):
            return hier_all_reduce(xs[0], dcn_axis, ici_axis,
                                   wire_format=fmt, chunks=chunks)
        in_specs, out_specs = P((dcn_axis, ici_axis)), P()
    else:
        raise ValueError(f"unknown hierarchical collective "
                         f"{collective!r}")
    return jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False))


def hier_all_gather_op(arr, mesh, dcn_axis: str = DCN_AXIS,
                       ici_axis: str = TP_AXIS, wire_format=None,
                       chunks: int = 1):
    """Host-level hierarchical AG: `arr` sharded on dim 0 across
    (dcn, tp); returns the full gather in global-rank order."""
    return _hier_jit(mesh, "allgather", dcn_axis, ici_axis,
                     wcodec.resolve(wire_format), chunks)(arr)


def hier_reduce_scatter_op(arr, mesh, dcn_axis: str = DCN_AXIS,
                           ici_axis: str = TP_AXIS, wire_format=None,
                           chunks: int = 1):
    """Host-level hierarchical RS: `arr` is (N, R, ...) — row g is
    rank g's full contribution; returns the per-rank chunks stacked in
    global-rank order (rank g's chunk is output chunk
    `local(g) * slices + sid(g)` of the summed array — see
    hier_reduce_scatter)."""
    return _hier_jit(mesh, "reduce_scatter", dcn_axis, ici_axis,
                     wcodec.resolve(wire_format), chunks)(arr)


def hier_all_reduce_op(arr, mesh, dcn_axis: str = DCN_AXIS,
                       ici_axis: str = TP_AXIS, wire_format=None,
                       chunks: int = 1):
    """Host-level 2-level AR: `arr` is (N, R, ...) — row g is rank g's
    contribution; returns the (R, ...) team sum."""
    return _hier_jit(mesh, "allreduce", dcn_axis, ici_axis,
                     wcodec.resolve(wire_format), chunks)(arr)


# -- protocol models (static verifier, triton_dist_tpu.verify) ---------------

from triton_dist_tpu import verify as _v  # noqa: E402
from triton_dist_tpu.kernels.allgather import _ag_protocol  # noqa: E402
from triton_dist_tpu.kernels.reduce_scatter import (  # noqa: E402
    _rs_protocol,
)

_XGRID = ({"slices": 2}, {"slices": 2, "fmt": "fp8"},
          {"slices": 2, "fmt": "int8"})


def _rail_exchange(team: SliceTeam, prefix="dcn.", fmt="native",
                   srcs=()):
    """The DCN-hop model: an all-to-all among each rank's rail (the
    same local rank in every slice). Every member stages its block
    (wire: the send-edge encode), puts it into each rail peer's inbox
    slot KEYED BY THE SENDER'S SLICE ID, waits its own sends, then
    consumes each arrival behind that sender's keyed recv slot — the
    keying is what gives each delivery its own happens-before edge
    (a shared slot would let slice j's wait be satisfied by slice k's
    arrival: the race class the verifier flags). `fmt` only changes
    the local stage dataflow, never the sem skeleton
    (check_format_invariance covers the xslice grids)."""
    me_g = shmem.my_pe(TP_AXIS)
    sid = team.slice_of(me_g)
    local = team.local_of(me_g)
    blk = _v.ref(prefix + "blk")
    inbox = _v.ref(prefix + "inbox")
    send, recv = _v.sem(prefix + "send_sem"), _v.sem(prefix + "recv_sem")
    for s in srcs:
        _v.read(s)         # stage from the ICI leg's output
    _v.write(blk.at())     # the staged block (wire: the packed image)
    handles = []
    for j in range(1, team.slices):
        peer = ((sid + j) % team.slices) * team.n_local + local
        handles.append(
            shmem.putmem_nbi(inbox.at(sid), blk.at(), send.at(),
                             recv.at(sid), peer, TP_AXIS))
    for h in handles:
        h.wait_send()
    for j in range(1, team.slices):
        src_sid = (sid + team.slices - j) % team.slices
        shmem.signal_wait_until(recv.at(src_sid), shmem.CMP_GE, 1)
        _v.read(inbox.at(src_sid))  # consume edge (wire: decode)
    return inbox


@_v.protocol("xslice_allgather", ns=(4, 8), grid=_XGRID,
             doc="2-level AG: slice-scoped ring AG (space= on "
                 "_ag_protocol) + DCN rail exchange of whole slice "
                 "blocks; fmt != native packs the DCN leg only")
def _xag_protocol(n, slices=2, fmt="native"):
    team = SliceTeam(slices, n // slices)
    _ag_protocol(team.n_local, method="ring", prefix="ici.", space=team)
    out = _v.ref("ici.out")
    _rail_exchange(team, prefix="dcn.", fmt=fmt,
                   srcs=[out.at(j) for j in range(team.n_local)])


@_v.protocol("xslice_reduce_scatter", ns=(4, 8), grid=_XGRID,
             doc="2-level RS: slice-scoped credit-flow ring RS + DCN "
                 "rail exchange of the slice-partial + fixed-order "
                 "local sum; fmt != native packs the DCN leg only")
def _xrs_protocol(n, slices=2, fmt="native"):
    team = SliceTeam(slices, n // slices)
    _rs_protocol(team.n_local, prefix="ici.", space=team)
    _rail_exchange(team, prefix="dcn.", fmt=fmt,
                   srcs=[_v.ref("ici.o").at()])
    # the cross-slice reduction: own staged block + every arrival
    # (arrivals were consumed behind their keyed recv waits above)
    _v.read(_v.ref("dcn.blk").at())
    _v.write(_v.ref("o").at())


@_v.protocol("xslice_allreduce", ns=(4, 8), grid=_XGRID,
             doc="2-level AR: slice RS + DCN rail allreduce + slice "
                 "AG — the two-shot composition with the slow hop "
                 "pinched to the slice-partial")
def _xar_protocol(n, slices=2, fmt="native"):
    team = SliceTeam(slices, n // slices)
    _rs_protocol(team.n_local, prefix="rs.", space=team)
    _rail_exchange(team, prefix="dcn.", fmt=fmt,
                   srcs=[_v.ref("rs.o").at()])
    _v.read(_v.ref("dcn.blk").at())
    _v.write(_v.ref("ar").at())
    _ag_protocol(team.n_local, method="ring", prefix="ag.", space=team)


# -- conformance runners (verify.conform) -------------------------------------
#
# The DCN hop is an XLA leg (lax collectives / _dcn_* helpers) and
# records no kernel stream; conformance checks the Pallas ICI legs
# against the model with the "dcn."-prefixed ops filtered out
# (docs/verification.md "Conformance", XLA-owned legs). Recorded ICI
# peers are tp-local on the (slices, n_local) mesh; peer_xform lifts
# them to the model's global (dcn-major) rank space.

from triton_dist_tpu.verify import conform as _conform  # noqa: E402

_XCONFORM_GRID = ((4, {"slices": 2}), (4, {"slices": 2, "fmt": "fp8"}),
                  (4, {"slices": 2, "fmt": "int8"}))


def _ici_only(params):
    del params

    def keep(op):
        for k in ("sem", "send_sem", "recv_sem"):
            s = op.f.get(k)
            if s is not None and isinstance(s[0], str) \
                    and s[0].startswith("dcn."):
                return False
        return True

    return keep


def _xmesh(n, slices):
    if n % slices:
        return _conform.Skip(f"n={n} not divisible by slices={slices}")
    return _conform.team_mesh((slices, n // slices),
                              (DCN_AXIS, TP_AXIS))


def _globalize(n, slices):
    n_local = n // slices
    return lambda r, p: (r // n_local) * n_local + p


def _xconform(n, slices, fmt, fn):
    mesh = _xmesh(n, slices)
    if isinstance(mesh, _conform.Skip):
        return mesh
    x = jnp.ones((8, 128), jnp.float32)
    return _conform.collect_streams(
        mesh, (DCN_AXIS, TP_AXIS), fn, in_specs=P(), args=(x,),
        peer_xform=_globalize(n, slices))


@_conform.conforms(
    "xslice_allgather", grids=_XCONFORM_GRID,
    model_filter=_ici_only,
    doc="ICI ring-AG leg of the 2-level AG (DCN rail leg is XLA)")
def _xag_conform(n, slices=2, fmt="native"):
    wf = None if fmt == "native" else fmt
    return _xconform(n, slices, fmt, lambda v: hier_all_gather(
        v, wire_format=wf, ici_method=AllGatherMethod.Ring1D))


@_conform.conforms(
    "xslice_reduce_scatter", grids=_XCONFORM_GRID,
    model_filter=_ici_only,
    doc="ICI credit-ring RS leg of the 2-level RS (DCN leg is XLA)")
def _xrs_conform(n, slices=2, fmt="native"):
    wf = None if fmt == "native" else fmt
    return _xconform(n, slices, fmt, lambda v: hier_reduce_scatter(
        v, wire_format=wf, ici_method=ReduceScatterMethod.Ring1D))


@_conform.conforms(
    "xslice_allreduce", grids=_XCONFORM_GRID,
    model_filter=_ici_only,
    doc="ICI RS + AG legs of the 2-level AR (DCN leg is XLA)")
def _xar_conform(n, slices=2, fmt="native"):
    wf = None if fmt == "native" else fmt
    return _xconform(n, slices, fmt, lambda v: hier_all_reduce(
        v, wire_format=wf, rs_method=ReduceScatterMethod.Ring1D,
        ag_method=AllGatherMethod.Ring1D))
