"""Hierarchical rank spaces for 2-level (ICI + DCN) collectives.

A multi-slice deployment factorizes the global team of N ranks into
`slices` pods of `n_local` chips each: global rank

    g = sid * n_local + local        (DCN-major)

where `local` addresses a chip inside its slice (the fast ICI domain)
and `sid` addresses the slice (the slow DCN domain). `SliceTeam` is
that factorization as an object — usable both with concrete ints (host
scheduling, tests) and with the verifier's symbolic rank `me`
(`verify.capture.Sym` supports exactly the `% // * + -` arithmetic the
split needs), which is what lets the SAME ring protocol models in
`kernels/allgather.py` / `kernels/reduce_scatter.py` re-run scoped to
a slice: the `space=` parameter rebases every ring peer from
`(me ± s) % n` to `base + (local ± s) % n_local`, and the verifier
concretizes the composed 2-level protocol at every global rank of an
(slices, n_local) grid (tests/test_xslice.py).

`make_xslice_mesh` builds the matching jax mesh over ("dcn", "tp")
axes by splitting a flat device list DCN-major (`runtime.split_mesh`),
so `jax.lax.axis_index("dcn") == sid` and `axis_index("tp") == local`
inside a 2-axis shard_map — the mesh the hierarchical collectives in
`xslice/collectives.py` run on.
"""

from __future__ import annotations

import dataclasses

DCN_AXIS = "dcn"


@dataclasses.dataclass(frozen=True)
class SliceTeam:
    """The slice-id / local-rank factorization of a global team.

    All rank arithmetic works on ints AND on the verifier's symbolic
    `me` (verify.capture.Sym). Methods that need a concrete enumeration
    (`rail`, `leaders`) take/return ints only.
    """

    slices: int
    n_local: int

    def __post_init__(self):
        assert self.slices >= 1 and self.n_local >= 1, (self.slices,
                                                        self.n_local)

    @property
    def n(self) -> int:
        return self.slices * self.n_local

    # -- rank arithmetic (int or Sym) -----------------------------------

    def slice_of(self, g):
        return g // self.n_local

    def local_of(self, g):
        return g % self.n_local

    def globalize(self, sid, local):
        return sid * self.n_local + local

    def split(self, g):
        """(base, local): `base` is the slice's first global rank, so a
        slice-scoped ring peer `(local ± s) % n_local` globalizes as
        `base + peer`. Works symbolically (base = g - g % n_local)."""
        local = g % self.n_local
        return g - local, local

    # -- concrete-only helpers ------------------------------------------

    def leader_of(self, sid: int) -> int:
        return sid * self.n_local

    def leaders(self):
        """The slice leaders (local rank 0 of every slice) — the ranks
        that terminate a leader-hop DCN exchange."""
        return [self.leader_of(s) for s in range(self.slices)]

    def rail(self, g: int):
        """The DCN rail through global rank g: the same local rank in
        every slice (the peers of the per-rank DCN exchange — every
        rail is disjoint, so the rail all-to-all needs no leader
        funnel)."""
        local = int(g) % self.n_local
        return [s * self.n_local + local for s in range(self.slices)]

    # -- verifier-side slice barrier ------------------------------------

    def neighbor_barrier(self, prefix: str, local, base, n_local: int):
        """Slice-scoped ring-neighbor barrier for protocol MODELS
        (capture-time): `shmem.neighbor_barrier` hard-codes the global
        ring `(me ± 1) % n`, so slice rings record their exact sem
        decomposition here instead — two signals to the slice-local
        ring neighbors (globalized through `base`) plus one consuming
        wait for both, the same decomposition neighbor_barrier itself
        records."""
        from triton_dist_tpu.lang import shmem
        from triton_dist_tpu.runtime.init import TP_AXIS
        from triton_dist_tpu import verify as _v

        bsem = _v.sem(prefix + "__slice_nbar__")
        for d in ((local - 1 + n_local) % n_local,
                  (local + 1) % n_local):
            shmem.signal(bsem.at(), 1, shmem.SIGNAL_ADD, base + d,
                         TP_AXIS, label="barrier")
        shmem.signal_wait_until(bsem.at(), shmem.CMP_GE, 2)


def make_xslice_mesh(slices: int, n_local: int, devices=None,
                     dcn_axis: str = DCN_AXIS, ici_axis: str = "tp"):
    """A ("dcn", "tp") mesh over `slices * n_local` devices, DCN-major
    (device order matches `SliceTeam.globalize`). On the CPU test rig
    the devices come from the virtual 12-device pool (tests/conftest);
    on real multi-slice hardware `devices` arrives pre-ordered from
    `jax.devices()` after `runtime.init` multi-host bring-up."""
    from triton_dist_tpu.runtime import make_mesh, split_mesh

    flat = make_mesh(mesh_shape=(slices * n_local,),
                     axis_names=(ici_axis,), devices=devices)
    return split_mesh(flat, ici_axis, (slices, n_local),
                      (dcn_axis, ici_axis))
