"""Wire-coded KV page migration between slices.

The disaggregated serving plane (docs/serving.md "Disaggregated
prefill/decode") moves a finished prefill's KV pages from the prefill
slice to a decode slice. The pages cross the DCN hop as
`wire.WireFormat` images — EQuARX economics (arXiv 2506.17615) bite
hardest on the slow transport, and `perf_model.choose_migration_format`
prices the shrink against an error budget — wrapped in a frame-level
crc32 envelope, so EVERY migration (native included — the codec itself
refuses `WireFormat("native", checksum=True)`) is integrity-gated at
the destination: admission happens only after `decode_pages` verifies
the envelope (and, for quantized images, the codec's own per-block
checksums via `unpack_checked`). A corrupted or truncated image raises
`MigrationError` — the decode slice NACKs and the prefill slice
re-encodes from its still-held pages; silent-wrong is structurally
unreachable.

Fidelity contract: a native image round-trips bitwise; an fp8/int8
image reproduces EXACTLY `wire.codec.roundtrip(x, fmt)` — the codec's
documented quantization, nothing more (tests/test_xslice.py pins both).

Transports:

  MigrationChannel       in-process deque pair — the DisaggPair /
                         chaos-cell rig. Chaos knobs (`drop_next`,
                         `corrupt_next`, and their `_all` persistent
                         forms) inject exactly the DCN faults the
                         `faults/` matrix classifies.
  FileMigrationChannel   a real cross-process transport over a shared
                         directory (atomic tmp+rename publication) —
                         what the 2-process DCN test in
                         tests/test_xslice.py runs the disaggregated
                         pair over.

The sender HOLDS its pool pages until the ack for a seq arrives —
resend/re-encode needs the source of truth — and every record carries
enough (`prompt`, `meta`, `first_token`) to rebuild the request on a
decode slice that shares no memory with the prefill slice.
"""

from __future__ import annotations

import dataclasses
import json
import os
import zlib
from collections import deque
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from triton_dist_tpu.faults.errors import WireIntegrityError
from triton_dist_tpu.wire import codec as wcodec

__all__ = [
    "MigrationError", "MigrationRecord", "MigrationChannel",
    "FileMigrationChannel", "encode_pages", "decode_pages",
]


class MigrationError(RuntimeError):
    """A migration image failed integrity verification (frame crc32 or
    codec per-block checksum) — the caller NACKs, never admits."""


def _crc(b: np.ndarray) -> int:
    return zlib.crc32(b.tobytes()) & 0xFFFFFFFF


def _to_bytes(img: np.ndarray) -> np.ndarray:
    return np.frombuffer(np.ascontiguousarray(img).tobytes(),
                         np.uint8).copy()


def encode_pages(k_pages, v_pages, wire_format=None) -> dict:
    """Encode a KV page stack pair ((L, Hkv, P, page, D) each, the
    `KVPool.export_pages` layout) into a checksummed migration payload.
    Quantized formats pack the DCN image through the wire codec; native
    ships raw bytes. Both get the frame crc32 envelope."""
    fmt = wcodec.resolve(wire_format)
    payload: dict = {
        "fmt": (fmt.kind, fmt.block, bool(fmt.checksum)),
        "shape": tuple(int(s) for s in k_pages.shape),
        "dtype": str(np.asarray(k_pages).dtype),
    }
    for name, a in (("k", k_pages), ("v", v_pages)):
        a = np.asarray(a)
        assert tuple(a.shape) == payload["shape"], (a.shape,
                                                    payload["shape"])
        if wcodec.is_native(fmt):
            img = np.ascontiguousarray(a)
        else:
            x2d = jnp.asarray(a).reshape(-1, a.shape[-1])
            img = np.asarray(wcodec.pack(x2d, fmt))
        payload[name + "_bytes"] = _to_bytes(img)
        payload[name + "_crc"] = _crc(payload[name + "_bytes"])
        payload[name + "_img_shape"] = tuple(int(s) for s in img.shape)
        payload[name + "_img_dtype"] = str(img.dtype)
    return payload


def payload_nbytes(payload: dict) -> int:
    return int(payload["k_bytes"].size + payload["v_bytes"].size)


def _decode_one(payload: dict, name: str, fmt):
    b = payload[name + "_bytes"]
    if _crc(b) != payload[name + "_crc"]:
        raise MigrationError(
            f"migration frame crc mismatch on {name!r} image")
    shape = tuple(payload["shape"])
    dt = jnp.dtype(payload["dtype"])
    img_dt = jnp.dtype(payload[name + "_img_dtype"])
    try:
        img = np.frombuffer(b.tobytes(), img_dt).reshape(
            payload[name + "_img_shape"])
    except ValueError as e:
        raise MigrationError(f"truncated {name!r} image: {e}") from e
    if wcodec.is_native(fmt):
        return img.reshape(shape)
    trailing = shape[-1:]
    unpack = wcodec.unpack_checked if fmt.checksum else wcodec.unpack
    try:
        x2d = unpack(jnp.asarray(img), trailing, fmt, dt)
    except WireIntegrityError as e:
        raise MigrationError(
            f"wire checksum failed on {name!r} image: {e}") from e
    return np.asarray(x2d).reshape(shape)


def decode_pages(payload: dict) -> Tuple[np.ndarray, np.ndarray]:
    """Verify and decode a migration payload back to the
    (L, Hkv, P, page, D) page-stack pair. Raises MigrationError on any
    integrity failure — admission must gate on this call succeeding."""
    kind, block, checksum = payload["fmt"]
    fmt = wcodec.WireFormat(kind=str(kind),
                            block=None if block is None else int(block),
                            checksum=bool(checksum))
    return (_decode_one(payload, "k", fmt),
            _decode_one(payload, "v", fmt))


@dataclasses.dataclass
class MigrationRecord:
    """One in-flight prefill→decode handoff.

    `payload` is the checksummed KV image (encode_pages); `first_token`
    is the token the prefill step emitted — it TRAVELS rather than
    being emitted on the prefill slice, so the token stream has a
    single producer (the decode slice) and bit-identity with the
    single-slice scheduler is checkable end-to-end. `req` is an
    in-process passenger only (DisaggPair keeps the live Request so
    streams/callbacks survive the hop); cross-process transports strip
    it and the decode slice rebuilds from `prompt` + `meta`.
    """

    seq: int
    request_id: int
    prompt: Tuple[int, ...]
    n_tokens: int
    first_token: int
    payload: dict
    meta: dict
    req: object = None

    def strip(self) -> "MigrationRecord":
        return dataclasses.replace(self, req=None)


def _corrupt_record(rec: MigrationRecord) -> MigrationRecord:
    """Bit-flip the first byte of the k image (payload copied — the
    sender's copy stays pristine for the re-encode/resend path)."""
    payload = dict(rec.payload)
    b = payload["k_bytes"].copy()
    b[0] ^= 0xFF
    payload["k_bytes"] = b
    return dataclasses.replace(rec, payload=payload)


class MigrationChannel:
    """In-process migration transport (deque pair) with DCN chaos
    knobs. `send` consumes one-shot knobs first, then persistent ones;
    a dropped record simply never arrives (the sender's unacked-resend
    loop is what recovers), a corrupted record arrives and FAILS
    decode_pages on the far side (the nack path)."""

    def __init__(self):
        self._q: deque = deque()
        self._acks: deque = deque()
        self.drop_next = 0
        self.drop_all = False
        self.corrupt_next = 0
        self.corrupt_all = False
        self.n_sent = 0
        self.n_dropped = 0
        self.n_corrupted = 0
        self.n_acked = 0
        self.n_nacked = 0

    def send(self, rec: MigrationRecord) -> None:
        self.n_sent += 1
        if self.drop_next > 0 or self.drop_all:
            if self.drop_next > 0:
                self.drop_next -= 1
            self.n_dropped += 1
            return
        if self.corrupt_next > 0 or self.corrupt_all:
            if self.corrupt_next > 0:
                self.corrupt_next -= 1
            rec = _corrupt_record(rec)
            self.n_corrupted += 1
        self._q.append(rec)

    def recv(self) -> Optional[MigrationRecord]:
        return self._q.popleft() if self._q else None

    def ack(self, seq: int) -> None:
        self.n_acked += 1
        self._acks.append(("ack", seq))

    def nack(self, seq: int) -> None:
        self.n_nacked += 1
        self._acks.append(("nack", seq))

    def pump_acks(self) -> List[Tuple[str, int]]:
        out = list(self._acks)
        self._acks.clear()
        return out


class FileMigrationChannel:
    """Cross-process migration transport over a shared directory.

    Records publish as `rec_<seq>_<n>.npz` via atomic tmp+rename (a
    reader can never observe a partial file); acks/nacks publish the
    same way as empty `ack_<seq>.ok` / `nack_<seq>.ok` markers. A
    resend of seq publishes under a bumped attempt counter `<n>` so it
    is a NEW file (the consumer tracks consumed (seq, n) pairs and
    decodes the freshest unconsumed attempt). This is the transport the
    2-process DCN test runs the disaggregated pair over — two
    schedulers in different OS processes, no shared memory.
    """

    def __init__(self, root):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self._consumed: set = set()
        self._seen_acks: set = set()
        self._attempt: Dict[int, int] = {}
        self.n_sent = 0
        self.n_acked = 0
        self.n_nacked = 0

    def _publish(self, name: str, writer) -> None:
        tmp = self.root / ("." + name + ".tmp")
        writer(tmp)
        os.replace(tmp, self.root / name)

    def send(self, rec: MigrationRecord) -> None:
        rec = rec.strip()
        n = self._attempt.get(rec.seq, 0)
        self._attempt[rec.seq] = n + 1
        hdr = {
            "seq": rec.seq, "request_id": rec.request_id,
            "prompt": list(rec.prompt), "n_tokens": rec.n_tokens,
            "first_token": rec.first_token, "meta": rec.meta,
            "payload": {k: (list(v) if isinstance(v, tuple) else v)
                        for k, v in rec.payload.items()
                        if not k.endswith("_bytes")},
        }

        def write(tmp: Path) -> None:
            with open(tmp, "wb") as f:
                np.savez(f, header=np.asarray(json.dumps(hdr)),
                         k_bytes=rec.payload["k_bytes"],
                         v_bytes=rec.payload["v_bytes"])

        self._publish(f"rec_{rec.seq:08d}_{n:04d}.npz", write)
        self.n_sent += 1

    def recv(self) -> Optional[MigrationRecord]:
        for p in sorted(self.root.glob("rec_*.npz")):
            seq, n = (int(x) for x in p.stem.split("_")[1:3])
            if (seq, n) in self._consumed:
                continue
            self._consumed.add((seq, n))
            with np.load(p) as z:
                hdr = json.loads(str(z["header"]))
                payload = {k: (tuple(v) if isinstance(v, list) else v)
                           for k, v in hdr["payload"].items()}
                payload["fmt"] = tuple(payload["fmt"])
                payload["k_bytes"] = z["k_bytes"]
                payload["v_bytes"] = z["v_bytes"]
            return MigrationRecord(
                seq=seq, request_id=hdr["request_id"],
                prompt=tuple(hdr["prompt"]), n_tokens=hdr["n_tokens"],
                first_token=hdr["first_token"], payload=payload,
                meta=hdr["meta"], req=None)
        return None

    def ack(self, seq: int) -> None:
        self._publish(f"ack_{seq:08d}.ok",
                      lambda tmp: tmp.write_bytes(b""))
        self.n_acked += 1

    def nack(self, seq: int) -> None:
        self._publish(f"nack_{seq:08d}.ok",
                      lambda tmp: tmp.write_bytes(b""))
        self.n_nacked += 1

    def pump_acks(self) -> List[Tuple[str, int]]:
        out = []
        for p in sorted(self.root.glob("*.ok")):
            if p.name in self._seen_acks:
                continue
            self._seen_acks.add(p.name)
            verb = "ack" if p.name.startswith("ack_") else "nack"
            out.append((verb, int(p.stem.split("_")[1])))
        return out
