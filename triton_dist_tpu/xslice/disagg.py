"""DisaggPair — a prefill slice and a decode slice wired together.

The in-process harness for disaggregated prefill/decode serving: two
`serve.Scheduler`s (role="prefill" / role="decode") joined by a
migration channel, pumped in lockstep. Each scheduler owns its own
KVPool (disjoint device state — nothing is shared but the channel), so
the pair exercises the REAL migration path: pages leave the prefill
pool as a checksummed wire image and enter the decode pool through
verified admission, with the first token traveling in the record.

The acceptance oracle (tests/test_xslice.py, tier-1): for the same
submissions, the pair's per-request token streams are BITWISE what a
single `role="both"` scheduler over the same engine emits — greedy and
sampled (the sampling key is derived from (seed, output index), worker
`key_for`, so it survives the hop by construction).

TTFT decomposition: the migrated Request object is the channel's
passenger, so its phase ledger accumulates across both schedulers —
queued/prefill on the prefill slice, migrate (send -> pulled off the
channel), admit (verify + install), decode on the decode slice — and
the prefill-side `ledger()` closes the full wall
(trace/ledger.py's contract, now over five phases).
"""

from __future__ import annotations

from typing import Optional

from triton_dist_tpu.xslice.migrate import MigrationChannel

__all__ = ["DisaggPair"]


class DisaggPair:
    """Prefill + decode schedulers over a migration channel.

    `engine` serves both sides by default (the CPU rig: two pools,
    one model); pass `decode_engine` for genuinely separate slices.
    Extra scheduler kwargs go through `prefill_kw` / `decode_kw`.
    """

    def __init__(self, engine, decode_engine=None, channel=None,
                 migration_format=None, prefill_kw: Optional[dict] = None,
                 decode_kw: Optional[dict] = None):
        from triton_dist_tpu.serve.scheduler import Scheduler

        self.channel = channel if channel is not None \
            else MigrationChannel()
        self.prefill = Scheduler(
            engine, role="prefill", migrate_to=self.channel,
            migration_format=migration_format, **(prefill_kw or {}))
        self.decode = Scheduler(
            decode_engine if decode_engine is not None else engine,
            role="decode", admit_from=self.channel,
            **(decode_kw or {}))

    def submit(self, *args, **kwargs):
        return self.prefill.submit(*args, **kwargs)

    def step(self) -> bool:
        """One lockstep round: the prefill slice first (it feeds the
        channel), then the decode slice (it drains it)."""
        a = self.prefill.step()
        b = self.decode.step()
        return a or b

    def run(self, max_steps: int = 100_000) -> None:
        """Pump both sides until queues, slots, and the channel drain."""
        for _ in range(max_steps):
            busy = self.step()
            if (not busy and self.prefill.queue.peek() is None
                    and not self.prefill._migrating
                    and not self.decode._pending_migrations):
                return
        raise RuntimeError(
            f"disaggregated pair did not drain in {max_steps} steps")

    def metrics(self) -> dict:
        """Both sides' metrics, plus the channel's fault counters."""
        out = {"prefill": self.prefill.metrics(),
               "decode": self.decode.metrics()}
        for key in ("n_sent", "n_dropped", "n_corrupted", "n_acked",
                    "n_nacked"):
            out["channel_" + key] = getattr(self.channel, key, 0)
        return out
