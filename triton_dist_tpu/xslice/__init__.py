"""triton_dist_tpu.xslice — scale beyond one slice.

Two planes:

  collectives   2-level ICI+DCN allgather / reduce-scatter / allreduce
                (slice-scoped Pallas rings + a wire-codable XLA DCN
                hop, chunk-overlapped), with verifier protocol models
                concretized at hierarchical (slices, n_local) grids;
  serving       disaggregated prefill/decode — a prefill slice streams
                finished KV pages to decode slices as checksummed
                `wire.WireFormat` images (`migrate`), and
                `serve.Scheduler` grows slice roles (`disagg`).

`topo.SliceTeam` is the shared rank factorization under both.
"""

from triton_dist_tpu.xslice.topo import (  # noqa: F401
    DCN_AXIS,
    SliceTeam,
    make_xslice_mesh,
)
from triton_dist_tpu.xslice.collectives import (  # noqa: F401
    hier_all_gather,
    hier_all_gather_op,
    hier_all_reduce,
    hier_all_reduce_op,
    hier_reduce_scatter,
    hier_reduce_scatter_op,
)
from triton_dist_tpu.xslice.migrate import (  # noqa: F401
    FileMigrationChannel,
    MigrationChannel,
    MigrationError,
    MigrationRecord,
    decode_pages,
    encode_pages,
)
from triton_dist_tpu.xslice.disagg import DisaggPair  # noqa: F401

__all__ = [
    "DCN_AXIS", "SliceTeam", "make_xslice_mesh",
    "hier_all_gather", "hier_reduce_scatter", "hier_all_reduce",
    "hier_all_gather_op", "hier_reduce_scatter_op",
    "hier_all_reduce_op",
    "MigrationRecord", "MigrationChannel", "FileMigrationChannel",
    "MigrationError", "encode_pages", "decode_pages",
    "DisaggPair",
]
