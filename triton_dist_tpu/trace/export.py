"""Trace export: Chrome-trace/Perfetto JSON + the host profiling
helpers (the one trace-merging code path).

`to_chrome_trace` emits the Trace Event Format
(docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU)
that both chrome://tracing and ui.perfetto.dev load: one process per
stream, one thread per (rank, lane), "X" complete events for spans and
"i" instants, with process/thread name metadata. Device streams tick on
the deterministic seq clock (1 tick = 1 us in the export); each stream
is offset to its host anchor when the session recorded one, so device
lanes line up with the python-level host spans (the documented
wall-time reconstruction for clocks with no hardware stamp).

`group_profile` / `merge_traces` moved here from `runtime.utils` (which
keeps back-compat aliases): xplane profiling and trace merging now live
beside the in-kernel trace exporter — one module owns every trace
artifact this framework writes.
"""

from __future__ import annotations

import contextlib
import json
import os
from typing import Optional

from triton_dist_tpu.trace import events as ev
from triton_dist_tpu.trace.collect import MalformedTrace, Timeline

_TICK_US = 1.0  # one seq tick rendered as 1 us


def _span_name(region: int, payload: int, aux: int) -> str:
    name = ev.region_name(region)
    if name in ("a2a.wait", "a2a.send"):
        return f"{name} s{payload}c{aux}"
    if name in ("ag.ring_wait", "rs.credit", "rs.hop", "rs.partial",
                "ep.ffn_chunk", "mega.sb_wait"):
        return f"{name} {payload}"
    if name == "mega.task":
        return f"{name} b{payload}@{aux}"
    return name


def to_chrome_trace(tl: Timeline) -> dict:
    """Timeline -> Chrome-trace dict (json.dump-able)."""
    streams = tl.streams()
    pid_of = {s: i + 1 for i, s in enumerate(streams)}
    host_pid = len(streams) + 1
    events = []
    # host anchoring: a device stream whose name matches a host span
    # starts at that span; all other streams start at the session's
    # FIRST host span (a multi-stream trace like the EP pipeline shares
    # one "ep_moe"-style span) — so device lanes always line up with the
    # host process when the session recorded any span at all
    t_host0 = min((t0 for _, t0, _ in tl.host_spans), default=0)
    first_off = ((tl.host_spans[0][1] - t_host0) / 1e3
                 if tl.host_spans else 0.0)
    offs = {s: first_off for s in streams}
    for name, t0, _t1 in tl.host_spans:
        if name in offs:
            offs[name] = (t0 - t_host0) / 1e3  # ns -> us

    def tid_of(rank: int, lane: int) -> int:
        return (max(rank, 0)) * 16 + lane + 1

    for s in streams:
        events.append({"ph": "M", "pid": pid_of[s],
                       "name": "process_name", "args": {"name": s}})
    seen_threads = set()
    for e in tl.events:
        key = (e.stream, e.rank, e.lane)
        if key not in seen_threads:
            seen_threads.add(key)
            events.append({
                "ph": "M", "pid": pid_of[e.stream],
                "tid": tid_of(e.rank, e.lane), "name": "thread_name",
                "args": {"name": f"rank{e.rank}/core{e.lane}"},
            })
    for sp in tl.spans:
        events.append({
            "ph": "X", "pid": pid_of[sp.stream],
            "tid": tid_of(sp.rank, sp.lane),
            "name": _span_name(sp.region, sp.payload, sp.aux),
            "cat": ev.REGION_CLASS.get(ev.region_name(sp.region),
                                       "trace"),
            "ts": offs[sp.stream] + sp.t0 * _TICK_US,
            "dur": max(sp.dur, 0.001) * _TICK_US,
            "args": ({"payload": sp.payload, "aux": sp.aux,
                      "seq_ticks": sp.dur, "plan": tl.plan_id}
                     if tl.plan_id else
                     {"payload": sp.payload, "aux": sp.aux,
                      "seq_ticks": sp.dur}),
        })
    for e in tl.events:
        if e.kind != ev.KIND_INSTANT:
            continue
        events.append({
            "ph": "i", "s": "t", "pid": pid_of[e.stream],
            "tid": tid_of(e.rank, e.lane),
            "name": _span_name(e.region, e.payload, e.aux),
            "ts": offs[e.stream] + e.t * _TICK_US,
            "args": {"payload": e.payload, "aux": e.aux},
        })
    for name, t0, t1 in tl.host_spans:
        events.append({
            "ph": "X", "pid": host_pid, "tid": 1, "name": name,
            "cat": "host",
            "ts": (t0 - t_host0) / 1e3, "dur": (t1 - t0) / 1e3,
        })
    if tl.host_spans:
        events.append({"ph": "M", "pid": host_pid, "name": "process_name",
                       "args": {"name": "host"}})
        events.append({"ph": "M", "pid": host_pid, "tid": 1,
                       "name": "thread_name", "args": {"name": "python"}})
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "label": tl.label,
            "clock": "seq" if not tl.host_spans else "seq+host-anchored",
            "drops": {f"{k[0]}/r{k[1]}/c{k[2]}": v
                      for k, v in tl.drops.items()},
            "format": "triton_dist_tpu.trace v1",
            **({"plan": tl.plan_id} if tl.plan_id else {}),
        },
    }


def write_trace(tl: Timeline, path: str, extra: Optional[dict] = None
                ) -> str:
    """Write the Perfetto JSON; `extra` merges into otherData (e.g. the
    attribution.compare_predicted report, which scripts/trace_report.py
    prints back as the predicted-stall diff)."""
    d = to_chrome_trace(tl)
    if extra:
        d["otherData"].update(extra)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as f:
        json.dump(d, f)
    return path


def load_trace_json(path: str) -> dict:
    """Load + validate an exported trace (scripts/trace_report.py's
    strict entry: malformed input raises MalformedTrace)."""
    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise MalformedTrace(f"{path}: {e}") from e
    if not isinstance(d, dict) or "traceEvents" not in d:
        raise MalformedTrace(f"{path}: no traceEvents key")
    fmt = d.get("otherData", {}).get("format", "")
    if not str(fmt).startswith("triton_dist_tpu.trace"):
        raise MalformedTrace(
            f"{path}: not a triton_dist_tpu trace (format={fmt!r})")
    for i, e in enumerate(d["traceEvents"]):
        if not isinstance(e, dict) or "ph" not in e or "pid" not in e:
            raise MalformedTrace(f"{path}: traceEvents[{i}] malformed")
        if e["ph"] in ("X", "i") and "ts" not in e:
            raise MalformedTrace(f"{path}: traceEvents[{i}] missing ts")
    return d


# -- host profiling (moved from runtime.utils — aliases remain there) --------


@contextlib.contextmanager
def group_profile(name: str = "profile", do_prof: bool = True,
                  out_dir: Optional[str] = None):
    """Profiling context writing an xplane trace per process.

    The reference merges per-rank chrome traces into one
    (ref: utils.py:505-589); on TPU jax.profiler writes a unified xplane
    trace per host that already carries all local device lanes;
    TensorBoard merges multi-host by directory.
    """
    import jax

    if not do_prof:
        yield
        return
    out_dir = out_dir or os.environ.get("TDT_PROFILE_DIR",
                                        "/tmp/tdt_profile")
    path = os.path.join(out_dir, f"{name}")
    jax.profiler.start_trace(path)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        from triton_dist_tpu.runtime.utils import dist_print

        dist_print(f"profile written to {path}")


def merge_traces(per_process_dirs, out_dir: str) -> str:
    """Collect per-process trace directories into one TensorBoard logdir
    (the reference's multi-rank trace merge, ref utils.py:370-502: chrome
    traces gathered to rank 0 with pid/tid remapping). The xplane format
    needs no event rewriting — TensorBoard renders every host found under
    one logdir — so the merge is a process-tagged relocation of each
    host's `plugins/profile` runs."""
    import shutil

    os.makedirs(out_dir, exist_ok=True)
    merged = []
    for pid, src in enumerate(per_process_dirs):
        prof_root = os.path.join(src, "plugins", "profile")
        if not os.path.isdir(prof_root):
            continue
        for run in sorted(os.listdir(prof_root)):
            dst = os.path.join(out_dir, "plugins", "profile",
                               f"{run}_p{pid}")
            shutil.copytree(os.path.join(prof_root, run), dst,
                            dirs_exist_ok=True)
            merged.append(dst)
    if not merged:
        raise FileNotFoundError(
            f"no plugins/profile runs found under {list(per_process_dirs)}"
        )
    return out_dir
