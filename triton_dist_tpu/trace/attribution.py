"""Stall attribution: classify traced time and diff it against the
scheduler's predictions.

Taxonomy (events.REGION_CLASS): every span region maps to one of

  compute   — MXU/VPU work (megakernel task bodies, GEMM+RS partials,
              per-chunk grouped FFN marks)
  sem_wait  — waiting on a semaphore another agent must signal (chunk
              delivery waits, ring-step recv waits, credit waits,
              scoreboard waits)
  dma_wait  — waiting on this core's own DMA queue (A-tile loads,
              local-segment copies)
  idle      — traced wall not covered by any span (scheduling gaps,
              untraced prologue)

All totals are in the timeline's clock units (vticks on the
deterministic interpret clock; cycles once a hardware stamp is wired —
see trace/events.py). Fractions, not absolute units, are what the
measured-vs-predicted comparisons assert.

`a2a_step_waits` is the delivery-replay reconstruction: receiver q's
wait for ring step i, chunk c gates on the SENDER-side "a2a.send"
instant of rank (q - i) mod n — the event that carries injected skew on
the lockstep interpreter (see trace/collect.py module doc). On hardware
the receiver-side wait spans measure the same quantity directly; the
replay is the clock-agnostic formulation.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from triton_dist_tpu.trace import events as ev
from triton_dist_tpu.trace.collect import Timeline

CLASSES = ("compute", "sem_wait", "dma_wait", "idle")


def classify(tl: Timeline) -> Dict[tuple, Dict[str, float]]:
    """Per (stream, rank, lane): time per attribution class + total.
    idle = traced extent not covered by classified spans (clamped at 0:
    nested spans may legitimately overlap)."""
    out: Dict[tuple, Dict[str, float]] = {}
    extent: Dict[tuple, list] = {}
    for e in tl.events:
        key = (e.stream, e.rank, e.lane)
        lo_hi = extent.setdefault(key, [e.t, e.t])
        lo_hi[0] = min(lo_hi[0], e.t)
        lo_hi[1] = max(lo_hi[1], e.t)
        out.setdefault(key, {c: 0.0 for c in CLASSES})
    for s in tl.spans:
        cls = ev.REGION_CLASS.get(ev.region_name(s.region))
        if cls is None:
            continue
        out[(s.stream, s.rank, s.lane)][cls] += s.dur
    for key, d in out.items():
        lo, hi = extent[key]
        d["total"] = hi - lo
        covered = d["compute"] + d["sem_wait"] + d["dma_wait"]
        d["idle"] = max(0.0, d["total"] - covered)
    return out


def per_region(tl: Timeline) -> Dict[tuple, Dict[str, float]]:
    """Per (stream, region name): total span time + span count +
    instant count — the per-region totals of the attribution table."""
    out: Dict[tuple, Dict[str, float]] = {}
    for s in tl.spans:
        d = out.setdefault((s.stream, ev.region_name(s.region)),
                           {"time": 0.0, "spans": 0, "instants": 0})
        d["time"] += s.dur
        d["spans"] += 1
    for e in tl.events:
        if e.kind == ev.KIND_INSTANT:
            d = out.setdefault((e.stream, ev.region_name(e.region)),
                               {"time": 0.0, "spans": 0, "instants": 0})
            d["instants"] += 1
    return out


def format_table(tl: Timeline) -> str:
    """The attribution table examples/scripts print: per-stream class
    fractions plus the per-region totals."""
    lines = []
    cls = classify(tl)
    by_stream: Dict[str, Dict[str, float]] = {}
    for (stream, _r, _l), d in cls.items():
        agg = by_stream.setdefault(
            stream, {c: 0.0 for c in CLASSES} | {"total": 0.0})
        for k in list(agg):
            agg[k] += d[k]
    lines.append(f"{'stream':<20} {'compute':>9} {'sem_wait':>9} "
                 f"{'dma_wait':>9} {'idle':>9}")
    for stream in sorted(by_stream):
        d = by_stream[stream]
        tot = max(d["total"], 1e-9)
        lines.append(
            f"{stream:<20} "
            + " ".join(f"{d[c] / tot:>8.1%}" for c in CLASSES))
    lines.append("")
    lines.append(f"{'stream/region':<28} {'time':>10} {'spans':>7} "
                 f"{'instants':>9}")
    for (stream, region), d in sorted(per_region(tl).items()):
        lines.append(f"{stream + '/' + region:<28} {d['time']:>10.0f} "
                     f"{d['spans']:>7} {d['instants']:>9}")
    return "\n".join(lines)


# -- chunked-A2A delivery replay ---------------------------------------------


def _delivery_replay(tl: Timeline, stream: str, send_region: str,
                     wait_region: str) -> Dict[int, np.ndarray]:
    """Shared delivery-wait reconstruction over a (send instant, wait
    span) region pair whose payload is the ring step / source offset:
    for each receiver-side wait span (payload=i, aux=sub-unit), arrival
    is the matching sender-side send instant on rank (q - i) mod n; the
    consumer cursor advances through max(ready, arrival), and the
    blocked amount accrues to offset i. Offset 0 (the local segment)
    never waits on a peer and reports 0. Used by the chunked-A2A replay
    and the flash-prefill per-segment replay."""
    ranks = tl.ranks(stream)
    n = len(ranks)
    if n == 0:
        return {}
    sends: Dict[tuple, float] = {}
    for e in tl.events:
        if (e.stream == stream and e.kind == ev.KIND_INSTANT
                and e.region == ev.REGIONS[send_region]):
            sends[(e.rank, e.payload, e.aux)] = e.t
    out: Dict[int, np.ndarray] = {}
    for q in ranks:
        waits = np.zeros(n, np.float64)
        cursor = 0.0
        spans = sorted(
            tl.spans_of(stream, rank=q, region=wait_region),
            key=lambda s: s.t0,
        )
        for s in spans:
            i, c = s.payload, s.aux
            if i == 0:
                continue
            src = ranks[(ranks.index(q) - i) % n]
            arrival = sends.get((src, i, c))
            if arrival is None:
                continue
            start = max(cursor, s.t0)
            waits[i] += max(0.0, arrival - start)
            cursor = max(start, arrival)
        out[q] = waits
    return out


def a2a_step_waits(tl: Timeline, stream: str) -> Dict[int, np.ndarray]:
    """Per receiver rank: reconstructed chunked-A2A delivery wait per
    ring step (see _delivery_replay; payload=step, aux=chunk)."""
    return _delivery_replay(tl, stream, "a2a.send", "a2a.wait")


def fp_seg_waits(tl: Timeline, stream: str) -> Dict[int, np.ndarray]:
    """Per receiver rank: reconstructed flash-prefill per-SEGMENT
    delivery wait (payload=source offset) — where prefill time goes
    when a producer straggles (see _delivery_replay; the SP flash
    kernel's fp.send/fp.wait records, kernels/flash_prefill.py)."""
    return _delivery_replay(tl, stream, "fp.send", "fp.wait")


# -- megakernel measured-vs-predicted ----------------------------------------


def wire_send_bytes(tl: Timeline, stream: str, region: str,
                    bytes_per_event: int) -> Dict[int, int]:
    """Per-rank WIRE bytes attributed to one transport region: the
    count of that region's records (spans and instants both — kernels
    mark sends as instants, delivery waits as spans) priced at
    `bytes_per_event`. With bytes_per_event =
    `wire.wire_row_bytes(h, fmt, dtype) * rows_per_transfer`, this is
    the per-format byte ledger of a transport leg: the SAME traced
    kernel run under native vs fp8 wire attributes bytes in exactly the
    packed ratio (the protocol — and therefore the event count — is
    format-invariant; only the per-event byte price moves). Returns
    {rank: bytes}."""
    rid = ev.region_id(region)
    out: Dict[int, int] = {}
    for s in tl.spans:
        if s.stream == stream and s.region == rid:
            out[s.rank] = out.get(s.rank, 0) + int(bytes_per_event)
    for e in tl.events:
        if (e.stream == stream and e.region == rid
                and e.kind == ev.KIND_INSTANT):
            out[e.rank] = out.get(e.rank, 0) + int(bytes_per_event)
    return out


def compare_predicted(sched, tl: Timeline, stream: str = "mega",
                      graph=None, tol: float = 0.1,
                      check: bool = True) -> List[dict]:
    """Diff the megakernel trace against the schedule, queue by queue.

    Structural checks (exact, any clock): every queue's traced task
    count equals its scheduled length, and tasks ran in queue order
    (aux carries the queue position).

    Stall check: measured scoreboard-wait fraction — sum of
    "mega.sb_wait" span time over (that + task-span time) per queue —
    must agree with the cost model's `sched.stall` fraction within
    `tol` (fractions, because the trace clock's units are ticks/cycles
    while `predicted_stalls` is in cost-model time; `graph` supplies
    the per-task costs for the predicted busy term and is required only
    when predicted stall is nonzero, i.e. multi-queue schedules). On
    the deterministic interpret clock a single-queue schedule measures
    exactly 0 == predicts exactly 0.

    Every rank executes the same schedule, so the comparison runs per
    (rank, queue) — one report row each; raises AssertionError on
    disagreement when `check`."""
    queues = sched.queues
    stall_pred = np.asarray(
        sched.stall if sched.stall is not None
        else np.zeros(len(queues)), np.float64)
    report: List[dict] = []
    for rank in (tl.ranks(stream) or [None]):
        for c, q in enumerate(queues):
            spans = tl.spans_of(stream, rank=rank, lane=c,
                                region="mega.task")
            spans.sort(key=lambda s: s.t0)
            busy = sum(s.dur for s in spans)
            sb = sum(s.dur for s in tl.spans_of(stream, rank=rank,
                                                lane=c,
                                                region="mega.sb_wait"))
            order_ok = all(s.aux < s2.aux
                           for s, s2 in zip(spans, spans[1:]))
            m_frac = sb / (sb + busy) if (sb + busy) > 0 else 0.0
            if graph is not None:
                busy_pred = float(sum(graph.tasks[t].cost for t in q))
            else:
                busy_pred = None
            if busy_pred is not None and stall_pred[c] + busy_pred > 0:
                p_frac = float(stall_pred[c]) / (stall_pred[c]
                                                 + busy_pred)
            else:
                # no graph (or an all-zero-cost queue): only a zero
                # prediction can be stated without the busy term
                p_frac = 0.0 if stall_pred[c] == 0 else None
            row = {
                "rank": rank,
                "queue": c,
                "n_tasks_scheduled": len(q),
                "n_tasks_traced": len(spans),
                "order_ok": order_ok,
                "measured_busy": busy,
                "measured_stall": sb,
                "measured_stall_frac": m_frac,
                "predicted_stall": float(stall_pred[c]),
                "predicted_stall_frac": p_frac,
            }
            report.append(row)
            if check:
                who = f"rank {rank} queue {c}"
                assert len(spans) == len(q), (
                    f"{who}: traced {len(spans)} task spans, schedule "
                    f"has {len(q)} — the trace does not cover the queue")
                assert order_ok, f"{who}: tasks traced out of order"
                assert p_frac is not None, (
                    f"{who}: predicted stall {stall_pred[c]} != 0 needs "
                    "`graph` for the predicted busy term")
                assert abs(m_frac - p_frac) <= tol, (
                    f"{who}: measured stall fraction {m_frac:.3f} vs "
                    f"predicted {p_frac:.3f} beyond tol {tol}")
    return report


def task_time_by_branch(tl: Timeline, branch_keys=None,
                        stream: str = "mega") -> Dict[object, dict]:
    """Per-branch megakernel task-time buckets: "mega.task" spans carry
    payload=branch id, so the traced step decomposes into matmul vs
    attention vs AR vs elementwise time — the first cut of the world=1
    ledger workflow (docs/performance.md): a latency regression names
    its branch before anyone stares at a timeline.

    branch_keys (CompiledMega.branch_keys) maps ids to keys; without it
    the buckets key on the raw branch id. Returns
    {key_or_id: {"time": total span time, "count": spans}}."""
    out: Dict[object, dict] = {}
    for s in tl.spans:
        if s.stream != stream or ev.region_name(s.region) != "mega.task":
            continue
        key = s.payload
        if branch_keys is not None and 0 <= s.payload < len(branch_keys):
            key = branch_keys[s.payload]
        d = out.setdefault(key, {"time": 0.0, "count": 0})
        d["time"] += s.dur
        d["count"] += 1
    return out


def prefetch_hit_rate(tl: Timeline,
                      stream: str = "mega") -> Optional[float]:
    """Fraction of prefetch-arena consumes that hit (payload > 0) among
    all "mega.pf" instants; None when the trace has none."""
    hits = total = 0
    for e in tl.events:
        if (e.stream == stream and e.kind == ev.KIND_INSTANT
                and e.region == ev.REGIONS["mega.pf"]):
            total += 1
            hits += 1 if e.payload > 0 else 0
    return (hits / total) if total else None


def guard_trips(tl: Timeline) -> List[dict]:
    """The guard-trip instants of a timeline (kernels built under BOTH
    trace.building and faults.guard.building emit one per watchdog
    trip): [{rank, site, slot, t}] rows, the trace-side view of the
    guard rows the host raised on — every recovery the degradation
    ladder performs is attributable next to the stalls that caused it
    (docs/robustness.md)."""
    from triton_dist_tpu.faults.guard import site_name

    rid = ev.REGIONS["guard.trip"]
    return [
        {"rank": e.rank, "site": site_name(e.payload), "slot": e.aux,
         "t": e.t}
        for e in tl.events
        if e.region == rid and e.kind == ev.KIND_INSTANT
    ]
