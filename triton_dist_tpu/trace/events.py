"""Device-side event records for the in-kernel trace subsystem.

The reference ships intra-kernel profiling hooks (ref: the
`profile_allocated_buffer` / in-kernel event slots of
python/triton_dist/tools/profiler.py) so overlap quality can be SEEN,
not inferred from end-to-end scalars; T3 (arXiv 2401.16677) makes the
same point — fine-grained tracking of compute/collective progress is
the substrate for both diagnosing and driving overlap. This module is
the TPU-native analog: a fixed-capacity per-core buffer of fixed-width
i32 records written by scalar SMEM stores inside Pallas kernels.

Record format (RECORD_WORDS i32 words per row):

    [region, kind, seq, payload, t_lo, t_hi, aux, 0]

  region   stable id from REGIONS (see region_id/region_name)
  kind     KIND_BEGIN | KIND_END | KIND_INSTANT
  seq      per-buffer monotonic record index (the deterministic clock)
  payload  region-specific datum (ring step, chunk id, branch id, ...)
  t_lo/hi  split i64 timestamp; all-zero under the seq clock
  aux      second region-specific datum

Row 0 of every buffer is a header:

    [MAGIC, count, cap, rank, lane, clock, stream, 0]

`count` is the TOTAL number of emits (count > cap means count - cap
records were dropped off the tail — the buffer saturates rather than
wrapping, so BEGIN/END pairs in the kept prefix never tear).

Clock semantics (the injectable-clock design): records carry the
monotonic `seq` counter — deterministic, identical across reruns, and
exactly ordered within a buffer. Wall-clock is reconstructed host-side
(trace/collect.py): per-region host timing anchors each buffer, and
injected straggler delays ride as REGION "straggle" payload ticks so
skew is visible deterministically on the lockstep CPU interpreter.
`t_lo/t_hi` are reserved for a real cycle-counter stamp on hardware —
`TraceCtx.stamp` is the single injection point; today it returns zeros
(documented limitation: in-kernel host callbacks segfault under the
0.4.x Shardy partitioner, and Mosaic has no portable cycle read).

Zero cost when off: every helper is a trace-time no-op when its ctx (or
the active build) is None — no refs are added, no stores are emitted,
and instrumented kernels trace byte-identical programs (enforced by
tests/test_trace.py).
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

RECORD_WORDS = 8
MAGIC = 0x7D7A  # 'trace' header tag
KIND_BEGIN = 0
KIND_END = 1
KIND_INSTANT = 2

CLOCK_SEQ = 0  # monotonic per-buffer record index (deterministic)

# Stable region registry: ids are part of the on-disk trace format
# (scripts/trace_report.py reads exported JSONs from other runs), so
# entries are append-only.
REGIONS = {
    "straggle": 1,       # injected skew (payload = delay ticks, 0 off-rank)
    "a2a.local": 2,      # local-segment chunk copy wait (payload=chunk)
    "a2a.send": 3,       # remote chunk DMA issued (payload=step, aux=chunk)
    "a2a.wait": 4,       # delivery-semaphore wait (payload=step, aux=chunk)
    "a2a.meta": 5,       # splits-metadata transfer
    "ag.ring_wait": 6,   # AG+GEMM ring-step delivery wait (payload=step)
    "ag.a_wait": 7,      # AG+GEMM A-tile DMA wait (payload=flat tile)
    "ag.tile": 8,        # AG+GEMM output tile stored (payload=flat tile)
    "rs.partial": 9,     # GEMM+RS partial-chunk MXU work (payload=chunk)
    "rs.credit": 10,     # GEMM+RS credit wait (payload=ring step)
    "rs.hop": 11,        # GEMM+RS hop recv wait (payload=ring step)
    "mega.task": 12,     # megakernel task span (payload=branch, aux=row)
    "mega.sb_wait": 13,  # scoreboard wait (payload=queue waited on)
    "mega.pf": 14,       # prefetch-arena consume (payload=pf_in; 0=cold)
    "ep.phase": 15,      # pipeline phase mark (payload=phase code)
    "ep.ffn_chunk": 16,  # per-chunk grouped FFN (payload=chunk)
    "host": 17,          # host-side python span (collect.TraceSession)
    "fp.send": 18,       # flash-prefill segment DMA issued (payload=offset)
    "fp.wait": 19,       # flash-prefill segment delivery wait (payload=offset)
    "fp.fold": 20,       # flash-prefill per-segment fold (payload=offset)
    "guard.trip": 21,    # watchdog trip (payload=site id, aux=slot) —
    # emitted when a kernel carries BOTH a trace ctx and a guard ctx
    # (faults/guard.py), so every recovery is attributable in Perfetto
    "fault.inject": 22,  # host-side fault-injection instant (chaos
    # plane / scheduler quarantine markers ride host spans; this region
    # tags in-band injection points)
    "serve.step": 23,    # resident-loop serve step (payload=device step,
    # aux=active-slot bitmask — the slot lanes of the step, ISSUE 13)
    "serve.poll": 24,    # resident-loop ring boundary drain (payload=
    # records consumed at this boundary, aux=records still pending)
    "serve.idle": 25,    # resident-loop idle poll (nothing active, ring
    # pending but gated — payload=device step)
}
_REGION_NAMES = {v: k for k, v in REGIONS.items()}

# Attribution taxonomy (trace/attribution.py): how each region's span
# time is classified. Regions absent here are structural (instants).
REGION_CLASS = {
    "a2a.local": "dma_wait",
    "a2a.wait": "sem_wait",
    "a2a.meta": "dma_wait",
    "ag.ring_wait": "sem_wait",
    "ag.a_wait": "dma_wait",
    "rs.partial": "compute",
    "rs.credit": "sem_wait",
    "rs.hop": "sem_wait",
    "mega.task": "compute",
    "mega.sb_wait": "sem_wait",
    "ep.ffn_chunk": "compute",
    "fp.wait": "sem_wait",
    "fp.fold": "compute",
    "serve.step": "compute",
}

# ep.phase payload codes
PHASE_DISPATCH = 1
PHASE_FFN = 2
PHASE_COMBINE = 3

# Shared verify/trace event taxonomy: which trace region OBSERVES each
# static-verifier op kind at run time, per instrumented protocol. The
# static HB engine (verify/engine.py) proves ordering over "put" and
# "wait_recv" ops; the trace subsystem measures the same events as
# "a2a.send" instants and "a2a.wait" spans — tests/test_verify.py
# cross-validates the verifier's delivery edges against the
# a2a_step_waits replay through this table, so the two subsystems can
# never silently disagree about what a protocol event is.
VERIFY_OP_REGIONS = {
    "all_to_all_chunked": {"put": "a2a.send", "wait_recv": "a2a.wait"},
    "allgather_gemm": {"wait_recv": "ag.ring_wait"},
    "gemm_reduce_scatter": {"wait": "rs.credit", "wait_recv": "rs.hop"},
    "flash_prefill": {"put": "fp.send", "wait_recv": "fp.wait"},
}


def region_id(name: str) -> int:
    return REGIONS[name]


def region_name(rid: int) -> str:
    return _REGION_NAMES.get(int(rid), f"region{int(rid)}")


# -- build flag (host side) ---------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TraceBuild:
    """Active trace build: kernels constructed while a build is active
    compile the record stores in (an extra trailing SMEM output on each
    instrumented kernel); otherwise they compile to exactly the
    uninstrumented program."""

    cap: int = 512
    clock: int = CLOCK_SEQ


_BUILD: Optional[TraceBuild] = None

# the fusion plan whose execution the trace covers (plan.Plan.plan_id):
# models/dense.forward notes it at trace time, collect.assemble stamps
# it onto the Timeline so exported spans carry provenance
_LAST_PLAN: Optional[str] = None


def note_plan(plan_id: Optional[str]) -> None:
    """Record the plan-id of the forward being traced (host-side,
    trace-time). The most recent note wins — one Timeline covers one
    step shape, which holds one Plan."""
    global _LAST_PLAN
    _LAST_PLAN = plan_id


def last_plan() -> Optional[str]:
    return _LAST_PLAN


def active_build() -> Optional[TraceBuild]:
    """The build in effect at TRACE time (None = tracing off). Kernels
    consult this when the pallas_call is constructed — flipping it after
    a jit has cached its executable has no effect on that executable."""
    return _BUILD


@contextlib.contextmanager
def building(cap: int = 512):
    """Enable trace instrumentation for kernels traced inside the block.

    Contract: while a build is active, every instrumented entry point
    returns ONE extra trailing output — its (1+cap, RECORD_WORDS) i32
    trace buffer (per core for the megakernel) — which the caller feeds
    to trace.collect.assemble. Default builds return exactly their
    documented outputs."""
    global _BUILD, _LAST_PLAN
    prev = _BUILD
    _BUILD = TraceBuild(cap=int(cap))
    _LAST_PLAN = None  # a fresh build must not inherit a stale plan-id
    try:
        yield _BUILD
    finally:
        _BUILD = prev


def with_trace(build: Optional["TraceBuild"], res, tbuf=None):
    """Append the trailing trace output an instrumented entry point owes
    its caller under an active build (an empty stream when the executed
    path produced none — fallbacks, n==1 shortcuts). THE one helper for
    that contract; kernels share it instead of hand-rolling the arity
    logic."""
    if build is None:
        return res
    if tbuf is None:
        tbuf = new_stream(build)
    return res + (tbuf,) if isinstance(res, tuple) else (res, tbuf)


def primary(res):
    """The instrumented call's primary result(s), with the trailing
    trace buffer stripped when a build is active. Composite callers that
    do not (yet) thread per-kernel buffers outward wrap their inner
    calls with this so their call graphs stay build-safe — the records
    of that inner call are dropped, nothing else changes."""
    if _BUILD is None:
        return res
    out = res[:-1]
    return out[0] if len(out) == 1 else out


# -- kernel-side API ----------------------------------------------------------


@dataclasses.dataclass
class TraceCtx:
    """In-kernel handle: `buf` is the (lanes, 1+cap, WORDS) or
    (1+cap, WORDS) i32 SMEM output ref, `cur` a small SMEM scratch
    holding the cursor, `lane` the per-core row (None for single-buffer
    kernels)."""

    buf: Any
    cur: Any
    cap: int
    lane: Any = None

    def stamp(self, seq):
        """The injectable in-kernel clock. Seq clock: no extra words
        (t_lo/t_hi stay 0). Hardware cycle counters hook in here."""
        del seq
        return None

    def _row(self, r):
        return (self.buf.at[self.lane, r] if self.lane is not None
                else self.buf.at[r])

    def _store(self, r, w, v):
        if self.lane is not None:
            self.buf[self.lane, r, w] = v
        else:
            self.buf[r, w] = v


def out_shape(build: TraceBuild, lanes: int = 0):
    """ShapeDtypeStruct of the kernel's trace output (lanes=0: single
    buffer; >0: one buffer per core)."""
    shape = (1 + build.cap, RECORD_WORDS)
    if lanes:
        shape = (lanes,) + shape
    return jax.ShapeDtypeStruct(shape, jnp.int32)


def out_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def cursor_scratch():
    return pltpu.SMEM((2,), jnp.int32)


def make_ctx(build: Optional[TraceBuild], buf_ref, cur_ref,
             lane=None) -> Optional[TraceCtx]:
    if build is None:
        return None
    return TraceCtx(buf=buf_ref, cur=cur_ref, cap=build.cap, lane=lane)


def init_ctx(ctx: Optional[TraceCtx], rank=0, lane_id=0,
             stream: int = 0) -> None:
    """Write the header and zero the cursor. Must run before the first
    emit of the (core's) buffer: SMEM scratch and output memory are NOT
    zero-initialized (the interpreter leaves an int32-min sentinel, and
    Mosaic leaves garbage) — decode trusts only rows the header counts."""
    if ctx is None:
        return
    ctx.cur[0] = 0
    ctx._store(0, 0, MAGIC)
    ctx._store(0, 1, 0)
    ctx._store(0, 2, ctx.cap)
    ctx._store(0, 3, jnp.asarray(rank, jnp.int32))
    ctx._store(0, 4, jnp.asarray(lane_id, jnp.int32))
    ctx._store(0, 5, CLOCK_SEQ)
    ctx._store(0, 6, stream)
    ctx._store(0, 7, 0)


def emit(ctx: Optional[TraceCtx], region: int, kind: int, payload=0,
         aux=0) -> None:
    """Append one record (drop + count when the buffer is full). A
    trace-time no-op when ctx is None — the uninstrumented program."""
    if ctx is None:
        return
    idx = ctx.cur[0]

    @pl.when(idx < ctx.cap)
    def _write():
        r = idx + 1
        ctx._store(r, 0, region)
        ctx._store(r, 1, kind)
        ctx._store(r, 2, idx)
        ctx._store(r, 3, jnp.asarray(payload, jnp.int32))
        t = ctx.stamp(idx)
        ctx._store(r, 4, 0 if t is None else t[0])
        ctx._store(r, 5, 0 if t is None else t[1])
        ctx._store(r, 6, jnp.asarray(aux, jnp.int32))
        ctx._store(r, 7, 0)

    ctx.cur[0] = idx + 1
    ctx._store(0, 1, idx + 1)


def instant(ctx: Optional[TraceCtx], region: int, payload=0,
            aux=0) -> None:
    emit(ctx, region, KIND_INSTANT, payload, aux)


@contextlib.contextmanager
def span(ctx: Optional[TraceCtx], region: int, payload=0, aux=0):
    """BEGIN on enter, END on exit — trace-time sugar (kernel bodies are
    python, so the context manager costs nothing at run time)."""
    emit(ctx, region, KIND_BEGIN, payload, aux)
    yield
    emit(ctx, region, KIND_END, payload, aux)


# -- host/jit-level marks (pure jnp — no kernels, no callbacks) ---------------


def new_stream(build: TraceBuild, stream: int = 0, rank=None):
    """A mark stream: the same (1+cap, WORDS) buffer layout as a value
    threaded functionally through jit-level code (XLA ops between
    kernels — e.g. the per-chunk FFN of the EP pipeline). Works under
    any partitioner and on hardware: marks are dynamic_update_slice, not
    callbacks."""
    buf = jnp.zeros((1 + build.cap, RECORD_WORDS), jnp.int32)
    hdr = jnp.array(
        [MAGIC, 0, build.cap, -1, 0, CLOCK_SEQ, stream, 0], jnp.int32)
    buf = buf.at[0].set(hdr)
    if rank is not None:
        buf = buf.at[0, 3].set(jnp.asarray(rank, jnp.int32))
    return buf


def mark(buf, region: int, kind: int = KIND_INSTANT, payload=0, aux=0,
         token=None):
    """Append a record to a mark stream; returns the updated stream.
    `token`: any scalar the mark must execute after — folded in as a
    zero so the data dependency (not a side effect) carries ordering.
    No-op (returns None) when buf is None."""
    if buf is None:
        return None
    idx = buf[0, 1]
    cap = buf.shape[0] - 1
    payload = jnp.asarray(payload, jnp.int32)
    if token is not None:
        payload = payload + (jnp.asarray(token).astype(jnp.int32) * 0)
    row = jnp.stack([
        jnp.asarray(region, jnp.int32), jnp.asarray(kind, jnp.int32),
        idx, payload, jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32),
        jnp.asarray(aux, jnp.int32), jnp.zeros((), jnp.int32),
    ])
    # saturating semantics, same as the device buffers: a full stream
    # drops the record (the header count keeps counting)
    at = jnp.where(idx < cap, idx + 1, cap)
    keep = (idx < cap)[None]
    cur = jax.lax.dynamic_slice(buf, (at, 0), (1, RECORD_WORDS))
    new = jnp.where(keep, row[None], cur)
    buf = jax.lax.dynamic_update_slice(buf, new, (at, 0))
    return buf.at[0, 1].set(idx + 1)
