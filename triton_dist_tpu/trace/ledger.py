"""Per-request attribution ledger — the request-scoped tier of the
observability story (ISSUE 13, docs/observability.md "Request-scoped
attribution").

Every earlier observability surface is kernel- or step-scoped: trace
spans name a region, obs stat rows name a kernel, the scheduler's
metrics name the fleet. This module folds them along the REQUEST axis —
the unit users experience latency in — using three sources the serve
plane already records:

  phase accumulators   serve.Request.phase_ns: wall time per lifecycle
                       phase (queued / prefill / decode), accumulated
                       by the scheduler at every phase close. Because
                       phases are contiguous from submit to finish,
                       their sum CLOSES against the request's
                       submit->finish wall time — `check_close` pins
                       |close_frac - 1| <= tol (default 0.05; the slack
                       is the handful of bookkeeping instructions
                       between a phase close and the next open).
  slot history         scheduler.history: per-step (host loop) /
                       per-window (resident) entries carrying wall
                       time, the slot->request map, and — when the
                       resident loop was built under
                       obs.stats.building() — the decoded
                       resident-window stat rows (obs.stats.WMAGIC
                       slot lanes). Device wall time splits across a
                       step's occupants equally; across a window's by
                       the slot lanes' per-slot step counts (launch-
                       occupant attribution — a slot that turns over
                       mid-window credits its launch occupant; that is
                       the documented resolution of the ring contract).
  output-ring metadata mega.ring.summarize_records: per-request
                       emits / step bounds / retirement reason.

Products: a JSON-able ledger document (magic "tdt-req-ledger",
rendered by `scripts/trace_report.py --requests`), a per-request
Perfetto export (`write_request_trace`: ONE process track per
request), and `attribute_branch_time` — the per-request split of
`trace.attribution.task_time_by_branch`'s per-branch buckets.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List

LEDGER_MAGIC = "tdt-req-ledger"

# phases whose accumulated spans must close against wall time.
# migrate/admit are the disaggregated prefill/decode legs (ISSUE 18,
# xslice/): 0 on a single-slice scheduler, and on the in-process
# DisaggPair the passenger Request accumulates all five across both
# schedulers, so the prefill-side ledger closes the full TTFT
# decomposition — prefill-slice time + migration + decode admission.
_PHASES = ("queued", "prefill", "migrate", "admit", "decode")


def _us(ns: int) -> float:
    return round(ns / 1e3, 2)


def build_ledger(sch, tol: float = 0.05) -> dict:
    """Assemble the ledger document from a serve.Scheduler. Covers
    every submitted request; `close_frac` (phase sum / wall) is
    computed for DONE requests — the tier-1 close pin applies to them
    (`check_close`)."""
    device_us = _device_time_by_request(sch)
    rows: List[dict] = []
    for req in sch.requests:
        phases = dict(req.phase_ns)
        if req.done and getattr(req, "_phase", None) is not None:
            # a request finished without a phase close (e.g. cancelled
            # while queued): credit the open phase up to finish time so
            # the ledger still closes
            name, t0 = req._phase
            phases[name] = phases.get(name, 0) + max(
                0, req.t_finish - t0)
        wall_ns = (req.t_finish - req.t_submit) if req.done else 0
        covered = sum(phases.get(p, 0) for p in _PHASES)
        close = (covered / wall_ns) if wall_ns > 0 else None
        rows.append({
            "request_id": req.request_id,
            "state": req.state.value,
            "reason": req.finish_reason,
            "wall_us": _us(wall_ns) if req.done else None,
            "ttft_us": (round(req.ttft_us(), 2)
                        if req.ttft_us() is not None else None),
            "tpot_us": (round(req.tpot_us(), 2)
                        if req.tpot_us() is not None else None),
            "queued_us": _us(phases.get("queued", 0)),
            "inject_wait_us": _us(req.inject_wait_ns),
            "prefill_us": _us(phases.get("prefill", 0)),
            "migrate_us": _us(phases.get("migrate", 0)),
            "admit_us": _us(phases.get("admit", 0)),
            "decode_us": _us(phases.get("decode", 0)),
            # spec_verify is a SUB-BUCKET of decode (ISSUE 14): the
            # wall share of decode steps that ran a verify row. It is
            # NOT added to the close sum — the decode phase already
            # contains it, so the close-against-wall contract (and its
            # tol) is untouched. 0 on unspecced runs and in resident
            # mode (windows are step-unresolved; the counters still
            # land in spec_steps).
            "spec_verify_us": _us(req.spec_verify_ns),
            "spec_steps": req.n_spec_steps,
            # a prefix-cache hit skips [0, prefix_hit_tokens) of
            # prefill entirely: hit requests report prefill_us ~= 0
            # by construction (the phase only spans the residual
            # chunks), which is the TTFT collapse the cache buys
            "prefix_hit_tokens": req.prefix_len,
            "close_frac": (round(close, 4)
                           if close is not None else None),
            "tokens_out": len(req.out_tokens),
            "prefill_chunks": req.n_prefill_chunks,
            "decode_steps": max(
                0, req.n_device_steps - req.n_prefill_chunks),
            "device_steps": req.n_device_steps,
            "windows": req.n_windows,
            "evictions": req.n_evictions,
            "device_share_us": round(
                device_us.get(req.request_id, 0.0), 2),
        })
    return {
        "magic": LEDGER_MAGIC,
        "mode": "resident" if sch.resident else "host",
        "chunk": sch.chunk,
        "tol": tol,
        "history_dropped": sch.history_dropped,
        "requests": rows,
    }


def _device_time_by_request(sch) -> Dict[int, float]:
    """Device wall time (us) per request from the slot history: step
    entries split equally across occupants; window entries split by
    the stat lanes' per-slot step counts when the loop was metered,
    else equally across the launch occupants."""
    out: Dict[int, float] = {}
    for e in sch.history:
        dur_us = (e["t1"] - e["t0"]) / 1e3
        slots = e.get("slots") or {}
        if not slots:
            continue
        if e["kind"] == "step":
            share = dur_us / len(slots)
            for rid, _phase, _n in slots.values():
                out[rid] = out.get(rid, 0.0) + share
            continue
        # window entry
        weights: Dict[int, float] = {}
        ws = e.get("stats")
        if ws is not None:
            lane_steps = {lane.slot: lane.steps for lane in ws.slots}
            for slot, rid in slots.items():
                weights[rid] = weights.get(rid, 0.0) + lane_steps.get(
                    slot, 0)
        if not weights or not any(weights.values()):
            weights = {rid: 1.0 for rid in slots.values()}
        total = sum(weights.values())
        for rid, w in weights.items():
            out[rid] = out.get(rid, 0.0) + dur_us * w / total
    return out


def check_close(ledger: dict, states=("finished",)) -> List[str]:
    """The ledger close contract: for every request in one of `states`,
    |close_frac - 1| <= tol — the decomposed phase times sum to the
    submit->finish wall time. Returns problem strings (empty = closed);
    the tier-1 pin asserts empty on a traced+metered resident run."""
    tol = float(ledger.get("tol", 0.05))
    problems = []
    for row in ledger["requests"]:
        if row["state"] not in states:
            continue
        close = row.get("close_frac")
        if close is None:
            problems.append(
                f"req{row['request_id']}: no close_frac (phases never "
                "closed against wall time)")
        elif abs(close - 1.0) > tol:
            problems.append(
                f"req{row['request_id']}: phase sum closes at "
                f"{close:.4f} of wall (tol {tol})")
    return problems


def check_ledger(doc: dict) -> dict:
    """Validate a ledger document (the trace_report strictness
    contract); returns it. Raises ValueError on malformed input."""
    if not isinstance(doc, dict) or doc.get("magic") != LEDGER_MAGIC:
        raise ValueError(
            f"not a request ledger (magic="
            f"{doc.get('magic') if isinstance(doc, dict) else None!r} "
            f"!= {LEDGER_MAGIC!r})")
    reqs = doc.get("requests")
    if not isinstance(reqs, list):
        raise ValueError("ledger 'requests' missing or not a list")
    for i, row in enumerate(reqs):
        if not isinstance(row, dict):
            raise ValueError(f"ledger requests[{i}] is not an object")
        for key in ("request_id", "state", "queued_us", "prefill_us",
                    "decode_us", "device_steps"):
            if key not in row:
                raise ValueError(f"ledger requests[{i}] missing {key!r}")
    return doc


def write_ledger(ledger: dict, path: str) -> str:
    check_ledger(ledger)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(ledger, f, indent=1)
    return path


def load_ledger(path: str) -> dict:
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise ValueError(f"{path}: {e}") from e
    return check_ledger(doc)


def format_requests_table(ledger: dict) -> str:
    """The per-request table `scripts/trace_report.py --requests`
    prints: one row per request, decomposition columns in ms."""
    cols = (f"{'req':>5} {'state':<10} {'wall_ms':>9} {'queued':>8} "
            f"{'inject':>8} {'prefill':>8} {'migrate':>8} "
            f"{'admit':>8} {'decode':>9} {'close':>6} "
            f"{'ttft_ms':>8} {'tok':>4} {'steps':>6} {'win':>4} "
            f"{'dev_ms':>8}")
    lines = [cols]

    def ms(v):
        return "-" if v is None else f"{v / 1e3:.1f}"

    for row in ledger["requests"]:
        close = row.get("close_frac")
        lines.append(
            f"{row['request_id']:>5} {row['state']:<10} "
            f"{ms(row.get('wall_us')):>9} {ms(row['queued_us']):>8} "
            f"{ms(row.get('inject_wait_us', 0)):>8} "
            f"{ms(row['prefill_us']):>8} "
            f"{ms(row.get('migrate_us', 0)):>8} "
            f"{ms(row.get('admit_us', 0)):>8} "
            f"{ms(row['decode_us']):>9} "
            f"{'-' if close is None else format(close, '.3f'):>6} "
            f"{ms(row.get('ttft_us')):>8} {row.get('tokens_out', 0):>4} "
            f"{row['device_steps']:>6} {row.get('windows', 0):>4} "
            f"{ms(row.get('device_share_us', 0)):>8}")
    if ledger.get("history_dropped"):
        lines.append(f"(history truncated: {ledger['history_dropped']} "
                     "oldest entries dropped — device shares are lower "
                     "bounds)")
    return "\n".join(lines)


def attribute_branch_time(ledger: dict, tl, branch_keys=None,
                          stream: str = "mega") -> Dict[int, dict]:
    """Split `attribution.task_time_by_branch`'s per-branch buckets
    across requests, proportional to each request's device-step share
    — the per-request view of the world=1 branch ledger (a latency
    regression names its branch AND its victim). Returns
    {request_id: {branch_key: time}}; the proportional rule is the
    documented resolution (branch spans carry no request tag — the
    megakernel runs whole steps)."""
    from triton_dist_tpu.trace.attribution import task_time_by_branch

    buckets = task_time_by_branch(tl, branch_keys, stream=stream)
    steps = {row["request_id"]: row["device_steps"]
             for row in ledger["requests"]}
    total = sum(steps.values())
    if total == 0:
        return {}
    return {
        rid: {key: d["time"] * n / total for key, d in buckets.items()}
        for rid, n in steps.items() if n > 0
    }


def write_request_trace(sch, path: str) -> str:
    """Perfetto export with ONE PROCESS TRACK PER REQUEST: every
    req<N>/<phase> span of the scheduler's host-span log lands in its
    request's own track (instants — evictions, quarantines — as 'i'
    events), with the scheduler-level spans (step retries, resident
    windows) in a 'serve' track beside them. Loads at ui.perfetto.dev
    next to the in-kernel traces (same format tag)."""
    spans = list(sch._spans)
    # a live export must not lose in-flight requests: each OPEN phase
    # (req._phase — closed spans land in sch._spans only at phase end)
    # is exported as a zero-length instant at its open stamp
    for req in sch.requests:
        ph = getattr(req, "_phase", None)
        if ph is not None:
            name, t0 = ph
            spans.append((f"req{req.request_id}/{name}", t0, t0))
    t_all = [t for _n, t0, t1 in spans for t in (t0, t1)]
    t_base = min(t_all) if t_all else 0
    req_ids = sorted({row.request_id for row in sch.requests})
    pid_of = {rid: i + 2 for i, rid in enumerate(req_ids)}
    events = [{"ph": "M", "pid": 1, "name": "process_name",
               "args": {"name": "serve"}}]
    for rid in req_ids:
        events.append({"ph": "M", "pid": pid_of[rid],
                       "name": "process_name",
                       "args": {"name": f"req{rid}"}})
    for name, t0, t1 in spans:
        pid, label = 1, name
        if name.startswith("req"):
            head, _, rest = name.partition("/")
            try:
                rid = int(head[3:])
            except ValueError:
                rid = None
            if rid in pid_of:
                pid, label = pid_of[rid], rest or name
        ts = (t0 - t_base) / 1e3
        if t1 > t0:
            events.append({"ph": "X", "pid": pid, "tid": 1,
                           "name": label, "cat": "request",
                           "ts": ts, "dur": (t1 - t0) / 1e3})
        else:
            events.append({"ph": "i", "s": "t", "pid": pid, "tid": 1,
                           "name": label, "ts": ts})
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "label": "serve-requests",
            "clock": "host",
            "format": "triton_dist_tpu.trace v1",
        },
    }
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    return path
