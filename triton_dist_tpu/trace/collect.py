"""Host-side trace assembly: device buffers -> a merged Timeline.

The device side (trace/events.py) hands back fixed-capacity i32 buffers
of (region, kind, seq, payload) records on the deterministic seq clock.
This module decodes them, derives spans, and assigns every record a
VIRTUAL TIME:

    vtime(record) = seq + sum of straggle payloads of earlier records

i.e. one tick per record, plus any injected skew (shmem.straggler_delay
provocations ride along as a "straggle" instant whose payload is the
delay on the delayed rank and 0 elsewhere — emitted on EVERY rank so
record sequences stay aligned across ranks). On the lockstep CPU
interpreter this is the honest clock: the discharge model executes the
mesh as synchronous rendezvous waves, so per-rank wall time carries no
per-source information — but the PROTOCOL events (which chunk was sent
when, relative to the injected skew) are real, and replaying them
(attribution.a2a_step_waits) reproduces exactly the per-step waits a
delivery-granular consumer would observe. On hardware, the same
pipeline runs on real stamps once TraceCtx.stamp is wired to a cycle
counter (events.py clock notes).

Wall-clock anchoring: TraceSession.host_span records python-level
perf_counter_ns spans around the traced calls; export.to_chrome_trace
places device streams at their host anchors so the Perfetto view lines
up with real time (per-region host timing — the documented compiled-
mode reconstruction).
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from triton_dist_tpu.trace import events as ev


@dataclasses.dataclass(frozen=True)
class Event:
    stream: str
    rank: int
    lane: int
    region: int
    kind: int
    seq: int
    payload: int
    aux: int
    t: float  # vticks (seq clock) — see module doc


@dataclasses.dataclass(frozen=True)
class Span:
    stream: str
    rank: int
    lane: int
    region: int
    payload: int
    aux: int
    t0: float
    t1: float

    @property
    def dur(self) -> float:
        return self.t1 - self.t0


@dataclasses.dataclass
class Timeline:
    events: List[Event]
    spans: List[Span]
    drops: Dict[Tuple[str, int, int], int]  # (stream, rank, lane) -> n
    host_spans: List[Tuple[str, int, int]]  # (name, t0_ns, t1_ns)
    label: str = "trace"
    plan_id: Optional[str] = None  # fusion plan provenance (plan.Plan)

    def streams(self):
        return sorted({e.stream for e in self.events})

    def ranks(self, stream: str):
        return sorted({e.rank for e in self.events if e.stream == stream})

    def select(self, stream: str, rank: Optional[int] = None,
               lane: Optional[int] = None) -> List[Event]:
        return [e for e in self.events
                if e.stream == stream
                and (rank is None or e.rank == rank)
                and (lane is None or e.lane == lane)]

    def spans_of(self, stream: str, rank: Optional[int] = None,
                 lane: Optional[int] = None,
                 region: Optional[str] = None) -> List[Span]:
        rid = ev.REGIONS[region] if isinstance(region, str) else region
        return [s for s in self.spans
                if s.stream == stream
                and (rank is None or s.rank == rank)
                and (lane is None or s.lane == lane)
                and (rid is None or s.region == rid)]


class MalformedTrace(ValueError):
    """A buffer without the header magic / an unpairable BEGIN-END
    structure. scripts/trace_report.py turns this into exit 1."""


def _decode_buffer(stream: str, buf: np.ndarray) -> Tuple[List[Event],
                                                          int]:
    """One (1+cap, WORDS) buffer -> (events in seq order, n_dropped)."""
    hdr = buf[0]
    if int(hdr[0]) != ev.MAGIC:
        raise MalformedTrace(
            f"stream {stream!r}: header magic {int(hdr[0]):#x} != "
            f"{ev.MAGIC:#x} (not a trace buffer, or clobbered)")
    count, cap = int(hdr[1]), int(hdr[2])
    rank, lane = int(hdr[3]), int(hdr[4])
    kept = min(count, cap)
    out: List[Event] = []
    delay = 0.0
    for r in range(1, 1 + kept):
        region, kind, seq, payload, _tl, _th, aux, _ = (
            int(x) for x in buf[r])
        t = float(seq) + delay
        if region == ev.REGIONS["straggle"]:
            delay += float(payload)
        out.append(Event(stream, rank, lane, region, kind, seq, payload,
                         aux, t))
    return out, max(0, count - cap)


def _pair_spans(events: List[Event],
                allow_unclosed: bool = False) -> List[Span]:
    """Match BEGIN/END within one buffer by (region, payload, aux) —
    span identity is carried on both records, so nesting of DIFFERENT
    spans is free and same-key spans pair LIFO. An unclosed BEGIN is
    malformed unless `allow_unclosed` (its END was dropped past the
    buffer cap — the saturating-drop casualty)."""
    stacks: Dict[tuple, List[Event]] = {}
    spans: List[Span] = []
    for e in events:
        key = (e.region, e.payload, e.aux)
        if e.kind == ev.KIND_BEGIN:
            stacks.setdefault(key, []).append(e)
        elif e.kind == ev.KIND_END:
            st = stacks.get(key)
            if not st:
                raise MalformedTrace(
                    f"stream {e.stream!r} rank {e.rank}: END without "
                    f"BEGIN for region {ev.region_name(e.region)} "
                    f"payload={e.payload} aux={e.aux} at seq {e.seq}")
            b = st.pop()
            spans.append(Span(e.stream, e.rank, e.lane, e.region,
                              e.payload, e.aux, b.t, e.t))
    if not allow_unclosed:
        for key, st in stacks.items():
            if st:
                e = st[0]
                raise MalformedTrace(
                    f"stream {e.stream!r} rank {e.rank}: BEGIN without "
                    f"END for region {ev.region_name(e.region)} "
                    f"payload={e.payload} (and no drops to explain it)")
    return spans


def assemble(buffers: Dict[str, np.ndarray],
             label: str = "trace",
             host_spans=None,
             plan_id: Optional[str] = None) -> Timeline:
    """Build a Timeline from {stream: buffer array}. Each value may be
    one buffer (1+cap, WORDS), a stack (k, 1+cap, WORDS) — e.g. the
    shard_map-stacked per-rank outputs — or any higher-rank stack, which
    is flattened over the leading dims. Buffers whose header count is 0
    are kept (empty streams are legal); a missing magic raises
    MalformedTrace."""
    all_events: List[Event] = []
    all_spans: List[Span] = []
    drops: Dict[Tuple[str, int, int], int] = {}
    for stream, arr in buffers.items():
        a = np.asarray(arr)
        if a.ndim < 2 or a.shape[-1] != ev.RECORD_WORDS:
            raise MalformedTrace(
                f"stream {stream!r}: shape {a.shape} is not a record "
                f"buffer (minor dim must be {ev.RECORD_WORDS})")
        flat = a.reshape(-1, a.shape[-2], a.shape[-1]) if a.ndim > 2 \
            else a[None]
        for b in flat:
            evs, dropped = _decode_buffer(stream, b)
            spans = _pair_spans(evs, allow_unclosed=dropped > 0)
            all_events.extend(evs)
            all_spans.extend(spans)
            if evs or dropped:
                key = (stream, evs[0].rank if evs else -1,
                       evs[0].lane if evs else 0)
                drops[key] = drops.get(key, 0) + dropped
    all_events.sort(key=lambda e: (e.stream, e.rank, e.lane, e.seq))
    if plan_id is None:
        # the plan noted by the forward traced under this build, if any
        plan_id = ev.last_plan()
    return Timeline(all_events, all_spans, drops,
                    list(host_spans or []), label=label,
                    plan_id=plan_id)


class TraceSession:
    """Host-side bookkeeping around traced calls: python-level wall
    spans (the per-region host timing that anchors device streams in the
    export) and a one-stop assemble."""

    def __init__(self, label: str = "trace"):
        self.label = label
        self.host_spans: List[Tuple[str, int, int]] = []
        self._t0 = time.perf_counter_ns()

    @contextlib.contextmanager
    def host_span(self, name: str):
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            self.host_spans.append((name, t0, time.perf_counter_ns()))

    def assemble(self, buffers: Dict[str, np.ndarray]) -> Timeline:
        return assemble(buffers, label=self.label,
                        host_spans=self.host_spans)


@contextlib.contextmanager
def tracing(label: str = "trace", cap: int = 512):
    """`with tracing("x") as (build, session):` — enables the device
    build AND opens a host session in one step."""
    with ev.building(cap=cap) as build:
        yield build, TraceSession(label)
