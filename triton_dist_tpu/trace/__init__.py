"""triton_dist_tpu.trace — in-kernel event tracing, stall attribution,
and Perfetto export for the overlapping kernels.

The predicted-vs-measured loop: `perf_model` and `mega.scheduler`
PREDICT overlap quality (per-queue scoreboard stalls, per-chunk A2A/FFN
exposure); this subsystem MEASURES it — per-core ring buffers of i32
(region, kind, seq, payload) records written inside the kernels,
assembled into a timeline, classified into compute / sem_wait /
dma_wait / idle, exported as Perfetto-loadable JSON, and diffed against
`scheduler.predicted_stalls` queue by queue.

Quick start (docs/observability.md has the full story):

    from triton_dist_tpu import trace

    with trace.tracing("ep_moe") as (build, session):
        # instrumented entry points now return one extra trailing
        # trace-buffer output
        out, bufs = jitted_overlapped_moe(x)
    tl = session.assemble({k: np.asarray(v) for k, v in bufs.items()})
    print(trace.format_table(tl))
    trace.write_trace(tl, "/tmp/ep_moe.trace.json")

Tracing is strictly opt-in: with no active `building()` block, the
instrumented kernels trace byte-identical programs with unchanged
`pallas_call_count()` (tests/test_trace.py enforces both).
"""

from triton_dist_tpu.trace.events import (  # noqa: F401
    KIND_BEGIN,
    KIND_END,
    KIND_INSTANT,
    RECORD_WORDS,
    REGIONS,
    TraceBuild,
    TraceCtx,
    active_build,
    building,
    instant,
    mark,
    new_stream,
    primary,
    region_id,
    region_name,
    span,
    with_trace,
)
from triton_dist_tpu.trace.collect import (  # noqa: F401
    Event,
    MalformedTrace,
    Span,
    Timeline,
    TraceSession,
    assemble,
    tracing,
)
from triton_dist_tpu.trace.attribution import (  # noqa: F401
    a2a_step_waits,
    classify,
    compare_predicted,
    format_table,
    fp_seg_waits,
    per_region,
    prefetch_hit_rate,
    task_time_by_branch,
    wire_send_bytes,
)
from triton_dist_tpu.trace.export import (  # noqa: F401
    group_profile,
    load_trace_json,
    merge_traces,
    to_chrome_trace,
    write_trace,
)
from triton_dist_tpu.trace.ledger import (  # noqa: F401
    LEDGER_MAGIC,
    attribute_branch_time,
    build_ledger,
    check_close,
    check_ledger,
    format_requests_table,
    load_ledger,
    write_ledger,
    write_request_trace,
)
