"""Perf-trend regression sentinel — the artifact series grown teeth
(ISSUE 13, ROADMAP item 1's measurement debt made standing).

`scripts/check_perf_claims.py` guards each claim against the NEWEST
artifact carrying its key — a point check against a hand-maintained
band. This module reads the FULL BENCH_r*.json / MULTICHIP_r*.json
series (through check_perf_claims' own reader, `artifact_series` —
reused, not re-implemented, so the two tools can never disagree about
what an artifact says) and flags what a band cannot see:

  trend_regression   the newest point of a (key, rig) series is worse
                     than the MEDIAN of its prior points by more than
                     `trend_tol` (default 25%) — a drift no band edge
                     has been crossed by yet.
  watermark_break    the newest point is worse than the series'
                     BEST-EVER point by more than `watermark_tol`
                     (default 50%) — a capability the repo once
                     demonstrated and lost.
  band_violation     the newest point contradicts a `[perf:...]` claim
                     band (check_perf_claims' contradiction, restated
                     per-series so the report is one document).
  missing_family     a key a rig measured in an earlier round is absent
                     from that rig's newest artifact — an arm that
                     silently errored out of the schema.
  multichip_*        the MULTICHIP series' ok/rc/skipped state went
                     backwards.

plus non-fatal NOTES: `band_drift` (inside the band but within
`drift_margin` of the adverse edge) and `improvement` (newest beats the
best prior point).

RIG-AWARENESS is the load-bearing part: BENCH_r06 comes from the
reduced cpu-world1 rig (docs/performance.md "Rigs") and its values are
incomparable with the r02-r05 TPU points, so every series is keyed
(key, rig) — per-key newest-wins within a rig, never across. Keys an
artifact quarantines under `parsed.cpu_incomparable` land in a
`<rig>-quarantine` series that is tracked but NEVER flagged.

Direction: most keys are latency/ratio shaped (lower is better);
throughput keys (`*tokens_per_s*`, the serving speedup ratios) invert.
Neutral keys (config echoes like window steps, the model-derived HBM
floors) are tracked, never flagged.

Acknowledgement: a flagged regression that is UNDERSTOOD gets an entry
in ACKNOWLEDGED ((key, flag kind) -> reason — kind-scoped, so muting a
known trend drift never mutes a future watermark break on the same
key). Acknowledged flags stay in the report (with their reason inline)
but do not fail the CI gate (`scripts/perf_trend.py` exit 1 is
UNacknowledged flags only) — the PENDING_FIRST_ARTIFACT pattern: the
bookkeeping lives next to the rule, and an ack whose flag no longer
fires is reported as a stale_ack note so the ledger shrinks back.
"""

from __future__ import annotations

import importlib.util
import json
import os
import statistics
from typing import Dict, List, Optional, Tuple

TREND_MAGIC = "tdt-perf-trend"

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# keys that mirror the per-round headline metric (whose NAME changes
# round to round) — their cross-round series compares different
# quantities, so they are skipped entirely
SKIP_KEYS = {"value", "vs_baseline"}

# tracked in the report, never flagged: config echoes, model-derived
# constants, pressure stats whose "direction" is workload-shaped
NEUTRAL_KEYS = {
    "serve_resident_window_steps",
    "serve_resident_ring_depth_max", "serve_resident_ring_depth_mean",
    "ep_moe_chunks", "ep_moe_drop_frac",
    "mega_8b_hbm_floor_ms", "mega_32b_hbm_floor_ms",
    "faults_guard_trips", "obs_stat_events",
    # planner parity ratios sit at ~1.0 by construction (bit-identical
    # programs; tests/test_plan.py) — movement is host-timer noise,
    # not a regression direction
    "plan_vs_hand_prefill", "plan_vs_hand_decode",
}

# throughput-shaped keys: HIGHER is better (everything else numeric
# defaults to lower-is-better — latency, time ratios, overhead fracs)
HIGHER_IS_BETTER_SUFFIXES = ("tokens_per_s",)
HIGHER_IS_BETTER = {
    "serve_vs_seq_tokens",        # batched/sequential throughput ratio
    "serve_resident_vs_hostloop",  # resident/host-loop throughput ratio
    "spec_vs_plain_tokens",       # spec/plain-decode throughput ratio
    "spec_accept_rate",           # accepted/proposed draft tokens
    "plan_recover_misroute_ratio",  # misrouted/planned — the
                                    # regression the planner removes
}

# (key, flag kind) -> reason. The scope is deliberately NARROW: an ack
# mutes exactly one flag class on one key — a future watermark_break or
# band_violation on the same key still fails the gate. An acknowledged
# flag reports WITH its reason; an ack that matched NO flag is itself
# reported as a stale_ack note (the series recovered — delete the
# entry).
ACKNOWLEDGED = {
    ("a2a_dispatch_us", "trend_regression"): (
        "retired key: renamed a2a_dispatch_world1_us in round 6 "
        "(round-5 verdict — the bare name beside the 32-rank DeepEP "
        "baseline invited a false read). The r04->r05 +39% move is on "
        "the dead alias; the world1 key restarts the series on the "
        "next default-rig artifact."),
    ("allreduce_wire_native_us", "watermark_break"): (
        "2-core rig-local absolute arm, not a codec change: r08 read "
        "the native ring at 1221us vs the 798-819us of r06/r07 while "
        "the fp8/int8 ABSOLUTE arms stayed flat (~31ms/~16ms — their "
        "vs_native ratios moved inversely, 39->26 and 19->12, exactly "
        "as a slow native denominator predicts). The cpu-world1 rig "
        "only claims ratios (docs/performance.md 'Rigs'); the "
        "watermark re-arms on the next artifact inside tolerance."),
    # the round-7 ("sp_prefill_vs_ring", "trend_regression") ack was
    # deleted in round 8: r08 measured the arm back inside tolerance,
    # turning the entry into a stale_ack note (the series recovered)
    ("plan_decode_ms", "trend_regression"): (
        "2-core rig-local absolute arm, not a planner change: r09 "
        "read the planned decode step at 11.4ms vs the 7.5-8.8ms of "
        "r07/r08 while the SAME-RUN hand-routed denominator moved "
        "with it (plan_vs_hand_decode 0.79, the best ratio of the "
        "series — a slow machine, not a slow plan; the r09 routing "
        "is byte-identical to r08's committed PLAN_TABLE.json, "
        "plan_report --diff 0 flips). The cpu-world1 rig only claims "
        "ratios (docs/performance.md 'Rigs'); the trend re-arms on "
        "the next artifact inside tolerance."),
}


def higher_is_better(key: str) -> bool:
    return key in HIGHER_IS_BETTER or any(
        s in key for s in HIGHER_IS_BETTER_SUFFIXES)


_CLAIMS_MOD_CACHE: Dict[str, object] = {}


def _claims_mod(repo: str):
    """Load scripts/check_perf_claims.py by path — ITS parsing is the
    one artifact-reading definition (see module doc); the package must
    not fork it, and the script deliberately is not a package. The
    script is taken from the analyzed repo when it ships one, else
    from THIS package's repo (so a synthetic artifact corpus in a bare
    tmp dir still reads through the shared parser). Cached per path:
    one analyze() must read ONE on-disk version of the script."""
    path = os.path.join(repo, "scripts", "check_perf_claims.py")
    if not os.path.isfile(path):
        path = os.path.join(_REPO, "scripts", "check_perf_claims.py")
    mod = _CLAIMS_MOD_CACHE.get(path)
    if mod is None:
        spec = importlib.util.spec_from_file_location(
            "_tdt_check_perf_claims", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _CLAIMS_MOD_CACHE[path] = mod
    return mod


def bench_series(repo: str = _REPO, strict: bool = False
                 ) -> Dict[Tuple[str, str], List[dict]]:
    """(key, rig) -> [{round, label, value}] oldest-first over every
    BENCH_r*.json, via check_perf_claims.artifact_series. Quarantined
    keys (`parsed.cpu_incomparable`) ride under rig
    `<rig>-quarantine`."""
    mod = _claims_mod(repo)
    series: Dict[Tuple[str, str], List[dict]] = {}

    def add(key, rig, rnd, label, value):
        if key in SKIP_KEYS:
            return
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            return
        series.setdefault((key, rig), []).append(
            {"round": rnd, "label": label, "value": float(value)})

    for label, rnd, parsed in mod.artifact_series(repo, strict=strict):
        rig = parsed.get("rig", "default")
        for k, v in parsed.items():
            if k == "cpu_incomparable" and isinstance(v, dict):
                for qk, qv in v.items():
                    add(qk, f"{rig}-quarantine", rnd, label, qv)
                continue
            add(k, rig, rnd, label, v)
    return series


def multichip_series(repo: str = _REPO, strict: bool = False
                     ) -> List[dict]:
    """[{label, round, ok, rc, skipped, n_devices}] oldest-first over
    MULTICHIP_r*.json. strict raises on unreadable/structurally
    malformed artifacts."""
    import glob
    import re

    out = []
    for path in sorted(glob.glob(os.path.join(repo,
                                              "MULTICHIP_r*.json"))):
        label = os.path.basename(path)
        m = re.search(r"MULTICHIP_r(\d+)", label)
        rnd = int(m.group(1)) if m else 0
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            if strict:
                raise ValueError(f"{label}: unreadable artifact: {e}")
            continue
        if not isinstance(doc, dict) or "ok" not in doc \
                or "rc" not in doc:
            if strict:
                raise ValueError(f"{label}: not a MULTICHIP artifact "
                                 "(ok/rc missing)")
            continue
        out.append({
            "label": label, "round": rnd, "ok": bool(doc["ok"]),
            "rc": int(doc["rc"]), "skipped": bool(doc.get("skipped")),
            "n_devices": doc.get("n_devices"),
        })
    return out


def _claim_bands(repo: str) -> Dict[str, Tuple[float, float]]:
    """key -> tightest claimed (lo, hi) over every [perf:...] bracket
    (check_perf_claims.collect_claims reused)."""
    mod = _claims_mod(repo)
    bands: Dict[str, Tuple[float, float]] = {}
    for _rel, key, lo, hi in mod.collect_claims(repo):
        cur = bands.get(key)
        bands[key] = (max(lo, cur[0]) if cur else lo,
                      min(hi, cur[1]) if cur else hi)
    return bands


def _worse_by(newest: float, ref: float, key: str) -> Optional[float]:
    """Relative amount `newest` is WORSE than `ref` (None when the
    comparison is degenerate — a zero reference)."""
    if higher_is_better(key):
        if newest <= 0:
            return None
        return ref / newest - 1.0
    if ref <= 0:
        return None
    return newest / ref - 1.0


def _flag(key, rig, kind, detail) -> dict:
    ack = ACKNOWLEDGED.get((key, kind))
    return {"key": key, "rig": rig, "kind": kind, "detail": detail,
            "acknowledged": ack is not None, "ack": ack}


def analyze(repo: str = _REPO, trend_tol: float = 0.25,
            watermark_tol: float = 0.50, drift_margin: float = 0.05,
            strict: bool = False) -> dict:
    """The sentinel: build the rig-aware series, apply the flag rules
    (module doc), return the report document (magic tdt-perf-trend).
    Deterministic: same artifacts -> same report."""
    series = bench_series(repo, strict=strict)
    bands = _claim_bands(repo)
    flags: List[dict] = []
    notes: List[dict] = []

    # newest round per rig (missing-family needs it)
    newest_round: Dict[str, int] = {}
    newest_label: Dict[str, str] = {}
    for (key, rig), pts in series.items():
        last = pts[-1]
        if last["round"] >= newest_round.get(rig, -1):
            newest_round[rig] = last["round"]
            newest_label[rig] = last["label"]

    for (key, rig), pts in sorted(series.items()):
        if rig.endswith("-quarantine") or key in NEUTRAL_KEYS:
            continue
        newest = pts[-1]["value"]
        at_newest = pts[-1]["round"] == newest_round.get(rig)

        # missing-family: measured before, absent from the rig's
        # newest artifact
        if not at_newest:
            flags.append(_flag(
                key, rig, "missing_family",
                f"last measured in {pts[-1]['label']} "
                f"({pts[-1]['value']}); absent from the {rig} rig's "
                f"newest artifact {newest_label.get(rig)} — the arm "
                "silently dropped out of the schema"))
            continue

        prior = [p["value"] for p in pts[:-1]]
        if prior:
            med = statistics.median(prior)
            best = (max(prior) if higher_is_better(key)
                    else min(prior))
            w_med = _worse_by(newest, med, key)
            w_best = _worse_by(newest, best, key)
            if w_best is not None and w_best > watermark_tol:
                flags.append(_flag(
                    key, rig, "watermark_break",
                    f"newest {newest} is {w_best:+.0%} worse than the "
                    f"best-ever {best} (tol {watermark_tol:.0%})"))
            elif w_med is not None and w_med > trend_tol:
                flags.append(_flag(
                    key, rig, "trend_regression",
                    f"newest {newest} is {w_med:+.0%} worse than the "
                    f"prior median {med} (tol {trend_tol:.0%})"))
            if w_best is not None and w_best < -trend_tol:
                notes.append({
                    "key": key, "rig": rig, "kind": "improvement",
                    "detail": f"newest {newest} beats the best prior "
                              f"{best} by {-w_best:.0%}"})

        band = bands.get(key)
        if band is not None:
            lo, hi = band
            if not (lo <= newest <= hi):
                flags.append(_flag(
                    key, rig, "band_violation",
                    f"newest {newest} outside the claimed band "
                    f"[{lo}, {hi}]"))
            else:
                edge = lo if higher_is_better(key) else hi
                rel = abs(newest - edge) / max(abs(edge), 1e-12)
                if rel < drift_margin:
                    notes.append({
                        "key": key, "rig": rig, "kind": "band_drift",
                        "detail": f"newest {newest} is within "
                                  f"{rel:.1%} of the adverse band edge "
                                  f"{edge} — the next wiggle "
                                  "contradicts the claim"})

    mseries = multichip_series(repo, strict=strict)
    if mseries:
        last = mseries[-1]
        prior_ok = any(m["ok"] for m in mseries[:-1])
        if last["rc"] != 0:
            flags.append(_flag("multichip", "multichip",
                               "multichip_regression",
                               f"{last['label']} exited rc="
                               f"{last['rc']}"))
        if not last["ok"] and prior_ok:
            flags.append(_flag(
                "multichip", "multichip", "multichip_regression",
                f"{last['label']} ok=false while an earlier round "
                "passed"))
        if last["skipped"] and any(not m["skipped"]
                                   for m in mseries[:-1]):
            flags.append(_flag(
                "multichip", "multichip", "multichip_regression",
                f"{last['label']} skipped while earlier rounds ran"))

    # stale acks: an ACKNOWLEDGED entry that matched no flag means the
    # series recovered (or the key/kind was typo'd) — surface it so the
    # ledger shrinks back instead of silently accreting mutes
    matched = {(f["key"], f["kind"]) for f in flags
               if f["acknowledged"]}
    for (key, kind) in sorted(ACKNOWLEDGED):
        if (key, kind) not in matched:
            notes.append({
                "key": key, "rig": "-", "kind": "stale_ack",
                "detail": f"ACKNOWLEDGED[({key!r}, {kind!r})] matched "
                          "no flag — the series recovered; delete the "
                          "entry"})

    unack = [f for f in flags if not f["acknowledged"]]
    return {
        "magic": TREND_MAGIC,
        "newest": newest_label,
        "series": {
            f"{key} [{rig}]": pts
            for (key, rig), pts in sorted(series.items())
        },
        "multichip": mseries,
        "flags": flags,
        "notes": notes,
        "summary": {
            "n_series": len(series),
            "n_flags": len(flags),
            "n_unacknowledged": len(unack),
            "n_notes": len(notes),
        },
    }


def unacknowledged(report: dict) -> List[dict]:
    return [f for f in report["flags"] if not f["acknowledged"]]


def check_report(doc: dict) -> dict:
    """Validate a sentinel report document (the trace_report --trend
    strictness contract); returns it. ValueError on malformed input."""
    if not isinstance(doc, dict) or doc.get("magic") != TREND_MAGIC:
        raise ValueError(
            f"not a perf-trend report (magic="
            f"{doc.get('magic') if isinstance(doc, dict) else None!r} "
            f"!= {TREND_MAGIC!r})")
    for sect in ("series", "flags", "notes", "summary"):
        if sect not in doc:
            raise ValueError(f"report section {sect!r} missing")
    if not isinstance(doc["flags"], list):
        raise ValueError("report 'flags' is not a list")
    for i, f in enumerate(doc["flags"]):
        if not isinstance(f, dict) or "key" not in f or "kind" not in f \
                or "acknowledged" not in f:
            raise ValueError(f"report flags[{i}] malformed")
    return doc


def render_markdown(report: dict) -> str:
    """The human half of the report — committed beside the artifacts
    (docs/perf_trend.md) and uploaded by CI."""
    lines = ["# Perf-trend sentinel report", ""]
    lines.append("Newest artifact per rig: "
                 + (", ".join(f"`{rig}` → {lbl}" for rig, lbl
                              in sorted(report["newest"].items()))
                    or "none"))
    s = report["summary"]
    lines.append("")
    lines.append(f"{s['n_series']} series · {s['n_flags']} flag(s) "
                 f"({s['n_unacknowledged']} unacknowledged) · "
                 f"{s['n_notes']} note(s)")
    lines.append("")
    if report["flags"]:
        lines.append("## Flags")
        lines.append("")
        lines.append("| key | rig | kind | detail | ack |")
        lines.append("|---|---|---|---|---|")
        for f in report["flags"]:
            ack = f["ack"] or ("yes" if f["acknowledged"] else "**NO**")
            lines.append(f"| `{f['key']}` | {f['rig']} | {f['kind']} | "
                         f"{f['detail']} | {ack} |")
        lines.append("")
    if report["notes"]:
        lines.append("## Notes (non-fatal)")
        lines.append("")
        lines.append("| key | rig | kind | detail |")
        lines.append("|---|---|---|---|")
        for n in report["notes"]:
            lines.append(f"| `{n['key']}` | {n['rig']} | {n['kind']} | "
                         f"{n['detail']} |")
        lines.append("")
    lines.append("## Multi-point series (tails)")
    lines.append("")
    lines.append("| series | points | values (oldest → newest) |")
    lines.append("|---|---|---|")
    for name, pts in sorted(report["series"].items()):
        if len(pts) < 2:
            continue
        vals = " → ".join(str(p["value"]) for p in pts)
        lines.append(f"| `{name}` | {len(pts)} | {vals} |")
    single = sum(1 for pts in report["series"].values()
                 if len(pts) < 2)
    lines.append("")
    lines.append(f"({single} single-point series omitted — they grow "
                 "teeth on their second artifact.)")
    if report["multichip"]:
        lines.append("")
        lines.append("## MULTICHIP series")
        lines.append("")
        lines.append("| round | ok | rc | skipped |")
        lines.append("|---|---|---|---|")
        for m in report["multichip"]:
            lines.append(f"| {m['label']} | {m['ok']} | {m['rc']} | "
                         f"{m['skipped']} |")
    lines.append("")
    return "\n".join(lines)
