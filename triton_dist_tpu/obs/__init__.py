"""triton_dist_tpu.obs — always-on telemetry: metrics registry,
in-kernel stat rows, flight recorder, SLO health.

The SECOND tier of the observability story (docs/observability.md).
`trace/` is the deep-dive tier: opt-in per run, full event streams,
offline decode. `obs/` is the tier you leave ON under production
traffic:

  registry  counters / gauges / fixed-log-bucket histograms —
            pure-numpy, thread-safe, snapshot/delta/merge. The serve
            plane streams TTFT/TPOT at retirement, queue/pool/slot
            gauges per step, and admission/eviction/preemption/retry/
            quarantine/guard-trip counters by site.
  stats     O(1) in-kernel stat rows (one trailing (1, 8) SMEM row per
            core instead of a full trace buffer): accumulated
            sem_wait/dma_wait vticks, wire bytes by format, guard-trip
            counts — test-pinned to agree with `trace.attribution`'s
            per-region sums when both builds coexist on one run.
            Metered families: ag_gemm, the two-shot-AR ring legs
            (ring RS + ring AG, native and wire), LL-AG.
  recorder  flight recorder: a bounded ring of step snapshots
            (registry deltas + guard rows + scheduler state) dumped
            automatically on quarantine / DeadlineExceeded so every
            faults-plane trip ships its context.
  health    rolling-window SLO rules (ttft_p99, tokens/s floor,
            guard-trip rate) evaluated into a structured HealthStatus;
            `action="degrade"` rules feed the PR-9 degradation ladder
            (guard.degrade -> fallback="xla" routes).
  export    Prometheus text format + JSON snapshots (the examples/11
            socket server's `/metrics` command; scripts/trace_report.py
            --metrics renders both snapshot and flight-dump files).

Zero cost when off (the trace/verify/faults discipline, test-enforced):
no active `obs.stats.building()` block means every metered kernel
traces a byte-identical program with unchanged `pallas_call_count`;
with metering ON, `bench.py --obs` hard-asserts the overhead on the
ag_gemm chain under 3%.
"""

from triton_dist_tpu.obs.registry import (  # noqa: F401
    Histogram,
    Registry,
    SNAPSHOT_MAGIC,
    log_buckets,
)
from triton_dist_tpu.obs import stats  # noqa: F401
from triton_dist_tpu.obs.stats import (  # noqa: F401
    KernelStats,
    STAT_WORDS,
    WindowStats,
    decode_window_rows,
    metered,
    record_stats,
    window_agree_with_trace,
)
from triton_dist_tpu.obs import trend  # noqa: F401
from triton_dist_tpu.obs.recorder import (  # noqa: F401
    FLIGHT_MAGIC,
    FlightRecorder,
    check_dump,
    load_dump,
)
from triton_dist_tpu.obs.health import (  # noqa: F401
    CRITICAL,
    DEGRADED,
    HEALTHY,
    HealthStatus,
    SLOMonitor,
    SLORule,
)
from triton_dist_tpu.obs.export import (  # noqa: F401
    load_snapshot,
    to_json,
    to_prometheus,
    write_snapshot,
)
