"""SLO health plane — rolling-window rules over the metrics registry.

The serving plane's operational question is not "what is ttft_p99
right now" but "is the service inside its objectives, and if not, what
should degrade". This module evaluates a small rule language over a
rolling window of registry snapshots and folds the verdict into ONE
structured health status — the signal that feeds the PR-9 degradation
ladder (a violated rule with `action="degrade"` marks its protocol
degraded via `faults.guard.degrade`, so entry points called with
`fallback="xla"` start taking the safe route).

Rule syntax (docs/observability.md "SLO rules"):

    "<metric> < <threshold>"   |   "<metric> > <threshold>"

where <metric> is one of

  ttft_p99_us / ttft_p50_us     TTFT quantile over the serve_ttft_us
  tpot_p99_us / tpot_p50_us     / serve_tpot_us histograms (computed
                                from the CURRENT registry state — the
                                histograms already aggregate history)
  tokens_per_s                  retirement throughput over the window:
                                delta(serve_tokens_out) / window wall
  guard_trip_rate               guard trips per step over the window
  <counter or gauge key>        any registry key, evaluated on the
                                newest snapshot (gauge) or the window
                                delta (counter)

`<` rules violate when the measured value is >= threshold? No — a rule
states the OBJECTIVE: "ttft_p99_us < 5000" is healthy while the
measured p99 stays under 5000 and VIOLATED once it reaches it.
"""

from __future__ import annotations

import collections
import dataclasses
import re
import time
from typing import List, Optional, Sequence, Tuple

from triton_dist_tpu.obs.registry import Registry, split_key

_RULE_RE = re.compile(
    r"^\s*([A-Za-z0-9_.{}=,\-]+)\s*([<>])\s*"
    r"([0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)\s*$")

# quantile shorthand: metric name -> (histogram key, q)
_QUANTILES = {
    "ttft_p50_us": ("serve_ttft_us", 0.50),
    "ttft_p99_us": ("serve_ttft_us", 0.99),
    "tpot_p50_us": ("serve_tpot_us", 0.50),
    "tpot_p99_us": ("serve_tpot_us", 0.99),
}

HEALTHY, DEGRADED, CRITICAL = "healthy", "degraded", "critical"


@dataclasses.dataclass(frozen=True)
class SLORule:
    """One objective. `action` decides what a violation does to the
    overall status ("warn" -> degraded, "degrade" -> critical + the
    named `protocol` is marked degraded in the guard registry)."""

    metric: str
    op: str          # "<" | ">"
    threshold: float
    action: str = "warn"          # "warn" | "degrade"
    protocol: Optional[str] = None  # guard.degrade target for "degrade"

    def __post_init__(self):
        assert self.op in ("<", ">"), self.op
        assert self.action in ("warn", "degrade"), self.action

    @classmethod
    def parse(cls, text: str, action: str = "warn",
              protocol: Optional[str] = None) -> "SLORule":
        m = _RULE_RE.match(text)
        if m is None:
            raise ValueError(
                f"bad SLO rule {text!r} (want '<metric> < <num>' or "
                "'<metric> > <num>')")
        return cls(metric=m.group(1), op=m.group(2),
                   threshold=float(m.group(3)), action=action,
                   protocol=protocol)

    def holds(self, value: Optional[float]) -> bool:
        """Unmeasurable (None) objectives hold — an idle service is not
        out of SLO."""
        if value is None:
            return True
        return value < self.threshold if self.op == "<" \
            else value > self.threshold


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: SLORule
    value: float

    def __str__(self):
        return (f"{self.rule.metric} = {self.value:.4g} violates "
                f"'{self.rule.metric} {self.rule.op} "
                f"{self.rule.threshold:g}'")


@dataclasses.dataclass(frozen=True)
class HealthStatus:
    status: str                  # healthy | degraded | critical
    violations: Tuple[Violation, ...]
    window_steps: int
    evaluated_at_ns: int

    @property
    def healthy(self) -> bool:
        return self.status == HEALTHY

    def to_dict(self) -> dict:
        return {
            "status": self.status,
            "violations": [str(v) for v in self.violations],
            "window_steps": self.window_steps,
            "evaluated_at_ns": self.evaluated_at_ns,
        }


class SLOMonitor:
    """Rolling-window evaluator. `feed(registry)` once per step;
    `evaluate(registry)` (also called by feed) returns the current
    HealthStatus and applies degrade actions."""

    def __init__(self, rules: Sequence, window: int = 32):
        self.rules: List[SLORule] = [
            r if isinstance(r, SLORule) else SLORule.parse(r)
            for r in rules
        ]
        assert window >= 1
        self.window = window
        self._snaps: collections.deque = collections.deque(maxlen=window)
        self.last: Optional[HealthStatus] = None

    # -- window metrics -------------------------------------------------

    def _window_delta(self, key: str) -> Optional[float]:
        if len(self._snaps) < 2:
            return None
        newest, oldest = self._snaps[-1], self._snaps[0]
        if key not in newest["counters"] and key not in oldest["counters"]:
            return None  # absent != zero: the objective is unmeasurable
        return float(newest["counters"].get(key, 0)
                     - oldest["counters"].get(key, 0))

    def _window_delta_base(self, base: str) -> Optional[float]:
        """Window delta summed over every labelled series of `base`
        (counters land as 'base{k=v}' — registry.split_key identity)."""
        if len(self._snaps) < 2:
            return None
        newest, oldest = self._snaps[-1], self._snaps[0]
        keys = {k for k in newest["counters"] if split_key(k)[0] == base}
        keys |= {k for k in oldest["counters"] if split_key(k)[0] == base}
        if not keys:
            return None
        return float(sum(newest["counters"].get(k, 0)
                         - oldest["counters"].get(k, 0) for k in keys))

    def _window_seconds(self) -> Optional[float]:
        if len(self._snaps) < 2:
            return None
        dt = (self._snaps[-1]["t_ns"] - self._snaps[0]["t_ns"]) / 1e9
        return dt if dt > 0 else None

    def measure(self, metric: str, registry: Registry) -> Optional[float]:
        """The rule language's measurement function (None =
        unmeasurable in the current window)."""
        q = _QUANTILES.get(metric)
        if q is not None:
            name, quant = q
            if registry.hist_count(name) == 0:
                return None
            return registry.quantile(name, quant)
        if metric == "tokens_per_s":
            d = self._window_delta("serve_tokens_out")
            secs = self._window_seconds()
            return None if d is None or secs is None else d / secs
        if metric == "guard_trip_rate":
            trips = self._window_delta_base("serve_guard_trips")
            steps = self._window_delta("serve_steps")
            if not steps:
                return None
            # steps measured but no trip series yet: a clean run's
            # rate is genuinely 0, not unmeasurable
            return (trips or 0.0) / steps
        g = registry.gauge(metric)
        if g is not None:
            return g
        d = self._window_delta(metric)
        if d is not None:
            return d
        c = registry.counter(metric)
        return float(c) if c else None

    # -- evaluation -----------------------------------------------------

    def feed(self, registry: Registry) -> HealthStatus:
        snap = registry.snapshot()
        snap["t_ns"] = time.time_ns()
        self._snaps.append(snap)
        return self.evaluate(registry)

    def evaluate(self, registry: Registry) -> HealthStatus:
        violations = []
        worst = HEALTHY
        for rule in self.rules:
            value = self.measure(rule.metric, registry)
            if rule.holds(value):
                continue
            violations.append(Violation(rule, float(value)))
            if rule.action == "degrade":
                worst = CRITICAL
                if rule.protocol is not None:
                    from triton_dist_tpu.faults import guard as _guard

                    _guard.degrade(rule.protocol)
            elif worst == HEALTHY:
                worst = DEGRADED
        self.last = HealthStatus(
            status=worst, violations=tuple(violations),
            window_steps=len(self._snaps),
            evaluated_at_ns=time.time_ns())
        return self.last
