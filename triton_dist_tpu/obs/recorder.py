"""Flight recorder — a bounded ring of recent step snapshots, dumped
automatically when a faults-plane trip needs its context shipped.

The degradation ladder (PR 9) converts hangs into structured errors;
what it could not do was say what the system looked like in the steps
BEFORE the trip — by the time `DeadlineExceeded` reaches a log line,
the queue depths, retry counts, and guard rows that explain it are
gone. The recorder keeps the last `cap` step snapshots in memory at
O(cap) cost:

    StepSnapshot = registry DELTA since the previous snapshot
                 + current gauges (absolute)
                 + scheduler state summary (active/queued/retries)
                 + the decoded guard rows of any FaultError seen

and `dump()` writes the whole ring as one JSON document. The serve
Scheduler records one snapshot per step and dumps automatically on
quarantine (every faults-plane trip ships its context); callers can
also dump on demand. `scripts/trace_report.py --metrics` renders dumps
in the attribution-table style; CI uploads them as artifacts when the
tier-1 gate fails.

Dump location: `dir` argument, else $TDT_FLIGHT_DIR, else ./flightrec.
"""

from __future__ import annotations

import collections
import json
import os
import time
from typing import List, Optional

from triton_dist_tpu.obs.registry import Registry

FLIGHT_MAGIC = "tdt-flight"
FLIGHT_VERSION = 1


def _trip_dict(t) -> dict:
    """A faults.GuardTrip (or compatible) as a plain dict."""
    return {
        "rank": int(t.rank), "site": int(t.site),
        "site_label": t.site_label, "slot": int(t.slot),
        "progress": int(t.progress), "expected": int(t.expected),
        "observed": int(t.observed), "seq": int(t.seq),
    }


class FlightRecorder:
    """Bounded ring of step snapshots (thread-safe through the GIL on
    the append path; readers copy)."""

    def __init__(self, cap: int = 64, dir: Optional[str] = None):
        assert cap >= 1
        self.cap = cap
        self.dir = dir
        self._ring: collections.deque = collections.deque(maxlen=cap)
        self._prev_snap: Optional[dict] = None
        self._step = 0
        self.n_dumps = 0

    def __len__(self) -> int:
        return len(self._ring)

    def record(self, registry: Optional[Registry] = None,
               scheduler_state: Optional[dict] = None,
               error=None, step: Optional[int] = None) -> dict:
        """Append one snapshot. `error` (a FaultError) contributes its
        decoded guard rows — the evidence a later dump must contain."""
        snap = registry.snapshot() if registry is not None else None
        entry = {
            "step": self._step if step is None else int(step),
            "t_ns": time.time_ns(),
            "metrics_delta": (Registry.delta(snap, self._prev_snap)
                              if snap is not None else None),
            "gauges": dict(snap["gauges"]) if snap is not None else {},
            "scheduler": dict(scheduler_state or {}),
            "guard_rows": [_trip_dict(t)
                           for t in getattr(error, "trips", []) or []],
            "error": None if error is None else repr(error),
        }
        self._prev_snap = snap
        self._step = entry["step"] + 1
        self._ring.append(entry)
        return entry

    def snapshots(self) -> List[dict]:
        return list(self._ring)

    @property
    def last(self) -> Optional[dict]:
        return self._ring[-1] if self._ring else None

    # -- dump / load ----------------------------------------------------

    def dump(self, path: Optional[str] = None,
             reason: str = "manual") -> str:
        """Write the ring as one JSON document; returns the path."""
        if path is None:
            d = self.dir or os.environ.get("TDT_FLIGHT_DIR", "flightrec")
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"flight_{time.strftime('%Y%m%d_%H%M%S')}"
                   f"_{os.getpid()}_{self.n_dumps}.json")
        doc = {
            "magic": FLIGHT_MAGIC,
            "version": FLIGHT_VERSION,
            "reason": reason,
            "dumped_at_ns": time.time_ns(),
            "snapshots": self.snapshots(),
        }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        self.n_dumps += 1
        return path


def check_dump(doc: dict) -> dict:
    """Validate a flight-recorder document (trace_report strictness);
    returns it, raises ValueError on malformed input."""
    if not isinstance(doc, dict) or doc.get("magic") != FLIGHT_MAGIC:
        raise ValueError(
            "not a flight-recorder dump (magic="
            f"{doc.get('magic') if isinstance(doc, dict) else None!r})")
    snaps = doc.get("snapshots")
    if not isinstance(snaps, list):
        raise ValueError("flight dump: snapshots missing or not a list")
    for i, s in enumerate(snaps):
        if not isinstance(s, dict) or "step" not in s \
                or "guard_rows" not in s:
            raise ValueError(f"flight dump: snapshot {i} malformed")
        for r in s["guard_rows"]:
            if not isinstance(r, dict) or "site" not in r \
                    or "rank" not in r:
                raise ValueError(
                    f"flight dump: snapshot {i} guard row malformed")
    return doc


def load_dump(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        try:
            doc = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f"{path}: not JSON: {e}") from e
    return check_dump(doc)
