"""In-kernel aggregate stat rows — O(1) always-on kernel telemetry.

The trace plane (trace/events.py) answers "where did this run's time
go" with a full event stream: per-record SMEM stores, a (1+cap, 8)
buffer per core, offline decode. That is the right tool for a deep
dive and the wrong one for always-on production telemetry. This module
is the O(1) counterpart: each metered kernel carries ONE trailing
(1, STAT_WORDS) i32 SMEM row per core — the trace-buffer
trailing-output idiom with the buffer collapsed to aggregates:

    [OMAGIC, rank, events, sem_wait, dma_wait, send_bytes, trips, fmt]

  events      the metering clock: one tick per trace-record-equivalent
              event (span BEGIN/END, instant) — the same deterministic
              seq clock trace/collect.py assigns virtual time on.
  sem_wait /  accumulated wait-span durations in vticks, classified by
  dma_wait    trace.events.REGION_CLASS. When a kernel is built under
              BOTH trace.building() and obs.stats.building(), the
              combined span/instant helpers below advance this clock in
              lockstep with the trace cursor, so the stat-row sums are
              EXACTLY the per-region span-time sums trace/attribution
              computes from the full stream (test-pinned,
              tests/test_obs.py). Metered-only builds tick the same
              clock without the stream: each bare wait costs 1 vtick.
  send_bytes  wire bytes this core pushed (remote DMA payload bytes at
              the format actually on the wire — int8 image bytes for
              quantized legs), the always-on form of
              attribution.wire_send_bytes.
  trips       guard-watchdog trips recorded by a coexisting guard build
              (faults/guard.py bumps this through GuardCtx.octx).
  fmt         wire-format code (FMT_CODES) so bytes are attributable
              by format without a side channel.

Two instrumentation styles, both zero-cost when off:

  explicit    kernels with existing trace regions (ag_gemm) replace
              their raw trace_ev.span/instant calls with the combined
              helpers here, passing (tctx, octx) — the agreement-pinned
              style.
  ambient     kernels whose waits/puts all route through lang/shmem
              primitives (the two-shot-AR ring legs, ring/full-mesh
              allgather, LL-AG) attach ONE MeterCtx around their body
              (`with stats.attached(octx):`, the faults/guard pattern);
              the primitives call `meter_wait`/`meter_send` hooks that
              are a single None-check when no ctx is attached.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.trace import events as trace_ev

STAT_WORDS = 8
OMAGIC = 0x5D7A  # 'obs' header tag (trace 0x7D7A / guard 0x6D7A family)

# word indices of the stat row
W_MAGIC, W_RANK, W_EVENTS, W_SEM, W_DMA, W_BYTES, W_TRIPS, W_FMT = \
    range(STAT_WORDS)

FMT_CODES = {"native": 0, "fp8": 1, "int8": 2}
_FMT_NAMES = {v: k for k, v in FMT_CODES.items()}

_WAIT_WORD = {"sem_wait": W_SEM, "dma_wait": W_DMA}


def fmt_code(fmt) -> int:
    """Stat-row format code of a wire.WireFormat / format kind / None."""
    kind = getattr(fmt, "kind", fmt) or "native"
    return FMT_CODES.get(str(kind), 0)


# -- build flag (host side, the trace.building discipline) -------------------


@dataclasses.dataclass(frozen=True)
class ObsBuild:
    """Active metering build: kernels constructed while one is active
    compile the stat-row stores in (one extra trailing SMEM output per
    metered entry point, AFTER any trace and guard buffers); otherwise
    they compile to exactly the unmetered program."""


_BUILD_STATE = threading.local()


def active_build() -> Optional[ObsBuild]:
    return getattr(_BUILD_STATE, "build", None)


@contextlib.contextmanager
def building():
    """Enable stat-row metering for kernels traced inside the block.

    Contract: every metered entry point returns ONE extra trailing
    output — its (1, STAT_WORDS) i32 stat row ((cores, 1, STAT_WORDS)
    for multi-core kernels) — after any trace buffer and guard buffer;
    fallback paths return an empty row (build-stable output trees, the
    trace.with_trace idiom)."""
    prev = getattr(_BUILD_STATE, "build", None)
    _BUILD_STATE.build = ObsBuild()
    try:
        yield _BUILD_STATE.build
    finally:
        _BUILD_STATE.build = prev


def out_shape(build: ObsBuild, lanes: int = 0):
    shape = (1, STAT_WORDS)
    if lanes:
        shape = (lanes,) + shape
    return jax.ShapeDtypeStruct(shape, jnp.int32)


def out_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def cursor_scratch():
    # [0] = event (vtick) cursor, [1] = accumulated straggle delay
    return pltpu.SMEM((2,), jnp.int32)


def new_stream(build: ObsBuild, rank=-1, fmt=0):
    """An empty host-level stat row (fallback paths owe one under an
    active build)."""
    row = jnp.zeros((1, STAT_WORDS), jnp.int32)
    return row.at[0, W_MAGIC].set(OMAGIC) \
              .at[0, W_RANK].set(jnp.asarray(rank, jnp.int32)) \
              .at[0, W_FMT].set(jnp.asarray(fmt, jnp.int32))


def with_stats(build: Optional[ObsBuild], res, row=None):
    """Append the trailing stat-row output a metered entry point owes
    its caller under an active build — the outermost trailing buffer
    (strip order: stats, then guard, then trace)."""
    if build is None:
        return res
    if row is None:
        row = new_stream(build)
    return res + (row,) if isinstance(res, tuple) else (res, row)


def primary(res):
    """The metered call's primary result(s), stat row stripped when a
    build is active (the trace/guard `primary` analog for composite
    callers that do not thread rows outward)."""
    if active_build() is None:
        return res
    out = res[:-1]
    return out[0] if len(out) == 1 else out


@contextlib.contextmanager
def metered(registry=None):
    """building() plus an ambient Registry: host entry points that own
    their kernels' stat rows (all_reduce_op, ll_all_gather_op) decode
    the rows into this registry and return their ORIGINAL output tree —
    the ergonomic always-on form:

        with obs.stats.metered() as reg:
            out = all_reduce_op(arr, mesh, wire_format="fp8")
        reg.counter("obs_wire_bytes", kernel="allreduce", fmt="fp8")

    Lower-level entry points (ag_gemm, two_shot_all_reduce) still
    return their trailing rows — they run inside jit, where a registry
    cannot be written."""
    from triton_dist_tpu.obs.registry import Registry

    reg = registry if registry is not None else Registry()
    prev_reg = getattr(_BUILD_STATE, "registry", None)
    _BUILD_STATE.registry = reg
    try:
        with building():
            yield reg
    finally:
        _BUILD_STATE.registry = prev_reg


def ambient_registry():
    """The registry of the innermost `metered()` block (None outside).
    Host entry points fold decoded rows into it via record_stats."""
    return getattr(_BUILD_STATE, "registry", None)


# -- kernel-side context ------------------------------------------------------


@dataclasses.dataclass
class MeterCtx:
    """In-kernel handle: `row` the (1, STAT_WORDS) (or per-core
    (lanes, 1, STAT_WORDS)) i32 SMEM output ref, `cur` the 2-word SMEM
    event-cursor/straggle scratch."""

    row: Any
    cur: Any
    lane: Any = None

    def _set(self, w, v):
        if self.lane is not None:
            self.row[self.lane, 0, w] = v
        else:
            self.row[0, w] = v

    def _get(self, w):
        return (self.row[self.lane, 0, w] if self.lane is not None
                else self.row[0, w])

    def vt(self):
        """Current virtual time: event count + injected straggle delay
        (exactly trace/collect.py's vtime at the same program point)."""
        return self.cur[0] + self.cur[1]

    def tick(self) -> None:
        """One trace-record-equivalent event on the metering clock."""
        nxt = self.cur[0] + 1
        self.cur[0] = nxt
        self._set(W_EVENTS, nxt)

    def straggle(self, payload) -> None:
        """Injected-skew delay (the trace 'straggle' payload): shifts
        the virtual clock for every later event."""
        self.cur[1] = self.cur[1] + jnp.asarray(payload, jnp.int32)

    def add(self, word: int, amount) -> None:
        self._set(word, self._get(word) + jnp.asarray(amount, jnp.int32))

    def add_wait(self, cls: Optional[str], dur) -> None:
        w = _WAIT_WORD.get(cls)
        if w is not None:
            self.add(w, dur)

    def add_bytes(self, nbytes) -> None:
        self.add(W_BYTES, nbytes)

    def add_trip(self) -> None:
        self.add(W_TRIPS, 1)


def make_ctx(build: Optional[ObsBuild], row_ref, cur_ref,
             lane=None) -> Optional[MeterCtx]:
    if build is None:
        return None
    return MeterCtx(row=row_ref, cur=cur_ref, lane=lane)


def init_ctx(ctx: Optional[MeterCtx], rank=0, fmt: int = 0) -> None:
    """Write the header words and zero every counter (SMEM is NOT
    zero-initialized — decode trusts only rows carrying the magic)."""
    if ctx is None:
        return
    ctx.cur[0] = 0
    ctx.cur[1] = 0
    ctx._set(W_MAGIC, OMAGIC)
    ctx._set(W_RANK, jnp.asarray(rank, jnp.int32))
    for w in (W_EVENTS, W_SEM, W_DMA, W_BYTES, W_TRIPS):
        ctx._set(w, 0)
    ctx._set(W_FMT, jnp.asarray(fmt, jnp.int32))


# -- the trace-time attach stack (ambient style, the guard pattern) ----------

_CTX_STATE = threading.local()


def current() -> Optional[MeterCtx]:
    stack = getattr(_CTX_STATE, "stack", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def attached(ctx: Optional[MeterCtx]):
    """Make `ctx` the ambient meter while the kernel body traces (None
    attaches nothing — the zero-cost-off path)."""
    if ctx is None:
        yield None
        return
    stack = getattr(_CTX_STATE, "stack", None)
    if stack is None:
        stack = _CTX_STATE.stack = []
    stack.append(ctx)
    try:
        yield ctx
    finally:
        stack.pop()


def meter_wait(cls: str = "sem_wait",
               ctx: Optional[MeterCtx] = None) -> None:
    """Ambient hook the shmem wait primitives call after consuming: one
    wait-span equivalent (BEGIN + END ticks, duration 1 vtick) accrued
    to `cls`. One None-check when nothing is attached."""
    ctx = ctx or current()
    if ctx is None:
        return
    ctx.tick()
    ctx.add_wait(cls, 1)
    ctx.tick()


def meter_send(nbytes: int, ctx: Optional[MeterCtx] = None) -> None:
    """Ambient hook for remote puts: `nbytes` is the payload byte count
    actually on the wire (the int8 image bytes on quantized legs)."""
    ctx = ctx or current()
    if ctx is None:
        return
    ctx.tick()
    ctx.add_bytes(nbytes)


# -- combined trace+obs emit helpers (explicit style) -------------------------


@contextlib.contextmanager
def span(tctx, octx: Optional[MeterCtx], region: int, payload=0, aux=0):
    """Combined span: the trace BEGIN/END records (when tctx) plus the
    span's vtick duration accrued to the region's REGION_CLASS bucket
    (when octx). The meter clock ticks once per record in the same
    order the trace cursor advances, which is what makes the stat-row
    sums bitwise-equal to attribution's per-region totals on a shared
    traced+metered build."""
    trace_ev.emit(tctx, region, trace_ev.KIND_BEGIN, payload, aux)
    t0 = octx.vt() if octx is not None else None
    if octx is not None:
        octx.tick()
    yield
    if octx is not None:
        cls = trace_ev.REGION_CLASS.get(trace_ev.region_name(region))
        octx.add_wait(cls, octx.vt() - t0)
        octx.tick()
    trace_ev.emit(tctx, region, trace_ev.KIND_END, payload, aux)


def instant(tctx, octx: Optional[MeterCtx], region: int, payload=0,
            aux=0) -> None:
    """Combined instant; 'straggle' payloads also advance the meter's
    delay clock (trace/collect.py's virtual-time rule)."""
    trace_ev.instant(tctx, region, payload, aux)
    if octx is not None:
        octx.tick()
        if region == trace_ev.REGIONS["straggle"]:
            octx.straggle(payload)


# -- host-side decode ---------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class KernelStats:
    """One decoded stat row (one core of one kernel execution)."""

    rank: int
    events: int
    sem_wait: int
    dma_wait: int
    send_bytes: int
    trips: int
    fmt: int

    @property
    def fmt_name(self) -> str:
        return _FMT_NAMES.get(self.fmt, f"fmt{self.fmt}")

    def __str__(self):
        return (f"rank {self.rank}: events={self.events} "
                f"sem_wait={self.sem_wait} dma_wait={self.dma_wait} "
                f"bytes={self.send_bytes} trips={self.trips} "
                f"fmt={self.fmt_name}")


def decode(buf) -> List[KernelStats]:
    """Decode stat row(s) — any array whose trailing dims are
    (1, STAT_WORDS); leading dims (ranks, legs, ...) flatten. A row
    without the magic is malformed (uninitialized or clobbered)."""
    import numpy as np

    a = np.asarray(buf)
    if a.ndim < 2 or a.shape[-1] != STAT_WORDS:
        raise ValueError(f"not a stat row: shape {a.shape}")
    flat = a.reshape(-1, STAT_WORDS)
    out: List[KernelStats] = []
    for r in flat:
        if int(r[W_MAGIC]) != OMAGIC:
            raise ValueError(
                f"stat row magic {int(r[W_MAGIC]):#x} != {OMAGIC:#x} "
                "(uninitialized or clobbered)")
        out.append(KernelStats(
            rank=int(r[W_RANK]), events=int(r[W_EVENTS]),
            sem_wait=int(r[W_SEM]), dma_wait=int(r[W_DMA]),
            send_bytes=int(r[W_BYTES]), trips=int(r[W_TRIPS]),
            fmt=int(r[W_FMT])))
    return out


def totals(*bufs) -> KernelStats:
    """Sum of every decoded row (rank/fmt = -1/0 unless uniform)."""
    rows: List[KernelStats] = []
    for b in bufs:
        if b is not None:
            rows.extend(decode(b))
    ranks = {r.rank for r in rows}
    fmts = {r.fmt for r in rows}
    return KernelStats(
        rank=ranks.pop() if len(ranks) == 1 else -1,
        events=sum(r.events for r in rows),
        sem_wait=sum(r.sem_wait for r in rows),
        dma_wait=sum(r.dma_wait for r in rows),
        send_bytes=sum(r.send_bytes for r in rows),
        trips=sum(r.trips for r in rows),
        fmt=fmts.pop() if len(fmts) == 1 else 0)


def record_stats(registry, stats, kernel: str) -> None:
    """Fold decoded rows (or a buffer) into a metrics Registry — the
    bridge from the in-kernel tier to the always-on tier: counters
    obs_sem_wait_ticks / obs_dma_wait_ticks / obs_wire_bytes{fmt=} /
    obs_guard_trips, labelled by kernel."""
    if not isinstance(stats, (list, tuple)):
        stats = decode(stats)
    for s in stats:
        registry.inc("obs_sem_wait_ticks", s.sem_wait, kernel=kernel)
        registry.inc("obs_dma_wait_ticks", s.dma_wait, kernel=kernel)
        registry.inc("obs_wire_bytes", s.send_bytes, kernel=kernel,
                     fmt=s.fmt_name)
        registry.inc("obs_guard_trips", s.trips, kernel=kernel)
        registry.inc("obs_kernel_events", s.events, kernel=kernel)


def consume_rows(buf, kernel: str) -> None:
    """Host-op tail: fold a trailing stat-row output into the ambient
    metered() registry (no-op without one). ONE definition of the
    consume contract — the rows are eaten here, so every host op's
    output tree keeps its documented shape."""
    import numpy as np

    reg = ambient_registry()
    if reg is None:
        return
    record_stats(
        reg, decode(np.asarray(buf).reshape(-1, STAT_WORDS)),
        kernel=kernel)


# -- resident-window stat rows (the serve plane's lane set, ISSUE 13) --------
#
# The resident serve loop (models/engine.make_resident_loop) is pure
# XLA — it has no semaphores or DMA queues of its own, so its wait
# taxonomy is the loop-level analog of sem_wait/dma_wait: RING POLLS
# (boundary drains of the injection ring) and IDLE POLLS (poll-budget
# burn while nothing is active). Under obs.stats.building() the loop
# returns one trailing (1 + slots, 1, STAT_WORDS) i32 output — the
# PR-11 trailing-row idiom with one LANE PER SLOT plus a loop lane —
# so every word of device time in a window is attributable to a slot
# (and through the scheduler's slot history, to a REQUEST):
#
#   lane 0 (the loop lane, RW_LANE = -1):
#     [WMAGIC, -1, steps, ring_polls, idle_polls, consumed, starved, 0]
#   lane 1+s (slot lane s):
#     [WMAGIC, s, steps_active, steps_idle, emits, last_req_id, 0, 0]
#
# The agreement contract (the PR-11 pin extended): a loop built under
# BOTH trace.building() and stats.building() emits serve.step spans /
# serve.poll / serve.idle instants whose counts (and per-slot
# active-mask popcounts) equal these counters exactly —
# `window_agree_with_trace` below, tier-1-pinned.

WMAGIC = 0x5D7B  # resident-window row tag (OMAGIC family)

# loop-lane words
RW_MAGIC, RW_LANE, RW_STEPS, RW_POLLS, RW_IDLE, RW_CONSUMED, \
    RW_STARVED = range(7)
# slot-lane words (RW_MAGIC/RW_LANE/RW_STEPS shared)
RW_SLOT_IDLE, RW_EMITS, RW_REQID = 3, 4, 5


@dataclasses.dataclass(frozen=True)
class WindowSlotStats:
    """One decoded slot lane of a resident-window row set."""

    slot: int
    steps: int       # device steps this slot ran a plan row in
    idle_steps: int  # executed steps the slot sat inactive through
    emits: int       # tokens emitted (prefill-tail + decode)
    req_id: int      # the lane's occupant at window end (-0 when idle)


@dataclasses.dataclass(frozen=True)
class WindowStats:
    """One decoded resident-window row set (loop lane + slot lanes)."""

    steps: int       # executed device steps this window
    ring_polls: int  # injection-ring boundary drains
    idle_polls: int  # loop iterations that executed no step
    consumed: int    # injection records consumed this window
    starved: int     # abandoned-ring flag at window exit
    slots: List[WindowSlotStats] = dataclasses.field(
        default_factory=list)


def decode_window_rows(buf) -> WindowStats:
    """Decode one (1 + slots, 1, STAT_WORDS) resident-window output.
    A row without the window magic is malformed."""
    import numpy as np

    a = np.asarray(buf).reshape(-1, STAT_WORDS)
    if a.shape[0] < 1:
        raise ValueError(f"empty window-row buffer {a.shape}")
    for r in a:
        if int(r[RW_MAGIC]) != WMAGIC:
            raise ValueError(
                f"window row magic {int(r[RW_MAGIC]):#x} != {WMAGIC:#x} "
                "(uninitialized or clobbered)")
    loop = a[0]
    if int(loop[RW_LANE]) != -1:
        raise ValueError(
            f"window row 0 lane {int(loop[RW_LANE])} != -1 (loop lane "
            "must lead)")
    slots = [
        WindowSlotStats(
            slot=int(r[RW_LANE]), steps=int(r[RW_STEPS]),
            idle_steps=int(r[RW_SLOT_IDLE]), emits=int(r[RW_EMITS]),
            req_id=int(r[RW_REQID]))
        for r in a[1:]
    ]
    return WindowStats(
        steps=int(loop[RW_STEPS]), ring_polls=int(loop[RW_POLLS]),
        idle_polls=int(loop[RW_IDLE]), consumed=int(loop[RW_CONSUMED]),
        starved=int(loop[RW_STARVED]), slots=slots)


def record_window_stats(registry, ws: WindowStats) -> None:
    """Fold one window's counters into a metrics Registry — the serve
    plane's record_stats analog: serve_resident_ring_polls /
    serve_resident_idle_polls counters beside the existing
    serve_resident_windows/steps family."""
    registry.inc("serve_resident_ring_polls", ws.ring_polls)
    registry.inc("serve_resident_idle_polls", ws.idle_polls)


def window_agree_with_trace(ws: WindowStats, tl, stream: str) -> None:
    """THE agreement pin, resident-window form: on a loop built under
    BOTH trace.building() and stats.building(), the window row's
    counters must equal the trace stream's serve.* record counts —
    steps == serve.step spans, ring_polls == serve.poll instants,
    idle_polls == serve.idle instants, consumed == the summed
    serve.poll payloads — and each slot lane's steps must equal the
    popcount of its bit across the serve.step active masks (aux).
    Raises AssertionError with the diff."""
    from triton_dist_tpu.trace import events as ev

    steps = tl.spans_of(stream, region="serve.step")
    polls = [e for e in tl.events
             if e.stream == stream and e.kind == ev.KIND_INSTANT
             and e.region == ev.REGIONS["serve.poll"]]
    idles = [e for e in tl.events
             if e.stream == stream and e.kind == ev.KIND_INSTANT
             and e.region == ev.REGIONS["serve.idle"]]
    assert ws.steps == len(steps), (
        f"window row steps {ws.steps} != {len(steps)} serve.step spans")
    assert ws.ring_polls == len(polls), (
        f"window row ring_polls {ws.ring_polls} != {len(polls)} "
        "serve.poll instants")
    assert ws.idle_polls == len(idles), (
        f"window row idle_polls {ws.idle_polls} != {len(idles)} "
        "serve.idle instants")
    consumed = sum(e.payload for e in polls)
    assert ws.consumed == consumed, (
        f"window row consumed {ws.consumed} != {consumed} summed "
        "serve.poll payloads")
    for lane in ws.slots:
        mask_steps = sum(1 for s in steps
                         if (s.aux >> lane.slot) & 1)
        assert lane.steps == mask_steps, (
            f"slot lane {lane.slot}: {lane.steps} steps != {mask_steps} "
            "serve.step active-mask bits")


def agree_with_trace(stats: List[KernelStats], tl, stream: str) -> None:
    """THE agreement pin: on a run whose kernel was built under BOTH
    trace.building() and stats.building(), every rank's stat-row
    sem_wait/dma_wait must equal the summed span durations of that
    class in the trace timeline (attribution's per-region totals
    aggregated by REGION_CLASS). Raises AssertionError with the diff."""
    from triton_dist_tpu.trace import attribution as attr

    cls = attr.classify(tl)
    by_rank: dict = {}
    for (st, rank, _lane), d in cls.items():
        if st != stream:
            continue
        agg = by_rank.setdefault(rank, {"sem_wait": 0.0, "dma_wait": 0.0})
        agg["sem_wait"] += d["sem_wait"]
        agg["dma_wait"] += d["dma_wait"]
    for s in stats:
        want = by_rank.get(s.rank, {"sem_wait": 0.0, "dma_wait": 0.0})
        assert s.sem_wait == int(want["sem_wait"]), (
            f"rank {s.rank}: stat-row sem_wait {s.sem_wait} != trace "
            f"attribution {want['sem_wait']}")
        assert s.dma_wait == int(want["dma_wait"]), (
            f"rank {s.rank}: stat-row dma_wait {s.dma_wait} != trace "
            f"attribution {want['dma_wait']}")
