"""Exporters: Prometheus text format + JSON snapshots.

The registry's external faces (docs/observability.md "Exporters"):

  to_prometheus(registry_or_snapshot) -> str
      Prometheus text exposition (0.0.4): counters as `name_total`,
      gauges as-is, histograms as cumulative `_bucket{le=...}` series
      plus `_sum`/`_count` — scrape-ready (the examples/11 socket
      server's `/metrics` line command serves exactly this).
  to_json(registry) / write_snapshot / load_snapshot
      the snapshot document (registry.SNAPSHOT_MAGIC tagged) that
      `scripts/trace_report.py --metrics` renders and the flight
      recorder embeds; loading validates the format and raises
      ValueError on malformed input — the trace-plane strictness
      contract (a tool that silently rendered a clobbered snapshot
      would hide exactly what it exists to show).
"""

from __future__ import annotations

import json
from typing import Union

from triton_dist_tpu.obs.registry import Registry, split_key


def _snap(reg_or_snap: Union[Registry, dict]) -> dict:
    if isinstance(reg_or_snap, Registry):
        return reg_or_snap.snapshot()
    return Registry.check_snapshot(reg_or_snap)


def _prom_labels(labels: dict, extra: str = "") -> str:
    parts = [f'{k}="{v}"' for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def to_prometheus(reg_or_snap: Union[Registry, dict]) -> str:
    """Prometheus text format of a registry (or snapshot dict)."""
    snap = _snap(reg_or_snap)
    lines = []
    typed = set()

    def head(name: str, kind: str):
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {kind}")

    for key in sorted(snap["counters"]):
        name, labels = split_key(key)
        head(f"{name}_total", "counter")
        lines.append(f"{name}_total{_prom_labels(labels)} "
                     f"{snap['counters'][key]}")
    for key in sorted(snap["gauges"]):
        name, labels = split_key(key)
        head(name, "gauge")
        lines.append(f"{name}{_prom_labels(labels)} "
                     f"{_fmt_num(snap['gauges'][key])}")
    for key in sorted(snap["histograms"]):
        name, labels = split_key(key)
        h = snap["histograms"][key]
        head(name, "histogram")
        cum = 0
        for bound, count in zip(h["bounds"], h["counts"]):
            cum += count
            le = "+Inf" if bound is None else _fmt_num(bound)
            le_attr = 'le="%s"' % le
            lines.append(
                f"{name}_bucket{_prom_labels(labels, le_attr)} {cum}")
        lines.append(f"{name}_sum{_prom_labels(labels)} "
                     f"{_fmt_num(h['sum'])}")
        lines.append(f"{name}_count{_prom_labels(labels)} {h['count']}")
    return "\n".join(lines) + "\n"


def _fmt_num(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def to_json(reg_or_snap: Union[Registry, dict], indent=None) -> str:
    return json.dumps(_snap(reg_or_snap), indent=indent)


def write_snapshot(reg_or_snap: Union[Registry, dict],
                   path: str) -> str:
    with open(path, "w", encoding="utf-8") as f:
        f.write(to_json(reg_or_snap))
    return path


def load_snapshot(path: str) -> dict:
    """Load + validate a snapshot JSON (ValueError on malformed)."""
    with open(path, encoding="utf-8") as f:
        try:
            doc = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f"{path}: not JSON: {e}") from e
    return Registry.check_snapshot(doc)
