"""Metrics registry — counters, gauges, fixed-log-bucket histograms.

The always-on tier of the two-tier observability story
(docs/observability.md): where `trace/` is opt-in, per-run, and decoded
offline, this registry is cheap enough to leave running under
production traffic — every operation is a dict lookup plus a few numpy
scalar updates under one lock, no jax, no device round trips. The serve
plane streams into it at retirement (TTFT/TPOT histograms), per step
(queue/pool/slot gauges), and at every policy decision (admission /
eviction / preemption / retry / quarantine / guard-trip counters by
site); the in-kernel stat rows (`obs/stats.py`) fold into it through
`stats.record_stats`.

Design constraints, in order:

  deterministic   fixed log-spaced bucket bounds (a power-of-`growth`
                  ladder between lo and hi) — two registries built with
                  the same spec always have identical bucket edges, so
                  snapshots from different workers/steps MERGE exactly
                  (bucket-wise addition), the property streaming
                  percentile sketches give up.
  pure numpy      no jax imports: the registry must be importable (and
                  cheap) in host threads, exporters, and report tools.
  thread-safe     one lock per registry; the serve scheduler's
                  background thread and client threads share it.
  snapshot/delta  `snapshot()` is a plain-dict value; `delta(prev)`
                  subtracts counter-like state (the flight recorder's
                  per-step record), `merge(other)` adds it (multi-worker
                  aggregation). Gauges are last-write in both.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

# snapshot format tag (obs/export.py + scripts/trace_report.py --metrics)
SNAPSHOT_MAGIC = "tdt-metrics"


def _label_key(name: str, labels: Optional[dict]) -> str:
    """Canonical flat key: name{k=v,...} with sorted label keys — the
    Prometheus identity convention, so a (name, labels) pair is one
    time series everywhere (registry, snapshot, exporters)."""
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


def split_key(key: str) -> Tuple[str, dict]:
    """Inverse of the flat-key convention (exporters need the parts)."""
    if "{" not in key:
        return key, {}
    name, _, rest = key.partition("{")
    labels = {}
    for part in rest.rstrip("}").split(","):
        if part:
            k, _, v = part.partition("=")
            labels[k] = v
    return name, labels


def log_buckets(lo: float, hi: float, growth: float = 1.25) -> np.ndarray:
    """Fixed log-spaced bucket UPPER bounds covering [lo, hi]: the
    ladder lo * growth^i, extended one rung past hi, with +inf as the
    final overflow bound. growth=1.25 bounds the quantile estimation
    error at ~12% of the value — tight enough for p99 SLO math, small
    enough (~60 buckets across 6 decades) to snapshot every step."""
    assert 0 < lo < hi and growth > 1, (lo, hi, growth)
    n = int(math.ceil(math.log(hi / lo) / math.log(growth))) + 1
    bounds = lo * np.power(growth, np.arange(n + 1, dtype=np.float64))
    return np.concatenate([bounds, [np.inf]])


class Histogram:
    """Fixed-log-bucket histogram: counts per bucket + exact count /
    sum / min / max. Quantiles interpolate log-linearly inside the
    bucket, which keeps the relative error under (growth - 1)/2."""

    kind = "histogram"

    def __init__(self, bounds: np.ndarray):
        self.bounds = np.asarray(bounds, np.float64)
        self.counts = np.zeros(len(self.bounds), np.int64)
        self.total = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, value: float) -> None:
        v = float(value)
        i = int(np.searchsorted(self.bounds, v, side="left"))
        self.counts[i] += 1
        self.total += 1
        self.sum += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)

    def quantile(self, q: float) -> float:
        """q in [0, 1]; 0.0 when empty. Clamped to the exact observed
        min/max so p0/p100 are honest despite bucketing."""
        if self.total == 0:
            return 0.0
        target = q * self.total
        cum = np.cumsum(self.counts)
        i = int(np.searchsorted(cum, target, side="left"))
        lo = self.bounds[i - 1] if i > 0 else self.min
        hi = self.bounds[i]
        if not np.isfinite(hi):
            hi = self.max
        lo = max(float(lo), 1e-12)
        hi = max(float(hi), lo)
        prev = float(cum[i - 1]) if i > 0 else 0.0
        frac = (target - prev) / max(float(self.counts[i]), 1.0)
        frac = min(max(frac, 0.0), 1.0)
        est = lo * (hi / lo) ** frac
        return float(min(max(est, self.min), self.max))

    def state(self) -> dict:
        return {
            "kind": "histogram",
            "bounds": [None if not np.isfinite(b) else float(b)
                       for b in self.bounds],
            "counts": [int(c) for c in self.counts],
            "count": int(self.total),
            "sum": float(self.sum),
            "min": None if self.total == 0 else float(self.min),
            "max": None if self.total == 0 else float(self.max),
        }

    @classmethod
    def from_state(cls, d: dict) -> "Histogram":
        bounds = np.asarray(
            [np.inf if b is None else b for b in d["bounds"]], np.float64)
        h = cls(bounds)
        h.counts = np.asarray(d["counts"], np.int64).copy()
        h.total = int(d["count"])
        h.sum = float(d["sum"])
        h.min = math.inf if d.get("min") is None else float(d["min"])
        h.max = -math.inf if d.get("max") is None else float(d["max"])
        return h


# default histogram spec per metric-name PREFIX: latency-class metrics
# in microseconds span 10us..100s; byte/tick metrics span wider
DEFAULT_HIST_SPEC = (10.0, 1e8, 1.25)


class Registry:
    """One metrics plane: counters (monotone), gauges (last write),
    histograms (fixed log buckets). All methods thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, Histogram] = {}
        self._hist_spec: Dict[str, tuple] = {}

    # -- writes ---------------------------------------------------------

    def inc(self, name: str, value: int = 1, **labels) -> None:
        """Monotone counter increment (negative increments are a
        programming error — counters only move forward)."""
        assert value >= 0, f"counter {name} decremented by {value}"
        key = _label_key(name, labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + int(value)

    def set_gauge(self, name: str, value: float, **labels) -> None:
        with self._lock:
            self._gauges[_label_key(name, labels)] = float(value)

    def declare_histogram(self, name: str, lo: float, hi: float,
                          growth: float = 1.25) -> None:
        """Pin a histogram's bucket spec before first observe (merge
        requires identical bounds, so specs are per-name, declared
        once)."""
        with self._lock:
            self._hist_spec[name] = (lo, hi, growth)

    def observe(self, name: str, value: float, **labels) -> None:
        key = _label_key(name, labels)
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                lo, hi, growth = self._hist_spec.get(name,
                                                     DEFAULT_HIST_SPEC)
                h = self._hists[key] = Histogram(log_buckets(lo, hi,
                                                             growth))
            h.observe(value)

    # -- reads ----------------------------------------------------------

    def counter(self, name: str, **labels) -> int:
        with self._lock:
            return self._counters.get(_label_key(name, labels), 0)

    def gauge(self, name: str, **labels) -> Optional[float]:
        with self._lock:
            return self._gauges.get(_label_key(name, labels))

    def quantile(self, name: str, q: float, **labels) -> float:
        with self._lock:
            h = self._hists.get(_label_key(name, labels))
            return 0.0 if h is None else h.quantile(q)

    def hist_count(self, name: str, **labels) -> int:
        with self._lock:
            h = self._hists.get(_label_key(name, labels))
            return 0 if h is None else h.total

    def keys(self) -> List[str]:
        with self._lock:
            return sorted(set(self._counters) | set(self._gauges)
                          | set(self._hists))

    # -- snapshot / delta / merge ---------------------------------------

    def snapshot(self) -> dict:
        """Plain-dict value of the whole registry — JSON-serializable,
        the unit the flight recorder rings and the exporters render."""
        with self._lock:
            return {
                "magic": SNAPSHOT_MAGIC,
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.state()
                               for k, h in self._hists.items()},
            }

    @staticmethod
    def check_snapshot(doc: dict) -> dict:
        """Validate a snapshot document (the exporters/report strictness
        contract); returns it. Raises ValueError on malformed input."""
        if not isinstance(doc, dict) or doc.get("magic") != SNAPSHOT_MAGIC:
            raise ValueError(
                f"not a metrics snapshot (magic={doc.get('magic')!r} "
                f"!= {SNAPSHOT_MAGIC!r})" if isinstance(doc, dict)
                else "not a metrics snapshot (not an object)")
        for sect in ("counters", "gauges", "histograms"):
            if not isinstance(doc.get(sect), dict):
                raise ValueError(f"snapshot section {sect!r} missing or "
                                 "not an object")
        for k, h in doc["histograms"].items():
            if not isinstance(h, dict) or "counts" not in h \
                    or "bounds" not in h:
                raise ValueError(f"histogram {k!r} malformed")
            if len(h["counts"]) != len(h["bounds"]):
                raise ValueError(
                    f"histogram {k!r}: {len(h['counts'])} counts vs "
                    f"{len(h['bounds'])} bounds")
        return doc

    @staticmethod
    def delta(cur: dict, prev: Optional[dict]) -> dict:
        """cur - prev over counter-like state (counters + histogram
        counts/sums); gauges ride as cur's values. prev=None returns cur
        whole — the flight recorder's first ring entry."""
        if prev is None:
            return cur
        out = {"magic": SNAPSHOT_MAGIC, "gauges": dict(cur["gauges"])}
        out["counters"] = {
            k: v - prev["counters"].get(k, 0)
            for k, v in cur["counters"].items()
            if v - prev["counters"].get(k, 0) != 0
        }
        hists = {}
        for k, h in cur["histograms"].items():
            p = prev["histograms"].get(k)
            if p is None:
                hists[k] = h
                continue
            dcounts = [a - b for a, b in zip(h["counts"], p["counts"])]
            if any(dcounts):
                hists[k] = dict(h, counts=dcounts,
                                count=h["count"] - p["count"],
                                sum=h["sum"] - p["sum"])
        out["histograms"] = hists
        return out

    def merge(self, other: dict) -> None:
        """Fold a snapshot (e.g. another worker's) into this registry:
        counters and histogram buckets add (bounds must match — the
        deterministic-buckets property), gauges last-write."""
        Registry.check_snapshot(other)
        with self._lock:
            for k, v in other["counters"].items():
                self._counters[k] = self._counters.get(k, 0) + int(v)
            self._gauges.update(other["gauges"])
            for k, hd in other["histograms"].items():
                h = Histogram.from_state(hd)
                mine = self._hists.get(k)
                if mine is None:
                    self._hists[k] = h
                    continue
                if not np.array_equal(mine.bounds, h.bounds):
                    raise ValueError(
                        f"histogram {k!r}: bucket bounds differ — "
                        "snapshots only merge across identical specs")
                mine.counts += h.counts
                mine.total += h.total
                mine.sum += h.sum
                mine.min = min(mine.min, h.min)
                mine.max = max(mine.max, h.max)

    def clear(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


def summarize_values(values: Iterable[float], name: str,
                     registry: Registry, unit_lo: float = 10.0,
                     unit_hi: float = 1e8) -> None:
    """Stream a batch of observations into `registry[name]` (helper for
    call sites migrating off ad-hoc percentile math)."""
    registry.declare_histogram(name, unit_lo, unit_hi)
    for v in values:
        registry.observe(name, v)
