"""Topology and bandwidth discovery.

TPU-native re-design of the reference's topology queries
(ref: python/triton_dist/utils.py topology helpers +
kernels/nvidia/comm_perf_model.py:51-93, which probe NVLink/NUMA/NIC
through pynvml). On TPU the static topology is fully determined by the
chip generation (ICI link count/bandwidth — `perf_model.CHIPS`) and the
mesh shape; what remains worth *measuring* is the achieved collective
bandwidth per mesh axis, which this module probes with the chain timer
(link contention, tunnel overhead, and XLA scheduling all land in the
measurement, exactly like the reference's measured-NIC path).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.perf_model import (
    ChipSpec,
    detect_chip,
    estimate_ag_ms,
)
from triton_dist_tpu.runtime.utils import chain_timer


@dataclasses.dataclass
class AxisBandwidth:
    size: int
    measured_gbps: Optional[float]  # None when size == 1 (nothing to move)
    model_gbps: float


@dataclasses.dataclass
class Topology:
    chip: ChipSpec
    process_count: int
    devices_per_process: int
    axes: Dict[str, AxisBandwidth]


def measure_axis_bandwidth(
    mesh, axis: str, nbytes: int = 4 << 20, k_hi: int = 11
) -> float:
    """Achieved all-gather algorithm bandwidth (GB/s per device) over one
    mesh axis: bytes received per device / measured time."""
    n = int(mesh.shape[axis])
    assert n > 1
    rows = max(8, nbytes // (128 * 4))
    x = jnp.ones((n * rows, 128), jnp.float32)

    def build(k):
        def per_rank(x):
            def body(_, x):
                g = jax.lax.all_gather(x, axis, tiled=True)
                return (x * (1.0 + 0.0 * g[0, 0])).astype(x.dtype)

            out = jax.lax.fori_loop(0, k, body, x)
            return jnp.sum(out).reshape(1)

        return jax.jit(jax.shard_map(
            per_rank, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
            check_vma=False,
        ))

    ms, _ = chain_timer(build, (x,), k_hi=k_hi, pairs=3, warmup=1)
    recv_bytes = (n - 1) * rows * 128 * 4
    return recv_bytes / (ms * 1e-3) / 1e9


def discover_topology(
    mesh=None, measure: bool = True, nbytes: int = 4 << 20
) -> Topology:
    """The reference's init-time topology report, TPU edition: chip table
    + mesh axes, optionally with measured per-axis bandwidth."""
    chip = detect_chip()
    axes: Dict[str, AxisBandwidth] = {}
    if mesh is not None:
        for name in mesh.axis_names:
            n = int(mesh.shape[name])
            model_ms = estimate_ag_ms(nbytes, n, chip)
            model_gbps = (
                (n - 1) * nbytes / (model_ms * 1e-3) / 1e9
                if n > 1 else 0.0
            )
            measured = None
            if measure and n > 1:
                measured = measure_axis_bandwidth(mesh, name, nbytes)
            axes[name] = AxisBandwidth(n, measured, model_gbps)
    return Topology(
        chip=chip,
        process_count=jax.process_count(),
        devices_per_process=len(jax.local_devices()),
        axes=axes,
    )
