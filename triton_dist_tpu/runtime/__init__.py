"""Host runtime: distributed bring-up, meshes, symmetric buffers, perf utils.

TPU-native analog of the reference's host runtime
(ref: python/triton_dist/utils.py:182-205 `initialize_distributed`,
:114-176 symmetric tensors, :274-318 perf/printing).
"""

from triton_dist_tpu.runtime.init import (  # noqa: F401
    initialize_distributed,
    finalize_distributed,
    get_default_mesh,
    set_default_mesh,
    make_mesh,
    split_mesh,
    rank,
    num_ranks,
    init_seed,
    TP_AXIS,
    EP_AXIS,
    SP_AXIS,
    PP_AXIS,
    DP_AXIS,
)
from triton_dist_tpu.runtime.symm_mem import (  # noqa: F401
    symm_tensor,
    symm_zeros,
    SymmetricWorkspace,
)
from triton_dist_tpu.runtime.utils import (  # noqa: F401
    dist_print,
    perf_func,
    chain_timer,
    assert_allclose,
    group_profile,
    merge_traces,
)
from triton_dist_tpu.runtime.topology import (  # noqa: F401
    Topology,
    discover_topology,
    measure_axis_bandwidth,
)
