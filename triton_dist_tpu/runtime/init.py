"""Distributed bring-up and mesh management.

TPU-native re-design of the reference's `initialize_distributed`
(ref: python/triton_dist/utils.py:182-205): where the reference bootstraps
torch.distributed + NVSHMEM symmetric heaps over NCCL/gloo, on TPU the
"transport" is the ICI/DCN fabric already owned by the XLA runtime, so
bring-up reduces to (a) optional multi-host jax.distributed init and
(b) constructing a named `jax.sharding.Mesh` whose axes play the role of
NVSHMEM teams (ref: language/extra/libshmem_device.py:326-340 teams ->
mesh axes).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis names. NVSHMEM teams map to mesh axes
# (ref: SURVEY.md "Teams map to mesh axes").
TP_AXIS = "tp"
EP_AXIS = "ep"
SP_AXIS = "sp"
PP_AXIS = "pp"
DP_AXIS = "dp"

_DEFAULT_MESH: Optional[Mesh] = None
_INITIALIZED = False


def _maybe_init_multihost() -> None:
    """Initialize jax.distributed when launched multi-process.

    The reference reads RANK/LOCAL_RANK/WORLD_SIZE from torchrun env
    (ref: utils.py:182-188). The JAX equivalent: coordinator env vars; we
    only call jax.distributed.initialize when they are present so
    single-host usage needs no env.
    """
    if os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get(
        "COORDINATOR_ADDRESS"
    ):
        coord = os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get(
            "COORDINATOR_ADDRESS"
        )
        num_procs = int(os.environ.get("JAX_NUM_PROCESSES", os.environ.get("WORLD_SIZE", "1")))
        proc_id = int(os.environ.get("JAX_PROCESS_ID", os.environ.get("RANK", "0")))
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=num_procs,
            process_id=proc_id,
        )


def make_mesh(
    mesh_shape: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = (TP_AXIS,),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a named device mesh.

    Defaults to a 1-D mesh over all devices on axis "tp" — the analog of the
    reference's world-spanning TP group (ref: utils.py:198-201).
    """
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices, dtype=object)
    if mesh_shape is None:
        mesh_shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    n = int(np.prod(mesh_shape))
    if n > devices.size:
        raise ValueError(
            f"mesh shape {tuple(mesh_shape)} needs {n} devices, have {devices.size}"
        )
    return Mesh(devices[:n].reshape(mesh_shape), tuple(axis_names))


def split_mesh(mesh: Mesh, axis: str, sizes: Sequence[int],
               names: Sequence[str]) -> Mesh:
    """Split one mesh axis into sub-axes (teams): the TPU analog of
    NVSHMEM team_split_strided (ref: shmem teams, libshmem_device.py:
    326-340; test/nvidia/test_team_split.py). A (8,)-"tp" mesh split by
    (2, 4) into ("pp", "tp") yields 2 pipeline groups of 4-way TP; kernels
    address either team by its axis name."""
    import math

    if math.prod(sizes) != mesh.shape[axis]:
        raise ValueError(
            f"split sizes {tuple(sizes)} do not cover axis {axis} "
            f"(size {mesh.shape[axis]})"
        )
    idx = mesh.axis_names.index(axis)
    new_shape = []
    new_names = []
    for i, name in enumerate(mesh.axis_names):
        if i == idx:
            new_shape.extend(sizes)
            new_names.extend(names)
        else:
            new_shape.append(mesh.devices.shape[i])
            new_names.append(name)
    return Mesh(mesh.devices.reshape(new_shape), tuple(new_names))


def initialize_distributed(
    mesh_shape: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = (TP_AXIS,),
    seed: int = 42,
) -> Mesh:
    """Bring up the distributed runtime and install the default mesh.

    Mirrors the reference's single entry point (ref: utils.py:182-205):
    process-group init -> seeds -> symmetric-heap transport init. On TPU the
    symmetric heap is implicit (every kernel's comm buffers live in each
    chip's HBM and are addressed by mesh coordinates), so step three is free.
    """
    global _INITIALIZED
    if not _INITIALIZED:
        _maybe_init_multihost()
        _INITIALIZED = True
    init_seed(seed)
    mesh = make_mesh(mesh_shape, axis_names)
    set_default_mesh(mesh)
    return mesh


def finalize_distributed() -> None:
    """Tear down (ref: utils.py finalize_distributed analog)."""
    global _DEFAULT_MESH, _INITIALIZED
    _DEFAULT_MESH = None
    _INITIALIZED = False


def set_default_mesh(mesh: Mesh) -> None:
    global _DEFAULT_MESH
    _DEFAULT_MESH = mesh


def get_default_mesh() -> Mesh:
    if _DEFAULT_MESH is None:
        raise RuntimeError(
            "No default mesh; call initialize_distributed() or set_default_mesh()."
        )
    return _DEFAULT_MESH


def rank(mesh: Optional[Mesh] = None, axis: str = TP_AXIS) -> int:
    """Host-side rank of this process's first local device along `axis`.

    Looks up the mesh coordinate of the first addressable device, so it is
    correct for multi-host meshes regardless of process/device layout.
    Device-side rank (inside kernels) is lang.my_pe / lax.axis_index
    (ref: distributed_ops.py:57-111 rank()).
    """
    mesh = mesh or get_default_mesh()
    first_local = jax.local_devices()[0]
    axis_pos = mesh.axis_names.index(axis)
    coords = np.argwhere(mesh.devices == first_local)
    if coords.size == 0:
        raise ValueError(f"first local device {first_local} not in mesh {mesh}")
    return int(coords[0][axis_pos])


def num_ranks(mesh: Optional[Mesh] = None, axis: str = TP_AXIS) -> int:
    mesh = mesh or get_default_mesh()
    return int(mesh.shape[axis])


_SEED = 42


def init_seed(seed: int = 42) -> None:
    """Deterministic seeding (ref: utils.py:77-96 init_seed)."""
    global _SEED
    _SEED = seed
    np.random.seed(seed)


def get_prng_key(salt: int = 0) -> jax.Array:
    return jax.random.PRNGKey(_SEED + salt)
