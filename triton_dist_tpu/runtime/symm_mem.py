"""Symmetric-memory abstraction for TPU.

The reference allocates NVSHMEM symmetric tensors: one same-shaped buffer per
rank, remotely addressable (ref: python/triton_dist/utils.py:114-176
`nvshmem_create_tensor(s)`). On TPU the analog is a sharded jax.Array over a
mesh axis: each device owns an identically-shaped shard in its HBM, and
Pallas kernels running under shard_map address peers' shards via async remote
DMA (`pltpu.make_async_remote_copy`) with mesh-logical device ids. There is
no persistent heap to manage — XLA owns allocation — so "symmetric tensors"
are ordinary arrays with a guaranteed uniform per-device local shape.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.runtime.init import get_default_mesh, TP_AXIS


def symm_sharding(mesh: Mesh, axis: str) -> NamedSharding:
    """Sharding that gives every device along `axis` one leading-dim shard."""
    return NamedSharding(mesh, P(axis))


def symm_tensor(
    local_shape: Tuple[int, ...],
    dtype=jnp.float32,
    mesh: Optional[Mesh] = None,
    axis: str = TP_AXIS,
    fill: Optional[float] = None,
) -> jax.Array:
    """Allocate a symmetric tensor: global shape (n_ranks, *local_shape),
    sharded along the leading dim so each device holds `local_shape`.

    Analog of `nvshmem_create_tensors` returning per-rank peer views
    (ref: utils.py:121-136).
    """
    mesh = mesh or get_default_mesh()
    n = int(mesh.shape[axis])
    global_shape = (n,) + tuple(local_shape)
    if fill is None:
        arr = jnp.zeros(global_shape, dtype=dtype)
    else:
        arr = jnp.full(global_shape, fill, dtype=dtype)
    return jax.device_put(arr, symm_sharding(mesh, axis))


def symm_zeros(local_shape, dtype=jnp.float32, mesh=None, axis=TP_AXIS):
    return symm_tensor(local_shape, dtype=dtype, mesh=mesh, axis=axis)


@dataclass
class SymmetricWorkspace:
    """A reusable bag of symmetric buffers keyed by (name, shape, dtype).

    Kernel contexts in the reference own symmetric workspaces + barrier
    tensors (ref: kernels/nvidia/allgather_gemm.py:417-487
    `AllGatherGEMMTensorParallelContext`). On TPU, barrier words are Pallas
    semaphores scoped to a single fused kernel, so the workspace only needs
    data staging buffers. A caller that donates a buffer to a jit (input
    donation deletes the array) must store the aliased output back with
    `update()` before the next `get()`.
    """

    mesh: Mesh
    axis: str = TP_AXIS
    _buffers: dict = field(default_factory=dict)

    def contains(self, name: str, local_shape: Tuple[int, ...],
                 dtype=jnp.float32) -> bool:
        key = (name, tuple(local_shape), jnp.dtype(dtype).name)
        return key in self._buffers

    def get(self, name: str, local_shape: Tuple[int, ...], dtype=jnp.float32):
        key = (name, tuple(local_shape), jnp.dtype(dtype).name)
        if key not in self._buffers:
            self._buffers[key] = symm_tensor(
                local_shape, dtype=dtype, mesh=self.mesh, axis=self.axis
            )
        return self._buffers[key]

    def update(self, name: str, arr) -> None:
        """Store back the aliased output of a donating kernel so the cache
        never hands out a deleted array."""
        key = (name, tuple(arr.shape[1:]), jnp.dtype(arr.dtype).name)
        self._buffers[key] = arr

    def free(self) -> None:
        self._buffers.clear()
