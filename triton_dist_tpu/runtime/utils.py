"""Perf measurement, rank-filtered printing, allclose with diff dump.

TPU-native analogs of the reference host utilities
(ref: python/triton_dist/utils.py:274-318 perf_func/dist_print,
:870-899 assert_allclose, :505-589 group_profile).
"""

from __future__ import annotations

import time
from typing import Callable, Tuple

import jax
import numpy as np


def dist_print(*args, prefix: bool = True, allowed_ranks="0", **kwargs):
    """Rank-filtered printing (ref: utils.py:289-318).

    allowed_ranks: comma string, list of ints, or "all".
    """
    r = jax.process_index()
    if allowed_ranks == "all":
        allowed = None
    elif isinstance(allowed_ranks, str):
        allowed = {int(x) for x in allowed_ranks.split(",") if x != ""}
    else:
        allowed = set(int(x) for x in allowed_ranks)
    if allowed is None or r in allowed:
        if prefix:
            print(f"[rank {r}]", *args, **kwargs)
        else:
            print(*args, **kwargs)


def perf_func(
    fn: Callable[[], jax.Array],
    iters: int = 10,
    warmup_iters: int = 3,
) -> Tuple[object, float]:
    """Time `fn` with blocking sync; returns (last_output, ms_per_iter).

    The reference times with CUDA events (ref: utils.py:274-286); on TPU we
    block on the async dispatch queue with block_until_ready, which measures
    the same device-side wall clock once warm.
    """
    out = None
    for _ in range(warmup_iters):
        out = fn()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    jax.block_until_ready(out)
    t1 = time.perf_counter()
    return out, (t1 - t0) * 1e3 / iters


def chain_timer(build_fn, args, k_lo=1, k_hi=101, pairs=9, warmup=2):
    """Interleaved paired diffs of two chain lengths inside one jit.

    The reliable timing method behind bench.py on a high-RTT link (the
    TPU may sit behind a ~90 ms tunnel): build_fn(k) must return a jitted
    callable whose device time scales linearly in k via a data-dependent
    chain; the per-iteration estimate is the median of paired
    (k_hi - k_lo)-normalized differences, so RTT and drift cancel. A
    non-positive median raises (never clamped — round-2 ADVICE)."""
    f_lo, f_hi = build_fn(k_lo), build_fn(k_hi)
    np.asarray(f_lo(*args))  # compile
    np.asarray(f_hi(*args))

    def once(f):
        t0 = time.perf_counter()
        np.asarray(f(*args))  # host fetch forces completion
        return (time.perf_counter() - t0) * 1e3

    for _ in range(warmup):
        once(f_lo), once(f_hi)
    diffs = [
        (once(f_hi) - once(f_lo)) / (k_hi - k_lo) for _ in range(pairs)
    ]
    ms = float(np.median(diffs))
    if ms <= 0:
        raise RuntimeError(f"measurement failed: median diff {ms} <= 0")
    # p25/min ride along for pool-interference context: contamination is
    # predominantly upward (the hi chain is ~k_hi/k_lo times more exposed
    # than the lo chain), so the lower tail approximates the uncontended
    # latency. Tail stats drop glitched non-positive pairs (a lo-chain
    # RTT spike can make a diff negative — same filter ratio_timer
    # applies). The headline stays the median — never the optimistic
    # tail.
    pos = [d for d in diffs if d > 0]
    return ms, {
        "diffs_ms": [round(d, 4) for d in diffs],
        "k": (k_lo, k_hi),
        "p25_ms": round(float(np.percentile(pos, 25)), 4),
        "min_ms": round(float(np.min(pos)), 4),
    }


def ratio_timer(build_a, build_b, args, k_lo=1, k_hi=51, pairs=7,
                warmup=2):
    """Median per-round ratio of two chain-timed kernels.

    The chip's clock drifts on a seconds timescale (shared pool /
    DVFS): two chain_timer calls made back to back can disagree by
    ±8%, which swamps a few-percent kernel comparison. Here each round
    measures BOTH chains within milliseconds of each other, so the
    drift cancels in the per-round ratio; the cross-round median then
    rejects stragglers. Returns (ratio, a_ms, b_ms)."""
    fa_lo, fa_hi = build_a(k_lo), build_a(k_hi)
    fb_lo, fb_hi = build_b(k_lo), build_b(k_hi)
    for f in (fa_lo, fa_hi, fb_lo, fb_hi):
        np.asarray(f(*args))  # compile

    def once(f):
        t0 = time.perf_counter()
        np.asarray(f(*args))  # host fetch forces completion
        return (time.perf_counter() - t0) * 1e3

    for _ in range(warmup):
        once(fa_hi), once(fb_hi)
    ratios, da_all, db_all = [], [], []
    for _ in range(pairs):
        da = (once(fa_hi) - once(fa_lo)) / (k_hi - k_lo)
        db = (once(fb_hi) - once(fb_lo)) / (k_hi - k_lo)
        if da > 0 and db > 0:  # drop glitched rounds, never clamp
            ratios.append(da / db)
            da_all.append(da)
            db_all.append(db)
    if not ratios:
        raise RuntimeError("ratio measurement failed: no positive rounds")
    return (float(np.median(ratios)), float(np.median(da_all)),
            float(np.median(db_all)))


def _once_ms(f, args):
    t0 = time.perf_counter()
    np.asarray(f(*args))  # host fetch forces completion
    return (time.perf_counter() - t0) * 1e3


def _theil_sen(t_by_k: dict) -> float:
    """Median of pairwise slopes over {chain length: median time}."""
    ks = sorted(t_by_k)
    slopes = [
        (t_by_k[k2] - t_by_k[k1]) / (k2 - k1)
        for i, k1 in enumerate(ks) for k2 in ks[i + 1:]
    ]
    return float(np.median(slopes))


def slope_timer(build_fn, args, ks=(1, 201, 401), rounds=6, warmup=2):
    """Per-iteration time via a robust slope fit over chain lengths.

    Why not paired diffs at small k: the tunnel's fixed per-call overhead
    is ~70-125 ms and jitters BOTH ways (a 76.9 ms k=51 sample was
    measured below the 108 ms k=1 baseline), so a 16 ms chain signal
    drowns. The answer is signal amplification — chains long enough
    (ks up to ~400 iterations for sub-ms kernels) that the per-k spread
    is small relative to the span — plus a median per chain length (the
    jitter is two-sided, so min would chase deflated samples) and a
    Theil-Sen slope (median of pairwise slopes) across chain lengths,
    which tolerates one fully-contaminated k. Costs one compile per
    chain length — use for small kernels, not model-scale programs."""
    fns = {k: build_fn(k) for k in ks}
    for f in fns.values():
        np.asarray(f(*args))  # compile
    for _ in range(warmup):
        for f in fns.values():
            _once_ms(f, args)
    t_med = {
        k: float(np.median([_once_ms(fns[k], args)
                            for _ in range(rounds)]))
        for k in ks
    }
    ms = _theil_sen(t_med)
    if ms <= 0:
        raise RuntimeError(f"measurement failed: median slope {ms} <= 0")
    return ms, {"t_med_ms": {k: round(v, 4) for k, v in t_med.items()}}


def slope_ratio_timer(build_a, build_b, args, ks=(1, 201, 401), rounds=6,
                      warmup=2):
    """Ratio of two kernels' per-iteration slopes, rounds interleaved
    across both arms so a clock-drift window hits them alike. Returns
    (ratio, a_ms, b_ms). See slope_timer for the robustness argument."""
    fa = {k: build_a(k) for k in ks}
    fb = {k: build_b(k) for k in ks}
    for f in list(fa.values()) + list(fb.values()):
        np.asarray(f(*args))  # compile
    for _ in range(warmup):
        for k in ks:
            _once_ms(fa[k], args), _once_ms(fb[k], args)
    ta = {k: [] for k in ks}
    tb = {k: [] for k in ks}
    for _ in range(rounds):
        for k in ks:
            ta[k].append(_once_ms(fa[k], args))
            tb[k].append(_once_ms(fb[k], args))

    def slope(t):
        return _theil_sen({k: float(np.median(v)) for k, v in t.items()})

    a_ms, b_ms = slope(ta), slope(tb)
    if a_ms <= 0 or b_ms <= 0:
        raise RuntimeError(
            f"measurement failed: slopes {a_ms}, {b_ms} not positive")
    return a_ms / b_ms, a_ms, b_ms


def assert_allclose(x, y, atol=1e-3, rtol=1e-3, verbose=True):
    """allclose with mismatch dump (ref: utils.py:870-899)."""
    x = np.asarray(x)
    y = np.asarray(y)
    if x.shape != y.shape:
        raise AssertionError(f"shape mismatch {x.shape} vs {y.shape}")
    if np.allclose(x, y, atol=atol, rtol=rtol):
        return
    diff = np.abs(x.astype(np.float64) - y.astype(np.float64))
    mask = diff > (atol + rtol * np.abs(y.astype(np.float64)))
    n_bad = int(mask.sum())
    idx = np.argwhere(mask)[:10]
    msg = [
        f"assert_allclose failed: {n_bad}/{x.size} mismatched "
        f"(atol={atol}, rtol={rtol}), max_abs_diff={diff.max():.6g}"
    ]
    if verbose:
        for i in idx:
            ti = tuple(int(v) for v in i)
            msg.append(f"  at {ti}: {x[ti]!r} vs {y[ti]!r}")
    raise AssertionError("\n".join(msg))


# group_profile / merge_traces moved to triton_dist_tpu.trace.export —
# ONE trace-merging code path beside the in-kernel trace exporter. These
# aliases keep the historical `runtime.utils` import surface working.
from triton_dist_tpu.trace.export import (  # noqa: E402,F401
    group_profile,
    merge_traces,
)
