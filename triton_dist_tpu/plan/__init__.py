"""triton_dist_tpu.plan — graph-level overlap autofusion (ROADMAP item 5).

The reference's thesis is that compute/communication pairing is a
*compiler decision*: every fused pairing this repo ships (AG+GEMM,
GEMM+RS, GEMM+AR, the grouped-GEMM MoE pipeline, the SP flash prefill,
the quantized wire) used to be hand-wired at a specific call site in
`layers/` and `models/dense.py`, with the `perf_model` choosers
consulted ad hoc. This package is the ONE planning pass over all of
them (cf. arXiv 2305.06942's fused computation-collective rewriting and
ML-Triton's multi-level lowering, arXiv 2503.14985):

  ir.py       a small explicit layer-IR — op nodes (gemm / grouped-gemm
              / attention / norm / collective) with shapes, dtypes,
              sharding axis, and wire-format eligibility — plus builders
              that emit it from the dense/MoE forward structure.
  planner.py  pattern-matches producer -> collective -> consumer triples
              in the IR and prices fused-vs-sequential, wire format,
              prefill impl, tile configs, and EP chunking per triple —
              the existing `perf_model` estimators and `autotuner`
              pruners stay the pricing primitives; the planner owns the
              composition behind ONE `plan_forward(ir, world, rig)`.
  execute.py  routes the model forward through the Plan: the layer MODES
              registries (tp_attn / tp_mlp / tp_moe) are the rewrite
              targets, so `models/dense.py` carries no hand
              fused-vs-sequential branches.

Every fused rewrite must be backed by its registered `@verify.protocol`
model; a triple whose fusion has no shipped protocol skeleton falls
back to the sequential lowering LOUDLY (a warnings.warn the tests pin).
The acceptance oracle is the house discipline: planned execution is
bit-identical to the hand-routed path it selects (tier-1-pinned), and a
new naively-wired model config gets fused paths with zero layer code.

See docs/performance.md "Fusion planner" for the triple taxonomy,
decision inputs, and fallback rules; scripts/plan_report.py renders a
plan with per-triple pricing.
"""

from triton_dist_tpu.plan.ir import (  # noqa: F401
    LayerIR,
    OpNode,
    Triple,
    build_dense_ir,
    find_triples,
)
from triton_dist_tpu.plan.planner import (  # noqa: F401
    PATTERN_PROTOCOLS,
    SEQ_SHARDED_MODES,
    Plan,
    TripleDecision,
    plan_dense_forward,
    plan_ep_chunks,
    plan_forward,
    route_prefill_impl,
)
from triton_dist_tpu.plan.execute import (  # noqa: F401
    attn_fwd,
    ffn_fwd,
    gather_tokens,
    shard_tokens,
)
