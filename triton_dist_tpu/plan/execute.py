"""Execute a Plan: route the model forward through the planner's
decisions (plan/__init__ doc).

The layer MODES registries (tp_attn / tp_mlp / tp_moe) are the rewrite
targets: a TripleDecision never constructs a kernel call itself, it
picks WHICH registered lowering the layer runs, so every fused path the
plan can select is exactly a hand path the tier-1 suite already pins —
that is the bit-identity oracle. `models/dense.py` calls these four
helpers and carries no fused-vs-sequential routing of its own:

  shard_tokens / gather_tokens   the sequence-sharding boundary
      (Plan.seq_sharded — was dense.py's inline
      `mode in ("dist", "xla")` predicate)
  attn_fwd / ffn_fwd             the per-block dispatch through the
      layer registries under Plan.mode / Plan.ffn_mode

Unknown triples never reach here: the planner already lowered them
sequentially (loudly), so execution only ever sees mode strings the
layer registries define — an unplanned mode string is a KeyError at
trace time, not a silent wrong kernel.

Tuned launches: when a TripleDecision carries an applied_config (a
MEASURED tune-cache winner; planner module doc), `_site_configs`
threads it into the layer call as the kernel `config=` kwarg — the dist
lowering takes both ag_config and rs_config, the ar lowering only
rs_config (its gather is the kernel-internal allreduce), the xla
lowering none (no pallas kernels to configure). An empty cache leaves
every applied_config blank, all kwargs stay None, and the compiled
program is byte-for-byte the legacy one (the zero-risk off-switch,
pinned in tests/test_tuning_loop.py).
"""

from __future__ import annotations

import jax

from triton_dist_tpu.plan.planner import Plan


def shard_tokens(x: jax.Array, axis: str, plan: Plan) -> jax.Array:
    """Slice this rank's token rows on forward entry when the plan's
    lowering is sequence-sharded; replicated lowerings pass through."""
    if not plan.seq_sharded:
        return x
    n = jax.lax.axis_size(axis)
    m = x.shape[0]
    assert m % n == 0, (
        f"B*S={m} must divide tp={n} in {plan.mode} mode")
    me = jax.lax.axis_index(axis)
    return jax.lax.dynamic_slice_in_dim(x, me * (m // n), m // n)


def gather_tokens(x: jax.Array, axis: str, plan: Plan) -> jax.Array:
    """Regather the full token set before the head (the seq-sharded
    lowerings' closing collective; replicated lowerings pass through)."""
    if not plan.seq_sharded:
        return x
    return jax.lax.all_gather(x, axis, tiled=True)


def _site_configs(plan: Plan, mode: str, ag_site: str, rs_site: str) -> dict:
    """Kwargs threading the plan's applied tune-cache winners into a
    layer call — only the kwargs the `mode` lowering accepts, only when
    the decision actually carries a winner (empty cache => {})."""
    kw = {}
    if mode in ("dist",):
        cfg = plan.launch_config(ag_site)
        if cfg is not None:
            kw["ag_config"] = cfg
    if mode in ("dist", "ar"):
        cfg = plan.launch_config(rs_site)
        if cfg is not None:
            kw["rs_config"] = cfg
    return kw


def attn_fwd(plan: Plan, h, attn_params, spec, cos, sin, positions,
             batch, axis, kv_cache, kv_len):
    """The attention block under the plan: tp_attn's MODES registry
    keyed by Plan.mode, prefill impl per Plan.attn_impl (None = the
    planner's per-shape route_prefill_impl at the call site), tile
    configs and flash block per the plan's applied tune-cache winners
    (module doc)."""
    from triton_dist_tpu.layers import tp_attn_fwd

    kw = _site_configs(plan, plan.mode, "attn.ag", "attn.rs")
    if plan.attn_block is not None:
        kw["attn_block"] = plan.attn_block
    return tp_attn_fwd(
        h, attn_params, spec, cos, sin, positions, batch,
        axis=axis, mode=plan.mode, kv_cache=kv_cache, kv_len=kv_len,
        attn_impl=plan.attn_impl, **kw,
    )


def ffn_fwd(plan: Plan, h, params, axis, top_k=None):
    """The FFN block under the plan: tp_moe's registry keyed by
    Plan.moe_mode for MoE configs (which is where the planner may pick
    the one-kernel fused pipeline), tp_mlp's keyed by Plan.mode with the
    plan's applied tune-cache winners threaded in (module doc; the MoE
    registry lowerings pick their own chunking via plan_ep_chunks, which
    consults the same cache)."""
    if plan.is_moe:
        from triton_dist_tpu.layers import tp_moe_fwd

        return tp_moe_fwd(h, params, top_k, axis=axis,
                          mode=plan.moe_mode)
    from triton_dist_tpu.layers import tp_mlp_fwd

    kw = _site_configs(plan, plan.mode, "mlp.ag", "mlp.rs")
    return tp_mlp_fwd(h, params, axis=axis, mode=plan.mode, **kw)
