"""The fusion planner: price the candidate lowerings of one LayerIR and
emit a `Plan` (plan/__init__ doc; ROADMAP item 5).

The planner owns COMPOSITION, not pricing: every number it compares
comes from the existing `perf_model` estimators (estimate_ag_gemm_ms,
estimate_ag_ms/rs/ar, estimate_gemm_ms, choose_wire_format,
choose_prefill_impl, choose_ep_chunks) and the `autotuner` pruners —
those stay the single sources of truth. What used to be scattered as
hand `mode=` wiring in `models/dense.py` and `layers/tp_moe.py` is here
one decision per matched producer -> collective -> consumer triple:

  lowering   "dist" fuses AG+GEMM / GEMM+RS, "xla" runs the sequential
             lax reference, "ar" elides the gather (replicated
             activations) and fuses the reduction as GEMM+AR, and the
             MoE "fused" pipeline runs the one-kernel grouped path.
  verify     a fusion is only CHOSEN when its transport skeleton has a
             shipped `@verify.protocol` model (PATTERN_PROTOCOLS);
             otherwise the triple falls back to sequential with a
             warnings.warn the tests pin. A forced legacy mode string
             is the caller's contract and is honored bit-for-bit.
  wire       per-collective via choose_wire_format under the plan's
             error budget (the default budget 0.0 forces native wire,
             which is what keeps planned execution bit-identical to the
             hand path).
  configs    the autotuner's top-1 pruned tile config is recorded per
             fused triple as the pricing witness. What LAUNCHES is a
             separate decision: a MEASURED winner from the persistent
             tune cache (autotuner.TuneCache — same rig, shape bucket,
             dtype, world and wire only) lands in
             TripleDecision.applied_config and plan/execute threads it
             into the kernel call, re-validated by the launch VMEM
             gates (stale entries degrade loudly to the default). With
             an empty cache every applied_config is "" and execution
             compiles exactly the legacy default-tile program, so the
             bit-identity oracle still gates the unoverridden world;
             overridden launches are gated by the epsilon-band oracle
             (verify/epsilon.py) instead — tile overrides reassociate
             the fold order, so bitwise equality is the wrong contract
             there.

`plan_dense_forward` memoizes on the hashable (cfg, geometry, mode)
tuple, so the model forward, `models/engine.Engine`, the serve
`Scheduler`, and `mega.schedule_graph` all hold the SAME Plan object
for the same step shape — resident serving and one-shot forwards agree
on pairings by construction.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import warnings
from typing import Optional, Tuple

from triton_dist_tpu.plan.ir import LayerIR, build_dense_ir, find_triples

# The two sequence-sharded lowerings: forward slices tokens by rank on
# entry and regathers before the head. This was models/dense.py's
# inline `mode in ("dist", "xla")` predicate — now THE routing fact,
# owned by the planner and consumed via Plan.seq_sharded.
SEQ_SHARDED_MODES = ("dist", "xla")

# fusion pattern -> the @verify.protocol skeleton covering its
# transport. The grouped-GEMM (MoE) patterns ride the dense skeletons:
# the verified property is the ring-AG / ring-RS HB-graph, which the
# grouped variants share (kernels/allgather_group_gemm.py builds on the
# same per-step semaphore ladder allgather_gemm ships).
PATTERN_PROTOCOLS = {
    "ag+gemm": "allgather_gemm",
    "ag+grouped_gemm": "allgather_gemm",
    "gemm+rs": "gemm_reduce_scatter",
    "grouped_gemm+rs": "gemm_reduce_scatter",
    "gemm+ar": "allreduce",
    "a2a+grouped_gemm": "ep_dispatch_chunked",
}

# (pattern, site-prefix) -> the fused kernel plan/execute can rewrite
# to, per lowering family. The "head" site is deliberately absent:
# the logits path is numerics-critical (sampling reads it bitwise) and
# stays sequential by design.
_DIST_KERNELS = {
    ("ag+gemm", "attn"): "ag_gemm",
    ("ag+gemm", "mlp"): "ag_gemm",
    ("ag+grouped_gemm", "moe"): "ag_group_gemm",
    ("gemm+rs", "attn"): "gemm_rs",
    ("gemm+rs", "mlp"): "gemm_rs",
    ("grouped_gemm+rs", "moe"): "moe_reduce_rs",
}
_AR_KERNELS = {
    ("gemm+rs", "attn"): "gemm_ar",
    ("gemm+rs", "mlp"): "gemm_ar",
}
_FUSED_MOE_KERNELS = {
    ("ag+grouped_gemm", "moe"): "fused_ag_moe_up",
    ("grouped_gemm+rs", "moe"): "fused_moe_down_combine_rs",
}

_DENSE_MODES = ("dist", "ar", "xla")


@dataclasses.dataclass(frozen=True)
class TripleDecision:
    """One collective site's lowering under the chosen mode.

    lowered   "ag+gemm" | "gemm+rs" | "gemm+ar" | "sequential" |
              "elided" — what the site becomes.
    kernel    the fused kernel (or lax primitive) the site lowers to.
    protocol  the shipped verify skeleton backing a fused pick (None
              for sequential lowerings).
    est_fused_ms / est_seq_ms   both prices, always recorded, so the
              report can show the margin the decision rests on.
    config    autotuner top-1 tile config (pricing witness; see module
              doc).
    applied_config   the config the launch actually overrides with
              ("" = the kernel's own default tiles). Only a MEASURED
              winner from the persistent tune cache lands here
              (autotuner.TuneCache, same rig + shape-bucket + wire
              only), re-validated against the launch-fit gates at plan
              time — the model-ranked witness never launches un-measured,
              so an empty cache compiles exactly the legacy program.
    config_source    "" (default tiles) | "cache" (measured winner,
              provenance in the cache entry's round stamp).
    """

    site: str
    pattern: str
    lowered: str
    fused: bool
    kernel: str
    protocol: Optional[str]
    wire: str
    est_fused_ms: float
    est_seq_ms: float
    config: str = ""
    reason: str = ""
    applied_config: str = ""
    config_source: str = ""

    @property
    def chosen_ms(self) -> float:
        return self.est_fused_ms if self.fused else self.est_seq_ms


@dataclasses.dataclass(frozen=True)
class Plan:
    """The one object every consumer routes through (module doc).

    mode       the attention + dense-MLP lowering ("dist"|"xla"|"ar").
    moe_mode   the MoE FFN lowering ("dist"|"xla"|"ar"|"fused").
    seq_sharded  whether the forward slices tokens by rank on entry
               (mode in SEQ_SHARDED_MODES) — consumed by
               plan/execute.shard_tokens / gather_tokens.
    attn_impl  forced prefill impl ("xla"|"pallas") or None = the
               per-shape `route_prefill_impl` decision at the call
               site (still the planner's single predicate).
    """

    plan_id: str
    key: str
    world: int
    chip: str
    requested: str
    mode: str
    moe_mode: str
    seq_sharded: bool
    is_moe: bool
    attn_impl: Optional[str]
    decisions: Tuple[TripleDecision, ...]
    est_layer_ms: float
    mega_strategy: str = "least_loaded"
    # measured flash-prefill KV page height from the tune cache (None =
    # the kernel's default block; plan/execute threads it into the
    # attention prefill fold). attn_block_source mirrors
    # TripleDecision.config_source.
    attn_block: Optional[int] = None
    attn_block_source: str = ""

    @property
    def ffn_mode(self) -> str:
        """The mode string the FFN layer call executes under."""
        return self.moe_mode if self.is_moe else self.mode

    def fused_sites(self) -> Tuple[str, ...]:
        return tuple(d.site for d in self.decisions if d.fused)

    def applied_configs(self) -> dict:
        """site -> (applied_config, source) for every decision that
        launches a non-default config (plan_report's applied_config
        column; Scheduler.metrics surfaces the count)."""
        out = {d.site: (d.applied_config, d.config_source)
               for d in self.decisions if d.applied_config}
        if self.attn_block is not None:
            out["attn.core"] = (f"FlashPrefillConfig(block={self.attn_block})",
                                self.attn_block_source)
        return out

    def launch_config(self, site: str):
        """The parsed config OBJECT a site launches with, or None for
        the kernel default — the single accessor plan/execute threads
        into the layer entry points."""
        for d in self.decisions:
            if d.site == site and d.applied_config:
                from triton_dist_tpu import autotuner as at

                return at.parse_config(_config_family(d.kernel),
                                       d.applied_config)
        return None


@functools.lru_cache(maxsize=1)
def _shipped_protocols() -> frozenset:
    from triton_dist_tpu.verify import registry

    return frozenset(registry.load_shipped().keys())


def _resolve_chip(rig):
    from triton_dist_tpu import perf_model as pm

    if rig is None:
        return pm.detect_chip()
    if isinstance(rig, pm.ChipSpec):
        return rig
    if rig in pm.CHIPS:
        return pm.CHIPS[rig]
    for spec in pm.CHIPS.values():
        if spec.name == rig:
            return spec
    raise KeyError(f"unknown rig {rig!r}; expected one of "
                   f"{sorted(set(s.name for s in pm.CHIPS.values()))}")


def _top_config(pattern: str, cons_or_prod, world: int, chip) -> str:
    """The autotuner's best tile config for a fused triple (top_n=1),
    recorded as the pricing witness. Never fatal: an unpriceable shape
    returns ''."""
    from triton_dist_tpu import autotuner as at

    node = cons_or_prod
    try:
        if pattern in ("ag+gemm", "ag+grouped_gemm"):
            picks = at.prune_ag_gemm_configs(
                node.m, node.k, node.n, dtype=node.dtype, chip=chip,
                top_n=1)
        elif pattern in ("gemm+rs", "grouped_gemm+rs"):
            picks = at.prune_gemm_rs_local_configs(
                node.m, node.k, node.n, dtype=node.dtype, chip=chip,
                top_n=1)
        else:
            return ""
        return str(picks[0]) if picks else ""
    except Exception:  # noqa: BLE001 — pricing witness only; never block planning
        return ""


def _config_family(kernel: str) -> str:
    """Fused-kernel name -> the tune-cache family whose config class it
    launches with (the grouped variants ride the dense families' config
    dataclasses)."""
    if kernel in ("ag_gemm", "ag_group_gemm", "fused_ag_moe_up"):
        return "ag_gemm"
    if kernel in ("gemm_rs", "moe_reduce_rs", "fused_moe_down_combine_rs"):
        return "gemm_rs"
    if kernel == "gemm_ar":
        return "gemm_ar"
    return kernel


def _cached_config(kernel: str, node, world: int, chip, wire: str):
    """Consult the persistent tune cache for a measured winner at this
    fused site: same kernel family, shape bucket, dtype, world, wire
    format AND rig only (autotuner.TuneCache — measured beats modeled,
    never across rigs). A hit is re-validated against the launch-fit
    gates with the SAME VMEM accounting the pruner admits configs by, so
    a stale entry (code moved, chip changed) degrades LOUDLY to the
    default tiles — never to a Mosaic allocation failure. Returns
    (applied_config_repr, source): ("", "") = launch the default."""
    from triton_dist_tpu import autotuner as at

    family = _config_family(kernel)
    if min(node.m, node.k, node.n) <= 0:
        # degenerate geometry (e.g. fewer heads than ranks shards a
        # projection to zero columns) — nothing to tune, and the fit
        # gates divide by these dims
        return "", ""
    if family in ("ag_gemm", "gemm_rs", "gemm_ar"):
        bucket = at.shape_bucket(node.m, node.k, node.n)
    else:
        return "", ""
    entry = at.active_tune_cache().lookup(
        family, bucket, node.dtype, world, wire,
        at.rig_name(chip, world))
    if entry is None:
        return "", ""
    try:
        cfg = at.parse_config(family, entry["config"])
    except ValueError as e:
        warnings.warn(
            f"plan: tune-cache entry for {node.name} is unparseable "
            f"({e}); launching default tiles", stacklevel=2)
        return "", ""
    if family == "ag_gemm":
        ok = at.ag_gemm_config_fits(cfg, node.m, node.k, node.n,
                                    dtype=node.dtype, chip=chip)
    elif world <= 1:
        # the world=1 local blocked-matmul regime is what the sweeps
        # measure; the ring regimes at world>1 fit their own tiles
        ok = at.gemm_rs_local_config_fits(cfg, node.m, node.k, node.n,
                                          dtype=node.dtype, chip=chip)
    else:
        ok = True
    if not ok:
        warnings.warn(
            f"plan: cached {family} config {entry['config']!r} for "
            f"{node.name} no longer passes the launch VMEM gate at "
            f"(m={node.m}, k={node.k}, n={node.n}); launching default "
            "tiles (stale tune cache — re-run the bench sweep)",
            stacklevel=2)
        return "", ""
    return entry["config"], "cache"


def _wire_name(node, world: int, chip, error_budget: float,
               collective: str) -> str:
    if not node.wire_eligible or world <= 1:
        return "native"
    from triton_dist_tpu.perf_model import choose_wire_format

    fmt = choose_wire_format(node.bytes, world, dtype=node.dtype,
                             error_budget=error_budget,
                             collective=collective, chip=chip)
    return getattr(fmt, "kind", str(fmt))


def _decide(ir: LayerIR, tri, mode: str, moe_mode: str, world: int,
            chip, shipped, error_budget: float, forced: bool):
    """One TripleDecision under the (mode, moe_mode) lowering pair."""
    from triton_dist_tpu import perf_model as pm

    nodes = ir.nodes
    node = nodes[tri.collective]
    # the kernel family is the COMPUTE op's (the MoE block's gather is
    # named mlp.ag but feeds moe.up — the grouped kernels own it)
    comp = (nodes[tri.consumer] if tri.consumer >= 0
            else nodes[tri.producer] if tri.producer >= 0 else node)
    site = comp.name.split(".")[0]
    site_mode = moe_mode if site == "moe" else mode
    dtype = node.dtype

    def seq(lowered, kernel, f_ms, s_ms, reason, wire="native",
            config=""):
        return TripleDecision(site=node.name, pattern=tri.pattern,
                              lowered=lowered, fused=False,
                              kernel=kernel, protocol=None, wire=wire,
                              est_fused_ms=f_ms, est_seq_ms=s_ms,
                              config=config, reason=reason)

    def fused(lowered, kernel, proto, f_ms, s_ms, reason, wire,
              config, comp_node=None):
        if proto not in shipped and not forced:
            warnings.warn(
                f"plan: fusion {tri.pattern!r} at {node.name} has no "
                f"shipped verify protocol {proto!r}; falling back to "
                f"sequential", stacklevel=2)
            return seq("sequential", "lax." + (node.collective or "?"),
                       f_ms, s_ms,
                       f"unverified fusion (protocol {proto!r} not "
                       f"shipped)", wire=wire)
        if proto not in shipped:
            reason += f" [forced: protocol {proto!r} not shipped]"
            warnings.warn(
                f"plan: forced mode keeps unverified fusion "
                f"{tri.pattern!r} at {node.name} (protocol {proto!r} "
                f"not shipped)", stacklevel=2)
        applied, source = ("", "") if comp_node is None else \
            _cached_config(kernel, comp_node, world, chip, wire)
        return TripleDecision(site=node.name, pattern=tri.pattern,
                              lowered=lowered, fused=True,
                              kernel=kernel, protocol=proto, wire=wire,
                              est_fused_ms=f_ms, est_seq_ms=s_ms,
                              config=config, reason=reason,
                              applied_config=applied,
                              config_source=source)

    if tri.pattern == "unknown":
        coll_ms = (pm.estimate_ag_ms(node.bytes, world, chip)
                   if node.collective == "all_gather"
                   else pm.estimate_ar_ms(node.bytes, world, chip))
        if node.wire_eligible:
            # a fusable-looking site the matcher could not pair: the
            # loud-fallback contract (tests pin this warning)
            warnings.warn(
                f"plan: unmatched collective {node.name} "
                f"({node.collective}); lowering sequentially",
                stacklevel=2)
            reason = "unmatched collective: sequential fallback"
        else:
            reason = "terminal numerics-critical collective"
        return seq("sequential", "lax." + (node.collective or "?"),
                   coll_ms, coll_ms, reason)

    wire = _wire_name(
        node, world, chip, error_budget,
        "allgather" if node.collective == "all_gather" else "allreduce")

    if tri.pattern.startswith("ag+"):
        cons = nodes[tri.consumer]
        gemm_ms = pm.estimate_gemm_ms(cons.m, cons.n, cons.k,
                                      dtype=dtype, chip=chip)
        ag_ms = pm.estimate_ag_ms(node.bytes, world, chip)
        s_ms = ag_ms + gemm_ms
        if cons.kind == "gemm":
            f_ms = pm.estimate_ag_gemm_ms(cons.m, cons.k, cons.n,
                                          world, dtype=dtype, chip=chip)
        else:
            # grouped consumer: the gather moves tokens, not
            # token*top_k rows — bound it from the node's own payload
            f_ms = max(gemm_ms, ag_ms) + 0.1 * min(gemm_ms, ag_ms)
        if site_mode == "ar":
            return TripleDecision(
                site=node.name, pattern=tri.pattern, lowered="elided",
                fused=False, kernel="none", protocol=None,
                wire="native", est_fused_ms=gemm_ms, est_seq_ms=gemm_ms,
                reason="replicated activations: no gather under ar")
        if site_mode == "xla":
            return seq("sequential", "lax.all_gather", f_ms, s_ms,
                       "xla lowering is the sequential reference",
                       wire=wire)
        kernels = (_FUSED_MOE_KERNELS if site_mode == "fused"
                   else _DIST_KERNELS)
        kernel = kernels.get((tri.pattern, site))
        if kernel is None:
            return seq("sequential", "lax.all_gather", f_ms, s_ms,
                       "no fused rewrite for this site", wire=wire)
        cfgstr = _top_config(tri.pattern, cons, world, chip)
        return fused("ag+" + cons.kind, kernel,
                     PATTERN_PROTOCOLS[tri.pattern], f_ms, s_ms,
                     f"overlap hides min(comm, compute): "
                     f"{f_ms:.3f}ms vs {s_ms:.3f}ms sequential",
                     wire, cfgstr, comp_node=cons)

    if tri.pattern.endswith("+rs") or tri.pattern.endswith("+ar"):
        prod = nodes[tri.producer]
        gemm_ms = pm.estimate_gemm_ms(prod.m, prod.n, prod.k,
                                      dtype=dtype, chip=chip)
        rs_ms = pm.estimate_rs_ms(node.bytes, world, chip)
        ar_ms = pm.estimate_ar_ms(node.bytes, world, chip)
        if site_mode == "ar":
            s_ms = gemm_ms + ar_ms
            f_ms = max(gemm_ms, ar_ms) + 0.1 * min(gemm_ms, ar_ms)
            kernel = _AR_KERNELS.get((tri.pattern, site))
            if kernel is None:
                # the MoE ar path reduces with a plain psum today
                return seq("sequential", "lax.psum", f_ms, s_ms,
                           "no fused gemm+ar rewrite for this site",
                           wire=wire)
            cfgstr = _top_config(tri.pattern, prod, world, chip)
            return fused("gemm+ar", kernel, PATTERN_PROTOCOLS["gemm+ar"],
                         f_ms, s_ms,
                         f"replicated lowering fuses the reduction: "
                         f"{f_ms:.3f}ms vs {s_ms:.3f}ms sequential",
                         wire, cfgstr, comp_node=prod)
        s_ms = gemm_ms + rs_ms
        f_ms = max(gemm_ms, rs_ms) + 0.1 * min(gemm_ms, rs_ms)
        if site_mode == "xla":
            return seq("sequential", "lax.psum_scatter", f_ms, s_ms,
                       "xla lowering is the sequential reference",
                       wire=wire)
        kernels = (_FUSED_MOE_KERNELS if site_mode == "fused"
                   else _DIST_KERNELS)
        kernel = kernels.get((tri.pattern, site))
        if kernel is None:
            return seq("sequential", "lax.psum_scatter", f_ms, s_ms,
                       "no fused rewrite for this site", wire=wire)
        cfgstr = _top_config(tri.pattern, prod, world, chip)
        return fused(tri.pattern, kernel,
                     PATTERN_PROTOCOLS[tri.pattern], f_ms, s_ms,
                     f"overlap hides min(comm, compute): "
                     f"{f_ms:.3f}ms vs {s_ms:.3f}ms sequential",
                     wire, cfgstr, comp_node=prod)

    # a2a+grouped_gemm (the EP plane) and anything future: the EP
    # chunked pipeline is planned by plan_ep_chunks; in a layer IR it
    # lowers sequentially here
    coll_ms = pm.estimate_a2a_ms(node.bytes, world, chip=chip) \
        if hasattr(pm, "estimate_a2a_ms") else 0.0
    return seq("sequential", "lax.all_to_all", coll_ms, coll_ms,
               "EP transport planned by plan_ep_chunks", wire=wire)


def _decisions_for(ir, triples, mode, moe_mode, world, chip, shipped,
                   error_budget, forced):
    return tuple(_decide(ir, t, mode, moe_mode, world, chip, shipped,
                         error_budget, forced) for t in triples)


# norm/residual passes over the token rows per block: ~2 rms_norms and
# ~2 residual adds, each streaming read+read+write of (rows, H)
_ELEMENTWISE_PASSES = 12


def _elementwise_ms(ir: LayerIR, mode: str, world: int, chip) -> float:
    """The replicated-lowering tax the collectives ledger cannot see:
    sequence-sharded modes run norms + residuals on m/n rows, "ar"
    runs them on all m rows on every rank. This is the term that makes
    "ar" the decode pick and "dist" the prefill pick — exactly the
    engine's hand defaults."""
    from triton_dist_tpu.plan.ir import _dtype_bytes

    h = next((nd.k for nd in ir.nodes if nd.kind == "gemm"), 0)
    if not h:
        return 0.0
    rows = ir.tokens if mode == "ar" else ir.tokens // max(world, 1)
    nbytes = rows * h * _dtype_bytes(ir.nodes[0].dtype)
    return nbytes * _ELEMENTWISE_PASSES / (chip.hbm_gbps * 1e9) * 1e3


def plan_forward(ir: LayerIR, world: Optional[int] = None,
                 rig=None, mode: str = "auto",
                 attn_impl: Optional[str] = None,
                 error_budget: float = 0.0) -> Plan:
    """THE planning pass (ISSUE: one `plan_forward(ir, world, rig)`).

    mode "auto" prices the candidate lowerings and picks the cheapest;
    a legacy mode string ("dist" | "xla" | "ar" | MoE "fused") is a
    constraint honored exactly — that is the bit-identity contract with
    the hand-routed paths. Token counts not divisible by `world`
    restrict candidates to "ar" (the sequence-sharded lowerings slice
    tokens by rank). error_budget feeds choose_wire_format per
    collective; the default 0.0 forces native wire (bitwise execution).
    """
    world = ir.world if world is None else world
    chip = _resolve_chip(rig)
    shipped = _shipped_protocols()
    forced = mode != "auto"

    if mode == "fused" and not ir.is_moe:
        raise ValueError("mode='fused' is the MoE one-kernel pipeline; "
                         f"IR {ir.key} is dense")
    if mode == "auto":
        cands = (_DENSE_MODES if ir.tokens % max(world, 1) == 0
                 else ("ar",))
        scored = []
        for m in cands:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                ds = _decisions_for(ir, find_triples(ir), m, m, world,
                                    chip, shipped, error_budget, False)
            scored.append((sum(d.chosen_ms for d in ds)
                           + _elementwise_ms(ir, m, world, chip), m))
        # stable min: candidate order breaks ties toward "dist"
        picked = min(scored, key=lambda t: t[0])[1]
        chosen_mode, chosen_moe = picked, picked
    elif mode == "fused":
        # the one-kernel MoE pipeline is sequence-sharded; attention
        # rides the dist lowering beside it
        chosen_mode, chosen_moe = "dist", "fused"
    else:
        if mode not in _DENSE_MODES:
            raise ValueError(f"unknown mode {mode!r}; expected one of "
                             f"{_DENSE_MODES + ('fused', 'auto')}")
        chosen_mode, chosen_moe = mode, mode

    triples = find_triples(ir)
    decisions = _decisions_for(ir, triples, chosen_mode, chosen_moe,
                               world, chip, shipped, error_budget,
                               forced)
    est = (sum(d.chosen_ms for d in decisions)
           + _elementwise_ms(ir, chosen_mode, world, chip))
    attn_block, blk_source = _cached_attn_block(ir, world, chip)
    # applied configs enter the plan id: a cache hit compiles a
    # DIFFERENT program than the default plan, so the stamp every
    # consumer carries (Scheduler.metrics, mega Schedule) must move too
    pid = hashlib.sha1(repr((
        ir.key, world, chip.name, mode, chosen_mode, chosen_moe,
        attn_impl, error_budget,
        tuple((d.site, d.applied_config) for d in decisions
              if d.applied_config),
        attn_block,
    )).encode()).hexdigest()[:12]
    return Plan(plan_id=pid, key=ir.key, world=world, chip=chip.name,
                requested=mode, mode=chosen_mode, moe_mode=chosen_moe,
                seq_sharded=chosen_mode in SEQ_SHARDED_MODES,
                is_moe=ir.is_moe, attn_impl=attn_impl,
                decisions=decisions, est_layer_ms=est,
                attn_block=attn_block, attn_block_source=blk_source)


def _cached_attn_block(ir: LayerIR, world: int, chip):
    """Measured flash-prefill KV page height for this step shape, from
    the tune cache (same rig + shape-bucket only), re-validated against
    the kernel's fit_block + VMEM gate. (None, "") = kernel default."""
    from triton_dist_tpu import autotuner as at

    attn = next((nd for nd in ir.nodes if nd.kind == "attention"), None)
    if attn is None:
        return None, ""
    meta = dict(attn.meta or ())
    s_q, t = meta.get("s_q", 0), meta.get("t", 0)
    hq, hkv, d = meta.get("hq", 0), meta.get("hkv", 0), meta.get("d", 0)
    if not (s_q > 1 and t and hq and hkv and d):
        return None, ""  # decode / malformed meta: nothing to prefill
    entry = at.active_tune_cache().lookup(
        "flash_prefill", at.shape_bucket(s_q, t, hq, hkv, d),
        attn.dtype, world, "native", at.rig_name(chip, world))
    if entry is None:
        return None, ""
    try:
        cfg = at.parse_config("flash_prefill", entry["config"])
    except ValueError as e:
        warnings.warn(
            f"plan: tune-cache flash_prefill entry is unparseable "
            f"({e}); launching default block", stacklevel=2)
        return None, ""
    if not at.flash_prefill_config_fits(cfg, s_q, t, hq, hkv, d,
                                        dtype=attn.dtype,
                                        batch=meta.get("batch", 1),
                                        chip=chip):
        warnings.warn(
            f"plan: cached flash_prefill block {cfg.block} no longer "
            f"passes the launch VMEM gate at (s_q={s_q}, t={t}); "
            "launching default block (stale tune cache)", stacklevel=2)
        return None, ""
    return int(cfg.block), "cache"


@functools.lru_cache(maxsize=512)
def _plan_dense_cached(cfg, batch, seq, world, mode, attn_impl, kv_len,
                       rig, error_budget, tune_gen):
    ir = build_dense_ir(cfg, batch, seq, world, kv_len=kv_len)
    return plan_forward(ir, world=world, rig=rig, mode=mode,
                        attn_impl=attn_impl, error_budget=error_budget)


def plan_dense_forward(cfg, batch: int, seq: int, world: int,
                       mode: str = "auto",
                       attn_impl: Optional[str] = None,
                       kv_len: Optional[int] = None,
                       rig: Optional[str] = None,
                       error_budget: float = 0.0) -> Plan:
    """Plan one `models/dense.forward` step shape. Memoized on the
    hashable ModelConfig + geometry, so every consumer of the same step
    shape holds the SAME Plan object (module doc) and planning inside a
    traced function costs a dict lookup. The tune-cache generation
    enters the memo key: a plan built before the cache was populated
    (or swapped by a test/bench arm) never masks a measured winner."""
    from triton_dist_tpu import autotuner as at

    if rig is None:
        rig = _resolve_chip(None).name
    return _plan_dense_cached(cfg, batch, seq, world, mode, attn_impl,
                              kv_len, rig, error_budget,
                              at.tune_cache_generation())


def plan_ep_chunks(m: int, hidden: int, inter: int, e_loc: int, n: int,
                   top_k: int, capacity: Optional[int] = None,
                   dtype=None, payload_dtype=None, chip=None,
                   overlap: bool = False) -> int:
    """ONE EP chunking entry (the a2a+grouped_gemm plane):
    `layers/ep_moe.py`'s n_chunks auto path routes here so the planner
    owns the composition; `perf_model.choose_ep_chunks` stays the
    pricing primitive. A measured winner in the tune cache (kernel
    "ep_moe", same rig + shape bucket) beats the modeled pick — the
    chunk count is re-fitted by the kernel's own fit_chunks at launch,
    so a stale entry degrades to a legal schedule, never a crash."""
    import jax.numpy as jnp

    from triton_dist_tpu import autotuner as at
    from triton_dist_tpu.perf_model import choose_ep_chunks

    entry = at.active_tune_cache().lookup(
        "ep_moe", at.shape_bucket(m, hidden, inter, e_loc, top_k),
        jnp.bfloat16 if dtype is None else dtype, n, "native",
        at.rig_name(chip, n))
    if entry is not None:
        try:
            return int(at.parse_config("ep_moe", entry["config"]).n_chunks)
        except ValueError as e:
            warnings.warn(
                f"plan: tune-cache ep_moe entry is unparseable ({e}); "
                "using the modeled chunk count", stacklevel=2)
    return choose_ep_chunks(
        m, hidden, inter, e_loc, n, top_k, capacity=capacity,
        dtype=jnp.bfloat16 if dtype is None else dtype,
        payload_dtype=payload_dtype, chip=chip, overlap=overlap)


def route_prefill_impl(b: int, s: int, t: int, hq: int, hkv: int,
                       d: int, dtype) -> str:
    """THE prefill-impl routing predicate ("pallas" | "xla"): native
    gate (kernels.flash_prefill.flash_prefill_native_ok — interpret
    stays xla for CPU bit-stability), the VMEM-fit gate, then the
    perf-model pick (perf_model.choose_prefill_impl). Moved here from
    layers/attention.py so the planner owns every impl decision;
    `layers.attention._route_prefill_impl` delegates."""
    from triton_dist_tpu.kernels.flash_prefill import (
        flash_prefill_fits,
        flash_prefill_native_ok,
    )

    if not flash_prefill_native_ok(hq, hkv, d):
        return "xla"
    if not flash_prefill_fits(s, t, hq, hkv, d, dtype=dtype):
        # per-grid-step state beyond the VMEM ceiling: the blockwise
        # xla path handles arbitrarily long context; auto must never
        # route into a Mosaic allocation failure
        return "xla"
    from triton_dist_tpu.perf_model import choose_prefill_impl

    return ("pallas" if choose_prefill_impl(s, t, hq, hkv, d, batch=b,
                                            dtype=dtype) == "flash"
            else "xla")
