"""The layer-IR the fusion planner pattern-matches (plan/__init__ doc).

One transformer block + the LM head, as a flat tuple of `OpNode`s in
execution order. The IR is MODE-AGNOSTIC: it records the logical
computation under its TP sharding (which collectives the sharding
implies, what each GEMM's local shape is), and the planner prices the
candidate LOWERINGS of that one IR — sequence-sharded fused ("dist"),
sequence-sharded unfused ("xla"), replicated ("ar"), and the MoE
one-kernel pipeline ("fused") — rather than holding one IR per mode.

Everything here is hashable pure-python data (frozen dataclasses of
ints/strings), so plans memoize on the IR key and building the IR at
trace time costs microseconds, never a recompile.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class OpNode:
    """One op of the layer computation.

    kind      "gemm" | "grouped_gemm" | "attention" | "norm" | "router"
              | "collective"
    m, k, n   GEMM row/contraction/column dims as the op sees them
              locally (m is the GLOBAL row count reaching the op; the
              lowering decides how rows shard). 0 for non-GEMM ops.
    collective  kind=="collective": "all_gather" | "reduce_scatter" |
              "all_reduce" | "all_to_all".
    bytes     collective payload in NATIVE bytes, per the perf_model
              convention (per-rank shard for the gather family, full
              per-device tensor for the reduction family).
    wire_eligible  whether the collective may ride a quantized wire
              (choose_wire_format prices it; numerics-critical legs —
              the logits gather — stay native).
    meta      sorted (key, value) extras (attention geometry, epilogue
              tags) — a tuple so the node stays hashable.
    """

    name: str
    kind: str
    m: int = 0
    k: int = 0
    n: int = 0
    dtype: str = "bfloat16"
    axis: Optional[str] = None
    collective: Optional[str] = None
    bytes: int = 0
    wire_eligible: bool = False
    meta: Tuple[Tuple[str, int], ...] = ()

    def get(self, key: str, default: int = 0) -> int:
        for k, v in self.meta:
            if k == key:
                return v
        return default


@dataclasses.dataclass(frozen=True)
class Triple:
    """A matched producer -> collective -> consumer site (indices into
    LayerIR.nodes; producer/consumer may be -1 when the collective has
    no compute op on that side, e.g. the logits gather)."""

    producer: int
    collective: int
    consumer: int
    pattern: str


@dataclasses.dataclass(frozen=True)
class LayerIR:
    """The planner's unit of work: `nodes` in execution order, plus the
    geometry the builders baked the shapes from."""

    key: str
    nodes: Tuple[OpNode, ...]
    world: int
    batch: int
    seq: int
    is_moe: bool = False

    @property
    def tokens(self) -> int:
        return self.batch * self.seq


_COMPUTE_KINDS = ("gemm", "grouped_gemm", "attention")

# collective -> which side the fusable compute op sits on
_CONSUMER_COLLECTIVES = ("all_gather",)
_PRODUCER_COLLECTIVES = ("reduce_scatter", "all_reduce")


def find_triples(ir: LayerIR) -> Tuple[Triple, ...]:
    """Pattern-match every producer -> collective -> consumer site.

    A gather feeds the NEXT compute op (AG+GEMM class); a reduction is
    fed by the PREVIOUS one (GEMM+RS / GEMM+AR class). Norms and
    routers are transparent: the reference fuses across them exactly
    like this repo's hand wiring does (the router consumes the same
    gathered activations the grouped GEMM does). An unmatched
    collective still yields a Triple (producer/consumer -1, pattern
    "unknown") so the planner can fall back LOUDLY instead of silently
    skipping a site."""
    nodes = ir.nodes
    out = []
    for i, node in enumerate(nodes):
        if node.kind != "collective":
            continue
        if node.collective in _CONSUMER_COLLECTIVES:
            cons = next((j for j in range(i + 1, len(nodes))
                         if nodes[j].kind in _COMPUTE_KINDS), -1)
            if cons >= 0:
                out.append(Triple(-1, i, cons,
                                  f"ag+{nodes[cons].kind}"))
            else:
                out.append(Triple(-1, i, -1, "unknown"))
        elif node.collective in _PRODUCER_COLLECTIVES:
            prod = next((j for j in range(i - 1, -1, -1)
                         if nodes[j].kind in _COMPUTE_KINDS), -1)
            tag = "rs" if node.collective == "reduce_scatter" else "ar"
            if prod >= 0:
                out.append(Triple(prod, i, -1,
                                  f"{nodes[prod].kind}+{tag}"))
            else:
                out.append(Triple(-1, i, -1, "unknown"))
        else:
            # all_to_all (the EP plane) and anything future: matched by
            # the adjacent grouped GEMM when present
            cons = i + 1 if (i + 1 < len(nodes)
                             and nodes[i + 1].kind == "grouped_gemm") \
                else -1
            out.append(Triple(-1, i, cons,
                              "a2a+grouped_gemm" if cons >= 0
                              else "unknown"))
    return tuple(out)


def _dtype_bytes(dtype: str) -> int:
    import jax.numpy as jnp
    import numpy as np

    return int(np.dtype(jnp.dtype(dtype)).itemsize)


def build_dense_ir(cfg, batch: int, seq: int, world: int,
                   kv_len: Optional[int] = None) -> LayerIR:
    """Emit the layer-IR of one `models/dense.py` transformer block plus
    the LM head, from the ModelConfig and the step geometry. The MoE
    block swaps the dense MLP for router + grouped GEMMs — the same
    structural walk `models/dense._layer_fwd` executes, as data."""
    n = max(world, 1)
    h, d = cfg.hidden_size, cfg.head_dim
    hq_l, hkv_l = cfg.num_q_heads // n, cfg.num_kv_heads // n
    wqkv_l = (hq_l + 2 * hkv_l) * d
    hqd_l = hq_l * d
    v_l = cfg.vocab_size // n
    m = batch * seq
    isz = _dtype_bytes(cfg.dtype)
    t = kv_len if kv_len is not None else seq
    ax = "tp"

    def ag(name, rows):
        # gather family: per-rank shard bytes (perf_model convention)
        return OpNode(name, "collective", axis=ax,
                      collective="all_gather", dtype=cfg.dtype,
                      bytes=max(rows // n, 1) * h * isz,
                      wire_eligible=True)

    def rs(name, rows):
        # reduction family: full per-device tensor bytes
        return OpNode(name, "collective", axis=ax,
                      collective="reduce_scatter", dtype=cfg.dtype,
                      bytes=rows * h * isz, wire_eligible=True)

    nodes = [
        OpNode("attn.ln", "norm", dtype=cfg.dtype),
        ag("attn.ag", m),
        OpNode("attn.qkv", "gemm", m=m, k=h, n=wqkv_l, dtype=cfg.dtype,
               axis=ax),
        OpNode("attn.core", "attention", dtype=cfg.dtype,
               meta=(("batch", batch), ("d", d), ("hkv", hkv_l),
                     ("hq", hq_l), ("s_q", seq), ("t", t))),
        OpNode("attn.o", "gemm", m=m, k=hqd_l, n=h, dtype=cfg.dtype,
               axis=ax),
        rs("attn.rs", m),
        OpNode("mlp.ln", "norm", dtype=cfg.dtype),
        ag("mlp.ag", m),
    ]
    if cfg.is_moe:
        mi_l = cfg.moe_intermediate_size // n
        e = cfg.num_experts
        top_k = cfg.num_experts_per_tok
        rows = m * top_k
        nodes += [
            OpNode("moe.router", "router", m=m, k=h, n=e,
                   dtype=cfg.dtype),
            OpNode("moe.up", "grouped_gemm", m=rows, k=h, n=2 * mi_l,
                   dtype=cfg.dtype, axis=ax,
                   meta=(("experts", e), ("top_k", top_k))),
            OpNode("moe.down", "grouped_gemm", m=rows, k=mi_l, n=h,
                   dtype=cfg.dtype, axis=ax,
                   meta=(("experts", e), ("top_k", top_k))),
            rs("moe.rs", m),
        ]
    else:
        i_l = cfg.intermediate_size // n
        nodes += [
            OpNode("mlp.gate_up", "gemm", m=m, k=h, n=2 * i_l,
                   dtype=cfg.dtype, axis=ax,
                   meta=(("epilogue_silu_pair", 1),)),
            OpNode("mlp.down", "gemm", m=m, k=i_l, n=h, dtype=cfg.dtype,
                   axis=ax),
            rs("mlp.rs", m),
        ]
    nodes += [
        OpNode("final.ln", "norm", dtype=cfg.dtype),
        # residual stream regathered for the head (seq-sharded lowering)
        ag("head.ag", m),
        OpNode("head.lm", "gemm", m=batch, k=h, n=v_l, dtype=cfg.dtype,
               axis=ax),
        # the logits gather is numerics-critical (sampling reads it
        # bitwise) — never wire-quantized
        OpNode("head.logits", "collective", axis=ax,
               collective="all_gather", dtype="float32",
               bytes=batch * v_l * 4, wire_eligible=False),
    ]
    kind = "moe" if cfg.is_moe else "dense"
    key = (f"{kind}(L={cfg.num_layers},h={h},b={batch},s={seq},"
           f"world={n})")
    return LayerIR(key=key, nodes=tuple(nodes), world=n, batch=batch,
                   seq=seq, is_moe=cfg.is_moe)
