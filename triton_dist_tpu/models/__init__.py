"""Models + inference engine.

TPU-native analog of the reference's model/runtime layer
(ref: python/triton_dist/models/: Engine, DenseLLM, KV_Cache, ModelConfig,
AutoLLM). The torch module tree becomes functional params pytrees; CUDA
graphs become jit executables; HF weight streaming becomes `load_hf`.
"""

from triton_dist_tpu.models.config import ModelConfig  # noqa: F401
from triton_dist_tpu.models.kv_cache import KVCache  # noqa: F401
from triton_dist_tpu.models.dense import (  # noqa: F401
    DenseLLMParams,
    DenseLayerParams,
    forward,
    init_params,
    param_specs,
    cache_specs,
)
from triton_dist_tpu.models.engine import Engine, sample_token  # noqa: F401
from triton_dist_tpu.models.load_hf import (  # noqa: F401
    AutoLLM,
    config_from_hf,
    load_hf,
)
from triton_dist_tpu.models.qwen_moe import (  # noqa: F401
    auto_engine,
    qwen3_moe_engine,
)
