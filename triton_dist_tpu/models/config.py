"""Model configuration.

TPU-native analog of the reference's ModelConfig
(ref: python/triton_dist/models/config.py:31). Carries the Qwen3-dense
geometry plus TPU partitioning knobs. Presets mirror the models the
reference benchmarks (Qwen3-8B/32B, e2e_dense.md).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    vocab_size: int = 151_936
    hidden_size: int = 5120
    intermediate_size: int = 25_600
    num_layers: int = 64
    num_q_heads: int = 64
    num_kv_heads: int = 8
    head_dim: int = 128
    rope_theta: float = 1_000_000.0
    rms_eps: float = 1e-6
    max_positions: int = 4096
    dtype: str = "bfloat16"
    # qk-norm (Qwen3 applies rmsnorm over head_dim to q and k)
    use_qk_norm: bool = True
    tie_word_embeddings: bool = False
    # MoE (0 experts = dense; ref: models/qwen_moe.py Qwen3MoE)
    num_experts: int = 0
    num_experts_per_tok: int = 8
    moe_intermediate_size: int = 0

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    @staticmethod
    def qwen3_32b(**kw) -> "ModelConfig":
        """Qwen3-32B geometry (the reference's headline e2e model,
        ref: docs/getting-started/e2e/e2e_dense.md)."""
        return ModelConfig(
            vocab_size=151_936, hidden_size=5120, intermediate_size=25_600,
            num_layers=64, num_q_heads=64, num_kv_heads=8, head_dim=128,
            **kw,
        )

    @staticmethod
    def qwen3_8b(**kw) -> "ModelConfig":
        return ModelConfig(
            vocab_size=151_936, hidden_size=4096, intermediate_size=12_288,
            num_layers=36, num_q_heads=32, num_kv_heads=8, head_dim=128,
            **kw,
        )

    @staticmethod
    def qwen3_30b_a3b(**kw) -> "ModelConfig":
        """Qwen3-30B-A3B MoE geometry (the reference's Qwen3MoE model,
        ref: models/qwen_moe.py:50-206)."""
        return ModelConfig(
            vocab_size=151_936, hidden_size=2048, intermediate_size=6144,
            num_layers=48, num_q_heads=32, num_kv_heads=4, head_dim=128,
            num_experts=128, num_experts_per_tok=8,
            moe_intermediate_size=768, **kw,
        )

    @staticmethod
    def tiny_moe(**kw) -> "ModelConfig":
        """Test-scale MoE config."""
        defaults = dict(
            vocab_size=256, hidden_size=128, intermediate_size=256,
            num_layers=2, num_q_heads=16, num_kv_heads=8, head_dim=32,
            max_positions=64, dtype="float32",
            num_experts=4, num_experts_per_tok=2, moe_intermediate_size=64,
        )
        defaults.update(kw)
        return ModelConfig(**defaults)

    @staticmethod
    def tiny(**kw) -> "ModelConfig":
        """Test-scale config (CPU-mesh parity tests)."""
        defaults = dict(
            vocab_size=256, hidden_size=128, intermediate_size=256,
            num_layers=2, num_q_heads=16, num_kv_heads=8, head_dim=32,
            max_positions=64, dtype="float32",
        )
        defaults.update(kw)
        return ModelConfig(**defaults)
