"""DenseLLM — Qwen3-style TP transformer over the fused kernel library.

TPU-native re-design of the reference's DenseLLM/DenseLLMLayer
(ref: python/triton_dist/models/dense.py:53-241): the torch module tree
with a per-layer fwd mode switch (:84-98) becomes a functional model —
params are pytrees of per-rank shards (leading mesh-axis dim, consumed by
shard_map in_specs), the layer stack is a `lax.scan` over stacked layer
params (one trace for all layers), and the forward modes mirror the
reference's torch / triton_dist / triton_dist_AR:

  xla  — unfused collectives (parity reference)
  dist — ag_gemm/gemm_rs sequence-sharded pipeline (prefill)
  ar   — replicated activations + gemm_ar (decode / low latency)

Mode routing is OWNED by the fusion planner (triton_dist_tpu.plan):
`forward` resolves its `mode` argument to a Plan and executes through
plan/execute — this module contains no fused-vs-sequential branches.
mode="auto" lets the planner price the lowerings per shape.

Sharding layout per tensor (n = tp size):
  embed (V, H) replicated · norms (L, H) replicated
  w_qkv (L, n, H, (Hq+2Hkv)/n*D) · w_o (L, n, Hq/n*D, H)
  w_gate / w_up (L, n, H, I/n) · w_down (L, n, I/n, H)
  lm_head (n, H, V/n)
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_dist_tpu.layers import (
    TPAttnParams,
    TPAttnSpec,
    TPMLPParams,
    rms_norm,
    rope_table,
)
from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.models.kv_cache import KVCache
from triton_dist_tpu.plan import execute as plan_exec
from triton_dist_tpu.plan.planner import Plan, plan_dense_forward
from triton_dist_tpu.runtime.init import TP_AXIS


class DenseLayerParams(NamedTuple):
    input_ln: jax.Array
    post_attn_ln: jax.Array
    w_qkv: jax.Array
    w_o: jax.Array
    q_norm: jax.Array
    k_norm: jax.Array
    # dense: w_gate/w_up (L, n, H, I/n) SEPARATE (like the HF checkpoint's
    #   gate_proj/up_proj; the split layout is what lets XLA fuse the silu
    #   epilogue — see layers/tp_mlp.py), w_down (L, n, I/n, H).
    #   The megakernel fuses them once at init for one-DMA streaming.
    # MoE:   w_gate_up (L, n, E, H, 2I_moe/n), w_down (L, n, E, I_moe/n, H)
    #   stays fused (the grouped-GEMM expert layout).
    w_down: jax.Array
    w_gate: Optional[jax.Array] = None
    w_up: Optional[jax.Array] = None
    w_gate_up: Optional[jax.Array] = None  # MoE only
    w_router: Optional[jax.Array] = None  # MoE only: (L, H, E) replicated


class DenseLLMParams(NamedTuple):
    embed: jax.Array
    layers: DenseLayerParams  # stacked: leading (L, n, ...) dims
    final_ln: jax.Array
    lm_head: jax.Array


def param_specs(axis: str = TP_AXIS, moe: bool = False):
    """shard_map in_specs for DenseLLMParams (leading n dim -> axis)."""
    layers = DenseLayerParams(
        input_ln=P(), post_attn_ln=P(),
        w_qkv=P(None, axis), w_o=P(None, axis),
        q_norm=P(), k_norm=P(),
        w_down=P(None, axis),
        w_gate=None if moe else P(None, axis),
        w_up=None if moe else P(None, axis),
        w_gate_up=P(None, axis) if moe else None,
        w_router=P() if moe else None,
    )
    return DenseLLMParams(
        embed=P(), layers=layers, final_ln=P(), lm_head=P(axis)
    )


def cache_specs(axis: str = TP_AXIS, batch_axis: Optional[str] = None):
    """KV cache specs: heads shard over tp; batch optionally over dp."""
    return KVCache(
        k=P(None, batch_axis, None, axis),
        v=P(None, batch_axis, None, axis),
        length=P(batch_axis),
    )


def init_params(
    cfg: ModelConfig, mesh, seed: int = 0, axis: str = TP_AXIS,
    fast: bool = False,
) -> DenseLLMParams:
    """Random-init global arrays laid out for shard_map (the reference
    streams HF weights at init, dense.py:150-167; random init keeps the
    framework dependency-free — `load_hf` maps real checkpoints).

    fast=True draws on-device with jax.random instead of host numpy —
    O(seconds) instead of O(minutes) at multi-billion-param scale; use it
    whenever the exact host RNG stream doesn't matter (benchmarks)."""
    n = int(mesh.shape[axis])
    assert cfg.num_q_heads % n == 0 and cfg.num_kv_heads % n == 0, (
        f"num_q_heads={cfg.num_q_heads} and num_kv_heads={cfg.num_kv_heads} "
        f"must both divide the tp size {n} (pick a smaller tp for this "
        "config, e.g. Qwen3-30B-A3B with 4 kv heads supports tp<=4)"
    )
    assert cfg.vocab_size % n == 0 and (
        (cfg.moe_intermediate_size if cfg.is_moe else cfg.intermediate_size)
        % n == 0
    ), "vocab/intermediate sizes must divide the tp size"
    rng = np.random.default_rng(seed)
    dt = jnp.dtype(cfg.dtype)
    h, d = cfg.hidden_size, cfg.head_dim
    hq_l, hkv_l = cfg.num_q_heads // n, cfg.num_kv_heads // n
    i_l = cfg.intermediate_size // n
    v_l = cfg.vocab_size // n
    L = cfg.num_layers

    if fast:
        key_box = [jax.random.PRNGKey(seed)]

        def mk(shape, scale=0.02):
            key_box[0], sub = jax.random.split(key_box[0])
            return (jax.random.normal(sub, shape, jnp.float32) * scale
                    ).astype(dt)
    else:
        def mk(shape, scale=0.02):
            return jnp.asarray(rng.standard_normal(shape) * scale, dt)

    if cfg.is_moe:
        e = cfg.num_experts
        mi_l = cfg.moe_intermediate_size // n
        ffn = dict(
            w_gate_up=mk((L, n, e, h, 2 * mi_l)),
            w_down=mk((L, n, e, mi_l, h)),
            w_router=mk((L, h, e)),
        )
    else:
        ffn = dict(
            w_gate=mk((L, n, h, i_l)),
            w_up=mk((L, n, h, i_l)),
            w_down=mk((L, n, i_l, h)),
            w_router=None,
        )
    layers = DenseLayerParams(
        input_ln=jnp.ones((L, h), dt),
        post_attn_ln=jnp.ones((L, h), dt),
        w_qkv=mk((L, n, h, (hq_l + 2 * hkv_l) * d)),
        w_o=mk((L, n, hq_l * d, h)),
        q_norm=jnp.ones((L, d), dt),
        k_norm=jnp.ones((L, d), dt),
        **ffn,
    )
    params = DenseLLMParams(
        embed=mk((cfg.vocab_size, h)),
        layers=layers,
        final_ln=jnp.ones((h,), dt),
        lm_head=mk((n, h, v_l)),
    )
    specs = param_specs(axis, cfg.is_moe)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


def _layer_fwd(cfg: ModelConfig, spec: TPAttnSpec, cos, sin, positions,
               kv_len, batch, axis, plan: Plan, x,
               lp: DenseLayerParams, kv):
    """One transformer block (ref DenseLLMLayer.fwd, dense.py:101-114).
    All mode/impl routing lives in the Plan (triton_dist_tpu.plan):
    this function only states the block structure."""
    attn_params = TPAttnParams(
        w_qkv=lp.w_qkv, w_o=lp.w_o,
        q_norm=lp.q_norm if cfg.use_qk_norm else None,
        k_norm=lp.k_norm if cfg.use_qk_norm else None,
    )
    h = rms_norm(x, lp.input_ln, cfg.rms_eps)
    attn_out, kv = plan_exec.attn_fwd(
        plan, h, attn_params, spec, cos, sin, positions, batch,
        axis, kv, kv_len,
    )
    x = x + attn_out
    h = rms_norm(x, lp.post_attn_ln, cfg.rms_eps)
    if cfg.is_moe:
        from triton_dist_tpu.layers import TPMoEParams

        ffn_params = TPMoEParams(lp.w_router, lp.w_gate_up, lp.w_down)
    else:
        ffn_params = TPMLPParams(lp.w_gate, lp.w_up, lp.w_down)
    mlp_out = plan_exec.ffn_fwd(plan, h, ffn_params, axis,
                                top_k=cfg.num_experts_per_tok)
    x = x + mlp_out
    return x, kv


def forward(
    cfg: ModelConfig,
    params: DenseLLMParams,
    tokens: jax.Array,  # (B, S) int32, replicated
    cache: Optional[KVCache],  # per-rank head shards
    mode: str = "dist",
    axis: str = TP_AXIS,
    return_full_logits: bool = False,
    attn_impl: Optional[str] = None,
    plan: Optional[Plan] = None,
):
    """Per-device forward (inside shard_map). Returns (logits, new_cache);
    logits (B, V) for the last position (or (B, S, V) if
    return_full_logits). attn_impl: prefill attention implementation
    override ("xla" | "pallas"; None = auto — the flash-prefill switch,
    plan.route_prefill_impl). Mirrors the reference inference entry
    (ref: models/dense.py:221-241 `inference`).

    Routing is the fusion planner's (triton_dist_tpu.plan): a legacy
    `mode` string is honored bit-for-bit as a plan constraint,
    mode="auto" lets the planner price the lowerings, and a prebuilt
    `plan` (the same memoized object Engine / serve / mega hold)
    short-circuits planning entirely."""
    if cache is None:
        raise ValueError("forward requires a KVCache (create one per serve)")
    n = jax.lax.axis_size(axis)
    b, s = tokens.shape
    h_dim = cfg.hidden_size
    m = b * s
    if plan is None:
        # trace-time planning on static shapes: memoized, so this is a
        # dict lookup on every retrace of the same step geometry
        plan = plan_dense_forward(cfg, b, s, n, mode=mode,
                                  attn_impl=attn_impl)
    from triton_dist_tpu.trace import events as _tev

    _tev.note_plan(plan.plan_id)  # trace provenance (Timeline.plan_id)
    spec = TPAttnSpec(cfg.num_q_heads // n, cfg.num_kv_heads // n,
                      cfg.head_dim)
    cos, sin = rope_table(cfg.head_dim, cfg.max_positions, cfg.rope_theta)

    start = cache.length
    positions = start[:, None] + jnp.arange(s)[None, :]  # (B, S)
    kv_len = start + s

    x = params.embed[tokens].reshape(m, h_dim)
    x = plan_exec.shard_tokens(x, axis, plan)

    def step(x, xs):
        lp, k_l, v_l = xs
        x, kv = _layer_fwd(cfg, spec, cos, sin, positions, kv_len, b,
                           axis, plan, x, lp, (k_l, v_l))
        return x, kv

    # strip the n-axis dim (shard_map gives size-1 shards on that dim)
    lp_local = jax.tree.map(
        lambda a, sp: a[:, 0] if sp == P(None, axis) else a,
        params.layers, param_specs(axis, cfg.is_moe).layers,
    )
    x, (k_new, v_new) = jax.lax.scan(
        step, x, (lp_local, cache.k, cache.v)
    )
    new_cache = KVCache(k=k_new, v=v_new, length=kv_len)

    x = plan_exec.gather_tokens(x, axis, plan)  # (M, H) when sharded
    x = rms_norm(x, params.final_ln, cfg.rms_eps)
    x = x.reshape(b, s, h_dim)
    if not return_full_logits:
        x = x[:, -1:]
    head = params.lm_head[0]  # strip n dim
    # bf16 operands + f32 accumulation: avoids materialising an f32 copy
    # of the (H, V/n) head shard (the MXU accumulates in f32 natively).
    logits = jnp.einsum(
        "bsh,hv->bsv", x, head, preferred_element_type=jnp.float32
    )
    logits = jax.lax.all_gather(logits, axis, axis=2, tiled=True)  # (B,S,V)
    if not return_full_logits:
        logits = logits[:, 0]
    return logits, new_cache
