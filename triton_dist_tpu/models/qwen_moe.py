"""Qwen3MoE — the MoE model family entry point.

TPU-native analog of the reference's Qwen3MoE
(ref: python/triton_dist/models/qwen_moe.py:50-206). The MoE transformer
shares the DenseLLM skeleton (dense.py) — per-layer MLPs swap for TP-MoE
blocks when cfg.num_experts > 0 — so prefill/decode/engine/cache all come
for free; this module carries the family presets and a convenience
constructor, the AutoLLM dispatch analog (ref: models/__init__.py AutoLLM).
"""

from __future__ import annotations

from typing import Optional

from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.models.engine import Engine
from triton_dist_tpu.runtime.init import TP_AXIS


def qwen3_moe_engine(
    mesh,
    cfg: Optional[ModelConfig] = None,
    axis: str = TP_AXIS,
    **kw,
) -> Engine:
    """Engine for a Qwen3MoE model (defaults to Qwen3-30B-A3B geometry)."""
    cfg = cfg or ModelConfig.qwen3_30b_a3b()
    assert cfg.is_moe, "qwen3_moe_engine requires an MoE config"
    return Engine(cfg, mesh, axis=axis, **kw)


def auto_engine(mesh, cfg: ModelConfig, **kw) -> Engine:
    """AutoLLM analog: dispatch on config (dense vs MoE share the Engine)."""
    return Engine(cfg, mesh, **kw)
