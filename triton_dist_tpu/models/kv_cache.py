"""KV cache — preallocated, functionally updated.

TPU-native analog of the reference's KV_Cache
(ref: python/triton_dist/models/kv_cache.py:29-66): there, per-layer torch
tensors mutated in place; here, one stacked array per model updated
functionally and donated through the jit'd decode step, which XLA turns
into the same in-place update (buffer donation is the TPU idiom for
mutation under jit).

Shapes (per tp rank): k/v (L, B, T_max, Hkv_loc, D). Inside shard_map the
head axis is the tp-sharded one.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class KVCache(NamedTuple):
    k: jax.Array  # (L, B, T_max, Hkv, D)
    v: jax.Array  # (L, B, T_max, Hkv, D)
    length: jax.Array  # (B,) valid entries per sequence

    @staticmethod
    def dense_view(pool_k, pool_v, table, lengths) -> "KVCache":
        """Dense (L, B, T, Hkv, D) view of a PAGED pool — the serve
        plane's read path (serve/kv_pool.KVPool): pool_k/pool_v are
        shared page pools in megakernel pool layout (L, Hkv, P, page, D)
        and `table` (B, MAXP) maps each sequence's page grid onto pool
        pages. The gather is a pure copy, so values round-trip bitwise —
        paging is an allocation policy, never a numeric one. Unallocated
        table entries point at page 0 (the pool's reserved null page);
        the garbage they gather sits beyond each sequence's `lengths`
        and is masked by attention's kv_len/causal bounds."""
        L, Hkv, _, page, D = pool_k.shape
        B, maxp = table.shape
        t = maxp * page
        k = jnp.moveaxis(pool_k[:, :, table].reshape(L, Hkv, B, t, D),
                         1, 3)
        v = jnp.moveaxis(pool_v[:, :, table].reshape(L, Hkv, B, t, D),
                         1, 3)
        return KVCache(k, v, lengths)

    @staticmethod
    def create(num_layers, batch, max_len, num_kv_heads, head_dim,
               dtype=jnp.bfloat16) -> "KVCache":
        shape = (num_layers, batch, max_len, num_kv_heads, head_dim)
        return KVCache(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            length=jnp.zeros((batch,), jnp.int32),
        )

    def layer(self, i):
        """(k, v) views for layer i (used as tp_attn_fwd's kv_cache)."""
        return self.k[i], self.v[i]

    def with_layer(self, i, kv) -> "KVCache":
        k_l, v_l = kv
        return self._replace(
            k=self.k.at[i].set(k_l), v=self.v.at[i].set(v_l)
        )

    def advanced(self, n: int) -> "KVCache":
        return self._replace(length=self.length + n)
