"""HF checkpoint loading: safetensors -> sharded DenseLLMParams.

TPU-native re-design of the reference's weight init path
(ref: python/triton_dist/models/dense.py:150-167 init_parameters — loads
the HF torch model on CPU and per-layer TP-shards it onto the GPU — and
models/__init__.py:33 AutoLLM, the name->model dispatch). Here there is
no torch module tree to walk: tensors stream straight out of the
checkpoint's safetensors files (mmap'd, one tensor at a time, via
`safetensors.safe_open`), are TP-sharded on the host exactly as the
reference's TP layers do (column-parallel q/k/v/gate/up, row-parallel
o/down, vocab-parallel lm_head), and land on the mesh with one
device_put per field.

Layout notes (HF torch Linear stores (out_features, in_features); our
kernels consume (in, out), so every projection transposes):
  model.embed_tokens.weight (V, H)     -> embed (V, H)
  model.norm.weight (H,)               -> final_ln
  lm_head.weight (V, H)                -> lm_head (n, H, V/n)   [col-TP]
  layers.i.self_attn.{q,k,v}_proj      -> w_qkv (L, n, H, (hq+2hkv)/n*D)
  layers.i.self_attn.o_proj            -> w_o (L, n, hq/n*D, H) [row-TP]
  layers.i.self_attn.{q,k}_norm (D,)   -> q_norm/k_norm (L, D)
  layers.i.mlp.{gate,up}_proj          -> w_gate/w_up (L, n, H, I/n)
  layers.i.mlp.down_proj               -> w_down (L, n, I/n, H) [row-TP]
MoE (ref models/qwen_moe.py):
  layers.i.mlp.gate.weight (E, H)      -> w_router (L, H, E)
  layers.i.mlp.experts.e.{gate,up,down}_proj
                                       -> w_gate_up (L, n, E, H, 2mi/n),
                                          w_down (L, n, E, mi/n, H)
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding

from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.models.dense import (
    DenseLayerParams,
    DenseLLMParams,
    param_specs,
)
from triton_dist_tpu.runtime.init import TP_AXIS


def config_from_hf(path: str) -> ModelConfig:
    """Build a ModelConfig from a checkpoint directory's config.json
    (the reference reads the same fields through transformers'
    AutoConfig inside init_model_cpu, models/utils.py)."""
    with open(os.path.join(path, "config.json")) as f:
        hf = json.load(f)
    arch = (hf.get("architectures") or [""])[0]
    moe = "Moe" in arch or "num_experts" in hf
    head_dim = hf.get("head_dim") or (
        hf["hidden_size"] // hf["num_attention_heads"]
    )
    kw = dict(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_layers=hf["num_hidden_layers"],
        num_q_heads=hf["num_attention_heads"],
        num_kv_heads=hf.get("num_key_value_heads",
                            hf["num_attention_heads"]),
        head_dim=head_dim,
        rope_theta=float(hf.get("rope_theta", 1e6)),
        rms_eps=float(hf.get("rms_norm_eps", 1e-6)),
        max_positions=hf.get("max_position_embeddings", 4096),
        dtype=str(hf.get("torch_dtype", "bfloat16")),
        tie_word_embeddings=bool(hf.get("tie_word_embeddings", False)),
        # Qwen3 applies per-head rmsnorm to q/k; presence of the weights
        # decides at load time, config decides here
        use_qk_norm="Qwen3" in arch or hf.get("use_qk_norm", False),
    )
    if moe:
        kw.update(
            num_experts=hf.get("num_experts", hf.get("n_routed_experts", 0)),
            num_experts_per_tok=hf.get("num_experts_per_tok", 8),
            moe_intermediate_size=hf.get("moe_intermediate_size", 0),
        )
    return ModelConfig(**kw)


class _Checkpoint:
    """name -> tensor access over one or many safetensors files (mmap'd,
    one tensor materialized at a time, in the checkpoint's own dtype —
    the analog of the reference's layer-by-layer streaming + gc,
    dense.py:160-165)."""

    def __init__(self, path: str):
        from safetensors import safe_open

        self.path = path
        index = os.path.join(path, "model.safetensors.index.json")
        if os.path.exists(index):
            with open(index) as f:
                self._file_of = json.load(f)["weight_map"]
        else:
            single = os.path.join(path, "model.safetensors")
            if not os.path.exists(single):
                raise FileNotFoundError(
                    f"no model.safetensors[.index.json] under {path}"
                )
            self._file_of = None
            self._single = single
        self._open = {}
        self._safe_open = safe_open

    def _handle(self, name: str):
        fname = (self._single if self._file_of is None
                 else os.path.join(self.path, self._file_of[name]))
        if fname not in self._open:
            # framework="flax" yields jax arrays with native bf16 support
            # (numpy has no bfloat16)
            self._open[fname] = self._safe_open(fname, framework="flax")
        return self._open[fname]

    def names(self):
        if self._file_of is not None:
            return set(self._file_of)
        return set(self._handle("").keys())

    def __contains__(self, name: str) -> bool:
        return name in self.names()

    def get(self, name: str) -> np.ndarray:
        # keep the checkpoint dtype (bf16 works on the host via
        # ml_dtypes): peak host memory stays ~1x checkpoint size
        return np.asarray(self._handle(name).get_tensor(name))


def load_hf(
    path: str,
    mesh,
    cfg: Optional[ModelConfig] = None,
    axis: str = TP_AXIS,
    dtype=None,
) -> DenseLLMParams:
    """Load an HF-format checkpoint directory into sharded
    DenseLLMParams (ref: models/dense.py:150-167 + layers' TP splits,
    layers/nvidia/tp_mlp.py:64-83 / tp_attn.py `_init_parameters`).

    `path` holds config.json + model.safetensors (or the sharded
    index). cfg defaults to config_from_hf(path). Returns params laid
    out exactly like init_params — Engine, DenseLLM forward and the
    megakernel consume them unchanged."""
    cfg = cfg or config_from_hf(path)
    n = int(mesh.shape[axis])
    dt = jnp.dtype(dtype or cfg.dtype)
    ckpt = _Checkpoint(path)
    L = cfg.num_layers
    h, d = cfg.hidden_size, cfg.head_dim
    hq_l = cfg.num_q_heads // n
    hkv_l = cfg.num_kv_heads // n
    names = ckpt.names()

    def shard_cols(w_t: np.ndarray, per: int) -> np.ndarray:
        """(in, out) -> (n, in, per) column-parallel shards."""
        return np.stack([w_t[:, r * per:(r + 1) * per] for r in range(n)])

    def shard_rows(w_t: np.ndarray, per: int) -> np.ndarray:
        """(in, out) -> (n, per, out) row-parallel shards."""
        return np.stack([w_t[r * per:(r + 1) * per] for r in range(n)])

    def proj(name: str) -> np.ndarray:
        # HF Linear is (out, in); kernels consume (in, out)
        return ckpt.get(name).T

    embed = ckpt.get("model.embed_tokens.weight")
    head_name = "lm_head.weight"
    if cfg.tie_word_embeddings or head_name not in names:
        head_t = embed.T  # (H, V)
    else:
        head_t = proj(head_name)
    v_l = cfg.vocab_size // n
    lm_head = shard_cols(head_t, v_l)

    use_qk_norm = "model.layers.0.self_attn.q_norm.weight" in names
    per_layer: Dict[str, list] = {k: [] for k in (
        "input_ln", "post_attn_ln", "w_qkv", "w_o", "q_norm", "k_norm",
        "w_gate", "w_up", "w_down", "w_gate_up", "w_router",
    )}
    for l in range(L):
        p = f"model.layers.{l}."
        per_layer["input_ln"].append(ckpt.get(p + "input_layernorm.weight"))
        per_layer["post_attn_ln"].append(
            ckpt.get(p + "post_attention_layernorm.weight"))
        q_t = proj(p + "self_attn.q_proj.weight")  # (H, Hq*D)
        k_t = proj(p + "self_attn.k_proj.weight")
        v_t = proj(p + "self_attn.v_proj.weight")
        qkv = np.concatenate([
            shard_cols(q_t, hq_l * d),
            shard_cols(k_t, hkv_l * d),
            shard_cols(v_t, hkv_l * d),
        ], axis=2)  # (n, H, (hq_l+2hkv_l)*D)
        per_layer["w_qkv"].append(qkv)
        per_layer["w_o"].append(
            shard_rows(proj(p + "self_attn.o_proj.weight"), hq_l * d))
        if use_qk_norm:
            per_layer["q_norm"].append(
                ckpt.get(p + "self_attn.q_norm.weight"))
            per_layer["k_norm"].append(
                ckpt.get(p + "self_attn.k_norm.weight"))
        else:
            ones = np.ones((d,), embed.dtype)
            per_layer["q_norm"].append(ones)
            per_layer["k_norm"].append(ones)
        if cfg.is_moe:
            e = cfg.num_experts
            mi_l = cfg.moe_intermediate_size // n
            per_layer["w_router"].append(proj(p + "mlp.gate.weight"))
            gus, downs = [], []
            for ei in range(e):
                ep = f"{p}mlp.experts.{ei}."
                g_t = proj(ep + "gate_proj.weight")  # (H, mi)
                u_t = proj(ep + "up_proj.weight")
                # fused per-rank [gate_r | up_r] (the grouped-GEMM
                # expert layout, layers/tp_moe.py)
                gus.append(np.concatenate([
                    shard_cols(g_t, mi_l), shard_cols(u_t, mi_l)
                ], axis=2))  # (n, H, 2mi_l)
                downs.append(shard_rows(proj(ep + "down_proj.weight"),
                                        mi_l))
            per_layer["w_gate_up"].append(np.stack(gus, axis=1))
            per_layer["w_down"].append(np.stack(downs, axis=1))
        else:
            i_l = cfg.intermediate_size // n
            per_layer["w_gate"].append(
                shard_cols(proj(p + "mlp.gate_proj.weight"), i_l))
            per_layer["w_up"].append(
                shard_cols(proj(p + "mlp.up_proj.weight"), i_l))
            per_layer["w_down"].append(
                shard_rows(proj(p + "mlp.down_proj.weight"), i_l))

    def stack(key):
        vals = per_layer[key]
        return np.stack(vals) if vals else None

    moe = cfg.is_moe
    layers = DenseLayerParams(
        input_ln=stack("input_ln"),
        post_attn_ln=stack("post_attn_ln"),
        w_qkv=stack("w_qkv"),
        w_o=stack("w_o"),
        q_norm=stack("q_norm"),
        k_norm=stack("k_norm"),
        w_down=stack("w_down"),
        w_gate=None if moe else stack("w_gate"),
        w_up=None if moe else stack("w_up"),
        w_gate_up=stack("w_gate_up") if moe else None,
        w_router=stack("w_router") if moe else None,
    )
    params = DenseLLMParams(
        embed=embed, layers=layers, final_ln=ckpt.get("model.norm.weight"),
        lm_head=lm_head,
    )
    specs = param_specs(axis, moe)
    return jax.tree.map(
        lambda x, s: jax.device_put(
            jnp.asarray(x, dt), NamedSharding(mesh, s)
        ),
        params, specs,
    )


class AutoLLM:
    """Checkpoint-directory -> ready Engine (the reference's AutoLLM
    name->class dispatch, models/__init__.py:33-50; the architecture
    field of config.json plays the model_mapping key)."""

    @staticmethod
    def from_pretrained(path: str, mesh, axis: str = TP_AXIS, **engine_kw):
        from triton_dist_tpu.models.engine import Engine

        cfg = config_from_hf(path)
        params = load_hf(path, mesh, cfg, axis)
        return Engine(cfg, mesh, axis=axis, params=params, **engine_kw)
