"""Inference engine: jit'd prefill + decode steps and a serve loop.

TPU-native re-design of the reference's Engine
(ref: python/triton_dist/models/engine.py:37-189): the CUDA-graph capture
of the decode step (:75-105) becomes a jit-compiled decode function with
donated KV cache — tracing once and replaying the compiled executable is
exactly the graph-replay idiom on TPU. `serve` (:113-189) is the same
prefill-then-decode loop, but the decode phase runs as ONE dispatch:
`generate` rolls the whole token loop (forward + sampling + cache append)
into a lax.fori_loop under a single jit, so generation costs one host
round-trip instead of one per token (the round-4 verdict's weak #8 —
where the reference replays one CUDA graph per step, the TPU-native move
is to compile the loop itself).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.models.dense import (
    DenseLLMParams,
    cache_specs,
    forward,
    init_params,
    param_specs,
)
from triton_dist_tpu.models.kv_cache import KVCache
from triton_dist_tpu.runtime.init import TP_AXIS


def sample_token(logits, key=None, temperature: float = 0.0):
    """Greedy or temperature sampling (ref: models/utils.py sample_token).
    logits: (B, V) f32 -> (B,) int32."""
    if temperature <= 0.0 or key is None:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(key, logits / temperature, axis=-1).astype(
        jnp.int32
    )


class Engine:
    """Holds sharded params + compiled prefill/decode executables.

    prefill_mode/decode_mode mirror the reference's backend switch
    (`--backend torch|triton_dist|triton_dist_AR`,
    ref: test/nvidia/test_e2e_inference.py)."""

    def __init__(
        self,
        cfg: ModelConfig,
        mesh,
        axis: str = TP_AXIS,
        prefill_mode: str = "dist",
        decode_mode: str = "ar",
        params: Optional[DenseLLMParams] = None,
        seed: int = 0,
        max_len: Optional[int] = None,
        batch_axis: Optional[str] = None,
        donate_cache: bool = True,
        fast_init: bool = False,
    ):
        self.cfg = cfg
        self.mesh = mesh
        self.axis = axis
        self.batch_axis = batch_axis
        self.max_len = max_len or cfg.max_positions
        self.params = (
            params if params is not None
            else init_params(cfg, mesh, seed, axis, fast=fast_init)
        )
        n = int(mesh.shape[axis])
        self._hkv_loc = cfg.num_kv_heads // n

        p_specs = param_specs(axis, cfg.is_moe)
        c_specs = cache_specs(axis, batch_axis)
        t_spec = P(batch_axis)

        def prefill_fn(params, tokens, cache):
            return forward(cfg, params, tokens, cache, mode=prefill_mode,
                           axis=axis)

        def decode_fn(params, tokens, cache):
            return forward(cfg, params, tokens, cache, mode=decode_mode,
                           axis=axis)

        def wrap(fn):
            return jax.jit(
                jax.shard_map(
                    fn,
                    mesh=mesh,
                    in_specs=(p_specs, t_spec, c_specs),
                    out_specs=(t_spec, c_specs),
                    check_vma=False,
                ),
                # donate the cache: XLA updates it in place (the reference
                # mutates torch tensors inside the captured graph). Callers
                # that must re-invoke on the same cache (compile checks)
                # pass donate_cache=False.
                donate_argnums=(2,) if donate_cache else (),
            )

        self._prefill = wrap(prefill_fn)
        self._decode = wrap(decode_fn)
        self._decode_fn = decode_fn
        self._wrap_specs = (p_specs, t_spec, c_specs)
        self._donate_cache = donate_cache
        # compiled generate() executables, keyed (steps, greedy). A
        # per-instance dict, NOT lru_cache on the bound method: that keys
        # a module-lifetime cache on self and pins every Engine (params +
        # compiled shard_map executables) for the process lifetime.
        # Bounded like the lru_cache it replaces — a server honoring
        # per-request step counts must not accumulate executables forever.
        self._gen_cache: dict = {}
        self._gen_cache_max = 8

    def _gen_fn(self, steps: int, greedy: bool):
        key = (steps, greedy)
        fn = self._gen_cache.pop(key, None)
        if fn is None:
            fn = self._build_gen_fn(steps, greedy)
            while len(self._gen_cache) >= self._gen_cache_max:
                self._gen_cache.pop(next(iter(self._gen_cache)))
        self._gen_cache[key] = fn  # re-insert = LRU touch
        return fn

    def _build_gen_fn(self, steps: int, greedy: bool):
        """Compiled multi-step generation: `steps` decode iterations —
        forward, sampling, cache append — inside one lax.fori_loop under
        one jit (one executable replay per GENERATION, not per token)."""
        p_specs, t_spec, c_specs = self._wrap_specs

        def per_rank(params, tok, cache, key, temp):
            b = tok.shape[0]

            def body(i, carry):
                tok, cache, key, out = carry
                logits, cache = self._decode_fn(params, tok[:, None],
                                                cache)
                if greedy:
                    nxt = jnp.argmax(logits, -1).astype(jnp.int32)
                else:
                    key, sub = jax.random.split(key)
                    nxt = jax.random.categorical(sub, logits / temp,
                                                 axis=-1)
                    nxt = nxt.astype(jnp.int32)
                return nxt, cache, key, out.at[:, i].set(nxt)

            out0 = jnp.zeros((b, steps), jnp.int32)
            tok, cache, key, out = jax.lax.fori_loop(
                0, steps, body, (tok, cache, key, out0))
            return out, cache

        return jax.jit(
            jax.shard_map(
                per_rank, mesh=self.mesh,
                in_specs=(p_specs, t_spec, c_specs, P(), P()),
                out_specs=(t_spec, c_specs),
                check_vma=False,
            ),
            donate_argnums=(2,) if self._donate_cache else (),
        )

    def generate(self, tokens, cache: KVCache, steps: int,
                 temperature: float = 0.0, key=None):
        """Decode `steps` tokens from `tokens` (B,) in ONE dispatch.
        Returns (generated ids (B, steps), cache). Greedy at
        temperature<=0 (or no key), else categorical on logits/T with
        per-step key splits; temperature rides as a traced scalar so
        distinct values replay one executable."""
        greedy = temperature <= 0.0 or key is None
        if key is None:
            key = jax.random.PRNGKey(0)
        fn = self._gen_fn(steps, greedy)
        tok = jnp.asarray(tokens, jnp.int32)
        temp = jnp.asarray(max(temperature, 1e-6), jnp.float32)
        return fn(self.params, tok, cache, key, temp)

    # -- API ----------------------------------------------------------------

    def new_cache(self, batch: int) -> KVCache:
        cache = KVCache.create(
            self.cfg.num_layers, batch, self.max_len,
            self._hkv_loc * int(self.mesh.shape[self.axis]),
            self.cfg.head_dim, jnp.dtype(self.cfg.dtype),
        )
        specs = cache_specs(self.axis, self.batch_axis)
        return jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)),
            cache, specs,
        )

    def prefill(self, input_ids, cache: Optional[KVCache] = None):
        """input_ids: (B, S) -> (last-token logits (B, V), cache)."""
        input_ids = jnp.asarray(input_ids, jnp.int32)
        if cache is None:
            cache = self.new_cache(input_ids.shape[0])
        return self._prefill(self.params, input_ids, cache)

    def decode_step(self, tokens, cache: KVCache):
        """tokens: (B,) -> (logits (B, V), cache)."""
        return self._decode(
            self.params, jnp.asarray(tokens, jnp.int32)[:, None], cache
        )

    def serve(
        self,
        input_ids,
        gen_len: int,
        temperature: float = 0.0,
        seed: int = 0,
    ):
        """Prefill + gen_len decode steps (ref Engine.serve,
        engine.py:113-189). Returns generated ids (B, gen_len). The
        decode phase is ONE `generate` dispatch (see module doc)."""
        key = jax.random.PRNGKey(seed)
        logits, cache = self.prefill(input_ids)
        key, sub = jax.random.split(key)
        tok = sample_token(logits, sub, temperature)
        if gen_len == 1:
            return tok[:, None]
        key, sub = jax.random.split(key)
        rest, _ = self.generate(
            tok, cache, gen_len - 1, temperature,
            key=sub if temperature > 0.0 else None,
        )
        return jnp.concatenate([tok[:, None], rest], axis=1)
