"""Inference engine: jit'd prefill + decode steps and a serve loop.

TPU-native re-design of the reference's Engine
(ref: python/triton_dist/models/engine.py:37-189): the CUDA-graph capture
of the decode step (:75-105) becomes a jit-compiled decode function with
donated KV cache — tracing once and replaying the compiled executable is
exactly the graph-replay idiom on TPU; `serve` (:113-189) is the same
prefill-then-decode loop.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.models.dense import (
    DenseLLMParams,
    cache_specs,
    forward,
    init_params,
    param_specs,
)
from triton_dist_tpu.models.kv_cache import KVCache
from triton_dist_tpu.runtime.init import TP_AXIS


def sample_token(logits, key=None, temperature: float = 0.0):
    """Greedy or temperature sampling (ref: models/utils.py sample_token).
    logits: (B, V) f32 -> (B,) int32."""
    if temperature <= 0.0 or key is None:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(key, logits / temperature, axis=-1).astype(
        jnp.int32
    )


class Engine:
    """Holds sharded params + compiled prefill/decode executables.

    prefill_mode/decode_mode mirror the reference's backend switch
    (`--backend torch|triton_dist|triton_dist_AR`,
    ref: test/nvidia/test_e2e_inference.py)."""

    def __init__(
        self,
        cfg: ModelConfig,
        mesh,
        axis: str = TP_AXIS,
        prefill_mode: str = "dist",
        decode_mode: str = "ar",
        params: Optional[DenseLLMParams] = None,
        seed: int = 0,
        max_len: Optional[int] = None,
        batch_axis: Optional[str] = None,
        donate_cache: bool = True,
        fast_init: bool = False,
    ):
        self.cfg = cfg
        self.mesh = mesh
        self.axis = axis
        self.batch_axis = batch_axis
        self.max_len = max_len or cfg.max_positions
        self.params = (
            params if params is not None
            else init_params(cfg, mesh, seed, axis, fast=fast_init)
        )
        n = int(mesh.shape[axis])
        self._hkv_loc = cfg.num_kv_heads // n

        p_specs = param_specs(axis, cfg.is_moe)
        c_specs = cache_specs(axis, batch_axis)
        t_spec = P(batch_axis)

        def prefill_fn(params, tokens, cache):
            return forward(cfg, params, tokens, cache, mode=prefill_mode,
                           axis=axis)

        def decode_fn(params, tokens, cache):
            return forward(cfg, params, tokens, cache, mode=decode_mode,
                           axis=axis)

        def wrap(fn):
            return jax.jit(
                jax.shard_map(
                    fn,
                    mesh=mesh,
                    in_specs=(p_specs, t_spec, c_specs),
                    out_specs=(t_spec, c_specs),
                    check_vma=False,
                ),
                # donate the cache: XLA updates it in place (the reference
                # mutates torch tensors inside the captured graph). Callers
                # that must re-invoke on the same cache (compile checks)
                # pass donate_cache=False.
                donate_argnums=(2,) if donate_cache else (),
            )

        self._prefill = wrap(prefill_fn)
        self._decode = wrap(decode_fn)

    # -- API ----------------------------------------------------------------

    def new_cache(self, batch: int) -> KVCache:
        cache = KVCache.create(
            self.cfg.num_layers, batch, self.max_len,
            self._hkv_loc * int(self.mesh.shape[self.axis]),
            self.cfg.head_dim, jnp.dtype(self.cfg.dtype),
        )
        specs = cache_specs(self.axis, self.batch_axis)
        return jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)),
            cache, specs,
        )

    def prefill(self, input_ids, cache: Optional[KVCache] = None):
        """input_ids: (B, S) -> (last-token logits (B, V), cache)."""
        input_ids = jnp.asarray(input_ids, jnp.int32)
        if cache is None:
            cache = self.new_cache(input_ids.shape[0])
        return self._prefill(self.params, input_ids, cache)

    def decode_step(self, tokens, cache: KVCache):
        """tokens: (B,) -> (logits (B, V), cache)."""
        return self._decode(
            self.params, jnp.asarray(tokens, jnp.int32)[:, None], cache
        )

    def serve(
        self,
        input_ids,
        gen_len: int,
        temperature: float = 0.0,
        seed: int = 0,
    ):
        """Prefill + gen_len decode steps (ref Engine.serve,
        engine.py:113-189). Returns generated ids (B, gen_len)."""
        key = jax.random.PRNGKey(seed)
        logits, cache = self.prefill(input_ids)
        out = []
        key, sub = jax.random.split(key)
        tok = sample_token(logits, sub, temperature)
        out.append(tok)
        for _ in range(gen_len - 1):
            key, sub = jax.random.split(key)
            logits, cache = self.decode_step(tok, cache)
            tok = sample_token(logits, sub, temperature)
            out.append(tok)
        return jnp.stack(out, axis=1)  # (B, gen_len)
