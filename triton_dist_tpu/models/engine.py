"""Inference engine: jit'd prefill + decode steps and a serve loop.

TPU-native re-design of the reference's Engine
(ref: python/triton_dist/models/engine.py:37-189): the CUDA-graph capture
of the decode step (:75-105) becomes a jit-compiled decode function with
donated KV cache — tracing once and replaying the compiled executable is
exactly the graph-replay idiom on TPU. `serve` (:113-189) is the same
prefill-then-decode loop, but the decode phase runs as ONE dispatch:
`generate` rolls the whole token loop (forward + sampling + cache append)
into a lax.fori_loop under a single jit, so generation costs one host
round-trip instead of one per token (the round-4 verdict's weak #8 —
where the reference replays one CUDA graph per step, the TPU-native move
is to compile the loop itself).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.models.dense import (
    DenseLLMParams,
    cache_specs,
    forward,
    init_params,
    param_specs,
)
from triton_dist_tpu.models.kv_cache import KVCache
from triton_dist_tpu.runtime.init import TP_AXIS


def sample_token(logits, key=None, temperature: float = 0.0):
    """Greedy or temperature sampling (ref: models/utils.py sample_token).
    logits: (B, V) f32 -> (B,) int32."""
    if temperature <= 0.0 or key is None:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(key, logits / temperature, axis=-1).astype(
        jnp.int32
    )


def _serve_step_math(cfg, mode, axis, slots, chunk, page, t_pool,
                     params, tokens, pool_k, pool_v, table, lengths,
                     n_valid, temps, keys, per_pos: bool = False,
                     plan=None):
    """THE per-rank serve-step computation (inside shard_map): one
    fixed-geometry (slots, chunk) forward over the paged pool's dense
    view, per-slot sampling, and the null-page-routed KV scatter.
    Shared VERBATIM between `make_serve_step` (the host-loop replay)
    and `make_resident_loop` (the device-resident window) — the serve
    plane's bit-identity discipline extends to the resident loop
    because both compile exactly this function on identical inputs
    (tests/test_serve_resident.py pins the loop-vs-standalone bitwise
    equality end to end).

    per_pos=False: keys (K, 2) u32, the returned token is sampled at
    column n_valid-1 only — the classic one-emission step. per_pos=True
    (the spec-verify form, ISSUE 14): keys (K, C, 2) — EVERY column is
    sampled under its own key and the returned token array is (K, C);
    column j's token is what sequential decode would emit after
    consuming tokens[:, :j+1] (the per-(seed, token-index) key stream
    makes that literal, greedy AND sampled), which is exactly the
    bit-identity oracle the longest-accepted-prefix rule needs
    (triton_dist_tpu.spec.verify)."""
    cache = KVCache.dense_view(pool_k, pool_v, table, lengths)
    logits, new_cache = forward(
        cfg, params, tokens, cache, mode=mode, axis=axis,
        return_full_logits=True, plan=plan,
    )  # logits (K, C, V) f32, new_cache k/v (L, K, T, Hkv, D)
    bidx = jnp.arange(slots)[:, None]
    last = logits[jnp.arange(slots),
                  jnp.maximum(n_valid - 1, 0)]  # (K, V)
    if per_pos:
        greedy_all = jnp.argmax(logits, -1).astype(jnp.int32)  # (K, C)
        temp = jnp.maximum(temps, 1e-6)[:, None, None]
        sampled_all = jax.vmap(jax.vmap(jax.random.categorical))(
            keys, logits / temp
        ).astype(jnp.int32)
        tok = jnp.where(temps[:, None] > 0.0, sampled_all, greedy_all)
    else:
        greedy = jnp.argmax(last, -1).astype(jnp.int32)
        temp = jnp.maximum(temps, 1e-6)[:, None]
        sampled = jax.vmap(jax.random.categorical)(
            keys, last / temp
        ).astype(jnp.int32)
        tok = jnp.where(temps > 0.0, sampled, greedy)

    # scatter this step's K/V rows back into the pool: valid
    # columns land on their table pages; padding columns are
    # routed to page 0, the pool's reserved null page (their
    # positions may sit past the slot's allocated pages, whose
    # table entries still map to live pages of OTHER slots)
    pos = lengths[:, None] + jnp.arange(chunk)[None, :]  # (K, C)
    posc = jnp.minimum(pos, t_pool - 1)
    valid = jnp.arange(chunk)[None, :] < n_valid[:, None]
    pg = jnp.where(valid, table[bidx, posc // page], 0)
    off = posc % page
    kn = jnp.moveaxis(new_cache.k[:, bidx, posc], 3, 1)
    vn = jnp.moveaxis(new_cache.v[:, bidx, posc], 3, 1)
    pool_k = pool_k.at[:, :, pg, off].set(kn.astype(pool_k.dtype))
    pool_v = pool_v.at[:, :, pg, off].set(vn.astype(pool_v.dtype))
    return tok, last, pool_k, pool_v


class Engine:
    """Holds sharded params + compiled prefill/decode executables.

    prefill_mode/decode_mode mirror the reference's backend switch
    (`--backend torch|triton_dist|triton_dist_AR`,
    ref: test/nvidia/test_e2e_inference.py)."""

    def __init__(
        self,
        cfg: ModelConfig,
        mesh,
        axis: str = TP_AXIS,
        prefill_mode: str = "dist",
        decode_mode: str = "ar",
        params: Optional[DenseLLMParams] = None,
        seed: int = 0,
        max_len: Optional[int] = None,
        batch_axis: Optional[str] = None,
        donate_cache: bool = True,
        fast_init: bool = False,
    ):
        self.cfg = cfg
        self.mesh = mesh
        self.axis = axis
        self.batch_axis = batch_axis
        self.max_len = max_len or cfg.max_positions
        self.prefill_mode = prefill_mode
        self.decode_mode = decode_mode
        self.params = (
            params if params is not None
            else init_params(cfg, mesh, seed, axis, fast=fast_init)
        )
        n = int(mesh.shape[axis])
        self._hkv_loc = cfg.num_kv_heads // n

        p_specs = param_specs(axis, cfg.is_moe)
        c_specs = cache_specs(axis, batch_axis)
        t_spec = P(batch_axis)

        def prefill_fn(params, tokens, cache):
            return forward(cfg, params, tokens, cache, mode=prefill_mode,
                           axis=axis)

        def decode_fn(params, tokens, cache):
            return forward(cfg, params, tokens, cache, mode=decode_mode,
                           axis=axis)

        def wrap(fn):
            return jax.jit(
                jax.shard_map(
                    fn,
                    mesh=mesh,
                    in_specs=(p_specs, t_spec, c_specs),
                    out_specs=(t_spec, c_specs),
                    check_vma=False,
                ),
                # donate the cache: XLA updates it in place (the reference
                # mutates torch tensors inside the captured graph). Callers
                # that must re-invoke on the same cache (compile checks)
                # pass donate_cache=False.
                donate_argnums=(2,) if donate_cache else (),
            )

        self._prefill = wrap(prefill_fn)
        self._decode = wrap(decode_fn)
        self._decode_fn = decode_fn
        self._wrap_specs = (p_specs, t_spec, c_specs)
        self._donate_cache = donate_cache
        # compiled generate() executables, keyed (steps, greedy). A
        # per-instance dict, NOT lru_cache on the bound method: that keys
        # a module-lifetime cache on self and pins every Engine (params +
        # compiled shard_map executables) for the process lifetime.
        # Bounded like the lru_cache it replaces — a server honoring
        # per-request step counts must not accumulate executables forever.
        self._gen_cache: dict = {}
        self._gen_cache_max = 8
        # compiled serve-step executables, keyed on the batch-of-
        # sequence-states geometry (see make_serve_step) — bounded like
        # _gen_cache, and shared between Engine.serve's stepwise path
        # and the serve-plane Worker so both replay ONE executable.
        self._serve_cache: dict = {}

    def plan_for(self, batch: int, seq: int, kind: str = "decode"):
        """The fusion plan (triton_dist_tpu.plan.Plan) this engine's
        forwards execute under at the given step geometry. Memoized in
        the planner, so this IS the same object `forward` resolves
        inside the compiled step — the serve Scheduler and
        mega.schedule_graph consume it to provably agree on pairings."""
        from triton_dist_tpu.plan.planner import plan_dense_forward

        mode = self.prefill_mode if kind == "prefill" else self.decode_mode
        n = int(self.mesh.shape[self.axis])
        return plan_dense_forward(self.cfg, batch, seq, n, mode=mode)

    def _gen_fn(self, steps: int, greedy: bool):
        key = (steps, greedy)
        fn = self._gen_cache.pop(key, None)
        if fn is None:
            fn = self._build_gen_fn(steps, greedy)
            while len(self._gen_cache) >= self._gen_cache_max:
                self._gen_cache.pop(next(iter(self._gen_cache)))
        self._gen_cache[key] = fn  # re-insert = LRU touch
        return fn

    def _build_gen_fn(self, steps: int, greedy: bool):
        """Compiled multi-step generation: `steps` decode iterations —
        forward, sampling, cache append — inside one lax.fori_loop under
        one jit (one executable replay per GENERATION, not per token)."""
        p_specs, t_spec, c_specs = self._wrap_specs

        def per_rank(params, tok, cache, key, temp):
            b = tok.shape[0]

            def body(i, carry):
                tok, cache, key, out = carry
                logits, cache = self._decode_fn(params, tok[:, None],
                                                cache)
                if greedy:
                    nxt = jnp.argmax(logits, -1).astype(jnp.int32)
                else:
                    key, sub = jax.random.split(key)
                    nxt = jax.random.categorical(sub, logits / temp,
                                                 axis=-1)
                    nxt = nxt.astype(jnp.int32)
                return nxt, cache, key, out.at[:, i].set(nxt)

            out0 = jnp.zeros((b, steps), jnp.int32)
            tok, cache, key, out = jax.lax.fori_loop(
                0, steps, body, (tok, cache, key, out0))
            return out, cache

        return jax.jit(
            jax.shard_map(
                per_rank, mesh=self.mesh,
                in_specs=(p_specs, t_spec, c_specs, P(), P()),
                out_specs=(t_spec, c_specs),
                check_vma=False,
            ),
            donate_argnums=(2,) if self._donate_cache else (),
        )

    def generate(self, tokens, cache: KVCache, steps: int,
                 temperature: float = 0.0, key=None):
        """Decode `steps` tokens from `tokens` (B,) in ONE dispatch.
        Returns (generated ids (B, steps), cache). Greedy at
        temperature<=0 (or no key), else categorical on logits/T with
        per-step key splits; temperature rides as a traced scalar so
        distinct values replay one executable."""
        greedy = temperature <= 0.0 or key is None
        if key is None:
            key = jax.random.PRNGKey(0)
        fn = self._gen_fn(steps, greedy)
        tok = jnp.asarray(tokens, jnp.int32)
        temp = jnp.asarray(max(temperature, 1e-6), jnp.float32)
        return fn(self.params, tok, cache, key, temp)

    # -- serve step (batch-of-sequence-states contract) ---------------------

    def make_serve_step(self, slots: int, chunk: int, page: int,
                        max_pages: int, per_pos: bool = False):
        """ONE jit'd step function over a shared paged-KV pool — the
        contract the continuous-batching serve plane replays
        (triton_dist_tpu.serve; ref: the model_server loop replaying
        the captured decode graph, mega_triton_kernel/test/models/
        model_server.py).

        Geometry is FIXED at (slots, chunk): every step runs the model
        over a (slots, chunk) token block in `decode_mode`, whatever
        mixture of prefill chunks and single-token decode steps the
        scheduler packed into it. A slot's row carries `n_valid` real
        tokens (prefill: up to `chunk` prompt tokens; decode: 1;
        inactive: 0) starting at its current sequence length; the rest
        of the row is padding whose outputs are discarded and whose KV
        writes are routed to the pool's reserved null page. Because the
        geometry never changes and XLA's row numerics are independent
        of the CONTENT and COLUMN PLACEMENT of other rows (only of the
        operand shapes), each request's tokens are bitwise invariant to
        batch composition, slot placement, chunk alignment, and
        eviction/re-prefill — the property tests/test_serve.py pins.

        Signature of the returned callable:
          fn(params, tokens (K, C) i32, pool_k, pool_v
             (L, Hkv, P, page, D) — megakernel pool layout, shared with
             mega.qwen3.PagedMegaKVCache — table (K, MAXP) i32,
             lengths (K,) i32, n_valid (K,) i32, temps (K,) f32,
             keys (K, 2) u32)
          -> (next_token (K,) i32, last_logits (K, V) f32,
              pool_k, pool_v)

        next_token is greedy argmax where temps<=0, else categorical on
        logits/temp under the slot's key — keys are derived host-side
        from (request seed, token index), so sampled generations are
        ALSO scheduling-invariant. Pool buffers are donated when the
        engine was built with donate_cache=True.

        per_pos=True compiles the SPEC-VERIFY form of the same step
        (ISSUE 14, triton_dist_tpu.spec): keys become (K, C, 2) — one
        per column — and next_token becomes the (K, C) per-position
        token matrix, column j sampled from the logits after consuming
        tokens[:, :j+1] under its own key. One dispatch scores a whole
        k-token draft per slot; the scheduler's longest-accepted-prefix
        rule reads the matrix host-side (spec/verify.py). The caller
        owns the length advance (accepted count, not n_valid)."""
        key = (slots, chunk, page, max_pages, per_pos)
        fn = self._serve_cache.pop(key, None)
        if fn is None:
            fn = self._build_serve_step(slots, chunk, page, max_pages,
                                        per_pos=per_pos)
            while len(self._serve_cache) >= self._gen_cache_max:
                self._serve_cache.pop(next(iter(self._serve_cache)))
        self._serve_cache[key] = fn  # re-insert = LRU touch
        return fn

    def _build_serve_step(self, slots: int, chunk: int, page: int,
                          max_pages: int, per_pos: bool = False):
        cfg = self.cfg
        mode = self.decode_mode
        axis = self.axis
        t_pool = max_pages * page
        self._check_serve_geometry(slots, chunk, page, max_pages)
        # the ONE Plan for this step geometry (same memoized object the
        # serve Scheduler and mega builders hold — plan_for doc)
        plan = self.plan_for(slots, chunk, kind="decode")

        def per_rank(params, tokens, pool_k, pool_v, table, lengths,
                     n_valid, temps, keys):
            return _serve_step_math(
                cfg, mode, axis, slots, chunk, page, t_pool,
                params, tokens, pool_k, pool_v, table, lengths,
                n_valid, temps, keys, per_pos=per_pos, plan=plan)

        pool_spec = P(None, self.axis)
        return jax.jit(
            jax.shard_map(
                per_rank, mesh=self.mesh,
                in_specs=((self._wrap_specs[0], P(), pool_spec, pool_spec)
                          + (P(),) * 5),
                out_specs=(P(), P(), pool_spec, pool_spec),
                check_vma=False,
            ),
            donate_argnums=(2, 3) if self._donate_cache else (),
        )

    def _check_serve_geometry(self, slots: int, chunk: int, page: int,
                              max_pages: int) -> None:
        t_pool = max_pages * page
        assert t_pool <= self.cfg.max_positions, (
            f"pool horizon {t_pool} exceeds max_positions "
            f"{self.cfg.max_positions} (rope table)"
        )
        n = int(self.mesh.shape[self.axis])
        from triton_dist_tpu.plan.planner import SEQ_SHARDED_MODES

        if self.decode_mode in SEQ_SHARDED_MODES:
            assert (slots * chunk) % n == 0, (
                f"sequence-sharded mode {self.decode_mode!r} needs "
                f"slots*chunk ({slots}*{chunk}) divisible by tp={n}"
            )

    # -- resident step loop (megakernel-resident serving, ISSUE 12) ---------

    def make_resident_loop(self, slots: int, chunk: int, page: int,
                           max_pages: int, window: int,
                           ring_cap: int = 64,
                           prompt_cap: Optional[int] = None,
                           poll_budget: int = 8, spec_k: int = 0):
        """Compile the DEVICE-RESIDENT serve loop: up to `window` serve
        steps inside one executable — consume work-injection records at
        each step boundary, run the SAME per-rank step math as
        `make_serve_step`, self-feed decode tokens, and stream
        completions (emitted tokens + retirement flags) into a mirrored
        output ring — so a window of W steps costs ONE dispatch instead
        of W (the r05 `engine_decode_ms` vs `mega_decode_*` gap is pure
        per-step dispatch tax; this loop is how the serve plane stops
        paying it per token).

        Contract (docs/serving.md "Device-resident serving"):

          fn(params, ring (cap, RW) i32, published () i32,
             consumed () i32, step0 () i32, slot_state (K, SS) i32,
             table (K, MAXP) i32, lengths (K,) i32, pool_k, pool_v)
          -> (consumed, executed, slot_state, table, lengths,
              pool_k, pool_v, out_ring (out_cap, OW) i32,
              out_count, starved)

        All loop state round-trips through the call, so successive
        windows chain seamlessly; pool buffers are donated like the
        host-loop step. The loop exits when `window` steps executed OR
        nothing is active and the pending-record poll budget is
        exhausted; `starved` is set when a published head record never
        became visible (abandoned ring — the host raises a structured
        DeadlineExceeded from it, see serve.worker.ResidentWorker).

        Per-request tokens are BITWISE what the host-loop scheduler
        emits: both paths compile `_serve_step_math` and the device
        plan assembly (`mega.ring.slot_plan`) reproduces the host
        scheduler's per-step inputs field for field, including the
        fold_in(PRNGKey(seed), n_out) sampling-key stream.

        Telemetry (ISSUE 13, docs/observability.md "Request-scoped
        attribution"): a loop constructed under `trace.building()`
        returns one extra trailing output — a pure-jnp mark stream of
        serve.step spans (payload=device step, aux=active-slot mask)
        plus serve.poll / serve.idle instants; under
        `obs.stats.building()` one more — the (1 + slots, 1,
        STAT_WORDS) resident-window stat rows (obs.stats.WMAGIC: loop
        lane + one lane per slot), OUTERMOST last (the stats-then-trace
        strip order). Both are data-independent integer streams: tokens
        stay bitwise identical with telemetry on, and the bare loop's
        program is untouched (zero-cost-off, tier-1-pinned).

        spec_k > 0 compiles the SPEC-CAPABLE loop (ISSUE 14,
        triton_dist_tpu.spec): KIND_VERIFY injection records stage up
        to spec_k draft tokens on a decoding slot, the next step runs
        the per-position verify row, and the longest accepted prefix
        streams out as FLAG_SPEC output records (up to spec_k + 1 per
        slot per step — out_cap scales accordingly). spec_k=0 keeps
        today's program exactly (the branch is trace-time)."""
        from triton_dist_tpu.obs import stats as _ost
        from triton_dist_tpu.trace import events as _tev

        prompt_cap = prompt_cap if prompt_cap is not None \
            else max_pages * page
        # the build contexts are consulted when the loop is CONSTRUCTED
        # (the trace/obs discipline) — a loop built under
        # trace.building()/obs.stats.building() returns extra trailing
        # telemetry outputs, so it must never share an executable with
        # the bare loop
        _tb = _tev.active_build()
        _ob = _ost.active_build()
        key = ("resident", slots, chunk, page, max_pages, window,
               ring_cap, prompt_cap, poll_budget, spec_k,
               _tb.cap if _tb is not None else -1, _ob is not None)
        fn = self._serve_cache.pop(key, None)
        if fn is None:
            fn = self._build_resident_loop(slots, chunk, page, max_pages,
                                           window, ring_cap, prompt_cap,
                                           poll_budget, spec_k)
            while len(self._serve_cache) >= self._gen_cache_max:
                self._serve_cache.pop(next(iter(self._serve_cache)))
        self._serve_cache[key] = fn  # re-insert = LRU touch
        return fn

    def _build_resident_loop(self, slots: int, chunk: int, page: int,
                             max_pages: int, window: int, ring_cap: int,
                             prompt_cap: int, poll_budget: int,
                             spec_k: int = 0):
        from triton_dist_tpu.mega import ring as mring
        from triton_dist_tpu.obs import stats as _ost
        from triton_dist_tpu.trace import events as _tev

        cfg = self.cfg
        mode = self.decode_mode
        axis = self.axis
        t_pool = max_pages * page
        self._check_serve_geometry(slots, chunk, page, max_pages)
        # same memoized Plan object as make_serve_step's — the resident
        # loop and the host-loop replay agree on pairings by identity
        plan = self.plan_for(slots, chunk, kind="decode")
        assert window >= 1 and ring_cap >= 2 and poll_budget >= 1
        tb_build = _tev.active_build()
        ob_build = _ost.active_build()
        # serve.step aux carries the active-slot BITMASK, so traced
        # builds need every slot lane to fit an i32
        assert tb_build is None or slots <= 30, (
            f"traced resident loop supports <= 30 slots (got {slots}): "
            "the serve.step active mask is one i32")
        # worst case: every step emits on every slot — up to 1 + spec_k
        # tokens each on a spec-verify step — plus one token-less
        # retirement record per injection-ring retire
        out_cap = window * slots * (1 + spec_k) + ring_cap

        def scatter_out(out_ring, out_count, step, rows_mask, slot_ids,
                        toks, flags, reasons, reqids, spares=None):
            """Append one output record per set slot of rows_mask, in
            slot order; non-writers scatter to the trash row out_cap."""
            offs = jnp.cumsum(rows_mask) - rows_mask
            rows = jnp.where(rows_mask > 0, out_count + offs, out_cap)
            rec = jnp.stack([
                out_count + offs + 1, slot_ids,
                jnp.full_like(slot_ids, step), toks, flags, reasons,
                reqids,
                jnp.zeros_like(slot_ids) if spares is None else spares,
            ], axis=-1)
            return (out_ring.at[rows].set(rec),
                    out_count + jnp.sum(rows_mask))

        def per_rank(params, ring, published, consumed0, step0,
                     slot_state, table, lengths, pool_k, pool_v):
            out_ring0 = jnp.zeros((out_cap + 1, mring.OR_WIDTH),
                                  jnp.int32)
            slot_ids = jnp.arange(slots, dtype=jnp.int32)
            # telemetry carried through the loop — trace-time gated, so
            # the bare build's carry (and program) is exactly the
            # untelemetered one. All entries are data-independent
            # integer streams: they never feed the step math.
            aux0 = {}
            if tb_build is not None:
                aux0["t"] = _tev.new_stream(tb_build, stream=0, rank=0)
            if ob_build is not None:
                zk = jnp.zeros((slots,), jnp.int32)
                aux0.update(polls=jnp.int32(0), idlep=jnp.int32(0),
                            s_steps=zk, s_idle=zk, s_emits=zk)

            def boundary(executed, consumed, ss, tb, ln, out, n_out,
                         aux):
                """Step boundary: drain visible injection records and
                report host-forced retirements out."""
                step = step0 + executed
                consumed2, ss, tb, ln, retired = mring.device_consume(
                    ring, published, consumed, step, ss, tb, ln)
                out, n_out = scatter_out(
                    out, n_out, step, retired, slot_ids,
                    jnp.full((slots,), -1, jnp.int32),
                    jnp.full((slots,), mring.FLAG_RETIRED, jnp.int32),
                    jnp.full((slots,), mring.REASON_HOST, jnp.int32),
                    ss[:, mring.SS_REQID])
                if tb_build is not None:
                    aux = dict(aux, t=_tev.mark(
                        aux["t"], _tev.REGIONS["serve.poll"],
                        payload=consumed2 - consumed,
                        aux=published - consumed2))
                if ob_build is not None:
                    aux = dict(aux, polls=aux["polls"] + 1)
                return consumed2, ss, tb, ln, out, n_out, aux

            def cond(carry):
                (executed, consumed, idle, ss, tb, ln, pk, pv, out,
                 n_out, aux) = carry
                any_active = jnp.any(ss[:, mring.SS_ACTIVE] > 0)
                pending = consumed < published
                return (executed < window) & (
                    any_active | (pending & (idle < poll_budget)))

            def body(carry):
                (executed, consumed, idle, ss, tb, ln, pk, pv, out,
                 n_out, aux) = carry
                consumed2, ss, tb, ln, out, n_out, aux = boundary(
                    executed, consumed, ss, tb, ln, out, n_out, aux)
                any_active = jnp.any(ss[:, mring.SS_ACTIVE] > 0)

                def run_step_spec(ss, tb, ln, pk, pv, out, n_out, aux):
                    """The spec-capable step (ISSUE 14, compiled only
                    when spec_k > 0 — the plain loop's program is
                    untouched): a decoding slot with a fresh KIND_VERIFY
                    record runs a [last, d_1..d_kd] verify row through
                    the per-position step math; the longest accepted
                    prefix (plus the bonus token) is emitted — one
                    output record per token, FLAG_SPEC-tagged, the
                    first carrying kd — and the slot length advances by
                    the EMITTED count (rejected positions hold masked
                    garbage the next step overwrites, exactly the
                    post-eviction stale-page class). Every emitted
                    token is bitwise the sequential emission for its
                    output index (per-column fold_in keys)."""
                    step = step0 + executed
                    active = ss[:, mring.SS_ACTIVE] > 0
                    if tb_build is not None:
                        mask = jnp.sum(jnp.where(
                            active, jnp.int32(1) << slot_ids, 0))
                        aux = dict(aux, t=_tev.mark(
                            aux["t"], _tev.REGIONS["serve.step"],
                            _tev.KIND_BEGIN, payload=step, aux=mask))
                    tokens, n_valid, temps, keys, emits, kdv = \
                        mring.slot_plan_spec(ring, ss, chunk,
                                             max_pages, spec_k)
                    tok_all, _last, pk, pv = _serve_step_math(
                        cfg, mode, axis, slots, chunk, page, t_pool,
                        params, tokens, pk, pv, tb, ln,
                        n_valid, temps, keys, per_pos=True, plan=plan)
                    prefill = ss[:, mring.SS_PHASE] == 0
                    base = jnp.maximum(n_valid - 1 - kdv, 0)
                    span = jnp.arange(spec_k + 1, dtype=jnp.int32)
                    colsm = jnp.clip(base[:, None] + span[None, :],
                                     0, chunk - 1)
                    o = jnp.take_along_axis(tok_all, colsm, axis=1)
                    d = jnp.take_along_axis(
                        tokens, jnp.clip(colsm + 1, 0, chunk - 1),
                        axis=1)
                    accept = ((o == d)
                              & (span[None, :] < kdv[:, None])
                              ).astype(jnp.int32)
                    acc = jnp.sum(jnp.cumprod(accept, axis=1), axis=1)
                    e = jnp.where(emits, acc + 1, 0)
                    eos = ss[:, mring.SS_EOS]
                    hits = (eos[:, None] > 0) & (o == eos[:, None] - 1)
                    hit_in = hits & (span[None, :] < e[:, None])
                    e = jnp.where(jnp.any(hit_in, axis=1),
                                  jnp.argmax(hit_in, axis=1) + 1, e)
                    rem = jnp.maximum(
                        ss[:, mring.SS_MAX_NEW] - ss[:, mring.SS_N_OUT],
                        0)
                    e = jnp.minimum(e, rem)
                    hit_eos = jnp.any(
                        hits & (span[None, :] < e[:, None]), axis=1)
                    n_out_new = ss[:, mring.SS_N_OUT] + e
                    hit_len = (emits & (e > 0) & (~hit_eos)
                               & (n_out_new >= ss[:, mring.SS_MAX_NEW]))
                    finished = hit_eos | hit_len
                    advance = jnp.where(prefill, n_valid, e)
                    ln = ln + advance
                    last_tok = jnp.take_along_axis(
                        o, jnp.maximum(e - 1, 0)[:, None], axis=1)[:, 0]
                    new_pos = ss[:, mring.SS_POS] + jnp.where(
                        prefill, n_valid, 0)
                    completing = (prefill
                                  & (new_pos
                                     >= ss[:, mring.SS_PROMPT_LEN])
                                  & (ss[:, mring.SS_ACTIVE] > 0))
                    ss = (ss
                          .at[:, mring.SS_POS].set(new_pos)
                          .at[:, mring.SS_PHASE].set(jnp.where(
                              completing, 1, ss[:, mring.SS_PHASE]))
                          .at[:, mring.SS_N_OUT].set(n_out_new)
                          .at[:, mring.SS_LAST_TOK].set(jnp.where(
                              e > 0, last_tok,
                              ss[:, mring.SS_LAST_TOK]))
                          .at[:, mring.SS_ACTIVE].set(jnp.where(
                              finished, 0, ss[:, mring.SS_ACTIVE]))
                          # staged verify records are one-shot
                          .at[:, mring.SS_SPEC_K].set(0))
                    spec_row = (kdv > 0).astype(jnp.int32)
                    for j in range(spec_k + 1):
                        m_j = (e > j).astype(jnp.int32)
                        is_last = jnp.equal(e - 1, j)
                        flags = (m_j * mring.FLAG_EMIT
                                 + (is_last & finished).astype(jnp.int32)
                                 * mring.FLAG_RETIRED
                                 + m_j * spec_row * mring.FLAG_SPEC)
                        reasons = jnp.where(
                            is_last & hit_eos, mring.REASON_EOS,
                            jnp.where(is_last & hit_len,
                                      mring.REASON_LENGTH, 0))
                        spare = spec_row * (
                            kdv if j == 0 else jnp.zeros_like(kdv))
                        out, n_out = scatter_out(
                            out, n_out, step, m_j, slot_ids, o[:, j],
                            flags, reasons, ss[:, mring.SS_REQID],
                            spares=spare)
                    if tb_build is not None:
                        aux = dict(aux, t=_tev.mark(
                            aux["t"], _tev.REGIONS["serve.step"],
                            _tev.KIND_END, payload=step, aux=mask))
                    if ob_build is not None:
                        active_i = active.astype(jnp.int32)
                        aux = dict(
                            aux,
                            s_steps=aux["s_steps"] + active_i,
                            s_idle=aux["s_idle"] + 1 - active_i,
                            s_emits=aux["s_emits"] + e)
                    return 1, ss, tb, ln, pk, pv, out, n_out, aux

                def run_step(ss, tb, ln, pk, pv, out, n_out, aux):
                    step = step0 + executed
                    active = ss[:, mring.SS_ACTIVE] > 0
                    if tb_build is not None:
                        mask = jnp.sum(jnp.where(
                            active, jnp.int32(1) << slot_ids, 0))
                        aux = dict(aux, t=_tev.mark(
                            aux["t"], _tev.REGIONS["serve.step"],
                            _tev.KIND_BEGIN, payload=step, aux=mask))
                    tokens, n_valid, temps, keys, emits = \
                        mring.slot_plan(ring, ss, chunk, max_pages)
                    tok, _last, pk, pv = _serve_step_math(
                        cfg, mode, axis, slots, chunk, page, t_pool,
                        params, tokens, pk, pv, tb, ln,
                        n_valid, temps, keys, plan=plan)
                    ln = ln + n_valid
                    # post-step slot-state advance (mirrors the host
                    # scheduler's per-plan bookkeeping field for field)
                    prefill = ss[:, mring.SS_PHASE] == 0
                    new_pos = ss[:, mring.SS_POS] + jnp.where(
                        prefill, n_valid, 0)
                    completing = (prefill
                                  & (new_pos >= ss[:, mring.SS_PROMPT_LEN])
                                  & (ss[:, mring.SS_ACTIVE] > 0))
                    emits_i = emits.astype(jnp.int32)
                    n_out_new = ss[:, mring.SS_N_OUT] + emits_i
                    eos = ss[:, mring.SS_EOS]
                    hit_eos = emits & (eos > 0) & (tok == eos - 1)
                    hit_len = emits & (n_out_new
                                       >= ss[:, mring.SS_MAX_NEW])
                    finished = hit_eos | hit_len
                    ss = (ss
                          .at[:, mring.SS_POS].set(new_pos)
                          .at[:, mring.SS_PHASE].set(jnp.where(
                              completing, 1, ss[:, mring.SS_PHASE]))
                          .at[:, mring.SS_N_OUT].set(n_out_new)
                          .at[:, mring.SS_LAST_TOK].set(jnp.where(
                              emits, tok, ss[:, mring.SS_LAST_TOK]))
                          .at[:, mring.SS_ACTIVE].set(jnp.where(
                              finished, 0, ss[:, mring.SS_ACTIVE])))
                    flags = (emits_i * mring.FLAG_EMIT
                             + finished.astype(jnp.int32)
                             * mring.FLAG_RETIRED)
                    reasons = jnp.where(
                        hit_eos, mring.REASON_EOS,
                        jnp.where(hit_len, mring.REASON_LENGTH, 0))
                    out, n_out = scatter_out(
                        out, n_out, step, emits_i, slot_ids, tok,
                        flags, reasons, ss[:, mring.SS_REQID])
                    if tb_build is not None:
                        aux = dict(aux, t=_tev.mark(
                            aux["t"], _tev.REGIONS["serve.step"],
                            _tev.KIND_END, payload=step, aux=mask))
                    if ob_build is not None:
                        active_i = active.astype(jnp.int32)
                        aux = dict(
                            aux,
                            s_steps=aux["s_steps"] + active_i,
                            s_idle=aux["s_idle"] + 1 - active_i,
                            s_emits=aux["s_emits"] + emits_i)
                    return 1, ss, tb, ln, pk, pv, out, n_out, aux

                def idle_step(ss, tb, ln, pk, pv, out, n_out, aux):
                    if tb_build is not None:
                        aux = dict(aux, t=_tev.mark(
                            aux["t"], _tev.REGIONS["serve.idle"],
                            payload=step0 + executed))
                    return 0, ss, tb, ln, pk, pv, out, n_out, aux

                (stepped, ss, tb, ln, pk, pv, out, n_out,
                 aux) = jax.lax.cond(
                    any_active,
                    run_step_spec if spec_k else run_step, idle_step,
                    ss, tb, ln, pk, pv, out, n_out, aux)
                if ob_build is not None:
                    aux = dict(aux, idlep=aux["idlep"] + 1 - stepped)
                progressed = (stepped > 0) | (consumed2 > consumed)
                idle = jnp.where(progressed, 0, idle + 1)
                return (executed + stepped, consumed2, idle, ss, tb,
                        ln, pk, pv, out, n_out, aux)

            carry = (jnp.int32(0), consumed0, jnp.int32(0), slot_state,
                     table, lengths, pool_k, pool_v, out_ring0,
                     jnp.int32(0), aux0)
            (executed, consumed, _idle, ss, tb, ln, pk, pv, out,
             n_out, aux) = jax.lax.while_loop(cond, body, carry)
            # a final boundary drain: records whose at_step gate opened
            # on the LAST executed step (e.g. a retire targeted at the
            # window's end) must not wait a whole extra window
            consumed, ss, tb, ln, out, n_out, aux = boundary(
                executed, consumed, ss, tb, ln, out, n_out, aux)
            starved = mring.head_abandoned(
                ring, published, consumed).astype(jnp.int32)
            extras = ()
            if tb_build is not None:
                extras += (aux["t"],)
            if ob_build is not None:
                # the resident-window stat rows (obs/stats.py WMAGIC
                # layout): loop lane first, then one lane per slot
                i32 = jnp.int32
                loop_row = jnp.stack([
                    i32(_ost.WMAGIC), i32(-1), executed, aux["polls"],
                    aux["idlep"], consumed - consumed0, starved,
                    i32(0)])
                slot_rows = jnp.stack([
                    jnp.full((slots,), _ost.WMAGIC, jnp.int32),
                    slot_ids, aux["s_steps"], aux["s_idle"],
                    aux["s_emits"], ss[:, mring.SS_REQID],
                    jnp.zeros((slots,), jnp.int32),
                    jnp.zeros((slots,), jnp.int32)], axis=-1)
                wrow = jnp.concatenate(
                    [loop_row[None], slot_rows], 0)[:, None, :]
                extras += (wrow,)
            return (consumed, executed, ss, tb, ln, pk, pv,
                    out[:out_cap], n_out, starved) + extras

        n_extras = (tb_build is not None) + (ob_build is not None)
        pool_spec = P(None, self.axis)
        return jax.jit(
            jax.shard_map(
                per_rank, mesh=self.mesh,
                in_specs=((self._wrap_specs[0],) + (P(),) * 7
                          + (pool_spec, pool_spec)),
                out_specs=((P(),) * 5 + (pool_spec, pool_spec)
                           + (P(),) * (3 + n_extras)),
                check_vma=False,
            ),
            donate_argnums=(8, 9) if self._donate_cache else (),
        )

    # -- API ----------------------------------------------------------------

    def new_cache(self, batch: int) -> KVCache:
        cache = KVCache.create(
            self.cfg.num_layers, batch, self.max_len,
            self._hkv_loc * int(self.mesh.shape[self.axis]),
            self.cfg.head_dim, jnp.dtype(self.cfg.dtype),
        )
        specs = cache_specs(self.axis, self.batch_axis)
        return jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)),
            cache, specs,
        )

    def prefill(self, input_ids, cache: Optional[KVCache] = None):
        """input_ids: (B, S) -> (last-token logits (B, V), cache)."""
        input_ids = jnp.asarray(input_ids, jnp.int32)
        if cache is None:
            cache = self.new_cache(input_ids.shape[0])
        return self._prefill(self.params, input_ids, cache)

    def decode_step(self, tokens, cache: KVCache):
        """tokens: (B,) -> (logits (B, V), cache)."""
        return self._decode(
            self.params, jnp.asarray(tokens, jnp.int32)[:, None], cache
        )

    def serve(
        self,
        input_ids,
        gen_len: int,
        temperature: float = 0.0,
        seed: int = 0,
        slots: Optional[int] = None,
        chunk: Optional[int] = None,
        page: Optional[int] = None,
    ):
        """Prefill + gen_len decode steps (ref Engine.serve,
        engine.py:113-189). Returns generated ids (B, gen_len). The
        decode phase is ONE `generate` dispatch (see module doc).

        With `slots` set, serve instead runs the STEPWISE path: the
        request batch is admitted into a fresh continuous-batching
        scheduler (triton_dist_tpu.serve) over the (slots, chunk)
        serve-step geometry — the sequential baseline the serve plane's
        in-flight batching is bit-identical to (docs/serving.md).
        Sampling then uses per-request key streams (seed + row index),
        not the legacy batch-shared key."""
        if slots is not None:
            from triton_dist_tpu.serve import Scheduler

            ids = np.asarray(input_ids, np.int32)
            sch = Scheduler(self, slots=slots, chunk=chunk, page=page)
            reqs = [
                sch.submit(list(map(int, row)), max_new_tokens=gen_len,
                           temperature=temperature, seed=seed + i)
                for i, row in enumerate(ids)
            ]
            sch.run()
            return jnp.asarray([r.out_tokens for r in reqs], jnp.int32)
        key = jax.random.PRNGKey(seed)
        logits, cache = self.prefill(input_ids)
        key, sub = jax.random.split(key)
        tok = sample_token(logits, sub, temperature)
        if gen_len == 1:
            return tok[:, None]
        key, sub = jax.random.split(key)
        rest, _ = self.generate(
            tok, cache, gen_len - 1, temperature,
            key=sub if temperature > 0.0 else None,
        )
        return jnp.concatenate([tok[:, None], rest], axis=1)
