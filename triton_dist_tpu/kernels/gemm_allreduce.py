"""GEMM+AllReduce — the low-latency decode-path matmul.

TPU-native analog of the reference's fused GEMM+AR
(ref: python/triton_dist/kernels/nvidia/gemm_allreduce.py:48-111
`GemmARContext`/`gemm_allreduce_op`/`low_latency_gemm_allreduce_op`), used by
the `gemm_ar` forward mode of TP layers (ref: layers/nvidia/tp_attn.py:297,
e2e 1.26-1.35x wins in docs/getting-started/e2e/e2e_dense.md:34-38). The
reference keeps a double-buffered symmetric phase counter so consecutive
calls don't need a barrier; on TPU each fused call is one Pallas kernel
whose semaphores are kernel-local, so re-entrancy is structural.

Two regimes, as in the reference:
  - low-latency (small M, decode): partial = a @ b on the MXU, then the
    one-shot push AllReduce (n-1 direct puts) — minimum hop count.
  - bandwidth (large M, prefill): gemm_rs ring (compute-overlapped) + ring
    AG, the two-shot analog.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from triton_dist_tpu.kernels.allgather import ring_all_gather
from triton_dist_tpu.kernels.allreduce import one_shot_all_reduce
from triton_dist_tpu.kernels.gemm_reduce_scatter import gemm_rs, GemmRsConfig
from triton_dist_tpu.runtime.init import TP_AXIS

_LOW_LATENCY_MAX_ROWS = 256


def gemm_ar(
    a: jax.Array,
    b: jax.Array,
    axis: str = TP_AXIS,
    config: Optional[GemmRsConfig] = None,
) -> jax.Array:
    """AllReduce(a @ b); per-device function inside shard_map.

    a: (M, K_loc); b: (K_loc, N). Returns the replicated (M, N) sum over
    the axis (ref op: gemm_allreduce.py:94-111).
    """
    n = jax.lax.axis_size(axis)
    m = a.shape[0]
    if n == 1:
        return jnp.dot(a, b, preferred_element_type=jnp.float32).astype(a.dtype)
    if m <= _LOW_LATENCY_MAX_ROWS or m % n:
        partial = jnp.dot(a, b, preferred_element_type=jnp.float32).astype(
            a.dtype
        )
        return one_shot_all_reduce(partial, axis)
    from triton_dist_tpu.trace.events import primary

    # primary(): build-safe under trace.building() (buffers dropped; see
    # tp_mlp.dist_fwd)
    scattered = primary(gemm_rs(a, b, axis, config=config))
    from triton_dist_tpu.faults import guard as _guard
    from triton_dist_tpu.obs import stats as _obs

    return _guard.primary(_obs.primary(ring_all_gather(scattered, axis)))


def gemm_ar_ref(a: jax.Array, b: jax.Array, axis: str = TP_AXIS) -> jax.Array:
    """Unfused XLA reference path (torch AR analog)."""
    partial = jnp.dot(a, b, preferred_element_type=jnp.float32).astype(a.dtype)
    return jax.lax.psum(partial, axis)
