"""Sequence-parallel attention — ring attention over KV shards.

TPU-native re-design of the reference's SP-AG attention
(ref: python/triton_dist/kernels/nvidia/sp_ag_attention_intra_node.py:105-427
and sp_ag_attention_inter_node.py:115-499): there, the KV shards are
allgathered segment-by-segment on the copy engine while a flash-attention
consumer waits on per-segment barriers. On TPU the same
compute/communication overlap is the *ring attention* formulation: KV
blocks rotate around the ring with `ppermute` while each rank folds the
arriving block into its online-softmax state — XLA overlaps the collective
permute with the attention einsums (async collectives over ICI), which is
exactly the copy-engine/consumer split, without a barrier in sight. The
rank's own block is folded at step 0 (the reference's rank-offset swizzle:
zero-wait start).

Memory never exceeds one KV block per step — the blockwise/ring-attention
long-context property: sequence length scales linearly with the number of
chips.

Since ISSUE 7 this formulation is the FALLBACK arm of the SP prefill
switch: `kernels/flash_prefill.sp_flash_prefill` applies the repo's
thesis here too — the same fold as one Pallas kernel whose ring ingest
waits on per-segment delivery semaphores (the reference's consumer-wait
mechanism, no XLA scheduling trust required). `sp_prefill_attention`
selects between them via `perf_model.choose_sp_prefill_impl`;
ring_attention remains the always-available XLA path (interpret
no-headroom meshes, unsupported native shapes).

Layout: rank r holds Q rows and KV rows [r*S_loc, (r+1)*S_loc) of the
global sequence (contiguous sharding).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from triton_dist_tpu.runtime.init import SP_AXIS

NEG_INF = -1e30


def _block_update(q, k, v, q_pos, k_pos, acc, m, l, scale, causal,
                  kv_len=None):
    """Fold one KV block into the online-softmax state (f32).

    q: (B, Sq, Hkv, G, D); k/v: (B, Skv, Hkv, D);
    acc: (B, Hkv, G, Sq, D); m, l: (B, Hkv, G, Sq, 1).
    kv_len: optional (B,) per-sequence valid KV length (varlen batches:
    rows at k_pos >= kv_len[b] are masked for that sequence only)."""
    s = jnp.einsum("bskgd,btkd->bkgst", q, k) * scale
    if causal:
        mask = k_pos[None, None, None, None, :] <= q_pos[:, None, None, :, None]
        s = jnp.where(mask, s, NEG_INF)
    if kv_len is not None:
        valid = k_pos[None, :] < jnp.reshape(kv_len, (-1, 1))  # (B, Skv)
        s = jnp.where(valid[:, None, None, None, :], s, NEG_INF)
    m_blk = jnp.max(s, axis=-1, keepdims=True)  # (B,Hkv,G,Sq,1)
    m_new = jnp.maximum(m, m_blk)
    # guard fully-masked blocks: exp(NEG_INF - NEG_INF) would be 1
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new)
    p = jnp.where(m_new <= NEG_INF / 2, 0.0, p)
    alpha = jnp.where(m_new <= NEG_INF / 2, 1.0, alpha)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum("bkgst,btkd->bkgsd", p, v)
    acc_new = acc * alpha + pv
    return acc_new, m_new, l_new


def ring_attention(
    q: jax.Array,  # (B, Sq_loc, Hq, D)
    k: jax.Array,  # (B, Skv_loc, Hkv, D)
    v: jax.Array,  # (B, Skv_loc, Hkv, D)
    axis: str = SP_AXIS,
    causal: bool = True,
    scale: Optional[float] = None,
    kv_len: Optional[jax.Array] = None,
) -> jax.Array:
    """Sequence-parallel GQA attention; per-device inside shard_map.

    Returns (B, Sq_loc, Hq, D) — each rank's query block attended over the
    FULL (sharded) sequence (ref consumer contract:
    sp_ag_attention_intra_node.py:256-427).

    kv_len: optional (B,) per-sequence GLOBAL valid length — the varlen /
    ragged-batch form (the reference's cu_seqlens path,
    sp_ag_attention_intra_node.py:256-427): sequence b attends only KV
    positions < kv_len[b]. Query rows at positions >= kv_len[b] are
    padding; they still attend the valid prefix (the causal mask keeps
    the past open) — callers ignore those rows."""
    n = jax.lax.axis_size(axis)
    me = jax.lax.axis_index(axis)
    b, sq, hq, d = q.shape
    _, skv, hkv, _ = k.shape
    g = hq // hkv
    scale = scale if scale is not None else d ** -0.5

    qf = q.astype(jnp.float32).reshape(b, sq, hkv, g, d)
    q_pos = me * sq + jnp.arange(sq)  # (Sq,); broadcast over batch
    q_pos = jnp.tile(q_pos[None], (b, 1))

    acc0 = jnp.zeros((b, hkv, g, sq, d), jnp.float32)
    m0 = jnp.full((b, hkv, g, sq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hkv, g, sq, 1), jnp.float32)

    if n == 1:
        acc, m, l = _block_update(
            qf, k.astype(jnp.float32), v.astype(jnp.float32),
            q_pos, jnp.arange(skv), acc0, m0, l0, scale, causal,
            kv_len=kv_len,
        )
    else:
        perm = [(i, (i + 1) % n) for i in range(n)]
        acc, m, l = acc0, m0, l0
        k_cur, v_cur = k, v
        # Unrolled (n is static inside shard_map): the last block is folded
        # WITHOUT a trailing rotate — n-1 hops move n blocks.
        for s in range(n):
            chunk = jnp.mod(me - s, n)
            k_pos = chunk * skv + jnp.arange(skv)
            acc, m, l = _block_update(
                qf, k_cur.astype(jnp.float32), v_cur.astype(jnp.float32),
                q_pos, k_pos, acc, m, l, scale, causal, kv_len=kv_len,
            )
            if s < n - 1:
                # rotate the KV block to the right neighbor (the
                # per-segment AG push of the reference, expressed as a
                # collective permute XLA runs async against the einsums)
                k_cur = jax.lax.ppermute(k_cur, axis, perm)
                v_cur = jax.lax.ppermute(v_cur, axis, perm)

    out = acc / jnp.maximum(l, 1e-30)
    out = jnp.einsum("bkgsd->bskgd", out).reshape(b, sq, hq, d)
    return out.astype(q.dtype)


def ring_attention_ref(q, k, v, axis: str = SP_AXIS, causal: bool = True,
                       scale: Optional[float] = None,
                       kv_len: Optional[jax.Array] = None):
    """Unfused oracle: gather the full KV and run plain GQA attention."""
    from triton_dist_tpu.layers.attention import gqa_attention

    n = jax.lax.axis_size(axis)
    me = jax.lax.axis_index(axis)
    sq = q.shape[1]
    k_full = jax.lax.all_gather(k, axis, axis=1, tiled=True)
    v_full = jax.lax.all_gather(v, axis, axis=1, tiled=True)
    q_pos = me * sq + jnp.tile(jnp.arange(sq)[None], (q.shape[0], 1))
    # prefill_impl pinned: an oracle must not auto-route into the very
    # Pallas kernel it is the oracle FOR (native-TPU runs)
    return gqa_attention(
        q, k_full, v_full, causal=causal, q_positions=q_pos, scale=scale,
        kv_len=kv_len, prefill_impl="xla",
    )
