"""ReduceScatter kernels over ICI remote DMA.

TPU-native re-design of the reference's standalone RS library
(ref: python/triton_dist/kernels/nvidia/reduce_scatter.py:47-866): copy-engine
ring, SM ring kernel, RMA ring for non-P2P, per-node two-stage, ring-reduce
TMA variants. On TPU one ring kernel (VMEM-accumulating, double-buffered)
plus the XLA psum_scatter fallback covers the same space; stage-wise
composition over two mesh axes is the two-stage inter-node analog
(ref: reduce_scatter.py:617-672).

Two orthogonal precision knobs (docs/performance.md "Quantized wire"):
`accum_dtype` is the NATIVE wire's ring accumulation dtype (the
accumulator IS the RDMA payload there, so f32 accumulation implies a
2x-byte wire as a side effect); `wire_format` owns the PAYLOAD ENCODING
— fp8/int8 block-scaled wire images at ~itemsize x fewer hop bytes, with
consume-edge accumulation fixed at f32 by the codec contract
(`triton_dist_tpu.wire`). Quantization never changes the semaphore
protocol: `_ring_rs_wire_kernel` runs the exact credit/parity ring of
`_ring_rs_kernel`, proven format-invariant by the verifier.
"""

from __future__ import annotations

import enum
import functools
from typing import Sequence, Union

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.faults import guard as _guard
from triton_dist_tpu.lang import shmem
from triton_dist_tpu.lang.core import (
    tpu_call,
    compiler_params,
    next_collective_id,
    compute_vmem_bytes,
    interpret_no_headroom,
)
from triton_dist_tpu.obs import stats as _obs
from triton_dist_tpu.runtime.init import TP_AXIS
from triton_dist_tpu.wire import codec as wcodec


class ReduceScatterMethod(enum.Enum):
    Auto = "auto"
    Ring1D = "ring_1d"
    XLA = "xla"


def _rs_unpack(casting, gbuild, obuild, refs):
    """Shared ref unpacking of the two ring kernels: outputs (o_ref +
    guard buffer + stat row) precede scratch; cast_buf and the
    guard/obs cursors are the trailing scratch entries."""
    refs = list(refs)
    x_ref, o_ref = refs[0], refs[1]
    del refs[:2]
    gbuf = refs.pop(0) if gbuild is not None else None
    obuf = refs.pop(0) if obuild is not None else None
    ocur = refs.pop() if obuild is not None else None
    gcur = refs.pop() if gbuild is not None else None
    cast_buf = refs.pop() if casting else None
    acc, stage = refs[0], refs[1]
    sems = refs[2:]
    return (x_ref, o_ref, gbuf, gcur, obuf, ocur, cast_buf, acc, stage,
            sems)


# A ring step holds 3 chunk-sized VMEM buffers (2 accumulator slots + local
# staging); above this chunk size fall back to psum_scatter.
_VMEM_CHUNK_LIMIT = 4 * (1 << 20)


def _ring_rs_kernel(axis: str, n: int, acc_dtype, casting, gbuild,
                    obuild, *refs):
    """Ring reduce-scatter.

    Chunk schedule (mirrors the SM-ring of ref reduce_scatter.py:327-413):
    step s: send accumulated chunk (me-s-1) mod n to right neighbor, receive
    chunk (me-s-2) mod n, add own contribution. After n-1 steps rank me holds
    the full sum of chunk me.

    acc: VMEM (2, m, k) double buffer — step s sends slot s%2 and receives
    into slot (s+1)%2. Because the two slots are REUSED across steps, flow
    control is required: without it a fast upstream neighbor (the dependency
    chain around the ring only reaches back to us after n hops) could land
    step s+2 into the slot step-s data still occupies. `credit_sem` is the
    backpressure: we grant our LEFT neighbor one credit whenever one of our
    slots becomes receivable (initially slot 1; later each time a send
    completes, freeing that slot for the incoming step that targets it), and
    we take one credit before each send. Credits cap outstanding incoming
    puts at 2, which always target opposite-parity slots, so the
    parity-indexed recv semaphores make every wait exact.

    Dtype contract: accumulation happens in acc_dtype. The DEFAULT is
    the input dtype — bf16 inputs take n-1 bf16 additions around the
    ring, keeping the ring's bandwidth optimality (the accumulator IS
    the RDMA payload, so acc_dtype=f32 buys psum-grade ACCUMULATION at
    the side cost of a doubled-byte hop — the cost is a measured column
    in benchmark/bench_collectives.py, not an assertion). What the
    PAYLOAD ENCODING on the wire is belongs to the separate
    `wire_format` knob (_ring_rs_wire_kernel: block-scaled fp8/int8
    images, f32 consume-edge accumulation) — the two knobs were
    conflated before the wire plane; they are orthogonal. Loads cast
    through cast_buf (DMA cannot cast); the output returns in x.dtype.
    """
    (x_ref, o_ref, gbuf, gcur, obuf, ocur, cast_buf, acc, stage,
     (ld_sem, st_sem, send_sem, recv_sem, credit_sem)) = _rs_unpack(
        casting, gbuild, obuild, refs)
    me = jax.lax.axis_index(axis)
    m = o_ref.shape[0]
    left = jnp.mod(me - 1, n)
    right = jnp.mod(me + 1, n)
    octx = _obs.make_ctx(obuild, obuf, ocur)
    _obs.init_ctx(octx, rank=me)
    gctx = _guard.make_ctx(gbuild, gbuf, gcur, octx=octx)
    _guard.init_ctx(gctx, rank=me)
    with _guard.attached(gctx), _obs.attached(octx):
        shmem.neighbor_barrier(axis, me, n)
        shmem.fault_delay(axis, "reduce_scatter")

        # Step-0 incoming targets our slot 1, free from the start: grant
        # credit. n == 1 (reachable via force_kernel) runs no ring step
        # and must not leave a dangling credit at kernel exit — a leaked
        # count in the physical semaphore pool could spuriously satisfy
        # a later kernel's credit wait (the sem-leak class the verifier
        # flags).
        if n > 1:
            shmem.signal(credit_sem, 1, shmem.SIGNAL_ADD, left, axis,
                         label="credit")

        def load_chunk(chunk, dst):
            """x[chunk] -> dst(acc_dtype), via cast_buf when dtypes
            differ. Returns a finish() that must run before dst is
            read."""
            tgt = cast_buf if casting else dst
            cp = pltpu.make_async_copy(x_ref.at[pl.ds(chunk * m, m)],
                                       tgt, ld_sem)
            cp.start()

            def finish():
                cp.wait()
                if casting:
                    dst[...] = cast_buf[...].astype(acc_dtype)

            return finish

        # Load our contribution to the first travelling chunk,
        # (me-1) mod n.
        load_chunk(jnp.mod(me - 1, n), acc.at[0])()

        for s in range(n - 1):
            cur, nxt = s % 2, (s + 1) % 2
            shmem.guard_progress(s)
            # right's slot `nxt` is free
            shmem.signal_wait_until(credit_sem, shmem.CMP_GE, 1,
                                    site="credit", slot=s)
            h = shmem.putmem_nbi(acc.at[nxt], acc.at[cur], send_sem,
                                 recv_sem.at[nxt], right, axis)
            # Prefetch our contribution to the incoming chunk while it
            # travels.
            chunk = jnp.mod(me - s - 2, n)
            finish = load_chunk(chunk, stage)
            h.wait_send()
            if s + 1 <= n - 2:
                # Slot `cur` is sent out: receivable for incoming step
                # s+1 (which targets (s+2)%2 == cur). Grant the left
                # neighbor.
                shmem.signal(credit_sem, 1, shmem.SIGNAL_ADD, left,
                             axis, label="credit")
            h.wait_recv(slot=s)
            finish()
            acc[nxt] = acc[nxt] + stage[...]

        final = (n - 1) % 2
        if casting:
            cast_buf[...] = acc[final].astype(o_ref.dtype)
            st = pltpu.make_async_copy(cast_buf, o_ref, st_sem)
        else:
            st = pltpu.make_async_copy(acc.at[final], o_ref, st_sem)
        st.start()
        st.wait()


def _ring_rs_wire_kernel(axis: str, n: int, fmt, casting, gbuild,
                         obuild, *refs):
    """Quantized-wire ring RS: the EXACT credit/parity protocol of
    `_ring_rs_kernel` — same puts, same per-parity recv semaphores,
    same credit flow toward the left neighbor (`verify` proves the
    synchronization skeleton format-invariant) — with the travelling
    acc slots holding the block-scaled WIRE IMAGE (wire.encode_rows)
    instead of raw rows. Each hop quantizes at the send edge and
    accumulates in f32 at the consume edge (decode + add; EQuARX's
    per-hop requantization): `acc` is int8 (2, m, wire_cols), `stage`
    the f32 contribution/accumulation buffer, and the LAST arrival is
    stored without a re-encode, so the output is exactly the f32 fold
    (wire.simulate_ring_rs replays this order bit-for-bit)."""
    (x_ref, o_ref, gbuf, gcur, obuf, ocur, cast_buf, acc, stage,
     (ld_sem, st_sem, send_sem, recv_sem, credit_sem)) = _rs_unpack(
        casting, gbuild, obuild, refs)
    me = jax.lax.axis_index(axis)
    m, k = stage.shape
    left = jnp.mod(me - 1, n)
    right = jnp.mod(me + 1, n)
    octx = _obs.make_ctx(obuild, obuf, ocur)
    _obs.init_ctx(octx, rank=me, fmt=_obs.fmt_code(fmt))
    gctx = _guard.make_ctx(gbuild, gbuf, gcur, octx=octx)
    _guard.init_ctx(gctx, rank=me)
    with _guard.attached(gctx), _obs.attached(octx):
        shmem.neighbor_barrier(axis, me, n)
        shmem.fault_delay(axis, "reduce_scatter")

        # see _ring_rs_kernel: no dangling credit at n == 1
        # (force_kernel)
        if n > 1:
            shmem.signal(credit_sem, 1, shmem.SIGNAL_ADD, left, axis,
                         label="credit")

        def load_chunk(chunk):
            """x[chunk] -> stage (f32), via cast_buf (DMA cannot cast).
            Returns a finish() that must run before stage is read."""
            tgt = cast_buf if casting else stage
            cp = pltpu.make_async_copy(x_ref.at[pl.ds(chunk * m, m)],
                                       tgt, ld_sem)
            cp.start()

            def finish():
                cp.wait()
                if casting:
                    stage[...] = cast_buf[...].astype(jnp.float32)

            return finish

        # Our contribution to the first travelling chunk: quantize at
        # the send edge into the wire slot.
        load_chunk(jnp.mod(me - 1, n))()
        acc[0] = wcodec.encode_rows(stage[...], fmt)

        for s in range(n - 1):
            cur, nxt = s % 2, (s + 1) % 2
            shmem.guard_progress(s)
            shmem.signal_wait_until(credit_sem, shmem.CMP_GE, 1,
                                    site="credit", slot=s)
            h = shmem.putmem_nbi(acc.at[nxt], acc.at[cur], send_sem,
                                 recv_sem.at[nxt], right, axis)
            finish = load_chunk(jnp.mod(me - s - 2, n))
            h.wait_send()
            if s + 1 <= n - 2:
                shmem.signal(credit_sem, 1, shmem.SIGNAL_ADD, left,
                             axis, label="credit")
            h.wait_recv(slot=s)
            finish()
            # consume edge: verify integrity (checksum formats under a
            # guard build: a corrupted hop becomes a guard row, not a
            # silently wrong sum), dequantize, accumulate in f32
            if gctx is not None and fmt.checksum:
                _guard.integrity_trip(
                    jnp.all(wcodec.verify_rows(acc[nxt], k, fmt)),
                    slot=s, ctx=gctx)
            val = wcodec.decode_rows(acc[nxt], k, fmt, jnp.float32) \
                + stage[...]
            if s == n - 2:
                stage[...] = val  # final arrival: no re-encode
            else:
                acc[nxt] = wcodec.encode_rows(val, fmt)

        if casting:
            cast_buf[...] = stage[...].astype(o_ref.dtype)
            st = pltpu.make_async_copy(cast_buf, o_ref, st_sem)
        else:
            st = pltpu.make_async_copy(stage, o_ref, st_sem)
        st.start()
        st.wait()


def _wire_rs_xla(x: jax.Array, axis: str, n: int, fmt) -> jax.Array:
    """XLA-collective replay of the quantized ring RS — the SAME fold
    order as `_ring_rs_wire_kernel` (quantize at each send edge,
    decode+add in f32, final arrival un-re-encoded), with ppermute
    carrying the wire image. Used as the no-headroom fallback and by
    the numerics tests as the mesh-level oracle."""
    m = x.shape[0] // n
    xf = x.reshape(x.shape[0], -1).astype(jnp.float32)
    k = xf.shape[1]
    me = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def chunk(c):
        return jax.lax.dynamic_slice_in_dim(xf, c * m, m, axis=0)

    val = chunk(jnp.mod(me - 1, n))
    for s in range(n - 1):
        w = wcodec.encode_rows(val, fmt)
        w = jax.lax.ppermute(w, axis, perm)
        val = wcodec.decode_rows(w, k, fmt, jnp.float32) \
            + chunk(jnp.mod(me - s - 2, n))
    return val.astype(x.dtype).reshape((m,) + x.shape[1:])


def ring_reduce_scatter(x: jax.Array, axis: str = TP_AXIS,
                        accum_dtype=None, wire_format=None,
                        force_kernel: bool = False) -> jax.Array:
    """Ring RS of per-device (n*m, ...) -> (m, ...). Call inside shard_map.

    Two orthogonal knobs (they were conflated before the wire plane —
    see docs/performance.md "Quantized wire"):

    accum_dtype — the ring ACCUMULATION dtype of the native wire
    (default x.dtype; f32 is the psum-parity accumulation at 2x hop
    bytes — see _ring_rs_kernel). It does not exist on quantized wires,
    whose consume-edge accumulation is f32 by construction.

    wire_format — what the PAYLOAD BYTES are on the wire
    (wire.WireFormat / "fp8" / "int8"; None = native). Quantized
    formats ship the block-scaled wire image per hop
    (_ring_rs_wire_kernel) at ~itemsize x fewer ICI bytes; the
    semaphore protocol is unchanged (format-invariant, verify-proved).
    Pass accum_dtype=None (or f32) with a quantized wire — any other
    accumulation dtype would silently contradict the codec's f32
    contract, so it raises.

    force_kernel skips the n == 1 early return so the kernel's
    world=1 edge cost is measurable (bench.py wire arms)."""
    n = jax.lax.axis_size(axis)
    if x.shape[0] % n != 0:
        raise ValueError(f"leading dim {x.shape[0]} not divisible by {n}")
    fmt = wcodec.resolve(wire_format)
    if not wcodec.is_native(fmt):
        if accum_dtype is not None and \
                jnp.dtype(accum_dtype) != jnp.float32:
            raise ValueError(
                "quantized wire accumulates in f32 at the consume edge "
                "by construction; accum_dtype is the NATIVE wire's ring "
                f"accumulation knob — got accum_dtype={accum_dtype!r} "
                f"with wire_format={fmt}")
        if x.ndim < 2:
            raise ValueError(
                f"quantized wire needs >=2D per-device arrays, got "
                f"{x.shape}")
        return _ring_rs_quantized(x, axis, n, fmt, force_kernel)
    acc_dtype = jnp.dtype(accum_dtype or x.dtype)
    gbuild = _guard.active_build()
    obuild = _obs.active_build()

    def fallback(res):
        return _obs.with_stats(obuild, _guard.with_guard(gbuild, res))

    if n == 1 and not force_kernel:
        return fallback(x)
    if interpret_no_headroom():
        if acc_dtype != x.dtype:
            return fallback(jax.lax.psum_scatter(
                x.astype(acc_dtype), axis, tiled=True).astype(x.dtype))
        return fallback(jax.lax.psum_scatter(x, axis, tiled=True))
    m = x.shape[0] // n
    chunk_shape = (m,) + x.shape[1:]
    casting = acc_dtype != x.dtype
    kernel = functools.partial(_ring_rs_kernel, axis, n, acc_dtype,
                               casting, gbuild, obuild)
    out_shape = jax.ShapeDtypeStruct(chunk_shape, x.dtype)
    out_specs = pl.BlockSpec(memory_space=pl.ANY)
    if gbuild is not None:
        out_shape = (out_shape, _guard.out_shape(gbuild))
        out_specs = (out_specs, _guard.out_spec())
    if obuild is not None:
        out_shape = (out_shape if isinstance(out_shape, tuple)
                     else (out_shape,)) + (_obs.out_shape(obuild),)
        out_specs = (out_specs if isinstance(out_specs, tuple)
                     else (out_specs,)) + (_obs.out_spec(),)
    scratch = [
        pltpu.VMEM((2,) + chunk_shape, acc_dtype),
        pltpu.VMEM(chunk_shape, acc_dtype),
        pltpu.SemaphoreType.DMA,
        pltpu.SemaphoreType.DMA,
        pltpu.SemaphoreType.DMA,
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.REGULAR,
    ]
    if casting:
        scratch.append(pltpu.VMEM(chunk_shape, x.dtype))
    if gbuild is not None:
        scratch.append(_guard.cursor_scratch())
    if obuild is not None:
        scratch.append(_obs.cursor_scratch())
    return tpu_call(
        kernel,
        out_shape=out_shape,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=out_specs,
        scratch_shapes=scratch,
        compiler_params=compiler_params(
            has_side_effects=True,
            collective_id=next_collective_id(f"ring_rs_{axis}"),
            vmem_limit_bytes=min(
                128 << 20,
                5 * compute_vmem_bytes((chunk_shape, acc_dtype)),
            ),
        ),
    )(x)


def _ring_rs_quantized(x: jax.Array, axis: str, n: int, fmt,
                       force_kernel: bool) -> jax.Array:
    """Dispatch the quantized-wire ring: the Pallas kernel when the
    interpreter has headroom (or on hardware), else the ppermute replay
    of the identical fold. n == 1 is a pass-through (no hop ever
    travels; the kernel still pays the send-edge encode when forced,
    which is what the bench's world=1 wire arm measures)."""
    gbuild = _guard.active_build()
    obuild = _obs.active_build()

    def fallback(res):
        row = _obs.new_stream(obuild, fmt=_obs.fmt_code(fmt)) \
            if obuild is not None else None
        return _obs.with_stats(obuild, _guard.with_guard(gbuild, res),
                               row)

    if n == 1 and not force_kernel:
        return fallback(x)
    if interpret_no_headroom():
        if n == 1:
            return fallback(x)
        return fallback(_wire_rs_xla(x, axis, n, fmt))
    m = x.shape[0] // n
    flat = x.reshape(x.shape[0], -1)
    k = flat.shape[1]
    kw = wcodec.wire_cols(k, fmt)
    casting = x.dtype != jnp.float32
    kernel = functools.partial(_ring_rs_wire_kernel, axis, n, fmt,
                               casting, gbuild, obuild)
    out_shape = jax.ShapeDtypeStruct((m, k), x.dtype)
    out_specs = pl.BlockSpec(memory_space=pl.ANY)
    if gbuild is not None:
        out_shape = (out_shape, _guard.out_shape(gbuild))
        out_specs = (out_specs, _guard.out_spec())
    if obuild is not None:
        out_shape = (out_shape if isinstance(out_shape, tuple)
                     else (out_shape,)) + (_obs.out_shape(obuild),)
        out_specs = (out_specs if isinstance(out_specs, tuple)
                     else (out_specs,)) + (_obs.out_spec(),)
    scratch = [
        pltpu.VMEM((2, m, kw), jnp.int8),     # travelling wire slots
        pltpu.VMEM((m, k), jnp.float32),      # f32 stage/accumulator
        pltpu.SemaphoreType.DMA,
        pltpu.SemaphoreType.DMA,
        pltpu.SemaphoreType.DMA,
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.REGULAR,
    ]
    if casting:
        scratch.append(pltpu.VMEM((m, k), x.dtype))
    if gbuild is not None:
        scratch.append(_guard.cursor_scratch())
    if obuild is not None:
        scratch.append(_obs.cursor_scratch())
    res = tpu_call(
        kernel,
        out_shape=out_shape,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=out_specs,
        scratch_shapes=scratch,
        compiler_params=compiler_params(
            has_side_effects=True,
            collective_id=next_collective_id(
                f"ring_rs_wire_{fmt.kind}_{axis}"),
            vmem_limit_bytes=min(
                128 << 20,
                3 * compute_vmem_bytes(((m, k), jnp.float32),
                                       ((2, m, kw), jnp.int8))),
        ),
    )(flat)
    res = res if isinstance(res, tuple) else (res,)
    out = res[0].reshape((m,) + x.shape[1:])
    gbuf = res[1] if gbuild is not None else None
    obuf = res[-1] if obuild is not None else None
    return _obs.with_stats(
        obuild, _guard.with_guard(gbuild, out, gbuf), obuf)


def reduce_scatter(
    x: jax.Array,
    axis: Union[str, Sequence[str]] = TP_AXIS,
    method: ReduceScatterMethod = ReduceScatterMethod.Auto,
    accum_dtype=None,
    wire_format=None,
) -> jax.Array:
    """Reduce-scatter per-device (n*m, ...) -> (m, ...); per-device function.

    Axis tuples run stage-wise outermost-first (the two-stage per-node path
    of ref reduce_scatter.py:617-672): RS over the slow axis first so the
    fast-axis stage reduces already-combined super-chunks.
    accum_dtype: the NATIVE wire's ring accumulation dtype;
    wire_format: the payload encoding on the wire (fp8/int8 block-scaled
    wire image, f32 consume-edge accumulation) — two separate knobs, see
    ring_reduce_scatter.
    """
    if not isinstance(axis, str):
        out = x
        for ax in tuple(axis):
            out = reduce_scatter(out, ax, method=method,
                                 accum_dtype=accum_dtype,
                                 wire_format=wire_format)
        return out

    if not wcodec.is_native(wire_format):
        # the quantized ring owns its own fallback routing (the XLA
        # psum_scatter cannot express per-hop requantization)
        return _guard.primary(_obs.primary(ring_reduce_scatter(
            x, axis, accum_dtype=accum_dtype, wire_format=wire_format)))
    if method == ReduceScatterMethod.Auto:
        n = jax.lax.axis_size(axis)
        chunk_bytes = (x.size // n) * x.dtype.itemsize
        method = (
            ReduceScatterMethod.Ring1D
            if chunk_bytes <= _VMEM_CHUNK_LIMIT
            else ReduceScatterMethod.XLA
        )
    if method == ReduceScatterMethod.XLA:
        if accum_dtype is not None and jnp.dtype(accum_dtype) != x.dtype:
            return jax.lax.psum_scatter(
                x.astype(accum_dtype), axis, tiled=True).astype(x.dtype)
        return jax.lax.psum_scatter(x, axis, tiled=True)
    return _guard.primary(_obs.primary(
        ring_reduce_scatter(x, axis, accum_dtype=accum_dtype)))


def reduce_scatter_op(
    arr: jax.Array,
    mesh,
    axis: str = TP_AXIS,
    method: ReduceScatterMethod = ReduceScatterMethod.Auto,
    wire_format=None,
) -> jax.Array:
    """Host-level RS. `arr` stacks per-rank contributions: (n, n*m, ...),
    sharded on dim 0 — rank r contributes arr[r] and keeps sum chunk r
    (ref op contract: reduce_scatter.py:857-866). Returns (n*m, ...) sharded
    along the leading dim. wire_format as in reduce_scatter."""
    n = int(mesh.shape[axis])
    if arr.shape[0] != n:
        raise ValueError(
            f"reduce_scatter_op expects one stacked contribution per rank: "
            f"leading dim {arr.shape[0]} != axis size {n}"
        )
    return _rs_op_jit(mesh, axis, method,
                      wcodec.resolve(wire_format))(arr)


@functools.lru_cache(maxsize=None)
def _rs_op_jit(mesh, axis: str, method: ReduceScatterMethod, fmt):
    def fn(xs):
        return reduce_scatter(xs[0], axis, method=method,
                              wire_format=fmt)

    return jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
                      check_vma=False)
    )


# -- protocol models (static verifier, triton_dist_tpu.verify) ---------------

from triton_dist_tpu import verify as _v  # noqa: E402


def _ring_rs_skeleton(n, fill_stage, prefix="", fmt="native",
                      space=None):
    """The shared RS producer ring protocol (_ring_rs_kernel /
    _ring_rs_wire_kernel / gemm_reduce_scatter._rs_ring): credit flow
    control toward the left neighbor, parity-indexed recv semaphores,
    double-buffered acc slots. `fill_stage(s)` supplies the per-step
    stage fill — an async x-chunk load here, a synchronous partial-GEMM
    write in the fused kernel — so all three kernels share ONE verified
    skeleton, exactly as they share the runtime ring.

    `fmt` mirrors the wire_format knob: the quantized variants carry
    the block-scaled wire image in the SAME acc slots on the SAME
    semaphores — only the LOCAL dataflow differs (each consume edge
    decodes + re-encodes, and the final arrival is staged un-re-encoded
    before the store). The synchronization skeleton is identical by
    construction AND by proof: `verify.protocol_skeleton` re-derives it
    per format and `registry.check_format_invariance` asserts equality
    (docs/verification.md "Format invariance").

    The credit protocol is what makes the acc slot reuse safe: the
    verifier proves it by the HB chain my wait_send -> my credit grant
    -> left's credit wait -> left's next put into that slot (drop the
    credits and the race detector fires — tests/_mutants.py
    rs_ring_no_credit).

    `space` (xslice.topo.SliceTeam, capture-only) scopes the ring to
    one slice of a hierarchical team — `n` is then the slice-local
    size and peers rebase through `space.split(my_pe)` (see
    allgather._ag_protocol; xslice/collectives.py composes this
    skeleton with the DCN rail exchange). None = flat, bit-for-bit the
    previous behavior."""
    wire = fmt != "native"
    me_g = shmem.my_pe(TP_AXIS)
    base, me = (0, me_g) if space is None else space.split(me_g)
    o = _v.ref(prefix + "o")
    acc, stage = _v.ref(prefix + "acc"), _v.ref(prefix + "stage")
    st = _v.sem(prefix + "st_sem")
    send, recv = _v.sem(prefix + "send_sem"), _v.sem(prefix + "recv_sems")
    credit = _v.sem(prefix + "credit_sem")
    left, right = base + (me - 1) % n, base + (me + 1) % n
    if space is None:
        shmem.neighbor_barrier(TP_AXIS, me, n)
    else:
        space.neighbor_barrier(prefix, me, base, n)
    # step-0 incoming targets our slot 1, free from the start
    shmem.signal(credit.at(), 1, shmem.SIGNAL_ADD, left, TP_AXIS)
    # our contribution to the first travelling chunk -> acc[0]
    fill_stage(-1)
    if wire:
        _v.read(stage.at())  # send-edge encode reads the f32 stage
    _v.write(acc.at(0))
    for s in range(n - 1):
        cur, nxt = s % 2, (s + 1) % 2
        shmem.signal_wait_until(credit.at(), shmem.CMP_GE, 1)
        h = shmem.putmem_nbi(acc.at(nxt), acc.at(cur), send.at(),
                             recv.at(nxt), right, TP_AXIS)
        fill_stage(s)
        _v.write(stage.at())
        h.wait_send()
        if s + 1 <= n - 2:
            # slot cur is drained: receivable for incoming step s+1
            shmem.signal(credit.at(), 1, shmem.SIGNAL_ADD, left, TP_AXIS)
        h.wait_recv()
        _v.read(stage.at())
        _v.read(acc.at(nxt))
        if wire and s == n - 2:
            _v.write(stage.at())  # final arrival: staged, no re-encode
        else:
            _v.write(acc.at(nxt))  # acc[nxt] += stage (native) / encode
    final_src = stage.at() if wire else acc.at((n - 1) % 2)
    fc = _v.copy(o.at(), final_src, st.at())
    fc.wait()


@_v.protocol("reduce_scatter",
             grid=({}, {"fmt": "fp8"}, {"fmt": "int8"}),
             doc="credit-flow ring RS (_ring_rs_kernel; fmt != native "
                 "models _ring_rs_wire_kernel — same sync skeleton, "
                 "wire-image acc slots)")
def _rs_protocol(n, prefix="", fmt="native", space=None):
    x = _v.ref(prefix + "x")
    ld = _v.sem(prefix + "ld_sem")

    def fill_stage(s):
        # async load of our contribution; finish() runs before the read
        me = shmem.my_pe(TP_AXIS)
        if space is not None:
            me = space.local_of(me)  # chunk index is slice-local
        chunk = (me - 1) % n if s < 0 else (me - s - 2) % n
        dst = (_v.ref(prefix + "acc").at(0) if s < 0 and fmt == "native"
               else _v.ref(prefix + "stage").at())
        _v.copy(dst, x.at(chunk), ld.at()).wait()

    _ring_rs_skeleton(n, fill_stage, prefix=prefix, fmt=fmt, space=space)


# -- conformance runner (verify.conform) --------------------------------------

from triton_dist_tpu.verify import conform as _conform  # noqa: E402


@_conform.conforms(
    "reduce_scatter",
    grids=((4, {}), (4, {"fmt": "fp8"}), (4, {"fmt": "int8"})),
    doc="credit-flow ring RS entry on the interpret mesh")
def _rs_conform(n, fmt="native"):
    mesh = _conform.team_mesh(n, (TP_AXIS,))
    if isinstance(mesh, _conform.Skip):
        return mesh
    wf = None if fmt == "native" else fmt
    # every rank holds its own full contribution: replicated input is
    # exact for conformance (the sync skeleton is data-independent)
    x = jnp.ones((n * 2, 128), jnp.float32)
    return _conform.collect_streams(
        mesh, TP_AXIS,
        lambda v: ring_reduce_scatter(v, TP_AXIS, wire_format=wf),
        in_specs=P(), args=(x,))
