"""ReduceScatter kernels over ICI remote DMA.

TPU-native re-design of the reference's standalone RS library
(ref: python/triton_dist/kernels/nvidia/reduce_scatter.py:47-866): copy-engine
ring, SM ring kernel, RMA ring for non-P2P, per-node two-stage, ring-reduce
TMA variants. On TPU one ring kernel (VMEM-accumulating, double-buffered)
plus the XLA psum_scatter fallback covers the same space; stage-wise
composition over two mesh axes is the two-stage inter-node analog
(ref: reduce_scatter.py:617-672).
"""

from __future__ import annotations

import enum
import functools
from typing import Sequence, Union

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.lang import shmem
from triton_dist_tpu.lang.core import (
    tpu_call,
    compiler_params,
    next_collective_id,
    compute_vmem_bytes,
    interpret_no_headroom,
)
from triton_dist_tpu.runtime.init import TP_AXIS


class ReduceScatterMethod(enum.Enum):
    Auto = "auto"
    Ring1D = "ring_1d"
    XLA = "xla"


# A ring step holds 3 chunk-sized VMEM buffers (2 accumulator slots + local
# staging); above this chunk size fall back to psum_scatter.
_VMEM_CHUNK_LIMIT = 4 * (1 << 20)


def _ring_rs_kernel(axis: str, n: int, acc_dtype, x_ref, o_ref, acc,
                    stage, ld_sem, st_sem, send_sem, recv_sem,
                    credit_sem, cast_buf):
    """Ring reduce-scatter.

    Chunk schedule (mirrors the SM-ring of ref reduce_scatter.py:327-413):
    step s: send accumulated chunk (me-s-1) mod n to right neighbor, receive
    chunk (me-s-2) mod n, add own contribution. After n-1 steps rank me holds
    the full sum of chunk me.

    acc: VMEM (2, m, k) double buffer — step s sends slot s%2 and receives
    into slot (s+1)%2. Because the two slots are REUSED across steps, flow
    control is required: without it a fast upstream neighbor (the dependency
    chain around the ring only reaches back to us after n hops) could land
    step s+2 into the slot step-s data still occupies. `credit_sem` is the
    backpressure: we grant our LEFT neighbor one credit whenever one of our
    slots becomes receivable (initially slot 1; later each time a send
    completes, freeing that slot for the incoming step that targets it), and
    we take one credit before each send. Credits cap outstanding incoming
    puts at 2, which always target opposite-parity slots, so the
    parity-indexed recv semaphores make every wait exact.

    Dtype contract: accumulation happens in acc_dtype. The DEFAULT is
    the input dtype — bf16 inputs take n-1 bf16 additions around the
    ring, keeping the ring's bandwidth optimality (the accumulator IS
    the RDMA payload). acc_dtype=f32 is the f32-wire option (round-4
    verdict weak #5): every hop ships double the bytes, bought for
    psum-grade accumulation — the cost is a measured column in
    benchmark/bench_collectives.py, not an assertion. Loads cast
    through cast_buf (DMA cannot cast); the output returns in x.dtype.
    """
    me = jax.lax.axis_index(axis)
    m = o_ref.shape[0]
    left = jnp.mod(me - 1, n)
    right = jnp.mod(me + 1, n)
    casting = cast_buf is not None
    shmem.neighbor_barrier(axis, me, n)

    # Step-0 incoming targets our slot 1, free from the start: grant credit.
    pltpu.semaphore_signal(
        credit_sem, inc=1, device_id={axis: left},
        device_id_type=pltpu.DeviceIdType.MESH,
    )

    def load_chunk(chunk, dst):
        """x[chunk] -> dst(acc_dtype), via cast_buf when dtypes differ.
        Returns a finish() that must run before dst is read."""
        tgt = cast_buf if casting else dst
        cp = pltpu.make_async_copy(x_ref.at[pl.ds(chunk * m, m)], tgt,
                                   ld_sem)
        cp.start()

        def finish():
            cp.wait()
            if casting:
                dst[...] = cast_buf[...].astype(acc_dtype)

        return finish

    # Load our contribution to the first travelling chunk, (me-1) mod n.
    load_chunk(jnp.mod(me - 1, n), acc.at[0])()

    for s in range(n - 1):
        cur, nxt = s % 2, (s + 1) % 2
        pltpu.semaphore_wait(credit_sem, 1)  # right's slot `nxt` is free
        rdma = pltpu.make_async_remote_copy(
            src_ref=acc.at[cur],
            dst_ref=acc.at[nxt],
            send_sem=send_sem,
            recv_sem=recv_sem.at[nxt],
            device_id={axis: right},
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        rdma.start()
        # Prefetch our contribution to the incoming chunk while it travels.
        chunk = jnp.mod(me - s - 2, n)
        finish = load_chunk(chunk, stage)
        rdma.wait_send()
        if s + 1 <= n - 2:
            # Slot `cur` is sent out: receivable for incoming step s+1
            # (which targets (s+2)%2 == cur). Grant the left neighbor.
            pltpu.semaphore_signal(
                credit_sem, inc=1, device_id={axis: left},
                device_id_type=pltpu.DeviceIdType.MESH,
            )
        rdma.wait_recv()
        finish()
        acc[nxt] = acc[nxt] + stage[...]

    final = (n - 1) % 2
    if casting:
        cast_buf[...] = acc[final].astype(o_ref.dtype)
        st = pltpu.make_async_copy(cast_buf, o_ref, st_sem)
    else:
        st = pltpu.make_async_copy(acc.at[final], o_ref, st_sem)
    st.start()
    st.wait()


def ring_reduce_scatter(x: jax.Array, axis: str = TP_AXIS,
                        accum_dtype=None) -> jax.Array:
    """Ring RS of per-device (n*m, ...) -> (m, ...). Call inside shard_map.

    accum_dtype: ring accumulation/wire dtype (default x.dtype; f32 is
    the psum-parity wire at 2x hop bytes — see _ring_rs_kernel)."""
    n = jax.lax.axis_size(axis)
    if x.shape[0] % n != 0:
        raise ValueError(f"leading dim {x.shape[0]} not divisible by {n}")
    acc_dtype = jnp.dtype(accum_dtype or x.dtype)
    if n == 1:
        return x
    if interpret_no_headroom():
        if acc_dtype != x.dtype:
            return jax.lax.psum_scatter(
                x.astype(acc_dtype), axis, tiled=True).astype(x.dtype)
        return jax.lax.psum_scatter(x, axis, tiled=True)
    m = x.shape[0] // n
    chunk_shape = (m,) + x.shape[1:]
    casting = acc_dtype != x.dtype
    kernel = functools.partial(_ring_rs_kernel, axis, n, acc_dtype)
    if not casting:
        inner = kernel

        def kernel(*args):  # noqa: F811
            return inner(*args, None)

    scratch = [
        pltpu.VMEM((2,) + chunk_shape, acc_dtype),
        pltpu.VMEM(chunk_shape, acc_dtype),
        pltpu.SemaphoreType.DMA,
        pltpu.SemaphoreType.DMA,
        pltpu.SemaphoreType.DMA,
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.REGULAR,
    ]
    if casting:
        scratch.append(pltpu.VMEM(chunk_shape, x.dtype))
    return tpu_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(chunk_shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=scratch,
        compiler_params=compiler_params(
            has_side_effects=True,
            collective_id=next_collective_id(f"ring_rs_{axis}"),
            vmem_limit_bytes=min(
                128 << 20,
                5 * compute_vmem_bytes((chunk_shape, acc_dtype)),
            ),
        ),
    )(x)


def reduce_scatter(
    x: jax.Array,
    axis: Union[str, Sequence[str]] = TP_AXIS,
    method: ReduceScatterMethod = ReduceScatterMethod.Auto,
    accum_dtype=None,
) -> jax.Array:
    """Reduce-scatter per-device (n*m, ...) -> (m, ...); per-device function.

    Axis tuples run stage-wise outermost-first (the two-stage per-node path
    of ref reduce_scatter.py:617-672): RS over the slow axis first so the
    fast-axis stage reduces already-combined super-chunks.
    accum_dtype: ring wire/accumulation dtype (see ring_reduce_scatter).
    """
    if not isinstance(axis, str):
        out = x
        for ax in tuple(axis):
            out = reduce_scatter(out, ax, method=method,
                                 accum_dtype=accum_dtype)
        return out

    if method == ReduceScatterMethod.Auto:
        n = jax.lax.axis_size(axis)
        chunk_bytes = (x.size // n) * x.dtype.itemsize
        method = (
            ReduceScatterMethod.Ring1D
            if chunk_bytes <= _VMEM_CHUNK_LIMIT
            else ReduceScatterMethod.XLA
        )
    if method == ReduceScatterMethod.XLA:
        if accum_dtype is not None and jnp.dtype(accum_dtype) != x.dtype:
            return jax.lax.psum_scatter(
                x.astype(accum_dtype), axis, tiled=True).astype(x.dtype)
        return jax.lax.psum_scatter(x, axis, tiled=True)
    return ring_reduce_scatter(x, axis, accum_dtype=accum_dtype)


def reduce_scatter_op(
    arr: jax.Array,
    mesh,
    axis: str = TP_AXIS,
    method: ReduceScatterMethod = ReduceScatterMethod.Auto,
) -> jax.Array:
    """Host-level RS. `arr` stacks per-rank contributions: (n, n*m, ...),
    sharded on dim 0 — rank r contributes arr[r] and keeps sum chunk r
    (ref op contract: reduce_scatter.py:857-866). Returns (n*m, ...) sharded
    along the leading dim."""
    n = int(mesh.shape[axis])
    if arr.shape[0] != n:
        raise ValueError(
            f"reduce_scatter_op expects one stacked contribution per rank: "
            f"leading dim {arr.shape[0]} != axis size {n}"
        )
    return _rs_op_jit(mesh, axis, method)(arr)


@functools.lru_cache(maxsize=None)
def _rs_op_jit(mesh, axis: str, method: ReduceScatterMethod):
    def fn(xs):
        return reduce_scatter(xs[0], axis, method=method)

    return jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
                      check_vma=False)
    )


# -- protocol models (static verifier, triton_dist_tpu.verify) ---------------

from triton_dist_tpu import verify as _v  # noqa: E402


def _ring_rs_skeleton(n, fill_stage, prefix=""):
    """The shared RS producer ring protocol (_ring_rs_kernel /
    gemm_reduce_scatter._rs_ring): credit flow control toward the left
    neighbor, parity-indexed recv semaphores, double-buffered acc slots.
    `fill_stage(s)` supplies the per-step stage fill — an async x-chunk
    load here, a synchronous partial-GEMM write in the fused kernel —
    so both kernels share ONE verified skeleton, exactly as they share
    the runtime ring.

    The credit protocol is what makes the acc slot reuse safe: the
    verifier proves it by the HB chain my wait_send -> my credit grant
    -> left's credit wait -> left's next put into that slot (drop the
    credits and the race detector fires — tests/_mutants.py
    rs_ring_no_credit)."""
    me = shmem.my_pe(TP_AXIS)
    o = _v.ref(prefix + "o")
    acc, stage = _v.ref(prefix + "acc"), _v.ref(prefix + "stage")
    st = _v.sem(prefix + "st_sem")
    send, recv = _v.sem(prefix + "send_sem"), _v.sem(prefix + "recv_sems")
    credit = _v.sem(prefix + "credit_sem")
    left, right = (me - 1) % n, (me + 1) % n
    shmem.neighbor_barrier(TP_AXIS, me, n)
    # step-0 incoming targets our slot 1, free from the start
    shmem.signal(credit.at(), 1, shmem.SIGNAL_ADD, left, TP_AXIS)
    # our contribution to the first travelling chunk -> acc[0]
    fill_stage(-1)
    _v.write(acc.at(0))
    for s in range(n - 1):
        cur, nxt = s % 2, (s + 1) % 2
        shmem.signal_wait_until(credit.at(), shmem.CMP_GE, 1)
        h = shmem.putmem_nbi(acc.at(nxt), acc.at(cur), send.at(),
                             recv.at(nxt), right, TP_AXIS)
        fill_stage(s)
        _v.write(stage.at())
        h.wait_send()
        if s + 1 <= n - 2:
            # slot cur is drained: receivable for incoming step s+1
            shmem.signal(credit.at(), 1, shmem.SIGNAL_ADD, left, TP_AXIS)
        h.wait_recv()
        _v.read(stage.at())
        _v.read(acc.at(nxt))
        _v.write(acc.at(nxt))  # acc[nxt] += stage
    fc = _v.copy(o.at(), acc.at((n - 1) % 2), st.at())
    fc.wait()


@_v.protocol("reduce_scatter",
             doc="credit-flow ring RS (_ring_rs_kernel)")
def _rs_protocol(n, prefix=""):
    x = _v.ref(prefix + "x")
    ld = _v.sem(prefix + "ld_sem")

    def fill_stage(s):
        # async load of our contribution; finish() runs before the read
        me = shmem.my_pe(TP_AXIS)
        chunk = (me - 1) % n if s < 0 else (me - s - 2) % n
        dst = (_v.ref(prefix + "acc").at(0) if s < 0
               else _v.ref(prefix + "stage").at())
        _v.copy(dst, x.at(chunk), ld.at()).wait()

    _ring_rs_skeleton(n, fill_stage, prefix=prefix)
