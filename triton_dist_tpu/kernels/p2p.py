"""Point-to-point transfer kernels — the pipeline-parallel building block.

TPU-native analog of the reference's p2p kernels
(ref: python/triton_dist/kernels/nvidia/p2p.py:31-54 `p2p_copy_kernel` /
remote-to-local via symm_at), which back the PP CommOp layer
(ref: layers/nvidia/p2p.py:43-140: `read` remote pull, set_signal/wait_signal
via cuStreamWriteValue/cuStreamWaitValue).

ICI RDMA is push-based, so the canonical op is `send`/`recv` as one
matched collective kernel: the sender pushes into the receiver's output
buffer and signals; the stream-memop signal/wait pair becomes the DMA
delivery semaphore. A `p2p_read` (pull) is provided for API parity by
running the matched kernel in the reverse direction.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.lang import shmem
from triton_dist_tpu.lang.core import (
    tpu_call,
    compiler_params,
    next_collective_id,
    interpret_no_headroom,
    interpret_divergence_unsafe,
)
from triton_dist_tpu.runtime.init import PP_AXIS


def _p2p_kernel(axis: str, n: int, src_rank: int, dst_rank: int,
                x_ref, o_ref, cp_sem, send_sem, recv_sem):
    """Matched on all ranks of the axis: rank src pushes x to rank dst's
    output; everyone else forwards its own x to its own output (identity),
    so the kernel is a pure SPMD program with no divergent control flow
    hazards."""
    me = jax.lax.axis_index(axis)
    # Full barrier: src/dst may be arbitrary ranks, and the put must not
    # land while dst is still in a previous kernel using these semaphores.
    shmem.barrier_all(axis)

    if src_rank == dst_rank or n == 1:
        cp = pltpu.make_async_copy(x_ref, o_ref, cp_sem)
        cp.start()
        cp.wait()
        return

    # Local identity copy for every rank EXCEPT dst: dst's output is written
    # only by the incoming put. Nothing orders a local copy against the
    # remote DMA's arrival, so dst writing o_ref itself would race the put
    # (the put could land first and be overwritten after wait_recv).
    @pl.when(me != dst_rank)
    def _():
        cp = pltpu.make_async_copy(x_ref, o_ref, cp_sem)
        cp.start()
        cp.wait()

    rdma = pltpu.make_async_remote_copy(
        src_ref=x_ref,
        dst_ref=o_ref,
        send_sem=send_sem,
        recv_sem=recv_sem,
        device_id={axis: jnp.int32(dst_rank)},
        device_id_type=pltpu.DeviceIdType.MESH,
    )

    @pl.when(me == src_rank)
    def _():
        rdma.start()
        rdma.wait_send()

    @pl.when(me == dst_rank)
    def _():
        rdma.wait_recv()


def p2p_send(x: jax.Array, src_rank: int, dst_rank: int,
             axis: str = PP_AXIS) -> jax.Array:
    """Send rank src's `x` to rank dst; all other ranks pass through their
    own `x`. Per-device function inside shard_map — every rank must call it
    (matched collective), mirroring the reference's symmetric-buffer p2p
    contract (ref: kernels/nvidia/p2p.py:31-54)."""
    n = jax.lax.axis_size(axis)
    if n == 1:
        return x
    # divergence: only src puts, only dst waits (pl.when in _p2p_kernel)
    if interpret_no_headroom() or interpret_divergence_unsafe():
        me = jax.lax.axis_index(axis)
        shifted = jax.lax.ppermute(x, axis, [(src_rank, dst_rank)])
        return jnp.where(me == dst_rank, shifted, x)
    return tpu_call(
        functools.partial(_p2p_kernel, axis, n, src_rank, dst_rank),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        compiler_params=compiler_params(
            has_side_effects=True,
            collective_id=next_collective_id(f"p2p_{axis}"),
        ),
    )(x)


def p2p_read(x: jax.Array, reader_rank: int, owner_rank: int,
             axis: str = PP_AXIS) -> jax.Array:
    """Pull owner's buffer into reader (ref CommOp.read, layers/nvidia/
    p2p.py:43-140). Push-based under the hood."""
    return p2p_send(x, owner_rank, reader_rank, axis)


def ring_shift(x: jax.Array, shift: int = 1, axis: str = PP_AXIS) -> jax.Array:
    """Every rank sends its buffer `shift` hops right; the PP stage-to-stage
    microbatch handoff (all stages transfer simultaneously)."""
    n = jax.lax.axis_size(axis)
    if n == 1:
        return x
    if interpret_no_headroom():
        perm = [(i, (i + shift) % n) for i in range(n)]
        return jax.lax.ppermute(x, axis, perm)

    def kernel(x_ref, o_ref, send_sem, recv_sem):
        me = jax.lax.axis_index(axis)
        if abs(shift) == 1:
            shmem.neighbor_barrier(axis, me, n)
        else:
            shmem.barrier_all(axis)
        shmem.putmem_nbi(o_ref, x_ref, send_sem, recv_sem,
                         jnp.mod(me + shift, n), axis).wait()

    return tpu_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
        compiler_params=compiler_params(
            has_side_effects=True,
            collective_id=next_collective_id(f"ring_shift_{axis}"),
        ),
    )(x)


# -- protocol models (static verifier, triton_dist_tpu.verify) ---------------

from triton_dist_tpu import verify as _v  # noqa: E402


@_v.protocol("broadcast", grid=({"root": 0}, {"root": 1}),
             doc="team broadcast (lang/shmem.broadcast): root-guarded "
                 "fan-out, non-root single delivery wait")
def _broadcast_protocol(n, root=0):
    """Exercises the rank-divergent guard machinery (capture `when`):
    only the root records the fan-out puts, only non-roots the delivery
    wait — the same divergence the real kernel expresses with pl.when.
    The entry barrier is the documented caller precondition."""
    src, dst = _v.ref("src"), _v.ref("dst")
    send, recv = _v.sem("send_sem"), _v.sem("recv_sem")
    shmem.barrier_all(PP_AXIS)
    shmem.broadcast(dst, src, send.at(), recv.at(), root, PP_AXIS, n)
    _v.read(dst.at())  # every rank consumes the broadcast payload


@_v.protocol("ring_shift", grid=({"shift": 1}, {"shift": 3}),
             doc="PP stage handoff: every rank puts `shift` hops right")
def _ring_shift_protocol(n, shift=1):
    me = shmem.my_pe(PP_AXIS)
    x, o = _v.ref("x"), _v.ref("o")
    send, recv = _v.sem("send_sem"), _v.sem("recv_sem")
    if abs(shift) == 1:
        shmem.neighbor_barrier(PP_AXIS, me, n)
    else:
        shmem.barrier_all(PP_AXIS)
    h = shmem.putmem_nbi(o.at(), x.at(), send.at(), recv.at(),
                         (me + shift) % n, PP_AXIS)
    h.wait()
    _v.read(o.at())


# -- conformance runners (verify.conform) -------------------------------------

from jax.sharding import PartitionSpec as _P  # noqa: E402

from triton_dist_tpu.verify import conform as _conform  # noqa: E402


@_conform.conforms(
    "ring_shift",
    grids=((4, {"shift": 1}), (4, {"shift": 3})),
    doc="neighbor-barriered ring rotation on the interpret mesh")
def _ring_shift_conform(n, shift=1):
    mesh = _conform.team_mesh(n, (PP_AXIS,))
    if isinstance(mesh, _conform.Skip):
        return mesh
    x = jnp.ones((8, 128), jnp.float32)
    return _conform.collect_streams(
        mesh, PP_AXIS, lambda v: ring_shift(v, shift, PP_AXIS),
        in_specs=_P(), args=(x,))


@_conform.conforms(
    "broadcast",
    grids=((4, {"root": 0}), (4, {"root": 1})),
    doc="root-guarded fan-out (rank-divergent; see skip reason)")
def _broadcast_conform(n, root=0):
    return _conform.Skip(
        "rank-divergent protocol (root-guarded fan-out): the legacy "
        "lockstep interpreter cannot execute divergent Pallas branches, "
        "so broadcast routes to the value-level XLA fallback on this "
        "rig, which records no kernel stream")
