"""Grouped GEMM over expert segments — the MoE matmul core.

TPU-native analog of the reference's grouped-GEMM consumers
(ref: python/triton_dist/kernels/nvidia/allgather_group_gemm.py:535
`consumer scatter-group-GEMM`; moe_reduce_rs.py:167-246). The reference
hand-tiles a Triton kernel over sorted token blocks with per-block expert
ids; on TPU `lax.ragged_dot` is the native expression — XLA lowers it onto
the MXU with contiguous group segments, which is exactly what the sorted
token layout provides.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def grouped_gemm(
    x_sorted: jax.Array,  # (T, K) tokens sorted by expert
    w_stack: jax.Array,  # (E, K, N) per-expert weights
    group_sizes: jax.Array,  # (E,) rows per expert
    out_dtype=None,
) -> jax.Array:
    """y[i] = x_sorted[i] @ w_stack[expert_of_segment(i)] -> (T, N)."""
    out_dtype = out_dtype or x_sorted.dtype
    y = jax.lax.ragged_dot(
        x_sorted, w_stack, group_sizes,
        preferred_element_type=jnp.float32,
    )
    return y.astype(out_dtype)


def grouped_gemm_ref(x_sorted, w_stack, group_sizes, out_dtype=None):
    """Loop-over-experts reference (masked einsum; O(E) passes)."""
    out_dtype = out_dtype or x_sorted.dtype
    e = w_stack.shape[0]
    t = x_sorted.shape[0]
    starts = jnp.cumsum(group_sizes) - group_sizes
    rows = jnp.arange(t)[:, None]
    # membership mask (T, E)
    member = (rows >= starts[None, :]) & (
        rows < (starts + group_sizes)[None, :]
    )
    xf = x_sorted.astype(jnp.float32)
    acc = jnp.zeros((t, w_stack.shape[2]), jnp.float32)
    for ei in range(e):
        y = xf @ w_stack[ei].astype(jnp.float32)
        acc = jnp.where(member[:, ei:ei + 1], y, acc)
    return acc.astype(out_dtype)
