"""Fused GEMM+ReduceScatter.

TPU-native re-design of the reference's GEMM+RS
(ref: python/triton_dist/kernels/nvidia/gemm_reduce_scatter.py:122-583):
there, a producer GEMM counts finished tiles per M-segment and notifies a
consumer reduce kernel on a separate stream (:232-248, :559-562). Here the
producer and consumer fuse into ONE Pallas ring: the verified ring-RS
protocol (see reduce_scatter.py) with the stage buffer *computed by the MXU*
instead of loaded — each ring hop's transfer overlaps with the matmul of the
next chunk's partial product.

Computes: C_shard = ReduceScatter(a @ b)   [row-parallel TP matmul]
  a: (M, K_loc) per device, b: (K_loc, N) per device -> C_shard: (M/n, N),
  where rank r keeps sum_r' (a_r' @ b_r')[r*M/n:(r+1)*M/n].

Chunk schedule (= ring RS): step s sends accumulated chunk (me-s-1) mod n,
receives chunk (me-s-2) mod n, and contributes its own partial of that
chunk, computed *while the hop is in flight*. The reference's tile-counter
+ notify (:232-234) becomes the per-parity DMA delivery semaphore; its
dedicated rs_stream becomes the ring hop running concurrently with MXU work.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.lang import shmem
from triton_dist_tpu.lang.core import (
    cost_estimate,
    tpu_call,
    compiler_params,
    next_collective_id,
    interpret_no_headroom,
)
from triton_dist_tpu.runtime.init import TP_AXIS


@dataclasses.dataclass(frozen=True)
class GemmRsConfig:
    tile_m: int = 128
    vmem_budget: int = 14 << 20
    # race provocation (ref straggler_option, allreduce.py:137-142)
    straggler_rank: int = -1
    straggler_ns: int = 0


def _partial_chunk(a_ref, b_ref, chunk, m_loc, tm, a_tile, dst, ld_sems,
                   out_dtype):
    """dst[:] = a[chunk rows] @ b, tiled over M (b resident in VMEM).
    A-tile loads are double-buffered against the MXU so no load is
    exposed past the first (the consumer-side pipelining the reference
    gets from num_stages, gemm_reduce_scatter.py:122-248)."""
    mt = m_loc // tm

    def load(i, slot):
        return pltpu.make_async_copy(
            a_ref.at[pl.ds(chunk * m_loc + i * tm, tm)], a_tile.at[slot],
            ld_sems.at[slot],
        )

    load(0, 0).start()
    for i in range(mt):
        if i + 1 < mt:
            load(i + 1, (i + 1) % 2).start()
        load(i, i % 2).wait()
        dst[pl.ds(i * tm, tm), :] = jnp.dot(
            a_tile[i % 2], b_ref[...], preferred_element_type=jnp.float32
        ).astype(out_dtype)


def _gemm_rs_kernel(axis: str, n: int, tm: int, out_dtype, straggler,
                    a_arrival: bool,
                    a_ref, b_ref, o_ref, acc, stage, a_tile,
                    ld_sems, st_sem, send_sem, recv_sems, credit_sem):
    me = jax.lax.axis_index(axis)
    m_loc = o_ref.shape[0]
    left = jnp.mod(me - 1, n)
    right = jnp.mod(me + 1, n)

    def src_slot(chunk):
        # a_arrival: A's row blocks are in ag_gemm ring-arrival order
        # (block s = chunk (me - s) mod n), so global chunk c lives at
        # slot (me - c) mod n — a zero-cost index remap.
        return jnp.mod(me - chunk, n) if a_arrival else chunk

    if n == 1:
        _partial_chunk(a_ref, b_ref, 0, m_loc, tm, a_tile, acc.at[0], ld_sems,
                       out_dtype)
        st = pltpu.make_async_copy(acc.at[0], o_ref, st_sem)
        st.start()
        st.wait()
        return

    shmem.neighbor_barrier(axis, me, n)
    shmem.straggler_delay(axis, *straggler)
    # Step-0 incoming targets our slot 1 (free): grant left one credit
    # (flow-control protocol of reduce_scatter._ring_rs_kernel).
    pltpu.semaphore_signal(
        credit_sem, inc=1, device_id={axis: left},
        device_id_type=pltpu.DeviceIdType.MESH,
    )

    # Compute our partial of the first travelling chunk, (me-1) mod n.
    first = jnp.mod(me - 1, n)
    _partial_chunk(a_ref, b_ref, src_slot(first), m_loc, tm, a_tile,
                   acc.at[0], ld_sems, out_dtype)

    for s in range(n - 1):
        cur, nxt = s % 2, (s + 1) % 2
        pltpu.semaphore_wait(credit_sem, 1)
        rdma = pltpu.make_async_remote_copy(
            src_ref=acc.at[cur],
            dst_ref=acc.at[nxt],
            send_sem=send_sem,
            recv_sem=recv_sems.at[nxt],
            device_id={axis: right},
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        rdma.start()
        # MXU fills the stage with our partial of the incoming chunk while
        # the hop is in flight — this is the producer/consumer overlap.
        chunk = jnp.mod(me - s - 2, n)
        _partial_chunk(a_ref, b_ref, src_slot(chunk), m_loc, tm, a_tile,
                       stage, ld_sems, out_dtype)
        rdma.wait_send()
        if s + 1 <= n - 2:
            pltpu.semaphore_signal(
                credit_sem, inc=1, device_id={axis: left},
                device_id_type=pltpu.DeviceIdType.MESH,
            )
        rdma.wait_recv()
        acc[nxt] = acc[nxt] + stage[...]

    final = (n - 1) % 2
    st = pltpu.make_async_copy(acc.at[final], o_ref, st_sem)
    st.start()
    st.wait()


def gemm_rs(
    a: jax.Array,
    b: jax.Array,
    axis: str = TP_AXIS,
    config: Optional[GemmRsConfig] = None,
    out_dtype=None,
    force_kernel: bool = False,
    a_order: str = "rank",
) -> jax.Array:
    """Overlapped ReduceScatter(a @ b); per-device function inside shard_map
    (ref host entry: gemm_reduce_scatter.py:569-583 `gemm_rs`).

    a: (M, K_loc); b: (K_loc, N). Returns rank's reduced chunk (M/n, N).
    out_dtype also sets the cross-rank accumulation dtype in the ring.
    a_order="arrival" consumes A whose row blocks are in ag_gemm's
    ring-arrival order (see ag_gemm c_order) by remapping the chunk
    index — free in the kernel, a block un-permute on fallback paths.
    """
    cfg = config or GemmRsConfig()
    out_dtype = out_dtype or a.dtype
    assert a_order in ("rank", "arrival"), a_order
    a_arrival = a_order == "arrival"
    n = jax.lax.axis_size(axis)
    m, k_loc = a.shape
    k2, n_full = b.shape
    assert k_loc == k2, f"K mismatch {k_loc} vs {k2}"
    if n == 1 and not force_kernel:
        # Nothing to scatter at world=1; XLA's matmul wins (see ag_gemm).
        return jnp.dot(a, b, preferred_element_type=jnp.float32).astype(
            out_dtype
        )
    if m % n:
        raise ValueError(f"M={m} not divisible by axis size {n}")
    m_loc = m // n
    tm = min(cfg.tile_m, m_loc)
    if m_loc % tm:
        raise ValueError(f"chunk rows {m_loc} must divide tile_m {tm}")
    in_itemsize = jnp.dtype(a.dtype).itemsize
    out_itemsize = jnp.dtype(out_dtype).itemsize
    # VMEM residents: b (K_loc, N) and a tile (tm, K_loc) in the input
    # dtype; acc 2x(m_loc, N) + stage (m_loc, N) in the accumulation dtype.
    vmem_need = (
        k_loc * n_full * in_itemsize
        + 3 * m_loc * n_full * out_itemsize
        + 2 * tm * k_loc * in_itemsize
    )
    if (vmem_need > cfg.vmem_budget or interpret_no_headroom()) and (
        not force_kernel
    ):
        if a_arrival and n > 1:
            from triton_dist_tpu.kernels.allgather_gemm import (
                arrival_to_rank_order,
            )

            a = arrival_to_rank_order(a, axis)
        partial = jnp.dot(a, b, preferred_element_type=jnp.float32).astype(
            out_dtype
        )
        return jax.lax.psum_scatter(partial, axis, tiled=True)

    return tpu_call(
        functools.partial(_gemm_rs_kernel, axis, n, tm, out_dtype,
                          (cfg.straggler_rank, cfg.straggler_ns),
                          a_arrival),
        out_shape=jax.ShapeDtypeStruct((m_loc, n_full), out_dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, m_loc, n_full), out_dtype),
            pltpu.VMEM((m_loc, n_full), out_dtype),
            pltpu.VMEM((2, tm, k_loc), a.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR,
        ],
        compiler_params=compiler_params(
            has_side_effects=True,
            # barrier semaphore only exists in the n>1 kernel body (see
            # neighbor_barrier); collective_id must be omitted at world=1.
            collective_id=(
                next_collective_id(f"gemm_rs_{axis}") if n > 1 else None
            ),
            vmem_limit_bytes=cfg.vmem_budget + (2 << 20),
        ),
        # launch_metadata analog (ref allgather_gemm.py:145-155)
        cost_estimate=cost_estimate(
            flops=2 * m * k_loc * n_full,
            bytes_accessed=(m * k_loc + k_loc * n_full) * in_itemsize
            + m_loc * n_full * out_itemsize,
            remote_bytes=(n - 1) * m_loc * n_full * out_itemsize,
        ),
    )(a, b)


def gemm_rs_ref(a: jax.Array, b: jax.Array, axis: str = TP_AXIS) -> jax.Array:
    """Unfused XLA reference path."""
    partial = jnp.dot(a, b, preferred_element_type=jnp.float32).astype(a.dtype)
    return jax.lax.psum_scatter(partial, axis, tiled=True)
