"""Fused GEMM+ReduceScatter.

TPU-native re-design of the reference's GEMM+RS
(ref: python/triton_dist/kernels/nvidia/gemm_reduce_scatter.py:122-583):
there, a producer GEMM counts finished tiles per M-segment and notifies a
consumer reduce kernel on a separate stream (:232-248, :559-562). Here the
producer and consumer fuse into ONE Pallas ring: the verified ring-RS
protocol (see reduce_scatter.py) with the stage buffer *computed by the MXU*
instead of loaded — each ring hop's transfer overlaps with the matmul of the
next chunk's partial product.

Computes: C_shard = ReduceScatter(a @ b)   [row-parallel TP matmul]
  a: (M, K_loc) per device, b: (K_loc, N) per device -> C_shard: (M/n, N),
  where rank r keeps sum_r' (a_r' @ b_r')[r*M/n:(r+1)*M/n].

Chunk schedule (= ring RS): step s sends accumulated chunk (me-s-1) mod n,
receives chunk (me-s-2) mod n, and contributes its own partial of that
chunk, computed *while the hop is in flight*. The reference's tile-counter
+ notify (:232-234) becomes the per-parity DMA delivery semaphore; its
dedicated rs_stream becomes the ring hop running concurrently with MXU work.

Producer tiling (the reference's fully-tiled producer GEMM, :122-248): two
regimes, chosen by VMEM fit.
  resident — b (K_loc, N) lives in VMEM, A chunk rows stream in (tm, K_loc)
  double-buffered tiles. Minimal HBM traffic (b read once) but needs
  K_loc*N*itemsize of VMEM.
  streamed — when b exceeds the budget (e.g. the Qwen3-32B down-proj at
  tp=8: b = (3200, 5120) bf16 = 32.8 MB): the A chunk (m_loc, K_loc) is
  VMEM-resident instead and b streams through (K_loc, tn) double-buffered
  column tiles. b is re-streamed once per chunk (n passes total) — the
  traffic cost of keeping the ring payload full-width; at the 32B shape
  that is ~275 MB vs a ~340 us MXU-bound compute, so the stream still
  hides under the matmul. (The alternative — one ring per N tile so b
  streams once — trades it for nt x smaller, latency-exposed hops; not
  implemented.)

world=1 tax, per the artifact of record (driver-captured bench.py): the
forced local blocked-matmul regime at the 32B down-proj shape measured
1.07-1.10x XLA's dot across rounds 4-5 [perf:gemm_rs_vs_xla=0.90-1.12].
The round-6 candidate search reaches the few-grid-step nk==1
direct-store corner (e.g. (1024, 2560, 3200) — a 4-step sweep) the old
14 MiB prune budget excluded. scripts/check_perf_claims.py lints the
bracketed claim against the latest driver artifact.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.lang import shmem
from triton_dist_tpu.lang.core import (
    cost_estimate,
    fit_tile,
    tpu_call,
    compiler_params,
    next_collective_id,
    interpret_no_headroom,
)
from triton_dist_tpu.runtime.init import TP_AXIS
from triton_dist_tpu.trace import events as trace_ev
from triton_dist_tpu.wire import codec as wcodec


@dataclasses.dataclass(frozen=True)
class GemmRsConfig:
    tile_m: int = 128
    # streamed regime: b column-tile width (rounded to a fitting divisor)
    tile_n: int = 512
    # local blocked-matmul regime (world=1 forced): its own tiles. v5e
    # sweep at the 32B down-proj shape (a (2048,3200) @ b (3200,5120)
    # bf16, slope_timer): (512,1280,640) = 0.364 ms vs XLA's 0.337 —
    # wider N tiles (fewer grid steps) dominate; tk is lane-constrained
    # to multiples of 128 dividing K.
    tile_m_local: int = 512
    tile_n_local: int = 1280
    tile_k_local: int = 1024
    vmem_budget: int = 14 << 20
    # race provocation (ref straggler_option, allreduce.py:137-142)
    straggler_rank: int = -1
    straggler_ns: int = 0


def _col_tile_candidates(n_full: int, cap: int):
    """Divisors of n_full that are lane multiples, descending, <= cap."""
    cands = [t for t in range(128, min(cap, n_full) + 1, 128)
             if n_full % t == 0]
    return sorted(cands, reverse=True) or [n_full]


def _partial_chunk(a_ref, b_ref, chunk, m_loc, tm, a_tile, dst, ld_sems,
                   out_dtype):
    """dst[:] = a[chunk rows] @ b, tiled over M (b resident in VMEM).
    A-tile loads are double-buffered against the MXU so no load is
    exposed past the first (the consumer-side pipelining the reference
    gets from num_stages, gemm_reduce_scatter.py:122-248)."""
    mt = m_loc // tm

    def load(i, slot):
        return pltpu.make_async_copy(
            a_ref.at[pl.ds(chunk * m_loc + i * tm, tm)], a_tile.at[slot],
            ld_sems.at[slot],
        )

    load(0, 0).start()
    for i in range(mt):
        if i + 1 < mt:
            load(i + 1, (i + 1) % 2).start()
        load(i, i % 2).wait()
        dst[pl.ds(i * tm, tm), :] = jnp.dot(
            a_tile[i % 2], b_ref[...], preferred_element_type=jnp.float32
        ).astype(out_dtype)


def _partial_chunk_streamed(a_ref, b_ref, chunk, m_loc, tn, a_chunk,
                            b_tile, a_sem, b_sems, dst, out_dtype):
    """dst[:] = a[chunk rows] @ b with b STREAMED in (K_loc, tn) column
    tiles (double-buffered) and the A chunk VMEM-resident — the regime
    for b too large for VMEM (the reference's producer GEMM is fully
    tiled for the same reason, gemm_reduce_scatter.py:122-248)."""
    n_full = b_ref.shape[1]
    nt = n_full // tn

    cp_a = pltpu.make_async_copy(
        a_ref.at[pl.ds(chunk * m_loc, m_loc)], a_chunk, a_sem
    )
    cp_a.start()

    def bload(j, slot):
        return pltpu.make_async_copy(
            b_ref.at[:, pl.ds(j * tn, tn)], b_tile.at[slot],
            b_sems.at[slot],
        )

    bload(0, 0).start()
    cp_a.wait()
    for j in range(nt):
        if j + 1 < nt:
            bload(j + 1, (j + 1) % 2).start()
        bload(j, j % 2).wait()
        dst[:, pl.ds(j * tn, tn)] = jnp.dot(
            a_chunk[...], b_tile[j % 2], preferred_element_type=jnp.float32
        ).astype(out_dtype)


def _rs_ring(axis, n, straggler, partial_fn, o_ref, acc, stage, st_sem,
             send_sem, recv_sems, credit_sem, tctx=None, fmt=None,
             ostage=None):
    """The shared producer ring: partial_fn(chunk, dst_ref) fills dst with
    this rank's partial of a global chunk; the ring protocol (credit flow
    control, parity recv semaphores) is reduce_scatter._ring_rs_kernel's,
    with the stage computed instead of loaded.

    `fmt` (wire.WireFormat, quantized): the travelling acc slots hold
    the block-scaled wire image — partial_fn fills the f32 `stage`,
    each send edge encodes it into its wire slot, each consume edge
    decodes + adds in f32, and the final arrival stores WITHOUT a
    re-encode (via `ostage` when out_dtype != f32). Identical puts /
    credits / semaphores — the sync skeleton is format-invariant
    (verify-proved), only the payload bytes and the local VPU dataflow
    change.

    `tctx` (trace.events.TraceCtx or None) gates the event records:
    per-hop credit waits and recv waits (sem_wait class) vs per-chunk
    partial-GEMM spans (compute) — the wait-vs-MXU breakdown of the
    producer/consumer overlap this kernel exists for."""
    me = jax.lax.axis_index(axis)
    trace_ev.init_ctx(tctx, rank=me)
    R = trace_ev.REGIONS
    wirefmt = None if fmt is None or wcodec.is_native(fmt) else fmt

    def final_store(src):
        st = pltpu.make_async_copy(src, o_ref, st_sem)
        st.start()
        st.wait()

    if n == 1:
        with trace_ev.span(tctx, R["rs.partial"], payload=0):
            partial_fn(jnp.int32(0), stage if wirefmt else acc.at[0])
        if wirefmt:
            # world=1: nothing travels — the send-edge encode still runs
            # (the measurable codec edge cost), the store is the exact
            # partial (pass-through semantics, like RS at n == 1)
            acc[0] = wcodec.encode_rows(stage[...], wirefmt)
            if ostage is not None:
                ostage[...] = stage[...].astype(o_ref.dtype)
            final_store(ostage if ostage is not None else stage)
        else:
            final_store(acc.at[0])
        return

    left = jnp.mod(me - 1, n)
    right = jnp.mod(me + 1, n)
    shmem.neighbor_barrier(axis, me, n)
    if straggler[1] > 0:
        trace_ev.instant(
            tctx, R["straggle"],
            payload=jnp.where(me == straggler[0], straggler[1], 0))
    shmem.straggler_delay(axis, *straggler)
    # Step-0 incoming targets our slot 1 (free): grant left one credit
    # (flow-control protocol of reduce_scatter._ring_rs_kernel).
    shmem.signal(credit_sem, 1, shmem.SIGNAL_ADD, left, axis,
                 label="credit")

    # Compute our partial of the first travelling chunk, (me-1) mod n.
    with trace_ev.span(tctx, R["rs.partial"], payload=0):
        partial_fn(jnp.mod(me - 1, n), stage if wirefmt else acc.at[0])
    if wirefmt:
        acc[0] = wcodec.encode_rows(stage[...], wirefmt)

    for s in range(n - 1):
        cur, nxt = s % 2, (s + 1) % 2
        with trace_ev.span(tctx, R["rs.credit"], payload=s):
            shmem.signal_wait_until(credit_sem, shmem.CMP_GE, 1,
                                    site="credit", slot=s)
        h = shmem.putmem_nbi(acc.at[nxt], acc.at[cur], send_sem,
                             recv_sems.at[nxt], right, axis)
        # MXU fills the stage with our partial of the incoming chunk while
        # the hop is in flight — this is the producer/consumer overlap.
        with trace_ev.span(tctx, R["rs.partial"], payload=s + 1):
            partial_fn(jnp.mod(me - s - 2, n), stage)
        with trace_ev.span(tctx, R["rs.hop"], payload=s):
            h.wait_send()
            if s + 1 <= n - 2:
                shmem.signal(credit_sem, 1, shmem.SIGNAL_ADD, left,
                             axis, label="credit")
            h.wait_recv(slot=s)
        if wirefmt:
            k = stage.shape[-1]
            val = wcodec.decode_rows(acc[nxt], k, wirefmt, jnp.float32) \
                + stage[...]
            if s == n - 2:
                if ostage is not None:
                    ostage[...] = val.astype(o_ref.dtype)
                else:
                    stage[...] = val  # final arrival: no re-encode
            else:
                acc[nxt] = wcodec.encode_rows(val, wirefmt)
        else:
            acc[nxt] = acc[nxt] + stage[...]

    if wirefmt:
        final_store(ostage if ostage is not None else stage)
    else:
        final_store(acc.at[(n - 1) % 2])


def _src_slot(me, n, chunk, a_arrival):
    # a_arrival: A's row blocks are in ag_gemm ring-arrival order
    # (block s = chunk (me - s) mod n), so global chunk c lives at
    # slot (me - c) mod n — a zero-cost index remap.
    return jnp.mod(me - chunk, n) if a_arrival else chunk


def _gemm_rs_kernel(axis: str, n: int, tm: int, out_dtype, straggler,
                    a_arrival: bool, fmt, build, *refs):
    """Resident regime: b in VMEM, A in (tm, K_loc) tiles. `fmt`
    quantized: partials land in the f32 stage and the ring moves the
    wire image (see _rs_ring)."""
    refs = list(refs)
    a_ref, b_ref, o_ref = refs[:3]
    del refs[:3]
    tbuf = refs.pop(0) if build is not None else None
    tcur = refs.pop() if build is not None else None
    wire = fmt is not None and not wcodec.is_native(fmt)
    ostage = refs.pop(3) if wire and o_ref.dtype != jnp.float32 else None
    (acc, stage, a_tile, ld_sems, st_sem, send_sem, recv_sems,
     credit_sem) = refs
    me = jax.lax.axis_index(axis)
    m_loc = o_ref.shape[0]
    part_dtype = jnp.float32 if wire else out_dtype

    def partial_fn(chunk, dst):
        _partial_chunk(a_ref, b_ref, _src_slot(me, n, chunk, a_arrival),
                       m_loc, tm, a_tile, dst, ld_sems, part_dtype)

    _rs_ring(axis, n, straggler, partial_fn, o_ref, acc, stage, st_sem,
             send_sem, recv_sems, credit_sem,
             tctx=trace_ev.make_ctx(build, tbuf, tcur), fmt=fmt,
             ostage=ostage)


def _gemm_rs_kernel_streamed(axis: str, n: int, tn: int, out_dtype,
                             straggler, a_arrival: bool, fmt, build,
                             *refs):
    """Streamed regime: A chunk in VMEM, b in (K_loc, tn) column tiles.
    `fmt` quantized as in _gemm_rs_kernel."""
    refs = list(refs)
    a_ref, b_ref, o_ref = refs[:3]
    del refs[:3]
    tbuf = refs.pop(0) if build is not None else None
    tcur = refs.pop() if build is not None else None
    wire = fmt is not None and not wcodec.is_native(fmt)
    ostage = refs.pop(4) if wire and o_ref.dtype != jnp.float32 else None
    (acc, stage, a_chunk, b_tile, a_sem, b_sems, st_sem, send_sem,
     recv_sems, credit_sem) = refs
    me = jax.lax.axis_index(axis)
    m_loc = o_ref.shape[0]
    part_dtype = jnp.float32 if wire else out_dtype

    def partial_fn(chunk, dst):
        _partial_chunk_streamed(
            a_ref, b_ref, _src_slot(me, n, chunk, a_arrival), m_loc, tn,
            a_chunk, b_tile, a_sem, b_sems, dst, part_dtype,
        )

    _rs_ring(axis, n, straggler, partial_fn, o_ref, acc, stage, st_sem,
             send_sem, recv_sems, credit_sem,
             tctx=trace_ev.make_ctx(build, tbuf, tcur), fmt=fmt,
             ostage=ostage)


def _local_mm_kernel(nk: int, out_dtype, a_ref, b_ref, o_ref, acc=None):
    """world=1 forced-kernel regime at shapes whose accumulator exceeds
    VMEM: a standard blocked matmul on Mosaic's auto pipeline (grid
    (mt, nt, nk), kk innermost) — there is nothing to scatter, so the
    ring machinery would only add an (M, N)-resident accumulator.

    nk == 1 (full-K tiles, the autotuner's direct-store regime): the dot
    result goes straight to the output block — no f32 accumulator scratch
    and none of its zero + read-modify-write + read VMEM round-trips,
    the store restructuring that closes part of the vs-XLA gap at the
    benched Qwen3-32B down-proj shape."""
    if nk == 1:
        o_ref[...] = jnp.dot(a_ref[...], b_ref[...],
                             preferred_element_type=jnp.float32
                             ).astype(out_dtype)
        return

    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _zero():
        acc[...] = jnp.zeros_like(acc)

    acc[...] += jnp.dot(a_ref[...], b_ref[...],
                        preferred_element_type=jnp.float32)

    @pl.when(kk == nk - 1)
    def _store():
        o_ref[...] = acc[...].astype(out_dtype)


# Trace-time record of the regime the last gemm_rs call dispatched to
# ("resident" | "streamed" | "local_mm" | "xla") — a test/debug hook so
# regime-targeted tests can assert they exercise what they claim to
# (the round-5 reviewer caught a 'streamed' test silently running the
# resident kernel).
_last_regime = None


def last_regime():
    return _last_regime


# Trace-time record of the most recent gemm_rs lowering's fitted tiles
# and pallas grid (same idiom; "path" mirrors the regime). Tests pin
# that a tune-cache winner changes the launched grid.
_last_launch = None


def last_launch():
    return _last_launch


def gemm_rs(
    a: jax.Array,
    b: jax.Array,
    axis: str = TP_AXIS,
    config: Optional[GemmRsConfig] = None,
    out_dtype=None,
    force_kernel: bool = False,
    a_order: str = "rank",
    wire_format=None,
) -> jax.Array:
    """Overlapped ReduceScatter(a @ b); per-device function inside shard_map
    (ref host entry: gemm_reduce_scatter.py:569-583 `gemm_rs`).

    a: (M, K_loc); b: (K_loc, N). Returns rank's reduced chunk (M/n, N).
    On the NATIVE wire out_dtype also sets the cross-rank accumulation
    dtype in the ring — out_dtype=jnp.float32 is the f32-accumulation
    option (doubled hop bytes as a side effect, exact-sum parity with
    psum_scatter). wire_format owns the PAYLOAD ENCODING: quantized
    formats ("fp8"/"int8"/wire.WireFormat) ship the block-scaled wire
    image per hop and accumulate in f32 at the consume edge regardless
    of out_dtype (the codec contract) — ~out_itemsize x fewer ICI bytes
    on the SAME credit/parity protocol (format-invariant,
    verifier-proved). At world=1 nothing travels: quantized gemm_rs
    degrades to the plain dot (pass-through, like RS at n == 1).
    a_order="arrival" consumes A whose row blocks are in ag_gemm's
    ring-arrival order (see ag_gemm c_order) by remapping the chunk
    index — free in the kernel, a block un-permute on fallback paths.

    Tracing (trace.building active): one extra trailing output — the
    ring regimes' device trace buffer (credit/hop waits vs partial-GEMM
    spans); local_mm/xla paths return an empty buffer.
    """
    global _last_regime, _last_launch
    cfg = config or GemmRsConfig()
    _last_launch = {"kernel": "gemm_rs", "path": "xla",
                    "overridden": config is not None}
    out_dtype = out_dtype or a.dtype
    assert a_order in ("rank", "arrival"), a_order
    a_arrival = a_order == "arrival"
    fmt = wcodec.resolve(wire_format)
    wirefmt = None if wcodec.is_native(fmt) else fmt
    build = trace_ev.active_build()

    def with_trace(res, tbuf=None):
        return trace_ev.with_trace(build, res, tbuf)

    n = jax.lax.axis_size(axis)
    m, k_loc = a.shape
    k2, n_full = b.shape
    assert k_loc == k2, f"K mismatch {k_loc} vs {k2}"
    if n == 1 and not force_kernel:
        # Nothing to scatter at world=1; XLA's matmul wins (see ag_gemm).
        _last_regime = "xla"
        return with_trace(
            jnp.dot(a, b, preferred_element_type=jnp.float32).astype(
                out_dtype
            ))
    if m % n:
        raise ValueError(f"M={m} not divisible by axis size {n}")
    m_loc = m // n
    # degrade to a dividing tile rather than raising: only the resident
    # regime tiles A by tm (streamed/local_mm never use it)
    tm = fit_tile(cfg.tile_m, m_loc)
    in_itemsize = jnp.dtype(a.dtype).itemsize
    out_itemsize = jnp.dtype(out_dtype).itemsize
    kw = wcodec.wire_cols(n_full, fmt) if wirefmt else 0
    if wirefmt:
        # wire acc slots (int8) + f32 stage (+ out-dtype staging buffer
        # for the final store when out_dtype != f32)
        ring_bytes = 2 * m_loc * kw + m_loc * n_full * 4
        if out_dtype != jnp.float32:
            ring_bytes += m_loc * n_full * out_itemsize
    else:
        # Ring residents shared by both regimes: acc 2x(m_loc, N) + stage.
        ring_bytes = 3 * m_loc * n_full * out_itemsize
    # resident regime adds b plus the A tile double buffer.
    vmem_resident = (
        ring_bytes
        + k_loc * n_full * in_itemsize
        + 2 * tm * k_loc * in_itemsize
    )

    def vmem_streamed(tn):
        # A chunk resident + b column-tile double buffer.
        return (
            ring_bytes
            + m_loc * k_loc * in_itemsize
            + 2 * k_loc * tn * in_itemsize
        )

    def xla_path():
        a_ = a
        if a_arrival and n > 1:
            from triton_dist_tpu.kernels.allgather_gemm import (
                arrival_to_rank_order,
            )

            a_ = arrival_to_rank_order(a_, axis)
        partial = jnp.dot(a_, b, preferred_element_type=jnp.float32)
        if n == 1:
            return partial.astype(out_dtype)
        if wirefmt:
            # ppermute replay of the wire ring's exact fold order
            from triton_dist_tpu.kernels.reduce_scatter import (
                _wire_rs_xla,
            )

            return _wire_rs_xla(partial, axis, n, wirefmt).astype(
                out_dtype)
        return jax.lax.psum_scatter(partial.astype(out_dtype), axis,
                                    tiled=True)

    if interpret_no_headroom() and not force_kernel:
        _last_regime = "xla"
        return with_trace(xla_path())

    hop_bytes = m_loc * kw if wirefmt else m_loc * n_full * out_itemsize
    cost = cost_estimate(
        flops=2 * m * k_loc * n_full,
        bytes_accessed=(m * k_loc + k_loc * n_full) * in_itemsize
        + m_loc * n_full * out_itemsize,
        remote_bytes=(n - 1) * hop_bytes,
    )
    cid = next_collective_id(f"gemm_rs_{axis}") if n > 1 else None

    def _ring_call(kernel, out_shape, in_specs, out_specs, scratch,
                   params, cost_est):
        if build is not None:
            out_shape = (out_shape, trace_ev.out_shape(build))
            out_specs = (out_specs, trace_ev.out_spec())
            scratch = scratch + [trace_ev.cursor_scratch()]
        res = tpu_call(
            kernel, out_shape=out_shape, in_specs=in_specs,
            out_specs=out_specs, scratch_shapes=scratch,
            compiler_params=params, cost_estimate=cost_est,
        )(a, b)
        if build is not None:
            return with_trace(res[0], res[1])
        return res

    def _acc_stage_scratch(extra):
        """Ring scratch head: acc slots + stage (+ wire ostage), then
        the regime's own buffers — the order the kernels unpack."""
        if wirefmt:
            head = [
                pltpu.VMEM((2, m_loc, kw), jnp.int8),
                pltpu.VMEM((m_loc, n_full), jnp.float32),
            ] + extra
            if out_dtype != jnp.float32:
                head.append(pltpu.VMEM((m_loc, n_full), out_dtype))
            return head
        return [
            pltpu.VMEM((2, m_loc, n_full), out_dtype),
            pltpu.VMEM((m_loc, n_full), out_dtype),
        ] + extra

    if vmem_resident <= cfg.vmem_budget:
        _last_regime = "resident"
        _last_launch = {"kernel": "gemm_rs", "path": "resident",
                        "tm": tm, "overridden": config is not None}
        return _ring_call(
            functools.partial(_gemm_rs_kernel, axis, n, tm, out_dtype,
                              (cfg.straggler_rank, cfg.straggler_ns),
                              a_arrival, wirefmt, build),
            jax.ShapeDtypeStruct((m_loc, n_full), out_dtype),
            [
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pltpu.VMEM),
            ],
            pl.BlockSpec(memory_space=pl.ANY),
            _acc_stage_scratch([pltpu.VMEM((2, tm, k_loc), a.dtype)]) + [
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.REGULAR,
            ],
            compiler_params(
                has_side_effects=True,
                # barrier semaphore only exists in the n>1 kernel body (see
                # neighbor_barrier); collective_id must be omitted at n=1.
                collective_id=cid,
                vmem_limit_bytes=cfg.vmem_budget + (2 << 20),
            ),
            # launch_metadata analog (ref allgather_gemm.py:145-155)
            cost,
        )

    # Streamed regime: pick the widest b column tile that fits.
    tn_cands = _col_tile_candidates(n_full, cfg.tile_n)
    tn = next((t for t in tn_cands if vmem_streamed(t) <= cfg.vmem_budget),
              None)
    if tn is None and force_kernel and n > 1:
        tn = tn_cands[-1]  # forced: smallest tile, budget overridden below
    if n > 1 and tn is not None:
        _last_regime = "streamed"
        _last_launch = {"kernel": "gemm_rs", "path": "streamed",
                        "tn": tn, "overridden": config is not None}
        return _ring_call(
            functools.partial(
                _gemm_rs_kernel_streamed, axis, n, tn, out_dtype,
                (cfg.straggler_rank, cfg.straggler_ns), a_arrival,
                wirefmt, build),
            jax.ShapeDtypeStruct((m_loc, n_full), out_dtype),
            [
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            pl.BlockSpec(memory_space=pl.ANY),
            _acc_stage_scratch([
                pltpu.VMEM((m_loc, k_loc), a.dtype),
                pltpu.VMEM((2, k_loc, tn), b.dtype),
            ]) + [
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.REGULAR,
            ],
            compiler_params(
                has_side_effects=True,
                collective_id=cid,
                vmem_limit_bytes=max(cfg.vmem_budget,
                                     vmem_streamed(tn)) + (2 << 20),
            ),
            cost_estimate(
                flops=2 * m * k_loc * n_full,
                # b re-streams once per chunk in this regime
                bytes_accessed=(m * k_loc + n * k_loc * n_full)
                * in_itemsize + m_loc * n_full * out_itemsize,
                remote_bytes=(n - 1) * hop_bytes,
            ),
        )

    if n == 1:
        # force_kernel at world=1 past the resident budget: blocked matmul.
        _last_regime = "local_mm"
        tm_l = fit_tile(cfg.tile_m_local, m)
        tn_l = fit_tile(cfg.tile_n_local, n_full)
        tk_l = fit_tile(cfg.tile_k_local, k_loc)
        nk = k_loc // tk_l
        # Mosaic's auto pipeline double-buffers each block operand; wide
        # autotuner candidates (e.g. full-K direct-store tiles) may need
        # more than the default budget — grant what the tiling implies.
        vmem_local = 2 * (tm_l * tk_l + tk_l * tn_l) * in_itemsize \
            + 2 * tm_l * tn_l * out_itemsize \
            + (tm_l * tn_l * 4 if nk > 1 else 0)
        _last_launch = {"kernel": "gemm_rs", "path": "local_mm",
                        "tm": tm_l, "tn": tn_l, "tk": tk_l,
                        "grid": (m // tm_l, n_full // tn_l, nk),
                        "overridden": config is not None}
        return with_trace(tpu_call(
            functools.partial(_local_mm_kernel, nk, out_dtype),
            grid=(m // tm_l, n_full // tn_l, nk),
            out_shape=jax.ShapeDtypeStruct((m, n_full), out_dtype),
            in_specs=[
                pl.BlockSpec((tm_l, tk_l), lambda i, j, kk: (i, kk),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((tk_l, tn_l), lambda i, j, kk: (kk, j),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((tm_l, tn_l), lambda i, j, kk: (i, j),
                                   memory_space=pltpu.VMEM),
            # nk==1 stores the dot directly: no accumulator scratch
            scratch_shapes=(
                [pltpu.VMEM((tm_l, tn_l), jnp.float32)] if nk > 1 else []
            ),
            compiler_params=compiler_params(
                vmem_limit_bytes=max(cfg.vmem_budget, vmem_local)
                + (2 << 20),
            ),
            cost_estimate=cost,
        )(a, b))

    _last_regime = "xla"
    return with_trace(xla_path())


def gemm_rs_ref(a: jax.Array, b: jax.Array, axis: str = TP_AXIS) -> jax.Array:
    """Unfused XLA reference path."""
    partial = jnp.dot(a, b, preferred_element_type=jnp.float32).astype(a.dtype)
    return jax.lax.psum_scatter(partial, axis, tiled=True)


# -- protocol model (static verifier, triton_dist_tpu.verify) ----------------

from triton_dist_tpu import verify as _v  # noqa: E402
from triton_dist_tpu.kernels.reduce_scatter import (  # noqa: E402
    _ring_rs_skeleton,
)


@_v.protocol("gemm_reduce_scatter",
             grid=({}, {"fmt": "fp8"}, {"fmt": "int8"}),
             doc="GEMM+RS producer ring (_rs_ring): the RS credit ring "
                 "with the stage filled by the partial GEMM (fmt != "
                 "native: wire-image acc slots, same sync skeleton)")
def _gemm_rs_protocol(n, fmt="native"):
    a, b = _v.ref("a"), _v.ref("b")

    def fill_stage(s):
        # partial_fn: synchronous MXU fill of acc[0] / stage from the
        # rank-local A chunk and B shard (no cross-rank content beyond
        # the ring the skeleton carries)
        _v.read(a.at())
        _v.read(b.at())

    _ring_rs_skeleton(n, fill_stage, fmt=fmt)


# -- conformance runner (verify.conform) --------------------------------------

from jax.sharding import PartitionSpec as _P  # noqa: E402

from triton_dist_tpu.verify import conform as _conform  # noqa: E402


@_conform.conforms(
    "gemm_reduce_scatter",
    grids=((4, {}), (4, {"fmt": "fp8"}), (4, {"fmt": "int8"})),
    doc="resident-regime fused GEMM+RS ring on the interpret mesh")
def _gemm_rs_conform(n, fmt="native"):
    mesh = _conform.team_mesh(n, (TP_AXIS,))
    if isinstance(mesh, _conform.Skip):
        return mesh
    wf = None if fmt == "native" else fmt
    a = jnp.ones((8, 128), jnp.float32)
    b = jnp.ones((128, 128), jnp.float32)
    return _conform.collect_streams(
        mesh, TP_AXIS,
        lambda a_, b_: gemm_rs(a_, b_, TP_AXIS, wire_format=wf),
        in_specs=(_P(), _P()), args=(a, b))
