"""Device-side SP flash prefill — online-softmax consumer over per-segment
LL-AG delivery semaphores.

TPU-native re-design of the reference's signature SP mechanism
(ref: python/triton_dist/kernels/nvidia/sp_ag_attention_intra_node.py:105-427):
there, the copy engine allgathers the KV shards segment-by-segment while a
flash-attention consumer waits on per-segment barriers before folding each
arriving segment — the compute/collective overlap T3 (arXiv 2401.16677)
argues for at kernel granularity. `kernels/sp_attention.ring_attention`
expresses the same schedule as `lax.ppermute` + XLA async overlap; THIS
module is the thesis applied: one Pallas kernel whose ring ingest pushes
the local KV shard to every peer with a per-segment delivery semaphore
(the LL-AG producer discipline, `low_latency_allgather.segment_collect_
start` — each arriving segment counted on its own slot so the consumer can
gate on exactly one segment), folds the LOCAL block at step 0 (the
reference's rank-offset swizzle: zero-wait start), then waits each
remaining segment's slot before folding it while later segments are still
in flight.

Two kernel faces:

  flash_prefill_local — the n=1 core: blockwise online-softmax GQA
  prefill streaming (block, Hkv*D) KV pages double-buffered from HBM
  (the prefill analog of `flash_decode._fd_partial_kernel`), with
  general `q_positions` / `kv_len` masking so it serves both long-context
  prefill and the serve plane's prefill-into-cache chunks.

  sp_flash_prefill — the distributed form: per-device inside shard_map,
  rank r holds Q rows and KV rows [r*S_loc, (r+1)*S_loc). Bit-identical
  to `flash_prefill_ref` (the same swizzle-order fold over an XLA-
  gathered KV — the per-segment semaphore transport moves bytes, never
  bits) and allclose to the dense `ring_attention_ref` oracle (online
  softmax re-associates the reductions, so dense-softmax bit parity is
  not a meaningful target; the kernel-math oracle is the bit contract).

Numerical contract of the fold: a fully-masked block is a BITWISE no-op
(m_new == m so alpha == exp(0) == 1.0 exactly; p == 0 under the mask),
which is what lets the kernel skip dead KV pages (`n_act`) while staying
bit-identical to an unskipped replay, and lets causal ranks fold future
segments as masked no-ops without a divergent branch.

Impl selection (flash vs the `ring_attention` fallback) is priced by
`perf_model.estimate_flash_prefill_ms` / `choose_sp_prefill_impl` and
block candidates by `autotuner.prune_flash_prefill_configs`; see
`sp_prefill_attention` (the autotuner-selectable switch) and
docs/performance.md "Prefill regimes". Claimed against the bench artifact
(first measured by the r06 cpu-world1 rig — interpreter semantics, see
docs/performance.md "Rigs"; the bands span the 0.67-2.4x run-to-run
spread of the 2-core rig's slope ratio, and the default-rig S=4096
artifact re-narrows)
as [perf:sp_prefill_vs_ring=0.3-2.6] / [perf:sp_prefill_vs_xla=0.45-2.0].
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.kernels.flash_decode import _fd_chunk as _kv_block
from triton_dist_tpu.kernels.low_latency_allgather import (
    segment_collect_start,
)
from triton_dist_tpu.faults import guard as _guard
from triton_dist_tpu.faults import plan as _fplan
from triton_dist_tpu.lang import shmem
from triton_dist_tpu.lang.core import (
    cdiv,
    compiler_params,
    cost_estimate,
    interpret_no_headroom,
    next_collective_id,
    tpu_call,
    use_interpret,
)
from triton_dist_tpu.runtime.init import SP_AXIS
from triton_dist_tpu.trace import events as trace_ev

NEG_INF = -1e30


@dataclasses.dataclass(frozen=True)
class FlashPrefillConfig:
    """Tunable knobs of the flash-prefill kernels (the autotuner's
    candidate unit; `autotuner.flash_prefill_config_space`)."""

    block: int = 512  # KV page rows streamed per fold step


def supports_flash_prefill(hq: int, hkv: int, d: int) -> bool:
    """Shapes the native kernel accepts: lane-aligned head_dim (the
    per-head column slices of the (rows, Hkv*D) pages must be
    lane-aligned for Mosaic) and an integral GQA group. Interpret mode
    accepts anything; callers' auto paths gate on this for native."""
    return d % 128 == 0 and hq % hkv == 0


def flash_prefill_native_ok(hq: int, hkv: int, d: int) -> bool:
    """THE auto-routing gate: native backend + supported shape.
    Interpret-mode runs stay on the xla formulation so CPU-mesh results
    are bit-stable. Every auto path (layers.attention routing, the
    serve Scheduler's chunk pricing, sp_prefill_attention) consults
    this one definition — a constraint added here reaches them all.
    Memory feasibility is a separate, shape-dependent question:
    flash_prefill_fits."""
    return not use_interpret() and supports_flash_prefill(hq, hkv, d)


def fit_block(t: int, block: Optional[int] = None) -> int:
    """THE page-height fitting rule: the largest sublane-aligned
    DIVISOR of t that is <= block (whole-t fallback when none exists).
    sp_flash_prefill, flash_prefill_ref, the autotuner's pruner, and
    the bench arm all fit through here, so no consumer ever models or
    measures a page geometry the kernel would not run."""
    return _kv_block(t, int(block)) if block else _kv_block(t)


def flash_prefill_vmem_bytes(s_q: int, hq: int, hkv: int, d: int,
                             block: int, dtype=jnp.bfloat16,
                             batch: int = 1) -> int:
    """Per-grid-step resident VMEM of the flash-prefill kernels: the
    double-buffered K+V page pair plus the f32 Q slab and per-head
    m/l/acc states (the wrapper's vmem_limit accounting, shared with
    the pruner's fit rule and the routing gate). batch: rows resident
    AT ONCE — 1 for the local kernel (grid=(B,): one row per step), B
    for the SP kernel (grid=(1,): every row's state lives across the
    whole segment sweep)."""
    isz = jnp.dtype(dtype).itemsize
    return 4 * block * hkv * d * isz + batch * 5 * s_q * hq * d * 4


def flash_prefill_fits(s_q: int, t: int, hq: int, hkv: int, d: int,
                       block: Optional[int] = None,
                       dtype=jnp.bfloat16, batch: int = 1) -> bool:
    """Memory-feasibility gate for auto routing: the per-grid-step
    state must fit the forced-kernel VMEM ceiling (with the Mosaic
    compile margin). Long-context prefills whose (S, Hq*D) f32 state
    exceeds it stay on the fallback path (blockwise-xla locally, the
    ppermute ring for SP) instead of failing at Mosaic allocation.
    batch: see flash_prefill_vmem_bytes — pass B when gating the SP
    kernel."""
    from triton_dist_tpu.perf_model import kernel_vmem_ceiling

    need = flash_prefill_vmem_bytes(s_q, hq, hkv, d, fit_block(t, block),
                                    dtype, batch=batch)
    return need + (8 << 20) <= kernel_vmem_ceiling()


# -- shared fold math (kernel body AND the bit-exact host replay) ------------


def _block_live(s: int, blk: int, base, qp_col, valid_len, causal: bool):
    """(S, blk) liveness mask of one KV block at global offset `base`:
    rows are q positions (qp_col (S,1) i32), columns KV positions."""
    kpos = jax.lax.broadcasted_iota(jnp.int32, (s, blk), 1) + base
    live = kpos < valid_len
    if causal:
        live = jnp.logical_and(live, kpos <= qp_col)
    return live


def _head_update(q_hg, k_blk, v_blk, live, state):
    """Fold one (blk, D) KV block into one (h, g) head's online-softmax
    state (m, l (S,1); acc (S, D) — all f32). The same op sequence runs
    inside the kernel and in flash_prefill_ref: bit parity between the
    overlapped transport and the plain replay rests on it."""
    m, l, acc = state
    lg = jax.lax.dot_general(
        q_hg, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (S, blk)
    lg = jnp.where(live, lg, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(lg, -1, keepdims=True))
    # fully-masked block: m_new == m bitwise, alpha == exp(0) == 1.0,
    # p == 0 -> the whole update is a bitwise no-op (see module doc)
    alpha = jnp.exp(m - m_new)
    p = jnp.where(live, jnp.exp(lg - m_new), 0.0)
    l_new = l * alpha + jnp.sum(p, -1, keepdims=True)
    pv = jax.lax.dot_general(
        p, v_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (S, D)
    return (m_new, l_new, acc * alpha + pv)


def _fold_block_heads(q_slabs, kpage, vpage, live, states, hkv, g, d):
    """One KV page folded into every (h, g) head state. kpage/vpage:
    (blk, Hkv*D) f32; q_slabs[hg]: (S, D) f32 pre-scaled."""
    out = []
    for h in range(hkv):
        k_h = kpage[:, h * d:(h + 1) * d]
        v_h = vpage[:, h * d:(h + 1) * d]
        for gg in range(g):
            hg = h * g + gg
            out.append(_head_update(q_slabs[hg], k_h, v_h, live,
                                    states[hg]))
    return out


def _init_states(hq: int, s: int, d: int):
    return [
        (jnp.full((s, 1), NEG_INF, jnp.float32),
         jnp.zeros((s, 1), jnp.float32),
         jnp.zeros((s, d), jnp.float32))
        for _ in range(hq)
    ]


def _finalize(states):
    """(S, Hq*D) f32 output from the per-head states (empty rows -> 0)."""
    outs = []
    for m, l, acc in states:
        empty = l <= 0.0
        outs.append(jnp.where(empty, 0.0, acc / jnp.maximum(l, 1e-30)))
    return jnp.concatenate(outs, axis=-1)


def _q_slabs(qf, hq: int, d: int, scale: float):
    qs = qf.astype(jnp.float32) * scale  # (S, Hq*D)
    return [qs[:, hg * d:(hg + 1) * d] for hg in range(hq)]


# -- local kernel (n = 1 core; serves blockwise prefill + serve chunks) ------


def _fp_local_kernel(hq, hkv, d, s, t, blk, causal, scale,
                     len_ref, q_ref, qpos_ref, k_ref, v_ref, o_ref,
                     vkv, sems):
    """One grid step = one batch row: stream (blk, Hkv*D) KV pages
    double-buffered from HBM and fold each into the per-head online-
    softmax states (the prefill generalization of
    flash_decode._fd_partial_kernel: S query rows instead of 1, per-head
    2-D matmuls instead of the block-diagonal operand — prefill is
    MXU-bound, so the decode kernel's Hkv-times FLOP inflation is not
    free here)."""
    b = pl.program_id(0)
    g = hq // hkv
    nblk = t // blk
    valid = len_ref[b]

    def kv_start(ci, slot):
        for which, ref in ((0, k_ref), (1, v_ref)):
            pltpu.make_async_copy(
                ref.at[b, pl.ds(ci * blk, blk)], vkv.at[slot, which],
                sems.at[slot],
            ).start()

    def kv_wait(slot):
        for which, ref in ((0, k_ref), (1, v_ref)):
            pltpu.make_async_copy(
                ref.at[0, pl.ds(0, blk)], vkv.at[slot, which],
                sems.at[slot],
            ).wait()

    qp_col = qpos_ref[0]  # (S, 1) — pre-shaped by the host wrapper
    slabs = _q_slabs(q_ref[0], hq, d, scale)

    # dead-page skip: pages past kv_len — and, causally, past the last
    # q row — fold as bitwise no-ops, so skipping them changes nothing
    hi = valid
    if causal:
        hi = jnp.minimum(hi, jnp.max(qp_col) + 1)
    n_act = jnp.minimum(cdiv(hi, blk), nblk)

    def loop_body(ci, states):
        @pl.when(ci + 1 < n_act)
        def _ahead():
            kv_start(ci + 1, (ci + 1) % 2)

        kv_wait(ci % 2)
        kv = vkv[ci % 2].astype(jnp.float32)  # (2, blk, W)
        live = _block_live(s, blk, ci * blk, qp_col, valid, causal)
        return tuple(_fold_block_heads(slabs, kv[0], kv[1], live,
                                       list(states), hkv, g, d))

    @pl.when(n_act > 0)
    def _first():
        kv_start(0, 0)

    states = jax.lax.fori_loop(0, n_act, loop_body,
                               tuple(_init_states(hq, s, d)))
    o_ref[0] = _finalize(list(states)).astype(o_ref.dtype)


# Trace-time record of the most recent flash_prefill_local lowering —
# the fitted KV page height and grid (last_regime()/last_launch()
# idiom): tests pin that a tune-cache attn_block changes the launched
# fold without reverse-engineering the jaxpr.
_last_launch = None


def last_launch():
    return _last_launch


def flash_prefill_local(
    q: jax.Array,  # (B, S, Hq, D)
    k: jax.Array,  # (B, T, Hkv, D)
    v: jax.Array,
    q_positions: Optional[jax.Array] = None,  # (B, S) absolute positions
    q_offset=0,
    kv_len: Optional[jax.Array] = None,  # (B,) valid KV prefix
    causal: bool = True,
    scale: Optional[float] = None,
    block: Optional[int] = None,
) -> jax.Array:
    """Pallas blockwise (flash) GQA prefill over local KV: same contract
    as layers.attention.gqa_attention_blockwise, but KV streams through
    double-buffered (block, Hkv*D) pages so the (S, T) logits tensor
    never exists — peak memory O(S*block). Returns (B, S, Hq, D) in
    q.dtype."""
    global _last_launch
    b, s, hq, d = q.shape
    _, t, hkv, _ = k.shape
    w = hkv * d
    scale = float(scale if scale is not None else d ** -0.5)
    blk = int(block or _kv_block(t))
    _last_launch = {"kernel": "flash_prefill", "path": "local",
                    "block": blk, "grid": (b,),
                    "overridden": block is not None}
    t_valid = t
    if t % blk:
        pad = blk - t % blk
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
        t += pad
    len_arr = (jnp.full((b,), t_valid, jnp.int32) if kv_len is None
               else jnp.minimum(jnp.reshape(kv_len, (-1,)),
                                t_valid).astype(jnp.int32))
    if q_positions is None:
        q_positions = jnp.broadcast_to(
            jnp.arange(s)[None, :] + q_offset, (b, s))
    # column-shaped on the host: the kernel consumes (S, 1) directly
    # (no in-kernel minor-dim reshape for Mosaic to lower)
    qpos = q_positions.astype(jnp.int32).reshape(b, s, 1)
    itemsize = jnp.dtype(k.dtype).itemsize
    state_bytes = 5 * s * hq * d * 4  # q slab + acc/m/l states + out row
    out = tpu_call(
        functools.partial(_fp_local_kernel, hq, hkv, d, s, t, blk,
                          causal, scale),
        grid=(b,),
        out_shape=jax.ShapeDtypeStruct((b, s, hq * d), q.dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, s, hq * d), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, s, 1), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, s, hq * d), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, 2, blk, w), k.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=compiler_params(
            vmem_limit_bytes=4 * 2 * blk * w * itemsize + state_bytes
            + (8 << 20),
        ),
        cost_estimate=cost_estimate(
            flops=4 * b * s * hq * t * d,
            bytes_accessed=2 * b * t * w * itemsize,
        ),
    )(len_arr, q.reshape(b, s, hq * d), qpos,
      k.reshape(b, t, w), v.reshape(b, t, w))
    return out.reshape(b, s, hq, d)


# -- SP kernel: per-segment-semaphore ring ingest + in-kernel consumer -------


def _fp_sp_kernel(axis, n, bsz, s, hq, hkv, d, blk, causal, scale,
                  straggler, build, gbuild, *refs):
    refs = list(refs)
    len_ref, q_ref, k_ref, v_ref, o_ref, kbuf, vbuf = refs[:7]
    del refs[:7]
    tbuf = refs.pop(0) if build is not None else None
    gbuf = refs.pop(0) if gbuild is not None else None
    gcur = refs.pop() if gbuild is not None else None
    tcur = refs.pop() if build is not None else None
    vkv, sems, send_sem, seg_sems = refs
    me = jax.lax.axis_index(axis)
    g = hq // hkv
    nblk = s // blk
    tctx = trace_ev.make_ctx(build, tbuf, tcur)
    trace_ev.init_ctx(tctx, rank=me)
    R = trace_ev.REGIONS
    gctx = _guard.make_ctx(gbuild, gbuf, gcur, tctx=tctx)
    _guard.init_ctx(gctx, rank=me)

    # peers must be inside the kernel before the segment puts land
    with _guard.attached(gctx):
        shmem.barrier_all(axis)
        shmem.fault_delay(axis, "flash_prefill")
    if straggler is not None:
        trace_ev.instant(
            tctx, R["straggle"],
            payload=jnp.where(me == straggler[0], straggler[1], 0))
        shmem.straggler_delay(axis, straggler[0], straggler[1])

    # LL-AG producer with exposed per-segment delivery semaphores: our
    # shard flies to every peer while we fold the local block — the
    # copy-engine AG of the reference, with slot [t, i-1] counting
    # exactly segment-offset i's K (t=0) / V (t=1) arrival.
    handles = segment_collect_start(
        lambda t_i, i: (kbuf, vbuf)[t_i].at[i - 1],
        (k_ref, v_ref), send_sem,
        lambda t_i, i: seg_sems.at[t_i, i - 1], axis, n,
        on_send=lambda i: trace_ev.instant(tctx, R["fp.send"],
                                           payload=i),
    )

    qp_base = jax.lax.broadcasted_iota(jnp.int32, (s, 1), 0) + me * s
    slabs = {bi: _q_slabs(q_ref[bi], hq, d, scale) for bi in range(bsz)}
    states = {bi: _init_states(hq, s, d) for bi in range(bsz)}

    def fold_segment(seg_base, k_at, v_at):
        """Stream one segment's (bi, page) blocks double-buffered and
        fold each into every head state of its batch row."""
        pairs = [(bi, j) for bi in range(bsz) for j in range(nblk)]

        def start(idx, slot):
            bi, j = pairs[idx]
            for which, at in ((0, k_at), (1, v_at)):
                pltpu.make_async_copy(
                    at(bi, pl.ds(j * blk, blk)), vkv.at[slot, which],
                    sems.at[slot],
                ).start()

        def wait(slot):
            for which, at in ((0, k_at), (1, v_at)):
                pltpu.make_async_copy(
                    at(0, pl.ds(0, blk)), vkv.at[slot, which],
                    sems.at[slot],
                ).wait()

        start(0, 0)
        for idx, (bi, j) in enumerate(pairs):
            if idx + 1 < len(pairs):
                start(idx + 1, (idx + 1) % 2)
            wait(idx % 2)
            kv = vkv[idx % 2].astype(jnp.float32)
            live = _block_live(s, blk, seg_base + j * blk, qp_base,
                               len_ref[bi], causal)
            states[bi] = _fold_block_heads(slabs[bi], kv[0], kv[1], live,
                                           states[bi], hkv, g, d)

    # step 0: the rank-offset swizzle — fold the LOCAL block while the
    # segment puts are in flight (zero-wait start)
    with trace_ev.span(tctx, R["fp.fold"], payload=0):
        fold_segment(me * s,
                     lambda bi, ds: k_ref.at[bi, ds],
                     lambda bi, ds: v_ref.at[bi, ds])
    for i in range(1, n):
        # gate on exactly THIS segment's delivery (K then V — same slot
        # pair every rank's descriptor names for offset i), while
        # segments i+1.. are still in flight. Under a guard build each
        # gate is a bounded watchdog wait at site "recv" (slot = the
        # segment offset) — a dropped delivery becomes a guard row,
        # never a hang.
        _guard.set_progress(i, ctx=gctx)
        with trace_ev.span(tctx, R["fp.wait"], payload=i):
            with _guard.attached(gctx):
                for h in handles[i]:
                    h.wait_recv(slot=i)
        chunk = jax.lax.rem(me - i + n, n)
        with trace_ev.span(tctx, R["fp.fold"], payload=i):
            fold_segment(chunk * s,
                         lambda bi, ds, i=i: kbuf.at[i - 1, bi, ds],
                         lambda bi, ds, i=i: vbuf.at[i - 1, bi, ds])
    # drain outbound sends (semaphore balance: re-entrancy)
    for i in range(1, n):
        for h in handles[i]:
            h.wait_send()

    for bi in range(bsz):
        o_ref[bi] = _finalize(states[bi]).astype(o_ref.dtype)


def sp_flash_prefill(
    q: jax.Array,  # (B, S_loc, Hq, D)
    k: jax.Array,  # (B, S_loc, Hkv, D)
    v: jax.Array,
    axis: str = SP_AXIS,
    causal: bool = True,
    scale: Optional[float] = None,
    kv_len: Optional[jax.Array] = None,  # (B,) GLOBAL valid length
    block: Optional[int] = None,
    straggler=None,
):
    """SP flash prefill; per-device inside shard_map. Same contract as
    `sp_attention.ring_attention` (rank r holds Q rows and KV rows
    [r*S_loc, (r+1)*S_loc); returns (B, S_loc, Hq, D) attended over the
    full sharded sequence), but the KV exchange is the in-kernel
    per-segment-semaphore protocol instead of `lax.ppermute`.

    straggler: optional (rank, nanos) skew injection (stress/trace
    tests). Tracing (trace.building active): returns (out, trace_buf)
    on every path — fallbacks hand back an empty stream."""
    n = jax.lax.axis_size(axis)
    b, s, hq, d = q.shape
    hkv = k.shape[2]
    w = hkv * d
    scale = float(scale if scale is not None else d ** -0.5)
    build = trace_ev.active_build()
    gbuild = _guard.active_build()
    straggler = _fplan.scheduled_straggler("flash_prefill", straggler)
    # segments cannot pad (padding would shift global KV positions), so
    # a requested block is re-fitted to the divisor rule (fit_block) —
    # the same rule the autotuner's pruner models and flash_prefill_ref
    # replays. Fitted BEFORE the n==1 dispatch: the world=1 path must
    # fold the same page granularity as the replay
    # (flash_prefill_local would otherwise pad a non-dividing block)
    blk = fit_block(s, block)
    assert s % blk == 0, f"block {blk} must divide S_loc {s}"
    if n == 1:
        out = flash_prefill_local(q, k, v, kv_len=kv_len, causal=causal,
                                  scale=scale, block=blk)
        return _guard.with_guard(gbuild, trace_ev.with_trace(build, out))
    if interpret_no_headroom():
        from triton_dist_tpu.kernels.sp_attention import ring_attention

        return _guard.with_guard(gbuild, trace_ev.with_trace(
            build, ring_attention(
                q, k, v, axis, causal=causal, scale=scale,
                kv_len=kv_len)))
    len_arr = (jnp.full((b,), n * s, jnp.int32) if kv_len is None
               else jnp.reshape(kv_len, (-1,)).astype(jnp.int32))
    itemsize = jnp.dtype(k.dtype).itemsize
    k2 = k.reshape(b, s, w)
    v2 = v.reshape(b, s, w)
    out_shape = (
        jax.ShapeDtypeStruct((b, s, hq * d), q.dtype),
        jax.ShapeDtypeStruct((n - 1, b, s, w), k.dtype),  # gather bufs
        jax.ShapeDtypeStruct((n - 1, b, s, w), v.dtype),
    )
    out_specs = (
        pl.BlockSpec(memory_space=pltpu.VMEM),
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    )
    scratch = [
        pltpu.VMEM((2, 2, blk, w), k.dtype),
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA,
        pltpu.SemaphoreType.DMA((2, n - 1)),
    ]
    if build is not None:
        out_shape += (trace_ev.out_shape(build),)
        out_specs += (trace_ev.out_spec(),)
        scratch.append(trace_ev.cursor_scratch())
    if gbuild is not None:
        out_shape += (_guard.out_shape(gbuild),)
        out_specs += (_guard.out_spec(),)
        scratch.append(_guard.cursor_scratch())
    res = tpu_call(
        functools.partial(_fp_sp_kernel, axis, n, b, s, hq, hkv, d, blk,
                          causal, scale, straggler, build, gbuild),
        out_shape=out_shape,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=out_specs,
        scratch_shapes=scratch,
        compiler_params=compiler_params(
            has_side_effects=True,
            collective_id=next_collective_id(f"flash_prefill_{axis}"),
            vmem_limit_bytes=4 * 2 * blk * w * itemsize
            + b * (5 * s * hq * d) * 4 + (8 << 20),
        ),
        cost_estimate=cost_estimate(
            flops=4 * b * s * hq * n * s * d,
            bytes_accessed=2 * b * n * s * w * itemsize,
            remote_bytes=2 * b * (n - 1) * s * w * itemsize,
        ),
    )(len_arr, q.reshape(b, s, hq * d), k2, v2)
    out = res[0].reshape(b, s, hq, d)
    k_res = 3
    tbuf = res[k_res] if build is not None else None
    k_res += 1 if build is not None else 0
    gbuf = res[k_res] if gbuild is not None else None
    return _guard.with_guard(
        gbuild, trace_ev.with_trace(build, out, tbuf), gbuf)


def flash_prefill_ref(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis: str = SP_AXIS,
    causal: bool = True,
    scale: Optional[float] = None,
    kv_len: Optional[jax.Array] = None,
    block: Optional[int] = None,
) -> jax.Array:
    """Plain-transport replay of sp_flash_prefill: XLA all_gathers the
    KV shards, then folds segments in the SAME swizzle order through the
    SAME per-block `_head_update` op sequence. The overlapped kernel
    must be BIT-IDENTICAL to this — the per-segment semaphore protocol
    moves bytes, never bits (tests/test_flash_prefill.py pins it)."""
    n = jax.lax.axis_size(axis)
    me = jax.lax.axis_index(axis)
    b, s, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    w = hkv * d
    scale = float(scale if scale is not None else d ** -0.5)
    # same divisor re-fit as sp_flash_prefill — the replay must fold at
    # exactly the kernel's page granularity to stay bit-identical
    blk = fit_block(s, block)
    len_arr = (jnp.full((b,), n * s, jnp.int32) if kv_len is None
               else jnp.reshape(kv_len, (-1,)).astype(jnp.int32))
    k_full = jax.lax.all_gather(k, axis)  # (n, B, S, Hkv, D)
    v_full = jax.lax.all_gather(v, axis)
    nblk = s // blk
    outs = []
    for bi in range(b):
        slabs = _q_slabs(q[bi].reshape(s, hq * d), hq, d, scale)
        qp_col = jax.lax.broadcasted_iota(jnp.int32, (s, 1), 0) + me * s
        states = _init_states(hq, s, d)
        for i in range(n):
            chunk = jax.lax.rem(me - i + n, n)
            kseg = jax.lax.dynamic_index_in_dim(
                k_full, chunk, 0, keepdims=False)[bi].reshape(s, w)
            vseg = jax.lax.dynamic_index_in_dim(
                v_full, chunk, 0, keepdims=False)[bi].reshape(s, w)
            for j in range(nblk):
                kpage = kseg[j * blk:(j + 1) * blk].astype(jnp.float32)
                vpage = vseg[j * blk:(j + 1) * blk].astype(jnp.float32)
                live = _block_live(s, blk, chunk * s + j * blk, qp_col,
                                   len_arr[bi], causal)
                states = _fold_block_heads(slabs, kpage, vpage, live,
                                           states, hkv, g, d)
        outs.append(_finalize(states))
    return jnp.stack(outs).reshape(b, s, hq, d).astype(q.dtype)


# -- the autotuner-selectable switch -----------------------------------------


def sp_prefill_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis: str = SP_AXIS,
    causal: bool = True,
    scale: Optional[float] = None,
    kv_len: Optional[jax.Array] = None,
    impl: str = "auto",
    config: Optional[FlashPrefillConfig] = None,
):
    """SP prefill with impl selection: "flash" (this module's
    per-segment-semaphore kernel), "ring" (`sp_attention.ring_attention`,
    the XLA-overlap fallback — always available), or "auto" (the
    perf-model pick, `perf_model.choose_sp_prefill_impl`, gated on
    native-TPU shape support). The layers' blockwise prefill and the
    serve prefill-chunk path ride the same switch through
    `layers.attention.gqa_attention`."""
    from triton_dist_tpu.kernels.sp_attention import ring_attention

    b, s, hq, d = q.shape
    hkv = k.shape[2]
    if impl == "auto":
        # same gate pair as the local auto path (_route_prefill_impl):
        # native shape support AND VMEM feasibility — the SP kernel
        # keeps EVERY batch row's state resident (batch=b), and the
        # ring fallback handles what does not fit
        if not (flash_prefill_native_ok(hq, hkv, d)
                and flash_prefill_fits(
                    s, s, hq, hkv, d,
                    block=config.block if config else None,
                    dtype=k.dtype, batch=b)):
            impl = "ring"
        else:
            from triton_dist_tpu.perf_model import choose_sp_prefill_impl

            n = jax.lax.axis_size(axis)
            impl = choose_sp_prefill_impl(s, n, hq, hkv, d, batch=b,
                                          dtype=k.dtype)
    if impl == "flash":
        return sp_flash_prefill(
            q, k, v, axis, causal=causal, scale=scale, kv_len=kv_len,
            block=config.block if config else None)
    if impl == "ring":
        out = ring_attention(q, k, v, axis, causal=causal, scale=scale,
                             kv_len=kv_len)
        return _guard.with_guard(
            _guard.active_build(),
            trace_ev.with_trace(trace_ev.active_build(), out))
    raise ValueError(f"unknown sp prefill impl {impl!r}")


# -- protocol model (static verifier, triton_dist_tpu.verify) ----------------

from triton_dist_tpu import verify as _v  # noqa: E402


@_v.protocol("flash_prefill",
             doc="SP flash prefill ingest: full-mesh segment push with "
                 "per-(tensor, offset) delivery slots; consumer folds "
                 "local at step 0 then gates each remaining segment on "
                 "exactly its own slot pair (_fp_sp_kernel)")
def _fp_protocol(n):
    """The producer is the SAME segment_collect_start the kernel calls
    (protocol and kernel evolve together); the consumer contract is the
    swizzle-order read sequence: local shard first (no wait — the
    zero-wait start), then segment offset i's gather slots strictly
    after BOTH its K and V delivery waits. Outbound sends drain at the
    end (semaphore balance = re-entrancy)."""
    k, v = _v.ref("k"), _v.ref("v")
    kbuf, vbuf = _v.ref("kbuf"), _v.ref("vbuf")
    send = _v.sem("send_sem")
    seg = _v.sem("seg_sems")
    shmem.barrier_all(SP_AXIS)
    handles = segment_collect_start(
        lambda t_i, i: (kbuf, vbuf)[t_i].at(i - 1),
        (k.at(), v.at()), send.at(),
        lambda t_i, i: seg.at(t_i, i - 1), SP_AXIS, n,
    )
    _v.read(k.at())  # zero-wait local fold
    _v.read(v.at())
    for i in range(1, n):
        for h in handles[i]:
            h.wait_recv()
        _v.read(kbuf.at(i - 1))  # fold segment offset i
        _v.read(vbuf.at(i - 1))
    for i in range(1, n):
        for h in handles[i]:
            h.wait_send()


# -- conformance runner (verify.conform) --------------------------------------

from jax.sharding import PartitionSpec as _P  # noqa: E402

from triton_dist_tpu.verify import conform as _conform  # noqa: E402


@_conform.conforms(
    "flash_prefill",
    grids=((4, {}),),
    doc="ring-rotated KV flash prefill on the interpret mesh")
def _fp_conform(n):
    mesh = _conform.team_mesh(n, (SP_AXIS,))
    if isinstance(mesh, _conform.Skip):
        return mesh
    q = jnp.ones((1, 8, 1, 128), jnp.float32)
    k = jnp.ones((1, 8, 1, 128), jnp.float32)
    v = jnp.ones((1, 8, 1, 128), jnp.float32)
    return _conform.collect_streams(
        mesh, SP_AXIS,
        lambda q_, k_, v_: sp_flash_prefill(q_, k_, v_, SP_AXIS),
        in_specs=(_P(), _P(), _P()), args=(q, k, v))
