"""MoE routing utilities — topk routing + expert-aligned token sort.

TPU-native re-design of the reference's MoE utils
(ref: python/triton_dist/kernels/nvidia/moe_utils.py:1-405 topk
reduce/histogram; csrc/lib/moe_utils.cu:61-165
`moe_ag_scatter_align_block_size`, the CUDA kernel building the sorted
token->block mapping). On TPU the alignment problem disappears:
`lax.ragged_dot` takes contiguous group sizes directly, so the "align to
GEMM block size" native op reduces to a stable argsort by expert id +
bincount — static shapes, no atomics, fully fused by XLA.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


def topk_routing(
    router_logits: jax.Array,  # (M, E) f32
    k: int,
    normalize: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Softmax-then-topk router (Qwen3MoE's norm_topk_prob convention,
    ref: models/qwen_moe.py:50-206). Returns (weights (M, k) f32,
    ids (M, k) int32)."""
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    weights, ids = jax.lax.top_k(probs, k)
    if normalize:
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
    return weights, ids.astype(jnp.int32)


def expert_histogram(topk_ids: jax.Array, n_experts: int) -> jax.Array:
    """Tokens per expert (the reference's triton bincount,
    ref: kernels/nvidia/ep_a2a.py:310-336)."""
    return jnp.bincount(topk_ids.reshape(-1), length=n_experts).astype(
        jnp.int32
    )


class ExpertSort(NamedTuple):
    """Sorted (token, choice) pairs grouped by expert — the align-block-
    size output analog (ref: csrc/lib/moe_utils.cu:61-165)."""

    sort_idx: jax.Array  # (M*k,) flat position -> original flat (tok*k+j)
    token_idx: jax.Array  # (M*k,) source token row per sorted position
    group_sizes: jax.Array  # (E,) tokens per expert, sorted-order segments
    unsort_idx: jax.Array  # (M*k,) original flat -> sorted position


def sort_by_expert(topk_ids: jax.Array, n_experts: int) -> ExpertSort:
    """Stable sort of the (M, k) routing table by expert id."""
    m, k = topk_ids.shape
    flat = topk_ids.reshape(-1)
    sort_idx = jnp.argsort(flat, stable=True).astype(jnp.int32)
    group_sizes = expert_histogram(topk_ids, n_experts)
    unsort_idx = jnp.argsort(sort_idx, stable=True).astype(jnp.int32)
    token_idx = (sort_idx // k).astype(jnp.int32)
    return ExpertSort(sort_idx, token_idx, group_sizes, unsort_idx)


def combine_topk(
    y_sorted: jax.Array,  # (M*k, H) expert outputs in sorted order
    sort: ExpertSort,
    topk_weights: jax.Array,  # (M, k) f32
) -> jax.Array:
    """Unsort + weighted sum over the k choices -> (M, H) f32
    (the reference's topk-reduce, moe_reduce_rs.py:293-488)."""
    m, k = topk_weights.shape
    y_flat = y_sorted[sort.unsort_idx]  # (M*k, H) original order
    y_flat = y_flat.reshape(m, k, -1).astype(jnp.float32)
    return jnp.einsum("mkh,mk->mh", y_flat, topk_weights)
