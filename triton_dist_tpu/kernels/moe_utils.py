"""MoE routing utilities — topk routing + expert-aligned token sort.

TPU-native re-design of the reference's MoE utils
(ref: python/triton_dist/kernels/nvidia/moe_utils.py:1-405 topk
reduce/histogram; csrc/lib/moe_utils.cu:61-165
`moe_ag_scatter_align_block_size`, the CUDA kernel building the sorted
token->block mapping). On TPU the alignment problem disappears:
`lax.ragged_dot` takes contiguous group sizes directly, so the "align to
GEMM block size" native op reduces to a stable argsort by expert id +
bincount — static shapes, no atomics, fully fused by XLA.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


def topk_routing(
    router_logits: jax.Array,  # (M, E) f32
    k: int,
    normalize: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Softmax-then-topk router (Qwen3MoE's norm_topk_prob convention,
    ref: models/qwen_moe.py:50-206). Returns (weights (M, k) f32,
    ids (M, k) int32)."""
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    weights, ids = jax.lax.top_k(probs, k)
    if normalize:
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
    return weights, ids.astype(jnp.int32)


def silu_mul(h: jax.Array) -> jax.Array:
    """silu(gate) * up over a fused (…, 2I) gate_up projection, in f32 —
    the FFN epilogue shared by the TP-MoE layer and the EP expert FFNs
    (sequential and chunk-pipelined paths must share ONE implementation:
    the overlap parity tests compare their outputs bitwise)."""
    gate, up = jnp.split(h.astype(jnp.float32), 2, axis=-1)
    return jax.nn.silu(gate) * up


def expert_histogram(topk_ids: jax.Array, n_experts: int) -> jax.Array:
    """Tokens per expert (the reference's triton bincount,
    ref: kernels/nvidia/ep_a2a.py:310-336)."""
    return jnp.bincount(topk_ids.reshape(-1), length=n_experts).astype(
        jnp.int32
    )


class ExpertSort(NamedTuple):
    """Sorted (token, choice) pairs grouped by expert — the align-block-
    size output analog (ref: csrc/lib/moe_utils.cu:61-165)."""

    sort_idx: jax.Array  # (M*k,) flat position -> original flat (tok*k+j)
    token_idx: jax.Array  # (M*k,) source token row per sorted position
    group_sizes: jax.Array  # (E,) tokens per expert, sorted-order segments
    unsort_idx: jax.Array  # (M*k,) original flat -> sorted position


def sort_by_expert(topk_ids: jax.Array, n_experts: int) -> ExpertSort:
    """Stable sort of the (M, k) routing table by expert id."""
    m, k = topk_ids.shape
    flat = topk_ids.reshape(-1)
    sort_idx = jnp.argsort(flat, stable=True).astype(jnp.int32)
    group_sizes = expert_histogram(topk_ids, n_experts)
    unsort_idx = jnp.argsort(sort_idx, stable=True).astype(jnp.int32)
    token_idx = (sort_idx // k).astype(jnp.int32)
    return ExpertSort(sort_idx, token_idx, group_sizes, unsort_idx)


class ExpertPack(NamedTuple):
    """Local tokens packed into fixed-capacity per-expert blocks — the
    static-shape MXU formulation of the reference's sorted ragged layout
    (ref: kernels/nvidia/allgather_group_gemm.py:85-199 sorted gather
    index). Capacity-padded blocks trade pad FLOPs for fully static
    tiles; overflow beyond `capacity` rows per expert is dropped (GShard
    trade, same as kernels/ep_a2a.py — `drops` counts them)."""

    x: jax.Array           # (E * cap, H) tokens grouped by expert
    slot_of: jax.Array     # (M, k) flat slot e*cap+p per choice, -1=drop
    counts: jax.Array      # (E,) tokens per expert (clamped to cap)
    drops: jax.Array       # () int32 overflow rows dropped


def pack_by_expert(
    x: jax.Array,          # (M, H)
    topk_ids: jax.Array,   # (M, k)
    n_experts: int,
    capacity: int,
) -> ExpertPack:
    """Gather-formulated fixed-capacity pack (one dense gather, no
    row-scatter — see kernels/ep_a2a.py `_pack_by_dest` for why scatter
    is serial on TPU). Slot (e, p) takes the p-th (token, choice) pair
    routed to expert e in stable token order; `slot_of` is the inverse
    map (also gather-built, via the double argsort), which lets the
    combine read expert outputs back with one dense gather."""
    m, k = topk_ids.shape
    c = capacity
    flat_ids = topk_ids.reshape(-1)
    order = jnp.argsort(flat_ids, stable=True)
    inv_order = jnp.argsort(order, stable=True)
    seg_count = jnp.bincount(flat_ids, length=n_experts)
    seg_start = jnp.cumsum(seg_count) - seg_count

    slot_e = (jnp.arange(n_experts * c) // c).astype(jnp.int32)
    slot_p = (jnp.arange(n_experts * c) % c).astype(jnp.int32)
    valid = slot_p < jnp.minimum(seg_count, c)[slot_e]
    entry = order[jnp.minimum(seg_start[slot_e] + slot_p, m * k - 1)]
    tok = jnp.where(valid, (entry // k).astype(jnp.int32), 0)
    xp = jnp.where(valid[:, None], x[tok], jnp.zeros((), x.dtype))

    # inverse map: choice f sits at within-expert position
    # inv_order[f] - seg_start[expert(f)]; beyond capacity -> dropped
    p_of = inv_order - seg_start[flat_ids]
    slot_of = jnp.where(
        p_of < c, flat_ids * c + p_of, -1
    ).astype(jnp.int32).reshape(m, k)
    drops = jnp.sum(jnp.maximum(seg_count - c, 0)).astype(jnp.int32)
    return ExpertPack(
        x=xp,
        slot_of=slot_of,
        counts=jnp.minimum(seg_count, c).astype(jnp.int32),
        drops=drops,
    )


def chunk_group_sizes(
    expert_counts: jax.Array,  # (n, E) valid rows per (segment, expert)
    capacity: int,
    lo: int,
    rows: int,
) -> jax.Array:
    """Expert-group sizes of one capacity chunk of an expert-sorted
    dispatch buffer — the per-chunk sort/segment metadata of the
    chunk-pipelined EP MoE (kernels/ep_a2a.py).

    Each received segment is expert-sorted with its invalid slots packed
    at the tail (ep_a2a._pack_by_dest expert_sorted=True), so segment
    j's group boundaries are the running sums of expert_counts[j]
    followed by `capacity` for the trailing null group. The chunk
    [lo, lo+rows) intersects each group as
    clip(b[e+1]) - clip(b[e]); returns (n, E+1) int32 summing to `rows`
    per segment (last column = null/invalid rows — callers mask them)."""
    n, e = expert_counts.shape
    bounds = jnp.concatenate(
        [
            jnp.zeros((n, 1), jnp.int32),
            jnp.cumsum(expert_counts.astype(jnp.int32), axis=1),
            jnp.full((n, 1), capacity, jnp.int32),
        ],
        axis=1,
    )  # (n, E+2): [0, cs_1..cs_E, capacity]
    clipped = jnp.clip(bounds, lo, lo + rows)
    return (clipped[:, 1:] - clipped[:, :-1]).astype(jnp.int32)


def combine_topk(
    y_sorted: jax.Array,  # (M*k, H) expert outputs in sorted order
    sort: ExpertSort,
    topk_weights: jax.Array,  # (M, k) f32
) -> jax.Array:
    """Unsort + weighted sum over the k choices -> (M, H) f32
    (the reference's topk-reduce, moe_reduce_rs.py:293-488)."""
    m, k = topk_weights.shape
    y_flat = y_sorted[sort.unsort_idx]  # (M*k, H) original order
    y_flat = y_flat.reshape(m, k, -1).astype(jnp.float32)
    return jnp.einsum("mkh,mk->mh", y_flat, topk_weights)
