"""Low-latency allgather for small messages — barrier-free steady state.

TPU-native re-design of the reference's LL fast allgather
(ref: python/triton_dist/kernels/nvidia/low_latency_allgather.py:530-607
`_pack_ll_block`/`_recv_ll_block` — LAMPORT-style 8-byte flag-in-data
packing so the receiver validates payload arrival without a separate
signal round-trip; context `FastAllGatherContext` :781).

On TPU the DMA delivery semaphore IS the flag: it is updated by the same
hardware transaction that writes the payload, so flag-in-data packing is
obviated. What the LL design still contributes — and what this kernel
keeps — is the *barrier-free steady state* via double buffering:

  - the destination is a persistent (2, n, ...) context buffer; call k
    uses slot parity k%2;
  - each parity has its own recv semaphore (recv_sems[parity]): a
    semaphore increment can never be attributed to the wrong call,
    because call k+2 (same parity) on any peer is gated behind that
    peer's call k+1 wait, which is gated behind OUR call-k consume —
    exactly the flag-validation ordering of the LL protocol, carried by
    semaphore counting instead of flag words (the `call_count % 2`
    double buffer of the reference, low_latency_all_to_all.py:36-118);
  - only the FIRST call on a fresh context barriers the team (the
    reference syncs at context creation).

Use for latency-class payloads (flash-decode partials, splits metadata).
Bandwidth-class payloads want the ring/2-axis kernels in allgather.py.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.faults import guard as _guard
from triton_dist_tpu.lang import shmem
from triton_dist_tpu.obs import stats as _obs
from triton_dist_tpu.verify import capture as _vcap
from triton_dist_tpu.lang.core import (
    compiler_params,
    interpret_no_headroom,
    next_collective_id,
    tpu_call,
)
from triton_dist_tpu.runtime.init import TP_AXIS
from triton_dist_tpu.wire import codec as wcodec


def create_ll_ag_buffer(x_shape, dtype, n: int,
                        wire_format=None) -> jax.Array:
    """Persistent per-device context buffer (2 parities × n slots), the
    FastAllGatherContext analog. Thread it through calls (it is donated /
    aliased by the kernel). With a quantized wire_format the context
    holds the int8 wire image per slot (the parity protocol is
    format-invariant — only the slot byte shape changes)."""
    fmt = wcodec.resolve(wire_format)
    if not wcodec.is_native(fmt):
        import math

        rows = x_shape[0]
        kw = wcodec.wire_cols(math.prod(x_shape[1:]), fmt)
        return jnp.zeros((2, n, rows, kw), jnp.int8)
    return jnp.zeros((2, n) + tuple(x_shape), dtype)


def _ll_ag_kernel(axis: str, n: int, gbuild, obuild, fmtc, flags_ref,
                  x_ref, buf_in, buf_out, *refs):
    refs = list(refs)
    # outputs precede scratch: gbuf/obuf follow buf_out, the obs/guard
    # cursors are the trailing scratch entries
    gbuf = refs.pop(0) if gbuild is not None else None
    obuf = refs.pop(0) if obuild is not None else None
    ocur = refs.pop() if obuild is not None else None
    gcur = refs.pop() if gbuild is not None else None
    send_sem, recv_sems, local_sem = refs
    parity = flags_ref[0]
    first = flags_ref[1]
    del buf_in  # aliased: access through buf_out

    me = shmem.my_pe(axis)
    octx = _obs.make_ctx(obuild, obuf, ocur)
    _obs.init_ctx(octx, rank=me, fmt=fmtc)
    gctx = _guard.make_ctx(gbuild, gbuf, gcur, octx=octx)
    _guard.init_ctx(gctx, rank=me)
    with _guard.attached(gctx), _obs.attached(octx):
        @pl.when(first == 1)
        def _():
            # fresh context: peers must be inside the kernel before the
            # first puts land (afterwards the parity protocol orders
            # everything)
            shmem.barrier_all(axis)

        shmem.fault_delay(axis, "low_latency_allgather")
        shmem.fcollect_slots(
            lambda pe: buf_out.at[parity, pe], x_ref,
            local_sem, send_sem, recv_sems.at[parity], axis, n,
        )


def ll_all_gather(
    x: jax.Array,
    buf: jax.Array,
    call_count,
    axis: str = TP_AXIS,
    first=None,
    wire_format=None,
) -> Tuple[jax.Array, jax.Array]:
    """Small-message AG: returns (gathered (n,)+x.shape, new buf).

    Per-device inside shard_map. `call_count` is the 0-based call index
    on this context buffer (python int or traced scalar); the FIRST call
    on a fresh context performs the one-time entry barrier — by default
    call 0, overridable via `first` (bool/scalar) when the caller manages
    context lifetime separately from the call counter (ll_all_gather_op).
    The context must not be shared by two in-flight collectives.

    wire_format: quantized formats push the block-scaled wire image
    through the SAME parity protocol (the context must have been created
    with the same format — create_ll_ag_buffer(wire_format=...)); every
    slot including the rank's own passes the codec, so the gathered
    result is the pack/unpack roundtrip of the shards.

    Guarding (faults.guard.building active): one extra trailing output —
    the kernel's guard buffer (bounded-watchdog trip rows; empty stream
    on the fallback paths) — which the caller feeds to guard.check."""
    n = jax.lax.axis_size(axis)
    fmt = wcodec.resolve(wire_format)
    wire = not wcodec.is_native(fmt)
    gbuild = _guard.active_build()
    obuild = _obs.active_build()

    def with_builds(res, gbuf=None, obuf=None):
        if obuild is not None and obuf is None:
            obuf = _obs.new_stream(obuild, fmt=_obs.fmt_code(fmt))
        return _obs.with_stats(
            obuild, _guard.with_guard(gbuild, res, gbuf), obuf)

    def decode(slots):
        # (n, rows, kw) wire slots -> (n,) + x.shape in x.dtype
        if not wire:
            return slots
        flat = slots.reshape(n * slots.shape[1], slots.shape[2])
        return wcodec.unpack(flat, x.shape[1:], fmt, x.dtype).reshape(
            (n,) + x.shape)

    if n == 1:
        out = wcodec.roundtrip(x, fmt)[None] if wire else x[None]
        return with_builds((out, buf))
    xw = wcodec.pack(x, fmt)
    if interpret_no_headroom():
        return with_builds((decode(jax.lax.all_gather(xw, axis)), buf))

    call_count = jnp.asarray(call_count, jnp.int32)
    if first is None:
        first = call_count == 0
    flags = jnp.stack([
        jnp.asarray(call_count % 2, jnp.int32),
        jnp.asarray(first, jnp.int32),
    ])
    res = _ll_ag_call(flags, xw, buf, call_count % 2, axis, n, gbuild,
                      obuild, _obs.fmt_code(fmt))
    out, buf = res[:2]
    k_res = 2
    gbuf = res[k_res] if gbuild is not None else None
    k_res += 1 if gbuild is not None else 0
    obuf = res[k_res] if obuild is not None else None
    if gbuild is not None and wire and fmt.checksum:
        # detect-and-record consume edge: a corrupted slot becomes a
        # wire guard row the host raises on (WireIntegrityError via
        # guard.check) instead of dequantizing garbage silently
        import math as _math

        flat = out.reshape(n * out.shape[1], out.shape[2])
        ok = jnp.all(wcodec.verify_rows(
            flat, _math.prod(x.shape[1:]), fmt))
        gbuf = _guard.stream_trip(gbuf, ok)
    return with_builds((decode(out), buf), gbuf, obuf)


def _ll_ag_call(flags, x, buf, parity, axis, n, gbuild=None,
                obuild=None, fmtc=0):
    kernel = functools.partial(_ll_ag_kernel, axis, n, gbuild, obuild,
                               fmtc)
    out_shape = (jax.ShapeDtypeStruct(buf.shape, buf.dtype),)
    out_specs = (pl.BlockSpec(memory_space=pl.ANY),)
    scratch = [
        pltpu.SemaphoreType.DMA,
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA,
    ]
    if gbuild is not None:
        # explicit block shape: PrefetchScalarGridSpec does not accept
        # the shapeless SMEM spec the gridless kernels use
        out_shape += (_guard.out_shape(gbuild),)
        out_specs += (pl.BlockSpec(
            (1 + gbuild.cap, _guard.GUARD_WORDS),
            lambda i, *_: (0, 0),  # *_: the scalar-prefetch operand
            memory_space=pltpu.SMEM),)
        scratch.append(_guard.cursor_scratch())
    if obuild is not None:
        out_shape += (_obs.out_shape(obuild),)
        out_specs += (pl.BlockSpec(
            (1, _obs.STAT_WORDS),
            lambda i, *_: (0, 0),
            memory_space=pltpu.SMEM),)
        scratch.append(_obs.cursor_scratch())
    single = len(out_shape) == 1
    res = tpu_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(1,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
            out_specs=out_specs[0] if single else out_specs,
            scratch_shapes=scratch,
        ),
        out_shape=out_shape[0] if single else out_shape,
        input_output_aliases={2: 0},
        compiler_params=compiler_params(
            has_side_effects=True,
            collective_id=next_collective_id(f"ll_ag_{axis}"),
        ),
    )(flags, x, buf)
    res = res if isinstance(res, tuple) else (res,)
    buf = res[0]
    out = jax.lax.dynamic_index_in_dim(buf, parity, 0, keepdims=False)
    return (out, buf) + tuple(res[1:])


@functools.lru_cache(maxsize=None)
def _ll_op_fn(mesh, axis: str, fmt=None, gbuild=None,
              metered: bool = False):
    """Cached jitted executable per (mesh, axis, wire format, guard
    build): call_count and the fresh-context flag ride as traced
    arguments, so every decode step replays one compiled program (a
    fresh closure per call would retrace — the opposite of
    low-latency). An active guard build is part of the cache key — its
    executable has a different output tree (the trailing guard buffer)
    and must never be served to unguarded callers (or vice versa)."""
    from jax.sharding import PartitionSpec as P

    def per_device(x_shard, buf_shard, cc, first):
        with _guard.building(gbuild.cap, gbuild.deadline) if gbuild \
                else contextlib.nullcontext(), \
                _obs.building() if metered else contextlib.nullcontext():
            res = ll_all_gather(x_shard, buf_shard[0], cc, axis,
                                first=first, wire_format=fmt)
        out, new_buf = res[:2]
        return (out, new_buf[None]) + tuple(b[None] for b in res[2:])

    out_specs = (P(None, axis), P(axis))
    out_specs += (P(axis),) * ((gbuild is not None) + bool(metered))
    return jax.jit(
        jax.shard_map(
            per_device, mesh=mesh,
            in_specs=(P(axis), P(axis), P(), P()),
            out_specs=out_specs,
            check_vma=False,
        ),
        donate_argnums=(1,),
    )


@functools.lru_cache(maxsize=None)
def _ll_xla_fn(mesh, axis: str, fmt=None):
    """The degraded route: plain XLA all_gather of the (packed) shard —
    identical output contract (and wire fidelity) to the LL kernel,
    no Pallas protocol to hang. Collective entry points route here
    once a guard trip degraded the protocol (fallback="xla")."""
    from jax.sharding import PartitionSpec as P

    f = wcodec.resolve(fmt)

    def per_device(x_shard):
        xw = wcodec.pack(x_shard, f)
        g = jax.lax.all_gather(xw, axis)
        if wcodec.is_native(f):
            return g
        n = jax.lax.axis_size(axis)
        flat = g.reshape(n * g.shape[1], g.shape[2])
        return wcodec.unpack(flat, x_shard.shape[1:], f,
                             x_shard.dtype).reshape((n,) + x_shard.shape)

    return jax.jit(
        jax.shard_map(per_device, mesh=mesh, in_specs=P(axis),
                      out_specs=P(None, axis), check_vma=False))


PROTOCOL_NAME = "low_latency_allgather"  # degradation-registry key


def ll_all_gather_op(
    x: jax.Array,
    workspace,
    call_count: int,
    mesh,
    axis: str = TP_AXIS,
    name: str = "ll_ag",
    wire_format=None,
    fallback=None,
):
    """Host-level LL allgather over a SymmetricWorkspace-owned context
    (the reference's FastAllGatherContext held by a layer context and
    reused across calls, low_latency_allgather.py:781 +
    runtime/symm_mem.SymmetricWorkspace). x is a GLOBAL array sharded
    P(axis); the context buffer persists inside `workspace` between jit
    invocations (donated in, aliased out, stored back via update()).
    wire_format: quantized contexts are namespaced per format (a
    format switch is a fresh context, with its entry barrier).

    fallback="xla" is the guard-tripped degradation route
    (docs/robustness.md): under an active guard build
    (faults.guard.building), a watchdog trip inside the kernel marks
    the protocol degraded and this call — and every later one — returns
    the plain XLA all_gather result instead of raising, so a degraded
    step completes rather than dies. Without fallback, a trip raises
    DeadlineExceeded with the decoded guard rows."""
    n = int(mesh.shape[axis])
    loc_rows = x.shape[0] // n
    fmt = wcodec.resolve(wire_format)
    if fallback not in (None, "xla"):
        raise ValueError(f"unknown fallback {fallback!r} (None or 'xla')")
    if fallback == "xla" and _guard.is_degraded(PROTOCOL_NAME):
        return _ll_xla_fn(mesh, axis, fmt)(x)
    if wcodec.is_native(fmt):
        local_shape = (2, n, loc_rows) + tuple(x.shape[1:])
        buf_dtype = x.dtype
    else:
        import math

        kw = wcodec.wire_cols(math.prod(x.shape[1:]), fmt)
        local_shape = (2, n, loc_rows, kw)
        buf_dtype = jnp.int8
        name = f"{name}.{fmt.kind}{fmt.block or ''}"
    # the entry barrier keys off CONTEXT creation, not call_count: a new
    # shape/name at a nonzero count still needs the one-time team sync
    fresh = not workspace.contains(name, local_shape, buf_dtype)
    buf = workspace.get(name, local_shape, buf_dtype)
    gbuild = _guard.active_build()
    obuild = _obs.active_build()
    res = _ll_op_fn(mesh, axis, fmt, gbuild, obuild is not None)(
        x, buf, jnp.asarray(call_count, jnp.int32),
        jnp.asarray(fresh, jnp.int32),
    )
    out, new_buf = res[:2]
    workspace.update(name, new_buf)
    if gbuild is None and obuild is None:
        return out
    import numpy as np

    if obuild is not None:
        _obs.consume_rows(res[-1], kernel=PROTOCOL_NAME)
    if gbuild is None:
        return out
    gout = res[2]
    trips = _guard.decode(
        np.asarray(gout).reshape(n, -1, _guard.GUARD_WORDS))
    if trips:
        if fallback == "xla":
            _guard.degrade(PROTOCOL_NAME)
            return _ll_xla_fn(mesh, axis, fmt)(x)
        _guard.check(np.asarray(gout).reshape(
            n, -1, _guard.GUARD_WORDS), context=PROTOCOL_NAME)
    return out


# -- per-segment-signalled producer (exposed delivery semaphores) ------------


def segment_collect_start(dst_slot_at, srcs, send_sem, seg_sem_at,
                          axis: str, n: int, on_send=None):
    """Full-mesh segment push with EXPOSED per-segment delivery
    semaphores — the LL-AG producer discipline opened up for in-kernel
    consumers (kernels/flash_prefill.py): where `fcollect_slots` counts
    every arrival on one shared semaphore (consumable only by a full
    wait), here each (tensor, source-offset) pair gets its OWN slot, so
    a consumer can gate on exactly one segment's arrival while later
    segments are still in flight — the per-segment barrier of the
    reference's SP-AG attention (sp_ag_attention_intra_node.py:105-427)
    carried by semaphore counting, exactly as the parity slots of
    `_ll_ag_kernel` carry the LL flag-validation ordering.

    dst_slot_at(t, i): the symmetric destination slot ref for tensor t,
    source-offset i (1..n-1) — every rank's descriptor for offset i
    names the same static slot, which is what both the hardware DMA and
    the legacy interpreter's lockstep discharge require to agree (the
    PR-2 slot rule). seg_sem_at(t, i): that slot's delivery semaphore.
    srcs: the local tensors to push (each goes to every peer).
    on_send(i): optional per-offset hook (trace instants).

    Returns {offset: [PutHandle per tensor]}; the consumer pairs each
    offset's `wait_recv()`s (delivery gate) with a trailing
    `wait_send()` drain (semaphore balance). Caller must barrier the
    team first (same precondition as fcollect). Works under
    verify.capturing() — the flash-prefill protocol model replays this
    exact producer."""
    me = shmem.my_pe(axis)
    sym = _vcap.active() is not None
    handles = {}
    for i in range(1, n):
        peer = (me + i) % n if sym else jnp.mod(me + i, n)
        if on_send is not None:
            on_send(i)
        ctx = _vcap.tag(step=i) if sym else contextlib.nullcontext()
        with ctx:
            handles[i] = [
                shmem.putmem_nbi(dst_slot_at(t, i), src, send_sem,
                                 seg_sem_at(t, i), peer, axis)
                for t, src in enumerate(srcs)
            ]
    return handles


# -- protocol model (static verifier, triton_dist_tpu.verify) ----------------

from triton_dist_tpu import verify as _v  # noqa: E402


@_v.protocol("low_latency_allgather",
             grid=({"calls": 1}, {"calls": 3},
                   {"calls": 3, "fmt": "fp8"}),
             doc="parity double-buffered LL AG: entry barrier on call 0 "
                 "only; calls=3 exercises the same-parity slot reuse "
                 "(call k+2) the parity counting protocol protects; "
                 "fmt != native pushes the wire image on the same slots")
def _ll_ag_protocol(n, calls=3, fmt="native"):
    """Back-to-back _ll_ag_kernel calls on one context buffer. The
    barrier-free steady state is the point: call k+2 reuses parity
    k%2's slots and semaphores, and its safety rests on the counting
    chain (my call-k+1 waits consumed every peer's call-k+1 delivery,
    which is program-ordered after their call-k consumption) — the HB
    argument the verifier replays, not a barrier."""
    x, buf = _v.ref("x"), _v.ref("buf")
    lsem = _v.sem("local_sem")
    send, recv = _v.sem("send_sem"), _v.sem("recv_sems")
    for k in range(calls):
        parity = k % 2
        if fmt != "native":
            # send edge: pack the shard into the wire image
            _v.read(x.at())
            _v.write(x.at())
        if k == 0:
            shmem.barrier_all(TP_AXIS)  # fresh-context entry barrier
        shmem.fcollect_slots(
            lambda pe: buf.at(parity, pe), x,
            lsem.at(), send.at(), recv.at(parity), TP_AXIS, n,
        )
        for j in range(n):
            _v.read(buf.at(parity, j))  # consume (wire: per-slot decode)


# -- conformance runner (verify.conform) --------------------------------------

from jax.sharding import PartitionSpec as _P  # noqa: E402

from triton_dist_tpu.verify import conform as _conform  # noqa: E402


@_conform.conforms(
    "low_latency_allgather",
    grids=((4, {"calls": 1}), (4, {"calls": 3}),
           (4, {"calls": 3, "fmt": "fp8"})),
    doc="double-buffered LL AG across repeated calls on the interpret mesh")
def _ll_ag_conform(n, calls=3, fmt="native"):
    mesh = _conform.team_mesh(n, (TP_AXIS,))
    if isinstance(mesh, _conform.Skip):
        return mesh
    wf = None if fmt == "native" else fmt

    def run(v):
        buf = create_ll_ag_buffer(v.shape, v.dtype, n, wire_format=wf)
        out = v
        for kk in range(calls):
            out, buf = ll_all_gather(v, buf, kk, TP_AXIS, wire_format=wf)
        return out

    x = jnp.ones((8, 128), jnp.float32)
    return _conform.collect_streams(
        mesh, TP_AXIS, run, in_specs=_P(), args=(x,))
