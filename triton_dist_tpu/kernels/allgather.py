"""AllGather kernels over ICI remote DMA.

TPU-native re-design of the reference's producer-side AllGather library
(ref: python/triton_dist/kernels/nvidia/allgather.py:46-578), which picks
between full-mesh copy-engine push, 1-D ring, NUMA-aware 2-D ring and
SM-driven NVSHMEM-put variants by topology. On TPU the transport is Pallas
async remote DMA over ICI; the method space maps as:

  reference (NVLink/NUMA)                this file (ICI mesh)
  -----------------------                -------------------
  full-mesh copy-engine push/pull        full_mesh_all_gather (n-1 direct puts)
  1-D ring (allgather.py:140)            ring_all_gather (neighbor hops)
  NUMA-aware 2-D ring (:196)             all_gather over 2 mesh axes (2 stages)
  auto-select by topology (:57-71)       choose_allgather_method (by size/axes)
  NCCL reference path                    method XLA (lax.all_gather)

All per-device functions take the *local shard* (the value inside
`jax.shard_map`) and return the gathered array; `all_gather_op` wraps a
global sharded array.
"""

from __future__ import annotations

import enum
import functools
from typing import Sequence, Union

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.lang import shmem
from triton_dist_tpu.lang.core import (
    tpu_call,
    compiler_params,
    next_collective_id,
    interpret_no_headroom,
)
from triton_dist_tpu.faults import guard as _guard
from triton_dist_tpu.obs import stats as _obs
from triton_dist_tpu.runtime.init import TP_AXIS
from triton_dist_tpu.wire import codec as wcodec


class AllGatherMethod(enum.Enum):
    """Analog of the reference's AllGatherMethod enum
    (ref: kernels/nvidia/allgather.py:46-55)."""

    Auto = "auto"
    Ring1D = "ring_1d"
    FullMesh = "full_mesh"
    Ring2D = "ring_2d"
    XLA = "xla"


# Messages smaller than this go full-mesh (latency-bound: one hop instead of
# n-1 ring hops); larger go ring (bandwidth-bound: ring keeps every ICI link
# busy with chunk-sized transfers). Mirrors the size/topology auto-select of
# the reference (ref: allgather.py:57-71).
_FULL_MESH_MAX_BYTES = 1 << 20


def choose_allgather_method(nbytes_per_rank: int) -> AllGatherMethod:
    if nbytes_per_rank <= _FULL_MESH_MAX_BYTES:
        return AllGatherMethod.FullMesh
    return AllGatherMethod.Ring1D


def _ring_ag_kernel(axis: str, n: int, gbuild, obuild, fmtc, *refs):
    """1-D ring AG: step s sends chunk (me-s) mod n to the right neighbor
    (ref: allgather.py:140-194 ring push; same chunk rotation).

    recv_sem is a per-step semaphore array: DMA arrivals carry no ordering
    guarantee across steps, so a shared semaphore would let the step-s wait
    be satisfied by a step-(s+k) arrival and the forward would read a slot
    whose data has not landed. Per-step semaphores make each wait exact
    (the analog of the reference's per-chunk barrier words,
    allgather.py:106-138). Output slots are distinct per chunk, so no
    flow control is needed on the data buffers themselves."""
    (x_ref, o_ref, gbuf, gcur, obuf, ocur, local_sem, send_sem,
     recv_sem) = _ag_unpack(gbuild, obuild, refs)
    me = jax.lax.axis_index(axis)
    m = x_ref.shape[0]
    octx = _obs.make_ctx(obuild, obuf, ocur)
    _obs.init_ctx(octx, rank=me, fmt=fmtc)
    gctx = _guard.make_ctx(gbuild, gbuf, gcur, octx=octx)
    _guard.init_ctx(gctx, rank=me)
    with _guard.attached(gctx), _obs.attached(octx):
        shmem.neighbor_barrier(axis, me, n)
        shmem.fault_delay(axis, "allgather")

        # Publish the local shard into our own slot.
        cp = pltpu.make_async_copy(x_ref, o_ref.at[pl.ds(me * m, m)],
                                   local_sem)
        cp.start()
        cp.wait()

        right = jnp.mod(me + 1, n)
        for s in range(n - 1):
            slot = jnp.mod(me - s, n)
            shmem.guard_progress(s)
            h = shmem.putmem_nbi(
                o_ref.at[pl.ds(slot * m, m)],
                o_ref.at[pl.ds(slot * m, m)],
                send_sem, recv_sem.at[s], right, axis,
            )
            # Wait for our send AND for the incoming chunk (me-s-1)
            # mod n — next step's send source; program order is the
            # dependency chain.
            h.wait_send()
            h.wait_recv(slot=s)


def _full_mesh_ag_kernel(axis: str, n: int, gbuild, obuild, fmtc,
                         *refs):
    """Full-mesh push AG: put the local shard directly into every peer's
    slot `me` (ref: allgather.py:81-138 cp_engine full-mesh push). The
    body is the device-side `fcollect` primitive."""
    (x_ref, o_ref, gbuf, gcur, obuf, ocur, local_sem, send_sem,
     recv_sem) = _ag_unpack(gbuild, obuild, refs)
    me = jax.lax.axis_index(axis)
    octx = _obs.make_ctx(obuild, obuf, ocur)
    _obs.init_ctx(octx, rank=me, fmt=fmtc)
    gctx = _guard.make_ctx(gbuild, gbuf, gcur, octx=octx)
    _guard.init_ctx(gctx, rank=me)
    with _guard.attached(gctx), _obs.attached(octx):
        shmem.barrier_all(axis)
        shmem.fault_delay(axis, "allgather")
        shmem.fcollect(o_ref, x_ref, local_sem, send_sem, recv_sem,
                       axis, n)


def _ag_unpack(gbuild, obuild, refs):
    """Outputs (o_ref + guard buffer + stat row) precede scratch; the
    guard/obs cursors are the trailing scratch entries."""
    refs = list(refs)
    x_ref, o_ref = refs[0], refs[1]
    del refs[:2]
    gbuf = refs.pop(0) if gbuild is not None else None
    obuf = refs.pop(0) if obuild is not None else None
    ocur = refs.pop() if obuild is not None else None
    gcur = refs.pop() if gbuild is not None else None
    local_sem, send_sem, recv_sem = refs
    return (x_ref, o_ref, gbuf, gcur, obuf, ocur, local_sem, send_sem,
            recv_sem)


def _pallas_ag(x: jax.Array, axis: str, kernel_body, name: str,
               per_step_recv: bool, fmtc: int = 0) -> jax.Array:
    n = jax.lax.axis_size(axis)
    if x.ndim < 2:
        raise ValueError(f"all_gather needs >=2D shards, got shape {x.shape}")
    gbuild = _guard.active_build()
    obuild = _obs.active_build()
    out_shape = jax.ShapeDtypeStruct((n * x.shape[0],) + x.shape[1:], x.dtype)
    out_specs = pl.BlockSpec(memory_space=pl.ANY)
    recv = (
        pltpu.SemaphoreType.DMA((max(n - 1, 1),))
        if per_step_recv
        else pltpu.SemaphoreType.DMA
    )
    scratch = [
        pltpu.SemaphoreType.DMA,
        pltpu.SemaphoreType.DMA,
        recv,
    ]
    if gbuild is not None:
        out_shape = (out_shape, _guard.out_shape(gbuild))
        out_specs = (out_specs, _guard.out_spec())
        scratch.append(_guard.cursor_scratch())
    if obuild is not None:
        out_shape = (out_shape if isinstance(out_shape, tuple)
                     else (out_shape,)) + (_obs.out_shape(obuild),)
        out_specs = (out_specs if isinstance(out_specs, tuple)
                     else (out_specs,)) + (_obs.out_spec(),)
        scratch.append(_obs.cursor_scratch())
    return tpu_call(
        functools.partial(kernel_body, axis, n, gbuild, obuild, fmtc),
        out_shape=out_shape,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=out_specs,
        scratch_shapes=scratch,
        compiler_params=compiler_params(
            has_side_effects=True, collective_id=next_collective_id(name)
        ),
    )(x)


def _wire_ag(x: jax.Array, axis: str, fmt, transport,
             force_kernel: bool) -> jax.Array:
    """Quantized-wire gather: encode ONCE at the send edge (the wire
    image is what every transport below moves — ring forwards re-send
    received bytes unchanged, so there is no per-hop requantization on
    the gather family), decode every slot at the consume edge. The
    transport kernel is the UNCHANGED native kernel running on the int8
    wire image — the semaphore protocol is format-invariant by
    construction (and by verifier proof). Every slot — including the
    rank's own — passes the codec, so the gathered tensor has uniform
    wire fidelity (kernel output is BITWISE the pack/unpack roundtrip
    composition, which the tests pin)."""
    n = jax.lax.axis_size(axis)
    gbuild = _guard.active_build()
    obuild = _obs.active_build()
    w = wcodec.pack(x, fmt)
    gbuf = obuf = None
    if n == 1 and not force_kernel:
        gathered = w
    elif interpret_no_headroom():
        gathered = jax.lax.all_gather(w, axis, tiled=True)
    else:
        res = transport(w)
        res = res if isinstance(res, tuple) else (res,)
        gathered = res[0]
        gbuf = res[1] if gbuild is not None else None
        obuf = res[-1] if obuild is not None else None
    if obuild is not None and obuf is None:
        obuf = _obs.new_stream(obuild, fmt=_obs.fmt_code(fmt))
    return _obs.with_stats(
        obuild,
        _guard.with_guard(
            gbuild, wcodec.unpack(gathered, x.shape[1:], fmt, x.dtype),
            gbuf),
        obuf)


def ring_all_gather(x: jax.Array, axis: str = TP_AXIS, wire_format=None,
                    force_kernel: bool = False) -> jax.Array:
    """Ring AG of per-device shard `x` -> (n*m, ...). Call inside shard_map.

    wire_format: payload encoding on the wire (wire.WireFormat; None =
    native). Quantized formats move the block-scaled int8 wire image
    through the SAME ring kernel — encoded once at the send edge,
    decoded at the consume edge (see _wire_ag). force_kernel skips the
    n == 1 early return (bench.py wire arms measure the world=1 edge
    cost)."""
    fmt = wcodec.resolve(wire_format)
    gbuild = _guard.active_build()
    obuild = _obs.active_build()
    if not wcodec.is_native(fmt):
        return _wire_ag(
            x, axis, fmt,
            lambda w: _pallas_ag(w, axis, _ring_ag_kernel,
                                 f"ring_ag_{axis}", per_step_recv=True,
                                 fmtc=_obs.fmt_code(fmt)),
            force_kernel)
    if jax.lax.axis_size(axis) == 1 and not force_kernel:
        return _obs.with_stats(obuild, _guard.with_guard(gbuild, x))
    if interpret_no_headroom():
        return _obs.with_stats(obuild, _guard.with_guard(
            gbuild, jax.lax.all_gather(x, axis, tiled=True)))
    return _pallas_ag(x, axis, _ring_ag_kernel, f"ring_ag_{axis}",
                      per_step_recv=True)


def full_mesh_all_gather(x: jax.Array, axis: str = TP_AXIS,
                         wire_format=None) -> jax.Array:
    """Full-mesh push AG (latency-optimal for small messages). All incoming
    puts target distinct slots and are only consumed after the full wait, so
    a single shared recv semaphore is exact here. wire_format as in
    ring_all_gather (the push moves the wire image)."""
    fmt = wcodec.resolve(wire_format)
    gbuild = _guard.active_build()
    obuild = _obs.active_build()
    if not wcodec.is_native(fmt):
        return _wire_ag(
            x, axis, fmt,
            lambda w: _pallas_ag(w, axis, _full_mesh_ag_kernel,
                                 f"fm_ag_{axis}", per_step_recv=False,
                                 fmtc=_obs.fmt_code(fmt)),
            force_kernel=False)
    if jax.lax.axis_size(axis) == 1:
        return _obs.with_stats(obuild, _guard.with_guard(gbuild, x))
    if interpret_no_headroom():
        return _obs.with_stats(obuild, _guard.with_guard(
            gbuild, jax.lax.all_gather(x, axis, tiled=True)))
    return _pallas_ag(x, axis, _full_mesh_ag_kernel, f"fm_ag_{axis}",
                      per_step_recv=False)


def all_gather(
    x: jax.Array,
    axis: Union[str, Sequence[str]] = TP_AXIS,
    method: AllGatherMethod = AllGatherMethod.Auto,
    wire_format=None,
) -> jax.Array:
    """Gather shards along mesh axis/axes; per-device function.

    Axis tuples run stage-wise (innermost first) — the 2-D analog of the
    reference's NUMA-aware 2-D ring (ref: allgather.py:196-261): gather over
    the fast axis, then the slow axis, each stage moving already-gathered
    super-chunks. wire_format applies PER STAGE (each stage re-encodes
    its already-gathered super-chunks — wire fidelity compounds once per
    axis; see docs/performance.md "Quantized wire").
    """
    if not isinstance(axis, str):
        stage_method = (
            AllGatherMethod.Auto
            if method in (AllGatherMethod.Ring2D, AllGatherMethod.Auto)
            else method
        )
        out = x
        for ax in reversed(tuple(axis)):
            out = all_gather(out, ax, method=stage_method,
                             wire_format=wire_format)
        return out

    if method == AllGatherMethod.Ring2D:
        raise ValueError(
            "Ring2D is selected by passing an axis *tuple* (stage-wise AG); "
            "a single axis has no 2-D structure"
        )
    if method == AllGatherMethod.Auto:
        nbytes = x.size * x.dtype.itemsize
        method = choose_allgather_method(nbytes)
    if method == AllGatherMethod.XLA:
        if not wcodec.is_native(wire_format):
            # wire fidelity is a property of the bytes moved, not of the
            # transport: the XLA arm gathers the same wire image
            return wcodec.unpack(
                jax.lax.all_gather(wcodec.pack(x, wire_format), axis,
                                   tiled=True),
                x.shape[1:], wire_format, x.dtype)
        return jax.lax.all_gather(x, axis, tiled=True)
    if method == AllGatherMethod.Ring1D:
        return _guard.primary(_obs.primary(
            ring_all_gather(x, axis, wire_format=wire_format)))
    if method == AllGatherMethod.FullMesh:
        return _guard.primary(_obs.primary(
            full_mesh_all_gather(x, axis, wire_format=wire_format)))
    raise ValueError(f"unknown method {method}")


@functools.lru_cache(maxsize=None)
def _ag_op_jit(mesh, axis: str, method: AllGatherMethod, fmt):
    def fn(xs):
        return all_gather(xs, axis, method=method, wire_format=fmt)

    return jax.jit(
        jax.shard_map(
            fn, mesh=mesh, in_specs=P(axis), out_specs=P(), check_vma=False
        )
    )


def all_gather_op(
    arr: jax.Array,
    mesh,
    axis: str = TP_AXIS,
    method: AllGatherMethod = AllGatherMethod.Auto,
    wire_format=None,
) -> jax.Array:
    """Host-level AG on a global array sharded along its leading dim
    (ref host entry: allgather.py:263-338 dispatch wrappers).
    wire_format as in all_gather."""
    return _ag_op_jit(mesh, axis, method,
                      wcodec.resolve(wire_format))(arr)


# -- protocol models (static verifier, triton_dist_tpu.verify) ---------------

from triton_dist_tpu import verify as _v  # noqa: E402


@_v.protocol("allgather",
             grid=({"method": "ring"}, {"method": "full_mesh"},
                   {"method": "ring", "fmt": "fp8"},
                   {"method": "full_mesh", "fmt": "fp8"},
                   {"method": "ring", "fmt": "int8"}),
             doc="ring AG (_ring_ag_kernel) / full-mesh push "
                 "(fcollect); fmt != native models the same transport "
                 "over the packed wire image (_wire_ag)")
def _ag_protocol(n, method="ring", prefix="", fmt="native", space=None):
    """Ring: step s forwards chunk (me-s) to the right neighbor on the
    per-step recv semaphore (a shared one would let step s's wait be
    satisfied by a step s+k arrival — the race the per-step slots
    exist to prevent, and the one the verifier would flag). Full-mesh:
    the fcollect primitive, shared recv semaphore made exact by the
    full wait before any slot is consumed.

    `fmt` mirrors the wire_format knob: the gather family encodes ONCE
    at the send edge (a pack of the local shard before the transport)
    and decodes every slot at the consume edge — the transport moves
    wire bytes on the IDENTICAL semaphore protocol (the kernel is
    literally the same function running on the int8 image), which
    `registry.check_format_invariance` proves from the captured
    skeletons.

    `prefix` namespaces buffers/semaphores when this skeleton is
    embedded in a larger protocol (two-shot allreduce).

    `space` (xslice.topo.SliceTeam, capture-only) scopes the ring to
    ONE SLICE of a hierarchical team: `n` becomes the slice-local team
    size and every peer rebases through `space.split(my_pe)` — the
    2-level protocols in xslice/collectives.py embed this exact
    skeleton per slice, and the verifier proves the composition at
    every global rank. None keeps the flat behavior bit-for-bit."""
    wire = fmt != "native"
    me_g = shmem.my_pe(TP_AXIS)
    if space is None:
        base, me = 0, me_g
    else:
        assert method == "ring", "slice-scoped AG models the ring only"
        base, me = space.split(me_g)
    x, o = _v.ref(prefix + "x"), _v.ref(prefix + "out")
    lsem = _v.sem(prefix + "local_sem")
    send, recv = _v.sem(prefix + "send_sem"), _v.sem(prefix + "recv_sem")
    if wire:
        # send edge: pack x into the wire image the transport moves
        _v.read(x.at())
        _v.write(x.at())
    if method == "full_mesh":
        shmem.barrier_all(TP_AXIS)
        shmem.fcollect(o, x, lsem.at(), send.at(), recv.at(), TP_AXIS, n)
        for j in range(n):
            _v.read(o.at(j))
        return
    if space is None:
        shmem.neighbor_barrier(TP_AXIS, me, n)
    else:
        space.neighbor_barrier(prefix, me, base, n)
    lc = _v.copy(o.at(me), x.at(), lsem.at())
    lc.wait()
    for s in range(n - 1):
        slot = (me - s) % n
        h = shmem.putmem_nbi(o.at(slot), o.at(slot), send.at(),
                             recv.at(s), base + (me + 1) % n, TP_AXIS)
        # wait our send AND the incoming chunk (me-s-1) — next step's
        # send source; program order is the dependency chain
        h.wait()
    for j in range(n):
        _v.read(o.at(j))  # consume edge (wire: the per-slot decode)


# -- conformance runners (verify.conform: recorded kernel vs model) -----------

from triton_dist_tpu.verify import conform as _conform  # noqa: E402


@_conform.conforms(
    "allgather",
    grids=((4, {"method": "ring"}), (4, {"method": "full_mesh"}),
           (4, {"method": "ring", "fmt": "fp8"}),
           (4, {"method": "full_mesh", "fmt": "fp8"}),
           (4, {"method": "ring", "fmt": "int8"})),
    doc="ring_all_gather / full_mesh_all_gather on the interpret mesh")
def _ag_conform(n, method="ring", fmt="native"):
    mesh = _conform.team_mesh(n, (TP_AXIS,))
    if isinstance(mesh, _conform.Skip):
        return mesh
    wf = None if fmt == "native" else fmt
    entry = ring_all_gather if method == "ring" else full_mesh_all_gather
    x = jnp.arange(n * 8 * 128, dtype=jnp.float32).reshape(n * 8, 128)
    return _conform.collect_streams(
        mesh, TP_AXIS, lambda v: entry(v, TP_AXIS, wire_format=wf),
        in_specs=P(TP_AXIS), args=(x,))
