"""AllReduce kernels.

TPU-native re-design of the reference's 7-method AllReduce library
(ref: python/triton_dist/kernels/nvidia/allreduce.py:28-1208): one-shot push,
two-shot push, double-tree, one/two-shot multimem (NVLS). The TPU method
space:

  reference                         this file
  ---------                         ---------
  one-shot push (:333)              one_shot_all_reduce — full-mesh put of the
                                    local tensor to all peers + local sum
  two-shot push (:447)              two_shot_all_reduce — ring RS + ring AG
  multimem NVLS (:602-737)          method XLA — lax.psum (XLA owns the ICI
                                    reduction trees, the NVLS analog)
  auto-select by size/hw (:1101)    choose_allreduce_method
"""

from __future__ import annotations

import enum
import functools
from typing import Sequence, Union

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.faults import guard as _guard
from triton_dist_tpu.lang import shmem
from triton_dist_tpu.lang.core import (
    tpu_call,
    compiler_params,
    next_collective_id,
    interpret_no_headroom,
)
from triton_dist_tpu.kernels.allgather import ring_all_gather
from triton_dist_tpu.kernels.reduce_scatter import ring_reduce_scatter
from triton_dist_tpu.obs import stats as _obs
from triton_dist_tpu.runtime.init import TP_AXIS
from triton_dist_tpu.wire import codec as wcodec


class AllReduceMethod(enum.Enum):
    Auto = "auto"
    OneShot = "one_shot"
    TwoShot = "two_shot"
    XLA = "xla"


_ONE_SHOT_MAX_BYTES = 256 << 10  # latency-bound regime (ref :1101-1126)
# One-shot materializes (n+1) tensor copies in VMEM; above this the kernel
# cannot compile under Mosaic — fall back (the chunked-entry rationale of
# ref allreduce.py:1129-1208). Same VMEM-resident budget convention as
# AgGemmConfig/GemmRsConfig.
_ONE_SHOT_VMEM_BUDGET = 14 << 20


def choose_allreduce_method(nbytes: int, n: int) -> AllReduceMethod:
    """Size/topology selection (ref auto-select, allreduce.py:1101-1126),
    backed by the analytic perf model: one-shot pays n-1 full-tensor
    sends (latency-optimal), two-shot is RS+AG (bandwidth-optimal); below
    the crossover the model favors one-shot, and a hard byte cap keeps
    the one-shot VMEM residents compilable."""
    from triton_dist_tpu.perf_model import estimate_ar_ms

    if nbytes > _ONE_SHOT_MAX_BYTES:
        return AllReduceMethod.TwoShot
    one = estimate_ar_ms(nbytes, n, method="one_shot")
    two = estimate_ar_ms(nbytes, n, method="two_shot")
    return (AllReduceMethod.OneShot if one <= two
            else AllReduceMethod.TwoShot)


def _one_shot_ar_kernel(axis: str, n: int, x_ref, o_ref, ws, acc, ld_sem,
                        send_sem, recv_sem):
    """One-shot AR: every rank puts its full tensor into every peer's
    workspace slot, then reduces locally (ref: allreduce.py:333-386)."""
    me = jax.lax.axis_index(axis)
    shmem.barrier_all(axis)

    cp = pltpu.make_async_copy(x_ref, ws.at[me], ld_sem)
    cp.start()
    handles = []
    for i in range(1, n):
        peer = jnp.mod(me + i, n)
        handles.append(shmem.putmem_nbi(
            ws.at[me], x_ref, send_sem, recv_sem, peer, axis))
    cp.wait()
    for h in handles:
        h.wait()

    acc[...] = ws[0]
    for r in range(1, n):
        acc[...] = acc[...] + ws[r]
    st = pltpu.make_async_copy(acc, o_ref, ld_sem)
    st.start()
    st.wait()


def one_shot_all_reduce(x: jax.Array, axis: str = TP_AXIS) -> jax.Array:
    """Latency-optimal AR of a per-device tensor. Call inside shard_map."""
    n = jax.lax.axis_size(axis)
    if n == 1:
        return x
    vmem_need = (n + 1) * x.size * x.dtype.itemsize
    if vmem_need > _ONE_SHOT_VMEM_BUDGET or interpret_no_headroom():
        return jax.lax.psum(x, axis)
    return tpu_call(
        functools.partial(_one_shot_ar_kernel, axis, n),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((n,) + x.shape, x.dtype),
            pltpu.VMEM(x.shape, x.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        compiler_params=compiler_params(
            has_side_effects=True,
            collective_id=next_collective_id(f"one_shot_ar_{axis}"),
            vmem_limit_bytes=vmem_need + (2 << 20),
        ),
    )(x)


def two_shot_all_reduce(x: jax.Array, axis: str = TP_AXIS,
                        wire_format=None,
                        force_kernel: bool = False) -> jax.Array:
    """Bandwidth-optimal AR = ring RS + ring AG (ref: allreduce.py:447-526).

    Requires leading dim divisible by the axis size.

    wire_format ("fp8"/"int8"/wire.WireFormat; None = native) quantizes
    BOTH wire legs — the RS leg per hop (quantize at the send edge,
    f32 decode-add at the consume edge: _ring_rs_wire_kernel) and the
    AG leg once per reduced chunk (the gather forwards wire bytes
    unchanged) — at ~itemsize x fewer ICI bytes per hop and the drift
    measured by wire.numerics (EQuARX, arXiv 2506.17615). The semaphore
    protocols of both legs are format-invariant (verify-proved).
    Measured: [perf:allreduce_wire_fp8_vs_native=0.3-60.0] (r06
    cpu-world1 rig read 44.6 — world=1 reads the codec edge tax,
    interpreter-amplified on that rig; world>=2 on the default rig
    reads the ICI-bound wire win, modeled ~0.55x at n=8, so the band
    must span both regimes until a TPU artifact lands; see
    docs/performance.md "Quantized wire"/"Rigs").
    force_kernel: run the ring kernels even at world=1 (bench arms).

    Guarding (faults.guard.building active): one extra trailing output,
    the stacked (2, 1+cap, GUARD_WORDS) guard buffers of the RS and AG
    legs (both legs' watchdog trips are attributable separately).
    Metering (obs.stats.building active): one extra trailing output
    AFTER the guard buffer — the stacked (2, 1, STAT_WORDS) stat rows
    of the two legs (docs/observability.md "In-kernel stat rows")."""
    gbuild = _guard.active_build()
    obuild = _obs.active_build()
    if gbuild is None and obuild is None:
        scattered = ring_reduce_scatter(x, axis, wire_format=wire_format,
                                        force_kernel=force_kernel)
        return ring_all_gather(scattered, axis, wire_format=wire_format,
                               force_kernel=force_kernel)
    res_rs = ring_reduce_scatter(
        x, axis, wire_format=wire_format, force_kernel=force_kernel)
    res_rs = res_rs if isinstance(res_rs, tuple) else (res_rs,)
    res_ag = ring_all_gather(res_rs[0], axis, wire_format=wire_format,
                             force_kernel=force_kernel)
    res_ag = res_ag if isinstance(res_ag, tuple) else (res_ag,)
    out = (res_ag[0],)
    if gbuild is not None:
        out += (jnp.stack([res_rs[1], res_ag[1]]),)
    if obuild is not None:
        out += (jnp.stack([res_rs[-1], res_ag[-1]]),)
    return out


def all_reduce(
    x: jax.Array,
    axis: Union[str, Sequence[str]] = TP_AXIS,
    method: AllReduceMethod = AllReduceMethod.Auto,
    wire_format=None,
    error_budget: float = None,
) -> jax.Array:
    """AllReduce of a per-device tensor; per-device function.

    wire_format: payload encoding for the two-shot wire legs (see
    two_shot_all_reduce); "auto" asks perf_model.choose_wire_format for
    the fastest format whose modeled drift clears `error_budget`
    (default wire.DEFAULT_ERROR_BUDGET; budget 0.0 forces native).
    Quantized wire is a two-shot construct — it forces the TwoShot
    method (one-shot pushes full tensors whose local sum wants the
    native payload; XLA psum cannot express the codec)."""
    if not isinstance(axis, str):
        gbuild = _guard.active_build()
        obuild = _obs.active_build()
        out = x
        gbufs = []
        obufs = []
        for ax in tuple(axis):
            res = all_reduce(out, ax, method=method,
                             wire_format=wire_format,
                             error_budget=error_budget)
            if gbuild is None and obuild is None:
                out = res
                continue
            res = res if isinstance(res, tuple) else (res,)
            out = res[0]
            if gbuild is not None:
                # keep every stage's guard buffer — stripping them
                # would mute a tripped watchdog into a silently wrong
                # result (the failure class this plane exists to kill)
                g = res[1]
                gbufs.append(g if g.ndim == 3 else g[None])
            if obuild is not None:
                o = res[-1]
                obufs.append(o if o.ndim == 3 else o[None])
        ret = (out,)
        if gbuild is not None:
            ret += (jnp.concatenate(gbufs, axis=0),)
        if obuild is not None:
            ret += (jnp.concatenate(obufs, axis=0),)
        return ret if len(ret) > 1 else out

    n = jax.lax.axis_size(axis)
    nbytes = x.size * x.dtype.itemsize
    if wire_format == "auto":
        if x.shape[0] % n != 0:
            # the two-shot construct is inexpressible at this shape, so
            # the admissible format set is {native}: degrade to the
            # native method chain (which handles non-divisible shapes
            # via one-shot/XLA) instead of crashing world-size-dependently
            wire_format = None
        else:
            from triton_dist_tpu.perf_model import choose_wire_format

            wire_format = choose_wire_format(
                nbytes, n, dtype=x.dtype, error_budget=error_budget,
                collective="allreduce", row_width=x.shape[-1])
    if not wcodec.is_native(wire_format):
        if x.shape[0] % n != 0:
            # an EXPLICITLY requested quantized wire stays loud
            raise ValueError(
                f"quantized wire AR needs leading dim divisible by the "
                f"axis size (two-shot construct): {x.shape[0]} % {n}")
        return two_shot_all_reduce(x, axis, wire_format=wire_format)
    if method == AllReduceMethod.Auto:
        if x.shape[0] % n != 0:
            method = (
                AllReduceMethod.OneShot
                if nbytes <= _ONE_SHOT_MAX_BYTES
                else AllReduceMethod.XLA
            )
        else:
            method = choose_allreduce_method(nbytes, n)
    if method == AllReduceMethod.XLA:
        return _obs.with_stats(
            _obs.active_build(),
            _guard.with_guard(_guard.active_build(),
                              jax.lax.psum(x, axis)))
    if method == AllReduceMethod.OneShot:
        return _obs.with_stats(
            _obs.active_build(),
            _guard.with_guard(_guard.active_build(),
                              one_shot_all_reduce(x, axis)))
    return two_shot_all_reduce(x, axis)


PROTOCOL_NAME = "allreduce"  # degradation-registry key


def all_reduce_op(
    arr: jax.Array,
    mesh,
    axis: str = TP_AXIS,
    method: AllReduceMethod = AllReduceMethod.Auto,
    wire_format=None,
    fallback=None,
) -> jax.Array:
    """Host-level AR. `arr` stacks per-rank contributions: (n, ...), sharded
    on dim 0; returns the replicated sum over ranks
    (ref host entry: allreduce.py:1129-1208 chunked all_reduce).
    wire_format as in all_reduce (quantized = two-shot wire legs;
    "auto" defers to choose_wire_format inside the jitted program).

    fallback="xla" is the guard-tripped degradation route
    (docs/robustness.md): under an active guard build, a watchdog trip
    inside the ring kernels marks the protocol degraded and this call —
    and every later one — returns lax.psum's result instead of raising,
    so a degraded step completes rather than dies. Without fallback, a
    trip raises DeadlineExceeded with the decoded guard rows."""
    n = int(mesh.shape[axis])
    if arr.shape[0] != n:
        raise ValueError(
            f"all_reduce_op expects one stacked contribution per rank: "
            f"leading dim {arr.shape[0]} != axis size {n}"
        )
    if fallback not in (None, "xla"):
        raise ValueError(f"unknown fallback {fallback!r} (None or 'xla')")
    if fallback == "xla" and _guard.is_degraded(PROTOCOL_NAME):
        return _ar_xla_jit(mesh, axis)(arr)
    fmt = "auto" if wire_format == "auto" else wcodec.resolve(wire_format)
    gbuild = _guard.active_build()
    obuild = _obs.active_build()
    res = _ar_op_jit(mesh, axis, method, fmt, gbuild,
                     obuild is not None)(arr)
    if gbuild is None and obuild is None:
        return res
    res = res if isinstance(res, tuple) else (res,)
    out = res[0]
    import numpy as np

    if obuild is not None:
        _obs.consume_rows(res[-1], kernel=PROTOCOL_NAME)
    if gbuild is None:
        return out
    g = np.asarray(res[1])
    trips = _guard.decode(g)
    if trips:
        if fallback == "xla":
            _guard.degrade(PROTOCOL_NAME)
            return _ar_xla_jit(mesh, axis)(arr)
        _guard.check(g, context=PROTOCOL_NAME)
    return out


@functools.lru_cache(maxsize=None)
def _ar_op_jit(mesh, axis: str, method: AllReduceMethod, fmt,
               gbuild=None, metered: bool = False):
    from jax.sharding import PartitionSpec as P

    def fn(xs):
        import contextlib

        with _guard.building(gbuild.cap, gbuild.deadline) if gbuild \
                else contextlib.nullcontext(), \
                _obs.building() if metered else contextlib.nullcontext():
            res = all_reduce(xs[0], axis, method=method, wire_format=fmt)
        if gbuild is None and not metered:
            return res
        res = res if isinstance(res, tuple) else (res,)
        ret = (res[0],)
        if gbuild is not None:
            # normalize to (legs, 1+cap, WORDS) so the gathered global
            # is decode-ready regardless of which method path traced
            g = res[1]
            ret += (g[None] if g.ndim == 2 else g,)
        if metered:
            o = res[-1]
            ret += (o[None] if o.ndim == 2 else o,)
        return ret

    out_specs = P()
    if gbuild is not None or metered:
        out_specs = (P(),) + (P(axis),) * ((gbuild is not None)
                                           + bool(metered))
    return jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=P(axis), out_specs=out_specs,
                      check_vma=False)
    )


@functools.lru_cache(maxsize=None)
def _ar_xla_jit(mesh, axis: str):
    """The degraded route: lax.psum (XLA owns the reduction trees) —
    no Pallas protocol to hang."""
    from jax.sharding import PartitionSpec as P

    return jax.jit(
        jax.shard_map(lambda xs: jax.lax.psum(xs[0], axis), mesh=mesh,
                      in_specs=P(axis), out_specs=P(), check_vma=False)
    )


# -- protocol models (static verifier, triton_dist_tpu.verify) ---------------

from triton_dist_tpu import verify as _v  # noqa: E402


@_v.protocol("allreduce",
             grid=({"method": "one_shot"}, {"method": "two_shot"},
                   {"method": "two_shot", "fmt": "fp8"},
                   {"method": "two_shot", "fmt": "int8"}),
             doc="one-shot full-mesh push AR / two-shot RS+AG ring "
                 "composition (fmt != native: both legs on the wire "
                 "image — same sync skeleton, verifier-proved)")
def _ar_protocol(n, method="one_shot", fmt="native"):
    if method == "two_shot":
        # the composition IS the protocol: ring RS then ring AG, each
        # with its own kernel-local semaphores (namespaced here so the
        # verifier sees two disjoint semaphore sets, as at run time);
        # fmt threads into both legs exactly as wire_format does
        from triton_dist_tpu.kernels.reduce_scatter import _rs_protocol
        from triton_dist_tpu.kernels.allgather import _ag_protocol

        _rs_protocol(n, prefix="rs.", fmt=fmt)
        _ag_protocol(n, method="ring", prefix="ag.", fmt=fmt)
        return
    assert fmt == "native", "one-shot AR has no quantized wire"
    me = shmem.my_pe(TP_AXIS)
    x, o = _v.ref("x"), _v.ref("o")
    ws, acc = _v.ref("ws"), _v.ref("acc")
    ld = _v.sem("ld_sem")
    send, recv = _v.sem("send_sem"), _v.sem("recv_sem")
    shmem.barrier_all(TP_AXIS)
    lc = _v.copy(ws.at(me), x.at(), ld.at())
    handles = [
        shmem.putmem_nbi(ws.at(me), x.at(), send.at(), recv.at(),
                         (me + i) % n, TP_AXIS)
        for i in range(1, n)
    ]
    lc.wait()
    for h in handles:
        h.wait()
    for r in range(n):
        _v.read(ws.at(r))  # the local reduction over all slots
    _v.write(acc.at())
    st = _v.copy(o.at(), acc.at(), ld.at())
    st.wait()


# -- conformance runner (verify.conform) --------------------------------------

from jax.sharding import PartitionSpec as _P  # noqa: E402

from triton_dist_tpu.verify import conform as _conform  # noqa: E402


@_conform.conforms(
    "allreduce",
    grids=((4, {"method": "one_shot"}), (4, {"method": "two_shot"}),
           (4, {"method": "two_shot", "fmt": "fp8"}),
           (4, {"method": "two_shot", "fmt": "int8"})),
    doc="one-shot workspace AR and two-shot RS+AG on the interpret mesh")
def _ar_conform(n, method="one_shot", fmt="native"):
    mesh = _conform.team_mesh(n, (TP_AXIS,))
    if isinstance(mesh, _conform.Skip):
        return mesh
    wf = None if fmt == "native" else fmt
    x = jnp.ones((8, 128), jnp.float32)
    if method == "one_shot":
        fn = lambda v: one_shot_all_reduce(v, TP_AXIS)  # noqa: E731
    else:
        fn = lambda v: two_shot_all_reduce(  # noqa: E731
            v, TP_AXIS, wire_format=wf)
    return _conform.collect_streams(
        mesh, TP_AXIS, fn, in_specs=_P(), args=(x,))
