"""Low-latency AllToAll — the MoE dispatch/combine transport.

TPU-native re-design of the reference's DeepEP-style A2A
(ref: python/triton_dist/kernels/nvidia/low_latency_all_to_all.py:36-118
`all_to_all_kernel`: one block per peer does putmem_nbi_block of the token
segment + putmem_signal of scales, fence, signal_op, then
signal_wait_until on its own incoming segment; 137 µs on 32 ranks,
README.md:93). On TPU the whole exchange is one Pallas kernel issuing n-1
concurrent remote DMAs — segment i of the send buffer lands in peer i's
slot `me` — with DMA delivery semaphores playing the role of the
putmem_signal flags. Segment sizes are static (max tokens per peer, as jit
requires); actual counts travel in the same kernel as a second, tiny
`splits` transfer, mirroring the reference's split-metadata exchange
(ref: ep_a2a.py:244-309 splits AG + recv-offset calc).

The reference double-buffers by call parity so back-to-back layer calls
don't collide (low_latency_all_to_all.py:36-118 `call_count % 2`); here
every call's semaphores are kernel-local scratch, so calls are re-entrant
by construction and no parity state exists.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.lang import shmem
from triton_dist_tpu.lang.core import (
    tpu_call,
    compiler_params,
    next_collective_id,
    interpret_no_headroom,
)
from triton_dist_tpu.runtime.init import EP_AXIS


def _a2a_kernel(axis: str, n: int, x_ref, s_ref, o_ref, os_ref,
                cp_sem, send_sem, recv_sem, meta_send_sem, meta_recv_sem):
    me = jax.lax.axis_index(axis)
    shmem.barrier_all(axis)

    # Local segment: x[me] -> out[me]; splits likewise.
    cp = pltpu.make_async_copy(x_ref.at[me], o_ref.at[me], cp_sem)
    cp.start()
    cps = pltpu.make_async_copy(s_ref.at[me], os_ref.at[me], cp_sem)

    handles = []
    for i in range(1, n):
        peer = jnp.mod(me + i, n)
        rdma = pltpu.make_async_remote_copy(
            src_ref=x_ref.at[peer],
            dst_ref=o_ref.at[me],
            send_sem=send_sem,
            recv_sem=recv_sem,
            device_id={axis: peer},
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        rdma.start()
        handles.append(rdma)
        meta = pltpu.make_async_remote_copy(
            src_ref=s_ref.at[peer],
            dst_ref=os_ref.at[me],
            send_sem=meta_send_sem,
            recv_sem=meta_recv_sem,
            device_id={axis: peer},
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        meta.start()
        handles.append(meta)
    cp.wait()
    cps.start()
    cps.wait()
    for h in handles:
        h.wait()


def all_to_all(
    x: jax.Array,
    splits: jax.Array,
    axis: str = EP_AXIS,
) -> Tuple[jax.Array, jax.Array]:
    """Exchange per-peer segments: out[j] = peer j's x[me]. Per-device
    function inside shard_map (ref host entry:
    low_latency_all_to_all.py:198 `fast_all_to_all`).

    x: (n, m, hidden) send buffer — segment i goes to rank i.
    splits: (n,) int32 — actual token counts per segment.
    Returns (out, out_splits): out[j] holds rank j's segment for us, valid
    rows given by out_splits[j].
    """
    n = jax.lax.axis_size(axis)
    if x.shape[0] != n:
        raise ValueError(f"x leading dim {x.shape[0]} != axis size {n}")
    if n == 1:
        return x, splits.astype(jnp.int32)
    if interpret_no_headroom():
        return all_to_all_ref(x, splits, axis)
    splits2d = splits.reshape(n, 1).astype(jnp.int32)
    out, out_splits = tpu_call(
        functools.partial(_a2a_kernel, axis, n),
        out_shape=(
            jax.ShapeDtypeStruct(x.shape, x.dtype),
            jax.ShapeDtypeStruct((n, 1), jnp.int32),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        compiler_params=compiler_params(
            has_side_effects=True,
            collective_id=next_collective_id(f"a2a_{axis}"),
        ),
    )(x, splits2d)
    return out, out_splits.reshape(n)


def fast_all_to_all(x, splits, axis: str = EP_AXIS):
    """Alias matching the reference's public name
    (ref: kernels/nvidia/__init__.py fast_all_to_all)."""
    return all_to_all(x, splits, axis)


def all_to_all_ref(x: jax.Array, splits: jax.Array, axis: str = EP_AXIS):
    """XLA reference path (lax.all_to_all over the leading dim)."""
    out = jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=0, tiled=False)
    out_splits = jax.lax.all_to_all(
        splits.reshape(-1, 1), axis, split_axis=0, concat_axis=0, tiled=True
    ).reshape(-1)
    return out, out_splits
