"""Low-latency AllToAll — the MoE dispatch/combine transport.

TPU-native re-design of the reference's DeepEP-style A2A
(ref: python/triton_dist/kernels/nvidia/low_latency_all_to_all.py:36-118
`all_to_all_kernel`: one block per peer does putmem_nbi_block of the token
segment + putmem_signal of scales, fence, signal_op, then
signal_wait_until on its own incoming segment; 137 µs on 32 ranks,
README.md:93). On TPU the whole exchange is one Pallas kernel issuing n-1
concurrent remote DMAs — segment i of the send buffer lands in peer i's
slot `me` — with DMA delivery semaphores playing the role of the
putmem_signal flags. Segment sizes are static (max tokens per peer, as jit
requires); actual counts travel in the same kernel as a second, tiny
`splits` transfer, mirroring the reference's split-metadata exchange
(ref: ep_a2a.py:244-309 splits AG + recv-offset calc).

The reference double-buffers by call parity so back-to-back layer calls
don't collide (low_latency_all_to_all.py:36-118 `call_count % 2`); here
every call's semaphores are kernel-local scratch, so calls are re-entrant
by construction and no parity state exists.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu import verify as _v
from triton_dist_tpu.faults import guard as _guard
from triton_dist_tpu.faults import plan as _fplan
from triton_dist_tpu.lang import shmem
from triton_dist_tpu.lang.core import (
    tpu_call,
    compiler_params,
    next_collective_id,
    interpret_no_headroom,
)
from triton_dist_tpu.runtime.init import EP_AXIS
from triton_dist_tpu.trace import events as trace_ev


def _a2a_kernel(axis: str, n: int, x_ref, s_ref, o_ref, os_ref,
                cp_sem, send_sem, recv_sem, meta_send_sem, meta_recv_sem):
    me = jax.lax.axis_index(axis)
    shmem.barrier_all(axis)

    # Local segment: x[me] -> out[me]; splits likewise.
    cp = pltpu.make_async_copy(x_ref.at[me], o_ref.at[me], cp_sem)
    cp.start()
    cps = pltpu.make_async_copy(s_ref.at[me], os_ref.at[me], cp_sem)

    handles = []
    for i in range(1, n):
        peer = jnp.mod(me + i, n)
        handles.append(shmem.putmem_nbi(
            o_ref.at[me], x_ref.at[peer], send_sem, recv_sem, peer, axis))
        handles.append(shmem.putmem_nbi(
            os_ref.at[me], s_ref.at[peer], meta_send_sem, meta_recv_sem,
            peer, axis))
    cp.wait()
    cps.start()
    cps.wait()
    for h in handles:
        h.wait()


def all_to_all(
    x: jax.Array,
    splits: jax.Array,
    axis: str = EP_AXIS,
) -> Tuple[jax.Array, jax.Array]:
    """Exchange per-peer segments: out[j] = peer j's x[me]. Per-device
    function inside shard_map (ref host entry:
    low_latency_all_to_all.py:198 `fast_all_to_all`).

    x: (n, m, hidden) send buffer — segment i goes to rank i.
    splits: (n,) or (n, S) int32 — per-segment metadata rows travelling
    alongside (the classic case is the single valid-token count; the
    chunk-pipelined EP dispatch rides its per-expert counts here too).
    Returns (out, out_splits): out[j] holds rank j's segment for us, with
    rank j's metadata row in out_splits[j] (same shape as splits).
    """
    n = jax.lax.axis_size(axis)
    if x.shape[0] != n:
        raise ValueError(f"x leading dim {x.shape[0]} != axis size {n}")
    if n == 1:
        return x, splits.astype(jnp.int32)
    if interpret_no_headroom():
        return all_to_all_ref(x, splits, axis)
    splits2d = splits.reshape(n, -1).astype(jnp.int32)
    out, out_splits = tpu_call(
        functools.partial(_a2a_kernel, axis, n),
        out_shape=(
            jax.ShapeDtypeStruct(x.shape, x.dtype),
            jax.ShapeDtypeStruct(splits2d.shape, jnp.int32),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        compiler_params=compiler_params(
            has_side_effects=True,
            collective_id=next_collective_id(f"a2a_{axis}"),
        ),
    )(x, splits2d)
    return out, out_splits.reshape(splits.shape)


def fast_all_to_all(x, splits, axis: str = EP_AXIS):
    """Alias matching the reference's public name
    (ref: kernels/nvidia/__init__.py fast_all_to_all)."""
    return all_to_all(x, splits, axis)


def all_to_all_ref(x: jax.Array, splits: jax.Array, axis: str = EP_AXIS):
    """XLA reference path (lax.all_to_all over the leading dim).
    splits may be (n,) or (n, S); the output matches its shape."""
    out = jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=0, tiled=False)
    n = x.shape[0]
    out_splits = jax.lax.all_to_all(
        splits.reshape(n, -1), axis, split_axis=0, concat_axis=0, tiled=True
    ).reshape(splits.shape)
    return out, out_splits


# -- chunked transport (the EP MoE pipeline's arrival-granular A2A) ----------


def _a2a_chunked_kernel(axis, n, q, rows, straggler, build, gbuild,
                        *refs):
    """Chunk-granular A2A: segment payloads travel as `q` row-chunks, and
    chunk (step i, c) lands on its OWN delivery semaphore slot
    recv_sems[i, c] — the TPU analog of the reference's per-peer
    putmem_signal + signal_wait_until (low_latency_all_to_all.py:36-118):
    a consumer can wait on chunk c of every source while chunks c+1..q-1
    are still in flight.

    Semaphore slots are indexed by RING STEP i (source offset me-i), not
    absolute source rank: every rank's descriptor for step (i, c) then
    names the same static slot, which is what both the hardware DMA
    (slot on the destination chip) and the legacy interpreter's lockstep
    discharge (slot on the local instance) require to agree.

    `build` (trace.events.TraceBuild or None) gates the event records:
    instants per chunk send, spans per delivery wait, and the straggle
    instant every rank emits (payload = this rank's injected delay, 0
    off-rank — uniform record sequences keep cross-rank seq aligned for
    the delivery replay, trace/attribution.a2a_step_waits)."""
    refs = list(refs)
    x_ref, s_ref, o_ref, os_ref = refs[:4]
    del refs[:4]
    tbuf = refs.pop(0) if build is not None else None
    gbuf = refs.pop(0) if gbuild is not None else None
    gcur = refs.pop() if gbuild is not None else None
    tcur = refs.pop() if build is not None else None
    (cp_sem, send_sem, recv_sems, meta_send_sem, meta_recv_sem) = refs
    me = jax.lax.axis_index(axis)
    tctx = trace_ev.make_ctx(build, tbuf, tcur)
    trace_ev.init_ctx(tctx, rank=me)
    R = trace_ev.REGIONS
    gctx = _guard.make_ctx(gbuild, gbuf, gcur, tctx=tctx)
    _guard.init_ctx(gctx, rank=me)
    with _guard.attached(gctx):
        shmem.barrier_all(axis)
        if straggler is not None:
            # race provocation: stall one rank between entering the
            # kernel and issuing its sends, so its peers' per-chunk
            # waits really wait (pattern of the megakernel AR skew
            # stress)
            trace_ev.instant(
                tctx, R["straggle"],
                payload=jnp.where(me == straggler[0], straggler[1], 0))
            shmem.straggler_delay(axis, straggler[0], straggler[1])

        # Local segment: chunk-granular local copies, each on its own
        # slot (recv_sems row 0 — ring step 0 is "self", so the slot
        # space is uniform: slot [i, c] == chunk c from source offset
        # i). A shared local semaphore would let chunk c's wait be
        # satisfied by chunk c+1's completion (waits are byte-counted,
        # not tagged), silently voiding the chunk-major arrival
        # guarantee.
        local = []
        for c in range(q):
            sl = pl.ds(c * rows, rows)
            cp = pltpu.make_async_copy(x_ref.at[me, sl],
                                       o_ref.at[me, sl],
                                       recv_sems.at[0, c])
            cp.start()
            local.append(cp)
        cps = pltpu.make_async_copy(s_ref.at[me], os_ref.at[me], cp_sem)

        handles = {}
        meta_handles = []
        for i in range(1, n):
            peer = jnp.mod(me + i, n)
            for c in range(q):
                sl = pl.ds(c * rows, rows)
                trace_ev.instant(tctx, R["a2a.send"], payload=i, aux=c)
                handles[(i, c)] = shmem.putmem_nbi(
                    o_ref.at[me, sl], x_ref.at[peer, sl], send_sem,
                    recv_sems.at[i, c], peer, axis,
                )
            meta_handles.append(shmem.putmem_nbi(
                os_ref.at[me], s_ref.at[peer], meta_send_sem,
                meta_recv_sem, peer, axis,
            ))

        # Chunk-major consumption: after iteration c the output rows of
        # chunk c are complete FROM EVERY SOURCE while chunks c+1.. are
        # still in flight — the wait order a fused consumer interleaves
        # compute into.
        for c in range(q):
            shmem.guard_progress(c)
            with trace_ev.span(tctx, R["a2a.local"], payload=c):
                local[c].wait()
            for i in range(1, n):
                with trace_ev.span(tctx, R["a2a.wait"], payload=i,
                                   aux=c):
                    handles[(i, c)].wait_send()
                    handles[(i, c)].wait_recv(slot=i)
        cps.start()
        cps.wait()
        for i, h in enumerate(meta_handles):
            with trace_ev.span(tctx, R["a2a.meta"], payload=i + 1):
                h.wait()


def all_to_all_chunked(
    x: jax.Array,
    splits: jax.Array,
    axis: str = EP_AXIS,
    n_chunks: int = 1,
    straggler: Optional[Tuple[int, int]] = None,
) -> Tuple[jax.Array, jax.Array]:
    """all_to_all with per-chunk delivery semaphores: each segment's rows
    travel as `n_chunks` independently-signalled chunks (see
    _a2a_chunked_kernel). Byte-identical output to `all_to_all`; what
    changes is the ARRIVAL protocol — chunk c of every source can be
    consumed while later chunks stream, which is what the chunk-pipelined
    EP MoE dispatch builds on (kernels/ep_a2a.py).

    x: (n, C, hidden) with C % n_chunks == 0; splits: (n,) or (n, S).
    straggler: optional (rank, nanos) skew injection for stress tests.

    Tracing (trace.building active): returns an extra trailing output —
    the per-rank device trace buffer — on every path (fallbacks hand
    back an empty buffer), so callers' output trees are build-stable.
    Guarding (faults.guard.building active): one more trailing output,
    the guard buffer (after the trace buffer when both are active).
    """
    n = jax.lax.axis_size(axis)
    if x.shape[0] != n:
        raise ValueError(f"x leading dim {x.shape[0]} != axis size {n}")
    q = int(n_chunks)
    if q < 1 or x.shape[1] % q:
        raise ValueError(
            f"n_chunks={q} must be >= 1 and divide the capacity dim "
            f"{x.shape[1]}"
        )
    build = trace_ev.active_build()
    gbuild = _guard.active_build()
    straggler = _fplan.scheduled_straggler("all_to_all_chunked",
                                           straggler)

    def with_both(res, tbuf=None, gbuf=None):
        return _guard.with_guard(
            gbuild, trace_ev.with_trace(build, res, tbuf), gbuf)

    if n == 1:
        return with_both((x, splits.astype(jnp.int32)))
    if interpret_no_headroom():
        return with_both(all_to_all_ref(x, splits, axis))
    rows = x.shape[1] // q
    splits2d = splits.reshape(n, -1).astype(jnp.int32)
    out_shape = (
        jax.ShapeDtypeStruct(x.shape, x.dtype),
        jax.ShapeDtypeStruct(splits2d.shape, jnp.int32),
    )
    out_specs = (
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    )
    scratch = [
        pltpu.SemaphoreType.DMA,
        pltpu.SemaphoreType.DMA,
        pltpu.SemaphoreType.DMA((n, q)),
        pltpu.SemaphoreType.DMA,
        pltpu.SemaphoreType.DMA,
    ]
    if build is not None:
        out_shape += (trace_ev.out_shape(build),)
        out_specs += (trace_ev.out_spec(),)
        scratch.append(trace_ev.cursor_scratch())
    if gbuild is not None:
        out_shape += (_guard.out_shape(gbuild),)
        out_specs += (_guard.out_spec(),)
        scratch.append(_guard.cursor_scratch())
    res = tpu_call(
        functools.partial(_a2a_chunked_kernel, axis, n, q, rows,
                          straggler, build, gbuild),
        out_shape=out_shape,
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=out_specs,
        scratch_shapes=scratch,
        compiler_params=compiler_params(
            has_side_effects=True,
            collective_id=next_collective_id(f"a2a_chunk{q}_{axis}"),
        ),
    )(x, splits2d)
    out, out_splits = res[:2]
    k = 2
    tbuf = res[k] if build is not None else None
    k += 1 if build is not None else 0
    gbuf = res[k] if gbuild is not None else None
    return with_both((out, out_splits.reshape(splits.shape)), tbuf, gbuf)


# -- protocol models (static verifier, triton_dist_tpu.verify) ---------------
#
# Each model replays its kernel's cross-rank communication skeleton
# through the shmem primitives under verify.capturing(): same barrier,
# same DMA slot/semaphore indexing, same wait order, with the consumer
# contract spelled as read annotations. scripts/verify_kernels.py proves
# them deadlock-free / race-free / semaphore-balanced at n = 2/4/8.


@_v.protocol("all_to_all",
             doc="single-shot segment exchange (_a2a_kernel)")
def _a2a_protocol(n):
    me = shmem.my_pe(EP_AXIS)
    x, s = _v.ref("x"), _v.ref("splits")
    o, os_ = _v.ref("out"), _v.ref("out_splits")
    cp = _v.sem("cp_sem")
    send, recv = _v.sem("send_sem"), _v.sem("recv_sem")
    msend, mrecv = _v.sem("meta_send_sem"), _v.sem("meta_recv_sem")
    shmem.barrier_all(EP_AXIS)
    lc = _v.copy(o.at(me), x.at(me), cp.at())
    handles = []
    for i in range(1, n):
        peer = (me + i) % n
        handles.append(shmem.putmem_nbi(
            o.at(me), x.at(peer), send.at(), recv.at(), peer, EP_AXIS))
        handles.append(shmem.putmem_nbi(
            os_.at(me), s.at(peer), msend.at(), mrecv.at(), peer,
            EP_AXIS))
    lc.wait()
    lcs = _v.copy(os_.at(me), s.at(me), cp.at())
    lcs.wait()
    for h in handles:
        h.wait()
    # consumer contract: the caller reads every segment after the kernel
    for j in range(n):
        _v.read(o.at(j))
        _v.read(os_.at(j))


@_v.protocol("all_to_all_chunked",
             grid=({"q": 1}, {"q": 2}, {"q": 4}),
             doc="per-(step, chunk) delivery slots (_a2a_chunked_kernel)")
def _a2a_chunked_protocol(n, q=2):
    """Slots indexed by RING STEP (source offset), never absolute rank —
    the exact invariant the verifier's deadlock check proves (the
    absolute-rank mutant in tests/_mutants.py is the counterexample).
    Chunk-major consumer reads model the fused EP pipeline: chunk c of
    every source is read while chunks c+1.. are still in flight."""
    me = shmem.my_pe(EP_AXIS)
    x, o = _v.ref("x"), _v.ref("out")
    s, os_ = _v.ref("splits"), _v.ref("out_splits")
    cp = _v.sem("cp_sem")
    send, recv = _v.sem("send_sem"), _v.sem("recv_sems")
    msend, mrecv = _v.sem("meta_send_sem"), _v.sem("meta_recv_sem")
    shmem.barrier_all(EP_AXIS)
    local = [_v.copy(o.at(me, c), x.at(me, c), recv.at(0, c))
             for c in range(q)]
    handles = {}
    metas = []
    for i in range(1, n):
        peer = (me + i) % n
        for c in range(q):
            with _v.tag(step=i, chunk=c):
                handles[(i, c)] = shmem.putmem_nbi(
                    o.at(me, c), x.at(peer, c), send.at(), recv.at(i, c),
                    peer, EP_AXIS)
        metas.append(shmem.putmem_nbi(
            os_.at(me), s.at(peer), msend.at(), mrecv.at(), peer,
            EP_AXIS))
    for c in range(q):
        local[c].wait()
        for i in range(1, n):
            with _v.tag(step=i, chunk=c):
                handles[(i, c)].wait()
        for j in range(n):
            _v.read(o.at(j, c))  # chunk-major consumer (EP FFN)
    lcs = _v.copy(os_.at(me), s.at(me), cp.at())
    lcs.wait()
    for m in metas:
        m.wait()
    for j in range(n):
        _v.read(os_.at(j))


# -- conformance runners (verify.conform: recorded kernel vs model) -----------

from jax.sharding import PartitionSpec as _P  # noqa: E402

from triton_dist_tpu.verify import conform as _conform  # noqa: E402


@_conform.conforms(
    "all_to_all", grids=((4, {}),),
    doc="single-shot segment exchange on the interpret mesh")
def _a2a_conform(n):
    mesh = _conform.team_mesh(n, (EP_AXIS,))
    if isinstance(mesh, _conform.Skip):
        return mesh
    x = jnp.ones((n * n, 8, 128), jnp.float32)
    sp = jnp.ones((n * n,), jnp.int32)
    return _conform.collect_streams(
        mesh, EP_AXIS, lambda v, s: all_to_all(v, s, EP_AXIS),
        in_specs=(_P(EP_AXIS), _P(EP_AXIS)), args=(x, sp))


@_conform.conforms(
    "all_to_all_chunked",
    grids=((4, {"q": 1}), (4, {"q": 2}), (4, {"q": 4})),
    doc="chunk-granular A2A: per-(step, chunk) delivery slots")
def _a2a_chunked_conform(n, q=2):
    mesh = _conform.team_mesh(n, (EP_AXIS,))
    if isinstance(mesh, _conform.Skip):
        return mesh
    x = jnp.ones((n * n, 8, 128), jnp.float32)
    sp = jnp.ones((n * n,), jnp.int32)
    return _conform.collect_streams(
        mesh, EP_AXIS,
        lambda v, s: all_to_all_chunked(v, s, EP_AXIS, n_chunks=q),
        in_specs=(_P(EP_AXIS), _P(EP_AXIS)), args=(x, sp))
