"""AG + grouped GEMM and grouped GEMM + topk-reduce + RS — the TP-MoE pair.

TPU-native re-design of the reference's TP-MoE kernel pair
(ref: python/triton_dist/kernels/nvidia/allgather_group_gemm.py:85-199
sorted gather index from topk ids + :535 consumer group GEMM;
moe_reduce_rs.py:167-246 grouped GEMM with dl.wait + :293-488
topk-reduce+RS kernels; host entries `ag_group_gemm`, `run_moe_reduce_rs`).

The overlap structure maps as:
  - the AG leg reuses the fused ring AG+GEMM machinery where profitable;
    the gathered tokens feed a `lax.ragged_dot` grouped GEMM (MXU-tiled by
    XLA over the expert segments the sorted layout provides);
  - the RS leg reuses the credit-flow ring reduce_scatter kernel; the
    topk-weighted reduce is the XLA epilogue feeding it.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from triton_dist_tpu.kernels.allgather import ring_all_gather
from triton_dist_tpu.kernels.allgather_gemm import ag_gemm
from triton_dist_tpu.kernels.grouped_gemm import grouped_gemm
from triton_dist_tpu.kernels.moe_utils import (
    ExpertSort,
    combine_topk,
    pack_by_expert,
)
from triton_dist_tpu.kernels.reduce_scatter import (
    ReduceScatterMethod,
    reduce_scatter,
)
from triton_dist_tpu.lang.core import interpret_no_headroom
from triton_dist_tpu.runtime.init import TP_AXIS


def moe_all_gather(x_shard: jax.Array, axis: str = TP_AXIS) -> jax.Array:
    """Gather the token shards once per MoE layer (ring kernel when the
    protocol path is available). The result feeds BOTH the router and the
    grouped GEMM — gathering twice would double the AG traffic."""
    n = jax.lax.axis_size(axis)
    if n == 1 or interpret_no_headroom():
        return jax.lax.all_gather(x_shard, axis, tiled=True)
    from triton_dist_tpu.faults import guard as _guard
    from triton_dist_tpu.obs import stats as _obs

    return _guard.primary(_obs.primary(ring_all_gather(x_shard, axis)))


def ag_group_gemm(
    x_shard: jax.Array,  # (M/n, H) sequence-sharded tokens
    w_stack: jax.Array,  # (E, H, N_loc) per-expert expert-dim shards
    sort: ExpertSort,  # routing sort over the FULL M tokens
    axis: str = TP_AXIS,
    x_full: Optional[jax.Array] = None,  # pre-gathered tokens, if available
) -> jax.Array:
    """AllGather tokens, replicate rows per routed expert (sorted), grouped
    GEMM against every expert's local N-shard. Returns (M*k, N_loc) in
    sorted order (ref host entry: allgather_group_gemm.py `ag_group_gemm`).
    """
    if x_full is None:
        x_full = moe_all_gather(x_shard, axis)
    x_rows = x_full[sort.token_idx]  # (M*k, H) sorted by expert
    return grouped_gemm(x_rows, w_stack, sort.group_sizes)


def moe_reduce_rs(
    act_sorted: jax.Array,  # (M*k, I_loc) activations in sorted order
    w_down_stack: jax.Array,  # (E, I_loc, H)
    sort: ExpertSort,
    topk_weights: jax.Array,  # (M, k)
    axis: str = TP_AXIS,
    out_dtype=None,
    method: Optional[ReduceScatterMethod] = None,
) -> jax.Array:
    """Grouped down-projection + topk-weighted combine + ReduceScatter.
    Returns (M/n, H) (ref host entry: moe_reduce_rs.py:569
    `run_moe_reduce_rs`)."""
    out_dtype = out_dtype or act_sorted.dtype
    y_sorted = grouped_gemm(
        act_sorted, w_down_stack, sort.group_sizes, out_dtype=jnp.float32
    )
    y = combine_topk(y_sorted, sort, topk_weights)  # (M, H) f32
    n = jax.lax.axis_size(axis)
    if n == 1:
        return y.astype(out_dtype)
    return reduce_scatter(y.astype(out_dtype), axis, method=method)


def ag_group_gemm_ref(x_shard, w_stack, sort, axis: str = TP_AXIS):
    """Unfused XLA reference (AG + ragged_dot)."""
    x_full = jax.lax.all_gather(x_shard, axis, tiled=True)
    return grouped_gemm(x_full[sort.token_idx], w_stack, sort.group_sizes)


# -- fused one-kernel MoE pair ------------------------------------------------
#
# TPU-native re-design of the reference's OVERLAPPED MoE pair (the ring AG
# consumed per-segment inside the group GEMM, allgather_group_gemm.py:535,
# and the grouped GEMM feeding the RS, moe_reduce_rs.py:167-246). The
# ragged sorted layout the reference's consumer walks cannot feed the MXU
# with static tiles, so the fused path reshapes the problem:
#
#   1. each rank packs its OWN tokens into fixed-capacity per-expert
#      blocks (moe_utils.pack_by_expert — router weights are replicated,
#      so local routing needs no pre-gather);
#   2. ONE Pallas kernel ring-allgathers the packed blocks while its MXU
#      consumer multiplies each arrived expert block against that
#      expert's weight slice, with the silu(gate)*up epilogue fused
#      (ag_gemm grouped mode — the dense AG+GEMM ring machinery, shared);
#   3. the down-projection runs as an E-batched dot, the topk combine is
#      one dense gather via the pack's inverse map, and the credit-flow
#      ring reduce_scatter returns the sequence shards.
#
# Capacity overflow drops (GShard trade, counted in meta.drops);
# capacity = m_tok * top_k is exact (zero drops possible).


class MoEFusedMeta(NamedTuple):
    """Origin-side combine metadata, gathered in rank order."""

    slot_of: jax.Array  # (n, m_tok, k) flat slot in the source shard; -1=drop
    weights: jax.Array  # (n, m_tok, k) f32 topk weights
    drops: jax.Array    # () int32 — THIS rank's dropped (token, choice) rows


def fused_ag_moe_up(
    x_shard: jax.Array,       # (m_tok, H) this rank's tokens
    topk_ids: jax.Array,      # (m_tok, k) expert ids (local routing)
    topk_weights: jax.Array,  # (m_tok, k) f32
    w_gate: jax.Array,        # (E, H, I_loc)
    w_up: jax.Array,          # (E, H, I_loc)
    axis: str = TP_AXIS,
    capacity: Optional[int] = None,
    capacity_factor: Optional[float] = None,
    config=None,
    force_kernel: bool = False,
):
    """Fused AG + grouped gate/up GEMM + silu. Returns
    (act (n, E, cap, I_loc) in x.dtype — arrival-step-major source
    blocks, meta). Per-device inside shard_map.

    Capacity: the DEFAULT (neither capacity nor capacity_factor given)
    is the exact m_tok*top_k — zero drops possible, matching every
    other mode's lossless semantics (round-4 ADVICE). A smaller
    capacity / a capacity_factor opts into the GShard drop trade;
    meta.drops counts this rank's dropped (token, choice) rows."""
    from triton_dist_tpu.lang.core import min_tile, round_up

    n = jax.lax.axis_size(axis)
    m_tok, h = x_shard.shape
    e = w_gate.shape[0]
    k = topk_ids.shape[1]
    if capacity is None:
        capacity = (m_tok * k if capacity_factor is None
                    else int(-(-m_tok * k * capacity_factor // e)))
    # block heights tile-aligned for the grouped ring kernel's A-row DMA
    # offsets (sublane tile is dtype-dependent: 16 for bf16 — round-4
    # ADVICE: a hard-coded 8 produced Mosaic-rejected offsets)
    st = min_tile(x_shard.dtype)[0]
    cap = round_up(min(max(capacity, 1), m_tok * k), st)
    pack = pack_by_expert(x_shard, topk_ids, e, cap)
    from triton_dist_tpu.trace.events import primary

    # primary(): build-safe under trace.building() (buffers dropped; see
    # tp_mlp.dist_fwd)
    act = primary(ag_gemm(
        pack.x, (w_gate, w_up), axis=axis, config=config,
        epilogue="silu_pair", c_order="arrival",
        force_kernel=force_kernel, out_dtype=x_shard.dtype,
    ))
    act = act.reshape(n, e, cap, w_gate.shape[-1])
    meta = MoEFusedMeta(
        slot_of=jax.lax.all_gather(pack.slot_of, axis),
        weights=jax.lax.all_gather(topk_weights.astype(jnp.float32), axis),
        drops=pack.drops,
    )
    return act, meta


def fused_moe_down_combine_rs(
    act: jax.Array,     # (n, E, cap, I_loc) from fused_ag_moe_up
    w_down: jax.Array,  # (E, I_loc, H)
    meta: MoEFusedMeta,
    axis: str = TP_AXIS,
    out_dtype=None,
    method: Optional[ReduceScatterMethod] = None,
) -> jax.Array:
    """E-batched down-projection + gather-formulated topk combine +
    ring ReduceScatter. Returns (m_tok, H) sequence shards."""
    n, e, cap, i_loc = act.shape
    h = w_down.shape[-1]
    out_dtype = out_dtype or act.dtype
    xe = jnp.moveaxis(act, 1, 0).reshape(e, n * cap, i_loc)
    ye = jax.lax.dot_general(
        xe, w_down, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )  # (E, n*cap, H) f32
    y_flat = jnp.moveaxis(
        ye.reshape(e, n, cap, h), 0, 1
    ).reshape(n * e * cap, h)  # arrival-step-major flat slots

    # combine: one dense gather via the inverse pack map. Source rank r's
    # blocks sit at arrival step s = (me - r) mod n.
    me = jax.lax.axis_index(axis)
    m_tok, k = meta.slot_of.shape[1], meta.slot_of.shape[2]
    base = (jnp.mod(me - jnp.arange(n), n) * (e * cap))[:, None, None]
    live = meta.slot_of >= 0
    gslot = jnp.where(live, meta.slot_of + base, 0)
    wts = jnp.where(live, meta.weights, 0.0)
    rows = y_flat[gslot.reshape(-1)].reshape(n, m_tok, k, h)
    y = jnp.einsum("nmkh,nmk->nmh", rows, wts)  # (n, m_tok, H) f32
    y = y.reshape(n * m_tok, h).astype(out_dtype)
    if n == 1:
        return y
    return reduce_scatter(y, axis, method=method)
