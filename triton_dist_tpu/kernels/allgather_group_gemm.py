"""AG + grouped GEMM and grouped GEMM + topk-reduce + RS — the TP-MoE pair.

TPU-native re-design of the reference's TP-MoE kernel pair
(ref: python/triton_dist/kernels/nvidia/allgather_group_gemm.py:85-199
sorted gather index from topk ids + :535 consumer group GEMM;
moe_reduce_rs.py:167-246 grouped GEMM with dl.wait + :293-488
topk-reduce+RS kernels; host entries `ag_group_gemm`, `run_moe_reduce_rs`).

The overlap structure maps as:
  - the AG leg reuses the fused ring AG+GEMM machinery where profitable;
    the gathered tokens feed a `lax.ragged_dot` grouped GEMM (MXU-tiled by
    XLA over the expert segments the sorted layout provides);
  - the RS leg reuses the credit-flow ring reduce_scatter kernel; the
    topk-weighted reduce is the XLA epilogue feeding it.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from triton_dist_tpu.kernels.allgather import ring_all_gather
from triton_dist_tpu.kernels.grouped_gemm import grouped_gemm
from triton_dist_tpu.kernels.moe_utils import (
    ExpertSort,
    combine_topk,
)
from triton_dist_tpu.kernels.reduce_scatter import (
    ReduceScatterMethod,
    reduce_scatter,
)
from triton_dist_tpu.lang.core import interpret_no_headroom
from triton_dist_tpu.runtime.init import TP_AXIS


def moe_all_gather(x_shard: jax.Array, axis: str = TP_AXIS) -> jax.Array:
    """Gather the token shards once per MoE layer (ring kernel when the
    protocol path is available). The result feeds BOTH the router and the
    grouped GEMM — gathering twice would double the AG traffic."""
    n = jax.lax.axis_size(axis)
    if n == 1 or interpret_no_headroom():
        return jax.lax.all_gather(x_shard, axis, tiled=True)
    return ring_all_gather(x_shard, axis)


def ag_group_gemm(
    x_shard: jax.Array,  # (M/n, H) sequence-sharded tokens
    w_stack: jax.Array,  # (E, H, N_loc) per-expert expert-dim shards
    sort: ExpertSort,  # routing sort over the FULL M tokens
    axis: str = TP_AXIS,
    x_full: Optional[jax.Array] = None,  # pre-gathered tokens, if available
) -> jax.Array:
    """AllGather tokens, replicate rows per routed expert (sorted), grouped
    GEMM against every expert's local N-shard. Returns (M*k, N_loc) in
    sorted order (ref host entry: allgather_group_gemm.py `ag_group_gemm`).
    """
    if x_full is None:
        x_full = moe_all_gather(x_shard, axis)
    x_rows = x_full[sort.token_idx]  # (M*k, H) sorted by expert
    return grouped_gemm(x_rows, w_stack, sort.group_sizes)


def moe_reduce_rs(
    act_sorted: jax.Array,  # (M*k, I_loc) activations in sorted order
    w_down_stack: jax.Array,  # (E, I_loc, H)
    sort: ExpertSort,
    topk_weights: jax.Array,  # (M, k)
    axis: str = TP_AXIS,
    out_dtype=None,
    method: Optional[ReduceScatterMethod] = None,
) -> jax.Array:
    """Grouped down-projection + topk-weighted combine + ReduceScatter.
    Returns (M/n, H) (ref host entry: moe_reduce_rs.py:569
    `run_moe_reduce_rs`)."""
    out_dtype = out_dtype or act_sorted.dtype
    y_sorted = grouped_gemm(
        act_sorted, w_down_stack, sort.group_sizes, out_dtype=jnp.float32
    )
    y = combine_topk(y_sorted, sort, topk_weights)  # (M, H) f32
    n = jax.lax.axis_size(axis)
    if n == 1:
        return y.astype(out_dtype)
    return reduce_scatter(y.astype(out_dtype), axis, method=method)


def ag_group_gemm_ref(x_shard, w_stack, sort, axis: str = TP_AXIS):
    """Unfused XLA reference (AG + ragged_dot)."""
    x_full = jax.lax.all_gather(x_shard, axis, tiled=True)
    return grouped_gemm(x_full[sort.token_idx], w_stack, sort.group_sizes)
