"""Distributed flash-decode — split-KV decode with cross-rank LSE combine.

TPU-native re-design of the reference's distributed flash-decode
(ref: python/triton_dist/kernels/nvidia/flash_decode.py: split-KV GQA
decode :130/:587, intra-rank combine emitting (acc, lse) partials :393-480,
inter-rank online-softmax combine :482-531). The KV cache shards by
sequence across the sp axis; each rank computes a masked partial decode
over its shard plus the log-sum-exp, the (acc, lse) partials are exchanged
with a small-message allgather (the reference uses its LL allgather for
this, sp_flash_decode_layer.py:136-146), and the merge is the standard
attention-partial combine: out = Σ_i exp(lse_i - lse*) o_i / Σ_i
exp(lse_i - lse*).

This is the 1→32-GPU decode-scaling mechanism of README.md:199-202, mapped
to ICI.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.lang.core import (
    cdiv,
    compiler_params,
    cost_estimate,
    round_up,
    tpu_call,
    use_interpret,
)
from triton_dist_tpu.runtime.init import SP_AXIS

NEG_INF = -1e30


def flash_decode_partial(
    q: jax.Array,  # (B, Hq, D) one decode token per sequence
    k_loc: jax.Array,  # (B, T_loc, Hkv, D) this rank's KV shard
    v_loc: jax.Array,
    valid_len: jax.Array,  # (B,) valid rows in this shard
    scale: Optional[float] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Partial attention over the local KV shard.

    Returns (o (B, Hq, D) f32 — the *unnormalized-softmax* partial output
    normalized by the local sum, and lse (B, Hq) f32 — the local
    log-sum-exp). Mirrors the reference's split-kv kernel contract
    (flash_decode.py:393-480)."""
    b, hq, d = q.shape
    _, t, hkv, _ = k_loc.shape
    g = hq // hkv
    scale = scale if scale is not None else d ** -0.5

    qf = q.astype(jnp.float32).reshape(b, hkv, g, d) * scale
    kf = k_loc.astype(jnp.float32)
    vf = v_loc.astype(jnp.float32)
    s = jnp.einsum("bkgd,btkd->bkgt", qf, kf)  # (B, Hkv, G, T)
    mask = jnp.arange(t)[None, :] < valid_len[:, None]  # (B, T)
    s = jnp.where(mask[:, None, None, :], s, NEG_INF)

    m = jnp.max(s, axis=-1, keepdims=True)  # (B, Hkv, G, 1)
    safe_m = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(s - safe_m)
    p = jnp.where(m <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bkgt,btkd->bkgd", p, vf) / jnp.maximum(l, 1e-30)
    lse = (safe_m + jnp.log(jnp.maximum(l, 1e-30)))[..., 0]  # (B, Hkv, G)
    empty = (m <= NEG_INF / 2)[..., 0]
    lse = jnp.where(empty, NEG_INF, lse)
    return o.reshape(b, hq, d), lse.reshape(b, hq)


def _fd_chunk(t: int, cap: int = 512) -> int:
    """KV page length: largest divisor of t <= cap whose offsets stay
    sublane-aligned; whole-shard when no aligned divisor exists."""
    cands = [c for c in range(8, min(cap, t) + 1, 8) if t % c == 0]
    return cands[-1] if cands else t


def _fd_partial_kernel(hq, hkv, d, t, chunk, scale,
                       len_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                       vkv, sems):
    """One grid step = one batch row: stream (chunk, Hkv*D) KV pages
    double-buffered from HBM and fold them into the online-softmax state
    (the reference's split-kv device loop, flash_decode.py:130-391; same
    page-streaming structure as the megakernel attention branch)."""
    b = pl.program_id(0)
    g = hq // hkv
    nch = t // chunk
    valid = len_ref[b]
    n_act = jnp.minimum(cdiv(valid, chunk), nch)

    w = hkv * d

    def kv_start(ci, slot):
        for which, ref in ((0, k_ref), (1, v_ref)):
            pltpu.make_async_copy(
                ref.at[b, pl.ds(ci * chunk, chunk)],
                vkv.at[slot, which],
                sems.at[slot],
            ).start()

    def kv_wait(slot):
        for which, ref in ((0, k_ref), (1, v_ref)):
            pltpu.make_async_copy(
                ref.at[0, pl.ds(0, chunk)], vkv.at[slot, which],
                sems.at[slot],
            ).wait()

    # Block-diagonal q: chunks stream CONTIGUOUSLY as (chunk, Hkv*D) —
    # one DMA per tensor per chunk at full burst width (per-head column
    # slices measured 256-byte bursts, and Mosaic rejects slicing the
    # head dim of the 4-D layout). The GQA structure moves into the
    # OPERAND instead: row h*G+i of qbd holds q[h*G+i] in head-h's
    # column block and zeros elsewhere, so one 2-D (Hq, W) x (W, chunk)
    # matmul yields exactly the per-head logits (cross-head terms
    # multiply zero blocks). The p@v product likewise runs full-width
    # and the head-diagonal is selected after. The inflated MXU flops
    # (x Hkv) are free — the kernel is HBM-bound by the KV stream.
    eye = jnp.eye(hkv, dtype=jnp.float32)
    qf = q_ref[0].astype(jnp.float32) * scale  # (Hq, D)
    qbd = (qf.reshape(hkv, g, 1, d)
           * eye[:, None, :, None]).reshape(hq, w)

    def chunk_update(ci, state):
        m, l, acc = state  # (Hq, 1), (Hq, 1), (Hq, D)
        kv = vkv[ci % 2].astype(jnp.float32)  # (2, chunk, W)
        lg = jax.lax.dot_general(
            qbd, kv[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (Hq, chunk)
        spos = jax.lax.broadcasted_iota(jnp.int32, (hq, chunk), 1) \
            + ci * chunk
        live = spos < valid
        lg = jnp.where(live, lg, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(lg, -1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.where(live, jnp.exp(lg - m_new), 0.0)
        l_new = l * alpha + jnp.sum(p, -1, keepdims=True)
        pv = jax.lax.dot_general(
            p, kv[1], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (Hq, W)
        diag = (pv.reshape(hkv, g, hkv, d)
                * eye[:, None, :, None]).sum(axis=2).reshape(hq, d)
        return (m_new, l_new, acc * alpha + diag)

    def loop_body(ci, state):
        @pl.when(ci + 1 < n_act)
        def _ahead():
            kv_start(ci + 1, (ci + 1) % 2)

        kv_wait(ci % 2)
        return chunk_update(ci, state)

    @pl.when(n_act > 0)
    def _first():
        kv_start(0, 0)

    state0 = (
        jnp.full((hq, 1), NEG_INF, jnp.float32),
        jnp.zeros((hq, 1), jnp.float32),
        jnp.zeros((hq, d), jnp.float32),
    )
    m, l, acc = jax.lax.fori_loop(0, n_act, loop_body, state0)

    empty = l <= 0.0
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = jnp.where(empty, 0.0, acc / l_safe)
    lse = jnp.maximum(m, NEG_INF / 2) + jnp.log(l_safe)
    lse = jnp.where(empty[:, 0], NEG_INF, lse[:, 0])
    # lse rides a (1, HQP) lane-padded row: a bare (1, Hq) block fails
    # native lowering when Hq < 128 and B > 1 (block != array dim)
    hqp = lse_ref.shape[-1]
    lse_ref[0, 0] = jnp.concatenate(
        [lse, jnp.zeros((hqp - hq,), jnp.float32)]) if hqp > hq else lse


def flash_decode_partial_pallas(
    q: jax.Array,  # (B, Hq, D)
    k_loc: jax.Array,  # (B, T_loc, Hkv, D)
    v_loc: jax.Array,
    valid_len: jax.Array,  # (B,)
    scale: Optional[float] = None,
    chunk: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Chunked Pallas local partial: same (o, lse) contract as
    flash_decode_partial, but KV streams through (chunk, Hkv*D) pages so
    peak memory is O(chunk), not O(T_loc) — the long-context regime the
    round-4 verdict asked for (ref split-kv kernel,
    flash_decode.py:130-391). Only pages intersecting a sequence's valid
    prefix are touched."""
    b, hq, d = q.shape
    _, t, hkv, _ = k_loc.shape
    scale = scale if scale is not None else d ** -0.5
    c = chunk or _fd_chunk(t)
    assert t % c == 0, f"chunk {c} must divide T_loc {t}"
    w = hkv * d
    hqp = round_up(hq, 128)
    k2 = k_loc.reshape(b, t, w)
    v2 = v_loc.reshape(b, t, w)
    itemsize = jnp.dtype(k_loc.dtype).itemsize
    o, lse = tpu_call(
        functools.partial(_fd_partial_kernel, hq, hkv, d, t, c,
                          float(scale)),
        grid=(b,),
        out_shape=(
            jax.ShapeDtypeStruct((b, hq, d), jnp.float32),
            jax.ShapeDtypeStruct((b, 1, hqp), jnp.float32),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, hq, d), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(
            pl.BlockSpec((1, hq, d), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, hqp), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, 2, c, w), k_loc.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=compiler_params(
            vmem_limit_bytes=4 * 2 * c * w * itemsize + (4 << 20),
        ),
        cost_estimate=cost_estimate(
            flops=4 * b * hq * t * d,
            bytes_accessed=2 * b * t * w * itemsize,
        ),
    )(jnp.asarray(valid_len, jnp.int32), q, k2, v2)
    return o, lse[:, 0, :hq]


def flash_decode_combine(
    o_parts: jax.Array,  # (n, B, Hq, D) f32 per-rank partials
    lse_parts: jax.Array,  # (n, B, Hq) f32
) -> jax.Array:
    """Online-softmax merge of per-rank partials
    (ref inter-rank combine: flash_decode.py:482-531)."""
    lse_max = jnp.max(lse_parts, axis=0, keepdims=True)  # (1, B, Hq)
    safe = jnp.maximum(lse_max, NEG_INF / 2)
    w = jnp.exp(lse_parts - safe)  # (n, B, Hq)
    w = jnp.where(lse_parts <= NEG_INF / 2, 0.0, w)
    denom = jnp.maximum(jnp.sum(w, axis=0), 1e-30)  # (B, Hq)
    out = jnp.einsum("nbh,nbhd->bhd", w, o_parts) / denom[..., None]
    return out


def partials_buf_shape(b: int, hq: int, d: int) -> Tuple[int, int]:
    """Per-rank payload shape of the packed (o, lse) LL-AG exchange."""
    return (b, round_up(hq * d + hq, 128))


def create_sp_decode_buf(b: int, hq: int, d: int, n: int) -> jax.Array:
    """Persistent LL-AG context for sp_flash_decode's partial exchange
    (the FastAllGatherContext the reference's SP decode layer holds,
    sp_flash_decode_layer.py:113-146). Thread through decode steps."""
    from triton_dist_tpu.kernels.low_latency_allgather import (
        create_ll_ag_buffer,
    )

    return create_ll_ag_buffer(partials_buf_shape(b, hq, d), jnp.float32,
                               n)


def sp_flash_decode(
    q: jax.Array,  # (B, Hq, D)
    k_shard: jax.Array,  # (B, T_max/n, Hkv, D) per-rank cache shard
    v_shard: jax.Array,
    kv_len: jax.Array,  # (B,) GLOBAL valid length
    axis: str = SP_AXIS,
    scale: Optional[float] = None,
    ll_buf: Optional[jax.Array] = None,
    call_count=0,
    partial_impl: str = "auto",
    chunk: Optional[int] = None,
):
    """Distributed decode over a sequence-sharded KV cache; per-device
    inside shard_map. Rank r owns global positions
    [r*T_loc, (r+1)*T_loc). Returns (B, Hq, D) in q.dtype, replicated
    (ref layer: sp_flash_decode_layer.py:44-110).

    ll_buf: LL-allgather context from create_sp_decode_buf — the (o, lse)
    partials then ride ONE low-latency fcollect (packed payload; the
    reference's fast-allgather exchange, sp_flash_decode_layer.py:136-146)
    instead of two XLA all_gathers, and the call returns (out, new_buf)
    with call_count the 0-based step index on that context.
    partial_impl: "xla" | "pallas" | "auto" (pallas — the chunked-KV
    streaming kernel — on native TPU at long T_loc)."""
    me = jax.lax.axis_index(axis)
    b, hq, d = q.shape
    t_loc = k_shard.shape[1]
    local_len = jnp.clip(kv_len - me * t_loc, 0, t_loc)
    if partial_impl == "auto":
        # pallas only when a bounded KV page exists: _fd_chunk's
        # whole-shard fallback (T_loc with no aligned divisor) would put
        # the full shard in VMEM scratch and fail Mosaic compilation on
        # exactly the long-context path this heuristic targets
        partial_impl = (
            "pallas" if not use_interpret() and t_loc >= 2048
            and d % 128 == 0 and _fd_chunk(t_loc) <= 1024 else "xla"
        )
    if partial_impl == "pallas":
        o, lse = flash_decode_partial_pallas(q, k_shard, v_shard,
                                             local_len, scale, chunk)
    else:
        o, lse = flash_decode_partial(q, k_shard, v_shard, local_len,
                                      scale)
    if ll_buf is not None:
        from triton_dist_tpu.kernels.low_latency_allgather import (
            ll_all_gather,
        )

        w = hq * d + hq
        wp = partials_buf_shape(b, hq, d)[1]
        payload = jnp.concatenate([o.reshape(b, hq * d), lse], axis=-1)
        payload = jnp.pad(payload, ((0, 0), (0, wp - w)))
        from triton_dist_tpu.faults import guard as _guard
        from triton_dist_tpu.obs import stats as _obs

        gathered, new_buf = _guard.primary(_obs.primary(
            ll_all_gather(payload, ll_buf, call_count, axis)))
        n = gathered.shape[0]
        o_parts = gathered[..., :hq * d].reshape(n, b, hq, d)
        lse_parts = gathered[..., hq * d:w]
        out = flash_decode_combine(o_parts, lse_parts)
        return out.astype(q.dtype), new_buf
    # small-message exchange of partials via XLA collectives
    o_parts = jax.lax.all_gather(o, axis)  # (n, B, Hq, D)
    lse_parts = jax.lax.all_gather(lse, axis)  # (n, B, Hq)
    out = flash_decode_combine(o_parts, lse_parts)
    return out.astype(q.dtype)
