"""Distributed flash-decode — split-KV decode with cross-rank LSE combine.

TPU-native re-design of the reference's distributed flash-decode
(ref: python/triton_dist/kernels/nvidia/flash_decode.py: split-KV GQA
decode :130/:587, intra-rank combine emitting (acc, lse) partials :393-480,
inter-rank online-softmax combine :482-531). The KV cache shards by
sequence across the sp axis; each rank computes a masked partial decode
over its shard plus the log-sum-exp, the (acc, lse) partials are exchanged
with a small-message allgather (the reference uses its LL allgather for
this, sp_flash_decode_layer.py:136-146), and the merge is the standard
attention-partial combine: out = Σ_i exp(lse_i - lse*) o_i / Σ_i
exp(lse_i - lse*).

This is the 1→32-GPU decode-scaling mechanism of README.md:199-202, mapped
to ICI.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from triton_dist_tpu.runtime.init import SP_AXIS

NEG_INF = -1e30


def flash_decode_partial(
    q: jax.Array,  # (B, Hq, D) one decode token per sequence
    k_loc: jax.Array,  # (B, T_loc, Hkv, D) this rank's KV shard
    v_loc: jax.Array,
    valid_len: jax.Array,  # (B,) valid rows in this shard
    scale: Optional[float] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Partial attention over the local KV shard.

    Returns (o (B, Hq, D) f32 — the *unnormalized-softmax* partial output
    normalized by the local sum, and lse (B, Hq) f32 — the local
    log-sum-exp). Mirrors the reference's split-kv kernel contract
    (flash_decode.py:393-480)."""
    b, hq, d = q.shape
    _, t, hkv, _ = k_loc.shape
    g = hq // hkv
    scale = scale if scale is not None else d ** -0.5

    qf = q.astype(jnp.float32).reshape(b, hkv, g, d) * scale
    kf = k_loc.astype(jnp.float32)
    vf = v_loc.astype(jnp.float32)
    s = jnp.einsum("bkgd,btkd->bkgt", qf, kf)  # (B, Hkv, G, T)
    mask = jnp.arange(t)[None, :] < valid_len[:, None]  # (B, T)
    s = jnp.where(mask[:, None, None, :], s, NEG_INF)

    m = jnp.max(s, axis=-1, keepdims=True)  # (B, Hkv, G, 1)
    safe_m = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(s - safe_m)
    p = jnp.where(m <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bkgt,btkd->bkgd", p, vf) / jnp.maximum(l, 1e-30)
    lse = (safe_m + jnp.log(jnp.maximum(l, 1e-30)))[..., 0]  # (B, Hkv, G)
    empty = (m <= NEG_INF / 2)[..., 0]
    lse = jnp.where(empty, NEG_INF, lse)
    return o.reshape(b, hq, d), lse.reshape(b, hq)


def flash_decode_combine(
    o_parts: jax.Array,  # (n, B, Hq, D) f32 per-rank partials
    lse_parts: jax.Array,  # (n, B, Hq) f32
) -> jax.Array:
    """Online-softmax merge of per-rank partials
    (ref inter-rank combine: flash_decode.py:482-531)."""
    lse_max = jnp.max(lse_parts, axis=0, keepdims=True)  # (1, B, Hq)
    safe = jnp.maximum(lse_max, NEG_INF / 2)
    w = jnp.exp(lse_parts - safe)  # (n, B, Hq)
    w = jnp.where(lse_parts <= NEG_INF / 2, 0.0, w)
    denom = jnp.maximum(jnp.sum(w, axis=0), 1e-30)  # (B, Hq)
    out = jnp.einsum("nbh,nbhd->bhd", w, o_parts) / denom[..., None]
    return out


def sp_flash_decode(
    q: jax.Array,  # (B, Hq, D)
    k_shard: jax.Array,  # (B, T_max/n, Hkv, D) per-rank cache shard
    v_shard: jax.Array,
    kv_len: jax.Array,  # (B,) GLOBAL valid length
    axis: str = SP_AXIS,
    scale: Optional[float] = None,
) -> jax.Array:
    """Distributed decode over a sequence-sharded KV cache; per-device
    inside shard_map. Rank r owns global positions
    [r*T_loc, (r+1)*T_loc). Returns (B, Hq, D) in q.dtype, replicated
    (ref layer: sp_flash_decode_layer.py:44-110)."""
    me = jax.lax.axis_index(axis)
    t_loc = k_shard.shape[1]
    local_len = jnp.clip(kv_len - me * t_loc, 0, t_loc)
    o, lse = flash_decode_partial(q, k_shard, v_shard, local_len, scale)
    # small-message exchange of partials (the LL allgather analog)
    o_parts = jax.lax.all_gather(o, axis)  # (n, B, Hq, D)
    lse_parts = jax.lax.all_gather(lse, axis)  # (n, B, Hq)
    out = flash_decode_combine(o_parts, lse_parts)
    return out.astype(q.dtype)
