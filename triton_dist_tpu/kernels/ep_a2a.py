"""EP dispatch/combine — expert-parallel token exchange over the A2A.

TPU-native re-design of the reference's EP A2A
(ref: python/triton_dist/kernels/nvidia/ep_a2a.py:37-150
`kernel_dispatch_token`: tokens pushed to expert-owner ranks with per-
expert atomic slot allocation; :152 `kernel_combine_token`; :244-309 splits
AG + recv-offset calculation). The reference allocates receive slots with
device atomics because its shapes are dynamic; XLA requires static shapes,
so the TPU design uses the standard capacity-factor formulation: each rank
packs at most `capacity` routed (token, expert) pairs per destination rank
— overflow tokens are dropped from the MoE sum (they keep their residual
path), the same trade GShard/Switch make. The transport is the one-kernel
Pallas all_to_all (all segments + counts in flight concurrently).

Metadata rides as a second A2A payload: (src_row, local_expert, weight,
valid) per slot, the splits-metadata analog.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from triton_dist_tpu.kernels.all_to_all import all_to_all, all_to_all_ref
from triton_dist_tpu.kernels.grouped_gemm import grouped_gemm
from triton_dist_tpu.lang.core import interpret_no_headroom
from triton_dist_tpu.runtime.init import EP_AXIS


class EPDispatch(NamedTuple):
    """Received tokens + routing metadata after dispatch.

    The send_* fields are the ORIGIN-side copies of what this rank packed
    for each destination: combine pairs them with the returning segments
    (back[j] is by construction the segment we sent to rank j), so no
    metadata needs to travel back — one a2a saved per combine."""

    x: jax.Array  # (n, C, H) tokens from each source rank
    local_expert: jax.Array  # (n, C) expert index within this rank
    valid: jax.Array  # (n, C) bool, recv side
    counts: jax.Array  # (n,) valid slots per received segment
    send_src_row: jax.Array  # (n, C) our token row per sent slot
    send_weight: jax.Array  # (n, C) topk weight per sent slot
    send_valid: jax.Array  # (n, C) bool, send side
    send_counts: jax.Array  # (n,) slots we sent per destination


_FP8_MAX = 448.0  # e4m3 finite max


def _byte_wire(payload_dtype) -> bool:
    """True for the fp8 wire format; rejects unsupported widths loudly
    (a silently-ignored payload_dtype would ship a full-width wire while
    the caller believes it halved the ICI bytes)."""
    if payload_dtype is None:
        return False
    if jnp.dtype(payload_dtype).itemsize != 1:
        raise ValueError(
            f"payload_dtype {jnp.dtype(payload_dtype).name} unsupported: "
            "the quantized wire format requires a 1-byte dtype "
            "(jnp.float8_e4m3fn) or None for the full-width x.dtype wire"
        )
    return True


def _quantize_fp8(x):
    """Per-token e4m3 quantization -> (q (M, H) fp8, scale (M,) f32)
    (ref: the fp8 payload + scale plane of the LL dispatch,
    low_latency_all_to_all.py:36-118)."""
    s = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1) / _FP8_MAX
    s = jnp.maximum(s, 1e-12)
    q = (x.astype(jnp.float32) / s[:, None]).astype(jnp.float8_e4m3fn)
    return q, s


def _pack_by_dest(x, ids, weights, n_ranks, experts_per_rank, capacity,
                  payload_dtype=None):
    """Build fixed-capacity per-destination send buffers.

    x: (M, H); ids/weights: (M, k). Returns (send_x (n, C, H_pad) with the
    local-expert id folded into column H of the lane padding — one a2a
    moves tokens AND routing; send_row/send_w/valid (n, C) origin-side
    combine metadata; counts (n,)). Slot allocation is a stable sort by
    destination rank — the static analog of the reference's atomic slot
    counter (ep_a2a.py:133-150).

    payload_dtype=float8_e4m3fn selects the fp8 wire format (half the ICI
    bytes of bf16 — the reference's 137 us dispatch class): tokens are
    per-token-scale quantized and the f32 scale + int32 expert id are
    bitcast into 8 lane-padding byte columns.
    """
    m, k = ids.shape
    c = capacity
    flat_ids = ids.reshape(-1)
    dest = flat_ids // experts_per_rank  # (M*k,)
    order = jnp.argsort(dest, stable=True)
    seg_count = jnp.bincount(dest, length=n_ranks)
    seg_start = jnp.cumsum(seg_count) - seg_count

    # GATHER formulation: for each send slot (d, p), the flat entry that
    # fills it is order[seg_start[d] + p] (valid while p < seg_count[d]).
    # The scatter formulation (send.at[slot].set) lowers to an XLA
    # row-scatter that executes ~serially on TPU — measured 6.9 ms for
    # the 128-token fp8 dispatch vs ~sub-ms for these dense gathers.
    # Overflow (p >= capacity) is simply never gathered: same
    # GShard-style drop semantics as before.
    slot_dest = (jnp.arange(n_ranks * c) // c).astype(jnp.int32)
    slot_pos = (jnp.arange(n_ranks * c) % c).astype(jnp.int32)
    valid = slot_pos < jnp.minimum(seg_count, c)[slot_dest]
    entry = order[jnp.minimum(seg_start[slot_dest] + slot_pos, m * k - 1)]
    src_rows = jnp.where(valid, (entry // k).astype(jnp.int32), 0)
    local_exp = jnp.where(
        valid, (flat_ids[entry] % experts_per_rank).astype(jnp.int32), 0
    )
    w_flat = jnp.where(
        valid, weights.reshape(-1)[entry].astype(jnp.float32), 0.0
    )

    h = x.shape[1]
    if _byte_wire(payload_dtype):
        # fp8 wire format: quantized tokens + bitcast (scale, expert id)
        q, scale = _quantize_fp8(x)
        h_pad = -(-(h + 8) // 128) * 128  # +8 byte columns of metadata
        tokens = jnp.where(valid[:, None], q[src_rows],
                           jnp.zeros((), payload_dtype))
        meta = jnp.concatenate([
            jax.lax.bitcast_convert_type(
                jnp.where(valid, scale[src_rows], 0.0), jnp.uint8),
            jax.lax.bitcast_convert_type(local_exp, jnp.uint8),
        ], axis=-1)  # (n*C, 8)
        send_x = jnp.concatenate([
            tokens,
            jax.lax.bitcast_convert_type(meta, payload_dtype),
            jnp.zeros((n_ranks * c, h_pad - h - 8), payload_dtype),
        ], axis=-1)
    else:
        # Fold the travelling metadata (local expert id, the only field
        # the recv side needs) into lane-padding columns of the token
        # payload so a SINGLE a2a moves tokens + routing. Expert ids are
        # small integers and exact in bf16 (<= 256).
        assert experts_per_rank <= 256 or jnp.dtype(x.dtype).itemsize >= 4, (
            "expert id not exactly representable in bf16 lane padding"
        )
        h_pad = -(-(h + 1) // 128) * 128  # round_up(H+1, 128)
        tokens = jnp.where(valid[:, None], x[src_rows],
                           jnp.zeros((), x.dtype))
        send_x = jnp.concatenate([
            tokens,
            local_exp.astype(x.dtype)[:, None],
            jnp.zeros((n_ranks * c, h_pad - h - 1), x.dtype),
        ], axis=-1)
    counts = jnp.minimum(seg_count, capacity).astype(jnp.int32)
    return (
        send_x.reshape(n_ranks, c, h_pad),
        src_rows.reshape(n_ranks, c),
        w_flat.reshape(n_ranks, c),
        valid.reshape(n_ranks, c),
        counts,
    )


def ep_dispatch(
    x: jax.Array,  # (M, H) this rank's tokens
    topk_ids: jax.Array,  # (M, k) global expert ids
    topk_weights: jax.Array,  # (M, k)
    n_experts: int,
    capacity: int,
    axis: str = EP_AXIS,
    payload_dtype=None,
) -> EPDispatch:
    """Route tokens to their expert-owner ranks (ref dispatch path,
    ep_a2a.py:37-150 + layers/nvidia/ep_a2a_layer.py:195).

    payload_dtype=jnp.float8_e4m3fn dispatches on the fp8 wire format
    (the reference's latency-class configuration, README.md:93: 128
    tok/rank topk=8 hidden=7168 fp8 at 137 us); tokens are dequantized
    to x.dtype on arrival."""
    n = jax.lax.axis_size(axis)
    h = x.shape[1]
    experts_per_rank = n_experts // n
    send_x, send_row, send_w, send_valid, counts = _pack_by_dest(
        x, topk_ids, topk_weights, n, experts_per_rank, capacity,
        payload_dtype,
    )
    a2a = all_to_all_ref if interpret_no_headroom() else all_to_all
    recv, recv_counts = a2a(send_x, counts, axis)
    slot_idx = jnp.arange(capacity)[None, :]
    recv_valid = slot_idx < recv_counts[:, None]
    if _byte_wire(payload_dtype):
        meta = jax.lax.bitcast_convert_type(
            recv[..., h:h + 8], jnp.uint8
        ).reshape(n, capacity, 8)
        scale = jax.lax.bitcast_convert_type(
            meta[..., :4], jnp.float32
        ).reshape(n, capacity)
        local_expert = jax.lax.bitcast_convert_type(
            meta[..., 4:], jnp.int32
        ).reshape(n, capacity)
        tokens = (recv[..., :h].astype(jnp.float32)
                  * scale[..., None]).astype(x.dtype)
    else:
        tokens = recv[..., :h]
        local_expert = recv[..., h].astype(jnp.int32)
    return EPDispatch(
        x=tokens,
        local_expert=local_expert,
        valid=recv_valid,
        counts=recv_counts,
        send_src_row=send_row,
        send_weight=send_w,
        send_valid=send_valid,
        send_counts=counts,
    )


def ep_expert_ffn(
    disp: EPDispatch,
    w_gate_up: jax.Array,  # (E_loc, H, 2I)
    w_down: jax.Array,  # (E_loc, I, H)
) -> jax.Array:
    """Run this rank's experts over the received tokens -> (n, C, H).

    Tokens are sorted by local expert (invalid slots to a trailing null
    group) so the grouped GEMM sees contiguous segments."""
    e_loc = w_gate_up.shape[0]
    n, c, h = disp.x.shape
    t = n * c
    x_flat = disp.x.reshape(t, h)
    exp = jnp.where(disp.valid.reshape(t), disp.local_expert.reshape(t), e_loc)
    order = jnp.argsort(exp, stable=True).astype(jnp.int32)
    inv = jnp.argsort(order, stable=True).astype(jnp.int32)
    group_sizes = jnp.bincount(exp, length=e_loc + 1).astype(jnp.int32)

    x_sorted = x_flat[order]
    # null group (invalid slots) gets expert 0's weights; its outputs are
    # masked out below
    w_gu = jnp.concatenate([w_gate_up, w_gate_up[:1]], axis=0)
    w_dn = jnp.concatenate([w_down, w_down[:1]], axis=0)
    hh = grouped_gemm(x_sorted, w_gu, group_sizes, out_dtype=jnp.float32)
    gate, up = jnp.split(hh, 2, axis=-1)
    act = (jax.nn.silu(gate) * up).astype(disp.x.dtype)
    y_sorted = grouped_gemm(act, w_dn, group_sizes, out_dtype=jnp.float32)
    y = y_sorted[inv].reshape(n, c, h)
    return jnp.where(disp.valid[..., None], y, 0.0)


def ep_combine(
    y: jax.Array,  # (n, C, H) expert outputs per source rank
    disp: EPDispatch,
    m: int,
    out_dtype,
    axis: str = EP_AXIS,
) -> jax.Array:
    """Send results back to their source ranks and weighted-scatter into
    (M, H) (ref combine path, ep_a2a.py:152 + ep_a2a_layer.py:240).

    back[j] is the segment this rank originally packed for rank j, so the
    origin-side send_* metadata pairs with it directly — no metadata
    travels back."""
    a2a = all_to_all_ref if interpret_no_headroom() else all_to_all
    back, _ = a2a(y.astype(jnp.float32), disp.counts, axis)
    n, c, h = back.shape
    rows = disp.send_src_row.reshape(-1)
    w = jnp.where(disp.send_valid, disp.send_weight, 0.0).reshape(-1)
    contrib = back.reshape(-1, h) * w[:, None]
    out = jnp.zeros((m, h), jnp.float32)
    out = out.at[rows].add(contrib, mode="drop")
    return out.astype(out_dtype)
