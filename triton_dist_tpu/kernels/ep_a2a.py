"""EP dispatch/combine — expert-parallel token exchange over the A2A.

TPU-native re-design of the reference's EP A2A
(ref: python/triton_dist/kernels/nvidia/ep_a2a.py:37-150
`kernel_dispatch_token`: tokens pushed to expert-owner ranks with per-
expert atomic slot allocation; :152 `kernel_combine_token`; :244-309 splits
AG + recv-offset calculation). The reference allocates receive slots with
device atomics because its shapes are dynamic; XLA requires static shapes,
so the TPU design uses the standard capacity-factor formulation: each rank
packs at most `capacity` routed (token, expert) pairs per destination rank
— overflow tokens are dropped from the MoE sum (they keep their residual
path), the same trade GShard/Switch make. The transport is the one-kernel
Pallas all_to_all (all segments + counts in flight concurrently).

Metadata rides as a second A2A payload: (src_row, local_expert, weight,
valid) per slot, the splits-metadata analog.

The CHUNK-PIPELINED path (ep_dispatch_chunked / ep_expert_ffn_chunked /
ep_combine_chunked, assembled by ep_moe_pipeline) is the TPU analog of
the reference's double-buffered dispatch/combine overlap
(layers/nvidia/ep_a2a_layer.py:118-138): segments are expert-sorted at
pack time and their per-expert counts travel with the splits metadata,
so the receive side needs NO runtime sort — each capacity chunk's
grouped-GEMM segment structure is derived arithmetically
(moe_utils.chunk_group_sizes) and the FFN can run chunk-by-chunk as
chunks arrive (all_to_all_chunked's per-chunk delivery semaphores).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from triton_dist_tpu.kernels.all_to_all import (
    all_to_all,
    all_to_all_chunked,
    all_to_all_ref,
)
from triton_dist_tpu.kernels.grouped_gemm import grouped_gemm
from triton_dist_tpu.kernels.moe_utils import chunk_group_sizes, silu_mul
from triton_dist_tpu.lang.core import interpret_no_headroom
from triton_dist_tpu.runtime.init import EP_AXIS
from triton_dist_tpu.trace import events as trace_ev
from triton_dist_tpu.wire import codec as wcodec


class EPDispatch(NamedTuple):
    """Received tokens + routing metadata after dispatch.

    The send_* fields are the ORIGIN-side copies of what this rank packed
    for each destination: combine pairs them with the returning segments
    (back[j] is by construction the segment we sent to rank j), so no
    metadata needs to travel back — one a2a saved per combine."""

    x: jax.Array  # (n, C, H) tokens from each source rank
    local_expert: jax.Array  # (n, C) expert index within this rank
    valid: jax.Array  # (n, C) bool, recv side
    counts: jax.Array  # (n,) valid slots per received segment
    send_src_row: jax.Array  # (n, C) our token row per sent slot
    send_weight: jax.Array  # (n, C) topk weight per sent slot
    send_valid: jax.Array  # (n, C) bool, send side
    send_counts: jax.Array  # (n,) slots we sent per destination
    drops: jax.Array  # () int32 — (token, choice) pairs beyond capacity


def _byte_wire(payload_dtype) -> bool:
    """True for the fp8 wire format; rejects unsupported widths loudly
    (a silently-ignored payload_dtype would ship a full-width wire while
    the caller believes it halved the ICI bytes)."""
    if payload_dtype is None:
        return False
    if jnp.dtype(payload_dtype).itemsize != 1:
        raise ValueError(
            f"payload_dtype {jnp.dtype(payload_dtype).name} unsupported: "
            "the quantized wire format requires a 1-byte dtype "
            "(jnp.float8_e4m3fn) or None for the full-width x.dtype wire"
        )
    return True


def _quantize_fp8(x):
    """Per-token e4m3 quantization -> (q (M, H) fp8, scale (M,) f32).

    THE shared codec definition (wire.quantize at per-row granularity)
    — this module's original formula moved there verbatim when the wire
    plane landed (ISSUE 9); the dedupe test pins the payloads bitwise
    so the repo has exactly one quantization definition
    (ref: the fp8 payload + scale plane of the LL dispatch,
    low_latency_all_to_all.py:36-118)."""
    q, s = wcodec.quantize(x, wcodec.FP8)
    return q, s[..., 0]


class _Pack(NamedTuple):
    """Origin-side pack of routed tokens into per-destination buffers."""

    send_x: jax.Array      # (n, C, H_pad) wire payload
    src_rows: jax.Array    # (n, C) our token row per slot
    weights: jax.Array     # (n, C) topk weight per slot
    valid: jax.Array       # (n, C) bool
    counts: jax.Array      # (n,) valid slots per destination
    drops: jax.Array       # () int32 pairs beyond capacity
    exp_counts: Optional[jax.Array]  # (n, E_loc) when expert_sorted


def _pack_by_dest(x, ids, weights, n_ranks, experts_per_rank, capacity,
                  payload_dtype=None, expert_sorted=False) -> _Pack:
    """Build fixed-capacity per-destination send buffers.

    x: (M, H); ids/weights: (M, k). Returns a _Pack: send_x (n, C, H_pad)
    with the local-expert id folded into column H of the lane padding —
    one a2a moves tokens AND routing; src_rows/weights/valid (n, C)
    origin-side combine metadata; counts (n,); drops, the overflow stat.
    Slot allocation is a stable sort by destination rank — the static
    analog of the reference's atomic slot counter (ep_a2a.py:133-150).

    expert_sorted=True re-orders each destination block's KEPT slots by
    local expert (invalid slots packed at the tail) for the chunked
    pipeline: the receive side then needs no runtime sort, only the
    per-(destination, expert) counts returned in exp_counts. Which pairs
    survive the capacity cut is decided BEFORE this permutation, so
    routing and drops are identical to the unsorted pack — the
    overlapped and sequential MoE paths drop the same tokens.

    payload_dtype=float8_e4m3fn selects the fp8 wire format (half the ICI
    bytes of bf16 — the reference's 137 us dispatch class): tokens are
    per-token-scale quantized and the f32 scale + int32 expert id are
    bitcast into 8 lane-padding byte columns.
    """
    m, k = ids.shape
    c = capacity
    flat_ids = ids.reshape(-1)
    dest = flat_ids // experts_per_rank  # (M*k,)
    order = jnp.argsort(dest, stable=True)
    seg_count = jnp.bincount(dest, length=n_ranks)
    seg_start = jnp.cumsum(seg_count) - seg_count

    # GATHER formulation: for each send slot (d, p), the flat entry that
    # fills it is order[seg_start[d] + p] (valid while p < seg_count[d]).
    # The scatter formulation (send.at[slot].set) lowers to an XLA
    # row-scatter that executes ~serially on TPU — measured 6.9 ms for
    # the 128-token fp8 dispatch vs ~sub-ms for these dense gathers.
    # Overflow (p >= capacity) is simply never gathered: same
    # GShard-style drop semantics as before.
    slot_dest = (jnp.arange(n_ranks * c) // c).astype(jnp.int32)
    slot_pos = (jnp.arange(n_ranks * c) % c).astype(jnp.int32)
    valid = slot_pos < jnp.minimum(seg_count, c)[slot_dest]
    entry = order[jnp.minimum(seg_start[slot_dest] + slot_pos, m * k - 1)]
    src_rows = jnp.where(valid, (entry // k).astype(jnp.int32), 0)
    local_exp = jnp.where(
        valid, (flat_ids[entry] % experts_per_rank).astype(jnp.int32), 0
    )
    w_flat = jnp.where(
        valid, weights.reshape(-1)[entry].astype(jnp.float32), 0.0
    )

    exp_counts = None
    if expert_sorted:
        # Second stable sort, WITHIN destination blocks only (slot_dest
        # is the major key, and slots are already dest-major, so the
        # global stable sort never crosses a block boundary): valid
        # slots grouped by local expert, invalid slots to the tail.
        key = slot_dest * (experts_per_rank + 1) + jnp.where(
            valid, local_exp, experts_per_rank
        )
        perm = jnp.argsort(key, stable=True)
        src_rows = src_rows[perm]
        local_exp = local_exp[perm]
        w_flat = w_flat[perm]
        valid = valid[perm]
        exp_counts = jnp.bincount(
            jnp.where(valid, slot_dest * experts_per_rank + local_exp,
                      n_ranks * experts_per_rank),
            length=n_ranks * experts_per_rank + 1,
        )[:-1].reshape(n_ranks, experts_per_rank).astype(jnp.int32)

    h = x.shape[1]
    if _byte_wire(payload_dtype):
        # fp8 wire format: quantized tokens + bitcast (scale, expert id)
        q, scale = _quantize_fp8(x)
        h_pad = -(-(h + 8) // 128) * 128  # +8 byte columns of metadata
        tokens = jnp.where(valid[:, None], q[src_rows],
                           jnp.zeros((), payload_dtype))
        meta = jnp.concatenate([
            jax.lax.bitcast_convert_type(
                jnp.where(valid, scale[src_rows], 0.0), jnp.uint8),
            jax.lax.bitcast_convert_type(local_exp, jnp.uint8),
        ], axis=-1)  # (n*C, 8)
        send_x = jnp.concatenate([
            tokens,
            jax.lax.bitcast_convert_type(meta, payload_dtype),
            jnp.zeros((n_ranks * c, h_pad - h - 8), payload_dtype),
        ], axis=-1)
    else:
        # Fold the travelling metadata (local expert id, the only field
        # the recv side needs) into lane-padding columns of the token
        # payload so a SINGLE a2a moves tokens + routing. Expert ids are
        # small integers and exact in bf16 (<= 256).
        assert experts_per_rank <= 256 or jnp.dtype(x.dtype).itemsize >= 4, (
            "expert id not exactly representable in bf16 lane padding"
        )
        h_pad = -(-(h + 1) // 128) * 128  # round_up(H+1, 128)
        tokens = jnp.where(valid[:, None], x[src_rows],
                           jnp.zeros((), x.dtype))
        send_x = jnp.concatenate([
            tokens,
            local_exp.astype(x.dtype)[:, None],
            jnp.zeros((n_ranks * c, h_pad - h - 1), x.dtype),
        ], axis=-1)
    counts = jnp.minimum(seg_count, capacity).astype(jnp.int32)
    drops = jnp.sum(jnp.maximum(seg_count - capacity, 0)).astype(jnp.int32)
    return _Pack(
        send_x=send_x.reshape(n_ranks, c, h_pad),
        src_rows=src_rows.reshape(n_ranks, c),
        weights=w_flat.reshape(n_ranks, c),
        valid=valid.reshape(n_ranks, c),
        counts=counts,
        drops=drops,
        exp_counts=exp_counts,
    )


def ep_dispatch(
    x: jax.Array,  # (M, H) this rank's tokens
    topk_ids: jax.Array,  # (M, k) global expert ids
    topk_weights: jax.Array,  # (M, k)
    n_experts: int,
    capacity: int,
    axis: str = EP_AXIS,
    payload_dtype=None,
) -> EPDispatch:
    """Route tokens to their expert-owner ranks (ref dispatch path,
    ep_a2a.py:37-150 + layers/nvidia/ep_a2a_layer.py:195).

    payload_dtype=jnp.float8_e4m3fn dispatches on the fp8 wire format
    (the reference's latency-class configuration, README.md:93: 128
    tok/rank topk=8 hidden=7168 fp8 at 137 us); tokens are dequantized
    to x.dtype on arrival."""
    n = jax.lax.axis_size(axis)
    h = x.shape[1]
    experts_per_rank = n_experts // n
    pack = _pack_by_dest(
        x, topk_ids, topk_weights, n, experts_per_rank, capacity,
        payload_dtype,
    )
    a2a = all_to_all_ref if interpret_no_headroom() else all_to_all
    recv, recv_counts = a2a(pack.send_x, pack.counts, axis)
    slot_idx = jnp.arange(capacity)[None, :]
    recv_valid = slot_idx < recv_counts[:, None]
    tokens, local_expert = _decode_payload(recv, h, n, capacity,
                                           payload_dtype, x.dtype)
    return EPDispatch(
        x=tokens,
        local_expert=local_expert,
        valid=recv_valid,
        counts=recv_counts,
        send_src_row=pack.src_rows,
        send_weight=pack.weights,
        send_valid=pack.valid,
        send_counts=pack.counts,
        drops=pack.drops,
    )


def _decode_payload(recv, h, n, capacity, payload_dtype, out_dtype):
    """Unfold the wire payload: (tokens (n, C, H) in out_dtype,
    local_expert (n, C) int32) — fp8 dequant or lane-padding extraction."""
    if _byte_wire(payload_dtype):
        meta = jax.lax.bitcast_convert_type(
            recv[..., h:h + 8], jnp.uint8
        ).reshape(n, capacity, 8)
        scale = jax.lax.bitcast_convert_type(
            meta[..., :4], jnp.float32
        ).reshape(n, capacity)
        local_expert = jax.lax.bitcast_convert_type(
            meta[..., 4:], jnp.int32
        ).reshape(n, capacity)
        # shared codec decode (wire.dequantize): f32 multiply, cast last
        tokens = wcodec.dequantize(recv[..., :h], scale[..., None],
                                   wcodec.FP8, out_dtype)
    else:
        tokens = recv[..., :h]
        local_expert = recv[..., h].astype(jnp.int32)
    return tokens, local_expert


def ep_expert_ffn(
    disp: EPDispatch,
    w_gate_up: jax.Array,  # (E_loc, H, 2I)
    w_down: jax.Array,  # (E_loc, I, H)
) -> jax.Array:
    """Run this rank's experts over the received tokens -> (n, C, H).

    Tokens are sorted by local expert (invalid slots to a trailing null
    group) so the grouped GEMM sees contiguous segments."""
    e_loc = w_gate_up.shape[0]
    n, c, h = disp.x.shape
    t = n * c
    x_flat = disp.x.reshape(t, h)
    exp = jnp.where(disp.valid.reshape(t), disp.local_expert.reshape(t), e_loc)
    order = jnp.argsort(exp, stable=True).astype(jnp.int32)
    inv = jnp.argsort(order, stable=True).astype(jnp.int32)
    group_sizes = jnp.bincount(exp, length=e_loc + 1).astype(jnp.int32)

    x_sorted = x_flat[order]
    # null group (invalid slots) gets expert 0's weights; its outputs are
    # masked out below
    w_gu = jnp.concatenate([w_gate_up, w_gate_up[:1]], axis=0)
    w_dn = jnp.concatenate([w_down, w_down[:1]], axis=0)
    hh = grouped_gemm(x_sorted, w_gu, group_sizes, out_dtype=jnp.float32)
    act = silu_mul(hh).astype(disp.x.dtype)
    y_sorted = grouped_gemm(act, w_dn, group_sizes, out_dtype=jnp.float32)
    y = y_sorted[inv].reshape(n, c, h)
    return jnp.where(disp.valid[..., None], y, 0.0)


def _combine_scatter(back, disp, m, out_dtype):
    """Weighted scatter of returned segments into (M, H): back[j] is the
    segment this rank originally packed for rank j, so the origin-side
    send_* metadata pairs with it directly — no metadata travels back.
    Shared by the sequential and chunk-pipelined combines (their outputs
    must be bitwise comparable)."""
    n, c, h = back.shape
    rows = disp.send_src_row.reshape(-1)
    w = jnp.where(disp.send_valid, disp.send_weight, 0.0).reshape(-1)
    contrib = back.reshape(-1, h) * w[:, None]
    out = jnp.zeros((m, h), jnp.float32)
    out = out.at[rows].add(contrib, mode="drop")
    return out.astype(out_dtype)


def ep_combine(
    y: jax.Array,  # (n, C, H) expert outputs per source rank
    disp: EPDispatch,
    m: int,
    out_dtype,
    axis: str = EP_AXIS,
) -> jax.Array:
    """Send results back to their source ranks and weighted-scatter into
    (M, H) (ref combine path, ep_a2a.py:152 + ep_a2a_layer.py:240)."""
    a2a = all_to_all_ref if interpret_no_headroom() else all_to_all
    back, _ = a2a(y.astype(jnp.float32), disp.counts, axis)
    return _combine_scatter(back, disp, m, out_dtype)


# -- chunk-pipelined EP MoE ---------------------------------------------------
#
# The overlap story (ref: the double-buffered dispatch/combine of
# ep_a2a_layer.py:118-138 and T3-style fine-grained chunk overlap):
#
#   1. the pack expert-sorts each destination segment and ships the
#      per-(destination, expert) counts in the splits metadata, so the
#      receive side derives every chunk's grouped-GEMM segment structure
#      arithmetically (moe_utils.chunk_group_sizes) — no runtime argsort,
#      no inverse gather: the FFN consumes and produces slot order;
#   2. the transport is all_to_all_chunked: every capacity chunk gets its
#      own delivery semaphore, so chunk c is consumable while chunks
#      c+1.. are still in flight (the in-kernel wait order is chunk-major);
#   3. the expert FFN runs per chunk (gate_up -> silu -> down over the
#      chunk's expert segments), and the combine streams the per-chunk
#      results back through the same chunked transport.
#
# Even at n == 1 (no comm) the pipeline is the cheaper formulation: the
# sequential ep_expert_ffn pays a runtime argsort plus two full-width
# token gathers (sort + unsort) per layer; the pipelined FFN pays one
# extra int argsort at pack time and no (T, H) gathers at all.


@dataclasses.dataclass(frozen=True)
class EpMoeConfig:
    """Tunable knobs of the chunk-pipelined EP MoE (autotuner.
    ep_moe_config_space): n_chunks trades exposed A2A time against
    per-chunk grouped-GEMM efficiency (perf_model.estimate_ep_moe_ms);
    capacity_factor < 1 opts into the GShard drop trade (1.0 is
    lossless)."""

    n_chunks: int = 1
    capacity_factor: float = 1.0

    def fit_capacity(self, m: int, top_k: int) -> int:
        """THE capacity-from-factor rule — defined once, beside the
        config it belongs to, so the pruner's model and any runtime
        consumer derive the same capacity (a divergent re-derivation,
        e.g. int() truncation, would tune a chunking that never
        executes)."""
        import math

        return max(1, math.ceil(m * top_k * self.capacity_factor))


class EPChunkDispatch(NamedTuple):
    """ep_dispatch_chunked result: like EPDispatch, but segments arrive
    expert-sorted (invalid slots at each segment's tail) and carry their
    per-expert counts instead of per-slot expert ids."""

    x: jax.Array  # (n, C, H) tokens, expert-sorted within each segment
    expert_counts: jax.Array  # (n, E_loc) valid rows per (segment, expert)
    valid: jax.Array  # (n, C) bool (tail slots invalid)
    counts: jax.Array  # (n,) valid slots per received segment
    send_src_row: jax.Array  # (n, C)
    send_weight: jax.Array  # (n, C)
    send_valid: jax.Array  # (n, C)
    send_counts: jax.Array  # (n,)
    drops: jax.Array  # () int32


def _a2a_select(transport, n_chunks, straggler):
    """Transport arm of the pipeline: 'chunked' (per-chunk delivery
    semaphores), 'plain' (the single-shot kernel), or 'ref' (the XLA
    collective — the bit-identity oracle: all three move identical
    bytes, which the overlap parity tests assert).

    Under an active trace build every arm returns (out, splits, tbuf) —
    the chunked kernel's device buffer, or an empty stream for the
    untraced arms — so the pipeline's output tree is build-stable."""
    if transport == "chunked":
        from triton_dist_tpu.faults import guard as _guard

        # the EP pipeline does not thread guard buffers through its
        # output tree (trace buffers are), so the transport traces
        # UNGUARDED under an active build: a guarded kernel whose trip
        # rows were discarded would mute a detected fault into a
        # silently wrong MoE output — worse than the unguarded
        # behavior. guard.suppressed keeps the zero-cost-off program.
        def chunked(x, s, axis):
            with _guard.suppressed():
                return all_to_all_chunked(
                    x, s, axis, n_chunks=n_chunks, straggler=straggler)

        return chunked
    if transport == "plain":
        base = all_to_all  # falls back to the ref itself under
        # interpret_no_headroom — no second copy of that predicate here
    elif transport == "ref":
        base = all_to_all_ref
    else:
        raise ValueError(f"unknown transport {transport!r}")
    build = trace_ev.active_build()
    if build is None:
        return base

    def traced(x, s, axis):
        out, sp = base(x, s, axis)
        return out, sp, trace_ev.new_stream(build)

    return traced


def ep_dispatch_chunked(
    x: jax.Array,  # (M, H) this rank's tokens
    topk_ids: jax.Array,  # (M, k) global expert ids
    topk_weights: jax.Array,  # (M, k)
    n_experts: int,
    capacity: int,
    axis: str = EP_AXIS,
    n_chunks: int = 1,
    payload_dtype=None,
    transport: str = "chunked",
    straggler: Optional[Tuple[int, int]] = None,
) -> EPChunkDispatch:
    """Chunk-pipelined dispatch: expert-sorted pack + chunked A2A. Same
    routing and same drops as ep_dispatch (the capacity cut happens
    before the expert sort); the travelling metadata row is
    [count, per-expert counts] per destination.

    Under an active trace build returns (dispatch, trace_buf)."""
    n = jax.lax.axis_size(axis)
    h = x.shape[1]
    experts_per_rank = n_experts // n
    pack = _pack_by_dest(
        x, topk_ids, topk_weights, n, experts_per_rank, capacity,
        payload_dtype, expert_sorted=True,
    )
    meta = jnp.concatenate([pack.counts[:, None], pack.exp_counts], axis=1)
    a2a = _a2a_select(transport, n_chunks, straggler)
    build = trace_ev.active_build()
    res = a2a(pack.send_x, meta, axis)
    recv, recv_meta = res[:2]
    recv_counts = recv_meta[:, 0]
    recv_exp_counts = recv_meta[:, 1:]
    slot_idx = jnp.arange(capacity)[None, :]
    recv_valid = slot_idx < recv_counts[:, None]
    tokens, _ = _decode_payload(recv, h, n, capacity, payload_dtype,
                                x.dtype)
    disp = EPChunkDispatch(
        x=tokens,
        expert_counts=recv_exp_counts,
        valid=recv_valid,
        counts=recv_counts,
        send_src_row=pack.src_rows,
        send_weight=pack.weights,
        send_valid=pack.valid,
        send_counts=pack.counts,
        drops=pack.drops,
    )
    return (disp, res[2]) if build is not None else disp


def fit_chunks(n_chunks: int, capacity: int) -> int:
    """Largest chunk count <= n_chunks dividing capacity — THE fitting
    rule for every consumer (layer, autotuner pruner): a chunk count
    must never change `capacity` itself (capacity fixes which tokens
    drop; the overlapped and sequential paths must drop the same ones),
    so the count adapts, not the capacity. Shared so a tuned
    EpMoeConfig always describes the chunking that actually executes."""
    q = max(1, min(int(n_chunks), capacity))
    while capacity % q:
        q -= 1
    return q


def _extended_stacks(w_gate_up, w_down):
    """Null-group-extended expert stacks for the per-chunk grouped
    GEMMs: one extra trailing block (expert 0's weights) that the
    invalid-slot tail group runs against — its rows are masked out by
    the caller. NOT tiled per segment: the per-chunk FFN loops the
    (E_loc+1)-block stack over segments, so no n-fold HBM copy of the
    weights ever materializes."""
    w_gu = jnp.concatenate([w_gate_up, w_gate_up[:1]], axis=0)
    w_dn = jnp.concatenate([w_down, w_down[:1]], axis=0)
    return w_gu, w_dn


def ep_expert_ffn_chunked(
    disp: EPChunkDispatch,
    w_gate_up: jax.Array,  # (E_loc, H, 2I)
    w_down: jax.Array,  # (E_loc, I, H)
    n_chunks: int = 1,
    trace_rank=None,
):
    """Run this rank's experts chunk-by-chunk over the received tokens ->
    (n, C, H) f32 in slot order.

    Segments arrived expert-sorted, so chunk c's grouped-GEMM structure
    is pure arithmetic over the travelled per-expert counts — no argsort,
    no gather, and the output is already in slot order for the combine.
    Each chunk's FFN depends only on that chunk's rows: the compute for
    chunk c is issueable the moment all_to_all_chunked's chunk-c
    semaphores clear, while chunks c+1.. are still on the wire.

    Under an active trace build returns (y, mark_stream): each chunk's
    FFN is bracketed by BEGIN/END marks (pure-jnp records, data-chained
    through the chunk's input/output so they order with the real
    execution; `trace_rank` tags the stream's header)."""
    n, c, h = disp.x.shape
    if c % n_chunks:
        raise ValueError(f"n_chunks={n_chunks} must divide capacity {c}")
    build = trace_ev.active_build()
    marks = (trace_ev.new_stream(build, rank=trace_rank)
             if build is not None else None)
    R = trace_ev.REGIONS
    w_gu_e, w_dn_e = _extended_stacks(w_gate_up, w_down)
    rows = c // n_chunks
    ys = []
    for ci in range(n_chunks):
        lo = ci * rows
        gs = chunk_group_sizes(disp.expert_counts, c, lo, rows)
        xc = jax.lax.slice_in_dim(disp.x, lo, lo + rows, axis=1)
        marks = trace_ev.mark(marks, R["ep.ffn_chunk"],
                              trace_ev.KIND_BEGIN, payload=ci,
                              token=xc[0, 0, 0])
        # chunk rows are segment-major and the group-id sequence restarts
        # at every segment boundary, so the FFN loops segments (static,
        # n <= mesh axis) against the ONE (E_loc+1)-block stack rather
        # than tiling the weights n-fold in HBM
        yseg = []
        for j in range(n):
            hh = grouped_gemm(xc[j], w_gu_e, gs[j], out_dtype=jnp.float32)
            act = silu_mul(hh).astype(disp.x.dtype)
            yseg.append(
                grouped_gemm(act, w_dn_e, gs[j], out_dtype=jnp.float32))
        yc = jnp.stack(yseg, axis=0)  # (n, rows, h)
        marks = trace_ev.mark(marks, R["ep.ffn_chunk"], trace_ev.KIND_END,
                              payload=ci, token=yc[0, 0, 0])
        ys.append(yc)
    y = jnp.concatenate(ys, axis=1) if len(ys) > 1 else ys[0]
    # null-group rows ran expert 0's weights; mask them out
    y = jnp.where(disp.valid[..., None], y, 0.0)
    return (y, marks) if build is not None else y


def ep_combine_chunked(
    y: jax.Array,  # (n, C, H) expert outputs per source rank, slot order
    disp: EPChunkDispatch,
    m: int,
    out_dtype,
    axis: str = EP_AXIS,
    n_chunks: int = 1,
    transport: str = "chunked",
    straggler: Optional[Tuple[int, int]] = None,
) -> jax.Array:
    """Chunk-streamed combine: each capacity chunk of the result buffer
    travels back on its own delivery semaphore as it finishes, instead
    of waiting for the full (n, C, H) buffer (the return leg of the
    reference's double-buffered combine, ep_a2a_layer.py:240).

    Under an active trace build returns (out, trace_buf)."""
    a2a = _a2a_select(transport, n_chunks, straggler)
    res = a2a(y.astype(jnp.float32), disp.counts, axis)
    out = _combine_scatter(res[0], disp, m, out_dtype)
    return (out, res[2]) if trace_ev.active_build() is not None else out


def ep_moe_pipeline(
    x: jax.Array,  # (M, H) this rank's tokens
    topk_ids: jax.Array,  # (M, k)
    topk_weights: jax.Array,  # (M, k)
    w_gate_up: jax.Array,  # (E_loc, H, 2I)
    w_down: jax.Array,  # (E_loc, I, H)
    capacity: int,
    axis: str = EP_AXIS,
    n_chunks: int = 1,
    payload_dtype=None,
    transport: str = "chunked",
    straggler: Optional[Tuple[int, int]] = None,
) -> Tuple[jax.Array, jax.Array]:
    """The chunk-pipelined EP MoE core: chunked dispatch -> per-chunk
    grouped FFN -> chunk-streamed combine. Returns ((M, H) f32 output,
    drops). Same routing and drops as the sequential
    ep_dispatch/ep_expert_ffn/ep_combine composition.

    Under an active trace build returns (out, drops, traces): a dict of
    the three stage streams — the dispatch/combine transports' device
    buffers plus the per-chunk FFN mark stream — keyed for
    trace.assemble."""
    n = jax.lax.axis_size(axis)
    n_experts = w_gate_up.shape[0] * n
    build = trace_ev.active_build()
    res = ep_dispatch_chunked(
        x, topk_ids, topk_weights, n_experts, capacity, axis,
        n_chunks=n_chunks, payload_dtype=payload_dtype,
        transport=transport, straggler=straggler,
    )
    disp, disp_tbuf = res if build is not None else (res, None)
    rank = jax.lax.axis_index(axis) if build is not None else None
    res = ep_expert_ffn_chunked(disp, w_gate_up, w_down,
                                n_chunks=n_chunks, trace_rank=rank)
    y, ffn_marks = res if build is not None else (res, None)
    res = ep_combine_chunked(
        y, disp, x.shape[0], jnp.float32, axis, n_chunks=n_chunks,
        transport=transport, straggler=straggler,
    )
    out, comb_tbuf = res if build is not None else (res, None)
    if build is None:
        return out, disp.drops
    traces = {
        "ep.dispatch.a2a": disp_tbuf,
        "ep.ffn": ffn_marks,
        "ep.combine.a2a": comb_tbuf,
    }
    return out, disp.drops, traces


# -- protocol models (static verifier, triton_dist_tpu.verify) ---------------
#
# The chunk-pipelined dispatch and combine legs ride all_to_all_chunked
# unchanged — the pack/unpack around them is pure jnp with no cross-rank
# protocol content — so their registered models ARE the chunked-A2A
# skeleton at the chunk counts the EP pipeline uses. Registering them
# separately keeps the kernel list in scripts/verify_kernels.py honest
# (a future ep-specific transport change must bring its own model).

from triton_dist_tpu import verify as _v  # noqa: E402
from triton_dist_tpu.kernels.all_to_all import (  # noqa: E402
    _a2a_chunked_protocol,
)


@_v.protocol("ep_dispatch_chunked", grid=({"q": 2}, {"q": 4}),
             doc="EP dispatch leg over the chunked A2A (tokens + "
                 "per-(dest, expert) counts in the metadata row)")
def _ep_dispatch_protocol(n, q=2):
    _a2a_chunked_protocol(n, q=q)


@_v.protocol("ep_combine_chunked", grid=({"q": 2}, {"q": 4}),
             doc="EP combine return leg (chunk-streamed scatter-add "
                 "consumer) over the chunked A2A")
def _ep_combine_protocol(n, q=2):
    _a2a_chunked_protocol(n, q=q)


# -- conformance runners (verify.conform) -------------------------------------
#
# The EP legs ride all_to_all_chunked unchanged (their registered models
# ARE _a2a_chunked_protocol), so conformance drives the shared transport
# entry at the matching chunk count — any drift in the transport flags
# both EP protocols too.

from jax.sharding import PartitionSpec as _P  # noqa: E402

from triton_dist_tpu.verify import conform as _conform  # noqa: E402


def _ep_transport_conform(n, q):
    mesh = _conform.team_mesh(n, (EP_AXIS,))
    if isinstance(mesh, _conform.Skip):
        return mesh
    x = jnp.ones((n * n, 8, 128), jnp.float32)
    sp = jnp.ones((n * n,), jnp.int32)
    return _conform.collect_streams(
        mesh, EP_AXIS,
        lambda v, s: all_to_all_chunked(v, s, EP_AXIS, n_chunks=q),
        in_specs=(_P(EP_AXIS), _P(EP_AXIS)), args=(x, sp))


@_conform.conforms(
    "ep_dispatch_chunked", grids=((4, {"q": 2}), (4, {"q": 4})),
    doc="EP dispatch leg = the chunked A2A transport")
def _ep_dispatch_conform(n, q=2):
    return _ep_transport_conform(n, q)


@_conform.conforms(
    "ep_combine_chunked", grids=((4, {"q": 2}), (4, {"q": 4})),
    doc="EP combine leg = the chunked A2A transport")
def _ep_combine_conform(n, q=2):
    return _ep_transport_conform(n, q)
