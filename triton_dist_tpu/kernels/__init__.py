"""Overlapping collective + compute kernel library.

TPU-native analog of the reference kernel library
(ref: python/triton_dist/kernels/nvidia/__init__.py:25-41). Every kernel is
a Pallas TPU kernel (or an XLA-collective composition) designed to run
inside `jax.shard_map` over a named mesh; host-level `*_op` wrappers apply
the shard_map for callers holding global sharded arrays.
"""

from triton_dist_tpu.kernels.allgather import (  # noqa: F401
    AllGatherMethod,
    choose_allgather_method,
    ring_all_gather,
    full_mesh_all_gather,
    all_gather,
    all_gather_op,
)
from triton_dist_tpu.kernels.reduce_scatter import (  # noqa: F401
    ReduceScatterMethod,
    ring_reduce_scatter,
    reduce_scatter,
    reduce_scatter_op,
)
from triton_dist_tpu.kernels.allreduce import (  # noqa: F401
    AllReduceMethod,
    one_shot_all_reduce,
    two_shot_all_reduce,
    all_reduce,
    all_reduce_op,
)
from triton_dist_tpu.kernels.allgather_gemm import (  # noqa: F401
    AgGemmConfig,
    ag_gemm,
    ag_gemm_ref,
)
from triton_dist_tpu.kernels.gemm_reduce_scatter import (  # noqa: F401
    GemmRsConfig,
    gemm_rs,
    gemm_rs_ref,
)
from triton_dist_tpu.kernels.gemm_allreduce import (  # noqa: F401
    gemm_ar,
    gemm_ar_ref,
)
from triton_dist_tpu.kernels.low_latency_allgather import (  # noqa: F401
    create_ll_ag_buffer,
    ll_all_gather,
    ll_all_gather_op,
)
from triton_dist_tpu.kernels.all_to_all import (  # noqa: F401
    all_to_all,
    all_to_all_chunked,
    fast_all_to_all,
    all_to_all_ref,
)
from triton_dist_tpu.kernels.p2p import (  # noqa: F401
    p2p_send,
    p2p_read,
    ring_shift,
)
from triton_dist_tpu.kernels.moe_utils import (  # noqa: F401
    ExpertSort,
    chunk_group_sizes,
    combine_topk,
    expert_histogram,
    silu_mul,
    sort_by_expert,
    topk_routing,
)
from triton_dist_tpu.kernels.grouped_gemm import (  # noqa: F401
    grouped_gemm,
    grouped_gemm_ref,
)
from triton_dist_tpu.kernels.allgather_group_gemm import (  # noqa: F401
    ag_group_gemm,
    ag_group_gemm_ref,
    moe_reduce_rs,
)
from triton_dist_tpu.kernels.ep_a2a import (  # noqa: F401
    EPChunkDispatch,
    EPDispatch,
    EpMoeConfig,
    ep_combine,
    ep_combine_chunked,
    ep_dispatch,
    ep_dispatch_chunked,
    ep_expert_ffn,
    ep_expert_ffn_chunked,
    ep_moe_pipeline,
)
from triton_dist_tpu.kernels.sp_attention import (  # noqa: F401
    ring_attention,
    ring_attention_ref,
)
from triton_dist_tpu.kernels.flash_decode import (  # noqa: F401
    flash_decode_combine,
    flash_decode_partial,
    sp_flash_decode,
)
from triton_dist_tpu.kernels.flash_prefill import (  # noqa: F401
    FlashPrefillConfig,
    flash_prefill_local,
    flash_prefill_native_ok,
    flash_prefill_ref,
    sp_flash_prefill,
    sp_prefill_attention,
    supports_flash_prefill,
)
