"""Fused AllGather+GEMM — the flagship overlapped kernel.

TPU-native re-design of the reference's AG+GEMM
(ref: python/triton_dist/kernels/nvidia/allgather_gemm.py:158-575): there, a
copy-engine producer pushes shards while a persistent GEMM consumer spins on
per-rank barrier words before each M-tile (dl.wait :236, consume_token :237),
with a rank-offset threadblock swizzle so locally-available tiles compute
first (:224-229). Here the same overlap is ONE Pallas kernel:

  grid = (n_ranks, m_tiles, n_tiles) — outer dim s is the ring step.
  step s computes chunk (me - s) mod n: own shard at s=0 (the swizzle
  analog: zero-wait start), while the ring forward of the previous chunk is
  in flight. The per-rank barrier words become per-step DMA delivery
  semaphores; `dl.wait`+`consume_token` become `wait_recv` ordered before
  the A-tile loads by program order.

Computes: C = AllGather(a_shard) @ b   [column-parallel TP matmul]
  a_shard: (M/n, K) per device, b: (K, N_loc) per device -> C: (M, N_loc).
Also returns the gathered A (the reference's ctx workspace is reusable by
later kernels, allgather_gemm.py:458-487).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.lang import shmem
from triton_dist_tpu.lang.core import (
    tpu_call,
    compiler_params,
    next_collective_id,
    cdiv,
    interpret_no_headroom,
)
from triton_dist_tpu.runtime.init import TP_AXIS


@dataclasses.dataclass(frozen=True)
class AgGemmConfig:
    """Tile configuration (the reference's context tile fields,
    ref: allgather_gemm.py:417-456 BLOCK_M/N/K, num_stages)."""

    tile_m: int = 128
    tile_n: int = 256
    # VMEM ceiling for the auto fallback decision.
    vmem_budget: int = 14 << 20


def _ag_gemm_kernel(axis: str, n: int, tm: int, tn: int, out_dtype,
                    a_ref, b_ref, ws_ref, c_ref,
                    a_tile, acc, ld_sem, st_sem, cp_sem, send_sem, recv_sems):
    s = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)
    me = jax.lax.axis_index(axis)
    m_loc = a_ref.shape[0]
    chunk = jnp.mod(me - s, n)
    right = jnp.mod(me + 1, n)

    def fwd_copy(c_idx, step):
        """Ring descriptor for forwarding chunk rows to the right neighbor.
        Reconstructed identically wherever we need to start or wait it."""
        return pltpu.make_async_remote_copy(
            src_ref=ws_ref.at[pl.ds(c_idx * m_loc, m_loc)],
            dst_ref=ws_ref.at[pl.ds(c_idx * m_loc, m_loc)],
            send_sem=send_sem,
            recv_sem=recv_sems.at[step],
            device_id={axis: right},
            device_id_type=pltpu.DeviceIdType.MESH,
        )

    # --- producer side: runs once per ring step, before that step's tiles.
    @pl.when(jnp.logical_and(i == 0, j == 0))
    def _comm():
        @pl.when(s == 0)
        def _():
            if n > 1:
                shmem.neighbor_barrier(axis, me, n)
            cp = pltpu.make_async_copy(
                a_ref, ws_ref.at[pl.ds(me * m_loc, m_loc)], cp_sem
            )
            cp.start()
            cp.wait()
            if n > 1:
                fwd_copy(me, 0).start()

        if n > 1:
            @pl.when(s > 0)
            def _():
                prev_chunk = jnp.mod(me - s + 1, n)
                prev = fwd_copy(prev_chunk, s - 1)
                prev.wait_send()
                # consumer wait: this step's A rows have landed
                # (the dl.wait/consume_token contract, ref :236-237).
                prev.wait_recv()

                @pl.when(s < n - 1)
                def _():
                    fwd_copy(chunk, s).start()

    # --- consumer side: tiled matmul of this chunk against the B strip.
    @pl.when(j == 0)
    def _load_a():
        cp = pltpu.make_async_copy(
            ws_ref.at[pl.ds(chunk * m_loc + i * tm, tm)], a_tile, ld_sem
        )
        cp.start()
        cp.wait()

    acc[...] = jnp.dot(
        a_tile[...], b_ref[...], preferred_element_type=jnp.float32
    ).astype(out_dtype)
    st = pltpu.make_async_copy(
        acc,
        c_ref.at[pl.ds(chunk * m_loc + i * tm, tm), pl.ds(j * tn, tn)],
        st_sem,
    )
    st.start()
    st.wait()


def ag_gemm(
    a_shard: jax.Array,
    b: jax.Array,
    axis: str = TP_AXIS,
    config: Optional[AgGemmConfig] = None,
    return_gathered: bool = False,
    out_dtype=None,
    force_kernel: bool = False,
):
    """Overlapped AllGather(a_shard) @ b; per-device function inside shard_map
    (ref host entry: allgather_gemm.py:534-575 `ag_gemm`).

    a_shard: (M/n, K); b: (K, N_loc). Returns C (M, N_loc), and the gathered
    A (M, K) when return_gathered. out_dtype=float32 lets a following
    elementwise epilogue (e.g. TP-MLP's silu·mul) fuse without a bf16
    round-trip — the cast-early formulation measurably breaks XLA's fusion
    (~193 vs ~180 TF/s on v5e at the Qwen3-32B MLP shapes).
    """
    cfg = config or AgGemmConfig()
    out_dtype = out_dtype or a_shard.dtype
    n = jax.lax.axis_size(axis)
    m_loc, k = a_shard.shape
    k2, n_loc = b.shape
    assert k == k2, f"K mismatch {k} vs {k2}"
    if n == 1 and not force_kernel:
        # Nothing to overlap at world=1; XLA's matmul is the fastest path
        # (measured ~87% vs ~52% MFU for the Pallas grid on v5e).
        c = jnp.dot(a_shard, b, preferred_element_type=jnp.float32).astype(
            out_dtype
        )
        return (c, a_shard) if return_gathered else c
    tm = min(cfg.tile_m, m_loc)
    tn = min(cfg.tile_n, n_loc)
    if m_loc % tm or n_loc % tn:
        raise ValueError(
            f"shard dims ({m_loc},{n_loc}) must divide tiles ({tm},{tn})"
        )

    # VMEM residents: B strip (K, tn), A tile (tm, K), acc (tm, tn).
    itemsize = jnp.dtype(a_shard.dtype).itemsize
    vmem_need = k * tn * itemsize * 2 + tm * k * itemsize + tm * tn * 4
    if (vmem_need > cfg.vmem_budget or interpret_no_headroom()) and (
        not force_kernel
    ):
        # Fallback: XLA AG + dot (the reference's torch path analog).
        a_full = jax.lax.all_gather(a_shard, axis, tiled=True)
        c = jnp.dot(a_full, b, preferred_element_type=jnp.float32).astype(
            out_dtype
        )
        return (c, a_full) if return_gathered else c

    mt = cdiv(m_loc, tm)
    nt = cdiv(n_loc, tn)

    grid = (n, mt, nt)
    ws, c = tpu_call(
        functools.partial(_ag_gemm_kernel, axis, n, tm, tn, out_dtype),
        grid=grid,
        out_shape=(
            jax.ShapeDtypeStruct((n * m_loc, k), a_shard.dtype),
            jax.ShapeDtypeStruct((n * m_loc, n_loc), out_dtype),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(
                (k, tn), lambda s, i, j: (0, j), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ),
        scratch_shapes=[
            pltpu.VMEM((tm, k), a_shard.dtype),
            pltpu.VMEM((tm, tn), out_dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
        ],
        compiler_params=compiler_params(
            has_side_effects=True,
            # The barrier semaphore (keyed by collective_id) is only used by
            # the n>1 neighbor_barrier; Mosaic rejects a collective_id when
            # no custom barrier exists in the kernel (world=1).
            collective_id=(
                next_collective_id(f"ag_gemm_{axis}") if n > 1 else None
            ),
            vmem_limit_bytes=cfg.vmem_budget + (2 << 20),
        ),
    )(a_shard, b)
    return (c, ws) if return_gathered else c


def ag_gemm_ref(a_shard: jax.Array, b: jax.Array, axis: str = TP_AXIS):
    """Unfused XLA reference path (the reference's torch_fwd analog,
    ref: layers/nvidia/tp_mlp.py torch_fwd)."""
    a_full = jax.lax.all_gather(a_shard, axis, tiled=True)
    return jnp.dot(a_full, b, preferred_element_type=jnp.float32).astype(
        a_shard.dtype
    )
